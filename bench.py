"""Benchmark harness — GBM training throughput on the local accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no numbers (BASELINE.json "published": {}), so
vs_baseline is the ratio against the first number this harness ever
recorded on the SAME platform at the SAME shape (BENCH_BASELINE.json
keys entries by "<platform>:<rows>x<trees>").  A run with no matching
baseline emits ``vs_baseline: null`` — a CPU fallback round can never
again report a >1 ratio against an on-chip baseline (the round-3
scoreboard defect).  Every run also emits ``last_tpu_value``: the most
recent on-chip measurement on record, so the scoreboard always carries
the real number even when the chip is down.

North-star metric (BASELINE.json:2): GBM rows/sec/chip. We measure
steady-state boosting throughput (binning + per-tree grow + margin
update) on a synthetic airlines-like binary-classification table.

Robustness contract: this file IS the round scoreboard.  It probes the
TPU backend in a subprocess (a hung client-init cannot take down the
bench) and is STUBBORN: it keeps retrying with pauses for up to
H2O_TPU_PROBE_BUDGET seconds (default 600 — a recovering chip must not
cost the round its TPU number, the round-2 failure mode) before falling
back to CPU, and on any exception still emits a single diagnostic JSON
line instead of a traceback.
"""

import json
import os
import sys
import time
import traceback

import numpy as np

METRIC = "gbm_boosted_rows_per_sec_per_chip"
UNIT = "rows*trees/s/chip"
SCORE_METRIC = "gbm_score_rows_per_sec"


def measure_scoring(m, fr, fr1, Xn, rows: int,
                    reps_full: int = 3) -> dict:
    """THE serving-throughput harness (shared by `bench.py score` and
    bench_suite's gbm_score_rows_per_sec config — one protocol, two
    data shapes, no drift): legacy per-call predict() baselines
    (full-batch + batch-1, via models.gbm.legacy_scoring_path), then
    warm score_numpy at both shapes with the scorer-cache recompile
    check.  `fr1` is a 1-row frame (the "100k×1" per-call serving
    unit).  Returns the flat record; `compile_seconds` is the cold
    first score_numpy call."""
    from h2o_kubernetes_tpu.models.base import scorer_cache_stats
    from h2o_kubernetes_tpu.models.gbm import legacy_scoring_path

    def timed(fn, reps):
        fn()                       # warm (compile)
        t0 = time.perf_counter()
        for _ in range(reps):
            fn()
        return (time.perf_counter() - t0) / reps

    with legacy_scoring_path(m):
        dt_legacy = timed(lambda: m.predict(fr), reps_full)
        dt1_legacy = timed(lambda: m.predict(fr1), 10 * reps_full)
    m.predict(fr)                  # warm the new frame path
    t0 = time.perf_counter()
    m.score_numpy(Xn)              # cold serving call (compile)
    compile_s = time.perf_counter() - t0
    one = Xn[:1]
    m.score_numpy(one)
    dt_frame = timed(lambda: m.predict(fr), reps_full)
    s0 = scorer_cache_stats()
    dt_fast = timed(lambda: m.score_numpy(Xn), reps_full)
    dt1_fast = timed(lambda: m.score_numpy(one), 100 * reps_full)
    s1 = scorer_cache_stats()
    return {
        "value": round(rows / dt_fast, 1),
        "unit": "rows/s",
        "seconds": round(dt_fast, 3),
        "calls": reps_full,
        "compile_seconds": round(compile_s, 3),
        "legacy_predict_rows_per_s": round(rows / dt_legacy, 1),
        "speedup_vs_legacy_predict": round(dt_legacy / dt_fast, 2),
        "frame_predict_rows_per_s": round(rows / dt_frame, 1),
        "batch1_rows_per_s": round(1.0 / dt1_fast, 1),
        "batch1_legacy_rows_per_s": round(1.0 / dt1_legacy, 1),
        "speedup_batch1": round(dt1_legacy / dt1_fast, 2),
        "warm_cache_misses": s1["misses"] - s0["misses"],
        "rows": rows,
    }


def main_score() -> None:
    """`python bench.py score` — the serving fast-path number: warm
    score_numpy rows/s (flattened-tree scorer + jitted-predict cache)
    vs the per-call predict() Frame path, one JSON line.  The warm
    repeat must add 0 scorer-cache misses (recompile check)."""
    from h2o_kubernetes_tpu.runtime.backend import ensure_live_backend

    ensure_live_backend()
    import jax

    import h2o_kubernetes_tpu as h2o
    from h2o_kubernetes_tpu.models import GBM

    rows = int(os.environ.get("BENCH_SCORE_ROWS", 100_000))
    rng = np.random.default_rng(0)
    F = 10
    X = {f"x{i}": rng.normal(size=rows).astype(np.float32)
         for i in range(F - 1)}
    X["c1"] = np.array(["a", "b", "c", "d"])[rng.integers(0, 4, rows)]
    X["y"] = np.where(X["x0"] - X["x1"] > 0, "late", "ontime")
    fr = h2o.Frame.from_arrays(X)
    m = GBM(ntrees=20, max_depth=5, learn_rate=0.2, seed=1).train(
        y="y", training_frame=fr)
    Xn = np.asarray(m._design_matrix(fr))[:rows]
    fr1 = h2o.Frame.from_arrays(
        {k: v[:1] for k, v in X.items() if k != "y"})
    out = measure_scoring(m, fr, fr1, Xn, rows)
    print(json.dumps({"metric": SCORE_METRIC,
                      "platform": jax.default_backend(), **out}))


def main() -> None:
    from h2o_kubernetes_tpu.runtime.backend import ensure_live_backend

    ensure_live_backend()
    import jax

    import h2o_kubernetes_tpu as h2o
    from h2o_kubernetes_tpu.models import GBM

    n_chips = len(jax.devices())
    on_tpu = jax.default_backend() == "tpu"
    default_rows = 1_000_000 if on_tpu else 50_000
    rows = int(os.environ.get("BENCH_ROWS", default_rows))
    ntrees = int(os.environ.get("BENCH_TREES", 10))
    rng = np.random.default_rng(0)
    F = 10
    X = {f"x{i}": rng.normal(size=rows).astype(np.float32)
         for i in range(F - 2)}
    X["c1"] = np.array(["a", "b", "c", "d", "e", "f", "g", "h"])[
        rng.integers(0, 8, size=rows)]
    X["dep_delay"] = rng.exponential(10.0, size=rows).astype(np.float32)
    logit = (1.2 * X["x0"] - 0.8 * X["x1"] + 0.05 * X["dep_delay"]
             - 1.0 + rng.normal(scale=0.5, size=rows))
    X["y"] = np.where(logit > 0, "late", "ontime")
    fr = h2o.Frame.from_arrays(X)

    def run(nt):
        return GBM(ntrees=nt, max_depth=5, learn_rate=0.2, seed=1).train(
            y="y", training_frame=fr)

    # warm-up with the SAME ntrees: the fused boosting loop compiles a
    # scan whose length is the tree count, so a shorter warm-up would
    # leave the timed run paying a fresh XLA compile
    try:
        run(ntrees)
    except Exception:
        # a KERNEL-COMPILE regression must degrade, not zero, the
        # scoreboard: drop the grid dimension_semantics annotation
        # (the one compile-affecting knob CPU CI cannot validate) and
        # retry once. Non-compile failures (OOM, bad data, mesh
        # health) re-raise immediately — retrying them doubles
        # time-to-failure for no possible gain.
        from h2o_kubernetes_tpu.ops import histogram as H

        err = traceback.format_exc()
        # annotation-specific markers only: a generic "vmem" match also
        # catches genuine VMEM OOMs that dropping dimension_semantics
        # cannot fix, wasting a second compile+run before failing
        compileish = any(s in err for s in (
            "Mosaic", "mosaic", "dimension_semantics", "remote_compile"))
        if not H._DIMSEM or not compileish:
            raise
        traceback.print_exc()
        print("warm-up failed; retrying without dimension_semantics",
              file=sys.stderr)
        H._DIMSEM = False
        jax.clear_caches()
        run(ntrees)
    t0 = time.perf_counter()
    run(ntrees)
    dt = time.perf_counter() - t0
    rows_per_sec_per_chip = rows * ntrees / dt / n_chips

    platform = jax.default_backend()
    shape_key = f"{platform}:{rows}x{ntrees}"
    base_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_BASELINE.json")
    store = {"metric": METRIC, "baselines": {}, "last_tpu": None}
    if os.path.exists(base_path):
        with open(base_path) as f:
            raw = json.load(f)
        if "baselines" in raw:
            store = raw
        else:
            # legacy single-value file: that number was the round-1
            # on-chip capture at the TPU default shape (1M rows x 10)
            store["baselines"] = {"tpu:1000000x10": {"value": raw["value"]}}
    entry = store["baselines"].get(shape_key)
    if entry is None:
        store["baselines"][shape_key] = {"value": rows_per_sec_per_chip}
        base = None  # first run at this platform+shape: no ratio yet
    else:
        base = entry["value"]
    # H2O_TPU_BENCH_NO_STORE=1: measure without touching the baseline
    # store — experimental-mode runs (the watcher's 2-term capture)
    # must not overwrite last_tpu, the headline full-precision number
    if os.environ.get("H2O_TPU_BENCH_NO_STORE") != "1":
        if on_tpu:
            store["last_tpu"] = {"value": rows_per_sec_per_chip,
                                 "rows": rows, "trees": ntrees,
                                 "recorded": time.strftime(
                                     "%Y-%m-%dT%H:%M:%S")}
        with open(base_path, "w") as f:
            json.dump(store, f, indent=1)

    print(json.dumps({
        "metric": METRIC,
        "value": round(rows_per_sec_per_chip, 1),
        "unit": UNIT,
        "vs_baseline": (round(rows_per_sec_per_chip / base, 3)
                        if base else None),
        "baseline_key": shape_key if base else None,
        "last_tpu_value": (round(store["last_tpu"]["value"], 1)
                           if store["last_tpu"] else None),
        "platform": platform,
        "rows": rows,
        "trees": ntrees,
        "seconds": round(dt, 3),
    }))


if __name__ == "__main__":
    score_mode = "score" in sys.argv[1:]
    try:
        main_score() if score_mode else main()
    except Exception as e:  # scoreboard must emit a JSON line, always
        traceback.print_exc()
        print(json.dumps({
            "metric": SCORE_METRIC if score_mode else METRIC,
            "value": 0.0,
            "unit": "rows/s" if score_mode else UNIT,
            "vs_baseline": 0.0, "error": repr(e)[:300],
        }))
        sys.exit(0)
