# h2o.tpu — R client for the h2o_kubernetes_tpu REST API.
#
# The reference ships a full R package (h2o-r/ in h2o-3) whose verbs
# are thin wrappers over the same REST surface the Python client uses;
# this file is the equivalent for this framework: one source()-able
# script, base R + jsonlite, HTTP via the system curl binary (present
# in every deploy image this targets; no httr dependency).
#
#   source("h2o_tpu.R")
#   h2o.init("http://localhost:54321")
#   fr  <- h2o.importFile("/data/airlines.csv", "air.hex")
#   m   <- h2o.gbm(y = "IsDepDelayed", training_frame = "air.hex",
#                  ntrees = 50, max_depth = 5)
#   h2o.performance(m)                      # scoring history, CV, varimp
#   p   <- h2o.predict(m, "air.hex")
#   aml <- h2o.automl(y = "IsDepDelayed", training_frame = "air.hex",
#                     max_models = 12)
#   h2o.leaderboard(aml)
#
# NOTE: this environment has no R runtime, so unlike everything else
# in the repo this client is not exercised by CI; it sticks to the
# REST verbs tests/test_rest.py covers and to base-R constructs.

.h2o.env <- new.env(parent = emptyenv())

.h2o.url <- function(path) {
  base <- get0("base", envir = .h2o.env,
               ifnotfound = "http://localhost:54321")
  paste0(base, path)
}

.h2o.http <- function(method, path, body = NULL) {
  if (!requireNamespace("jsonlite", quietly = TRUE))
    stop("the h2o.tpu client needs the 'jsonlite' package")
  args <- c("-s", "-X", method, .h2o.url(path))
  if (!is.null(body)) {
    args <- c(args, "-H", "Content-Type: application/json",
              "--data-binary",
              jsonlite::toJSON(body, auto_unbox = TRUE))
  }
  raw <- suppressWarnings(system2("curl", shQuote(args), stdout = TRUE))
  txt <- paste(raw, collapse = "\n")
  if (!nzchar(txt))
    stop("no response from ", .h2o.url(path),
         " - is the server running? (h2o.init)")
  out <- jsonlite::fromJSON(txt, simplifyVector = FALSE)
  if (!is.null(out$http_status) && out$http_status >= 400)
    stop("HTTP ", out$http_status, ": ", out$msg)
  out
}

# -- cluster ----------------------------------------------------------------

h2o.init <- function(url = "http://localhost:54321") {
  assign("base", sub("/+$", "", url), envir = .h2o.env)
  st <- .h2o.http("GET", "/3/Cloud")
  cat(sprintf("Connected to h2o-tpu v%s: %d device(s), healthy=%s\n",
              st$version, st$cloud_size, st$cloud_healthy))
  invisible(st)
}

h2o.clusterStatus <- function() .h2o.http("GET", "/3/Cloud")

h2o.isLeaderNode <- function() {
  out <- tryCatch(.h2o.http("GET", "/kubernetes/isLeaderNode"),
                  error = function(e) list(leader = FALSE))
  isTRUE(out$leader)
}

# -- frames -----------------------------------------------------------------

h2o.importFile <- function(path, destination_frame = NULL) {
  body <- list(path = path)
  if (!is.null(destination_frame))
    body$destination_frame <- destination_frame
  out <- .h2o.http("POST", "/3/ImportFiles", body)
  out$frame_id$name
}

h2o.ls <- function() {
  out <- .h2o.http("GET", "/3/Frames")
  vapply(out$frames, function(f) f$frame_id$name, character(1))
}

h2o.describe <- function(frame_id) {
  .h2o.http("GET", paste0("/3/Frames/", utils::URLencode(frame_id),
                          "/summary"))$summary
}

h2o.rm <- function(key) {
  ok <- tryCatch({
    .h2o.http("DELETE", paste0("/3/Frames/", utils::URLencode(key)))
    TRUE
  }, error = function(e) FALSE)
  if (!ok)
    .h2o.http("DELETE", paste0("/3/Models/", utils::URLencode(key)))
  invisible(key)
}

h2o.removeAll <- function() invisible(.h2o.http("DELETE", "/3/DKV"))

# -- model builders ---------------------------------------------------------

.h2o.train <- function(algo, y = NULL, training_frame, model_id = NULL,
                       ...) {
  body <- list(training_frame = training_frame, ...)
  if (!is.null(y)) body$response_column <- y
  if (!is.null(model_id)) body$model_id <- model_id
  out <- .h2o.http("POST", paste0("/3/ModelBuilders/", algo), body)
  dest <- out$job$dest$name
  if (identical(out$job$status, "FAILED"))
    stop(algo, " build failed: ", out$job$msg)
  structure(list(model_id = dest, algo = algo), class = "H2OTpuModel")
}

h2o.gbm <- function(...) .h2o.train("gbm", ...)
h2o.randomForest <- function(...) .h2o.train("drf", ...)
h2o.glm <- function(...) .h2o.train("glm", ...)
h2o.deeplearning <- function(...) .h2o.train("deeplearning", ...)
h2o.xgboost <- function(...) .h2o.train("xgboost", ...)
h2o.kmeans <- function(...) .h2o.train("kmeans", ...)
h2o.naiveBayes <- function(...) .h2o.train("naivebayes", ...)
h2o.prcomp <- function(...) .h2o.train("pca", ...)
h2o.isolationForest <- function(...) .h2o.train("isolationforest", ...)
h2o.glrm <- function(...) .h2o.train("glrm", ...)
h2o.coxph <- function(...) .h2o.train("coxph", ...)
h2o.aggregator <- function(...) .h2o.train("aggregator", ...)

h2o.getModel <- function(model_id) {
  structure(list(model_id = model_id,
                 detail = .h2o.http(
                   "GET", paste0("/3/Models/",
                                 utils::URLencode(model_id)))),
            class = "H2OTpuModel")
}

h2o.performance <- function(model) {
  id <- if (inherits(model, "H2OTpuModel")) model$model_id else model
  .h2o.http("GET", paste0("/3/Models/", utils::URLencode(id)))
}

h2o.varimp <- function(model) {
  perf <- h2o.performance(model)
  vi <- perf$variable_importances
  if (is.null(vi)) return(NULL)
  data.frame(variable = names(vi),
             relative_importance = unlist(vi, use.names = FALSE))
}

h2o.download_mojo <- function(model, path = NULL) {
  id <- if (inherits(model, "H2OTpuModel")) model$model_id else model
  if (is.null(path)) path <- paste0(id, ".mojo")
  # -f: an HTTP error must fail the call, not write the JSON error
  # body into the artifact file
  args <- c("-s", "-f", "-o", path,
            .h2o.url(paste0("/3/Models/", utils::URLencode(id),
                            "/mojo")))
  status <- system2("curl", shQuote(args))
  if (status != 0 || !file.exists(path))
    stop("mojo download failed for ", id)
  invisible(path)
}

h2o.predict <- function(model, frame_id) {
  id <- if (inherits(model, "H2OTpuModel")) model$model_id else model
  out <- .h2o.http(
    "POST", paste0("/3/Predictions/models/", utils::URLencode(id),
                   "/frames/", utils::URLencode(frame_id)))
  out$predictions_frame$name
}

# -- grids / automl / jobs --------------------------------------------------

h2o.grid <- function(algo, hyper_params, y, training_frame,
                     grid_id = NULL, ...) {
  # as.list each value so toJSON(auto_unbox) keeps single-valued
  # hypers as JSON arrays — the server iterates every value list
  body <- list(training_frame = training_frame, response_column = y,
               hyper_parameters = lapply(hyper_params, as.list), ...)
  if (!is.null(grid_id)) body$grid_id <- grid_id
  out <- .h2o.http("POST", paste0("/99/Grid/", algo), body)
  gid <- out$grid_id$name
  .h2o.http("GET", paste0("/99/Grids/", utils::URLencode(gid)))
}

h2o.automl <- function(y, training_frame, project_name = "automl",
                       max_models = 12, ...) {
  body <- list(training_frame = training_frame, response_column = y,
               project_name = project_name, max_models = max_models,
               ...)
  out <- .h2o.http("POST", "/99/AutoMLBuilder", body)
  if (identical(out$job$status, "FAILED"))
    stop("AutoML failed: ", out$job$msg)
  structure(list(project_name = out$project_name),
            class = "H2OTpuAutoML")
}

h2o.leaderboard <- function(automl) {
  pn <- if (inherits(automl, "H2OTpuAutoML")) automl$project_name
        else automl
  out <- .h2o.http("GET", paste0("/3/AutoML/", utils::URLencode(pn)))
  rows <- out$leaderboard
  if (!length(rows)) return(data.frame())
  cols <- unique(unlist(lapply(rows, names)))
  # atomic columns (fromJSON(simplifyVector=FALSE) gives lists; rbind
  # of lists would make list-columns that break order()/mean());
  # JSON nulls (NaN metrics) become NA
  df <- lapply(cols, function(cn) {
    vals <- lapply(rows, function(r) r[[cn]])
    if (all(vapply(vals, function(v)
          is.null(v) || is.numeric(v), logical(1))))
      vapply(vals, function(v) if (is.null(v)) NA_real_
             else as.numeric(v), numeric(1))
    else
      vapply(vals, function(v) if (is.null(v)) NA_character_
             else as.character(v), character(1))
  })
  names(df) <- cols
  as.data.frame(df, stringsAsFactors = FALSE)
}

h2o.jobs <- function() {
  out <- .h2o.http("GET", "/3/Jobs")
  if (!length(out$jobs)) return(data.frame())
  do.call(rbind, lapply(out$jobs, function(j)
    data.frame(dest = j$dest, description = j$description,
               status = j$status, progress = j$progress,
               msg = if (nzchar(j$msg %||% "")) j$msg else "")))
}

`%||%` <- function(a, b) if (is.null(a)) b else a
