// tpuk — user-facing CLI (the reference's `h2ok`, cli/src/main.rs [U]):
//   tpuk deploy   --name n --cluster-size 3 [...]   create + wait + descriptor
//   tpuk undeploy --name n | -f n.tpuk              tear down
//   tpuk ingress  add|delete --name n [--host h]    external route
//   tpuk status   --name n                          CR/StatefulSet state
//   tpuk manifest --name n [...]                    print manifests (no
//                                                   cluster needed)
// After deploy a <name>.tpuk descriptor file is written so undeploy can
// find the resources later (SURVEY.md §2a R1).
#include <cstdio>
#include <map>
#include <set>
#include <string>
#include <vector>

#include "../deployment/crd.h"
#include "../deployment/deploy.h"
#include "../deployment/k8s_client.h"
#include "../deployment/manifests.h"

namespace {

using tpuk::H2OTpu;

void usage() {
  std::fprintf(stderr, R"(tpuk — deploy h2o_kubernetes_tpu clusters on Kubernetes

usage: tpuk <deploy|undeploy|ingress|status|manifest> [flags]

common flags:
  --name NAME              cluster name (required unless -f)
  --namespace NS           namespace (default: default)
  --kubeconfig PATH        kubeconfig (default $KUBECONFIG, ~/.kube/config,
                           then in-cluster)
  --server URL --token T   direct API access instead of kubeconfig

deploy flags (also honored by manifest):
  --cluster-size N         number of hosts/pods (default 1)
  --version V              image tag (default latest)
  --custom-image IMG       full image override
  --memory QTY             pod memory request/limit (default 16Gi)
  --cpus QTY               pod cpu request (default 4)
  --memory-percentage P    runtime memory fraction (default 90)
  --accelerator TYPE       GKE TPU accelerator (default tpu-v5-lite-podslice)
  --topology T             TPU topology (default 2x4)
  --chips-per-host N       google.com/tpu per pod (default 4)
  --timeout SECS           deploy readiness wait (default 300)

ingress:  tpuk ingress add|delete --name n [--host example.com]
undeploy: tpuk undeploy --name n | -f name.tpuk
)");
}

struct Args {
  std::string cmd;
  std::vector<std::string> positional;
  std::map<std::string, std::string> flags;

  std::string get(const std::string& k, const std::string& dflt = "") const {
    auto it = flags.find(k);
    return it == flags.end() ? dflt : it->second;
  }
  int get_int(const std::string& k, int dflt) const {
    auto it = flags.find(k);
    return it == flags.end() ? dflt : std::stoi(it->second);
  }
  bool has(const std::string& k) const { return flags.count(k) > 0; }
};

const std::set<std::string> kBoolFlags = {"insecure"};
const std::set<std::string> kValueFlags = {
    "name", "namespace", "kubeconfig", "server", "token", "cluster-size",
    "version", "custom-image", "memory", "cpus", "memory-percentage",
    "accelerator", "topology", "chips-per-host", "timeout", "host", "file"};

Args parse_args(int argc, char** argv) {
  Args a;
  if (argc < 2) { usage(); std::exit(2); }
  a.cmd = argv[1];
  for (int i = 2; i < argc; ++i) {
    std::string s = argv[i];
    if (s.rfind("--", 0) == 0 || s == "-f") {
      std::string key = s == "-f" ? "file" : s.substr(2);
      if (kBoolFlags.count(key)) {
        a.flags[key] = "true";
      } else if (kValueFlags.count(key)) {
        if (i + 1 >= argc) {
          std::fprintf(stderr, "tpuk: %s needs a value\n", s.c_str());
          std::exit(2);
        }
        a.flags[key] = argv[++i];
      } else {
        std::fprintf(stderr, "tpuk: unknown flag %s\n", s.c_str());
        std::exit(2);
      }
    } else {
      a.positional.push_back(s);
    }
  }
  return a;
}

H2OTpu cr_from_args(const Args& a) {
  H2OTpu cr;
  cr.name = a.get("name");
  if (cr.name.empty()) {
    std::fprintf(stderr, "tpuk: --name is required\n");
    std::exit(2);
  }
  cr.ns = a.get("namespace", "default");
  cr.spec.nodes = a.get_int("cluster-size", 1);
  cr.spec.version = a.get("version", "latest");
  if (a.has("custom-image")) cr.spec.custom_image = a.get("custom-image");
  cr.spec.resources.cpu = a.get("cpus", cr.spec.resources.cpu);
  cr.spec.resources.memory = a.get("memory", cr.spec.resources.memory);
  cr.spec.resources.memory_percentage =
      a.get_int("memory-percentage", cr.spec.resources.memory_percentage);
  cr.spec.tpu.accelerator = a.get("accelerator", cr.spec.tpu.accelerator);
  cr.spec.tpu.topology = a.get("topology", cr.spec.tpu.topology);
  cr.spec.tpu.chips_per_host =
      a.get_int("chips-per-host", cr.spec.tpu.chips_per_host);
  return cr;
}

std::unique_ptr<tpuk::ApiClient> client_from_args(const Args& a) {
  tpuk::K8sConfig cfg;
  if (a.has("server")) {
    cfg.server = a.get("server");
    cfg.token = a.get("token");
    cfg.insecure_skip_verify = a.has("insecure");
  } else {
    cfg = tpuk::K8sConfig::resolve(a.get("kubeconfig"));
  }
  return tpuk::make_curl_client(cfg);
}

int cmd_deploy(const Args& a) {
  H2OTpu cr = cr_from_args(a);
  auto api = client_from_args(a);
  tpuk::deploy_cluster(*api, cr);
  tpuk::write_descriptor(cr);
  std::printf("deployed %s/%s (%d nodes); descriptor: %s.tpuk\n",
              cr.ns.c_str(), cr.name.c_str(), cr.spec.nodes,
              cr.name.c_str());
  int timeout = a.get_int("timeout", 300);
  if (timeout > 0) {
    if (tpuk::wait_ready(*api, cr, timeout)) {
      std::printf("cluster ready; coordinator %s\n",
                  tpuk::coordinator_address(cr).c_str());
    } else {
      std::fprintf(stderr, "tpuk: timed out after %ds waiting for ready\n",
                   timeout);
      return 1;
    }
  }
  return 0;
}

int cmd_undeploy(const Args& a) {
  std::string name = a.get("name");
  std::string ns = a.get("namespace", "default");
  if (a.has("file")) {
    H2OTpu cr = tpuk::read_descriptor(a.get("file"));
    name = cr.name;
    ns = cr.ns;
  }
  if (name.empty()) {
    std::fprintf(stderr, "tpuk: undeploy needs --name or -f descriptor\n");
    return 2;
  }
  auto api = client_from_args(a);
  tpuk::undeploy_cluster(*api, name, ns);
  std::printf("undeployed %s/%s\n", ns.c_str(), name.c_str());
  return 0;
}

int cmd_ingress(const Args& a) {
  if (a.positional.empty() ||
      (a.positional[0] != "add" && a.positional[0] != "delete")) {
    std::fprintf(stderr, "tpuk: ingress add|delete\n");
    return 2;
  }
  H2OTpu cr = cr_from_args(a);
  auto api = client_from_args(a);
  if (a.positional[0] == "add") {
    tpuk::create_ingress(*api, cr, a.get("host"));
    std::printf("ingress created for %s/%s\n", cr.ns.c_str(),
                cr.name.c_str());
  } else {
    tpuk::delete_ingress(*api, cr.name, cr.ns);
    std::printf("ingress deleted for %s/%s\n", cr.ns.c_str(),
                cr.name.c_str());
  }
  return 0;
}

int cmd_status(const Args& a) {
  H2OTpu cr = cr_from_args(a);
  auto api = client_from_args(a);
  tpuk::Response r =
      api->request("GET", tpuk::statefulsets_path(cr.ns, cr.name));
  if (r.not_found()) {
    std::printf("%s/%s: not deployed\n", cr.ns.c_str(), cr.name.c_str());
    return 1;
  }
  if (!r.ok()) {
    std::fprintf(stderr, "tpuk: status failed (%ld): %s\n", r.status,
                 r.body.c_str());
    return 1;
  }
  tpuk::Json sts = r.json();
  auto num = [&](const char* path) -> long long {
    const tpuk::Json* v = sts.get_path(path);
    return v && v->is_number() ? v->as_int() : 0;
  };
  std::printf("%s/%s: %lld/%lld ready (coordinator %s)\n", cr.ns.c_str(),
              cr.name.c_str(), num("status.readyReplicas"),
              num("spec.replicas"),
              tpuk::coordinator_address(cr).c_str());
  return 0;
}

int cmd_manifest(const Args& a) {
  H2OTpu cr = cr_from_args(a);
  tpuk::Json bundle = tpuk::Json::object();
  bundle["service"] = tpuk::headless_service(cr);
  bundle["statefulSet"] = tpuk::stateful_set(cr);
  if (a.has("host")) bundle["ingress"] = tpuk::ingress(cr, a.get("host"));
  bundle["customResource"] = cr.to_json();
  bundle["customResourceDefinition"] = tpuk::crd_manifest();
  std::printf("%s", bundle.dump(2).c_str());
  return 0;
}

}  // namespace

int main(int argc, char** argv) {
  Args a = parse_args(argc, argv);
  try {
    if (a.cmd == "deploy") return cmd_deploy(a);
    if (a.cmd == "undeploy") return cmd_undeploy(a);
    if (a.cmd == "ingress") return cmd_ingress(a);
    if (a.cmd == "status") return cmd_status(a);
    if (a.cmd == "manifest") return cmd_manifest(a);
    if (a.cmd == "-h" || a.cmd == "--help" || a.cmd == "help") {
      usage();
      return 0;
    }
  } catch (const std::exception& e) {
    std::fprintf(stderr, "tpuk: %s\n", e.what());
    return 1;
  }
  std::fprintf(stderr, "tpuk: unknown command '%s'\n", a.cmd.c_str());
  usage();
  return 2;
}
