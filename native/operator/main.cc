// h2o-tpu-operator — long-running controller (the reference's
// operator/src/main.rs [U]): ensure the CRD exists, then watch H2OTpu
// resources and reconcile (SURVEY.md §3.2).
#include <cstdio>
#include <cstring>
#include <string>

#include "../deployment/k8s_client.h"
#include "controller.h"

namespace {

void usage() {
  std::fprintf(stderr,
               "usage: h2o-tpu-operator [--kubeconfig PATH]"
               " [--server URL --token TOKEN [--insecure]] [--once]\n"
               "Defaults to $KUBECONFIG, ~/.kube/config, then in-cluster"
               " config.\n");
}

}  // namespace

int main(int argc, char** argv) {
  std::string kubeconfig, server, token;
  bool insecure = false;
  bool once = false;
  for (int i = 1; i < argc; ++i) {
    std::string a = argv[i];
    auto next = [&]() -> std::string {
      if (i + 1 >= argc) { usage(); std::exit(2); }
      return argv[++i];
    };
    if (a == "--kubeconfig") kubeconfig = next();
    else if (a == "--server") server = next();
    else if (a == "--token") token = next();
    else if (a == "--insecure") insecure = true;
    else if (a == "--once") once = true;
    else if (a == "-h" || a == "--help") { usage(); return 0; }
    else { usage(); return 2; }
  }
  try {
    tpuk::K8sConfig cfg;
    if (!server.empty()) {
      cfg.server = server;
      cfg.token = token;
      cfg.insecure_skip_verify = insecure;
    } else {
      cfg = tpuk::K8sConfig::resolve(kubeconfig);
    }
    auto api = tpuk::make_curl_client(cfg);
    tpuk::run_operator(*api, 300, once);
  } catch (const std::exception& e) {
    std::fprintf(stderr, "h2o-tpu-operator: fatal: %s\n", e.what());
    return 1;
  }
  return 0;
}
