#include "controller.h"

#include <chrono>
#include <cstdio>
#include <thread>

#include "../deployment/deploy.h"
#include "../deployment/manifests.h"

namespace tpuk {

namespace {

void log_line(const std::string& msg) {
  std::fprintf(stderr, "[operator] %s\n", msg.c_str());
}

// add/remove OUR finalizer only: read the live list first so
// finalizers owned by other controllers survive (merge-patch replaces
// arrays wholesale)
void patch_finalizers(ApiClient& api, const H2OTpu& cr, bool present) {
  Response cur = api.request("GET", h2otpus_path(cr.ns, cr.name));
  if (cur.not_found()) return;
  if (!cur.ok())
    throw std::runtime_error("finalizer read failed (" +
                             std::to_string(cur.status) + "): " + cur.body);
  Json body = cur.json();  // keep alive: get_path returns a view into it
  Json fins = Json::array();
  bool have_ours = false;
  if (const Json* live = body.get_path("metadata.finalizers");
      live && live->is_array())
    for (const Json& f : live->as_array()) {
      if (f.is_string() && f.as_string() == kFinalizer) {
        have_ours = true;
        if (!present) continue;  // drop ours, keep the rest
      }
      fins.as_array().push_back(f);
    }
  if (present) {
    if (have_ours) return;  // already there
    fins.as_array().push_back(Json(kFinalizer));
  } else if (!have_ours) {
    return;
  }
  Json patch = Json::object();
  patch["metadata"] = Json(JsonObject{{"finalizers", fins}});
  Response r = api.request("PATCH", h2otpus_path(cr.ns, cr.name),
                           patch.dump(), "application/merge-patch+json");
  if (!r.ok() && !r.not_found())
    throw std::runtime_error("finalizer patch failed (" +
                             std::to_string(r.status) + "): " + r.body);
}

void patch_status(ApiClient& api, const H2OTpu& cr,
                  const std::string& phase, int64_t ready) {
  Json status = Json::object();
  status["phase"] = phase;
  status["readyNodes"] = ready;
  status["coordinator"] = coordinator_address(cr);
  Json patch = Json::object();
  patch["status"] = status;
  // status subresource; merge-patch keeps this a single round trip
  Response r = api.request("PATCH",
                           h2otpus_path(cr.ns, cr.name) + "/status",
                           patch.dump(), "application/merge-patch+json");
  if (!r.ok() && !r.not_found())
    log_line("status patch failed (" + std::to_string(r.status) + ") for " +
             cr.ns + "/" + cr.name);
}

}  // namespace

bool ensure_crd(ApiClient& api) {
  Response r = api.request("GET", crd_path());
  if (r.ok()) return false;
  if (!r.not_found())
    throw std::runtime_error("CRD get failed (" + std::to_string(r.status) +
                             "): " + r.body);
  Response c = api.request(
      "POST", "/apis/apiextensions.k8s.io/v1/customresourcedefinitions",
      crd_manifest().dump());
  if (!c.ok() && !c.conflict())
    throw std::runtime_error("CRD create failed (" +
                             std::to_string(c.status) + "): " + c.body);
  return c.ok();
}

std::string reconcile(ApiClient& api, const H2OTpu& cr) {
  if (cr.deleting) {
    // teardown, then release the finalizer so K8s GC completes
    undeploy_cluster(api, cr.name, cr.ns);
    if (cr.has_finalizer) patch_finalizers(api, cr, false);
    return "deleted";
  }
  std::string action;
  if (!cr.has_finalizer) {
    patch_finalizers(api, cr, true);
    action += "finalizer ";
  }
  // ensure service
  Response svc = api.request("GET", services_path(cr.ns, cr.name));
  if (svc.not_found()) {
    Response r = api.request("POST", services_path(cr.ns),
                             headless_service(cr).dump());
    if (!r.ok() && !r.conflict())
      throw std::runtime_error("service create failed (" +
                               std::to_string(r.status) + "): " + r.body);
    action += "service ";
  }
  // ensure statefulset at the right size
  Response sts = api.request("GET", statefulsets_path(cr.ns, cr.name));
  int64_t ready = 0;
  if (sts.not_found()) {
    Response r = api.request("POST", statefulsets_path(cr.ns),
                             stateful_set(cr).dump());
    if (!r.ok() && !r.conflict())
      throw std::runtime_error("statefulset create failed (" +
                               std::to_string(r.status) + "): " + r.body);
    action += "statefulset ";
  } else if (sts.ok()) {
    Json body = sts.json();
    if (const Json* rd = body.get_path("status.readyReplicas");
        rd && rd->is_number())
      ready = rd->as_int();
    const Json* replicas = body.get_path("spec.replicas");
    if (replicas && replicas->is_number() &&
        replicas->as_int() != cr.spec.nodes) {
      // spec drift: a TPU cluster cannot resize in place (the cloud
      // locks at formation — SURVEY.md §5.3), so recreate wholesale
      Json patch = Json::object();
      patch["spec"] = Json(JsonObject{{"replicas", Json(cr.spec.nodes)}});
      Response r =
          api.request("PATCH", statefulsets_path(cr.ns, cr.name),
                      patch.dump(), "application/merge-patch+json");
      if (!r.ok())
        throw std::runtime_error("statefulset scale failed (" +
                                 std::to_string(r.status) + "): " + r.body);
      action += "rescale ";
    }
  }
  patch_status(api, cr, ready >= cr.spec.nodes ? "Ready" : "Forming",
               ready);
  return action.empty() ? "noop" : action;
}

void run_operator(ApiClient& api, long watch_timeout_s, bool once) {
  ensure_crd(api);
  log_line("CRD ensured; entering watch loop");
  std::string all_path =
      std::string("/apis/") + kGroup + "/" + kVersion + "/" + kPlural;
  int backoff_s = 1;
  while (true) {
    std::string resource_version;
    try {
      Response list = api.request("GET", all_path);
      if (!list.ok())
        throw std::runtime_error("list failed (" +
                                 std::to_string(list.status) + ")");
      Json body = list.json();
      if (const Json* rv = body.get_path("metadata.resourceVersion");
          rv && rv->is_string())
        resource_version = rv->as_string();
      if (const Json* items = body.find("items"); items && items->is_array())
        for (const Json& item : items->as_array()) {
          H2OTpu cr = H2OTpu::from_json(item);
          try {
            log_line(cr.ns + "/" + cr.name + ": " + reconcile(api, cr));
          } catch (const std::exception& e) {
            log_line(cr.ns + "/" + cr.name + ": reconcile error: " +
                     e.what());
          }
        }
      if (once) return;  // single list+reconcile sweep (CI e2e)
      backoff_s = 1;
    } catch (const std::exception& e) {
      if (once) throw;
      log_line(std::string("list error: ") + e.what() + "; backoff " +
               std::to_string(backoff_s) + "s");
      std::this_thread::sleep_for(std::chrono::seconds(backoff_s));
      backoff_s = std::min(backoff_s * 2, 60);
      continue;
    }
    std::string watch_path = all_path + "?watch=true&resourceVersion=" +
                             resource_version;
    api.watch(watch_path, [&](const std::string& line) {
      try {
        Json event = Json::parse(line);
        const Json* type = event.find("type");
        const Json* obj = event.find("object");
        if (!type || !obj) return;
        if (type->as_string() == "ERROR") {
          log_line("watch ERROR event: " + line.substr(0, 200));
          return;
        }
        H2OTpu cr = H2OTpu::from_json(*obj);
        log_line(cr.ns + "/" + cr.name + " [" + type->as_string() + "]: " +
                 reconcile(api, cr));
      } catch (const std::exception& e) {
        log_line(std::string("watch event error: ") + e.what());
      }
    }, watch_timeout_s);
  }
}

}  // namespace tpuk
