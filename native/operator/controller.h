// Reconcile loop for H2OTpu resources — the reference operator's
// controller (operator/src/controller.rs [U]; SURVEY.md §3.2):
// ensure CRD at startup, watch H2O resources, Applied → finalizer +
// Service/StatefulSet, Deleted → teardown + finalizer removal,
// idempotent re-reconcile on every event, errors → requeue w/ backoff.
#pragma once

#include <string>

#include "../deployment/crd.h"
#include "../deployment/k8s_client.h"

namespace tpuk {

// create the CRD if absent; true if created, false if it existed
bool ensure_crd(ApiClient& api);

// one idempotent reconcile of a single resource; returns a short
// human-readable action summary (used by logs and tests)
std::string reconcile(ApiClient& api, const H2OTpu& cr);

// list+watch loop; runs until the process is stopped. watch_timeout_s
// bounds each watch window (the loop re-lists after every window).
// once=true performs a single list+reconcile sweep and returns (the
// CI e2e drives this against a real control plane).
void run_operator(ApiClient& api, long watch_timeout_s = 300,
                  bool once = false);

}  // namespace tpuk
