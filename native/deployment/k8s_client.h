// Kubernetes API client — the role kube/k8s-openapi play for the
// reference (SURVEY.md §2a R3: "client bootstrap from kubeconfig").
// HTTP rides the system libcurl loaded via dlopen (no dev headers in
// this toolchain; the curl C ABI is stable).  ApiClient is an
// interface so the controller/deploy logic tests run against an
// in-memory fake — the manifests and reconcile decisions are what the
// golden tests pin down, per VERDICT round 1 ("golden-file tests for
// the generated manifests (no cluster needed)").
#pragma once

#include <functional>
#include <memory>
#include <string>

#include "json.h"

namespace tpuk {

struct Response {
  long status = 0;
  std::string body;

  bool ok() const { return status >= 200 && status < 300; }
  bool not_found() const { return status == 404; }
  bool conflict() const { return status == 409; }
  Json json() const { return Json::parse(body); }
};

class ApiClient {
 public:
  virtual ~ApiClient() = default;
  // method: GET/POST/PUT/DELETE/PATCH; path: absolute API path;
  // content_type matters for PATCH (strategic vs merge vs json-patch)
  virtual Response request(const std::string& method,
                           const std::string& path,
                           const std::string& body = "",
                           const std::string& content_type =
                               "application/json") = 0;
  // streaming watch: invokes on_line for every newline-delimited JSON
  // event until the server closes or timeout_s elapses; returns false
  // on transport error (caller re-lists and re-watches)
  virtual bool watch(const std::string& path,
                     const std::function<void(const std::string&)>& on_line,
                     long timeout_s) = 0;
};

struct K8sConfig {
  std::string server;        // https://host:port
  std::string token;         // bearer token ("" = none)
  std::string ca_cert_path;  // "" = system roots
  std::string client_cert_path;
  std::string client_key_path;
  bool insecure_skip_verify = false;

  // in-cluster service account (env + mounted secrets)
  static K8sConfig in_cluster();
  // kubeconfig file: native JSON kubeconfigs and the standard
  // kubectl-generated YAML layout (subset parser; no anchors/flow)
  static K8sConfig from_kubeconfig(const std::string& path);
  // resolution order of the reference's client bootstrap: explicit
  // path > $KUBECONFIG > ~/.kube/config > in-cluster
  static K8sConfig resolve(const std::string& explicit_path = "");
};

std::unique_ptr<ApiClient> make_curl_client(const K8sConfig& config);

// minimal YAML(subset)->Json used for kubeconfigs; exposed for tests.
// Supports nested maps/lists by indentation, scalars, quotes, comments.
Json yaml_to_json(const std::string& text);

}  // namespace tpuk
