#include "manifests.h"

namespace tpuk {

namespace {

Json labels_for(const H2OTpu& cr) {
  Json l = Json::object();
  l["app"] = cr.name;
  l["app.kubernetes.io/managed-by"] = "tpuk";
  return l;
}

Json env_var(const std::string& name, const std::string& value) {
  return Json(JsonObject{{"name", Json(name)}, {"value", Json(value)}});
}

Json env_from_label(const std::string& name, const std::string& label) {
  Json field = Json::object();
  field["fieldPath"] = "metadata.labels['" + label + "']";
  Json source = Json::object();
  source["fieldRef"] = field;
  return Json(JsonObject{{"name", Json(name)}, {"valueFrom", source}});
}

}  // namespace

std::string coordinator_address(const H2OTpu& cr) {
  // pod-0's stable DNS name through the headless service
  return cr.name + "-0." + cr.name + "." + cr.ns + ".svc.cluster.local:" +
         std::to_string(kCoordinatorPort);
}

Json owner_reference(const H2OTpu& cr) {
  Json ref = Json::object();
  ref["apiVersion"] = std::string(kGroup) + "/" + kVersion;
  ref["kind"] = kKind;
  ref["name"] = cr.name;
  if (!cr.uid.empty()) ref["uid"] = cr.uid;
  ref["controller"] = true;
  ref["blockOwnerDeletion"] = true;
  return ref;
}

Json headless_service(const H2OTpu& cr) {
  Json svc = Json::object();
  svc["apiVersion"] = "v1";
  svc["kind"] = "Service";
  Json meta = Json::object();
  meta["name"] = cr.name;
  meta["namespace"] = cr.ns;
  meta["labels"] = labels_for(cr);
  if (!cr.uid.empty())
    meta["ownerReferences"] = Json(JsonArray{owner_reference(cr)});
  svc["metadata"] = meta;

  Json spec = Json::object();
  spec["clusterIP"] = "None";  // headless: per-pod DNS records
  spec["selector"] = Json(JsonObject{{"app", Json(cr.name)}});
  // publish addresses before readiness so the coordinator (pod-0) is
  // resolvable while peers are still starting — the same bootstrapping
  // need the reference's DNS lookup loop has during cloud formation
  spec["publishNotReadyAddresses"] = true;
  Json client_port = Json::object();
  client_port["name"] = "client";
  client_port["port"] = kClientPort;
  client_port["protocol"] = "TCP";
  Json coord_port = Json::object();
  coord_port["name"] = "coordinator";
  coord_port["port"] = kCoordinatorPort;
  coord_port["protocol"] = "TCP";
  spec["ports"] = Json(JsonArray{client_port, coord_port});
  svc["spec"] = spec;
  return svc;
}

Json stateful_set(const H2OTpu& cr) {
  const H2OTpuSpec& s = cr.spec;

  Json container = Json::object();
  container["name"] = "h2o-tpu";
  container["image"] = s.image();
  Json env = Json::array();
  env.as_array().push_back(
      env_var("H2O_TPU_COORDINATOR", coordinator_address(cr)));
  env.as_array().push_back(
      env_var("H2O_TPU_NUM_PROCESSES", std::to_string(s.nodes)));
  // the StatefulSet controller stamps every pod with its ordinal in
  // the apps.kubernetes.io/pod-index label; downward API turns it
  // into the process id the JAX distributed runtime needs
  env.as_array().push_back(
      env_from_label("H2O_TPU_PROCESS_ID", "apps.kubernetes.io/pod-index"));
  env.as_array().push_back(env_var(
      "H2O_TPU_MEMORY_PERCENTAGE",
      std::to_string(s.resources.memory_percentage)));
  container["env"] = env;

  Json ports = Json::array();
  ports.as_array().push_back(Json(JsonObject{
      {"containerPort", Json(kClientPort)}, {"name", Json("client")}}));
  ports.as_array().push_back(Json(JsonObject{
      {"containerPort", Json(kCoordinatorPort)},
      {"name", Json("coordinator")}}));
  container["ports"] = ports;

  Json requests = Json::object();
  requests["cpu"] = s.resources.cpu;
  requests["memory"] = s.resources.memory;
  requests["google.com/tpu"] = std::to_string(s.tpu.chips_per_host);
  Json limits = Json::object();
  limits["memory"] = s.resources.memory;
  limits["google.com/tpu"] = std::to_string(s.tpu.chips_per_host);
  container["resources"] = Json(JsonObject{{"requests", requests},
                                           {"limits", limits}});

  // leader-only readiness (the reference's /kubernetes/isLeaderNode,
  // h2o-kubernetes [U]): the endpoint 503s on every non-leader process,
  // so the Service routes clients only to the one consistent node —
  // /3/Cloud would pass on ANY pod once its REST port is up
  Json probe = Json::object();
  probe["httpGet"] = Json(JsonObject{
      {"path", Json("/kubernetes/isLeaderNode")},
      {"port", Json(kClientPort)}});
  probe["initialDelaySeconds"] = 10;
  probe["periodSeconds"] = 5;
  container["readinessProbe"] = probe;

  Json pod_spec = Json::object();
  pod_spec["containers"] = Json(JsonArray{container});
  Json selector = Json::object();
  selector["cloud.google.com/gke-tpu-accelerator"] = s.tpu.accelerator;
  selector["cloud.google.com/gke-tpu-topology"] = s.tpu.topology;
  pod_spec["nodeSelector"] = selector;
  // TPU slices are all-or-nothing: never restart a single pod into a
  // locked cluster (the reference's clouds cannot absorb rejoins either
  // — SURVEY.md §5.3); the operator recreates the whole set instead
  pod_spec["restartPolicy"] = "Always";

  Json pod_meta = Json::object();
  pod_meta["labels"] = labels_for(cr);

  Json tmpl = Json::object();
  tmpl["metadata"] = pod_meta;
  tmpl["spec"] = pod_spec;

  Json sts_spec = Json::object();
  sts_spec["serviceName"] = cr.name;
  sts_spec["replicas"] = s.nodes;
  sts_spec["podManagementPolicy"] = "Parallel";  // all hosts boot at once
  sts_spec["selector"] = Json(JsonObject{
      {"matchLabels", Json(JsonObject{{"app", Json(cr.name)}})}});
  sts_spec["template"] = tmpl;

  Json sts = Json::object();
  sts["apiVersion"] = "apps/v1";
  sts["kind"] = "StatefulSet";
  Json meta = Json::object();
  meta["name"] = cr.name;
  meta["namespace"] = cr.ns;
  meta["labels"] = labels_for(cr);
  if (!cr.uid.empty())
    meta["ownerReferences"] = Json(JsonArray{owner_reference(cr)});
  sts["metadata"] = meta;
  sts["spec"] = sts_spec;
  return sts;
}

Json ingress(const H2OTpu& cr, const std::string& host) {
  Json backend = Json::object();
  backend["service"] = Json(JsonObject{
      {"name", Json(cr.name)},
      {"port", Json(JsonObject{{"number", Json(kClientPort)}})}});
  Json path = Json::object();
  path["path"] = "/";
  path["pathType"] = "Prefix";
  path["backend"] = backend;
  Json rule = Json::object();
  if (!host.empty()) rule["host"] = host;
  rule["http"] = Json(JsonObject{{"paths", Json(JsonArray{path})}});

  Json ing = Json::object();
  ing["apiVersion"] = "networking.k8s.io/v1";
  ing["kind"] = "Ingress";
  Json meta = Json::object();
  meta["name"] = cr.name;
  meta["namespace"] = cr.ns;
  meta["labels"] = labels_for(cr);
  if (!cr.uid.empty())
    meta["ownerReferences"] = Json(JsonArray{owner_reference(cr)});
  ing["metadata"] = meta;
  ing["spec"] = Json(JsonObject{{"rules", Json(JsonArray{rule})}});
  return ing;
}

}  // namespace tpuk
