// Kubernetes resource construction for an H2OTpu cluster — the analog
// of the reference deployment crate's StatefulSet/Service/Ingress
// builders (deployment/src/lib.rs, ingress.rs [U]; SURVEY.md §2a R3,
// §3.1).  The reference injects H2O_KUBERNETES_SERVICE_DNS /
// H2O_NODE_EXPECTED_COUNT / H2O_NODE_LOOKUP_TIMEOUT so H2O-3's k8s
// module can DNS-discover peers; here the pods form a JAX distributed
// runtime instead, so the injected contract is H2O_TPU_COORDINATOR
// (pod-0's stable DNS name via the headless Service),
// H2O_TPU_NUM_PROCESSES (spec.nodes) and H2O_TPU_PROCESS_ID (the pod's
// StatefulSet ordinal, read from the apps.kubernetes.io/pod-index
// label via the downward API).
#pragma once

#include <string>

#include "crd.h"
#include "json.h"

namespace tpuk {

// headless Service (clusterIP: None) — stable per-pod DNS, the
// discovery substrate (same move as the reference's service)
Json headless_service(const H2OTpu& cr);

// StatefulSet sized to spec.nodes with TPU nodeselectors, resource
// requests (cpu/memory + google.com/tpu), and the clustering env
Json stateful_set(const H2OTpu& cr);

// Ingress routing external clients to the leader (pod-0) service port
Json ingress(const H2OTpu& cr, const std::string& host);

// ownerReference blocks child GC on the parent CR (plus our finalizer
// mirrors the reference's delete path)
Json owner_reference(const H2OTpu& cr);

std::string coordinator_address(const H2OTpu& cr);

}  // namespace tpuk
