#include "crd.h"

namespace tpuk {

H2OTpuSpec H2OTpuSpec::from_json(const Json& spec) {
  H2OTpuSpec s;
  s.nodes = static_cast<int>(spec.int_or("nodes", 1));
  if (s.nodes < 1) throw std::runtime_error("spec.nodes must be >= 1");
  s.version = spec.string_or("version", "latest");
  if (const Json* ci = spec.find("customImage"); ci && ci->is_string())
    s.custom_image = ci->as_string();
  if (const Json* r = spec.find("resources")) {
    s.resources.cpu = r->string_or("cpu", s.resources.cpu);
    s.resources.memory = r->string_or("memory", s.resources.memory);
    s.resources.memory_percentage = static_cast<int>(
        r->int_or("memoryPercentage", s.resources.memory_percentage));
    if (s.resources.memory_percentage < 1 ||
        s.resources.memory_percentage > 100)
      throw std::runtime_error("spec.resources.memoryPercentage not in 1..100");
  }
  if (const Json* t = spec.find("tpu")) {
    s.tpu.accelerator = t->string_or("accelerator", s.tpu.accelerator);
    s.tpu.topology = t->string_or("topology", s.tpu.topology);
    s.tpu.chips_per_host =
        static_cast<int>(t->int_or("chipsPerHost", s.tpu.chips_per_host));
    if (s.tpu.chips_per_host < 1)
      throw std::runtime_error("spec.tpu.chipsPerHost must be >= 1");
  }
  return s;
}

Json H2OTpuSpec::to_json() const {
  Json spec = Json::object();
  spec["nodes"] = nodes;
  spec["version"] = version;
  if (custom_image) spec["customImage"] = *custom_image;
  Json res = Json::object();
  res["cpu"] = resources.cpu;
  res["memory"] = resources.memory;
  res["memoryPercentage"] = resources.memory_percentage;
  spec["resources"] = res;
  Json tpu_j = Json::object();
  tpu_j["accelerator"] = tpu.accelerator;
  tpu_j["topology"] = tpu.topology;
  tpu_j["chipsPerHost"] = tpu.chips_per_host;
  spec["tpu"] = tpu_j;
  return spec;
}

H2OTpu H2OTpu::from_json(const Json& obj) {
  H2OTpu cr;
  const Json* meta = obj.find("metadata");
  if (!meta) throw std::runtime_error("resource has no metadata");
  cr.name = meta->string_or("name", "");
  if (cr.name.empty()) throw std::runtime_error("resource has no name");
  cr.ns = meta->string_or("namespace", "default");
  cr.uid = meta->string_or("uid", "");
  cr.resource_version = meta->string_or("resourceVersion", "");
  cr.deleting = meta->find("deletionTimestamp") != nullptr;
  if (const Json* fins = meta->find("finalizers"); fins && fins->is_array())
    for (const Json& f : fins->as_array())
      if (f.is_string() && f.as_string() == kFinalizer)
        cr.has_finalizer = true;
  const Json* spec = obj.find("spec");
  cr.spec = spec ? H2OTpuSpec::from_json(*spec) : H2OTpuSpec{};
  return cr;
}

Json H2OTpu::to_json() const {
  Json obj = Json::object();
  obj["apiVersion"] = std::string(kGroup) + "/" + kVersion;
  obj["kind"] = kKind;
  Json meta = Json::object();
  meta["name"] = name;
  meta["namespace"] = ns;
  if (has_finalizer) meta["finalizers"] = Json(JsonArray{Json(kFinalizer)});
  obj["metadata"] = meta;
  obj["spec"] = spec.to_json();
  return obj;
}

Json crd_manifest() {
  // openAPIV3Schema kept permissive-but-typed, like the reference's
  // schema for {nodes, version, resources} (crd.rs [U])
  Json props = Json::object();
  props["nodes"] = Json(JsonObject{{"type", Json("integer")},
                                   {"minimum", Json(1)}});
  props["version"] = Json(JsonObject{{"type", Json("string")}});
  props["customImage"] = Json(JsonObject{{"type", Json("string")}});
  Json res_props = Json::object();
  res_props["cpu"] = Json(JsonObject{{"type", Json("string")}});
  res_props["memory"] = Json(JsonObject{{"type", Json("string")}});
  res_props["memoryPercentage"] = Json(JsonObject{
      {"type", Json("integer")}, {"minimum", Json(1)},
      {"maximum", Json(100)}});
  props["resources"] = Json(JsonObject{{"type", Json("object")},
                                       {"properties", Json(res_props)}});
  Json tpu_props = Json::object();
  tpu_props["accelerator"] = Json(JsonObject{{"type", Json("string")}});
  tpu_props["topology"] = Json(JsonObject{{"type", Json("string")}});
  tpu_props["chipsPerHost"] = Json(JsonObject{{"type", Json("integer")},
                                              {"minimum", Json(1)}});
  props["tpu"] = Json(JsonObject{{"type", Json("object")},
                                 {"properties", Json(tpu_props)}});

  Json schema = Json::object();
  schema["type"] = "object";
  schema["properties"] = Json(JsonObject{
      {"spec", Json(JsonObject{{"type", Json("object")},
                               {"properties", Json(props)}})},
      {"status", Json(JsonObject{
          {"type", Json("object")},
          {"x-kubernetes-preserve-unknown-fields", Json(true)}})}});

  Json version = Json::object();
  version["name"] = kVersion;
  version["served"] = true;
  version["storage"] = true;
  version["schema"] = Json(JsonObject{{"openAPIV3Schema", schema}});
  version["subresources"] = Json(JsonObject{{"status", Json::object()}});

  Json crd = Json::object();
  crd["apiVersion"] = "apiextensions.k8s.io/v1";
  crd["kind"] = "CustomResourceDefinition";
  crd["metadata"] = Json(JsonObject{
      {"name", Json(std::string(kPlural) + "." + kGroup)}});
  Json spec = Json::object();
  spec["group"] = kGroup;
  spec["scope"] = "Namespaced";
  spec["names"] = Json(JsonObject{
      {"plural", Json(kPlural)},
      {"singular", Json("h2otpu")},
      {"kind", Json(kKind)},
      {"shortNames", Json(JsonArray{Json("h2ot")})}});
  spec["versions"] = Json(JsonArray{version});
  crd["spec"] = spec;
  return crd;
}

}  // namespace tpuk
