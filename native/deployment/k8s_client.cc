#include "k8s_client.h"

#include <dlfcn.h>
#include <unistd.h>

#include <cstdlib>
#include <cstring>
#include <fstream>
#include <sstream>
#include <stdexcept>
#include <vector>

namespace tpuk {

// ---------------------------------------------------------------- yaml

namespace {

struct YamlLine {
  int indent;
  std::string content;  // stripped of indent and trailing comment
};

std::string strip_comment(const std::string& s) {
  bool in_s = false, in_d = false;
  for (size_t i = 0; i < s.size(); ++i) {
    char c = s[i];
    if (c == '\'' && !in_d) in_s = !in_s;
    else if (c == '"' && !in_s) in_d = !in_d;
    else if (c == '#' && !in_s && !in_d &&
             (i == 0 || s[i - 1] == ' ' || s[i - 1] == '\t'))
      return s.substr(0, i);
  }
  return s;
}

std::string trim(const std::string& s) {
  size_t a = s.find_first_not_of(" \t\r");
  if (a == std::string::npos) return "";
  size_t b = s.find_last_not_of(" \t\r");
  return s.substr(a, b - a + 1);
}

Json scalar(const std::string& raw) {
  std::string v = trim(raw);
  if (v.size() >= 2 && ((v.front() == '"' && v.back() == '"') ||
                        (v.front() == '\'' && v.back() == '\'')))
    return Json(v.substr(1, v.size() - 2));
  if (v == "null" || v == "~" || v.empty()) return Json(nullptr);
  if (v == "true") return Json(true);
  if (v == "false") return Json(false);
  char* end = nullptr;
  double d = std::strtod(v.c_str(), &end);
  if (end && *end == '\0' && end != v.c_str()) return Json(d);
  return Json(v);
}

Json parse_block(const std::vector<YamlLine>& lines, size_t& i, int indent);

Json parse_entry_value(const std::vector<YamlLine>& lines, size_t& i,
                       int parent_indent, const std::string& inline_val) {
  std::string v = trim(inline_val);
  if (!v.empty()) return scalar(v);
  // value on following deeper-indented lines (map or list); YAML also
  // allows list items at the PARENT key's indent (the kubectl layout)
  if (i < lines.size() &&
      (lines[i].indent > parent_indent ||
       (lines[i].indent == parent_indent &&
        (lines[i].content.rfind("- ", 0) == 0 || lines[i].content == "-"))))
    return parse_block(lines, i, lines[i].indent);
  return Json(nullptr);
}

Json parse_block(const std::vector<YamlLine>& lines, size_t& i, int indent) {
  if (i >= lines.size()) return Json(nullptr);
  if (lines[i].content.rfind("- ", 0) == 0 || lines[i].content == "-") {
    JsonArray arr;
    while (i < lines.size() && lines[i].indent == indent &&
           (lines[i].content.rfind("- ", 0) == 0 || lines[i].content == "-")) {
      std::string rest = lines[i].content == "-"
                             ? ""
                             : trim(lines[i].content.substr(2));
      ++i;
      if (rest.empty()) {
        arr.push_back(parse_entry_value(lines, i, indent, ""));
      } else if (rest.find(": ") != std::string::npos ||
                 rest.back() == ':') {
        // "- key: val" opens an inline map; fold in subsequent deeper
        // keys (the kubectl kubeconfig list-of-maps shape)
        size_t colon = rest.find(':');
        std::string k = trim(rest.substr(0, colon));
        std::string v = colon + 1 < rest.size() ? rest.substr(colon + 1) : "";
        JsonObject obj;
        obj.emplace(k, parse_entry_value(lines, i, indent, v));
        while (i < lines.size() && lines[i].indent > indent &&
               lines[i].content.rfind("- ", 0) != 0) {
          const std::string& c = lines[i].content;
          size_t c2 = c.find(':');
          if (c2 == std::string::npos)
            throw std::runtime_error("yaml: bad mapping line: " + c);
          std::string k2 = trim(c.substr(0, c2));
          std::string v2 = c2 + 1 < c.size() ? c.substr(c2 + 1) : "";
          int child_indent = lines[i].indent;
          ++i;
          obj.emplace(k2, parse_entry_value(lines, i, child_indent, v2));
        }
        arr.push_back(Json(std::move(obj)));
      } else {
        arr.push_back(scalar(rest));
      }
    }
    return Json(std::move(arr));
  }
  JsonObject obj;
  while (i < lines.size() && lines[i].indent == indent) {
    const std::string& c = lines[i].content;
    if (c.rfind("- ", 0) == 0) break;
    size_t colon = c.find(':');
    if (colon == std::string::npos)
      throw std::runtime_error("yaml: bad mapping line: " + c);
    std::string k = trim(c.substr(0, colon));
    if (k.size() >= 2 && ((k.front() == '"' && k.back() == '"') ||
                          (k.front() == '\'' && k.back() == '\'')))
      k = k.substr(1, k.size() - 2);
    std::string v = colon + 1 < c.size() ? c.substr(colon + 1) : "";
    ++i;
    obj.emplace(k, parse_entry_value(lines, i, indent, v));
  }
  return Json(std::move(obj));
}

}  // namespace

Json yaml_to_json(const std::string& text) {
  std::vector<YamlLine> lines;
  std::istringstream in(text);
  std::string raw;
  while (std::getline(in, raw)) {
    std::string s = strip_comment(raw);
    size_t ind = s.find_first_not_of(' ');
    if (ind == std::string::npos) continue;
    std::string content = trim(s.substr(ind));
    if (content.empty() || content == "---") continue;
    lines.push_back({static_cast<int>(ind), content});
  }
  if (lines.empty()) return Json(nullptr);
  size_t i = 0;
  return parse_block(lines, i, lines[0].indent);
}

// -------------------------------------------------------------- config

namespace {

std::string read_file(const std::string& path) {
  std::ifstream f(path, std::ios::binary);
  if (!f) throw std::runtime_error("cannot read " + path);
  std::ostringstream ss;
  ss << f.rdbuf();
  return ss.str();
}

bool file_exists(const std::string& path) {
  return ::access(path.c_str(), R_OK) == 0;
}

// base64 decode (kubeconfig *-data fields)
std::string b64_decode(const std::string& in) {
  auto val = [](char c) -> int {
    if (c >= 'A' && c <= 'Z') return c - 'A';
    if (c >= 'a' && c <= 'z') return c - 'a' + 26;
    if (c >= '0' && c <= '9') return c - '0' + 52;
    if (c == '+') return 62;
    if (c == '/') return 63;
    return -1;
  };
  std::string out;
  int buf = 0, bits = 0;
  for (char c : in) {
    int v = val(c);
    if (v < 0) continue;
    buf = (buf << 6) | v;
    bits += 6;
    if (bits >= 8) {
      bits -= 8;
      out += static_cast<char>((buf >> bits) & 0xFF);
    }
  }
  return out;
}

// write decoded cert material to a private temp file, return its path
std::string materialize(const std::string& data, const std::string& tag) {
  std::string tmpl = "/tmp/tpuk-" + tag + "-XXXXXX";
  std::vector<char> buf(tmpl.begin(), tmpl.end());
  buf.push_back('\0');
  int fd = ::mkstemp(buf.data());
  if (fd < 0) throw std::runtime_error("mkstemp failed for " + tag);
  std::string decoded = b64_decode(data);
  ssize_t n = ::write(fd, decoded.data(), decoded.size());
  ::close(fd);
  if (n != static_cast<ssize_t>(decoded.size()))
    throw std::runtime_error("short write for " + tag);
  return std::string(buf.data());
}

const Json* find_named(const Json& list, const std::string& name) {
  if (!list.is_array()) return nullptr;
  for (const Json& item : list.as_array())
    if (const Json* n = item.find("name");
        n && n->is_string() && n->as_string() == name)
      return &item;
  return nullptr;
}

}  // namespace

K8sConfig K8sConfig::in_cluster() {
  const char* host = std::getenv("KUBERNETES_SERVICE_HOST");
  const char* port = std::getenv("KUBERNETES_SERVICE_PORT");
  if (!host || !port)
    throw std::runtime_error("not in cluster (no KUBERNETES_SERVICE_HOST)");
  K8sConfig c;
  c.server = std::string("https://") + host + ":" + port;
  const char* base = "/var/run/secrets/kubernetes.io/serviceaccount";
  c.token = trim(read_file(std::string(base) + "/token"));
  std::string ca = std::string(base) + "/ca.crt";
  if (file_exists(ca)) c.ca_cert_path = ca;
  return c;
}

K8sConfig K8sConfig::from_kubeconfig(const std::string& path) {
  std::string text = read_file(path);
  Json cfg;
  try {
    cfg = Json::parse(text);  // kubeconfigs may be JSON outright
  } catch (const std::exception&) {
    cfg = yaml_to_json(text);
  }
  std::string ctx_name = cfg.string_or("current-context", "");
  const Json* contexts = cfg.find("contexts");
  const Json* ctx_entry =
      contexts && !ctx_name.empty() ? find_named(*contexts, ctx_name)
      : (contexts && contexts->is_array() && !contexts->as_array().empty()
             ? &contexts->as_array()[0]
             : nullptr);
  if (!ctx_entry) throw std::runtime_error("kubeconfig: no usable context");
  const Json* ctx = ctx_entry->find("context");
  if (!ctx) throw std::runtime_error("kubeconfig: context missing body");

  const Json* clusters = cfg.find("clusters");
  const Json* cluster_entry =
      clusters ? find_named(*clusters, ctx->string_or("cluster", ""))
               : nullptr;
  if (!cluster_entry) throw std::runtime_error("kubeconfig: cluster missing");
  const Json* cluster = cluster_entry->find("cluster");
  if (!cluster) throw std::runtime_error("kubeconfig: cluster missing body");

  K8sConfig c;
  c.server = cluster->string_or("server", "");
  if (c.server.empty()) throw std::runtime_error("kubeconfig: no server");
  if (const Json* ca = cluster->find("certificate-authority");
      ca && ca->is_string())
    c.ca_cert_path = ca->as_string();
  else if (const Json* cad = cluster->find("certificate-authority-data");
           cad && cad->is_string())
    c.ca_cert_path = materialize(cad->as_string(), "ca");
  if (const Json* skip = cluster->find("insecure-skip-tls-verify");
      skip && skip->is_bool())
    c.insecure_skip_verify = skip->as_bool();

  const Json* users = cfg.find("users");
  const Json* user_entry =
      users ? find_named(*users, ctx->string_or("user", "")) : nullptr;
  if (user_entry) {
    const Json* user = user_entry->find("user");
    if (user) {
      c.token = user->string_or("token", "");
      if (const Json* cc = user->find("client-certificate");
          cc && cc->is_string())
        c.client_cert_path = cc->as_string();
      else if (const Json* ccd = user->find("client-certificate-data");
               ccd && ccd->is_string())
        c.client_cert_path = materialize(ccd->as_string(), "cert");
      if (const Json* ck = user->find("client-key"); ck && ck->is_string())
        c.client_key_path = ck->as_string();
      else if (const Json* ckd = user->find("client-key-data");
               ckd && ckd->is_string())
        c.client_key_path = materialize(ckd->as_string(), "key");
    }
  }
  return c;
}

K8sConfig K8sConfig::resolve(const std::string& explicit_path) {
  if (!explicit_path.empty()) return from_kubeconfig(explicit_path);
  if (const char* env = std::getenv("KUBECONFIG"); env && *env)
    return from_kubeconfig(env);
  if (const char* home = std::getenv("HOME")) {
    std::string def = std::string(home) + "/.kube/config";
    if (file_exists(def)) return from_kubeconfig(def);
  }
  return in_cluster();
}

// ---------------------------------------------------------------- curl

namespace {

// hand-declared slice of the libcurl C ABI (stable since 7.x); the
// toolchain ships libcurl.so.4 but no headers
using CURL = void;
struct curl_slist;

constexpr int CURLOPT_WRITEDATA = 10001;
constexpr int CURLOPT_URL = 10002;
constexpr int CURLOPT_POSTFIELDS = 10015;
constexpr int CURLOPT_HTTPHEADER = 10023;
constexpr int CURLOPT_CUSTOMREQUEST = 10036;
constexpr int CURLOPT_POSTFIELDSIZE = 60;
constexpr int CURLOPT_SSL_VERIFYPEER = 64;
constexpr int CURLOPT_CAINFO = 10065;
constexpr int CURLOPT_SSL_VERIFYHOST = 81;
constexpr int CURLOPT_SSLCERT = 10025;
constexpr int CURLOPT_SSLKEY = 10087;
constexpr int CURLOPT_WRITEFUNCTION = 20011;
constexpr int CURLOPT_TIMEOUT = 13;
constexpr int CURLOPT_NOSIGNAL = 99;
constexpr int CURLINFO_RESPONSE_CODE = 0x200000 + 2;

struct CurlApi {
  CURL* (*easy_init)();
  int (*easy_setopt)(CURL*, int, ...);
  int (*easy_perform)(CURL*);
  void (*easy_cleanup)(CURL*);
  int (*easy_getinfo)(CURL*, int, ...);
  curl_slist* (*slist_append)(curl_slist*, const char*);
  void (*slist_free_all)(curl_slist*);
  const char* (*easy_strerror)(int);

  static const CurlApi& get() {
    static CurlApi api = load();
    return api;
  }

 private:
  static CurlApi load() {
    void* lib = ::dlopen("libcurl.so.4", RTLD_NOW | RTLD_GLOBAL);
    if (!lib) lib = ::dlopen("libcurl-gnutls.so.4", RTLD_NOW | RTLD_GLOBAL);
    if (!lib)
      throw std::runtime_error(std::string("cannot load libcurl: ") +
                               ::dlerror());
    CurlApi api;
    auto sym = [&](const char* name) {
      void* p = ::dlsym(lib, name);
      if (!p)
        throw std::runtime_error(std::string("libcurl missing symbol ") +
                                 name);
      return p;
    };
    api.easy_init = reinterpret_cast<CURL* (*)()>(sym("curl_easy_init"));
    api.easy_setopt = reinterpret_cast<int (*)(CURL*, int, ...)>(
        sym("curl_easy_setopt"));
    api.easy_perform =
        reinterpret_cast<int (*)(CURL*)>(sym("curl_easy_perform"));
    api.easy_cleanup =
        reinterpret_cast<void (*)(CURL*)>(sym("curl_easy_cleanup"));
    api.easy_getinfo = reinterpret_cast<int (*)(CURL*, int, ...)>(
        sym("curl_easy_getinfo"));
    api.slist_append = reinterpret_cast<curl_slist* (*)(
        curl_slist*, const char*)>(sym("curl_slist_append"));
    api.slist_free_all = reinterpret_cast<void (*)(curl_slist*)>(
        sym("curl_slist_free_all"));
    api.easy_strerror =
        reinterpret_cast<const char* (*)(int)>(sym("curl_easy_strerror"));
    return api;
  }
};

size_t collect_body(char* data, size_t size, size_t nmemb, void* userp) {
  auto* out = static_cast<std::string*>(userp);
  out->append(data, size * nmemb);
  return size * nmemb;
}

struct LineSink {
  std::string pending;
  const std::function<void(const std::string&)>* on_line;
};

size_t collect_lines(char* data, size_t size, size_t nmemb, void* userp) {
  auto* sink = static_cast<LineSink*>(userp);
  sink->pending.append(data, size * nmemb);
  size_t pos;
  while ((pos = sink->pending.find('\n')) != std::string::npos) {
    std::string line = sink->pending.substr(0, pos);
    sink->pending.erase(0, pos + 1);
    if (!line.empty()) (*sink->on_line)(line);
  }
  return size * nmemb;
}

class CurlClient final : public ApiClient {
 public:
  explicit CurlClient(K8sConfig config) : config_(std::move(config)) {}

  Response request(const std::string& method, const std::string& path,
                   const std::string& body,
                   const std::string& content_type) override {
    const CurlApi& api = CurlApi::get();
    CURL* h = api.easy_init();
    if (!h) throw std::runtime_error("curl_easy_init failed");
    Response resp;
    curl_slist* headers = build_headers(api, content_type);
    std::string url = config_.server + path;
    api.easy_setopt(h, CURLOPT_URL, url.c_str());
    api.easy_setopt(h, CURLOPT_CUSTOMREQUEST, method.c_str());
    api.easy_setopt(h, CURLOPT_NOSIGNAL, 1L);
    api.easy_setopt(h, CURLOPT_TIMEOUT, 60L);
    api.easy_setopt(h, CURLOPT_HTTPHEADER, headers);
    apply_tls(api, h);
    if (!body.empty()) {
      api.easy_setopt(h, CURLOPT_POSTFIELDS, body.c_str());
      api.easy_setopt(h, CURLOPT_POSTFIELDSIZE,
                      static_cast<long>(body.size()));
    }
    api.easy_setopt(h, CURLOPT_WRITEFUNCTION, &collect_body);
    api.easy_setopt(h, CURLOPT_WRITEDATA, &resp.body);
    int rc = api.easy_perform(h);
    if (rc == 0) api.easy_getinfo(h, CURLINFO_RESPONSE_CODE, &resp.status);
    api.slist_free_all(headers);
    api.easy_cleanup(h);
    if (rc != 0)
      throw std::runtime_error(std::string("curl: ") +
                               api.easy_strerror(rc) + " for " + url);
    return resp;
  }

  bool watch(const std::string& path,
             const std::function<void(const std::string&)>& on_line,
             long timeout_s) override {
    const CurlApi& api = CurlApi::get();
    CURL* h = api.easy_init();
    if (!h) throw std::runtime_error("curl_easy_init failed");
    curl_slist* headers = build_headers(api, "application/json");
    std::string url = config_.server + path;
    LineSink sink{{}, &on_line};
    api.easy_setopt(h, CURLOPT_URL, url.c_str());
    api.easy_setopt(h, CURLOPT_NOSIGNAL, 1L);
    api.easy_setopt(h, CURLOPT_TIMEOUT, timeout_s);
    api.easy_setopt(h, CURLOPT_HTTPHEADER, headers);
    apply_tls(api, h);
    api.easy_setopt(h, CURLOPT_WRITEFUNCTION, &collect_lines);
    api.easy_setopt(h, CURLOPT_WRITEDATA, &sink);
    int rc = api.easy_perform(h);
    api.slist_free_all(headers);
    api.easy_cleanup(h);
    // timeout (rc 28) is the normal end of a watch window
    return rc == 0 || rc == 28;
  }

 private:
  curl_slist* build_headers(const CurlApi& api,
                            const std::string& content_type) {
    curl_slist* headers = nullptr;
    headers = api.slist_append(
        headers, ("Content-Type: " + content_type).c_str());
    headers = api.slist_append(headers, "Accept: application/json");
    if (!config_.token.empty())
      headers = api.slist_append(
          headers, ("Authorization: Bearer " + config_.token).c_str());
    return headers;
  }

  void apply_tls(const CurlApi& api, CURL* h) {
    if (config_.insecure_skip_verify) {
      api.easy_setopt(h, CURLOPT_SSL_VERIFYPEER, 0L);
      api.easy_setopt(h, CURLOPT_SSL_VERIFYHOST, 0L);
    } else if (!config_.ca_cert_path.empty()) {
      api.easy_setopt(h, CURLOPT_CAINFO, config_.ca_cert_path.c_str());
    }
    if (!config_.client_cert_path.empty())
      api.easy_setopt(h, CURLOPT_SSLCERT, config_.client_cert_path.c_str());
    if (!config_.client_key_path.empty())
      api.easy_setopt(h, CURLOPT_SSLKEY, config_.client_key_path.c_str());
  }

  K8sConfig config_;
};

}  // namespace

std::unique_ptr<ApiClient> make_curl_client(const K8sConfig& config) {
  return std::make_unique<CurlClient>(config);
}

}  // namespace tpuk
