// The H2OTpu custom resource: declarative spec for a TPU-backed
// h2o_kubernetes_tpu cluster, the analog of the reference's `kind: H2O`
// CRD (group h2o.ai, spec {nodes, version|customImage,
// resources{cpu,memory,memoryPercentage}} — deployment/src/crd.rs [U],
// SURVEY.md §1a/§2a R3).  Differences are deliberate and TPU-first: the
// spec names a TPU accelerator/topology (provisioned as GKE TPU slice
// pods) and the injected env is the JAX distributed-runtime contract
// (H2O_TPU_COORDINATOR / H2O_TPU_NUM_PROCESSES / H2O_TPU_PROCESS_ID,
// consumed by h2o_kubernetes_tpu.runtime.mesh.initialize_distributed)
// instead of H2O-3's flatfile DNS lookup vars.
#pragma once

#include <optional>
#include <string>

#include "json.h"

namespace tpuk {

inline constexpr const char* kGroup = "tpu.h2o.ai";
inline constexpr const char* kVersion = "v1";
inline constexpr const char* kKind = "H2OTpu";
inline constexpr const char* kPlural = "h2otpus";
inline constexpr const char* kFinalizer = "tpu.h2o.ai/finalizer";
inline constexpr const char* kDefaultImage = "h2o-kubernetes-tpu";
inline constexpr int kClientPort = 54321;   // REST/client port (reference's)
inline constexpr int kCoordinatorPort = 8476;  // jax.distributed coordinator

struct Resources {
  std::string cpu = "4";        // k8s quantity
  std::string memory = "16Gi";  // k8s quantity
  // fraction of pod memory handed to the runtime process (the
  // reference's memoryPercentage flag for the JVM -Xmx)
  int memory_percentage = 90;
};

struct TpuSpec {
  // GKE TPU nodeselector values, e.g. "tpu-v5-lite-podslice" / "2x4"
  std::string accelerator = "tpu-v5-lite-podslice";
  std::string topology = "2x4";
  int chips_per_host = 4;       // google.com/tpu resource request
};

struct H2OTpuSpec {
  int nodes = 1;                // hosts (pods); 1 pod slice = 1 cluster
  std::string version = "latest";
  std::optional<std::string> custom_image;
  Resources resources;
  TpuSpec tpu;

  std::string image() const {
    return custom_image ? *custom_image
                        : std::string(kDefaultImage) + ":" + version;
  }

  static H2OTpuSpec from_json(const Json& spec);  // throws on bad spec
  Json to_json() const;
};

// a named+namespaced custom resource as seen on the API server
struct H2OTpu {
  std::string name;
  std::string ns = "default";
  H2OTpuSpec spec;
  std::string uid;               // set by the API server
  std::string resource_version;  // set by the API server
  bool deleting = false;         // deletionTimestamp present
  bool has_finalizer = false;

  static H2OTpu from_json(const Json& obj);
  Json to_json() const;  // apiVersion/kind/metadata/spec (no status)
};

// the CustomResourceDefinition manifest the operator ensures at startup
// (reference: operator ensures `h2os.h2o.ai` exists — SURVEY.md §3.2)
Json crd_manifest();

}  // namespace tpuk
