#include "deploy.h"

#include <chrono>
#include <fstream>
#include <stdexcept>
#include <thread>

#include "manifests.h"

namespace tpuk {

std::string services_path(const std::string& ns, const std::string& name) {
  std::string p = "/api/v1/namespaces/" + ns + "/services";
  return name.empty() ? p : p + "/" + name;
}

std::string statefulsets_path(const std::string& ns,
                              const std::string& name) {
  std::string p = "/apis/apps/v1/namespaces/" + ns + "/statefulsets";
  return name.empty() ? p : p + "/" + name;
}

std::string ingresses_path(const std::string& ns, const std::string& name) {
  std::string p =
      "/apis/networking.k8s.io/v1/namespaces/" + ns + "/ingresses";
  return name.empty() ? p : p + "/" + name;
}

std::string h2otpus_path(const std::string& ns, const std::string& name) {
  std::string p = std::string("/apis/") + kGroup + "/" + kVersion +
                  "/namespaces/" + ns + "/" + kPlural;
  return name.empty() ? p : p + "/" + name;
}

std::string crd_path() {
  return std::string("/apis/apiextensions.k8s.io/v1/"
                     "customresourcedefinitions/") +
         kPlural + "." + kGroup;
}

namespace {

void create_tolerating_conflict(ApiClient& api, const std::string& path,
                                const Json& manifest,
                                const std::string& what) {
  Response r = api.request("POST", path, manifest.dump());
  if (!r.ok() && !r.conflict())
    throw std::runtime_error("create " + what + " failed (" +
                             std::to_string(r.status) + "): " + r.body);
}

void delete_tolerating_missing(ApiClient& api, const std::string& path,
                               const std::string& what) {
  Response r = api.request("DELETE", path);
  if (!r.ok() && !r.not_found())
    throw std::runtime_error("delete " + what + " failed (" +
                             std::to_string(r.status) + "): " + r.body);
}

}  // namespace

void deploy_cluster(ApiClient& api, const H2OTpu& cr) {
  create_tolerating_conflict(api, services_path(cr.ns),
                             headless_service(cr), "service " + cr.name);
  create_tolerating_conflict(api, statefulsets_path(cr.ns),
                             stateful_set(cr), "statefulset " + cr.name);
}

void undeploy_cluster(ApiClient& api, const std::string& name,
                      const std::string& ns) {
  delete_tolerating_missing(api, statefulsets_path(ns, name),
                            "statefulset " + name);
  delete_tolerating_missing(api, services_path(ns, name), "service " + name);
  delete_tolerating_missing(api, ingresses_path(ns, name), "ingress " + name);
}

void create_ingress(ApiClient& api, const H2OTpu& cr,
                    const std::string& host) {
  create_tolerating_conflict(api, ingresses_path(cr.ns), ingress(cr, host),
                             "ingress " + cr.name);
}

void delete_ingress(ApiClient& api, const std::string& name,
                    const std::string& ns) {
  delete_tolerating_missing(api, ingresses_path(ns, name), "ingress " + name);
}

bool wait_ready(ApiClient& api, const H2OTpu& cr, int timeout_s,
                int poll_interval_s) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::seconds(timeout_s);
  while (std::chrono::steady_clock::now() < deadline) {
    Response r = api.request("GET", statefulsets_path(cr.ns, cr.name));
    if (r.ok()) {
      Json sts = r.json();
      if (const Json* ready = sts.get_path("status.readyReplicas");
          ready && ready->is_number() &&
          ready->as_int() >= cr.spec.nodes)
        return true;
    }
    std::this_thread::sleep_for(std::chrono::seconds(poll_interval_s));
  }
  return false;
}

void write_descriptor(const H2OTpu& cr, const std::string& dir) {
  std::string path = dir + "/" + cr.name + ".tpuk";
  std::ofstream f(path, std::ios::trunc);
  if (!f) throw std::runtime_error("cannot write " + path);
  f << cr.to_json().dump(2);
}

H2OTpu read_descriptor(const std::string& path) {
  std::ifstream f(path);
  if (!f) throw std::runtime_error("cannot read " + path);
  std::string text((std::istreambuf_iterator<char>(f)),
                   std::istreambuf_iterator<char>());
  return H2OTpu::from_json(Json::parse(text));
}

}  // namespace tpuk
