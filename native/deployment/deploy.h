// Cluster lifecycle: create/delete/wait — the deployment crate's
// deploy_h2o_cluster / undeploy_h2o_cluster equivalents (SURVEY.md §3.1:
// create Service → create StatefulSet → poll ready → write descriptor).
#pragma once

#include <string>

#include "crd.h"
#include "k8s_client.h"

namespace tpuk {

// API path helpers
std::string services_path(const std::string& ns, const std::string& name = "");
std::string statefulsets_path(const std::string& ns,
                              const std::string& name = "");
std::string ingresses_path(const std::string& ns,
                           const std::string& name = "");
std::string h2otpus_path(const std::string& ns, const std::string& name = "");
std::string crd_path();

// create headless Service + StatefulSet (idempotent: 409 tolerated)
void deploy_cluster(ApiClient& api, const H2OTpu& cr);
// delete StatefulSet + Service (+ Ingress), 404-tolerant
void undeploy_cluster(ApiClient& api, const std::string& name,
                      const std::string& ns);
void create_ingress(ApiClient& api, const H2OTpu& cr,
                    const std::string& host);
void delete_ingress(ApiClient& api, const std::string& name,
                    const std::string& ns);
// poll StatefulSet status.readyReplicas == spec.nodes
bool wait_ready(ApiClient& api, const H2OTpu& cr, int timeout_s,
                int poll_interval_s = 2);

// <name>.tpuk descriptor, written after deploy so undeploy can find the
// resources later (the reference CLI's <name>.h2ok file — SURVEY §2a R1)
void write_descriptor(const H2OTpu& cr, const std::string& dir = ".");
H2OTpu read_descriptor(const std::string& path);

}  // namespace tpuk
