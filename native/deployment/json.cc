#include "json.h"

#include <cctype>
#include <cmath>
#include <cstdio>
#include <cstring>

namespace tpuk {

Json& Json::operator[](const std::string& key) {
  if (type_ == Type::Null) {
    type_ = Type::Object;
    obj_ = std::make_shared<JsonObject>();
  }
  check(Type::Object);
  return (*obj_)[key];
}

const Json* Json::find(const std::string& key) const {
  if (type_ != Type::Object) return nullptr;
  auto it = obj_->find(key);
  return it == obj_->end() ? nullptr : &it->second;
}

const Json* Json::get_path(const std::string& dotted) const {
  const Json* cur = this;
  size_t start = 0;
  while (start <= dotted.size()) {
    size_t dot = dotted.find('.', start);
    std::string key = dotted.substr(
        start, dot == std::string::npos ? std::string::npos : dot - start);
    cur = cur->find(key);
    if (!cur) return nullptr;
    if (dot == std::string::npos) break;
    start = dot + 1;
  }
  return cur;
}

std::string Json::string_or(const std::string& key,
                            const std::string& fallback) const {
  const Json* v = find(key);
  return v && v->is_string() ? v->as_string() : fallback;
}

int64_t Json::int_or(const std::string& key, int64_t fallback) const {
  const Json* v = find(key);
  return v && v->is_number() ? v->as_int() : fallback;
}

namespace {

void escape_to(const std::string& s, std::string& out) {
  out += '"';
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof buf, "\\u%04x", c);
          out += buf;
        } else {
          out += c;
        }
    }
  }
  out += '"';
}

void number_to(double v, std::string& out) {
  if (std::isfinite(v) && v == std::floor(v) &&
      std::fabs(v) < 9.0e15) {  // integral — keep manifests int-typed
    char buf[32];
    std::snprintf(buf, sizeof buf, "%lld", static_cast<long long>(v));
    out += buf;
  } else {
    char buf[32];
    std::snprintf(buf, sizeof buf, "%.17g", v);
    out += buf;
  }
}

}  // namespace

void Json::dump_to(std::string& out, int indent, int depth) const {
  auto pad = [&](int d) {
    if (indent >= 0) {
      out += '\n';
      out.append(static_cast<size_t>(indent) * d, ' ');
    }
  };
  switch (type_) {
    case Type::Null: out += "null"; break;
    case Type::Bool: out += bool_ ? "true" : "false"; break;
    case Type::Number: number_to(num_, out); break;
    case Type::String: escape_to(str_, out); break;
    case Type::Array: {
      if (arr_->empty()) { out += "[]"; break; }
      out += '[';
      bool first = true;
      for (const Json& v : *arr_) {
        if (!first) out += ',';
        first = false;
        pad(depth + 1);
        v.dump_to(out, indent, depth + 1);
      }
      pad(depth);
      out += ']';
      break;
    }
    case Type::Object: {
      if (obj_->empty()) { out += "{}"; break; }
      out += '{';
      bool first = true;
      for (const auto& [k, v] : *obj_) {
        if (!first) out += ',';
        first = false;
        pad(depth + 1);
        escape_to(k, out);
        out += indent >= 0 ? ": " : ":";
        v.dump_to(out, indent, depth + 1);
      }
      pad(depth);
      out += '}';
      break;
    }
  }
}

std::string Json::dump(int indent) const {
  std::string out;
  dump_to(out, indent, 0);
  if (indent >= 0) out += '\n';
  return out;
}

namespace {

struct Parser {
  const char* p;
  const char* end;

  [[noreturn]] void fail(const std::string& why) {
    throw std::runtime_error("json parse error: " + why);
  }

  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      ++p;
  }

  bool consume(char c) {
    skip_ws();
    if (p < end && *p == c) { ++p; return true; }
    return false;
  }

  void expect(char c) {
    if (!consume(c)) fail(std::string("expected '") + c + "'");
  }

  std::string parse_string() {
    expect('"');
    std::string s;
    while (p < end && *p != '"') {
      char c = *p++;
      if (c == '\\') {
        if (p >= end) fail("bad escape");
        char e = *p++;
        switch (e) {
          case '"': s += '"'; break;
          case '\\': s += '\\'; break;
          case '/': s += '/'; break;
          case 'b': s += '\b'; break;
          case 'f': s += '\f'; break;
          case 'n': s += '\n'; break;
          case 'r': s += '\r'; break;
          case 't': s += '\t'; break;
          case 'u': {
            if (end - p < 4) fail("bad \\u escape");
            unsigned cp = 0;
            for (int i = 0; i < 4; ++i) {
              char h = *p++;
              cp <<= 4;
              if (h >= '0' && h <= '9') cp += h - '0';
              else if (h >= 'a' && h <= 'f') cp += h - 'a' + 10;
              else if (h >= 'A' && h <= 'F') cp += h - 'A' + 10;
              else fail("bad \\u digit");
            }
            // UTF-8 encode (surrogate pairs unsupported; K8s names are
            // ASCII — fail loudly rather than corrupt)
            if (cp >= 0xD800 && cp <= 0xDFFF) fail("surrogates unsupported");
            if (cp < 0x80) s += static_cast<char>(cp);
            else if (cp < 0x800) {
              s += static_cast<char>(0xC0 | (cp >> 6));
              s += static_cast<char>(0x80 | (cp & 0x3F));
            } else {
              s += static_cast<char>(0xE0 | (cp >> 12));
              s += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
              s += static_cast<char>(0x80 | (cp & 0x3F));
            }
            break;
          }
          default: fail("bad escape char");
        }
      } else {
        s += c;
      }
    }
    expect('"');
    return s;
  }

  Json parse_value() {
    skip_ws();
    if (p >= end) fail("unexpected end");
    char c = *p;
    if (c == '{') {
      ++p;
      JsonObject obj;
      skip_ws();
      if (consume('}')) return Json(std::move(obj));
      while (true) {
        std::string key = parse_string();
        expect(':');
        obj.emplace(std::move(key), parse_value());
        if (consume('}')) break;
        expect(',');
      }
      return Json(std::move(obj));
    }
    if (c == '[') {
      ++p;
      JsonArray arr;
      skip_ws();
      if (consume(']')) return Json(std::move(arr));
      while (true) {
        arr.push_back(parse_value());
        if (consume(']')) break;
        expect(',');
      }
      return Json(std::move(arr));
    }
    if (c == '"') return Json(parse_string());
    if (std::strncmp(p, "true", 4) == 0 && end - p >= 4) {
      p += 4; return Json(true);
    }
    if (std::strncmp(p, "false", 5) == 0 && end - p >= 5) {
      p += 5; return Json(false);
    }
    if (std::strncmp(p, "null", 4) == 0 && end - p >= 4) {
      p += 4; return Json(nullptr);
    }
    // number
    char* num_end = nullptr;
    double v = std::strtod(p, &num_end);
    if (num_end == p) fail("bad token");
    p = num_end;
    return Json(v);
  }
};

}  // namespace

Json Json::parse(const std::string& text) {
  Parser parser{text.data(), text.data() + text.size()};
  Json v = parser.parse_value();
  parser.skip_ws();
  if (parser.p != parser.end) parser.fail("trailing content");
  return v;
}

}  // namespace tpuk
