// Minimal JSON value: parse + serialize, just enough for Kubernetes
// manifests and API responses.  The reference deployment stack leans on
// serde for this (deployment/src/crd.rs [U], SURVEY.md §2a R3); with no
// JSON library in this toolchain we carry our own ~small implementation
// instead of vendoring one.
#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

namespace tpuk {

class Json;
using JsonArray = std::vector<Json>;
// std::map keeps keys sorted -> deterministic serialization, which the
// golden-file tests rely on.
using JsonObject = std::map<std::string, Json>;

class Json {
 public:
  enum class Type { Null, Bool, Number, String, Array, Object };

  Json() : type_(Type::Null) {}
  Json(std::nullptr_t) : type_(Type::Null) {}
  Json(bool b) : type_(Type::Bool), bool_(b) {}
  Json(int v) : type_(Type::Number), num_(v) {}
  Json(int64_t v) : type_(Type::Number), num_(static_cast<double>(v)) {}
  Json(double v) : type_(Type::Number), num_(v) {}
  Json(const char* s) : type_(Type::String), str_(s) {}
  Json(std::string s) : type_(Type::String), str_(std::move(s)) {}
  Json(JsonArray a)
      : type_(Type::Array), arr_(std::make_shared<JsonArray>(std::move(a))) {}
  Json(JsonObject o)
      : type_(Type::Object),
        obj_(std::make_shared<JsonObject>(std::move(o))) {}

  static Json object() { return Json(JsonObject{}); }
  static Json array() { return Json(JsonArray{}); }

  Type type() const { return type_; }
  bool is_null() const { return type_ == Type::Null; }
  bool is_object() const { return type_ == Type::Object; }
  bool is_array() const { return type_ == Type::Array; }
  bool is_string() const { return type_ == Type::String; }
  bool is_number() const { return type_ == Type::Number; }
  bool is_bool() const { return type_ == Type::Bool; }

  bool as_bool() const { check(Type::Bool); return bool_; }
  double as_number() const { check(Type::Number); return num_; }
  int64_t as_int() const {
    check(Type::Number);
    return static_cast<int64_t>(num_);
  }
  const std::string& as_string() const { check(Type::String); return str_; }
  const JsonArray& as_array() const { check(Type::Array); return *arr_; }
  JsonArray& as_array() { check(Type::Array); return *arr_; }
  const JsonObject& as_object() const { check(Type::Object); return *obj_; }
  JsonObject& as_object() { check(Type::Object); return *obj_; }

  // object field access; operator[] inserts (like nlohmann), get() doesn't
  Json& operator[](const std::string& key);
  const Json* find(const std::string& key) const;
  // dotted-path lookup for tests/reconcile: get_path("spec.nodes")
  const Json* get_path(const std::string& dotted) const;

  // string "a" or number fallback helpers used by spec parsing
  std::string string_or(const std::string& key,
                        const std::string& fallback) const;
  int64_t int_or(const std::string& key, int64_t fallback) const;

  std::string dump(int indent = -1) const;
  static Json parse(const std::string& text);  // throws std::runtime_error

 private:
  void check(Type t) const {
    if (type_ != t) throw std::runtime_error("json: wrong type access");
  }
  void dump_to(std::string& out, int indent, int depth) const;

  Type type_;
  bool bool_ = false;
  double num_ = 0;
  std::string str_;
  std::shared_ptr<JsonArray> arr_;
  std::shared_ptr<JsonObject> obj_;
};

}  // namespace tpuk
