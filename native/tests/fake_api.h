// In-memory Kubernetes API double for controller/deploy tests.  The
// reference tests against a live k3s cluster (SURVEY.md §4a); with no
// cluster in this environment the reconcile logic is pinned down
// against this store plus golden manifests instead.  It implements
// just the verbs/paths the deployment stack uses: GET/POST/DELETE on
// collection+item paths and merge-PATCH on items (+ /status).
#pragma once

#include <map>
#include <string>

#include "../deployment/json.h"
#include "../deployment/k8s_client.h"

namespace tpuk_test {

class FakeApi final : public tpuk::ApiClient {
 public:
  std::map<std::string, tpuk::Json> store;  // item path -> object
  std::vector<std::string> log;             // "METHOD path"

  tpuk::Response request(const std::string& method, const std::string& path,
                         const std::string& body,
                         const std::string& /*content_type*/) override {
    log.push_back(method + " " + strip_query(path));
    std::string p = strip_query(path);
    if (method == "GET") return get(p);
    if (method == "POST") return post(p, body);
    if (method == "DELETE") return del(p);
    if (method == "PATCH") return patch(p, body);
    return {405, "method not allowed"};
  }

  bool watch(const std::string&,
             const std::function<void(const std::string&)>&,
             long) override {
    return true;  // tests drive reconcile() directly
  }

 private:
  static std::string strip_query(const std::string& path) {
    size_t q = path.find('?');
    return q == std::string::npos ? path : path.substr(0, q);
  }

  tpuk::Response get(const std::string& path) {
    auto it = store.find(path);
    if (it != store.end()) return {200, it->second.dump()};
    if (!is_collection_path(path))
      return {404, R"({"kind":"Status","code":404})"};
    tpuk::Json list = tpuk::Json::object();
    tpuk::JsonArray items;
    for (const auto& [k, v] : store)
      if (k.rfind(path + "/", 0) == 0 &&
          k.find('/', path.size() + 1) == std::string::npos)
        items.push_back(v);
    list["items"] = tpuk::Json(std::move(items));
    list["metadata"] = tpuk::Json(tpuk::JsonObject{
        {"resourceVersion", tpuk::Json("1")}});
    return {200, list.dump()};
  }

  tpuk::Response post(const std::string& path, const std::string& body) {
    tpuk::Json obj = tpuk::Json::parse(body);
    const tpuk::Json* name = obj.get_path("metadata.name");
    if (!name || !name->is_string()) return {422, "no metadata.name"};
    std::string item = path + "/" + name->as_string();
    if (store.count(item)) return {409, "exists"};
    store[item] = obj;
    return {201, obj.dump()};
  }

  tpuk::Response del(const std::string& path) {
    if (!store.count(path)) return {404, "not found"};
    store.erase(path);
    return {200, "{}"};
  }

  tpuk::Response patch(const std::string& path, const std::string& body) {
    // "/status" patches apply to the parent object's status field
    std::string target = path;
    bool status_sub = false;
    if (target.size() > 7 && target.rfind("/status") == target.size() - 7) {
      target = target.substr(0, target.size() - 7);
      status_sub = true;
    }
    auto it = store.find(target);
    if (it == store.end()) return {404, "not found"};
    tpuk::Json patch_body = tpuk::Json::parse(body);
    merge(it->second, patch_body);
    (void)status_sub;
    return {200, it->second.dump()};
  }

  // RFC 7386 merge patch
  static void merge(tpuk::Json& target, const tpuk::Json& patch) {
    if (!patch.is_object() || !target.is_object()) {
      target = patch;
      return;
    }
    for (const auto& [k, v] : patch.as_object()) {
      if (v.is_null()) {
        target.as_object().erase(k);
      } else if (v.is_object() && target.find(k) &&
                 target.find(k)->is_object()) {
        merge(target[k], v);
      } else {
        target[k] = v;
      }
    }
  }

  // collection iff the final path segment is a known resource plural
  // (item paths end with an object name instead)
  static bool is_collection_path(const std::string& path) {
    size_t slash = path.find_last_of('/');
    std::string last =
        slash == std::string::npos ? path : path.substr(slash + 1);
    return last == "services" || last == "statefulsets" ||
           last == "ingresses" || last == "h2otpus" ||
           last == "customresourcedefinitions";
  }
};

}  // namespace tpuk_test
