#!/usr/bin/env bash
# Real-control-plane e2e for the native stack (SURVEY.md §4a: the
# reference's CI runs deploy/undeploy against throwaway k3s, no API
# mocks). Two tiers, matching the split of responsibilities:
#
#   CLI tier      tpuk deploy/status/undeploy manage the Service +
#                 StatefulSet directly (like the reference CLI).
#   Operator tier h2o-tpu-operator owns the CRD + H2OTpu CRs: ensure
#                 CRD, reconcile CR -> svc/sts, drift repair, finalizer
#                 teardown on CR deletion.
#
# Pods cannot become Ready on a TPU-less runner (TPU nodeselector +
# google.com/tpu limits), so deploy runs with --timeout 0 and the
# assertions are resource-level.
#
# usage: e2e_k3s.sh <build-dir> <kubeconfig>
set -euo pipefail

BUILD=$(cd "${1:?build dir}" && pwd)   # absolute: we cd away below
export KUBECONFIG=${2:?kubeconfig}
TPUK="$BUILD/tpuk"
OPERATOR="$BUILD/h2o-tpu-operator"
KUBECTL="${KUBECTL:-sudo k3s kubectl}"

fail() { echo "E2E FAIL: $*" >&2; exit 1; }

cd "$(mktemp -d)"

# ---- CLI tier: deploy -> status -> undeploy ------------------------------
NAME=e2e-cli
"$TPUK" deploy --name "$NAME" --cluster-size 2 --timeout 0 \
    --kubeconfig "$KUBECONFIG"
[ -f "$NAME.tpuk" ] || fail "descriptor file not written"
$KUBECTL get statefulset "$NAME" >/dev/null || fail "StatefulSet missing"
$KUBECTL get service "$NAME" >/dev/null || fail "Service missing"
replicas=$($KUBECTL get statefulset "$NAME" -o jsonpath='{.spec.replicas}')
[ "$replicas" = "2" ] || fail "expected 2 replicas, got $replicas"

"$TPUK" status --name "$NAME" --kubeconfig "$KUBECONFIG" || \
    fail "status failed"

"$TPUK" undeploy -f "$NAME.tpuk" --kubeconfig "$KUBECONFIG"
$KUBECTL get statefulset "$NAME" >/dev/null 2>&1 && \
    fail "StatefulSet not removed"
$KUBECTL get service "$NAME" >/dev/null 2>&1 && fail "Service not removed"

# ---- operator tier: CRD + CR lifecycle -----------------------------------
OPNAME=e2e-op
# --once: ensure CRD + one list/reconcile sweep (no CRs yet). The first
# sweep can race CRD establishment on a fresh apiserver — retry once
# after waiting for the Established condition.
if ! timeout 60 "$OPERATOR" --once --kubeconfig "$KUBECONFIG"; then
    # only the establishment race is retryable; if the CRD never got
    # created, the operator itself failed — report that, not the wait
    $KUBECTL get crd h2otpus.tpu.h2o.ai >/dev/null 2>&1 || \
        fail "operator --once failed before creating the CRD"
    $KUBECTL wait --for condition=established --timeout=60s \
        crd/h2otpus.tpu.h2o.ai || fail "CRD never established"
    timeout 60 "$OPERATOR" --once --kubeconfig "$KUBECONFIG" || \
        fail "operator --once (CRD ensure) failed"
fi
$KUBECTL get crd h2otpus.tpu.h2o.ai >/dev/null || fail "CRD missing"
$KUBECTL wait --for condition=established --timeout=60s \
    crd/h2otpus.tpu.h2o.ai || fail "CRD never established"

# extract the CR from the manifest bundle and apply it
"$TPUK" manifest --name "$OPNAME" --cluster-size 1 > bundle.json
python3 - <<'PY'
import json
b = json.load(open("bundle.json"))
json.dump(b["customResource"], open("cr.json", "w"))
PY
$KUBECTL apply -f cr.json

# reconcile: CR -> Service + StatefulSet (+ finalizer + status)
timeout 60 "$OPERATOR" --once --kubeconfig "$KUBECONFIG" || \
    fail "operator reconcile failed"
$KUBECTL get statefulset "$OPNAME" >/dev/null || \
    fail "operator did not create the StatefulSet"
$KUBECTL get service "$OPNAME" >/dev/null || \
    fail "operator did not create the Service"
fin=$($KUBECTL get h2otpu "$OPNAME" -o jsonpath='{.metadata.finalizers[0]}')
[ -n "$fin" ] || fail "operator did not add a finalizer"

# drift repair on a live control plane: delete the StatefulSet, let the
# operator recreate it
$KUBECTL delete statefulset "$OPNAME" --wait=true
timeout 60 "$OPERATOR" --once --kubeconfig "$KUBECONFIG" || \
    fail "operator repair pass failed"
$KUBECTL get statefulset "$OPNAME" >/dev/null || \
    fail "operator did not repair the deleted StatefulSet"

# CR deletion: teardown + finalizer release lets K8s GC complete
$KUBECTL delete h2otpu "$OPNAME" --wait=false
timeout 60 "$OPERATOR" --once --kubeconfig "$KUBECONFIG" || \
    fail "operator teardown pass failed"
$KUBECTL get h2otpu "$OPNAME" >/dev/null 2>&1 && \
    fail "CR stuck (finalizer not released)"
$KUBECTL get statefulset "$OPNAME" >/dev/null 2>&1 && \
    fail "operator did not tear down the StatefulSet"
$KUBECTL get service "$OPNAME" >/dev/null 2>&1 && \
    fail "operator did not tear down the Service"

echo "E2E PASS"
