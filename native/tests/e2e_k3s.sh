#!/usr/bin/env bash
# Real-control-plane e2e for the native stack (SURVEY.md §4a: the
# reference's CI runs deploy/undeploy against throwaway k3s, no API
# mocks). Drives the tpuk CLI against a live apiserver and asserts the
# Kubernetes RESOURCES exist and clean up. Pods cannot become Ready on
# a TPU-less runner (TPU nodeselector + google.com/tpu limits), so
# deploy runs with --timeout 0 and the assertions are resource-level.
#
# usage: e2e_k3s.sh <build-dir> <kubeconfig>
set -euo pipefail

BUILD=$(cd "${1:?build dir}" && pwd)   # absolute: we cd away below
export KUBECONFIG=${2:?kubeconfig}
TPUK="$BUILD/tpuk"
KUBECTL="${KUBECTL:-sudo k3s kubectl}"
NAME=e2e-test

fail() { echo "E2E FAIL: $*" >&2; exit 1; }

cd "$(mktemp -d)"

# deploy: CRD ensured, CR + StatefulSet + headless Service created
"$TPUK" deploy --name "$NAME" --cluster-size 2 --timeout 0 \
    --kubeconfig "$KUBECONFIG"
[ -f "$NAME.tpuk" ] || fail "descriptor file not written"

$KUBECTL get crd h2otpus.tpu.h2o.ai >/dev/null || fail "CRD missing"
$KUBECTL get h2otpu "$NAME" >/dev/null || fail "CR missing"
$KUBECTL get statefulset "$NAME" >/dev/null || fail "StatefulSet missing"
$KUBECTL get service "$NAME" >/dev/null || fail "Service missing"
replicas=$($KUBECTL get statefulset "$NAME" -o jsonpath='{.spec.replicas}')
[ "$replicas" = "2" ] || fail "expected 2 replicas, got $replicas"

# status runs against the live apiserver
"$TPUK" status --name "$NAME" --kubeconfig "$KUBECONFIG" || \
    fail "status failed"

# one operator reconcile pass: drift repair on a live control plane —
# delete the StatefulSet, let the operator recreate it
$KUBECTL delete statefulset "$NAME" --wait=true
timeout 60 "$BUILD/h2o-tpu-operator" --once --kubeconfig "$KUBECONFIG" \
    || fail "operator reconcile pass failed"
$KUBECTL get statefulset "$NAME" >/dev/null || \
    fail "operator did not repair the deleted StatefulSet"

# undeploy: everything gone (CRD itself stays, like the reference)
"$TPUK" undeploy -f "$NAME.tpuk" --kubeconfig "$KUBECONFIG"
$KUBECTL get h2otpu "$NAME" >/dev/null 2>&1 && fail "CR not removed"
$KUBECTL get statefulset "$NAME" >/dev/null 2>&1 && \
    fail "StatefulSet not removed"
$KUBECTL get service "$NAME" >/dev/null 2>&1 && fail "Service not removed"

echo "E2E PASS"
