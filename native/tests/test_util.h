// Micro test harness (the role tests_common plays for the reference's
// crates — SURVEY.md §2a R4): CHECK macros + a main that runs
// registered cases and exits nonzero on failure (ctest-friendly).
#pragma once

#include <cstdio>
#include <functional>
#include <string>
#include <vector>

namespace tpuk_test {

struct Case {
  std::string name;
  std::function<void()> fn;
};

inline std::vector<Case>& cases() {
  static std::vector<Case> all;
  return all;
}

struct Register {
  Register(const std::string& name, std::function<void()> fn) {
    cases().push_back({name, std::move(fn)});
  }
};

inline int failures = 0;

#define TEST(name)                                              \
  static void test_##name();                                    \
  static ::tpuk_test::Register reg_##name(#name, test_##name);  \
  static void test_##name()

#define CHECK(cond)                                                     \
  do {                                                                  \
    if (!(cond)) {                                                      \
      std::fprintf(stderr, "  CHECK failed at %s:%d: %s\n", __FILE__,   \
                   __LINE__, #cond);                                    \
      ++::tpuk_test::failures;                                          \
    }                                                                   \
  } while (0)

#define CHECK_EQ(a, b)                                                   \
  do {                                                                   \
    auto va = (a);                                                       \
    auto vb = (b);                                                       \
    if (!(va == vb)) {                                                   \
      std::fprintf(stderr, "  CHECK_EQ failed at %s:%d: %s != %s\n",     \
                   __FILE__, __LINE__, #a, #b);                          \
      ++::tpuk_test::failures;                                           \
    }                                                                    \
  } while (0)

#define CHECK_THROWS(expr)                                              \
  do {                                                                  \
    bool threw = false;                                                 \
    try {                                                               \
      (void)(expr);                                                     \
    } catch (const std::exception&) {                                   \
      threw = true;                                                     \
    }                                                                   \
    if (!threw) {                                                       \
      std::fprintf(stderr, "  CHECK_THROWS failed at %s:%d: %s\n",      \
                   __FILE__, __LINE__, #expr);                          \
      ++::tpuk_test::failures;                                          \
    }                                                                   \
  } while (0)

inline int run_all() {
  int failed_cases = 0;
  for (const Case& c : cases()) {
    int before = failures;
    try {
      c.fn();
    } catch (const std::exception& e) {
      std::fprintf(stderr, "  EXCEPTION in %s: %s\n", c.name.c_str(),
                   e.what());
      ++failures;
    }
    bool ok = failures == before;
    std::printf("%s %s\n", ok ? "PASS" : "FAIL", c.name.c_str());
    if (!ok) ++failed_cases;
  }
  std::printf("%zu cases, %d failed\n", cases().size(), failed_cases);
  return failed_cases == 0 ? 0 : 1;
}

}  // namespace tpuk_test

#define TEST_MAIN() \
  int main() { return ::tpuk_test::run_all(); }
