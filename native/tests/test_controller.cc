// Reconcile-behavior tests against the in-memory API double: apply →
// finalizer+children created; idempotency; drift repair; delete →
// children gone + finalizer released (the reference's operator flows,
// SURVEY.md §3.2).
#include "../operator/controller.h"

#include "../deployment/deploy.h"
#include "fake_api.h"
#include "test_util.h"

using tpuk::H2OTpu;
using tpuk::Json;
using tpuk_test::FakeApi;

namespace {

H2OTpu make_cr(int nodes = 3) {
  H2OTpu cr;
  cr.name = "demo";
  cr.ns = "ml";
  cr.uid = "u1";
  cr.spec.nodes = nodes;
  return cr;
}

// put the CR itself into the fake store (as the API server would hold it)
void store_cr(FakeApi& api, const H2OTpu& cr) {
  api.store[tpuk::h2otpus_path(cr.ns, cr.name)] = cr.to_json();
}

}  // namespace

TEST(ensure_crd_creates_then_noops) {
  FakeApi api;
  CHECK(tpuk::ensure_crd(api));
  CHECK(api.store.count(
      "/apis/apiextensions.k8s.io/v1/customresourcedefinitions/"
      "h2otpus.tpu.h2o.ai"));
  CHECK(!tpuk::ensure_crd(api));  // second call finds it
}

TEST(reconcile_creates_children_and_finalizer) {
  FakeApi api;
  H2OTpu cr = make_cr();
  store_cr(api, cr);
  std::string action = tpuk::reconcile(api, cr);
  CHECK(action.find("service") != std::string::npos);
  CHECK(action.find("statefulset") != std::string::npos);
  CHECK(action.find("finalizer") != std::string::npos);
  CHECK(api.store.count(tpuk::services_path("ml", "demo")));
  CHECK(api.store.count(tpuk::statefulsets_path("ml", "demo")));
  // finalizer patched onto the stored CR
  const Json& stored = api.store[tpuk::h2otpus_path("ml", "demo")];
  CHECK(stored.get_path("metadata.finalizers") != nullptr);
  // status written
  CHECK_EQ(stored.get_path("status.phase")->as_string(), "Forming");
}

TEST(reconcile_is_idempotent) {
  FakeApi api;
  H2OTpu cr = make_cr();
  store_cr(api, cr);
  tpuk::reconcile(api, cr);
  cr.has_finalizer = true;  // as it would arrive on the next event
  CHECK_EQ(tpuk::reconcile(api, cr), "noop");
}

TEST(reconcile_repairs_replica_drift) {
  FakeApi api;
  H2OTpu cr = make_cr(3);
  store_cr(api, cr);
  tpuk::reconcile(api, cr);
  cr.has_finalizer = true;
  // someone scaled the statefulset by hand
  Json& sts = api.store[tpuk::statefulsets_path("ml", "demo")];
  sts["spec"]["replicas"] = 7;
  std::string action = tpuk::reconcile(api, cr);
  CHECK(action.find("rescale") != std::string::npos);
  CHECK_EQ(api.store[tpuk::statefulsets_path("ml", "demo")]
               .get_path("spec.replicas")->as_int(),
           3);
}

TEST(reconcile_reports_ready_status) {
  FakeApi api;
  H2OTpu cr = make_cr(2);
  store_cr(api, cr);
  tpuk::reconcile(api, cr);
  cr.has_finalizer = true;
  Json& sts = api.store[tpuk::statefulsets_path("ml", "demo")];
  sts["status"] = Json(tpuk::JsonObject{{"readyReplicas", Json(2)}});
  tpuk::reconcile(api, cr);
  CHECK_EQ(api.store[tpuk::h2otpus_path("ml", "demo")]
               .get_path("status.phase")->as_string(),
           "Ready");
}

TEST(reconcile_delete_tears_down_and_releases_finalizer) {
  FakeApi api;
  H2OTpu cr = make_cr();
  store_cr(api, cr);
  tpuk::reconcile(api, cr);
  cr.has_finalizer = true;
  cr.deleting = true;
  CHECK_EQ(tpuk::reconcile(api, cr), "deleted");
  CHECK(!api.store.count(tpuk::services_path("ml", "demo")));
  CHECK(!api.store.count(tpuk::statefulsets_path("ml", "demo")));
  const Json& stored = api.store[tpuk::h2otpus_path("ml", "demo")];
  CHECK(stored.get_path("metadata.finalizers")->as_array().empty());
}

TEST(reconcile_delete_tolerates_missing_children) {
  FakeApi api;
  H2OTpu cr = make_cr();
  store_cr(api, cr);
  cr.deleting = true;
  cr.has_finalizer = true;
  CHECK_EQ(tpuk::reconcile(api, cr), "deleted");  // nothing existed: fine
}

TEST(deploy_and_undeploy_cluster) {
  FakeApi api;
  H2OTpu cr = make_cr();
  tpuk::deploy_cluster(api, cr);
  CHECK(api.store.count(tpuk::services_path("ml", "demo")));
  CHECK(api.store.count(tpuk::statefulsets_path("ml", "demo")));
  tpuk::deploy_cluster(api, cr);  // idempotent: 409s tolerated
  tpuk::undeploy_cluster(api, "demo", "ml");
  CHECK(!api.store.count(tpuk::services_path("ml", "demo")));
  CHECK(!api.store.count(tpuk::statefulsets_path("ml", "demo")));
  tpuk::undeploy_cluster(api, "demo", "ml");  // 404s tolerated
}

TEST(wait_ready_polls_status) {
  FakeApi api;
  H2OTpu cr = make_cr(2);
  tpuk::deploy_cluster(api, cr);
  CHECK(!tpuk::wait_ready(api, cr, /*timeout_s=*/0));
  Json& sts = api.store[tpuk::statefulsets_path("ml", "demo")];
  sts["status"] = Json(tpuk::JsonObject{{"readyReplicas", Json(2)}});
  CHECK(tpuk::wait_ready(api, cr, /*timeout_s=*/2, /*poll_interval_s=*/1));
}

TEST(descriptor_round_trip) {
  H2OTpu cr = make_cr(5);
  tpuk::write_descriptor(cr, "/tmp");
  H2OTpu back = tpuk::read_descriptor("/tmp/demo.tpuk");
  CHECK_EQ(back.name, "demo");
  CHECK_EQ(back.ns, "ml");
  CHECK_EQ(back.spec.nodes, 5);
  remove("/tmp/demo.tpuk");
}


TEST(finalizer_patch_preserves_foreign_finalizers) {
  FakeApi api;
  H2OTpu cr = make_cr();
  Json obj = cr.to_json();
  obj["metadata"]["finalizers"] =
      Json(tpuk::JsonArray{Json("backup.io/finalizer")});
  api.store[tpuk::h2otpus_path(cr.ns, cr.name)] = obj;
  tpuk::reconcile(api, cr);  // adds ours
  const Json* fins = api.store[tpuk::h2otpus_path("ml", "demo")]
                         .get_path("metadata.finalizers");
  CHECK_EQ(fins->as_array().size(), 2u);
  CHECK_EQ(fins->as_array()[0].as_string(), "backup.io/finalizer");
  // delete: only OUR finalizer is released
  cr.deleting = true;
  cr.has_finalizer = true;
  tpuk::reconcile(api, cr);
  fins = api.store[tpuk::h2otpus_path("ml", "demo")]
             .get_path("metadata.finalizers");
  CHECK_EQ(fins->as_array().size(), 1u);
  CHECK_EQ(fins->as_array()[0].as_string(), "backup.io/finalizer");
}

TEST_MAIN()
