// Golden-file tests: the generated Service/StatefulSet/Ingress/CRD
// manifests are the deployment stack's entire observable output (the
// reference asserts the same shapes in its deployment-crate unit tests,
// SURVEY.md §4a).  Regenerate with TPUK_UPDATE_GOLDENS=1.
#include "../deployment/manifests.h"

#include <cstdlib>
#include <fstream>
#include <sstream>

#include "test_util.h"

using tpuk::H2OTpu;
using tpuk::Json;

namespace {

H2OTpu demo_cr() {
  H2OTpu cr;
  cr.name = "demo";
  cr.ns = "ml";
  cr.uid = "uid-123";
  cr.spec.nodes = 4;
  cr.spec.version = "0.2.0";
  cr.spec.resources.cpu = "8";
  cr.spec.resources.memory = "32Gi";
  cr.spec.resources.memory_percentage = 80;
  cr.spec.tpu.accelerator = "tpu-v5-lite-podslice";
  cr.spec.tpu.topology = "4x4";
  cr.spec.tpu.chips_per_host = 4;
  return cr;
}

void check_golden(const std::string& name, const Json& manifest) {
  std::string path = std::string(GOLDEN_DIR) + "/" + name + ".json";
  std::string got = manifest.dump(2);
  if (std::getenv("TPUK_UPDATE_GOLDENS")) {
    std::ofstream out(path, std::ios::trunc);
    out << got;
    std::printf("  updated %s\n", path.c_str());
    return;
  }
  std::ifstream in(path);
  if (!in) {
    std::fprintf(stderr, "  missing golden %s (set TPUK_UPDATE_GOLDENS=1)\n",
                 path.c_str());
    ++::tpuk_test::failures;
    return;
  }
  std::ostringstream want;
  want << in.rdbuf();
  if (got != want.str()) {
    std::fprintf(stderr,
                 "  golden mismatch for %s\n--- want\n%s\n--- got\n%s\n",
                 name.c_str(), want.str().c_str(), got.c_str());
    ++::tpuk_test::failures;
  }
}

}  // namespace

TEST(golden_service) { check_golden("service", headless_service(demo_cr())); }

TEST(golden_statefulset) {
  check_golden("statefulset", stateful_set(demo_cr()));
}

TEST(golden_ingress) {
  check_golden("ingress", ingress(demo_cr(), "h2o.example.com"));
}

TEST(golden_crd) { check_golden("crd", tpuk::crd_manifest()); }

TEST(env_contract_present) {
  // the coordinator env contract consumed by
  // h2o_kubernetes_tpu.runtime.mesh.initialize_distributed
  Json sts = stateful_set(demo_cr());
  const Json* env =
      sts.get_path("spec.template.spec.containers")->as_array()[0]
          .find("env");
  CHECK(env && env->is_array());
  bool coord = false, nproc = false, pid = false;
  for (const Json& e : env->as_array()) {
    std::string n = e.string_or("name", "");
    if (n == "H2O_TPU_COORDINATOR") {
      coord = true;
      CHECK_EQ(e.string_or("value", ""),
               "demo-0.demo.ml.svc.cluster.local:8476");
    }
    if (n == "H2O_TPU_NUM_PROCESSES") {
      nproc = true;
      CHECK_EQ(e.string_or("value", ""), "4");
    }
    if (n == "H2O_TPU_PROCESS_ID") {
      pid = true;
      CHECK(e.get_path("valueFrom.fieldRef.fieldPath") != nullptr);
    }
  }
  CHECK(coord);
  CHECK(nproc);
  CHECK(pid);
}

TEST(tpu_nodeselector_and_resources) {
  Json sts = stateful_set(demo_cr());
  const Json* sel = sts.get_path("spec.template.spec.nodeSelector");
  CHECK_EQ(sel->string_or("cloud.google.com/gke-tpu-accelerator", ""),
           "tpu-v5-lite-podslice");
  CHECK_EQ(sel->string_or("cloud.google.com/gke-tpu-topology", ""), "4x4");
  const Json& container =
      sts.get_path("spec.template.spec.containers")->as_array()[0];
  CHECK_EQ(container.get_path("resources.requests")
               ->string_or("google.com/tpu", ""),
           "4");
  CHECK_EQ(container.get_path("resources.limits")
               ->string_or("google.com/tpu", ""),
           "4");
}

TEST(service_is_headless_with_unready_addresses) {
  Json svc = headless_service(demo_cr());
  CHECK_EQ(svc.get_path("spec.clusterIP")->as_string(), "None");
  CHECK_EQ(svc.get_path("spec.publishNotReadyAddresses")->as_bool(), true);
}

TEST(owner_reference_set_when_uid_known) {
  Json svc = headless_service(demo_cr());
  const Json* refs = svc.get_path("metadata.ownerReferences");
  CHECK(refs && refs->as_array().size() == 1);
  CHECK_EQ(refs->as_array()[0].string_or("kind", ""), "H2OTpu");
  // CLI-created resources (no uid yet) must omit ownerReferences
  H2OTpu cli_cr = demo_cr();
  cli_cr.uid.clear();
  CHECK(headless_service(cli_cr).get_path("metadata.ownerReferences") ==
        nullptr);
}

TEST_MAIN()
