#include "../deployment/crd.h"

#include "test_util.h"

using tpuk::H2OTpu;
using tpuk::H2OTpuSpec;
using tpuk::Json;

TEST(spec_defaults) {
  H2OTpuSpec s = H2OTpuSpec::from_json(Json::object());
  CHECK_EQ(s.nodes, 1);
  CHECK_EQ(s.version, "latest");
  CHECK(!s.custom_image.has_value());
  CHECK_EQ(s.resources.memory_percentage, 90);
  CHECK_EQ(s.tpu.chips_per_host, 4);
  CHECK_EQ(s.image(), "h2o-kubernetes-tpu:latest");
}

TEST(spec_full_parse) {
  Json spec = Json::parse(R"({
    "nodes": 8, "version": "1.2.3",
    "resources": {"cpu": "8", "memory": "32Gi", "memoryPercentage": 75},
    "tpu": {"accelerator": "tpu-v5p-slice", "topology": "4x4",
            "chipsPerHost": 8}})");
  H2OTpuSpec s = H2OTpuSpec::from_json(spec);
  CHECK_EQ(s.nodes, 8);
  CHECK_EQ(s.image(), "h2o-kubernetes-tpu:1.2.3");
  CHECK_EQ(s.resources.cpu, "8");
  CHECK_EQ(s.resources.memory_percentage, 75);
  CHECK_EQ(s.tpu.topology, "4x4");
  CHECK_EQ(s.tpu.chips_per_host, 8);
}

TEST(spec_custom_image_wins) {
  Json spec = Json::parse(R"({"customImage": "gcr.io/me/img:tag"})");
  CHECK_EQ(H2OTpuSpec::from_json(spec).image(), "gcr.io/me/img:tag");
}

TEST(spec_validation) {
  CHECK_THROWS(H2OTpuSpec::from_json(Json::parse(R"({"nodes": 0})")));
  CHECK_THROWS(H2OTpuSpec::from_json(
      Json::parse(R"({"resources": {"memoryPercentage": 0}})")));
  CHECK_THROWS(H2OTpuSpec::from_json(
      Json::parse(R"({"resources": {"memoryPercentage": 101}})")));
  CHECK_THROWS(H2OTpuSpec::from_json(
      Json::parse(R"({"tpu": {"chipsPerHost": 0}})")));
}

TEST(cr_round_trip) {
  Json obj = Json::parse(R"({
    "apiVersion": "tpu.h2o.ai/v1", "kind": "H2OTpu",
    "metadata": {"name": "demo", "namespace": "ml", "uid": "u1",
                 "resourceVersion": "5",
                 "finalizers": ["tpu.h2o.ai/finalizer"]},
    "spec": {"nodes": 2}})");
  H2OTpu cr = H2OTpu::from_json(obj);
  CHECK_EQ(cr.name, "demo");
  CHECK_EQ(cr.ns, "ml");
  CHECK_EQ(cr.uid, "u1");
  CHECK(cr.has_finalizer);
  CHECK(!cr.deleting);
  CHECK_EQ(cr.spec.nodes, 2);
  Json back = cr.to_json();
  CHECK_EQ(back.get_path("metadata.name")->as_string(), "demo");
  CHECK_EQ(back.get_path("spec.nodes")->as_int(), 2);
  CHECK_EQ(back.get_path("metadata.finalizers")->as_array().size(), 1u);
}

TEST(cr_deletion_detected) {
  Json obj = Json::parse(R"({
    "metadata": {"name": "x", "deletionTimestamp": "2026-01-01T00:00:00Z"},
    "spec": {}})");
  CHECK(H2OTpu::from_json(obj).deleting);
}

TEST(cr_requires_name) {
  CHECK_THROWS(H2OTpu::from_json(Json::parse(R"({"metadata": {}})")));
  CHECK_THROWS(H2OTpu::from_json(Json::parse(R"({"spec": {}})")));
}

TEST(crd_manifest_shape) {
  Json crd = tpuk::crd_manifest();
  CHECK_EQ(crd.get_path("metadata.name")->as_string(), "h2otpus.tpu.h2o.ai");
  CHECK_EQ(crd.get_path("spec.group")->as_string(), "tpu.h2o.ai");
  CHECK_EQ(crd.get_path("spec.names.kind")->as_string(), "H2OTpu");
  const Json* versions = crd.get_path("spec.versions");
  CHECK(versions && versions->as_array().size() == 1);
  const Json& v0 = versions->as_array()[0];
  CHECK_EQ(v0.get_path("name")->as_string(), "v1");
  CHECK(v0.get_path("schema.openAPIV3Schema.properties.spec.properties."
                    "nodes") != nullptr);
  CHECK(v0.get_path("subresources.status") != nullptr);
}

TEST_MAIN()
