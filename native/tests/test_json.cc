#include "../deployment/json.h"

#include "test_util.h"

using tpuk::Json;
using tpuk::JsonArray;
using tpuk::JsonObject;

TEST(parse_scalars) {
  CHECK(Json::parse("null").is_null());
  CHECK_EQ(Json::parse("true").as_bool(), true);
  CHECK_EQ(Json::parse("false").as_bool(), false);
  CHECK_EQ(Json::parse("42").as_int(), 42);
  CHECK_EQ(Json::parse("-3.5").as_number(), -3.5);
  CHECK_EQ(Json::parse("\"hi\"").as_string(), "hi");
}

TEST(parse_structures) {
  Json v = Json::parse(R"({"a": [1, 2, {"b": "c"}], "d": {}})");
  CHECK_EQ(v.as_object().size(), 2u);
  CHECK_EQ(v["a"].as_array().size(), 3u);
  CHECK_EQ(v["a"].as_array()[2]["b"].as_string(), "c");
  CHECK(v["d"].as_object().empty());
}

TEST(parse_escapes) {
  Json v = Json::parse(R"("line\n\"quoted\"\t\\u0041:A")");
  CHECK_EQ(v.as_string(), "line\n\"quoted\"\t\\u0041:A");
}

TEST(parse_errors) {
  CHECK_THROWS(Json::parse(""));
  CHECK_THROWS(Json::parse("{"));
  CHECK_THROWS(Json::parse("[1,]"));
  CHECK_THROWS(Json::parse("{\"a\":1} trailing"));
  CHECK_THROWS(Json::parse("nulll"));
}

TEST(dump_round_trip) {
  std::string text =
      R"({"arr":[1,2.5,"x"],"nested":{"t":true},"z":null})";
  Json v = Json::parse(text);
  CHECK_EQ(v.dump(), text);  // std::map ordering == alphabetical input
  Json again = Json::parse(v.dump(2));
  CHECK_EQ(again.dump(), text);
}

TEST(dump_integral_numbers_stay_ints) {
  Json v = Json::object();
  v["n"] = 54321;
  CHECK_EQ(v.dump(), R"({"n":54321})");
}

TEST(get_path_and_helpers) {
  Json v = Json::parse(R"({"spec":{"nodes":3,"name":"x"}})");
  CHECK_EQ(v.get_path("spec.nodes")->as_int(), 3);
  CHECK(v.get_path("spec.missing") == nullptr);
  CHECK(v.get_path("no.such") == nullptr);
  CHECK_EQ(v["spec"].string_or("name", "d"), "x");
  CHECK_EQ(v["spec"].string_or("nope", "d"), "d");
  CHECK_EQ(v["spec"].int_or("nodes", 0), 3);
  CHECK_EQ(v["spec"].int_or("nope", 7), 7);
}

TEST(wrong_type_access_throws) {
  Json v = Json::parse("[1]");
  CHECK_THROWS(v.as_object());
  CHECK_THROWS(v.as_string());
}

TEST_MAIN()
