#include "../deployment/k8s_client.h"

#include "test_util.h"

using tpuk::Json;
using tpuk::yaml_to_json;

namespace {

const char* kKubeconfig = R"(apiVersion: v1
kind: Config
current-context: dev
clusters:
- cluster:
    server: https://10.0.0.1:6443
    certificate-authority: /etc/ca.crt
  name: devcluster
contexts:
- context:
    cluster: devcluster
    user: devuser
  name: dev
users:
- name: devuser
  user:
    token: sekret  # inline comment
preferences: {}
)";

}  // namespace

TEST(yaml_kubeconfig_shape) {
  Json cfg = yaml_to_json(kKubeconfig);
  CHECK_EQ(cfg.string_or("current-context", ""), "dev");
  const Json* clusters = cfg.find("clusters");
  CHECK(clusters && clusters->is_array());
  const Json& c0 = clusters->as_array()[0];
  CHECK_EQ(c0.string_or("name", ""), "devcluster");
  CHECK_EQ(c0.get_path("cluster.server")->as_string(),
           "https://10.0.0.1:6443");
  CHECK_EQ(cfg.get_path("users")->as_array()[0]
               .get_path("user.token")->as_string(),
           "sekret");
}

TEST(yaml_scalars_and_lists) {
  Json v = yaml_to_json("a: 1\nb: true\nc: 'q'\nlist:\n- x\n- y\n");
  CHECK_EQ(v["a"].as_int(), 1);
  CHECK_EQ(v["b"].as_bool(), true);
  CHECK_EQ(v["c"].as_string(), "q");
  CHECK_EQ(v["list"].as_array().size(), 2u);
  CHECK_EQ(v["list"].as_array()[1].as_string(), "y");
}

TEST(yaml_comments_and_blank_lines) {
  Json v = yaml_to_json("# header\n\na: x # tail\n");
  CHECK_EQ(v["a"].as_string(), "x");
}

TEST(kubeconfig_from_file) {
  std::string path = "/tmp/tpuk-test-kubeconfig.yaml";
  {
    FILE* f = fopen(path.c_str(), "w");
    fputs(kKubeconfig, f);
    fclose(f);
  }
  tpuk::K8sConfig cfg = tpuk::K8sConfig::from_kubeconfig(path);
  CHECK_EQ(cfg.server, "https://10.0.0.1:6443");
  CHECK_EQ(cfg.token, "sekret");
  CHECK_EQ(cfg.ca_cert_path, "/etc/ca.crt");
  remove(path.c_str());
}

TEST_MAIN()
