"""Standalone multi-host (DCN) dryrun — runnable without pytest.

Spawns a REAL 2-process `jax.distributed` cluster on localhost (4
virtual CPU devices per process → one global 8-device mesh) and runs,
in sequence: a cross-process psum MRTask, a full fused-scan GBM train,
a GLM IRLSM fit, and the member-drop fail-fast check. This is the
driver-facing analog of `dryrun_multichip` for the PROCESS-boundary
path that a single-process virtual mesh cannot exercise (SURVEY.md §2d
multi-host row; the round-2 DRF worker-crash class lives here).

Usage: python tools/dcn_dryrun.py   → prints one JSON line + exit 0/1.
"""

import json
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "dcn_worker.py")
MODES = [("psum", (0, 0)), ("gbm", (0, 0)), ("glm", (0, 0)),
         ("drop", (0, 17))]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def run_mode(mode: str, want_rc) -> dict:
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    t0 = time.monotonic()
    procs = [subprocess.Popen(
        [sys.executable, WORKER, str(port), str(i), mode],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        text=True) for i in range(2)]
    outs, ok = [], True
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        return {"mode": mode, "ok": False, "error": "timeout",
                "tails": [o[-300:] for o in outs]}
    for i, (p, out) in enumerate(zip(procs, outs)):
        # drop mode: worker 1 dies on purpose and prints EXITING, not OK
        marker = "EXITING" if (mode == "drop" and i == 1) else "OK"
        if p.returncode != want_rc[i] or marker not in out:
            ok = False
    return {"mode": mode, "ok": ok,
            "seconds": round(time.monotonic() - t0, 1),
            **({} if ok else {"tails": [o[-300:] for o in outs]})}


def main() -> int:
    results = [run_mode(m, rc) for m, rc in MODES]
    ok = all(r["ok"] for r in results)
    print(json.dumps({"dcn_dryrun": "ok" if ok else "fail",
                      "processes": 2, "global_devices": 8,
                      "modes": results}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
