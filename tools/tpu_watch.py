"""Background TPU watcher — runs all round, captures on-chip evidence.

Loop: probe TPU client init in a subprocess (the tunneled chip HANGS on
init when down, so every probe gets a hard timeout).  The moment a
probe succeeds, run the kernel gate (tools/kernel_gate.py) and the
bench (bench.py) on the chip and write their JSON lines to
``TPU_GATE_r05.json`` / ``BENCH_TPU_r05.json`` at the repo root, plus
an append-only probe log at ``tools/tpu_watch.log``.

After a successful capture it keeps watching and re-captures at most
every RECAPTURE_S seconds, keeping the BEST bench value (highest
rows*trees/s) in BENCH_TPU_r05.json and the latest in
BENCH_TPU_r05_latest.json — so late-session perf work still lands an
on-chip number without re-plumbing.

Usage: nohup python tools/tpu_watch.py &   (or driver background task)
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "tools", "tpu_watch.log")
PROBE_TIMEOUT = 150.0   # cold client init can take ~30s; hang means dead
PROBE_PAUSE = 150.0
RECAPTURE_S = 1800.0
GATE_TIMEOUT = 1200.0
BENCH_TIMEOUT = 2400.0


def log(msg: str) -> None:
    line = f"{time.strftime('%Y-%m-%d %H:%M:%S')} {msg}\n"
    with open(LOG, "a") as f:
        f.write(line)
    sys.stderr.write(line)


def probe() -> bool:
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; d=jax.devices(); "
             "print(jax.default_backend(), len(d))"],
            timeout=PROBE_TIMEOUT, capture_output=True, cwd=REPO)
    except subprocess.TimeoutExpired:
        log(f"probe hung >{PROBE_TIMEOUT:.0f}s")
        return False
    out = r.stdout.decode(errors="replace").strip()
    if r.returncode == 0 and out.startswith("tpu"):
        log(f"probe OK: {out}")
        return True
    log(f"probe rc={r.returncode} out={out!r} "
        f"err={r.stderr.decode(errors='replace')[-200:]!r}")
    return False


def run_json(cmd, timeout, env=None):
    """Run cmd, return (ok, last-JSON-line-dict-or-None, tail)."""
    e = dict(os.environ)
    e["H2O_TPU_PROBE_BUDGET"] = "60"  # chip just answered; don't stall
    if env:
        e.update(env)
    try:
        r = subprocess.run(cmd, timeout=timeout, capture_output=True,
                           cwd=REPO, env=e)
    except subprocess.TimeoutExpired:
        return False, None, "TIMEOUT"
    out = r.stdout.decode(errors="replace")
    obj = None
    for line in reversed(out.strip().splitlines()):
        try:
            obj = json.loads(line)
            break
        except ValueError:
            continue
    tail = (out[-400:] + "\nSTDERR: "
            + r.stderr.decode(errors="replace")[-400:])
    return r.returncode == 0, obj, tail


def _build_block() -> dict:
    """Which build produced this artifact (ISSUE 14: every capture
    states its package/jax versions, pid, host fingerprint)."""
    try:
        from h2o_kubernetes_tpu.runtime.telemetry import build_info

        return build_info()
    except Exception as e:  # noqa: BLE001 — the watch must not die
        return {"error": repr(e)[:120]}


def capture() -> float | None:
    """Gate + bench on the live chip. Returns bench value or None."""
    log("chip is live — running kernel gate")
    ok, gate, tail = run_json(
        [sys.executable, os.path.join("tools", "kernel_gate.py")],
        GATE_TIMEOUT)
    if gate is not None:
        gate["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%S")
        gate["build"] = _build_block()
        with open(os.path.join(REPO, "TPU_GATE_r05.json"), "w") as f:
            json.dump(gate, f, indent=1)
    log(f"gate ok={ok} result={json.dumps(gate)[:300] if gate else tail}")

    # carryover pin (rounds 12-16 shipped with the chip detached, so
    # goss_parity + shap_parity have only ever run interpret-mode on
    # CPU): record the REAL-lowering verdicts once, in their own
    # artifact, the first window a chip shows up
    parity_path = os.path.join(REPO, "TPU_GATE_parity_r16.json")
    if not os.path.exists(parity_path) and gate is not None \
            and gate.get("platform") == "tpu":
        wanted = [c for c in gate.get("checks", ())
                  if c.get("check") in ("goss_parity", "shap_parity")]
        if wanted:
            with open(parity_path, "w") as f:
                json.dump({"captured_at": gate.get("captured_at"),
                           "platform": "tpu",
                           "build": gate.get("build"),
                           "checks": wanted,
                           "ok": all(c.get("ok") for c in wanted)},
                          f, indent=1)
            log(f"pinned non-interpret parity artifact: {wanted}")

    # round-17 pin: the chip-native TreeSHAP kernel
    # (ops/shap_kernel.py) has only ever run interpret-mode on CPU —
    # the first chip window must record the REAL-Mosaic
    # shap_kernel_parity verdict plus the ≥2× gbm_shap_rows_per_sec
    # kernel-vs-XLA bar (the ROADMAP acceptance), alongside the
    # carried goss/shap pins from r16. The speedup is read back from
    # the on-chip bench_suite artifact captured later this window, so
    # this block runs AFTER the suite (see _pin_r17 call below).

    log("running bench.py on chip")
    ok, bench, tail = run_json([sys.executable, "bench.py"], BENCH_TIMEOUT)
    if bench is None:
        log(f"bench produced no JSON: {tail}")
        return None
    bench["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    bench["build"] = _build_block()
    log(f"bench ok={ok} result={json.dumps(bench)[:300]}")
    if bench.get("platform") != "tpu":
        log("bench fell back to CPU despite live probe — not recording")
        return None
    latest = os.path.join(REPO, "BENCH_TPU_r05_latest.json")
    with open(latest, "w") as f:
        json.dump(bench, f, indent=1)
    best_path = os.path.join(REPO, "BENCH_TPU_r05.json")
    best_val = -1.0
    if os.path.exists(best_path):
        try:
            with open(best_path) as f:
                best_val = float(json.load(f).get("value", -1.0))
        except Exception:
            pass
    if float(bench.get("value", 0.0)) > best_val:
        with open(best_path, "w") as f:
            json.dump(bench, f, indent=1)
        log(f"new best on-chip value {bench.get('value')}")

    # once per window: the 2-term mantissa throughput mode (gated
    # separately — ~2^-16 products; kernel gate's two_term_kernel
    # check covers parity). Kept in its OWN artifact so the headline
    # number stays the full-precision mode.
    two_path = os.path.join(REPO, "BENCH_TPU_r05_2term.json")
    if not os.path.exists(two_path):
        log("running bench.py with H2O_TPU_HIST_TERMS=2")
        ok, b2, tail = run_json([sys.executable, "bench.py"],
                                BENCH_TIMEOUT,
                                env={"H2O_TPU_HIST_TERMS": "2",
                                     "H2O_TPU_BENCH_NO_STORE": "1"})
        if b2 is not None and b2.get("platform") == "tpu":
            b2["mode"] = "two_term_mantissa"
            b2["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%S")
            with open(two_path, "w") as f:
                json.dump(b2, f, indent=1)
        log(f"2term bench ok={ok} "
            f"result={json.dumps(b2)[:200] if b2 else tail[:200]}")

    # once per chip window: per-phase + per-op boost profile (where the
    # bench seconds actually go — drives the MFU work)
    prof_path = os.path.join(REPO, "PROFILE_TPU_r05.json")
    if not os.path.exists(prof_path):
        log("running boost profile on chip")
        ok, prof, tail = run_json(
            [sys.executable, os.path.join("tools", "boost_profile.py")],
            2400.0)
        log(f"boost_profile ok={ok} "
            f"result={json.dumps(prof)[:300] if prof else ''}")
        if not ok:
            log(f"boost_profile tail: {tail}")

    # once per session, with the chip warm: the AutoML-at-scale
    # wall-clock the north star is phrased in (10M x 10, max_models=12,
    # 900 s budget — chip availability comes in ~20-min windows, so the
    # capture is a fixed-time-budget run, the same framing the
    # reference's AutoML wall-clock comparisons use)
    aml_path = os.path.join(REPO, "AUTOML_TPU_r05.json")
    if not os.path.exists(aml_path):
        log("running on-chip AutoML 10M scale capture")
        ok, aml, tail = run_json(
            [sys.executable, os.path.join("tools", "automl_scale.py"),
             "--max-models", "12", "--max-runtime-secs", "900"],
            2400.0)
        log(f"automl_scale ok={ok} "
            f"result={json.dumps(aml)[:300] if aml else ''}")
        if not ok:
            log(f"automl_scale tail: {tail}")
        # a chip death mid-run leaves a zero-model artifact — keep it
        # as evidence under a _failed name but retry next window
        try:
            with open(aml_path) as f:
                curve = json.load(f).get("curve", [])
            if not any(s.get("models_trained") for s in curve):
                os.replace(aml_path, aml_path.replace(
                    ".json", "_failed.json"))
                log("automl capture had no trained models — will retry")
        except (OSError, ValueError):
            pass

    # lowest priority (chip windows are ~20 min; profile + AutoML are
    # the round's named evidence): the non-GBM BASELINE configs (GLM
    # iters/sec, DRF HIGGS on the unit-hess path, XGBoost hist,
    # lambdarank, DL, Word2Vec) — r14 also carries the TreeSHAP
    # XLA-vs-kernel leg pair the r17 pin below reads back
    suite_path = os.path.join(REPO, "BENCH_SUITE_TPU_r14.json")
    if not os.path.exists(suite_path):
        log("running bench_suite on chip")
        ok, suite, tail = run_json(
            [sys.executable, os.path.join("tools", "bench_suite.py")],
            2400.0)
        log(f"bench_suite ok={ok} "
            f"result={json.dumps(suite)[:300] if suite else ''}")
        if not ok:
            log(f"bench_suite tail: {tail}")
    _pin_r17(gate, suite_path)
    return float(bench.get("value", 0.0))


def _pin_r17(gate, suite_path: str) -> None:
    """Round-17 chip-window pin (see comment at the r16 block): the
    non-interpret shap_kernel_parity verdict + the ≥2×
    gbm_shap_rows_per_sec kernel-vs-XLA bar, with the carried
    goss/shap pins, into TPU_GATE_parity_r17.json."""
    path = os.path.join(REPO, "TPU_GATE_parity_r17.json")
    if os.path.exists(path) or gate is None \
            or gate.get("platform") != "tpu":
        return
    wanted = [c for c in gate.get("checks", ())
              if c.get("check") in ("goss_parity", "shap_parity",
                                    "shap_kernel_parity")]
    speedup = None
    try:
        with open(suite_path) as f:
            for row in json.load(f).get("suite", []):
                if row.get("config") == "gbm_shap_rows_per_sec":
                    speedup = row.get("kernel_speedup_vs_xla")
    except (OSError, ValueError):
        pass
    bar = {"metric": "gbm_shap_rows_per_sec kernel vs xla",
           "required_x": 2.0, "measured_x": speedup,
           "met": bool(speedup is not None and speedup >= 2.0)}
    with open(path, "w") as f:
        json.dump({"captured_at": gate.get("captured_at"),
                   "platform": "tpu", "build": gate.get("build"),
                   "checks": wanted,
                   "shap_kernel_speedup_bar": bar,
                   "ok": bool(wanted
                              and all(c.get("ok") for c in wanted)
                              and bar["met"])},
                  f, indent=1)
    log(f"pinned r17 parity artifact: checks={len(wanted)} bar={bar}")


def main() -> None:
    log(f"tpu_watch starting pid={os.getpid()}")
    last_capture = 0.0
    while True:
        if probe():
            now = time.monotonic()
            if now - last_capture >= RECAPTURE_S or last_capture == 0.0:
                try:
                    capture()
                except Exception as e:  # watcher must never die
                    log(f"capture raised: {e!r}")
                last_capture = time.monotonic()
        time.sleep(PROBE_PAUSE)


if __name__ == "__main__":
    main()
