#!/usr/bin/env python
"""fleet_top — one-screen fleet telemetry aggregator (`kubectl top`
analog for scorer pools).

Scrapes ``GET /metrics`` (falling back to ``/3/Stats`` JSON) from every
target — pool replicas discovered through the durable store's endpoint
manifests, an explicitly listed router front door, ad-hoc ``--url``
targets — and renders fleet-wide request rates, queue/shed pressure,
scorer-cache residency vs budget, breaker state, and per-target p99
(interpolated from the ``h2o_request_phase_seconds{phase="total"}``
histogram the replicas export).

Usage::

    python tools/fleet_top.py --url http://127.0.0.1:54321 \
        [--url http://router:8080] [--interval 2] [--once] [--json]

    python tools/fleet_top.py --store /var/h2o/poolstore --pool churn \
        --workdir /var/h2o/pools/churn

``--once`` prints a single snapshot and exits (the scriptable mode the
drills and docs use); without it the screen redraws every
``--interval`` seconds until Ctrl-C. Device-free: scraping never
touches jax.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from h2o_kubernetes_tpu.runtime import telemetry  # noqa: E402


def _get(url: str, path: str, timeout: float = 3.0):
    with urllib.request.urlopen(url.rstrip("/") + path,
                                timeout=timeout) as r:
        return r.read().decode()


def discover_store_endpoints(store_root: str, pool: str,
                             workdir: str | None) -> list[str]:
    """Replica endpoints via the operator's own machinery: the durable
    store's status (routable endpoints the reconciler published) plus
    any pod manifests under the workdir (covers an operator that died
    before publishing)."""
    from h2o_kubernetes_tpu.operator.store import DurablePoolStore

    urls: list[str] = []
    try:
        st = DurablePoolStore(store_root).get_status(pool) or {}
        for ep in st.get("endpoints") or ():
            urls.append(str(ep))
    except Exception:  # noqa: BLE001 — discovery is best-effort
        pass
    if workdir:
        pods = os.path.join(workdir, "pods")
        if os.path.isdir(pods):
            for name in sorted(os.listdir(pods)):
                try:
                    with open(os.path.join(pods, name)) as f:
                        man = json.load(f)
                    port = man.get("port")
                    if port:
                        urls.append(f"http://127.0.0.1:{port}")
                except Exception:  # noqa: BLE001
                    continue
    seen, out = set(), []
    for u in urls:
        u = u.rstrip("/")
        if u not in seen:
            seen.add(u)
            out.append(u)
    return out


def _metric(parsed: dict, name: str, **labels) -> float | None:
    want = tuple(sorted(labels.items()))
    for (n, lbls), v in parsed.items():
        if n == name and (not want or lbls == want):
            return v
    return None


def _metric_sum(parsed: dict, name: str) -> float:
    return sum(v for (n, _l), v in parsed.items() if n == name)


def _hist_p99(parsed: dict, name: str, **labels) -> float | None:
    """p99 off the cumulative buckets of a Prometheus histogram in
    ``parsed`` (linear interpolation — same math as
    Histogram.quantile)."""
    want = tuple(sorted(labels.items()))
    buckets = []
    for (n, lbls), v in parsed.items():
        if n != name + "_bucket":
            continue
        d = dict(lbls)
        le = d.pop("le", None)
        if tuple(sorted(d.items())) != want or le is None:
            continue
        buckets.append((float("inf") if le == "+Inf" else float(le), v))
    if not buckets:
        return None
    buckets.sort()
    total = buckets[-1][1]
    if total <= 0:
        return None
    target = 0.99 * total
    prev_b, prev_c = 0.0, 0.0
    for b, c in buckets:
        if c >= target:
            if b == float("inf"):
                return prev_b
            span = c - prev_c
            frac = (target - prev_c) / span if span else 1.0
            return prev_b + (b - prev_b) * frac
        prev_b, prev_c = b, c
    return buckets[-2][0] if len(buckets) > 1 else buckets[0][0]


def scrape(url: str) -> dict:
    """One target's summarized row. Prefers /metrics; a target that
    only speaks JSON (older build) falls back to /3/Stats."""
    row = {"url": url, "up": False}
    t0 = time.monotonic()
    try:
        text = _get(url, "/metrics")
        row["scrape_ms"] = round((time.monotonic() - t0) * 1000.0, 2)
        row["scrape_bytes"] = len(text)
        p = telemetry.parse_prometheus_text(text)
        row["up"] = True
        is_router = _metric(p, "h2o_stats_router_router") is not None
        is_operator = any(k[0].startswith("h2o_stats_operator_")
                          for k in p)
        row["kind"] = "router" if is_router else \
            ("operator" if is_operator else "replica")
        if is_router:
            row["requests"] = _metric(
                p, "h2o_stats_router_stats_requests") or 0
            row["errors"] = (_metric(
                p, "h2o_stats_router_stats_relayed_5xx") or 0) + (
                _metric(p, "h2o_stats_router_stats_transport_errors")
                or 0)
            row["retries"] = _metric(
                p, "h2o_stats_router_stats_retries") or 0
            row["hedges"] = _metric(
                p, "h2o_stats_router_stats_hedges") or 0
            row["degraded"] = _metric(
                p, "h2o_stats_router_stats_degraded_503") or 0
            row["p99_ms"] = _ms(_hist_p99(p, "h2o_router_route_seconds"))
        else:
            row["requests"] = _metric(
                p, "h2o_stats_batcher_requests") or 0
            row["queue"] = _metric(
                p, "h2o_stats_batcher_queue_depth") or 0
            row["shed"] = (_metric(p, "h2o_stats_batcher_shed") or 0) \
                + (_metric(p, "h2o_stats_batcher_fairness_shed") or 0)
            row["deadline_504"] = _metric(
                p, "h2o_stats_counters_deadline_504") or 0
            row["cache_bytes"] = _metric(
                p, "h2o_stats_scorer_cache_resident_bytes") or 0
            row["cache_budget"] = _metric(
                p, "h2o_stats_scorer_cache_budget_bytes") or 0
            row["resident"] = _metric(
                p, "h2o_stats_scorer_cache_resident") or 0
            # breaker column only when the target EXPORTS the
            # lifecycle group (the operator status listener doesn't —
            # absence must render '-', never a false OPEN alarm)
            if any(k[0] == "h2o_stats_lifecycle_breaker_state"
                   for k in p):
                row["breaker_open"] = 0.0 if _metric(
                    p, "h2o_stats_lifecycle_breaker_state",
                    value="closed") else 1.0
            row["p99_ms"] = _ms(_hist_p99(
                p, "h2o_request_phase_seconds", phase="total"))
        return row
    except Exception:  # noqa: BLE001 — fall back to JSON
        pass
    try:
        st = json.loads(_get(url, "/3/Stats"))
        row["scrape_ms"] = round((time.monotonic() - t0) * 1000.0, 2)
        row["up"] = True
        if st.get("router"):
            row["kind"] = "router"
            row["requests"] = st["stats"]["requests"]
            row["retries"] = st["stats"]["retries"]
        else:
            row["kind"] = "replica"
            row["requests"] = st["batcher"]["requests"]
            row["queue"] = st["batcher"]["queue_depth"]
            row["shed"] = st["batcher"]["shed"]
    except Exception as e:  # noqa: BLE001
        row["error"] = repr(e)[:120]
    return row


def _ms(v: float | None) -> float | None:
    return None if v is None else round(v * 1000.0, 2)


def _fmt(v, width: int, suffix: str = "") -> str:
    if v is None:
        return "-".rjust(width)
    if isinstance(v, float) and not v.is_integer():
        return f"{v:.1f}{suffix}".rjust(width)
    return f"{int(v)}{suffix}".rjust(width)


def render(rows: list[dict], prev: dict | None,
           interval: float) -> str:
    """The one screen: per-target rows + fleet totals. ``prev`` maps
    url -> last requests counter for the rate column."""
    out = []
    b = telemetry.build_info()
    out.append(f"fleet_top  {time.strftime('%H:%M:%S')}  "
               f"build={b.get('version')} jax={b.get('jax')} "
               f"host={b.get('hostfp')}")
    hdr = (f"{'TARGET':<28}{'KIND':>8}{'UP':>4}{'REQS':>10}"
           f"{'RATE/S':>8}{'QUEUE':>7}{'SHED':>7}{'P99MS':>8}"
           f"{'CACHE':>12}{'BRKR':>6}")
    out.append(hdr)
    tot_reqs = tot_rate = 0.0
    for r in rows:
        url = r["url"].replace("http://", "")
        reqs = r.get("requests")
        rate = None
        if reqs is not None and prev is not None and \
                r["url"] in prev and interval > 0:
            rate = max(0.0, (reqs - prev[r["url"]]) / interval)
            tot_rate += rate
        tot_reqs += reqs or 0
        cache = None
        if r.get("cache_budget"):
            cache = (f"{r.get('cache_bytes', 0) / 2**20:.1f}/"
                     f"{r['cache_budget'] / 2**20:.0f}M")
        brkr = None
        if r.get("breaker_open") is not None:
            brkr = "OPEN" if r["breaker_open"] else "ok"
        out.append(
            f"{url:<28}{r.get('kind', '?'):>8}"
            f"{('y' if r['up'] else 'N'):>4}"
            f"{_fmt(reqs, 10)}{_fmt(rate, 8)}"
            f"{_fmt(r.get('queue'), 7)}{_fmt(r.get('shed'), 7)}"
            f"{_fmt(r.get('p99_ms'), 8)}"
            f"{(cache or '-'):>12}{(brkr or '-'):>6}")
    up = sum(1 for r in rows if r["up"])
    out.append(f"targets {up}/{len(rows)} up   fleet reqs "
               f"{int(tot_reqs)}   rate {tot_rate:.1f}/s   "
               f"scrape "
               f"{sum(r.get('scrape_ms') or 0 for r in rows):.1f}ms")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--url", action="append", default=[],
                    help="target base URL (repeatable): replicas, "
                    "the router front door, an operator status "
                    "listener")
    ap.add_argument("--store", help="DurablePoolStore root — discover "
                    "replica endpoints from the pool status")
    ap.add_argument("--pool")
    ap.add_argument("--workdir", help="pool workdir (pod manifests) "
                    "for discovery when the status has no endpoints")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--once", action="store_true",
                    help="print one snapshot and exit")
    ap.add_argument("--json", action="store_true",
                    help="emit raw row dicts as JSON instead of the "
                    "screen (scripting)")
    args = ap.parse_args(argv)

    targets = list(args.url)
    if args.store and args.pool:
        targets += discover_store_endpoints(args.store, args.pool,
                                            args.workdir)
    if not targets:
        ap.error("no targets: pass --url or --store/--pool")

    prev: dict | None = None
    while True:
        rows = [scrape(u) for u in targets]
        if args.json:
            print(json.dumps(rows))
        else:
            screen = render(rows, prev, args.interval)
            if not args.once:
                sys.stdout.write("\x1b[2J\x1b[H")   # clear + home
            print(screen, flush=True)
        if args.once:
            return 0 if any(r["up"] for r in rows) else 1
        prev = {r["url"]: r.get("requests") or 0 for r in rows}
        try:
            time.sleep(max(0.2, args.interval))
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
