"""TPU kernel-compile gate — run at round start, BEFORE the bench.

CPU CI can only exercise the Pallas kernels in interpret mode
(`ops/histogram.py` sets `interpret=jax.default_backend() != "tpu"`),
so a Mosaic-lowering regression lands green and is discovered on the
bench chip at round's end.  This script closes that hole: on a TPU it

1. pallas-compiles the FACTORIZED histogram kernel (interpret=False is
   automatic on tpu) at a bench-like shape and asserts parity vs the
   segment_sum reference path;
2. same for the BIN-BLOCKED kernel (deep-tree shape past the
   factorized VMEM cap) and the TreeSHAP serving kernel
   (`ops/shap_kernel.py`, bitwise vs the lowered-XLA
   `flat_shap_tab`);
3. jit-compiles and runs the fused boost scan (binomial AND
   multinomial) end to end on small shapes.

Checks are NAMED and individually selectable: `--check NAME` (repeat
or comma-separate) runs just those — iterating one kernel's parity
without the full sweep — and `--list` prints the names. The `N/N PASS`
summary counts only what RAN, and a filtered run says so in the JSON
(`"filtered": [...]`) so a 2/2 can't masquerade as the full gate.

Prints one JSON line {"gate": "pass"|"fail", ...} LAST on stdout
(tpu_watch parses bottom-up); exit code 0 on pass.  On CPU it still
runs (interpret-mode parity) and reports platform="cpu" so the ritual
can tell the gate did not see a chip.

Usage: python tools/kernel_gate.py [--check NAME ...] [--list]
       (H2O_TPU_PROBE_BUDGET honored)
"""

import argparse
import json
import os
import sys
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

CHECK_NAMES = [
    "fact_kernel", "fact_kernel_cap", "binblock_kernel",
    "leaf_totals_kernel", "unit_hess_kernel", "two_term_kernel",
    "boost_scan_binomial", "boost_scan_multinomial",
    "flat_scorer_parity", "flat_scorer_parity_multinomial",
    "shap_parity", "shap_kernel_parity", "efb_parity", "goss_parity",
]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", action="append", default=None,
                    metavar="NAME",
                    help="run only this check (repeat or comma-"
                         "separate); default: all")
    ap.add_argument("--list", action="store_true",
                    help="print check names and exit")
    args = ap.parse_args(argv)
    if args.list:
        print("\n".join(CHECK_NAMES))
        return 0
    selected = CHECK_NAMES
    if args.check:
        selected = [c.strip() for spec in args.check
                    for c in spec.split(",") if c.strip()]
        unknown = [c for c in selected if c not in CHECK_NAMES]
        if unknown:
            ap.error(f"unknown check(s) {unknown}; --list shows names")

    from h2o_kubernetes_tpu.runtime.backend import ensure_live_backend

    ensure_live_backend(budget=float(
        os.environ.get("H2O_TPU_PROBE_BUDGET", "300")))
    import jax
    import jax.numpy as jnp
    import numpy as np

    from h2o_kubernetes_tpu.ops.histogram import (_FACT_MAX_NHI,
                                                  _hist_segment,
                                                  build_histogram,
                                                  expand_unit_hess)

    platform = jax.default_backend()
    rng = np.random.default_rng(0)
    checks = []

    def parity(name, rows, F, n_nodes, n_bins, tol=1e-5):
        binned = jnp.asarray(
            rng.integers(0, n_bins, size=(rows, F)).astype(np.uint8))
        rel = jnp.asarray(np.where(
            rng.uniform(size=rows) < 0.9,
            rng.integers(0, n_nodes, size=rows), -1).astype(np.int32))
        g = jnp.asarray(rng.normal(size=rows).astype(np.float32))
        h = jnp.asarray(rng.uniform(0.01, 1, size=rows).astype(
            np.float32))
        w = jnp.asarray((rng.uniform(size=rows) < 0.95).astype(
            np.float32))
        got = jax.jit(build_histogram, static_argnums=(5, 6, 7))(
            binned, rel, g, h, w, n_nodes, n_bins, "pallas")
        live = (np.asarray(rel) >= 0) & (np.asarray(w) > 0)
        vals = np.where(live[:, None],
                        np.stack([np.asarray(g) * np.asarray(w),
                                  np.asarray(h) * np.asarray(w),
                                  np.asarray(w)], axis=1), 0.0)
        want = _hist_segment(binned, jnp.where(jnp.asarray(live),
                                               rel, -1),
                             jnp.asarray(vals), n_nodes, n_bins)
        err = float(jnp.max(jnp.abs(got - jnp.asarray(want))) /
                    (jnp.max(jnp.abs(jnp.asarray(want))) + 1e-30))
        ok = err < tol
        checks.append({"check": name, "ok": ok, "rel_err": err})
        return ok

    # ---- shared lazy fixtures (built once, whichever checks run) ----
    import h2o_kubernetes_tpu as h2o
    from h2o_kubernetes_tpu.models import GBM

    _fix: dict = {}
    n = 4096
    x = rng.normal(size=n).astype(np.float32)

    def fix_binomial():
        """fr2/m2: tiny binomial GBM (boost_scan_binomial + goss)."""
        if "m2" not in _fix:
            y2 = np.where(x > 0, "p", "n")
            fr2 = h2o.Frame.from_arrays({"x": x, "y": y2})
            _fix["fr2"] = fr2
            _fix["m2"] = GBM(ntrees=3, max_depth=4, seed=0).train(
                y="y", training_frame=fr2)
        return _fix["fr2"], _fix["m2"]

    def fix_multinomial():
        """fr3/m3: tiny multinomial GBM (boost scan + flat scorer)."""
        if "m3" not in _fix:
            y3 = np.where(x > 0.5, "a",
                          np.where(x < -0.5, "b", "c"))
            fr3 = h2o.Frame.from_arrays({"x": x, "y": y3})
            _fix["fr3"] = fr3
            _fix["m3"] = GBM(ntrees=3, max_depth=3, seed=0).train(
                y="y", training_frame=fr3)
        return _fix["fr3"], _fix["m3"]

    def fix_rich():
        """frf/mf/Xf: NA + high-cardinality grouped-enum frame (flat
        scorer, shap_parity, shap_kernel_parity)."""
        if "mf" not in _fix:
            xna = x.copy()
            xna[::13] = np.nan
            gg = np.array([f"L{i}" for i in range(80)])[
                rng.integers(0, 80, size=n)]
            yf = np.where(np.nan_to_num(xna) > 0, "p", "n")
            frf = h2o.Frame.from_arrays({"x": xna, "g": gg, "y": yf})
            mf = GBM(ntrees=4, max_depth=4, nbins=64, seed=0).train(
                y="y", training_frame=frf)
            _fix["frf"], _fix["mf"] = frf, mf
            _fix["Xf"] = mf._design_matrix(frf)
        return _fix["frf"], _fix["mf"], _fix["Xf"]

    # ------------------------- checks --------------------------------

    def chk_fact_kernel():
        # factorized kernel: node·bins within 128·_FACT_MAX_NHI
        n_nodes_fact = 16
        assert -(-n_nodes_fact * 256 // 128) <= _FACT_MAX_NHI
        parity("fact_kernel", 100_000, 10, n_nodes_fact, 256)

    def chk_fact_kernel_cap():
        # factorized kernel AT the VMEM cap (n_hi == _FACT_MAX_NHI):
        # validates the [3·C·n_hi, T] stacked-term A fits VMEM on real
        # Mosaic, where interpret mode can't see allocation failures
        parity("fact_kernel_cap", 50_000, 2,
               _FACT_MAX_NHI * 128 // 256, 256)

    def chk_binblock_kernel():
        # bin-blocked kernel: force past the factorized cap
        n_nodes_deep = (_FACT_MAX_NHI * 128 // 256) * 2
        parity("binblock_kernel", 50_000, 4, n_nodes_deep, 256)

    def chk_leaf_totals_kernel():
        # single-bin totals shape (the final-level leaf reduction)
        parity("leaf_totals_kernel", 100_000, 1, 32, 1)

    def chk_unit_hess_kernel():
        # unit-hessian 2-channel kernel (gaussian/DRF fast path): must
        # compile on Mosaic and match the 3-channel build with h = 1
        rows_u, F_u, n_u, B_u = 100_000, 10, 16, 256
        binned_u = jnp.asarray(
            rng.integers(0, B_u, size=(rows_u, F_u)).astype(np.uint8))
        rel_u = jnp.asarray(rng.integers(0, n_u, size=rows_u).astype(
            np.int32))
        g_u = jnp.asarray(rng.normal(size=rows_u).astype(np.float32))
        w_u = jnp.asarray((rng.uniform(size=rows_u) < 0.95).astype(
            np.float32))
        ones_u = jnp.ones_like(w_u)
        want_u = jax.jit(build_histogram, static_argnums=(5, 6, 7))(
            binned_u, rel_u, g_u, ones_u, w_u, n_u, B_u, "pallas")
        got_u = expand_unit_hess(jax.jit(
            build_histogram, static_argnums=(5, 6, 7),
            static_argnames=("unit_hess",))(
            binned_u, rel_u, g_u, ones_u, w_u, n_u, B_u, "pallas",
            unit_hess=True))
        err_u = float(jnp.max(jnp.abs(got_u - want_u)) /
                      (jnp.max(jnp.abs(want_u)) + 1e-30))
        checks.append({"check": "unit_hess_kernel",
                       "ok": err_u < 1e-5, "rel_err": err_u})

    def chk_two_term_kernel():
        # 2-term mantissa throughput mode (H2O_TPU_HIST_TERMS=2): the
        # stacked A drops a third of its M rows; parity is checked
        # against the SEGMENT reference (so the check stays meaningful
        # whatever mode the gate itself runs under) at
        # single-precision-histogram tolerance (products ~2^-16)
        import h2o_kubernetes_tpu.ops.histogram as H

        orig_terms = H._TERMS
        H._TERMS = 2
        jax.clear_caches()  # _TERMS is not a trace key: force retrace
        try:
            parity("two_term_kernel", 100_000, 10, 16, 256, tol=1e-4)
        finally:
            H._TERMS = orig_terms
            jax.clear_caches()

    def chk_boost_scan_binomial():
        _, m2 = fix_binomial()
        checks.append({"check": "boost_scan_binomial",
                       "ok": len(m2.scoring_history) > 0})

    def chk_boost_scan_multinomial():
        _, m3 = fix_multinomial()
        checks.append({"check": "boost_scan_multinomial",
                       "ok": m3.ntrees == 9})

    def chk_flat_scorer_parity():
        # flattened serving scorer (models/tree/core.py flat_margin)
        # must match the binned heap re-descent BITWISE on chip — the
        # serving fast path and MOJO export both descend these arrays.
        # NA + categorical + high-cardinality grouped bins in one
        # frame.
        _, mf, Xf = fix_rich()
        flat_ok = bool(np.array_equal(
            np.asarray(mf._margins(Xf)),
            np.asarray(mf._margins_binned(Xf))))
        checks.append({"check": "flat_scorer_parity", "ok": flat_ok})

    def chk_flat_scorer_parity_multinomial():
        fr3, m3 = fix_multinomial()
        X3 = m3._design_matrix(fr3)
        flat3_ok = bool(np.array_equal(
            np.asarray(m3._margins(X3)),
            np.asarray(m3._margins_binned(X3))))
        checks.append({"check": "flat_scorer_parity_multinomial",
                       "ok": flat3_ok})

    def chk_shap_parity():
        # compiled TreeSHAP serving (models/tree/shap.flat_shap) must
        # match the f64 host recursion on chip AND hold the additivity
        # invariant on device — the path tables + unwind DP must
        # survive real lowering, not just CPU interpret. Same NA +
        # high-card grouped-enum frame as the flat-scorer check.
        frf, mf, Xf = fix_rich()
        Xf_np = np.asarray(Xf)[:n]
        contrib = mf.predict_contributions(frf)
        host_phi = np.stack([contrib.vec(c).to_numpy()
                             for c in contrib.names], axis=1)
        dev_phi = mf.contrib_numpy(Xf_np)
        shap_err = float(np.abs(dev_phi - host_phi).max())
        margins_f = np.asarray(mf._margins(Xf))[:n]
        add_err = float(np.abs(dev_phi.sum(axis=1) - margins_f).max())
        checks.append({"check": "shap_parity",
                       "ok": shap_err < 1e-4 and add_err < 1e-4,
                       "host_err": shap_err, "additivity_err": add_err})

    def chk_shap_kernel_parity():
        # chip-native TreeSHAP kernel (ops/shap_kernel.py) must be
        # BITWISE-equal to the lowered-XLA `flat_shap_tab` it
        # hand-places — per virtual-tree group at a pow2 serving
        # shape, AND end-to-end through contrib_numpy with the env
        # knob forcing each impl on a fresh model copy (the scorer
        # cache keys on shape, not impl, so each leg needs its own
        # executables). On TPU this compiles real Mosaic
        # (interpret=False); on CPU it pins the interpret-mode path
        # tier-1 also covers.
        import pickle

        from h2o_kubernetes_tpu.models.tree.shap import flat_shap_tab
        from h2o_kubernetes_tpu.ops.shap_kernel import (
            flat_shap_tab_kernel, kernel_fits)

        frf, mf, Xf = fix_rich()
        groups, ctabs = mf._contrib_prepare()
        em = mf._contrib_enum_mask()
        Xp = jnp.asarray(np.asarray(Xf)[:1024])
        ngr = 0
        ok = True
        err = 0.0
        for g, ct in zip(groups, ctabs):
            if ct is None or not kernel_fits(g, ct, 1024):
                continue
            ngr += 1
            want = np.asarray(flat_shap_tab(g, ct, Xp, em))
            got = np.asarray(flat_shap_tab_kernel(g, ct, Xp, em))
            ok &= bool(np.array_equal(want, got))
            err = max(err, float(np.nanmax(np.abs(want - got))))
        ok &= ngr > 0   # the rich fixture must actually exercise it

        def _leg(env):
            mc = pickle.loads(pickle.dumps(mf))
            os.environ["H2O_TPU_SHAP_KERNEL"] = env
            try:
                return mc.contrib_numpy(np.asarray(Xf)[:n])
            finally:
                os.environ.pop("H2O_TPU_SHAP_KERNEL", None)

        e2e = bool(np.array_equal(_leg("1"), _leg("0")))
        checks.append({"check": "shap_kernel_parity",
                       "ok": bool(ok and e2e),
                       "kernel_groups": ngr, "e2e_bitwise": e2e,
                       "max_abs_err": err,
                       "interpret": platform != "tpu"})

    def chk_efb_parity():
        # EFB parity on chip: bundled vs unbundled training must pick
        # identical splits and produce bitwise-identical predictions
        # on an exact-sum wide one-hot fixture (models/tree/efb.py —
        # the bundled histogram runs the SAME pallas kernel at bundled
        # width, and the decode/remainder math must survive real
        # Mosaic, not just interpret mode). Single gaussian round on a
        # dyadic response = every sum exact, so any deviation is a
        # bug, not float noise.
        ne = 4096
        ecols = {}
        cat_e = rng.integers(0, 16, size=(4, ne))
        for gi in range(4):
            for k in range(16):
                ecols[f"c{gi}_{k}"] = (cat_e[gi] == k).astype(
                    np.float32)
        ecols["c0_0"][::31] = np.nan
        ecols["dx"] = rng.normal(size=ne).astype(np.float32)
        ecols["ye"] = ((cat_e[0] == 1).astype(np.float32)
                       - (cat_e[1] == 2) + (ecols["dx"] > 0)).astype(
            np.float32)
        fr_e = h2o.Frame.from_arrays(ecols)

        def _efb_leg(env):
            os.environ["H2O_TPU_EFB"] = env
            try:
                return GBM(ntrees=1, max_depth=5, seed=0).train(
                    y="ye", training_frame=fr_e)
            finally:
                os.environ.pop("H2O_TPU_EFB", None)

        m_b = _efb_leg("1")
        m_u = _efb_leg("0")
        isp = np.asarray(m_u.trees.is_split)
        efb_ok = bool(np.array_equal(isp,
                                     np.asarray(m_b.trees.is_split)))
        for fld in ("split_feat", "split_bin", "na_left"):
            a = np.where(isp, np.asarray(getattr(m_u.trees, fld)), -9)
            b = np.where(isp, np.asarray(getattr(m_b.trees, fld)), -9)
            efb_ok &= bool(np.array_equal(a, b))
        efb_ok &= bool(np.array_equal(
            np.asarray(m_u.predict_raw(fr_e)),
            np.asarray(m_b.predict_raw(fr_e))))
        checks.append({"check": "efb_parity", "ok": efb_ok})

    def chk_goss_parity():
        # GOSS sampled boost program (ISSUE 13): the static-capacity
        # compaction (jnp.nonzero + gathers inside the shard_map
        # scan), the hashed per-row draws and the full-row re-descent
        # margin update must survive real lowering, not just CPU.
        # Pinned two ways: a+b=1 keeps every row at amplification
        # (1-a)/b = 1, so the SAMPLED program must reproduce the
        # unsampled m2 BITWISE; and a really-sampled config must be
        # seeded-deterministic while actually differing from
        # unsampled.
        fr2, m2 = fix_binomial()

        def _goss_leg(a, b):
            os.environ.update({"H2O_TPU_GOSS": "1",
                               "H2O_TPU_GOSS_TOP_A": a,
                               "H2O_TPU_GOSS_RAND_B": b})
            try:
                return GBM(ntrees=3, max_depth=4, seed=0).train(
                    y="y", training_frame=fr2)
            finally:
                for k in ("H2O_TPU_GOSS", "H2O_TPU_GOSS_TOP_A",
                          "H2O_TPU_GOSS_RAND_B"):
                    os.environ.pop(k, None)

        def _trees_equal(ma, mb):
            return all(np.array_equal(np.asarray(xa), np.asarray(xb))
                       for xa, xb in zip(jax.tree.flatten(ma.trees)[0],
                                         jax.tree.flatten(mb.trees)[0]))

        m_gid = _goss_leg("0.5", "0.5")
        goss_ok = _trees_equal(m2, m_gid)
        m_g1 = _goss_leg("0.2", "0.2")
        m_g2 = _goss_leg("0.2", "0.2")
        goss_ok &= _trees_equal(m_g1, m_g2)
        goss_ok &= not _trees_equal(m2, m_g1)
        checks.append({"check": "goss_parity", "ok": bool(goss_ok)})

    registry = {
        "fact_kernel": chk_fact_kernel,
        "fact_kernel_cap": chk_fact_kernel_cap,
        "binblock_kernel": chk_binblock_kernel,
        "leaf_totals_kernel": chk_leaf_totals_kernel,
        "unit_hess_kernel": chk_unit_hess_kernel,
        "two_term_kernel": chk_two_term_kernel,
        "boost_scan_binomial": chk_boost_scan_binomial,
        "boost_scan_multinomial": chk_boost_scan_multinomial,
        "flat_scorer_parity": chk_flat_scorer_parity,
        "flat_scorer_parity_multinomial":
            chk_flat_scorer_parity_multinomial,
        "shap_parity": chk_shap_parity,
        "shap_kernel_parity": chk_shap_kernel_parity,
        "efb_parity": chk_efb_parity,
        "goss_parity": chk_goss_parity,
    }
    assert list(registry) == CHECK_NAMES
    for name in CHECK_NAMES:
        if name in selected:
            registry[name]()

    passed = sum(1 for c in checks if c["ok"])
    total = len(checks)
    ok = passed == total and total > 0
    sys.stderr.write(
        f"kernel_gate: {passed}/{total} PASS"
        + (" (filtered)" if args.check else "") + "\n")
    out = {"gate": "pass" if ok else "fail", "platform": platform,
           "passed": passed, "total": total, "checks": checks}
    if args.check:
        out["filtered"] = selected
    print(json.dumps(out))
    return 0 if ok else 1


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception as e:     # the gate must report, not traceback-die
        traceback.print_exc()
        print(json.dumps({"gate": "fail", "error": repr(e)[:300]}))
        sys.exit(1)
