"""TPU kernel-compile gate — run at round start, BEFORE the bench.

CPU CI can only exercise the Pallas kernels in interpret mode
(`ops/histogram.py` sets `interpret=jax.default_backend() != "tpu"`),
so a Mosaic-lowering regression lands green and is discovered on the
bench chip at round's end.  This script closes that hole: on a TPU it

1. pallas-compiles the FACTORIZED histogram kernel (interpret=False is
   automatic on tpu) at a bench-like shape and asserts parity vs the
   segment_sum reference path;
2. same for the BIN-BLOCKED kernel (deep-tree shape past the
   factorized VMEM cap);
3. jit-compiles and runs the fused boost scan (binomial AND
   multinomial) end to end on small shapes.

Prints one JSON line {"gate": "pass"|"fail", ...}; exit code 0 on pass.
On CPU it still runs (interpret-mode parity) and reports
platform="cpu" so the ritual can tell the gate did not see a chip.

Usage: python tools/kernel_gate.py  (H2O_TPU_PROBE_BUDGET honored)
"""

import json
import os
import sys
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    from h2o_kubernetes_tpu.runtime.backend import ensure_live_backend

    ensure_live_backend(budget=float(
        os.environ.get("H2O_TPU_PROBE_BUDGET", "300")))
    import jax
    import jax.numpy as jnp
    import numpy as np

    from h2o_kubernetes_tpu.ops.histogram import (_FACT_MAX_NHI,
                                                  _hist_segment,
                                                  build_histogram,
                                                  expand_unit_hess)

    platform = jax.default_backend()
    rng = np.random.default_rng(0)
    checks = []

    def parity(name, rows, F, n_nodes, n_bins, tol=1e-5):
        binned = jnp.asarray(
            rng.integers(0, n_bins, size=(rows, F)).astype(np.uint8))
        rel = jnp.asarray(np.where(
            rng.uniform(size=rows) < 0.9,
            rng.integers(0, n_nodes, size=rows), -1).astype(np.int32))
        g = jnp.asarray(rng.normal(size=rows).astype(np.float32))
        h = jnp.asarray(rng.uniform(0.01, 1, size=rows).astype(
            np.float32))
        w = jnp.asarray((rng.uniform(size=rows) < 0.95).astype(
            np.float32))
        got = jax.jit(build_histogram, static_argnums=(5, 6, 7))(
            binned, rel, g, h, w, n_nodes, n_bins, "pallas")
        live = (np.asarray(rel) >= 0) & (np.asarray(w) > 0)
        vals = np.where(live[:, None],
                        np.stack([np.asarray(g) * np.asarray(w),
                                  np.asarray(h) * np.asarray(w),
                                  np.asarray(w)], axis=1), 0.0)
        want = _hist_segment(binned, jnp.where(jnp.asarray(live),
                                               rel, -1),
                             jnp.asarray(vals), n_nodes, n_bins)
        err = float(jnp.max(jnp.abs(got - jnp.asarray(want))) /
                    (jnp.max(jnp.abs(jnp.asarray(want))) + 1e-30))
        ok = err < tol
        checks.append({"check": name, "ok": ok, "rel_err": err})
        return ok

    # 1. factorized kernel: node·bins within 128·_FACT_MAX_NHI
    n_nodes_fact = 16
    assert -(-n_nodes_fact * 256 // 128) <= _FACT_MAX_NHI
    parity("fact_kernel", 100_000, 10, n_nodes_fact, 256)
    # 1b. factorized kernel AT the VMEM cap (n_hi == _FACT_MAX_NHI):
    # validates the [3·C·n_hi, T] stacked-term A fits VMEM on real
    # Mosaic, where interpret mode can't see allocation failures
    parity("fact_kernel_cap", 50_000, 2, _FACT_MAX_NHI * 128 // 256,
           256)
    # 2. bin-blocked kernel: force past the factorized cap
    n_nodes_deep = (_FACT_MAX_NHI * 128 // 256) * 2
    parity("binblock_kernel", 50_000, 4, n_nodes_deep, 256)
    # 2b. single-bin totals shape (the final-level leaf reduction)
    parity("leaf_totals_kernel", 100_000, 1, 32, 1)

    # 2c. unit-hessian 2-channel kernel (gaussian/DRF fast path): must
    # compile on Mosaic and match the 3-channel build with h = 1
    rows_u, F_u, n_u, B_u = 100_000, 10, 16, 256
    binned_u = jnp.asarray(
        rng.integers(0, B_u, size=(rows_u, F_u)).astype(np.uint8))
    rel_u = jnp.asarray(rng.integers(0, n_u, size=rows_u).astype(
        np.int32))
    g_u = jnp.asarray(rng.normal(size=rows_u).astype(np.float32))
    w_u = jnp.asarray((rng.uniform(size=rows_u) < 0.95).astype(
        np.float32))
    ones_u = jnp.ones_like(w_u)
    want_u = jax.jit(build_histogram, static_argnums=(5, 6, 7))(
        binned_u, rel_u, g_u, ones_u, w_u, n_u, B_u, "pallas")
    got_u = expand_unit_hess(jax.jit(
        build_histogram, static_argnums=(5, 6, 7),
        static_argnames=("unit_hess",))(
        binned_u, rel_u, g_u, ones_u, w_u, n_u, B_u, "pallas",
        unit_hess=True))
    err_u = float(jnp.max(jnp.abs(got_u - want_u)) /
                  (jnp.max(jnp.abs(want_u)) + 1e-30))
    checks.append({"check": "unit_hess_kernel", "ok": err_u < 1e-5,
                   "rel_err": err_u})

    # 2d. 2-term mantissa throughput mode (H2O_TPU_HIST_TERMS=2): the
    # stacked A drops a third of its M rows; parity is checked against
    # the SEGMENT reference (so the check stays meaningful whatever
    # mode the gate itself runs under) at single-precision-histogram
    # tolerance (products ~2^-16)
    import h2o_kubernetes_tpu.ops.histogram as H

    orig_terms = H._TERMS
    H._TERMS = 2
    jax.clear_caches()    # _TERMS is not a trace key: force a retrace
    try:
        parity("two_term_kernel", 100_000, 10, 16, 256, tol=1e-4)
    finally:
        H._TERMS = orig_terms
        jax.clear_caches()

    # 3. fused boost scans compile + run (binomial and multinomial)
    import h2o_kubernetes_tpu as h2o
    from h2o_kubernetes_tpu.models import GBM

    n = 4096
    x = rng.normal(size=n).astype(np.float32)
    y2 = np.where(x > 0, "p", "n")
    fr2 = h2o.Frame.from_arrays({"x": x, "y": y2})
    m2 = GBM(ntrees=3, max_depth=4, seed=0).train(
        y="y", training_frame=fr2)
    checks.append({"check": "boost_scan_binomial",
                   "ok": len(m2.scoring_history) > 0})
    y3 = np.where(x > 0.5, "a", np.where(x < -0.5, "b", "c"))
    fr3 = h2o.Frame.from_arrays({"x": x, "y": y3})
    m3 = GBM(ntrees=3, max_depth=3, seed=0).train(
        y="y", training_frame=fr3)
    checks.append({"check": "boost_scan_multinomial",
                   "ok": m3.ntrees == 9})

    # 4. flattened serving scorer (models/tree/core.py flat_margin)
    # must match the binned heap re-descent BITWISE on chip — the
    # serving fast path and MOJO export both descend these arrays.
    # NA + categorical + high-cardinality grouped bins in one frame.
    xna = x.copy()
    xna[::13] = np.nan
    gg = np.array([f"L{i}" for i in range(80)])[
        rng.integers(0, 80, size=n)]
    yf = np.where(np.nan_to_num(xna) > 0, "p", "n")
    frf = h2o.Frame.from_arrays({"x": xna, "g": gg, "y": yf})
    mf = GBM(ntrees=4, max_depth=4, nbins=64, seed=0).train(
        y="y", training_frame=frf)
    Xf = mf._design_matrix(frf)
    flat_ok = bool(np.array_equal(np.asarray(mf._margins(Xf)),
                                  np.asarray(mf._margins_binned(Xf))))
    checks.append({"check": "flat_scorer_parity", "ok": flat_ok})
    X3 = m3._design_matrix(fr3)
    flat3_ok = bool(np.array_equal(np.asarray(m3._margins(X3)),
                                   np.asarray(m3._margins_binned(X3))))
    checks.append({"check": "flat_scorer_parity_multinomial",
                   "ok": flat3_ok})

    # 4b. compiled TreeSHAP serving (models/tree/shap.flat_shap) must
    # match the f64 host recursion on chip AND hold the additivity
    # invariant on device — the path tables + unwind DP must survive
    # real lowering, not just CPU interpret. Same NA + high-card
    # grouped-enum frame as the flat-scorer check.
    Xf_np = np.asarray(Xf)[: n]
    contrib = mf.predict_contributions(frf)
    host_phi = np.stack([contrib.vec(c).to_numpy()
                         for c in contrib.names], axis=1)
    dev_phi = mf.contrib_numpy(Xf_np)
    shap_err = float(np.abs(dev_phi - host_phi).max())
    margins_f = np.asarray(mf._margins(Xf))[: n]
    add_err = float(np.abs(dev_phi.sum(axis=1) - margins_f).max())
    checks.append({"check": "shap_parity",
                   "ok": shap_err < 1e-4 and add_err < 1e-4,
                   "host_err": shap_err, "additivity_err": add_err})

    # 5. EFB parity on chip: bundled vs unbundled training must pick
    # identical splits and produce bitwise-identical predictions on an
    # exact-sum wide one-hot fixture (models/tree/efb.py — the bundled
    # histogram runs the SAME pallas kernel at bundled width, and the
    # decode/remainder math must survive real Mosaic, not just
    # interpret mode). Single gaussian round on a dyadic response =
    # every sum exact, so any deviation is a bug, not float noise.
    ne = 4096
    ecols = {}
    cat_e = rng.integers(0, 16, size=(4, ne))
    for gi in range(4):
        for k in range(16):
            ecols[f"c{gi}_{k}"] = (cat_e[gi] == k).astype(np.float32)
    ecols["c0_0"][::31] = np.nan
    ecols["dx"] = rng.normal(size=ne).astype(np.float32)
    ecols["ye"] = ((cat_e[0] == 1).astype(np.float32)
                   - (cat_e[1] == 2) + (ecols["dx"] > 0)).astype(
        np.float32)
    fr_e = h2o.Frame.from_arrays(ecols)

    def _efb_leg(env):
        os.environ["H2O_TPU_EFB"] = env
        try:
            return GBM(ntrees=1, max_depth=5, seed=0).train(
                y="ye", training_frame=fr_e)
        finally:
            os.environ.pop("H2O_TPU_EFB", None)

    m_b = _efb_leg("1")
    m_u = _efb_leg("0")
    isp = np.asarray(m_u.trees.is_split)
    efb_ok = bool(np.array_equal(isp, np.asarray(m_b.trees.is_split)))
    for fld in ("split_feat", "split_bin", "na_left"):
        a = np.where(isp, np.asarray(getattr(m_u.trees, fld)), -9)
        b = np.where(isp, np.asarray(getattr(m_b.trees, fld)), -9)
        efb_ok &= bool(np.array_equal(a, b))
    efb_ok &= bool(np.array_equal(
        np.asarray(m_u.predict_raw(fr_e)),
        np.asarray(m_b.predict_raw(fr_e))))
    checks.append({"check": "efb_parity", "ok": efb_ok})

    # 6. GOSS sampled boost program (ISSUE 13): the static-capacity
    # compaction (jnp.nonzero + gathers inside the shard_map scan),
    # the hashed per-row draws and the full-row re-descent margin
    # update must survive real lowering, not just CPU. Pinned two
    # ways: a+b=1 keeps every row at amplification (1-a)/b = 1, so
    # the SAMPLED program must reproduce the unsampled m2 BITWISE;
    # and a really-sampled config must be seeded-deterministic while
    # actually differing from unsampled.
    def _goss_leg(a, b):
        os.environ.update({"H2O_TPU_GOSS": "1",
                           "H2O_TPU_GOSS_TOP_A": a,
                           "H2O_TPU_GOSS_RAND_B": b})
        try:
            return GBM(ntrees=3, max_depth=4, seed=0).train(
                y="y", training_frame=fr2)
        finally:
            for k in ("H2O_TPU_GOSS", "H2O_TPU_GOSS_TOP_A",
                      "H2O_TPU_GOSS_RAND_B"):
                os.environ.pop(k, None)

    def _trees_equal(ma, mb):
        return all(np.array_equal(np.asarray(x), np.asarray(y))
                   for x, y in zip(jax.tree.flatten(ma.trees)[0],
                                   jax.tree.flatten(mb.trees)[0]))

    m_gid = _goss_leg("0.5", "0.5")
    goss_ok = _trees_equal(m2, m_gid)
    m_g1 = _goss_leg("0.2", "0.2")
    m_g2 = _goss_leg("0.2", "0.2")
    goss_ok &= _trees_equal(m_g1, m_g2)
    goss_ok &= not _trees_equal(m2, m_g1)
    checks.append({"check": "goss_parity", "ok": bool(goss_ok)})

    ok = all(c["ok"] for c in checks)
    print(json.dumps({"gate": "pass" if ok else "fail",
                      "platform": platform, "checks": checks}))
    return 0 if ok else 1


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception as e:     # the gate must report, not traceback-die
        traceback.print_exc()
        print(json.dumps({"gate": "fail", "error": repr(e)[:300]}))
        sys.exit(1)
