#!/usr/bin/env python
"""Closed-loop REST scoring load generator (docs/SERVING.md).

Hammers POST /3/Predictions/models/{key} (the inline serving route:
JSON rows in, predictions out, micro-batched server-side) with N
concurrent closed-loop workers — each worker keeps exactly one request
in flight, so offered load tracks service capacity, the way a fleet of
synchronous clients behaves.  Reports rows/s + latency percentiles as
ONE JSON line, plus the server's micro-batcher stats when the server
runs in-process.

Usage::

    python tools/score_load.py                      # self-contained:
        # starts an in-process REST server with a synthetic GBM
    python tools/score_load.py --url http://host:54321 --model gbm1
    python tools/score_load.py --concurrency 16 --rows 32 --seconds 10
    python tools/score_load.py --contributions    # TreeSHAP explain
        # route (POST .../contributions) under the same closed loop
    python tools/score_load.py \
        --url http://h1:54321,http://h2:54321 --model pool \
        --columns x0,...  --assert-zero-5xx      # drive a scorer POOL

Multi-target mode (a comma list of ``--url`` targets, or a dynamic
target provider via :func:`run_load_multi`) is the Service analog the
operator drills ride: a background poller tracks each target's
``/readyz`` and workers round-robin over the READY set only — a
replica mid-warm-up or cordoned for a rolling update receives nothing,
like a pod pulled from a Service's endpoints. ``--assert-zero-5xx``
makes the run fail loudly (rc 1) on ANY 5xx response — the
rolling-update acceptance bar (docs/OPERATOR.md).

The gain this measures is recorded by ``bench_suite``'s
``gbm_score_rows_per_sec`` config; this tool is the REST-level
closed-loop view of the same fast path (request coalescing included).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _post_json(url: str, payload: dict, timeout: float = 120.0,
               headers: dict | None = None) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json", **(headers or {})})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _get_json(url: str, timeout: float = 5.0) -> dict | None:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.loads(r.read())
    except Exception:  # noqa: BLE001 — scrape is best-effort
        return None


def _self_server(port: int = 0):
    """Start an in-process server + synthetic GBM; returns
    (server, base_url, model_key, feature_columns, row_maker)."""
    import socket

    import numpy as np

    import h2o_kubernetes_tpu as h2o
    from h2o_kubernetes_tpu import rest
    from h2o_kubernetes_tpu.models import GBM
    from h2o_kubernetes_tpu.runtime import make_mesh, set_global_mesh

    set_global_mesh(make_mesh())
    rng = np.random.default_rng(0)
    n = 20_000
    cols = {f"x{i}": rng.normal(size=n).astype(np.float32)
            for i in range(8)}
    cols["c1"] = np.array(["a", "b", "c", "d"])[rng.integers(0, 4, n)]
    cols["y"] = np.where(cols["x0"] - cols["x1"] > 0, "late", "ontime")
    fr = h2o.Frame.from_arrays(cols)
    model = GBM(ntrees=20, max_depth=5, learn_rate=0.2, seed=1).train(
        y="y", training_frame=fr)
    rest.MODELS["score_load_gbm"] = model
    if port == 0:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
    srv = rest.start_server(port)
    return (srv, f"http://127.0.0.1:{port}", "score_load_gbm",
            [f"x{i}" for i in range(8)] + ["c1"])


def _percentile_ms(lat: list[float], p: float):
    """Index-pick percentile in ms over raw seconds latencies (None
    when empty) — the ONE percentile formula every load mode uses."""
    if not lat:
        return None
    lat = sorted(lat)
    return round(lat[min(len(lat) - 1, int(p * len(lat)))] * 1e3, 2)


def _result_record(latencies: list[float], wall: float,
                   rows_per_request: int, concurrency: int,
                   fivexx: list[str], errors: list[str],
                   **extra) -> dict:
    """The one result-record shape shared by every load mode — a new
    field lands in single-target AND multi-target AND zipf output or
    none of them."""
    n = len(latencies)
    return {
        "metric": "rest_score_rows_per_sec",
        "value": round(n * rows_per_request / max(wall, 1e-9), 1),
        "unit": "rows/s",
        "requests": n,
        "requests_per_s": round(n / max(wall, 1e-9), 1),
        "fivexx": len(fivexx),
        "fivexx_sample": fivexx[:5],
        "errors": len(errors),
        "error_sample": errors[:3],
        "p50_ms": _percentile_ms(latencies, 0.50),
        "p95_ms": _percentile_ms(latencies, 0.95),
        "p99_ms": _percentile_ms(latencies, 0.99),
        "concurrency": concurrency,
        "rows_per_request": rows_per_request,
        "seconds": round(wall, 2),
        **extra,
    }


def run_load(url: str, model_key: str, columns: list[str],
             concurrency: int = 8, rows_per_request: int = 32,
             seconds: float = 10.0, seed: int = 0,
             contributions: bool = False) -> dict:
    """Closed-loop drive; returns the result record (also printable).

    ``contributions=True`` drives the explainable-serving route
    (``POST .../contributions`` — per-row TreeSHAP through the same
    micro-batcher, docs/SERVING.md "Explainable serving") instead of
    predictions; success = a [rows, F+1] contributions matrix back."""
    suffix = "/contributions" if contributions else ""
    route = f"{url}/3/Predictions/models/{model_key}{suffix}"
    out_key = "contributions" if contributions else "predict"
    bodies = _make_bodies(columns, rows_per_request, seed)
    deadline = time.perf_counter() + seconds
    lock = threading.Lock()
    latencies: list[float] = []
    errors: list[str] = []
    fivexx: list[str] = []

    def worker(wid: int) -> None:
        import urllib.error

        i = wid
        while time.perf_counter() < deadline:
            body = bodies[i % len(bodies)]
            i += 1
            t0 = time.perf_counter()
            try:
                out = _post_json(route, body)
                ok = len(out[out_key]) == rows_per_request
            except urllib.error.HTTPError as e:
                # 5xx tracked apart from transport noise so
                # --assert-zero-5xx has a precise needle
                label = f"HTTP {e.code} {e.read()[:120]!r}"
                with lock:
                    (fivexx if e.code >= 500 else errors).append(label)
                continue
            except Exception as e:  # noqa: BLE001 — record, keep going
                with lock:
                    errors.append(repr(e)[:200])
                continue
            dt = time.perf_counter() - t0
            with lock:
                if ok:
                    latencies.append(dt)
                else:
                    errors.append("short response")

    # one warm-up request so the timed window measures steady state,
    # not the first XLA compile
    _post_json(route, bodies[0])
    t_start = time.perf_counter()
    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    return _result_record(latencies, wall, rows_per_request,
                          concurrency, fivexx, errors,
                          route="contributions" if contributions
                          else "predictions")


def _make_bodies(columns: list[str], rows_per_request: int, seed: int,
                 pool: int = 16) -> list[dict]:
    """Pre-generated list-shaped request bodies (shared by both load
    modes so workers spend their loop on HTTP, not JSON building)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    bodies = []
    for _ in range(pool):
        rows = [[(float(rng.normal()) if c != "c1" else
                  ["a", "b", "c", "d"][int(rng.integers(0, 4))])
                 for c in columns] for _ in range(rows_per_request)]
        bodies.append({"rows": rows, "columns": columns})
    return bodies


def run_load_multi(targets, model_key: str, columns: list[str],
                   concurrency: int = 4, rows_per_request: int = 8,
                   seconds: float | None = None, stop_event=None,
                   seed: int = 0, ready_poll_s: float = 0.05,
                   request_timeout: float = 30.0) -> dict:
    """Round-robin closed-loop drive over a DYNAMIC set of pool
    replicas — the k8s-Service analog for operator drills.

    ``targets`` is a list of base URLs or a zero-arg callable returning
    one (the reconciler's live endpoint list: replicas join as they
    are provisioned, leave the instant they are cordoned). A poller
    thread refreshes each target's ``/readyz`` every ``ready_poll_s``
    and workers pick targets FROM the ready set under its lock, so the
    generator never *chooses* an unready target by construction. (The
    pick→arrival in-flight race is the router race the operator's
    deregister grace exists for; the measured check of that contract
    is the SERVER-side ``scored_while_unready`` counter on /3/Stats,
    which the drills assert — not a client-side literal.) ``fivexx``
    counts real 5xx contract violations.

    Runs until ``stop_event`` is set (or ``seconds`` elapses). Returns
    the single-target record plus ``fivexx``/``fourxx``/``by_target``/
    ``no_ready_target_waits``."""
    import urllib.error

    get_targets = targets if callable(targets) else (lambda: targets)
    stop = stop_event or threading.Event()
    deadline = (time.perf_counter() + seconds) if seconds else None
    bodies = _make_bodies(columns, rows_per_request, seed)
    lock = threading.Lock()
    ready: set[str] = set()
    latencies: list[float] = []
    fivexx: list[str] = []
    fourxx: list[str] = []
    errors: list[str] = []
    by_target: dict[str, dict] = {}
    no_ready_waits = [0]

    def _done() -> bool:
        return stop.is_set() or \
            (deadline is not None and time.perf_counter() >= deadline)

    def poller():
        while not _done():
            now_ready = set()
            for t in list(get_targets()):
                try:
                    with urllib.request.urlopen(
                            t.rstrip("/") + "/readyz", timeout=2.0) as r:
                        if r.status == 200:
                            now_ready.add(t.rstrip("/"))
                except Exception:  # noqa: BLE001 — down/503 = unready
                    pass
            with lock:
                ready.clear()
                ready.update(now_ready)
            time.sleep(ready_poll_s)

    rr = [0]

    def worker(wid: int) -> None:
        i = wid
        while not _done():
            with lock:
                pool = sorted(ready)
                if pool:
                    target = pool[rr[0] % len(pool)]
                    rr[0] += 1
                else:
                    no_ready_waits[0] += 1   # under lock: workers race
            if not pool:
                time.sleep(0.02)
                continue
            body = bodies[i % len(bodies)]
            i += 1
            route = f"{target}/3/Predictions/models/{model_key}"
            t0 = time.perf_counter()
            try:
                out = _post_json(route, body, timeout=request_timeout)
                ok = len(out["predict"]) == rows_per_request
                dt = time.perf_counter() - t0
                with lock:
                    rec = by_target.setdefault(
                        target, {"requests": 0, "fivexx": 0})
                    rec["requests"] += 1
                    if ok:
                        latencies.append(dt)
                    else:
                        errors.append(f"{target}: short response")
            except urllib.error.HTTPError as e:
                label = f"{target}: HTTP {e.code} {e.read()[:120]!r}"
                with lock:
                    rec = by_target.setdefault(
                        target, {"requests": 0, "fivexx": 0})
                    rec["requests"] += 1
                    if e.code >= 500:
                        rec["fivexx"] += 1
                        fivexx.append(label)
                    else:
                        fourxx.append(label)
            except Exception as e:  # noqa: BLE001 — record, keep going
                with lock:
                    errors.append(f"{target}: {e!r}"[:200])

    t_start = time.perf_counter()
    pt = threading.Thread(target=poller, daemon=True,
                          name="score-load-ready-poller")
    pt.start()
    workers = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(concurrency)]
    for t in workers:
        t.start()
    for t in workers:
        t.join()
    pt.join(timeout=5.0)
    wall = time.perf_counter() - t_start
    return _result_record(latencies, wall, rows_per_request,
                          concurrency, fivexx, errors,
                          fourxx=len(fourxx),
                          no_ready_target_waits=no_ready_waits[0],
                          by_target=by_target)


# ---------------------------------------------------------------------------
# Multi-tenant Zipf traffic (docs/SERVING.md "Multi-tenant serving")
# ---------------------------------------------------------------------------
#
# ``--models N --zipf-s S`` drives N registry-pushed models with
# Zipf(s) popularity (rank 1 hottest) — the tenant-population shape a
# fleet node actually serves.  Per-model latency/5xx/shed accounting
# rides the same body-pool / result-record plumbing as the pool modes,
# plus popularity-DECILE percentiles (the tail decile is the fairness
# contract's needle) and a /3/Stats scrape of the byte-budgeted scorer
# cache (resident bytes vs budget, evictions, promotions, compile
# watch).  ``run_zipf_bench`` is the bench_suite entry: residency
# sweep + the hot-model storm legs (fairness on vs off).


def _self_server_tenants(n_models: int, seed: int = 0,
                         base_variants: int = 4,
                         warm_buckets=(128,), port: int = 0):
    """In-process REST server with ``n_models`` registry-loaded tiny
    FlatTreeScorers under keys m000..m{N-1}; returns
    (server, url, model_keys, feature_columns).

    A handful of distinct base GBMs rotate across the tenant keys:
    every tenant is its OWN model instance (own jitted executables,
    own byte charge) while the artifact variety keeps warm-up cost
    bounded — same-HLO tenants warm from the persistent XLA cache."""
    import socket

    import numpy as np

    import h2o_kubernetes_tpu as h2o
    from h2o_kubernetes_tpu import rest
    from h2o_kubernetes_tpu.models import GBM
    from h2o_kubernetes_tpu.operator.registry import ModelRegistry
    from h2o_kubernetes_tpu.runtime import make_mesh, set_global_mesh
    from h2o_kubernetes_tpu.runtime.backend import \
        enable_persistent_compile_cache

    # every serving compile must persist (threshold 0): the
    # evict→promote contract under a byte budget is "a pcache hit,
    # never a cold compile", and tenant models compile in << 0.5s
    enable_persistent_compile_cache(min_compile_secs=0.0)
    set_global_mesh(make_mesh())
    rng = np.random.default_rng(seed)
    n = 2000
    cols = {f"x{i}": rng.normal(size=n).astype(np.float32)
            for i in range(6)}
    cols["y"] = np.where(cols["x0"] - cols["x1"] > 0, "late", "ontime")
    fr = h2o.Frame.from_arrays(cols)
    reg = ModelRegistry(f"mem://score_load_tenants_{os.getpid()}")
    nb = max(1, min(base_variants, n_models))
    arts = []
    for b in range(nb):
        m = GBM(ntrees=2 + b, max_depth=2, seed=b + 1).train(
            y="y", training_frame=fr)
        reg.publish(m, f"tenant{b}")
        arts.append(f"tenant{b}")
    if port == 0:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
    srv = rest.start_server(port)
    url = f"http://127.0.0.1:{port}"
    keys = [f"m{i:03d}" for i in range(n_models)]
    for i, key in enumerate(keys):
        reg.push(url, arts[i % nb], 1, key,
                 warm_buckets=list(warm_buckets))
    return srv, url, keys, [f"x{i}" for i in range(6)]


def _popularity_deciles(model_keys: list[str],
                        per_model: dict) -> list[dict]:
    """Aggregate per-model records into 10 popularity-rank deciles
    (decile 1 = hottest ranks). The TAIL decile's p99 is the fairness
    acceptance needle: it must hold its SLO while a hot decile
    floods."""
    N = len(model_keys)
    out = []
    for d in range(10):
        lo, hi = (d * N) // 10, ((d + 1) * N) // 10
        ks = model_keys[lo:hi]
        if not ks:
            continue
        lats = [t for k in ks for t in per_model[k]["lat"]]
        out.append({
            "decile": d + 1,
            "models": len(ks),
            "requests": sum(per_model[k]["requests"] for k in ks),
            "fivexx": sum(per_model[k]["fivexx"] for k in ks),
            "shed": sum(per_model[k]["shed"] for k in ks),
            "degraded": sum(per_model[k].get("degraded", 0)
                            for k in ks),
            "p50_ms": _percentile_ms(lats, 0.50),
            "p99_ms": _percentile_ms(lats, 0.99),
        })
    return out


def run_load_zipf(targets, model_keys: list[str], columns: list[str],
                  concurrency: int = 8, rows_per_request: int = 16,
                  seconds: float = 15.0, zipf_s: float = 1.1,
                  seed: int = 0, stop_event=None,
                  request_timeout: float = 30.0,
                  stats_poll_s: float = 0.5,
                  router: bool = False) -> dict:
    """Closed-loop Zipf(s) model-popularity drive: each request picks
    its model by popularity rank (key order = rank, 1 hottest) and
    round-robins over the READY targets, exactly like the pool mode.

    Returns the shared result record plus ``by_model`` (per-tenant
    requests/latency/5xx/shed), popularity ``deciles``, and a
    ``residency`` section sampled off /3/Stats every ``stats_poll_s``
    (max resident bytes observed, whether the byte budget was ever
    exceeded, eviction/promotion/compile deltas over the run).

    ``router=True`` is the sharded-fleet mode (the target is a
    front-door router, tools/chaos.py ``router-shard-kill``): a typed
    503 carrying the ``placement_pending`` hint is counted per model
    as ``degraded`` — the EXPECTED answer for a tail tenant whose only
    shard just died, mid re-placement — instead of a raw 5xx, so the
    zero-5xx acceptance needle stays precise."""
    import urllib.error

    import numpy as np

    from tools.datasets import zipf_probs

    if isinstance(targets, str):
        targets = [targets]
    get_targets = targets if callable(targets) else (lambda: targets)
    probs = zipf_probs(len(model_keys), zipf_s)
    stop = stop_event or threading.Event()
    deadline = time.perf_counter() + seconds
    bodies = _make_bodies(columns, rows_per_request, seed)
    lock = threading.Lock()
    ready: set[str] = set()
    latencies: list[float] = []
    fivexx: list[str] = []
    errors: list[str] = []
    per_model = {k: {"requests": 0, "fivexx": 0, "shed": 0,
                     "fourxx": 0, "degraded": 0, "lat": []}
                 for k in model_keys}
    residency = {"samples": 0, "max_resident_bytes": 0,
                 "budget_bytes": None, "budget_exceeded": 0,
                 "max_resident_models": 0}
    stats_first: dict[str, dict] = {}   # per TARGET: deltas must not
    stats_last: dict[str, dict] = {}    # mix one replica into another
    target_failovers = [0]    # router mode: transport-level re-sends

    def _done() -> bool:
        return stop.is_set() or time.perf_counter() >= deadline

    def poller():
        while not _done():
            now_ready = set()
            for t in list(get_targets()):
                st = _get_json(t.rstrip("/") + "/readyz", timeout=2.0)
                if st is not None:
                    now_ready.add(t.rstrip("/"))
            with lock:
                ready.clear()
                ready.update(now_ready)
            # residency watch: the budget contract is "never exceeded
            # WHILE the storm runs", so it is sampled live, not once
            # at the end
            for t in sorted(now_ready):
                st = _get_json(t + "/3/Stats", timeout=2.0)
                if not st:
                    continue
                sc = st.get("scorer_cache") or {}
                with lock:
                    stats_first.setdefault(t, st)
                    stats_last[t] = st
                    residency["samples"] += 1
                    rb = int(sc.get("resident_bytes") or 0)
                    bb = int(sc.get("budget_bytes") or 0)
                    residency["max_resident_bytes"] = max(
                        residency["max_resident_bytes"], rb)
                    residency["max_resident_models"] = max(
                        residency["max_resident_models"],
                        int(sc.get("resident") or 0))
                    residency["budget_bytes"] = bb
                    if bb > 0 and rb > bb:
                        residency["budget_exceeded"] += 1
            time.sleep(stats_poll_s)

    rr = [0]

    def worker(wid: int) -> None:
        rng = np.random.default_rng(seed * 1000 + wid + 1)
        i = wid
        while not _done():
            with lock:
                pool = sorted(ready)
                if pool:
                    target = pool[rr[0] % len(pool)]
                    rr[0] += 1
            if not pool:
                time.sleep(0.02)
                continue
            key = model_keys[int(rng.choice(len(model_keys), p=probs))]
            body = bodies[i % len(bodies)]
            i += 1
            # router mode: a killed router's in-flight requests die at
            # the TRANSPORT level (reset/refused) — exactly the
            # failure N interchangeable routers behind a balancer
            # exist to absorb, so the same request retries on each
            # remaining ready target before anything lands in
            # `errors` (a balancer re-dispatches the same way)
            with lock:
                alts = [t for t in sorted(ready) if t != target]
            tries = [target] + (alts if router else [])
            for ti, tgt in enumerate(tries):
                route = f"{tgt}/3/Predictions/models/{key}"
                t0 = time.perf_counter()
                try:
                    out = _post_json(route, body,
                                     timeout=request_timeout)
                    ok = len(out["predict"]) == rows_per_request
                    dt = time.perf_counter() - t0
                    with lock:
                        rec = per_model[key]
                        rec["requests"] += 1
                        if ok:
                            rec["lat"].append(dt)
                            latencies.append(dt)
                        else:
                            errors.append(f"{key}: short response")
                    break
                except urllib.error.HTTPError as e:
                    ebody = e.read()
                    label = f"{key}: HTTP {e.code} {ebody[:120]!r}"
                    degraded = (router and e.code == 503
                                and (b"placement_pending" in ebody
                                     or b"table_pending" in ebody))
                    with lock:
                        rec = per_model[key]
                        rec["requests"] += 1
                        if degraded:
                            # the router's typed degraded answer: the
                            # tenant's shard is down and re-placement
                            # is in flight — expected during the
                            # drill's failure window, not a 5xx
                            # contract breach
                            rec["degraded"] += 1
                        elif e.code >= 500:
                            rec["fivexx"] += 1
                            fivexx.append(label)
                        elif e.code == 429:
                            rec["shed"] += 1
                        else:
                            rec["fourxx"] += 1
                            errors.append(label[:200])
                    if e.code == 429 or degraded:
                        time.sleep(0.005)   # shed: backoff, retry on
                    break
                except Exception as e:  # noqa: BLE001 — failover/record
                    with lock:
                        ready.discard(tgt.rstrip("/"))
                    if ti + 1 < len(tries):
                        with lock:
                            target_failovers[0] += 1
                        continue
                    with lock:
                        errors.append(f"{key}: {e!r}"[:200])

    t_start = time.perf_counter()
    pt = threading.Thread(target=poller, daemon=True,
                          name="score-load-zipf-poller")
    pt.start()
    workers = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(concurrency)]
    for t in workers:
        t.start()
    for t in workers:
        t.join()
    pt.join(timeout=5.0)
    wall = time.perf_counter() - t_start

    def _delta(section: str, field: str):
        tot, seen = 0, False
        for t, st0 in stats_first.items():
            st1 = stats_last.get(t)
            if not st1:
                continue
            a = (st0.get(section) or {}).get(field)
            b = (st1.get(section) or {}).get(field)
            if a is None or b is None:
                continue
            tot += b - a
            seen = True
        return tot if seen else None

    residency["evictions_delta"] = _delta("scorer_cache", "evictions")
    residency["promotions_delta"] = _delta("scorer_cache",
                                           "promotions")
    residency["compiles_delta"] = _delta("compiles", "compiles")
    residency["pcache_hits_delta"] = _delta("compiles", "pcache_hits")
    residency["pcache_misses_delta"] = _delta("compiles",
                                              "pcache_misses")
    shed = sum(r["shed"] for r in per_model.values())
    return _result_record(
        latencies, wall, rows_per_request, concurrency, fivexx, errors,
        zipf_s=zipf_s, models=len(model_keys), shed=shed,
        degraded=sum(r["degraded"] for r in per_model.values()),
        target_failovers=target_failovers[0],
        by_model={k: {"requests": r["requests"],
                      "fivexx": r["fivexx"], "shed": r["shed"],
                      "degraded": r["degraded"],
                      "p50_ms": _percentile_ms(r["lat"], 0.50),
                      "p99_ms": _percentile_ms(r["lat"], 0.99)}
                  for k, r in per_model.items()},
        deciles=_popularity_deciles(model_keys, per_model),
        residency=residency)


def _storm_leg(url: str, hot_key: str, tail_key: str,
               columns: list[str], fair: bool,
               hot_workers: int = 16, hot_rows: int = 256,
               tail_rows: int = 8, seconds: float = 6.0,
               queue_max: int = 8, tail_deadline_ms: float = 500.0,
               seed: int = 0) -> dict:
    """One hot-model storm leg: ``hot_workers`` closed-loop threads
    flood ``hot_key`` (standard class) while ONE tail worker sends
    small ``interactive``-class requests to ``tail_key``. The tail's
    SLO is met iff it was never shed and never 5xx'd/504'd: the
    interactive class carries an IMPLICIT server-side deadline
    (rest.SLO_CLASSES), so every 200 response proves its result was
    ready inside that deadline — zero 504s IS the server-side p99 ≤
    deadline proof, immune to the load generator's own scheduling
    noise (client-observed p99 is recorded alongside, informational:
    on a 1-core box it includes generator GIL/scheduler time). With
    fairness ON the hot model sheds against its own queue share and
    the tail is admitted + dispatched first by construction; with
    fairness OFF the hot flood owns the whole queue and the tail
    provably misses (shed and/or 504)."""
    import urllib.error

    os.environ["H2O_TPU_SCORE_FAIRNESS"] = "1" if fair else "0"
    os.environ["H2O_TPU_SCORE_QUEUE_MAX"] = str(queue_max)
    # a wide batch window makes the storm's queue dynamics structural
    # instead of timing-dependent: while the dispatcher collects, the
    # closed-loop hot flood refills the queue to its cap — unfair, the
    # tail then finds it FULL (shed/504, the provable miss); fair, the
    # hot model's share cap leaves tail room by construction
    os.environ["H2O_TPU_SCORE_BATCH_US"] = "20000"
    hot_bodies = _make_bodies(columns, hot_rows, seed, pool=4)
    tail_bodies = _make_bodies(columns, tail_rows, seed + 1, pool=4)
    stop = threading.Event()
    lock = threading.Lock()
    hot = {"requests": 0, "shed": 0, "fivexx": 0}
    tail = {"requests": 0, "shed": 0, "fivexx": 0, "deadline_504": 0,
            "fourxx": 0, "lat": []}

    def hot_worker(wid: int) -> None:
        i = wid
        route = f"{url}/3/Predictions/models/{hot_key}"
        while not stop.is_set():
            body = hot_bodies[i % len(hot_bodies)]
            i += 1
            try:
                _post_json(route, body, timeout=30.0)
                with lock:
                    hot["requests"] += 1
            except urllib.error.HTTPError as e:
                with lock:
                    hot["requests"] += 1
                    if e.code == 429:
                        hot["shed"] += 1
                    elif e.code >= 500:
                        hot["fivexx"] += 1
                e.read()
                if e.code == 429:
                    time.sleep(0.01)    # shed backoff: don't spin
            except Exception:  # noqa: BLE001 — the leg keeps driving
                pass

    def tail_worker() -> None:
        i = 0
        route = f"{url}/3/Predictions/models/{tail_key}"
        while not stop.is_set():
            body = tail_bodies[i % len(tail_bodies)]
            i += 1
            t0 = time.perf_counter()
            try:
                _post_json(route, body, timeout=30.0,
                           headers={"X-H2O-SLO": "interactive"})
                with lock:
                    tail["requests"] += 1
                    tail["lat"].append(time.perf_counter() - t0)
            except urllib.error.HTTPError as e:
                with lock:
                    tail["requests"] += 1
                    if e.code == 429:
                        tail["shed"] += 1
                    elif e.code == 504:
                        tail["deadline_504"] += 1
                    elif e.code >= 500:
                        tail["fivexx"] += 1
                    else:
                        # residual 4xx (bad key/payload): counted, so
                        # an all-errors leg cannot read as SLO-met
                        tail["fourxx"] += 1
                e.read()
                time.sleep(0.005)
            except Exception:  # noqa: BLE001
                pass
            time.sleep(0.01)    # ~100 rps offered tail rate

    # warm both request shapes before the clock starts: the leg
    # measures fairness under load, not a first-dispatch compile
    # (hot_rows may pad to a bucket warm-up never traced)
    try:
        _post_json(f"{url}/3/Predictions/models/{hot_key}",
                   hot_bodies[0], timeout=120.0)
        _post_json(f"{url}/3/Predictions/models/{tail_key}",
                   tail_bodies[0], timeout=120.0)
    except Exception:  # noqa: BLE001 — the leg's own counters judge
        pass
    threads = [threading.Thread(target=hot_worker, args=(w,),
                                daemon=True)
               for w in range(hot_workers)]
    threads.append(threading.Thread(target=tail_worker, daemon=True))
    for t in threads:
        t.start()
    time.sleep(seconds)
    stop.set()
    for t in threads:
        t.join(timeout=30.0)
    p99 = _percentile_ms(tail["lat"], 0.99)
    # zero shed + zero 504 + zero 5xx/4xx AND at least one SUCCESSFUL
    # score == the SLO held: every admitted tail request produced its
    # result inside the interactive class's server-enforced deadline
    # (a late result would have 504'd). len(lat) > 0, not requests >
    # 0: a leg that only ever errored (bad key, unloaded artifact)
    # must never read as a passing fairness proof.
    slo_met = (tail["shed"] == 0 and tail["fivexx"] == 0
               and tail["deadline_504"] == 0 and tail["fourxx"] == 0
               and len(tail["lat"]) > 0)
    return {"fair": fair, "seconds": seconds,
            "queue_max": queue_max, "hot_workers": hot_workers,
            "hot_rows": hot_rows, "tail_rows": tail_rows,
            "hot": dict(hot),
            "tail": {**{k: v for k, v in tail.items() if k != "lat"},
                     "p50_ms": _percentile_ms(tail["lat"], 0.50),
                     "p99_ms": p99,
                     "deadline_ms": tail_deadline_ms},
            "tail_slo_met": slo_met}


def _metrics_scrape(url: str) -> dict:
    """Time one GET /metrics against a serving target: the bench
    records exposition cost alongside the serving p99 so the artifact
    can state what a Prometheus scrape adds at the measured shape
    (acceptance note: < 1% of the storm-shape p99)."""
    import time as _time
    import urllib.request

    t0 = _time.monotonic()
    try:
        with urllib.request.urlopen(url.rstrip("/") + "/metrics",
                                    timeout=10) as r:
            body = r.read()
        return {"ok": True, "ms": round(
            (_time.monotonic() - t0) * 1000.0, 3),
            "bytes": len(body)}
    except Exception as e:  # noqa: BLE001 — the bench must not die
        return {"ok": False, "error": repr(e)[:120],
                "ms": round((_time.monotonic() - t0) * 1000.0, 3)}


def run_zipf_bench(n_models: int = 100, seconds: float = 15.0,
                   zipf_s: float = 1.1, budget_mb: float = 4.0,
                   concurrency: int = 6, rows_per_request: int = 16,
                   storm_seconds: float = 6.0, seed: int = 0) -> dict:
    """The BENCH_SUITE multi-tenant leg (one self-contained record):

    1. **Residency sweep** — ``n_models`` registry-pushed tenants
       under a ``budget_mb`` byte budget, Zipf(s) traffic: resident
       bytes must never exceed the budget, evictions/promotions churn,
       and every compile during the sweep is a persistent-cache HIT
       (promotion re-traces recompile known HLO — the "eviction costs
       a pcache hit, never a cold compile" contract).
    2. **Evict→promote parity** — one tenant force-evicted and
       re-scored: output must be bitwise-identical.
    3. **Hot-model storm** — fairness ON vs OFF: the tail tenant's
       interactive SLO must hold under fairness and provably miss
       without it."""
    import numpy as np

    saved = {k: os.environ.get(k) for k in
             ("H2O_TPU_SCORER_CACHE_BYTES", "H2O_TPU_SCORE_FAIRNESS",
              "H2O_TPU_SCORE_QUEUE_MAX", "H2O_TPU_SCORE_BATCH_US")}
    os.environ["H2O_TPU_SCORER_CACHE_BYTES"] = \
        str(int(budget_mb * 2 ** 20))
    srv = None
    try:
        srv, url, keys, columns = _self_server_tenants(
            n_models, seed=seed)
        scrape_before = _metrics_scrape(url)
        sweep = run_load_zipf(
            url, keys, columns, concurrency=concurrency,
            rows_per_request=rows_per_request, seconds=seconds,
            zipf_s=zipf_s, seed=seed)
        # /metrics AFTER the sweep: the exposition now carries the
        # full tenant series set — this is the scrape cost a live
        # fleet pays per Prometheus interval
        scrape_after = _metrics_scrape(url)

        # 2. evict→promote bitwise parity on a live tenant
        from h2o_kubernetes_tpu import rest
        from h2o_kubernetes_tpu.models.base import evict_scorer_cache

        probe = rest.MODELS[keys[-1]]
        rng = np.random.default_rng(seed + 7)
        Xp = rng.normal(size=(64, len(columns))).astype(np.float32)
        before = probe.score_numpy(Xp)
        evict_scorer_cache(probe)
        after = probe.score_numpy(Xp)
        bitwise = bool(np.array_equal(before, after))

        storm_fair = _storm_leg(url, keys[0], keys[-1], columns,
                                fair=True, seconds=storm_seconds,
                                seed=seed)
        storm_unfair = _storm_leg(url, keys[0], keys[-1], columns,
                                  fair=False, seconds=storm_seconds,
                                  seed=seed)
        final = _get_json(url + "/3/Stats") or {}
        return {
            "metric": "multitenant_zipf_p99",
            "models": n_models,
            "zipf_s": zipf_s,
            "budget_mb": budget_mb,
            "sweep": {k: sweep[k] for k in
                      ("value", "requests", "p50_ms", "p99_ms",
                       "fivexx", "shed", "deciles", "residency")},
            "evict_promote_bitwise": bitwise,
            "storm_fair": storm_fair,
            "storm_unfair": storm_unfair,
            "scorer_cache_final": final.get("scorer_cache"),
            "compiles_final": final.get("compiles"),
            "metrics_scrape": {"before": scrape_before,
                               "after": scrape_after},
        }
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        if srv is not None:
            srv.shutdown()


def run_router_bench(tenants: int = 120, shards: int = 3,
                     head: int = 8, budget_bytes: int = 2_000_000,
                     seconds: float = 15.0, zipf_s: float = 1.1,
                     concurrency: int = 6, rows_per_request: int = 16,
                     seed: int = 0) -> dict:
    """The BENCH_SUITE ``router_zipf_p99`` leg: the SAME Zipf tenant
    storm driven two ways at EQUAL total cache budget —

    1. **router + sharded catalog**: ``shards`` shard groups of one
       replica each, the catalog rendezvous-placed (head replicated,
       tail on one shard), traffic through the device-free front-door
       router;
    2. **direct everyone-has-everything pool** (the PR-7 baseline):
       the same replica count, every replica holding the FULL catalog
       under the same per-replica byte budget, traffic round-robined
       straight at the replicas.

    Records aggregate rows/s, head-decile and tail-decile p99 for
    both; the acceptance bar is router head p99 within 1.3x of the
    direct baseline (the router hop + health indirection must be
    cheap), with the sharded fleet's per-replica catalog share —
    not the router — absorbing the cache churn the baseline pays."""
    from tools.chaos import _ShardedFixture

    def leg(shard_count: int, use_router: bool, tag: str) -> dict:
        fx = _ShardedFixture(tag, tenants=tenants, shards=shard_count,
                             head=head if shard_count > 1 else 1,
                             replicas_per_shard=1 if shard_count > 1
                             else shards,
                             budget_bytes=budget_bytes,
                             with_router=use_router)
        try:
            targets = [fx.router_url] if use_router else \
                fx.pool.endpoints
            scrape_target = fx.router_url if use_router else \
                (fx.pool.endpoints()[0] if callable(fx.pool.endpoints)
                 else fx.pool.endpoints[0])
            scrape_before = _metrics_scrape(scrape_target)
            out = run_load_zipf(
                targets, fx.tenant_keys, fx.feature_cols,
                concurrency=concurrency,
                rows_per_request=rows_per_request, seconds=seconds,
                zipf_s=zipf_s, seed=seed, router=use_router)
            scrape_after = _metrics_scrape(scrape_target)
            deciles = out.get("deciles") or []
            return {
                "metrics_scrape": {"before": scrape_before,
                                   "after": scrape_after},
                "rows_per_s": out["value"],
                "requests": out["requests"],
                "p50_ms": out["p50_ms"],
                "p99_ms": out["p99_ms"],
                "fivexx": out["fivexx"],
                "errors": out["errors"],
                "degraded": out.get("degraded", 0),
                "head_p99_ms": deciles[0]["p99_ms"] if deciles
                else None,
                "tail_p99_ms": deciles[-1]["p99_ms"] if deciles
                else None,
                "router_stats": fx.router.snapshot()["stats"]
                if use_router else None,
            }
        finally:
            fx.close()

    routed = leg(shards, True, "rtbench")
    direct = leg(1, False, "rtbase")
    ratio = None
    if routed["head_p99_ms"] and direct["head_p99_ms"]:
        ratio = round(routed["head_p99_ms"] / direct["head_p99_ms"], 3)
    return {
        "metric": "router_zipf_p99",
        "tenants": tenants, "shards": shards, "head": head,
        "budget_bytes": budget_bytes, "zipf_s": zipf_s,
        "seconds": seconds,
        "router": routed,
        "direct": direct,
        "head_p99_ratio": ratio,
        "head_p99_within_1_3x": bool(ratio is not None
                                     and ratio <= 1.3),
    }


def main(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--url", default=None,
                    help="server base URL, or a comma list of pool "
                    "replica URLs (round-robin multi-target mode); "
                    "omit to self-host")
    ap.add_argument("--model", default=None, help="model key to score")
    ap.add_argument("--columns", default=None,
                    help="comma list of feature columns (remote mode)")
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--rows", type=int, default=32,
                    help="rows per request")
    ap.add_argument("--seconds", type=float, default=10.0)
    ap.add_argument("--models", type=int, default=0,
                    help="multi-tenant mode: drive N models under "
                    "Zipf popularity (self-host: N tiny registry-"
                    "pushed tenants m000..; with --url, keys "
                    "'{--model}{i:03d}' must already be loaded)")
    ap.add_argument("--zipf-s", type=float, default=1.1,
                    help="Zipf exponent for --models popularity "
                    "(rank 1 hottest; higher = hotter head)")
    ap.add_argument("--router", action="store_true",
                    help="with --models + --url: the target is a "
                    "sharded-fleet front-door router — typed 503s "
                    "with the placement_pending hint count as "
                    "'degraded' (expected while a dead shard's "
                    "tenants re-place), not as 5xx")
    ap.add_argument("--assert-zero-5xx", action="store_true",
                    help="fail (rc 1) if ANY response was a 5xx — the "
                    "rolling-update drill's acceptance bar")
    ap.add_argument("--contributions", action="store_true",
                    help="drive the explainable-serving route "
                    "(POST .../contributions, per-row TreeSHAP) "
                    "instead of predictions — single-target mode")
    args = ap.parse_args(argv)
    if args.contributions and (args.models > 0 or
                               (args.url and "," in args.url)):
        print("--contributions is a single-target mode (no --models / "
              "multi-URL)", file=sys.stderr)
        return 2

    srv = None
    multi = args.url is not None and "," in args.url
    if args.models > 0:
        # multi-tenant Zipf traffic mode
        if args.url is None:
            srv, url, keys, columns = _self_server_tenants(
                args.models, warm_buckets=(max(args.rows, 1),))
            targets = [url]
        else:
            if not args.model or not args.columns:
                print("--url + --models needs --model (key prefix) "
                      "and --columns", file=sys.stderr)
                return 2
            targets = [u.strip().rstrip("/")
                       for u in args.url.split(",") if u.strip()]
            keys = [f"{args.model}{i:03d}" for i in range(args.models)]
            columns = args.columns.split(",")
        try:
            out = run_load_zipf(targets, keys, columns,
                                concurrency=args.concurrency,
                                rows_per_request=args.rows,
                                seconds=args.seconds,
                                zipf_s=args.zipf_s,
                                router=args.router)
            print(json.dumps(out))
            if args.assert_zero_5xx and out.get("fivexx", 0) > 0:
                print(f"FAIL: {out['fivexx']} 5xx responses "
                      f"(sample: {out.get('fivexx_sample')})",
                      file=sys.stderr)
                return 1
            return 0 if out["errors"] == 0 and out["requests"] > 0 \
                and out.get("fivexx", 0) == 0 else 1
        finally:
            if srv is not None:
                srv.shutdown()
    if args.url is None:
        srv, url, model_key, columns = _self_server()
    else:
        url = args.url.rstrip(",")
        if not args.model or not args.columns:
            print("--url mode needs --model and --columns",
                  file=sys.stderr)
            return 2
        model_key, columns = args.model, args.columns.split(",")
    try:
        if multi:
            targets = [u.strip().rstrip("/")
                       for u in url.split(",") if u.strip()]
            out = run_load_multi(targets, model_key, columns,
                                 concurrency=args.concurrency,
                                 rows_per_request=args.rows,
                                 seconds=args.seconds)
        else:
            out = run_load(url.rstrip("/"), model_key, columns,
                           concurrency=args.concurrency,
                           rows_per_request=args.rows,
                           seconds=args.seconds,
                           contributions=args.contributions)
        if srv is not None:
            from h2o_kubernetes_tpu import rest

            out["batcher"] = dict(rest.BATCHER.stats)
        print(json.dumps(out))
        if args.assert_zero_5xx and out.get("fivexx", 0) > 0:
            print(f"FAIL: {out['fivexx']} 5xx responses "
                  f"(sample: {out.get('fivexx_sample')})",
                  file=sys.stderr)
            return 1
        return 0 if out["errors"] == 0 and out["requests"] > 0 \
            and out.get("fivexx", 0) == 0 else 1
    finally:
        if srv is not None:
            srv.shutdown()


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
