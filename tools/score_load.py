#!/usr/bin/env python
"""Closed-loop REST scoring load generator (docs/SERVING.md).

Hammers POST /3/Predictions/models/{key} (the inline serving route:
JSON rows in, predictions out, micro-batched server-side) with N
concurrent closed-loop workers — each worker keeps exactly one request
in flight, so offered load tracks service capacity, the way a fleet of
synchronous clients behaves.  Reports rows/s + latency percentiles as
ONE JSON line, plus the server's micro-batcher stats when the server
runs in-process.

Usage::

    python tools/score_load.py                      # self-contained:
        # starts an in-process REST server with a synthetic GBM
    python tools/score_load.py --url http://host:54321 --model gbm1
    python tools/score_load.py --concurrency 16 --rows 32 --seconds 10

The gain this measures is recorded by ``bench_suite``'s
``gbm_score_rows_per_sec`` config; this tool is the REST-level
closed-loop view of the same fast path (request coalescing included).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _post_json(url: str, payload: dict, timeout: float = 120.0) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _self_server(port: int = 0):
    """Start an in-process server + synthetic GBM; returns
    (server, base_url, model_key, feature_columns, row_maker)."""
    import socket

    import numpy as np

    import h2o_kubernetes_tpu as h2o
    from h2o_kubernetes_tpu import rest
    from h2o_kubernetes_tpu.models import GBM
    from h2o_kubernetes_tpu.runtime import make_mesh, set_global_mesh

    set_global_mesh(make_mesh())
    rng = np.random.default_rng(0)
    n = 20_000
    cols = {f"x{i}": rng.normal(size=n).astype(np.float32)
            for i in range(8)}
    cols["c1"] = np.array(["a", "b", "c", "d"])[rng.integers(0, 4, n)]
    cols["y"] = np.where(cols["x0"] - cols["x1"] > 0, "late", "ontime")
    fr = h2o.Frame.from_arrays(cols)
    model = GBM(ntrees=20, max_depth=5, learn_rate=0.2, seed=1).train(
        y="y", training_frame=fr)
    rest.MODELS["score_load_gbm"] = model
    if port == 0:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
    srv = rest.start_server(port)
    return (srv, f"http://127.0.0.1:{port}", "score_load_gbm",
            [f"x{i}" for i in range(8)] + ["c1"])


def run_load(url: str, model_key: str, columns: list[str],
             concurrency: int = 8, rows_per_request: int = 32,
             seconds: float = 10.0, seed: int = 0) -> dict:
    """Closed-loop drive; returns the result record (also printable)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    route = f"{url}/3/Predictions/models/{model_key}"
    # pre-generate a pool of request bodies (list-shaped rows) so the
    # workers spend their loop on HTTP + scoring, not on JSON building
    bodies = []
    for _ in range(16):
        rows = [[(float(rng.normal()) if c != "c1" else
                  ["a", "b", "c", "d"][int(rng.integers(0, 4))])
                 for c in columns] for _ in range(rows_per_request)]
        bodies.append({"rows": rows, "columns": columns})
    deadline = time.perf_counter() + seconds
    lock = threading.Lock()
    latencies: list[float] = []
    errors: list[str] = []

    def worker(wid: int) -> None:
        i = wid
        while time.perf_counter() < deadline:
            body = bodies[i % len(bodies)]
            i += 1
            t0 = time.perf_counter()
            try:
                out = _post_json(route, body)
                ok = len(out["predict"]) == rows_per_request
            except Exception as e:  # noqa: BLE001 — record, keep going
                with lock:
                    errors.append(repr(e)[:200])
                continue
            dt = time.perf_counter() - t0
            with lock:
                if ok:
                    latencies.append(dt)
                else:
                    errors.append("short response")

    # one warm-up request so the timed window measures steady state,
    # not the first XLA compile
    _post_json(route, bodies[0])
    t_start = time.perf_counter()
    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    lat = sorted(latencies)

    def pct(p):
        return round(lat[min(len(lat) - 1, int(p * len(lat)))] * 1e3, 2) \
            if lat else None

    return {
        "metric": "rest_score_rows_per_sec",
        "value": round(len(lat) * rows_per_request / wall, 1),
        "unit": "rows/s",
        "requests": len(lat),
        "requests_per_s": round(len(lat) / wall, 1),
        "errors": len(errors),
        "error_sample": errors[:3],
        "p50_ms": pct(0.50),
        "p95_ms": pct(0.95),
        "p99_ms": pct(0.99),
        "concurrency": concurrency,
        "rows_per_request": rows_per_request,
        "seconds": round(wall, 2),
    }


def main(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--url", default=None,
                    help="server base URL; omit to self-host")
    ap.add_argument("--model", default=None, help="model key to score")
    ap.add_argument("--columns", default=None,
                    help="comma list of feature columns (remote mode)")
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--rows", type=int, default=32,
                    help="rows per request")
    ap.add_argument("--seconds", type=float, default=10.0)
    args = ap.parse_args(argv)

    srv = None
    if args.url is None:
        srv, url, model_key, columns = _self_server()
    else:
        url = args.url.rstrip("/")
        if not args.model or not args.columns:
            print("--url mode needs --model and --columns",
                  file=sys.stderr)
            return 2
        model_key, columns = args.model, args.columns.split(",")
    try:
        out = run_load(url, model_key, columns,
                       concurrency=args.concurrency,
                       rows_per_request=args.rows,
                       seconds=args.seconds)
        if srv is not None:
            from h2o_kubernetes_tpu import rest

            out["batcher"] = dict(rest.BATCHER.stats)
        print(json.dumps(out))
        return 0 if out["errors"] == 0 and out["requests"] > 0 else 1
    finally:
        if srv is not None:
            srv.shutdown()


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
