#!/usr/bin/env python
"""Closed-loop REST scoring load generator (docs/SERVING.md).

Hammers POST /3/Predictions/models/{key} (the inline serving route:
JSON rows in, predictions out, micro-batched server-side) with N
concurrent closed-loop workers — each worker keeps exactly one request
in flight, so offered load tracks service capacity, the way a fleet of
synchronous clients behaves.  Reports rows/s + latency percentiles as
ONE JSON line, plus the server's micro-batcher stats when the server
runs in-process.

Usage::

    python tools/score_load.py                      # self-contained:
        # starts an in-process REST server with a synthetic GBM
    python tools/score_load.py --url http://host:54321 --model gbm1
    python tools/score_load.py --concurrency 16 --rows 32 --seconds 10
    python tools/score_load.py \
        --url http://h1:54321,http://h2:54321 --model pool \
        --columns x0,...  --assert-zero-5xx      # drive a scorer POOL

Multi-target mode (a comma list of ``--url`` targets, or a dynamic
target provider via :func:`run_load_multi`) is the Service analog the
operator drills ride: a background poller tracks each target's
``/readyz`` and workers round-robin over the READY set only — a
replica mid-warm-up or cordoned for a rolling update receives nothing,
like a pod pulled from a Service's endpoints. ``--assert-zero-5xx``
makes the run fail loudly (rc 1) on ANY 5xx response — the
rolling-update acceptance bar (docs/OPERATOR.md).

The gain this measures is recorded by ``bench_suite``'s
``gbm_score_rows_per_sec`` config; this tool is the REST-level
closed-loop view of the same fast path (request coalescing included).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _post_json(url: str, payload: dict, timeout: float = 120.0) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _self_server(port: int = 0):
    """Start an in-process server + synthetic GBM; returns
    (server, base_url, model_key, feature_columns, row_maker)."""
    import socket

    import numpy as np

    import h2o_kubernetes_tpu as h2o
    from h2o_kubernetes_tpu import rest
    from h2o_kubernetes_tpu.models import GBM
    from h2o_kubernetes_tpu.runtime import make_mesh, set_global_mesh

    set_global_mesh(make_mesh())
    rng = np.random.default_rng(0)
    n = 20_000
    cols = {f"x{i}": rng.normal(size=n).astype(np.float32)
            for i in range(8)}
    cols["c1"] = np.array(["a", "b", "c", "d"])[rng.integers(0, 4, n)]
    cols["y"] = np.where(cols["x0"] - cols["x1"] > 0, "late", "ontime")
    fr = h2o.Frame.from_arrays(cols)
    model = GBM(ntrees=20, max_depth=5, learn_rate=0.2, seed=1).train(
        y="y", training_frame=fr)
    rest.MODELS["score_load_gbm"] = model
    if port == 0:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
    srv = rest.start_server(port)
    return (srv, f"http://127.0.0.1:{port}", "score_load_gbm",
            [f"x{i}" for i in range(8)] + ["c1"])


def _result_record(latencies: list[float], wall: float,
                   rows_per_request: int, concurrency: int,
                   fivexx: list[str], errors: list[str],
                   **extra) -> dict:
    """The one result-record shape shared by both load modes — a new
    field lands in single-target AND multi-target output or neither."""
    lat = sorted(latencies)

    def pct(p):
        return round(lat[min(len(lat) - 1, int(p * len(lat)))] * 1e3, 2) \
            if lat else None

    return {
        "metric": "rest_score_rows_per_sec",
        "value": round(len(lat) * rows_per_request / max(wall, 1e-9), 1),
        "unit": "rows/s",
        "requests": len(lat),
        "requests_per_s": round(len(lat) / max(wall, 1e-9), 1),
        "fivexx": len(fivexx),
        "fivexx_sample": fivexx[:5],
        "errors": len(errors),
        "error_sample": errors[:3],
        "p50_ms": pct(0.50),
        "p95_ms": pct(0.95),
        "p99_ms": pct(0.99),
        "concurrency": concurrency,
        "rows_per_request": rows_per_request,
        "seconds": round(wall, 2),
        **extra,
    }


def run_load(url: str, model_key: str, columns: list[str],
             concurrency: int = 8, rows_per_request: int = 32,
             seconds: float = 10.0, seed: int = 0) -> dict:
    """Closed-loop drive; returns the result record (also printable)."""
    route = f"{url}/3/Predictions/models/{model_key}"
    bodies = _make_bodies(columns, rows_per_request, seed)
    deadline = time.perf_counter() + seconds
    lock = threading.Lock()
    latencies: list[float] = []
    errors: list[str] = []
    fivexx: list[str] = []

    def worker(wid: int) -> None:
        import urllib.error

        i = wid
        while time.perf_counter() < deadline:
            body = bodies[i % len(bodies)]
            i += 1
            t0 = time.perf_counter()
            try:
                out = _post_json(route, body)
                ok = len(out["predict"]) == rows_per_request
            except urllib.error.HTTPError as e:
                # 5xx tracked apart from transport noise so
                # --assert-zero-5xx has a precise needle
                label = f"HTTP {e.code} {e.read()[:120]!r}"
                with lock:
                    (fivexx if e.code >= 500 else errors).append(label)
                continue
            except Exception as e:  # noqa: BLE001 — record, keep going
                with lock:
                    errors.append(repr(e)[:200])
                continue
            dt = time.perf_counter() - t0
            with lock:
                if ok:
                    latencies.append(dt)
                else:
                    errors.append("short response")

    # one warm-up request so the timed window measures steady state,
    # not the first XLA compile
    _post_json(route, bodies[0])
    t_start = time.perf_counter()
    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    return _result_record(latencies, wall, rows_per_request,
                          concurrency, fivexx, errors)


def _make_bodies(columns: list[str], rows_per_request: int, seed: int,
                 pool: int = 16) -> list[dict]:
    """Pre-generated list-shaped request bodies (shared by both load
    modes so workers spend their loop on HTTP, not JSON building)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    bodies = []
    for _ in range(pool):
        rows = [[(float(rng.normal()) if c != "c1" else
                  ["a", "b", "c", "d"][int(rng.integers(0, 4))])
                 for c in columns] for _ in range(rows_per_request)]
        bodies.append({"rows": rows, "columns": columns})
    return bodies


def run_load_multi(targets, model_key: str, columns: list[str],
                   concurrency: int = 4, rows_per_request: int = 8,
                   seconds: float | None = None, stop_event=None,
                   seed: int = 0, ready_poll_s: float = 0.05,
                   request_timeout: float = 30.0) -> dict:
    """Round-robin closed-loop drive over a DYNAMIC set of pool
    replicas — the k8s-Service analog for operator drills.

    ``targets`` is a list of base URLs or a zero-arg callable returning
    one (the reconciler's live endpoint list: replicas join as they
    are provisioned, leave the instant they are cordoned). A poller
    thread refreshes each target's ``/readyz`` every ``ready_poll_s``
    and workers pick targets FROM the ready set under its lock, so the
    generator never *chooses* an unready target by construction. (The
    pick→arrival in-flight race is the router race the operator's
    deregister grace exists for; the measured check of that contract
    is the SERVER-side ``scored_while_unready`` counter on /3/Stats,
    which the drills assert — not a client-side literal.) ``fivexx``
    counts real 5xx contract violations.

    Runs until ``stop_event`` is set (or ``seconds`` elapses). Returns
    the single-target record plus ``fivexx``/``fourxx``/``by_target``/
    ``no_ready_target_waits``."""
    import urllib.error

    get_targets = targets if callable(targets) else (lambda: targets)
    stop = stop_event or threading.Event()
    deadline = (time.perf_counter() + seconds) if seconds else None
    bodies = _make_bodies(columns, rows_per_request, seed)
    lock = threading.Lock()
    ready: set[str] = set()
    latencies: list[float] = []
    fivexx: list[str] = []
    fourxx: list[str] = []
    errors: list[str] = []
    by_target: dict[str, dict] = {}
    no_ready_waits = [0]

    def _done() -> bool:
        return stop.is_set() or \
            (deadline is not None and time.perf_counter() >= deadline)

    def poller():
        while not _done():
            now_ready = set()
            for t in list(get_targets()):
                try:
                    with urllib.request.urlopen(
                            t.rstrip("/") + "/readyz", timeout=2.0) as r:
                        if r.status == 200:
                            now_ready.add(t.rstrip("/"))
                except Exception:  # noqa: BLE001 — down/503 = unready
                    pass
            with lock:
                ready.clear()
                ready.update(now_ready)
            time.sleep(ready_poll_s)

    rr = [0]

    def worker(wid: int) -> None:
        i = wid
        while not _done():
            with lock:
                pool = sorted(ready)
                if pool:
                    target = pool[rr[0] % len(pool)]
                    rr[0] += 1
                else:
                    no_ready_waits[0] += 1   # under lock: workers race
            if not pool:
                time.sleep(0.02)
                continue
            body = bodies[i % len(bodies)]
            i += 1
            route = f"{target}/3/Predictions/models/{model_key}"
            t0 = time.perf_counter()
            try:
                out = _post_json(route, body, timeout=request_timeout)
                ok = len(out["predict"]) == rows_per_request
                dt = time.perf_counter() - t0
                with lock:
                    rec = by_target.setdefault(
                        target, {"requests": 0, "fivexx": 0})
                    rec["requests"] += 1
                    if ok:
                        latencies.append(dt)
                    else:
                        errors.append(f"{target}: short response")
            except urllib.error.HTTPError as e:
                label = f"{target}: HTTP {e.code} {e.read()[:120]!r}"
                with lock:
                    rec = by_target.setdefault(
                        target, {"requests": 0, "fivexx": 0})
                    rec["requests"] += 1
                    if e.code >= 500:
                        rec["fivexx"] += 1
                        fivexx.append(label)
                    else:
                        fourxx.append(label)
            except Exception as e:  # noqa: BLE001 — record, keep going
                with lock:
                    errors.append(f"{target}: {e!r}"[:200])

    t_start = time.perf_counter()
    pt = threading.Thread(target=poller, daemon=True,
                          name="score-load-ready-poller")
    pt.start()
    workers = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(concurrency)]
    for t in workers:
        t.start()
    for t in workers:
        t.join()
    pt.join(timeout=5.0)
    wall = time.perf_counter() - t_start
    return _result_record(latencies, wall, rows_per_request,
                          concurrency, fivexx, errors,
                          fourxx=len(fourxx),
                          no_ready_target_waits=no_ready_waits[0],
                          by_target=by_target)


def main(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--url", default=None,
                    help="server base URL, or a comma list of pool "
                    "replica URLs (round-robin multi-target mode); "
                    "omit to self-host")
    ap.add_argument("--model", default=None, help="model key to score")
    ap.add_argument("--columns", default=None,
                    help="comma list of feature columns (remote mode)")
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--rows", type=int, default=32,
                    help="rows per request")
    ap.add_argument("--seconds", type=float, default=10.0)
    ap.add_argument("--assert-zero-5xx", action="store_true",
                    help="fail (rc 1) if ANY response was a 5xx — the "
                    "rolling-update drill's acceptance bar")
    args = ap.parse_args(argv)

    srv = None
    multi = args.url is not None and "," in args.url
    if args.url is None:
        srv, url, model_key, columns = _self_server()
    else:
        url = args.url.rstrip(",")
        if not args.model or not args.columns:
            print("--url mode needs --model and --columns",
                  file=sys.stderr)
            return 2
        model_key, columns = args.model, args.columns.split(",")
    try:
        if multi:
            targets = [u.strip().rstrip("/")
                       for u in url.split(",") if u.strip()]
            out = run_load_multi(targets, model_key, columns,
                                 concurrency=args.concurrency,
                                 rows_per_request=args.rows,
                                 seconds=args.seconds)
        else:
            out = run_load(url.rstrip("/"), model_key, columns,
                           concurrency=args.concurrency,
                           rows_per_request=args.rows,
                           seconds=args.seconds)
        if srv is not None:
            from h2o_kubernetes_tpu import rest

            out["batcher"] = dict(rest.BATCHER.stats)
        print(json.dumps(out))
        if args.assert_zero_5xx and out.get("fivexx", 0) > 0:
            print(f"FAIL: {out['fivexx']} 5xx responses "
                  f"(sample: {out.get('fivexx_sample')})",
                  file=sys.stderr)
            return 1
        return 0 if out["errors"] == 0 and out["requests"] > 0 \
            and out.get("fivexx", 0) == 0 else 1
    finally:
        if srv is not None:
            srv.shutdown()


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
