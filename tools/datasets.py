"""Reference-shaped synthetic dataset generators.

BASELINE.json's eval configs name real datasets this sandbox cannot
download (zero egress): airlines (10M x ~30 mixed numeric/categorical
with NAs), HIGGS (11M x 28 numeric), MSLR-WEB30K (qid-grouped graded
relevance).  These generators reproduce the SHAPES — column counts,
type mix, cardinalities, NA rates, group-size distributions — so
bench/AutoML wall-clocks are measured against honest workloads even
though the bytes are synthetic.  (Reference parity: the h2o-3 perf
suites train on exactly these tables; SURVEY.md §6.)

Categorical columns are emitted as integer codes + an explicit domain
(``Frame.from_arrays(cols, domains=...)``) so a 10M-row build never
factorizes 10M python strings; NA injection uses np.nan in the code
array (Vec maps nan -> NA_ENUM for enum columns).

Import cost is numpy only; h2o_kubernetes_tpu is imported inside the
frame-building helpers.
"""

from __future__ import annotations

import numpy as np

_CARRIERS = ["AA", "AS", "B6", "CO", "DL", "EV", "F9", "FL", "HA",
             "MQ", "NK", "NW", "OO", "UA", "US", "VX", "WN", "XE",
             "YV", "9E", "OH", "TZ"]


def zipf_probs(n: int, s: float = 1.0) -> np.ndarray:
    """Normalized Zipf(s) probabilities over ranks 1..n (rank 1
    hottest). The ONE popularity shape this repo uses — airport hubs
    (airlines_arrays), word frequencies (text8_like_tokens), and
    model-popularity traffic shaping (tools/score_load.py's
    multi-tenant mode all draw from it)."""
    if n < 1:
        raise ValueError(f"zipf_probs needs n >= 1, got {n}")
    p = 1.0 / (np.arange(1, n + 1, dtype=np.float64) ** float(s))
    return p / p.sum()


def airlines_arrays(rows: int, seed: int = 0, na_frac: float = 0.02):
    """Airlines-10M shape: ~30 mixed columns, NAs, binary target.

    Column plan mirrors the classic airlines table: schedule fields
    (year/month/day/times), carrier + origin/dest (high-cardinality
    enums), distances/elapsed/delay numerics with exponential tails,
    and the IsDepDelayed binary response driven by a nonlinear mix of
    carrier, hour, distance and weather-ish noise.

    Returns (cols, domains) ready for ``Frame.from_arrays``.
    """
    rng = np.random.default_rng(seed)
    f32 = np.float32

    def with_na(a: np.ndarray, frac: float = na_frac) -> np.ndarray:
        a = a.astype(f32)
        if frac > 0:
            mask = rng.random(size=len(a)) < frac
            a[mask] = np.nan
        return a

    n_airports = 300
    airports = [f"APT{i:03d}" for i in range(n_airports)]
    cols: dict[str, np.ndarray] = {}
    domains: dict[str, list[str]] = {}

    cols["Year"] = (1987 + rng.integers(0, 22, size=rows)).astype(f32)
    cols["Month"] = rng.integers(1, 13, size=rows).astype(f32)
    cols["DayofMonth"] = rng.integers(1, 29, size=rows).astype(f32)
    cols["DayOfWeek"] = rng.integers(1, 8, size=rows).astype(f32)
    crs_dep = rng.integers(0, 2400, size=rows).astype(f32)
    dep_hour = crs_dep // 100
    cols["CRSDepTime"] = crs_dep
    cols["DepTime"] = with_na(crs_dep + rng.exponential(12.0, size=rows))
    elapsed = (30 + rng.gamma(2.0, 60.0, size=rows)).astype(f32)
    cols["CRSArrTime"] = ((crs_dep + elapsed) % 2400).astype(f32)
    cols["ArrTime"] = with_na(cols["CRSArrTime"]
                              + rng.normal(0, 20, size=rows))
    carrier_idx = rng.integers(0, len(_CARRIERS), size=rows)
    cols["UniqueCarrier"] = with_na(carrier_idx, na_frac / 4)
    domains["UniqueCarrier"] = list(_CARRIERS)
    cols["FlightNum"] = rng.integers(1, 8000, size=rows).astype(f32)
    cols["ActualElapsedTime"] = with_na(
        elapsed + rng.normal(0, 10, size=rows))
    cols["CRSElapsedTime"] = elapsed
    cols["AirTime"] = with_na(elapsed * 0.8
                              + rng.normal(0, 5, size=rows))
    # Zipf-ish airport popularity (hubs dominate, like the real table)
    pop = zipf_probs(n_airports, s=0.8)
    origin_idx = rng.choice(n_airports, size=rows, p=pop)
    dest_idx = rng.choice(n_airports, size=rows, p=pop)
    cols["Origin"] = origin_idx.astype(f32)
    domains["Origin"] = airports
    cols["Dest"] = dest_idx.astype(f32)
    domains["Dest"] = airports
    dist = (100 + rng.gamma(2.0, 300.0, size=rows)).astype(f32)
    cols["Distance"] = with_na(dist, na_frac / 2)
    cols["TaxiIn"] = with_na(rng.exponential(6.0, size=rows))
    cols["TaxiOut"] = with_na(rng.exponential(14.0, size=rows))
    cols["Cancelled"] = (rng.random(size=rows) < 0.015).astype(f32)
    cols["CancellationCode"] = np.where(
        cols["Cancelled"] > 0,
        rng.integers(0, 4, size=rows).astype(f32), np.nan)
    domains["CancellationCode"] = ["A", "B", "C", "D"]
    cols["Diverted"] = (rng.random(size=rows) < 0.002).astype(f32)
    for name, scale in (("CarrierDelay", 8.0), ("WeatherDelay", 3.0),
                        ("NASDelay", 6.0), ("SecurityDelay", 0.5),
                        ("LateAircraftDelay", 7.0)):
        cols[name] = with_na(rng.exponential(scale, size=rows),
                             na_frac * 4)
    # response: nonlinear mix — evening departures, long taxi-out,
    # a few chronically-late carriers, winter months
    late_carrier = np.isin(carrier_idx, [3, 9, 12, 17]).astype(f32)
    logit = (0.12 * (dep_hour - 12)
             + 0.03 * np.nan_to_num(cols["TaxiOut"])
             + 0.9 * late_carrier
             + 0.4 * np.isin(cols["Month"], [12, 1, 6, 7]).astype(f32)
             - 0.0004 * dist
             + rng.normal(scale=1.2, size=rows).astype(f32) - 0.3)
    cols["IsDepDelayed"] = (logit > 0).astype(f32)
    domains["IsDepDelayed"] = ["NO", "YES"]
    return cols, domains


_GEN_CHUNK = 2_000_000


def _chunked_arrays(gen, rows: int, chunk: int, **kw):
    """Generate `rows` via per-chunk calls to `gen(n, seed=...)` and
    concatenate per column — bounds the generator's transient working
    set at 10M+ rows (each chunk draws under seed+k, matching
    airlines_csv's chunking scheme). Below one chunk this is byte-
    identical to a direct call."""
    seed = kw.pop("seed", 0)
    if rows <= chunk:
        return gen(rows, seed=seed, **kw)
    parts = []
    done, ck = 0, 0
    while done < rows:
        n = min(chunk, rows - done)
        parts.append(gen(n, seed=seed + ck, **kw))
        done += n
        ck += 1
    cols = {name: np.concatenate([p[0][name] for p in parts])
            for name in parts[0][0]}
    return cols, parts[0][1]


def airlines_frame(rows: int, seed: int = 0, na_frac: float = 0.02,
                   chunk: int = _GEN_CHUNK):
    import h2o_kubernetes_tpu as h2o

    cols, domains = _chunked_arrays(airlines_arrays, rows, chunk,
                                    seed=seed, na_frac=na_frac)
    return h2o.Frame.from_arrays(cols, domains=domains)


def higgs_arrays(rows: int, seed: int = 0):
    """HIGGS shape: 28 numeric features (21 low-level kinematics + 7
    derived masses), binary response from nonlinear combinations."""
    rng = np.random.default_rng(seed)
    F = 28
    X = rng.normal(size=(rows, F)).astype(np.float32)
    logit = (0.8 * X[:, 0] - 0.6 * X[:, 1] * X[:, 2]
             + 0.5 * np.abs(X[:, 3]) - 0.4 * (X[:, 4] ** 2)
             + rng.normal(scale=0.7, size=rows))
    cols = {f"f{i}": X[:, i] for i in range(F)}
    cols["y"] = (logit > 0).astype(np.float32)
    return cols, {"y": ["b", "s"]}


def higgs_frame(rows: int, seed: int = 0, chunk: int = _GEN_CHUNK):
    import h2o_kubernetes_tpu as h2o

    cols, domains = _chunked_arrays(higgs_arrays, rows, chunk,
                                    seed=seed)
    return h2o.Frame.from_arrays(cols, domains=domains)


def mslr_arrays(rows: int, seed: int = 0, n_features: int = 136,
                mean_group: int = 120):
    """MSLR-WEB30K shape: 136 numeric features, qid groups averaging
    ~120 docs (geometric spread), graded relevance 0-4 skewed toward 0
    (the real label histogram is ~52/32/13/2/1 %)."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(rows, n_features)).astype(np.float32)
    # group sizes: geometric-ish around the mean, min 8 docs
    sizes = np.maximum(8, rng.geometric(1.0 / mean_group,
                                        size=2 * rows // 8))
    cum = np.cumsum(sizes)
    n_groups = int(np.searchsorted(cum, rows) + 1)
    qid = np.repeat(np.arange(n_groups), sizes[:n_groups])[:rows]
    qid = np.sort(qid)
    # latent score: a handful of informative features + per-query shift
    latent = (X[:, 0] + 0.6 * X[:, 1] - 0.4 * X[:, 2]
              + 0.3 * X[:, 3] * X[:, 4]
              + rng.normal(scale=1.0, size=rows))
    # map to 0-4 with the real skew via fixed quantile cuts
    cuts = np.quantile(latent, [0.52, 0.84, 0.97, 0.995])
    rel = np.searchsorted(cuts, latent).astype(np.float32)
    cols = {f"f{i}": X[:, i] for i in range(n_features)}
    cols["rel"] = rel
    cols["qid"] = qid.astype(np.float32)
    return cols


def mslr_frame(rows: int, seed: int = 0, n_features: int = 136,
               mean_group: int = 120):
    import h2o_kubernetes_tpu as h2o

    return h2o.Frame.from_arrays(
        mslr_arrays(rows, seed, n_features, mean_group))


def wide_sparse_arrays(rows: int, n_groups: int = 40,
                       group_card: int = 25, n_dense: int = 5,
                       na_frac: float = 0.005, seed: int = 0,
                       zipf_s: float = 1.0):
    """Wide sparse CTR-style shape: ``n_groups`` one-hot groups of
    ``group_card`` 0/1 columns each (mutually exclusive WITHIN a group
    — the Exclusive Feature Bundling regime, docs/SCALING.md "Wide
    sparse frames") plus ``n_dense`` dense numerics.  Category
    popularity inside each group is Zipf-skewed via ``zipf_probs`` (the
    one popularity shape this repo uses), like real CTR hash features:
    a few hot categories, a long near-empty tail.  ``na_frac`` of the
    rows of a few one-hot columns carry NAs so bundling has NA routing
    to preserve.  F = n_groups * group_card + n_dense; binary response
    from a sparse linear model over a handful of active categories.

    Returns (cols, domains) ready for ``Frame.from_arrays``.
    """
    rng = np.random.default_rng(seed)
    f32 = np.float32
    cols: dict[str, np.ndarray] = {}
    pop = zipf_probs(group_card, s=zipf_s)
    logit = rng.normal(scale=0.4, size=rows).astype(f32)
    for g in range(n_groups):
        cat = rng.choice(group_card, size=rows, p=pop)
        w_hot = rng.normal(scale=0.8, size=min(4, group_card))
        for k in range(group_card):
            v = (cat == k).astype(f32)
            if na_frac > 0 and (g + k) % 17 == 0:
                v[rng.random(rows) < na_frac] = np.nan
            cols[f"c{g}_{k}"] = v
        for k, wk in enumerate(w_hot):
            logit += f32(wk) * np.nan_to_num(cols[f"c{g}_{k}"])
    for j in range(n_dense):
        d = rng.normal(size=rows).astype(f32)
        cols[f"d{j}"] = d
        logit += 0.3 * d
    cols["y"] = (logit > np.median(logit)).astype(f32)
    return cols, {"y": ["no", "yes"]}


def wide_sparse_frame(rows: int, n_groups: int = 40,
                      group_card: int = 25, n_dense: int = 5,
                      na_frac: float = 0.005, seed: int = 0,
                      zipf_s: float = 1.0):
    import h2o_kubernetes_tpu as h2o

    cols, domains = wide_sparse_arrays(
        rows, n_groups=n_groups, group_card=group_card,
        n_dense=n_dense, na_frac=na_frac, seed=seed, zipf_s=zipf_s)
    return h2o.Frame.from_arrays(cols, domains=domains)


def text8_like_tokens(n_tokens: int, vocab_size: int = 10_000,
                      seed: int = 0, sentence_len: int = 18):
    """Word2Vec corpus shape: Zipf-distributed token stream with
    NA sentence delimiters every ~sentence_len tokens (the h2o-3 W2V
    frame convention)."""
    rng = np.random.default_rng(seed)
    idx = rng.choice(vocab_size, size=n_tokens,
                     p=zipf_probs(vocab_size, s=1.0))
    toks = np.array([f"w{i}" for i in range(vocab_size)],
                    dtype=object)[idx]
    toks[::sentence_len] = None
    return toks


def airlines_csv(path: str, rows: int, seed: int = 0,
                 na_frac: float = 0.02, chunk: int = 1_000_000) -> str:
    """Write the airlines-shaped table as CSV (ingest benchmarking).

    Chunked so a 10M-row file never holds 10M formatted strings in
    memory at once.
    """
    import csv

    first = True
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        done = 0
        ck = 0
        while done < rows:
            n = min(chunk, rows - done)
            cols, domains = airlines_arrays(n, seed=seed + ck,
                                            na_frac=na_frac)
            names = list(cols)
            if first:
                w.writerow(names)
                first = False
            # decode enum codes back to labels for a realistic file
            decoded = {}
            for name in names:
                a = cols[name]
                if name in domains:
                    dom = np.asarray(domains[name] + [""], dtype=object)
                    code = np.where(np.isnan(a), len(domains[name]),
                                    a).astype(np.int64)
                    decoded[name] = dom[code]
                else:
                    s = np.char.mod("%g", a.astype(np.float64))
                    decoded[name] = np.where(np.isnan(a), "", s)
            for i in range(n):
                w.writerow([decoded[name][i] for name in names])
            done += n
            ck += 1
    return path
