#!/usr/bin/env python
"""Append the 10M-row AutoML scale point to the CPU curve.

The 100k/300k/1M CPU curve (AUTOML_SCALE_r05.json) measured the full
default plan; at 10M rows on the 1-core CPU mesh the full plan is
multi-day, so the 10M point uses the harness's fixed-budget framing
(tools/automl_scale.py --max-runtime-secs docstring): ONE plan family
(GBM — the north-star algo), no CV (the leaderboard ranks on training
metrics, the documented nfolds<2 fallback), and the recorded metric is
models + leader quality + wall at 10M. On a real chip
tools/tpu_watch.py runs the full-plan 10M capture instead.

Writes AUTOML_SCALE_r06.json = the r05 curve + the 10M point.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    from h2o_kubernetes_tpu.runtime.backend import ensure_live_backend

    ensure_live_backend()
    from tools.automl_scale import run_shape

    point = run_shape(
        rows=int(os.environ.get("AUTOML_10M_ROWS", 10_000_000)),
        max_models=1, nfolds=0,
        exclude_algos=["glm", "drf", "deeplearning", "xgboost",
                       "stackedensemble"])
    point["note"] = ("fixed-budget 10M point: single GBM family, "
                     "nfolds=0 (training-metric leaderboard fallback) "
                     "— the full plan is multi-day on 1 CPU core")
    prev_path = os.path.join(REPO, "AUTOML_SCALE_r05.json")
    try:
        with open(prev_path) as f:
            prev = json.load(f)
    except OSError:
        prev = {"curve": []}
    out = {"curve": prev.get("curve", []) + [point],
           "recompile_check": prev.get("recompile_check"),
           "note_10m": point["note"]}
    out_path = os.path.join(REPO, "AUTOML_SCALE_r06.json")
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"automl_scale_10m": "done", "file": out_path,
                      "wall_seconds": point["wall_seconds"],
                      "error": bool(point.get("error"))}))
    return 0 if not point.get("error") else 1


if __name__ == "__main__":
    sys.exit(main())
