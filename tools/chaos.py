#!/usr/bin/env python
"""Chaos drill CLI — rehearse failure scenarios on CPU, exit nonzero
if recovery fails.

Runs a short GBM train (and, for the resume scenario, a small AutoML
run) under a named fault scenario from the fault-injection harness
(h2o_kubernetes_tpu/runtime/faults.py) and asserts the system recovers
the way docs/RESILIENCE.md promises. Intended for CI gates and for
operators validating a new image before it meets real traffic.

Usage::

    python tools/chaos.py persist-503
    python tools/chaos.py all            # every scenario, first failure wins

Scenarios:

- ``persist-503``   HTTP 503 burst on the persist path: a model save
  to s3:// must land after retries — and must FAIL when the retry
  layer is disabled (proving the fault exercises the path).
- ``probe-hang``    the heartbeat probe wedges: unhealthy at the
  deadline, no probe-thread pileup, recovery after reset().
- ``device-error``  a device error escapes a GBM training step: the
  cloud locks, retraining without a restart fails fast, restart works.
- ``resume``        device error mid-AutoML with a checkpoint_dir: the
  rerun resumes finished steps instead of retraining them.
- ``score-under-fault``  REST scoring during a probe-hang unhealthy
  episode: requests must fail FAST with 503 (never queue behind the
  micro-batcher indefinitely) and recover after ``health.reset()``.
- ``ingest-truncated-csv``  a CSV stream aborts mid-file: the parse
  must fail cleanly on BOTH the streamed arrow reader and the
  pure-Python parser — never ship a short frame.
- ``breaker-trip``  repeated injected ``score.dispatch`` device errors
  trip the serving circuit breaker: instant 503s with NO device calls
  while open, ``/readyz`` unready, and the half-open probe restores
  SERVING once the faults clear.
- ``drain-under-load``  SIGTERM hits a pod serving concurrent REST
  scoring traffic with a build RUNNING: ``/readyz`` flips unready
  while ``/healthz`` stays live, every in-flight request gets a
  terminal response (result or 503/429 — zero hung clients), and the
  process exits cleanly inside ``H2O_TPU_DRAIN_TIMEOUT`` + 5s.
- ``automl-pipelined-fault``  an injected ``automl.step`` device error
  lands mid-overlap in the PIPELINED AutoML executor
  (runtime/scheduler.py): the job must fail terminally with the
  completed steps' manifest entries already written (the resume
  contract), no scheduler thread may outlive the run, and the
  ``H2O_TPU_AUTOML_PIPELINE=0`` kill switch must drain the same
  scenario clean on the serial path with an identical manifest.
- ``rolling-update``  a 2-replica operator scorer pool rolls its
  registry artifact v1 → v2 under closed-loop multi-target REST load
  (tools/score_load.py run_load_multi): ZERO 5xx responses, zero
  requests routed to a not-ready replica, both replicas end on v2,
  every replica reports ``warm_cache_misses == 0`` and
  ``scored_while_unready == 0`` (the warm-up-gated readiness
  contract), and the cordon → grace → drain event sequence lands in
  operator status.
- ``replica-kill``  SIGKILL one replica of a converged 2-replica pool:
  the reconciler observes the death, provisions a warmed replacement,
  and the pool returns to spec count with aggregate readiness inside
  the drill deadline — replica_died → replica_start → replica_ready
  visible in the operator event log.
- ``operator-restart``  SIGKILL the OPERATOR process mid-rollout
  under closed-loop load, restart it against the durable store: the
  successor adopts the live pods (zero duplicate spawns, zero leaked
  pods), finishes the rollout, zero 5xx end to end.
- ``poison-rollback``  push an artifact whose replica can never come
  up: respawns are backoff-spaced (provably >= the configured
  backoff), the rollout auto-rolls-back to last-good, old replicas
  stay READY throughout, zero 5xx.
- ``router-shard-kill``  a 3-shard tenant-sharded fleet serves a
  1000-tenant Zipf storm through the front-door router; one whole
  shard is SIGKILLed mid-run: zero 5xx for the replicated head
  tenants, the victim's tail tenants degrade to TYPED
  placement_pending 503s while the reconciler re-places each onto a
  surviving shard (targeted pushes) and then serve through the
  survivors, per-replica scorer-cache bytes never exceed the budget,
  every cross-shard retry is token-backed (budget never exceeded),
  and re-enabling the shard reconverges the pool.
- ``router-ha-kill``  the highly-available front door end to end
  (ISSUE 16): two lease-fenced ``operator.run --ha`` replicas + two
  stateless store-backed routers under a live Zipf storm. Sustained
  per-tenant 504 pressure triggers a make-before-break rebalance
  (destination bitwise-identical before the source retires); one
  router AND the lease holder are SIGKILLed together: zero client
  errors (transport failover to the surviving router), standby
  takeover within TTL + heartbeat with epoch+1, pods adopted (same
  pids), a stale-epoch routing publish provably rejected, the move
  retired by the NEW holder; then a whole shard dies and recovers —
  loss-driven overrides re-place its tenants and failback EMPTIES
  them once the home shard is provably healthy. Zero 5xx on head
  tenants, ``retries == granted`` on the surviving router.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time

# chaos drills always run on the virtual-CPU mesh: they rehearse
# failures, they must not depend on (or wedge) a real chip
os.environ["JAX_PLATFORMS"] = "cpu"
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8"
                               ).strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


class ChaosFailure(AssertionError):
    """A scenario's recovery contract was broken."""


def _check(cond: bool, msg: str) -> None:
    if not cond:
        raise ChaosFailure(msg)


def _frame(n=160, seed=7):
    import numpy as np

    import h2o_kubernetes_tpu as h2o

    rng = np.random.default_rng(seed)
    x = rng.normal(size=n).astype(np.float32)
    y = np.where(x + rng.normal(scale=0.4, size=n) > 0, "p", "n")
    return h2o.Frame.from_arrays({"x": x, "y": y})


def _fake_store():
    """In-process object store for s3:// drills; returns (server, url)."""
    from http.server import BaseHTTPRequestHandler, HTTPServer

    class Store(BaseHTTPRequestHandler):
        store: dict[str, bytes] = {}

        def log_message(self, *a):
            pass

        def do_GET(self):
            key = self.path.split("?", 1)[0]
            if key not in self.store:
                self.send_response(404)
                self.end_headers()
                return
            body = self.store[key]
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_PUT(self):
            n = int(self.headers.get("Content-Length", 0))
            self.store[self.path.split("?", 1)[0]] = self.rfile.read(n)
            self.send_response(200)
            self.end_headers()

        do_POST = do_PUT

    srv = HTTPServer(("127.0.0.1", 0), Store)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"http://127.0.0.1:{srv.server_port}", Store


def scenario_persist_503() -> None:
    import h2o_kubernetes_tpu as h2o
    from h2o_kubernetes_tpu.runtime import faults

    srv, url, store = _fake_store()
    saved = {k: os.environ.get(k) for k in
             ("AWS_ENDPOINT_URL", "AWS_ACCESS_KEY_ID",
              "AWS_SECRET_ACCESS_KEY", "H2O_TPU_RETRY_BASE")}
    os.environ["AWS_ENDPOINT_URL"] = url
    os.environ.pop("AWS_ACCESS_KEY_ID", None)
    os.environ.pop("AWS_SECRET_ACCESS_KEY", None)
    os.environ["H2O_TPU_RETRY_BASE"] = "0.02"
    try:
        fr = _frame()
        from h2o_kubernetes_tpu.models import GBM

        m = GBM(ntrees=3, max_depth=2, seed=0).train(
            y="y", training_frame=fr)
        with faults.inject("persist.http:http_503*2"):
            h2o.save_model(m, "s3://bkt/chaos/gbm.model")
        _check("/bkt/chaos/gbm.model" in store.store,
               "model save did not land after the 503 burst")
        m2 = h2o.load_model("s3://bkt/chaos/gbm.model")
        _check(m2.predict(fr).nrows == fr.nrows,
               "reloaded model does not predict")
        # negative control: same burst, retries disabled -> must fail
        os.environ["H2O_TPU_RETRY_DISABLE"] = "1"
        try:
            with faults.inject("persist.http:http_503*2"):
                try:
                    h2o.save_model(m, "s3://bkt/chaos/nope.model")
                except IOError:
                    pass
                else:
                    raise ChaosFailure(
                        "save survived a 503 burst with retries "
                        "DISABLED — the fault is not exercising the "
                        "retry path")
        finally:
            os.environ.pop("H2O_TPU_RETRY_DISABLE", None)
    finally:
        srv.shutdown()
        for k, v in saved.items():     # no leaks into later scenarios
            os.environ.pop(k, None)
            if v is not None:
                os.environ[k] = v


def scenario_probe_hang() -> None:
    from h2o_kubernetes_tpu.runtime import faults, health

    health.reset()
    with faults.inject("health.probe:hang~0.7"):
        _check(health.heartbeat(timeout=0.1) is False,
               "hung probe reported healthy")
        _check(not health.healthy(), "hang did not trip unhealthy")
        _check(health.heartbeat(timeout=0.1) is False,
               "second heartbeat did not skip-and-return-False")
        alive = [t for t in threading.enumerate()
                 if t.name == "h2o-tpu-probe" and t.is_alive()]
        _check(len(alive) <= 1,
               f"probe threads piled up: {len(alive)}")
    deadline = time.monotonic() + 10
    while [t for t in threading.enumerate()
           if t.name == "h2o-tpu-probe" and t.is_alive()] \
            and time.monotonic() < deadline:
        time.sleep(0.05)
    health.reset()
    _check(health.heartbeat(timeout=120.0) is True,
           "heartbeat did not recover after reset")


def scenario_device_error() -> None:
    from h2o_kubernetes_tpu.models import GBM
    from h2o_kubernetes_tpu.runtime import faults, health

    health.reset()
    fr = _frame()
    with faults.inject("train.step:device_error@1"):
        try:
            GBM(ntrees=4, max_depth=2, seed=0).train(
                y="y", training_frame=fr)
        except (faults.InjectedDeviceError, health.ClusterHealthError):
            pass
        else:
            raise ChaosFailure("train survived an injected device error")
    _check(not health.healthy(), "device error did not lock the cloud")
    try:
        GBM(ntrees=4, max_depth=2, seed=0).train(y="y", training_frame=fr)
    except health.ClusterHealthError:
        pass
    else:
        raise ChaosFailure("locked cloud accepted a new train")
    health.reset()
    m = GBM(ntrees=4, max_depth=2, seed=0).train(y="y", training_frame=fr)
    _check(m.predict(fr).nrows == fr.nrows,
           "post-restart model does not predict")


def scenario_resume() -> None:
    import h2o_kubernetes_tpu as h2o
    from h2o_kubernetes_tpu.runtime import faults, health

    health.reset()
    fr = _frame(seed=12)
    with tempfile.TemporaryDirectory() as ckpt:
        kw = dict(max_models=2, nfolds=2, seed=11, verbosity=None,
                  include_algos=["glm", "deeplearning"],
                  project_name="chaos_cli", checkpoint_dir=ckpt)
        a1 = h2o.AutoML(**kw)
        with faults.inject("automl.step:device_error@1"):
            try:
                a1.train(y="y", training_frame=fr)
            except health.ClusterHealthError:
                pass
            else:
                raise ChaosFailure(
                    "AutoML survived a mid-run device error")
        manifest = json.load(
            open(os.path.join(ckpt, "automl_manifest.json")))
        _check(len(manifest) == 1,
               f"manifest should hold 1 finished step, has "
               f"{len(manifest)}")
        health.reset()
        a2 = h2o.AutoML(**kw)
        a2.train(y="y", training_frame=fr)
        _check(any("resumed from checkpoint" in m
                   for _, m in a2.event_log),
               "rerun did not resume from the manifest")
        _check(len(a2.leaderboard.rows) >= 2,
               "resumed run did not finish the plan")


def scenario_score_under_fault() -> None:
    """Scoring during an unhealthy episode: 503 fast, then recovery.

    The serving contract (docs/SERVING.md): a request must NEVER wait
    out H2O_TPU_SCORE_TIMEOUT behind the micro-batcher while the cloud
    is locked — the health gate rejects it up front."""
    import json as _json
    import socket
    import urllib.error
    import urllib.request

    import numpy as np

    import h2o_kubernetes_tpu as h2o
    from h2o_kubernetes_tpu import rest
    from h2o_kubernetes_tpu.models import GBM
    from h2o_kubernetes_tpu.runtime import faults, health

    health.reset()
    fr = _frame()
    m = GBM(ntrees=3, max_depth=2, seed=0).train(y="y", training_frame=fr)
    rest.MODELS["chaos_scorer"] = m
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    srv = rest.start_server(port)
    url = f"http://127.0.0.1:{port}/3/Predictions/models/chaos_scorer"

    def score(timeout=30.0):
        req = urllib.request.Request(
            url, data=_json.dumps(
                {"rows": [{"x": 0.3}, {"x": -0.7}]}).encode(),
            method="POST", headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return _json.loads(r.read())

    try:
        out = score()
        _check(len(out["predict"]) == 2, "healthy scoring broken")
        with faults.inject("health.probe:hang~0.7"):
            _check(health.heartbeat(timeout=0.1) is False,
                   "hung probe reported healthy")
            _check(not health.healthy(), "hang did not trip unhealthy")
            t0 = time.monotonic()
            try:
                score()
            except urllib.error.HTTPError as e:
                dt = time.monotonic() - t0
                _check(e.code == 503,
                       f"unhealthy scoring returned {e.code}, want 503")
                _check(dt < 5.0,
                       f"503 took {dt:.1f}s — request queued behind "
                       "the micro-batcher instead of failing fast")
            else:
                raise ChaosFailure(
                    "scoring succeeded on an unhealthy cloud")
        # drain the hung probe thread, then recover
        deadline = time.monotonic() + 10
        while [t for t in threading.enumerate()
               if t.name == "h2o-tpu-probe" and t.is_alive()] \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        health.reset()
        out = score()
        _check(len(out["predict"]) == 2,
               "scoring did not recover after health.reset()")
    finally:
        srv.shutdown()
        rest.MODELS.pop("chaos_scorer", None)
        health.reset()


def _mid_record_cut(blob: bytes, near: int, sep: bytes = b",") -> int:
    """Byte offset near ``near`` that truncates ``blob`` two fields
    into a record: the partial trailing line then has fewer columns
    than any complete row, so BOTH parsers must reject it. (A cut at a
    record boundary — or inside the last field — yields a legally
    parseable shorter/equal row and cannot distinguish 'truncated'
    from 'complete shorter file'.)"""
    line_start = blob.rindex(b"\n", 0, near) + 1
    return blob.index(sep, line_start) + 1


def scenario_ingest_truncated_csv() -> None:
    """A CSV stream aborting mid-file must FAIL the parse cleanly —
    never ship a short frame (docs/SCALING.md §ingest). Rehearsed on
    both the streamed pyarrow record-batch reader (forced into many
    small batches) and the pure-Python parser that defines the parse
    semantics. The cut lands two fields into a record so the trailing
    partial line can never parse as a complete row — a cut exactly at
    a record boundary (or inside the LAST field) is indistinguishable
    from a complete shorter file and would false-alarm the drill."""
    import tempfile

    import h2o_kubernetes_tpu as h2o
    from tools import datasets as D

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "air.csv")
        D.airlines_csv(path, 20_000, chunk=20_000)
        fr = h2o.import_file(path)
        _check(fr.nrows == 20_000, "control parse lost rows")
        with open(path, "rb") as f:
            blob = f.read()
        cut = _mid_record_cut(blob, int(len(blob) * 0.6))
        with open(path, "r+b") as f:
            f.truncate(cut)
        saved = {k: os.environ.get(k) for k in
                 ("H2O_TPU_ARROW_CSV", "H2O_TPU_INGEST_CHUNK_BYTES")}
        try:
            # streamed arrow reader, tiny batches (stream abort lands
            # mid-iteration, not on the first block)
            os.environ.pop("H2O_TPU_ARROW_CSV", None)
            os.environ["H2O_TPU_INGEST_CHUNK_BYTES"] = str(64 << 10)
            try:
                h2o.import_file(path)
                _check(False, "streamed parse shipped a short frame "
                       "from a truncated CSV")
            except ChaosFailure:
                raise
            except Exception:
                pass                         # loud failure: correct
            # pure-Python definition path
            os.environ["H2O_TPU_ARROW_CSV"] = "0"
            try:
                h2o.import_file(path)
                _check(False, "python parse shipped a short frame "
                       "from a truncated CSV")
            except ChaosFailure:
                raise
            except ValueError:
                pass                         # loud failure: correct
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v


def scenario_breaker_trip() -> None:
    """Serving circuit breaker: trip open on consecutive dispatch
    errors, short-circuit with zero device work while open, recover
    SERVING through the half-open probe once faults clear."""
    import json as _json
    import socket
    import urllib.error
    import urllib.request

    import h2o_kubernetes_tpu as h2o  # noqa: F401 — package init
    from h2o_kubernetes_tpu import rest
    from h2o_kubernetes_tpu.models import GBM
    from h2o_kubernetes_tpu.runtime import faults, health, lifecycle

    saved = {k: os.environ.get(k) for k in
             ("H2O_TPU_BREAKER_FAILURES", "H2O_TPU_BREAKER_COOLDOWN")}
    os.environ["H2O_TPU_BREAKER_FAILURES"] = "3"
    # LONG cooldown for the open-phase assertions: the knob is read at
    # use time, so a loaded box can't race the breaker into half-open
    # between the trip and the checks below; the recovery phase lowers
    # it just before waiting for the half-open probe
    os.environ["H2O_TPU_BREAKER_COOLDOWN"] = "30"
    health.reset()
    lifecycle.BREAKER.reset()
    fr = _frame()
    m = GBM(ntrees=3, max_depth=2, seed=0).train(y="y", training_frame=fr)
    rest.MODELS["breaker_gbm"] = m
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    srv = rest.start_server(port)
    base = f"http://127.0.0.1:{port}"

    def score():
        req = urllib.request.Request(
            base + "/3/Predictions/models/breaker_gbm",
            data=_json.dumps({"rows": [{"x": 0.3}]}).encode(),
            method="POST", headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            return _json.loads(r.read())

    def probe(path):
        try:
            with urllib.request.urlopen(base + path, timeout=10) as r:
                return r.status
        except urllib.error.HTTPError as e:
            return e.code

    try:
        _check(len(score()["predict"]) == 1, "healthy scoring broken")
        _check(probe("/readyz") == 200, "/readyz not ready while healthy")
        # 3 consecutive injected dispatch errors -> breaker OPEN; the
        # cloud must NOT lock (dispatch_error is per-dispatch, not a
        # dead mesh)
        with faults.inject("score.dispatch:dispatch_error*3"):
            for i in range(3):
                try:
                    score()
                except urllib.error.HTTPError as e:
                    _check(e.code == 503,
                           f"faulted dispatch returned {e.code}")
                else:
                    raise ChaosFailure(
                        f"dispatch {i} survived an injected error")
        _check(health.healthy(),
               "dispatch_error locked the cloud (it must only feed "
               "the breaker)")
        _check(lifecycle.BREAKER.state() == "open",
               f"breaker not open: {lifecycle.BREAKER.status()}")
        _check(probe("/readyz") == 503, "/readyz ready with breaker open")
        # while open: instant 503, and NO device call — an armed fault
        # at the dispatch site must not be consumed
        # finite count: inf - 1 == inf would make the consumed-check
        # below vacuous; 5 is plenty for the single probe attempt
        with faults.inject("score.dispatch:dispatch_error*5") as armed:
            before = armed[0].count
            t0 = time.monotonic()
            try:
                score()
            except urllib.error.HTTPError as e:
                dt = time.monotonic() - t0
                _check(e.code == 503, f"open breaker returned {e.code}")
                _check(dt < 1.0, f"open-breaker 503 took {dt:.2f}s — "
                       "not an instant short-circuit")
                _check(int(e.headers.get("Retry-After") or 0) >= 1,
                       "open-breaker 503 lacks Retry-After")
            else:
                raise ChaosFailure("open breaker admitted a dispatch")
            _check(armed[0].count == before,
                   "device dispatch happened while the breaker was "
                   "open (armed fault consumed)")
        _check(lifecycle.BREAKER.stats["short_circuited"] >= 1,
               "no short-circuit recorded")
        # faults cleared: after the cooldown, the next request is the
        # half-open probe; success closes the breaker and restores
        # readiness (read-at-use-time knob: shortening it now makes
        # the already-elapsed open time count)
        os.environ["H2O_TPU_BREAKER_COOLDOWN"] = "0.2"
        time.sleep(0.3)
        _check(len(score()["predict"]) == 1,
               "half-open probe did not score")
        _check(lifecycle.BREAKER.state() == "closed",
               f"probe success did not close: {lifecycle.BREAKER.status()}")
        _check(probe("/readyz") == 200,
               "/readyz not restored after the breaker closed")
    finally:
        srv.shutdown()
        rest.MODELS.pop("breaker_gbm", None)
        lifecycle.BREAKER.reset()
        health.reset()
        for k, v in saved.items():
            os.environ.pop(k, None)
            if v is not None:
                os.environ[k] = v


# child process for the drain drill: a real pod-shaped server that
# installs the SIGTERM handler and exits when the drain completes
_DRAIN_CHILD = r"""
import os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, sys.argv[2])
import numpy as np
import h2o_kubernetes_tpu as h2o
from h2o_kubernetes_tpu import rest
from h2o_kubernetes_tpu.models import GBM
from h2o_kubernetes_tpu.runtime import (lifecycle, make_mesh,
                                        set_global_mesh)

set_global_mesh(make_mesh())
rng = np.random.default_rng(7)
x = rng.normal(size=400).astype(np.float32)
y = np.where(x + rng.normal(scale=0.4, size=400) > 0, "p", "n")
fr = h2o.Frame.from_arrays({"x": x, "y": y})
rest.FRAMES["drain_train"] = fr
rest.MODELS["drain_gbm"] = GBM(ntrees=3, max_depth=2, seed=0).train(
    y="y", training_frame=fr)
srv = rest.start_server(int(sys.argv[1]), install_signals=True)
print("READY", flush=True)
while not lifecycle.terminated():   # sleep is signal-interruptible;
    time.sleep(0.2)                 # the drain thread os._exit(0)s
sys.exit(0)
"""


def scenario_drain_under_load() -> None:
    """SIGTERM during concurrent REST scoring + a RUNNING build:
    readiness flips while liveness holds, every client gets a terminal
    response, the job settles, the process exits inside the budget."""
    import json as _json
    import signal
    import socket
    import subprocess
    import urllib.error
    import urllib.request

    drain_timeout = 15.0
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    base = f"http://127.0.0.1:{port}"
    env = dict(os.environ, H2O_TPU_DRAIN_TIMEOUT=str(drain_timeout))
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen(
        [sys.executable, "-c", _DRAIN_CHILD, str(port), repo],
        env=env, stdout=subprocess.PIPE, text=True)
    try:
        line = proc.stdout.readline()
        _check(line.strip() == "READY",
               f"child never came up (got {line!r})")

        def probe(path):
            try:
                with urllib.request.urlopen(base + path, timeout=10) as r:
                    return r.status
            except urllib.error.HTTPError as e:
                return e.code
            except urllib.error.URLError:
                return 0             # server gone (post-drain shutdown)

        _check(probe("/readyz") == 200, "pod not ready before SIGTERM")

        # closed-loop scoring load; every request must end terminally
        hung: list[str] = []
        stop = threading.Event()
        sigterm_at = [None]

        def worker(wid):
            body = _json.dumps(
                {"rows": [{"x": 0.1 * wid}] * 8}).encode()
            while not stop.is_set():
                req = urllib.request.Request(
                    base + "/3/Predictions/models/drain_gbm", data=body,
                    method="POST",
                    headers={"Content-Type": "application/json"})
                try:
                    with urllib.request.urlopen(req, timeout=10) as r:
                        r.read()                 # 200: scored
                except urllib.error.HTTPError as e:
                    e.read()                     # 503/429: shed — terminal
                except Exception as e:  # noqa: BLE001
                    import socket as _socket

                    # urlopen wraps a connect/read timeout in URLError
                    # (reason=socket.timeout) — unwrap it, or the main
                    # hang shape this drill exists to catch passes as a
                    # terminal outcome
                    cause = getattr(e, "reason", e)
                    if (isinstance(cause, (TimeoutError, _socket.timeout))
                            and not isinstance(e, ConnectionError)
                            and not isinstance(cause, ConnectionError)):
                        # a request that never returned = hung client,
                        # the one outcome the drain contract forbids
                        hung.append(f"w{wid}: hung — {e!r}")
                        return
                    # refused/reset/disconnected: an immediate error is
                    # terminal — but only legitimate once SIGTERM has
                    # allowed the server to be going away
                    if sigterm_at[0] is None:
                        hung.append(f"w{wid}: {e!r} before SIGTERM")
                    return

        workers = [threading.Thread(target=worker, args=(w,))
                   for w in range(4)]
        for t in workers:
            t.start()
        time.sleep(0.5)              # load in flight
        # a build RUNNING at SIGTERM time: the drain must wait for (or
        # terminally fail) it — and it holds DRAINING open long enough
        # to observe the probe flip
        req = urllib.request.Request(
            base + "/3/ModelBuilders/gbm",
            data=_json.dumps({
                "training_frame": "drain_train", "response_column": "y",
                "ntrees": 40, "max_depth": 3, "model_id": "drain_job",
                "_sync_timeout": 0}).encode(),
            method="POST", headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            r.read()
        sigterm_at[0] = time.monotonic()
        proc.send_signal(signal.SIGTERM)

        # readiness must flip within 5s of SIGTERM, while the process
        # (and its liveness) are still up
        flipped = False
        while time.monotonic() - sigterm_at[0] < 5.0:
            if proc.poll() is not None:
                break                # drained *very* fast: acceptable
            code = probe("/readyz")
            if code == 503:
                flipped = True
                break
            time.sleep(0.02)
        _check(flipped or proc.poll() is not None,
               "/readyz never went unready after SIGTERM")
        if flipped and proc.poll() is None:
            _check(probe("/healthz") == 200,
                   "liveness dropped during drain — the kubelet would "
                   "kill a draining pod")

        # the process must exit cleanly inside the drain budget
        try:
            rc = proc.wait(timeout=drain_timeout + 5.0)
        except subprocess.TimeoutExpired:
            raise ChaosFailure(
                f"process still alive {drain_timeout + 5:.0f}s after "
                "SIGTERM — drain wedged")
        _check(rc == 0, f"drained process exited rc={rc}")
        stop.set()
        deadline = time.monotonic() + 15
        for t in workers:
            t.join(timeout=max(0.1, deadline - time.monotonic()))
        _check(not any(t.is_alive() for t in workers),
               "load workers still blocked after process exit — "
               "hung clients")
        _check(not hung, f"non-terminal client outcomes: {hung}")
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.stdout.close()


def scenario_automl_pipelined_fault() -> None:
    """Mid-overlap step failure in the pipelined AutoML executor: the
    job fails terminally, finished steps' manifest writes have landed
    (the resume contract), no scheduler thread is left wedged — and
    the kill switch reproduces the exact same manifest serially."""
    import json as _json
    import threading as _threading

    import h2o_kubernetes_tpu as h2o  # noqa: F401 — package init
    from h2o_kubernetes_tpu.automl import AutoML
    from h2o_kubernetes_tpu.runtime import faults, health

    def sched_threads():
        return [t.name for t in _threading.enumerate()
                if t.is_alive() and (t.name.startswith("h2o-automl-")
                                     or t.name.startswith("h2o-cv-"))]

    def run_faulted(pipeline: str, ckpt: str) -> dict:
        saved = os.environ.get("H2O_TPU_AUTOML_PIPELINE")
        os.environ["H2O_TPU_AUTOML_PIPELINE"] = pipeline
        try:
            health.reset()
            aml = AutoML(max_models=2, nfolds=2, seed=11,
                         verbosity=None,
                         include_algos=["glm", "deeplearning"],
                         # same project name both legs: the model ids
                         # (manifest keys) embed it, and the identity
                         # check compares keys
                         project_name="chaos_pipe",
                         checkpoint_dir=ckpt)
            with faults.inject("automl.step:device_error@1"):
                try:
                    aml.train(y="y", training_frame=_frame(seed=12))
                except health.ClusterHealthError:
                    pass
                else:
                    raise ChaosFailure(
                        f"pipeline={pipeline}: AutoML survived a "
                        "mid-run device error")
            _check(aml.job.status == "FAILED",
                   f"pipeline={pipeline}: job not FAILED terminally "
                   f"({aml.job.status})")
            # the scheduler threads must settle — a wedged host/compile
            # worker would hold model references and block interpreter
            # shutdown hygiene
            deadline = time.monotonic() + 10
            while sched_threads() and time.monotonic() < deadline:
                time.sleep(0.05)
            _check(not sched_threads(),
                   f"pipeline={pipeline}: scheduler threads wedged: "
                   f"{sched_threads()}")
            man = _json.load(
                open(os.path.join(ckpt, "automl_manifest.json")))
            _check(len(man) == 1,
                   f"pipeline={pipeline}: manifest should hold the 1 "
                   f"finished step, has {sorted(man)}")
            return man
        finally:
            os.environ.pop("H2O_TPU_AUTOML_PIPELINE", None)
            if saved is not None:
                os.environ["H2O_TPU_AUTOML_PIPELINE"] = saved
            health.reset()

    def norm(man: dict) -> dict:
        return {k: {mk: mv for mk, mv in v["metrics"].items()
                    if mk != "training_time_s"}
                for k, v in man.items()}

    with tempfile.TemporaryDirectory() as d1, \
            tempfile.TemporaryDirectory() as d2:
        m_pipe = run_faulted("1", d1)
        m_serial = run_faulted("0", d2)
        _check(norm(m_pipe) == norm(m_serial),
               "pipelined manifest diverged from the serial kill-"
               f"switch run: {norm(m_pipe)} vs {norm(m_serial)}")


# ---------------------------------------------------------------------------
# Operator scorer-pool drills (docs/OPERATOR.md)
# ---------------------------------------------------------------------------


class _PoolFixture:
    """A converged 2-replica scorer pool on artifact v1 (+v2 staged in
    the registry) — the shared setup of the rolling-update,
    replica-kill and tenant-storm drills. ``tenants`` > 0 adds that
    many EXTRA artifacts to the spec (multi-artifact push: the pool
    serves a tenant population, /readyz held until every one is
    loaded+warmed); ``pod_env`` injects env overrides into the pods
    (the tenant-storm drill pins a tiny scorer-cache byte budget).
    Always tear down via close(): subprocess pods must not outlive a
    failed drill (tools/run_tests.py's preflight would reap them, but
    a clean drill leaves a clean box)."""

    def __init__(self, tag: str, tenants: int = 0,
                 pod_env: dict | None = None):
        import tempfile

        import numpy as np

        import h2o_kubernetes_tpu as h2o
        from h2o_kubernetes_tpu.models import GBM
        from h2o_kubernetes_tpu.operator import (ModelRegistry,
                                                 PoolStore, Reconciler,
                                                 ScorerPoolSpec)

        self.td = tempfile.mkdtemp(prefix=f"chaos_{tag}_")
        rng = np.random.default_rng(0)
        n = 500
        cols = {f"x{i}": rng.normal(size=n).astype(np.float32)
                for i in range(4)}
        cols["y"] = np.where(cols["x0"] - cols["x1"] > 0, "late",
                             "ontime")
        self.feature_cols = [f"x{i}" for i in range(4)]
        fr = h2o.Frame.from_arrays(cols)
        m1 = GBM(ntrees=4, max_depth=3, seed=1).train(
            y="y", training_frame=fr)
        m2 = GBM(ntrees=6, max_depth=3, seed=2).train(
            y="y", training_frame=fr)
        self.registry = ModelRegistry(os.path.join(self.td, "registry"))
        self.v1 = self.registry.publish(m1, "scorer")
        self.v2 = self.registry.publish(m2, "scorer")
        extra = ()
        self.tenant_keys = ["pm"]
        if tenants:
            # a second, structurally different artifact (more trees =
            # different HLO) so the tenant set is not one program
            # compiled once — the storm's pcache assertions must hold
            # across genuinely distinct executables
            m3 = GBM(ntrees=8, max_depth=3, seed=3).train(
                y="y", training_frame=fr)
            self.registry.publish(m3, "scorer2")
            keys = [f"t{i:02d}" for i in range(1, tenants + 1)]
            extra = tuple(
                ("scorer" if i % 2 else "scorer2",
                 self.v1 if i % 2 else 1, k)
                for i, k in enumerate(keys, start=1))
            self.tenant_keys += keys
        self.store = PoolStore()
        self.store.apply(ScorerPoolSpec(
            name="pool", artifact="scorer", version=self.v1,
            model_key="pm", replicas=2, warm_buckets=(128,),
            extra_artifacts=extra, env=dict(pod_env or {})))
        self.rec = Reconciler(self.store, self.registry, "pool",
                              log_dir=os.path.join(self.td, "logs"))
        self.stop = threading.Event()
        self.thread = threading.Thread(
            target=self.rec.run, args=(self.stop,),
            kwargs={"interval": 0.25}, daemon=True)
        self.thread.start()
        try:
            _check(self.rec.wait_converged(timeout=240),
                   f"pool never converged on v1: "
                   f"{self.store.get_status('pool')} "
                   f"(pod logs under {self.td}/logs)")
        except BaseException:
            # raising out of __init__ means the drill's try/finally
            # never runs — tear the pods down HERE or they leak as the
            # exact orphans the preflight reaper exists to catch
            # (keep_dir: the failure message points at the pod logs)
            self.close(keep_dir=True)
            raise

    def event_kinds(self) -> list[str]:
        return [e["kind"] for e in self.store.events("pool")]

    def close(self, keep_dir: bool = False) -> None:
        try:
            self.rec.shutdown(timeout=60)
        finally:
            self.stop.set()
            self.thread.join(timeout=10)
            if not keep_dir:
                import shutil

                shutil.rmtree(self.td, ignore_errors=True)


def scenario_rolling_update() -> None:
    """Artifact v1 → v2 across a 2-replica pool under closed-loop
    load: zero 5xx, zero unready routing, both replicas end on v2 with
    the warm-up contract intact."""
    from tools.score_load import run_load_multi

    fx = _PoolFixture("roll")
    try:
        load_stop = threading.Event()
        result: dict = {}

        def drive():
            result.update(run_load_multi(
                fx.rec.endpoints, "pm", fx.feature_cols,
                concurrency=3, rows_per_request=8,
                stop_event=load_stop))

        lt = threading.Thread(target=drive, daemon=True)
        lt.start()
        time.sleep(1.5)              # load in flight on v1
        fx.store.apply_update("pool", version=fx.v2)
        rolled = fx.rec.wait_converged(timeout=300)
        time.sleep(0.5)              # post-roll traffic on v2
        load_stop.set()
        lt.join(timeout=60)
        _check(rolled, "pool never converged on v2: "
               f"{fx.store.get_status('pool')}")
        _check(result.get("requests", 0) > 50,
               f"load generator barely ran: {result}")
        _check(result["fivexx"] == 0,
               f"{result['fivexx']} 5xx during the rolling update: "
               f"{result['fivexx_sample']}")
        _check(result["errors"] == 0,
               f"non-HTTP client errors during the roll: "
               f"{result['error_sample']}")
        versions = [r.loaded_version() for r in fx.rec.replicas]
        _check(versions == [fx.v2, fx.v2],
               f"replicas did not end on v2: {versions}")
        for r in fx.rec.replicas:
            st = r.stats()
            _check(st is not None, f"{r.rid}: /3/Stats unreachable")
            _check(st["counters"]["scored_while_unready"] == 0,
                   f"{r.rid} admitted scoring while unready: "
                   f"{st['counters']}")
            _check(st["registry"]["pm"]["warm_cache_misses"] == 0,
                   f"{r.rid} compiled on live traffic after warm-up: "
                   f"{st['registry']}")
        kinds = fx.event_kinds()
        for needed in ("replica_cordon", "replica_drain",
                       "replica_exit"):
            _check(needed in kinds,
                   f"event '{needed}' missing from operator status: "
                   f"{kinds}")
    finally:
        fx.close()


def scenario_replica_kill() -> None:
    """SIGKILL one replica of a converged pool: the reconciler
    replaces it and the pool recovers spec count + aggregate readiness
    inside the deadline, with the event sequence in status."""
    import signal

    fx = _PoolFixture("kill")
    try:
        victim = fx.rec.replicas[0]
        vid = victim.rid
        os.kill(victim.pid(), signal.SIGKILL)
        # SIGKILL delivery is async: wait until the process is
        # OBSERVABLY dead before polling convergence, or the first
        # converged() check can race the kill and declare victory over
        # a still-listed dead replica
        deadline = time.monotonic() + 10
        while victim.alive() and time.monotonic() < deadline:
            time.sleep(0.02)
        _check(not victim.alive(), f"SIGKILL did not kill {vid}")
        _check(fx.rec.wait_converged(timeout=240),
               "pool never reconverged after SIGKILL: "
               f"{fx.store.get_status('pool')}")
        for r in fx.rec.replicas:
            _check(r.readyz_ok(), f"{r.rid} not ready after recovery")
        _check(len(fx.rec.replicas) == 2,
               f"pool not back at spec count: "
               f"{fx.store.get_status('pool')}")
        kinds = fx.event_kinds()
        died = kinds.index("replica_died")
        _check("replica_start" in kinds[died:]
               and "replica_ready" in kinds[died:],
               f"no replacement start/ready after replica_died ({vid}):"
               f" {kinds}")
    finally:
        fx.close()


def scenario_tenant_storm() -> None:
    """Zipf tenant flood against a 2-replica multi-artifact pool under
    a deliberately tiny executable-cache byte budget: resident scorer
    bytes never exceed the budget on either replica, zero 5xx on any
    tenant (an evicted model must re-promote transparently, never
    error), eviction→promotion churn actually happens, and every
    compile during the flood is a persistent-XLA-cache HIT — the
    "eviction costs a pcache hit, never a cold compile" contract
    proven on real subprocess pods."""
    from tools.score_load import run_load_zipf

    budget = 400_000
    fx = _PoolFixture("storm", tenants=10, pod_env={
        "H2O_TPU_SCORER_CACHE_BYTES": str(budget)})
    try:
        out = run_load_zipf(fx.rec.endpoints, fx.tenant_keys,
                            fx.feature_cols, concurrency=4,
                            rows_per_request=8, seconds=8.0,
                            zipf_s=1.1)
        _check(out["requests"] > 50,
               f"tenant flood barely ran: {out}")
        _check(out["fivexx"] == 0,
               f"{out['fivexx']} 5xx during the tenant storm "
               f"(sample: {out['fivexx_sample']}) — an evicted tenant "
               "must re-promote, not error")
        _check(out["errors"] == 0,
               f"client errors during the storm: {out['error_sample']}")
        served = [k for k, r in out["by_model"].items()
                  if r["requests"] > 0]
        _check(len(served) == len(fx.tenant_keys),
               f"only {len(served)}/{len(fx.tenant_keys)} tenants saw "
               "traffic — the Zipf flood did not cover the tail")
        res = out["residency"]
        _check(res["samples"] > 0, "no /3/Stats residency samples")
        _check(res["budget_bytes"] == budget,
               f"pods did not pick up the byte budget: {res}")
        _check(res["budget_exceeded"] == 0
               and res["max_resident_bytes"] <= budget,
               f"resident bytes exceeded the budget: {res}")
        _check((res["promotions_delta"] or 0) > 0,
               f"no eviction→promotion churn under a {budget}B budget "
               f"with {len(fx.tenant_keys)} tenants: {res}")
        _check(res["pcache_misses_delta"] == 0,
               f"a promotion compiled COLD (persistent-cache miss) "
               f"during the flood: {res}")
        _check(res["compiles_delta"] == res["pcache_hits_delta"],
               f"flood-window compiles not fully served from the "
               f"persistent cache: {res}")
    finally:
        fx.close()


def _live_pods_for(workdir: str) -> list[tuple[int, str]]:
    """operator.pod processes whose cmdline references this pool's
    workdir — the leak check of the control-plane drills."""
    out = []
    try:
        pids = [int(d) for d in os.listdir("/proc") if d.isdigit()]
    except OSError:
        return out
    for pid in pids:
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().replace(b"\0", b" ").decode(
                    errors="replace")
        except OSError:
            continue
        if "operator.pod" in cmd and workdir in cmd:
            out.append((pid, cmd[:160]))
    return out


def scenario_operator_restart() -> None:
    """SIGKILL the operator process mid-rollout under closed-loop
    load, restart it against the durable store: the successor ADOPTS
    the live pods (zero duplicate spawns, zero leaked pods), finishes
    the rollout, and the load generator records zero 5xx end to end —
    the control plane died, the data plane never noticed."""
    import shutil
    import signal
    import subprocess

    import numpy as np

    import h2o_kubernetes_tpu as h2o
    from h2o_kubernetes_tpu.models import GBM
    from h2o_kubernetes_tpu.operator import (DurablePoolStore,
                                             ModelRegistry,
                                             ScorerPoolSpec)
    from tools.score_load import run_load_multi

    td = tempfile.mkdtemp(prefix="chaos_oprestart_")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    storedir = os.path.join(td, "store")
    workdir = os.path.join(td, "work")
    regdir = os.path.join(td, "registry")
    procs: list = []
    try:
        rng = np.random.default_rng(0)
        n = 500
        cols = {f"x{i}": rng.normal(size=n).astype(np.float32)
                for i in range(4)}
        cols["y"] = np.where(cols["x0"] - cols["x1"] > 0, "late",
                             "ontime")
        feature_cols = [f"x{i}" for i in range(4)]
        fr = h2o.Frame.from_arrays(cols)
        registry = ModelRegistry(regdir)
        v1 = registry.publish(GBM(ntrees=4, max_depth=3, seed=1).train(
            y="y", training_frame=fr), "scorer")
        v2 = registry.publish(GBM(ntrees=6, max_depth=3, seed=2).train(
            y="y", training_frame=fr), "scorer")
        store = DurablePoolStore(storedir)
        store.apply(ScorerPoolSpec(
            name="pool", artifact="scorer", version=v1,
            model_key="pm", replicas=2, warm_buckets=(128,)))

        def spawn_operator(tag: str) -> subprocess.Popen:
            log = open(os.path.join(td, f"operator_{tag}.log"), "ab")
            p = subprocess.Popen(
                [sys.executable, "-m",
                 "h2o_kubernetes_tpu.operator.run",
                 "--store", storedir, "--registry", regdir,
                 "--pool", "pool", "--workdir", workdir,
                 "--interval", "0.25"],
                cwd=repo, env=dict(os.environ, JAX_PLATFORMS="cpu"),
                stdout=log, stderr=log, start_new_session=True)
            procs.append(p)
            return p

        def status() -> dict:
            return store.get_status("pool")

        def wait_status(pred, timeout: float, what: str) -> dict:
            deadline = time.monotonic() + timeout
            st = status()
            while time.monotonic() < deadline:
                st = status()
                if pred(st):
                    return st
                time.sleep(0.05)
            raise ChaosFailure(f"timed out waiting for {what}: {st} "
                               f"(operator logs under {td})")

        def endpoints() -> list[str]:
            return [f"http://127.0.0.1:{r['port']}"
                    for r in status().get("replicas", ())
                    if r["state"] in ("STARTING", "LOADING", "READY")]

        op1 = spawn_operator("1")
        wait_status(lambda st: st.get("converged")
                    and st.get("desired_version") == v1,
                    240, "v1 convergence")

        load_stop = threading.Event()
        result: dict = {}

        def drive():
            result.update(run_load_multi(
                endpoints, "pm", feature_cols, concurrency=3,
                rows_per_request=8, stop_event=load_stop))

        lt = threading.Thread(target=drive, daemon=True)
        lt.start()
        time.sleep(1.5)                     # load in flight on v1
        store.apply_update("pool", version=v2)
        # the moment the surge-one v2 replica exists, the rollout is
        # mid-flight — SIGKILL the control plane RIGHT THERE
        wait_status(lambda st: any(r["version"] == v2
                                   for r in st.get("replicas", ())),
                    120, "the surge v2 replica to spawn")
        op1.kill()
        op1.wait(timeout=30)
        pods_at_kill = _live_pods_for(workdir)
        _check(len(pods_at_kill) >= 2,
               f"expected >=2 live pods surviving the operator kill, "
               f"found {pods_at_kill}")

        op2 = spawn_operator("2")
        wait_status(lambda st: st.get("converged")
                    and st.get("desired_version") == v2
                    and st.get("effective_version") == v2,
                    300, "the restarted operator to finish the "
                    "rollout")
        time.sleep(0.5)                     # post-roll traffic on v2
        load_stop.set()
        lt.join(timeout=60)

        _check(result.get("requests", 0) > 50,
               f"load generator barely ran: {result}")
        _check(result["fivexx"] == 0,
               f"{result['fivexx']} 5xx across the operator restart: "
               f"{result['fivexx_sample']}")
        _check(result["errors"] == 0,
               f"client errors across the restart: "
               f"{result['error_sample']}")
        # the durable event ring spans BOTH operator lives: the
        # successor must have adopted, not re-spawned — exactly two
        # v1 starts ever, and at least two adoptions
        events = store.events("pool")
        kinds = [e["kind"] for e in events]
        _check(kinds.count("replica_adopted") >= 2,
               f"successor did not adopt the live pods: {kinds}")
        v1_starts = [e for e in events if e["kind"] == "replica_start"
                     and f"v{v1} " in e["msg"] + " "]
        _check(len(v1_starts) == 2,
               f"v1 replicas were re-spawned (duplicates): "
               f"{[e['msg'] for e in v1_starts]}")
        # graceful teardown: SIGTERM drains the fleet, zero leaks
        op2.send_signal(signal.SIGTERM)
        rc = op2.wait(timeout=120)
        _check(rc == 0, f"operator exited rc={rc} on SIGTERM")
        leaked = _live_pods_for(workdir)
        _check(not leaked, f"leaked pods after teardown: {leaked}")
    finally:
        import signal as _sig

        for p in procs:
            if p.poll() is None:
                p.kill()
        for pid, _ in _live_pods_for(workdir):
            try:
                os.kill(pid, _sig.SIGKILL)
            except OSError:
                pass
        shutil.rmtree(td, ignore_errors=True)


def scenario_poison_rollback() -> None:
    """Push an artifact whose replica can never come up: respawns are
    backoff-spaced (provably >= the configured base), the rollout
    auto-rolls-back to last-good after H2O_TPU_POOL_ROLLOUT_RETRIES
    failures, the old replicas stay READY throughout, and the load
    generator records zero 5xx — a bad push degrades to 'nothing
    happened' instead of a wedged pool."""
    from h2o_kubernetes_tpu import persist
    from tools.score_load import run_load_multi

    base_backoff = 0.4
    retries = 4
    saved = {k: os.environ.get(k) for k in
             ("H2O_TPU_POOL_BACKOFF_BASE", "H2O_TPU_POOL_BACKOFF_MAX",
              "H2O_TPU_POOL_ROLLOUT_RETRIES")}
    os.environ["H2O_TPU_POOL_BACKOFF_BASE"] = str(base_backoff)
    os.environ["H2O_TPU_POOL_BACKOFF_MAX"] = "5"
    os.environ["H2O_TPU_POOL_ROLLOUT_RETRIES"] = str(retries)
    fx = _PoolFixture("poison")
    try:
        # poison v2 IN the registry: the blob no longer matches its
        # indexed digest, so every push of it fails verification and
        # the surge replica can never reach READY
        path = fx.registry.artifact_path("scorer", fx.v2)
        persist.write_bytes(path, b"POISON" + persist.read_bytes(path))

        load_stop = threading.Event()
        result: dict = {}

        def drive():
            result.update(run_load_multi(
                fx.rec.endpoints, "pm", fx.feature_cols,
                concurrency=3, rows_per_request=8,
                stop_event=load_stop))

        lt = threading.Thread(target=drive, daemon=True)
        lt.start()
        time.sleep(1.0)                 # load in flight on v1
        fx.store.apply_update("pool", version=fx.v2)

        # wait for the auto-rollback, sampling old-replica readiness
        # the whole way: the bad push must never disturb them
        ready_samples: list[int] = []
        deadline = time.monotonic() + 120
        rolled = False
        while time.monotonic() < deadline:
            st = fx.store.get_status("pool")
            ready_samples.append(st.get("ready", 0))
            if any(e["kind"] == "rollout_rolled_back"
                   for e in fx.store.events("pool")):
                rolled = True
                break
            time.sleep(0.1)
        _check(rolled, "rollout never rolled back: "
               f"{fx.event_kinds()} {fx.store.get_status('pool')}")
        _check(fx.rec.wait_converged(timeout=60),
               "pool did not re-converge on last-good after the "
               f"rollback: {fx.store.get_status('pool')}")
        time.sleep(1.0)                 # post-rollback traffic window
        load_stop.set()
        lt.join(timeout=60)

        _check(result.get("requests", 0) > 50,
               f"load generator barely ran: {result}")
        _check(result["fivexx"] == 0,
               f"{result['fivexx']} 5xx during the poisoned rollout: "
               f"{result['fivexx_sample']}")
        _check(result["errors"] == 0,
               f"client errors during the poisoned rollout: "
               f"{result['error_sample']}")
        _check(ready_samples and min(ready_samples) >= 2,
               f"old replicas dipped below spec count during the bad "
               f"push: min ready {min(ready_samples or [0])}")

        events = fx.store.events("pool")
        kinds = [e["kind"] for e in events]
        st = fx.store.get_status("pool")
        _check(st.get("rollout", {}).get("pinned_version") == fx.v1
               and st.get("effective_version") == fx.v1
               and st.get("desired_version") == fx.v2,
               f"status does not pin last-good v{fx.v1}: {st}")
        _check("replica_cordon" not in kinds,
               "a READY old replica was cordoned during the failed "
               f"rollout: {kinds}")
        # respawns provably backoff-spaced: starts 3+ of the poisoned
        # version must be >= base (then >= 2*base) apart — a hot
        # respawn loop fails here
        v2_starts = [e["t"] for e in events
                     if e["kind"] == "replica_start"
                     and f"v{fx.v2} " in e["msg"] + " "]
        _check(len(v2_starts) == retries,
               f"expected {retries} poisoned spawns before rollback, "
               f"got {len(v2_starts)}: {kinds}")
        gaps = [b - a for a, b in zip(v2_starts, v2_starts[1:])]
        _check(all(g >= base_backoff - 0.02 for g in gaps[1:]),
               f"respawns not backoff-spaced (base {base_backoff}s): "
               f"gaps {[round(g, 3) for g in gaps]}")
        _check("crash_loop_backoff" in kinds,
               f"no crash_loop_backoff event surfaced: {kinds}")
        # the pool is parked, not wedged: no further poisoned spawns
        n_before = len(v2_starts)
        time.sleep(2.0)
        v2_starts_after = [
            e for e in fx.store.events("pool")
            if e["kind"] == "replica_start"
            and f"v{fx.v2} " in e["msg"] + " "]
        _check(len(v2_starts_after) == n_before,
               "pool kept re-trying the rolled-back version")
        # replicas still serve the last-good artifact
        versions = sorted(r.loaded_version() for r in fx.rec.replicas)
        _check(versions == [fx.v1, fx.v1],
               f"replicas not on last-good v{fx.v1}: {versions}")
    finally:
        fx.close()
        for k, v in saved.items():
            os.environ.pop(k, None)
            if v is not None:
                os.environ[k] = v


class _ShardedFixture:
    """A converged tenant-SHARDED fleet (ISSUE 11): ``shards`` shard
    groups of ``replicas_per_shard`` subprocess pods each, a
    ``tenants``-key catalog rendezvous-placed across them (the first
    ``head`` keys replicated on every shard), every pod under a
    ``budget_bytes`` scorer-cache byte budget, and (optionally) the
    device-free front-door router over the pool's routing table. A
    handful of distinct base GBMs rotate across the tenant keys so
    warm-ups are persistent-cache hits, exactly like the tenant-storm
    fixture. ``shards=1`` degenerates to the everyone-has-everything
    baseline pool (the router bench's direct leg)."""

    def __init__(self, tag: str, tenants: int = 1000, shards: int = 3,
                 head: int = 10, replicas_per_shard: int = 1,
                 budget_bytes: int = 2_500_000, base_variants: int = 3,
                 with_router: bool = True,
                 startup_deadline: float = 600.0,
                 warm_buckets: tuple = (128,)):
        import shutil  # noqa: F401 — close() uses it

        import numpy as np

        import h2o_kubernetes_tpu as h2o
        from h2o_kubernetes_tpu.models import GBM
        from h2o_kubernetes_tpu.operator import (ModelRegistry,
                                                 PoolStore,
                                                 ScorerPoolSpec,
                                                 ShardedPool,
                                                 start_router)

        # hundreds of sequential artifact pushes per shard replica:
        # the stock 180s startup deadline is sized for a handful
        self._env_saved = {"H2O_TPU_POOL_STARTUP_DEADLINE":
                           os.environ.get(
                               "H2O_TPU_POOL_STARTUP_DEADLINE")}
        os.environ["H2O_TPU_POOL_STARTUP_DEADLINE"] = \
            str(startup_deadline)
        self.td = tempfile.mkdtemp(prefix=f"chaos_{tag}_")
        rng = np.random.default_rng(0)
        n = 400
        cols = {f"x{i}": rng.normal(size=n).astype(np.float32)
                for i in range(4)}
        cols["y"] = np.where(cols["x0"] - cols["x1"] > 0, "late",
                             "ontime")
        self.feature_cols = [f"x{i}" for i in range(4)]
        fr = h2o.Frame.from_arrays(cols)
        self.registry = ModelRegistry(os.path.join(self.td,
                                                   "registry"))
        nv = max(1, min(base_variants, tenants))
        arts = []
        for b in range(nv):
            m = GBM(ntrees=2 + b, max_depth=2, seed=b + 1).train(
                y="y", training_frame=fr)
            self.registry.publish(m, f"t{b}")
            arts.append(f"t{b}")
        self.tenant_keys = [f"m{i:03d}" for i in range(tenants)]
        extra = tuple((arts[i % nv], 1, k)
                      for i, k in enumerate(self.tenant_keys)
                      if i > 0)
        self.budget_bytes = budget_bytes
        self.store = PoolStore()
        self.store.apply(ScorerPoolSpec(
            name="pool", artifact=arts[0], version=1,
            model_key=self.tenant_keys[0],
            replicas=replicas_per_shard, shards=shards,
            head_models=max(1, min(head, tenants)), tail_replicas=1,
            warm_buckets=tuple(warm_buckets), extra_artifacts=extra,
            env={"H2O_TPU_SCORER_CACHE_BYTES": str(budget_bytes)}))
        self.pool = ShardedPool(self.store, self.registry, "pool",
                                log_dir=os.path.join(self.td, "logs"))
        self.stop = threading.Event()
        self.thread = threading.Thread(
            target=self.pool.run, args=(self.stop,),
            kwargs={"interval": 0.25}, daemon=True)
        self.thread.start()
        self.router_srv = None
        self.router = None
        self.router_url = None
        try:
            _check(self.pool.wait_converged(
                timeout=startup_deadline + 120),
                f"sharded pool never converged: "
                f"{self.store.get_status('pool')} "
                f"(pod logs under {self.td}/logs)")
            if with_router:
                self.router_srv, self.router = start_router(
                    self.pool.routing_table)
                self.router_url = ("http://127.0.0.1:"
                                   f"{self.router_srv.server_address[1]}")
        except BaseException:
            # raising out of __init__ skips the drill's try/finally —
            # tear the pods down here (logs kept for diagnosis)
            self.close(keep_dir=True)
            raise

    def replica_urls(self) -> list:
        urls = []
        for rec in self.pool.recs.values():
            with rec._lock:
                urls.extend(r.url for r in rec.replicas
                            if r.state != "DEAD")
        return urls

    def event_kinds(self) -> list:
        return [e["kind"] for e in self.store.events("pool")]

    def close(self, keep_dir: bool = False) -> None:
        import shutil

        try:
            if self.router is not None:
                self.router.stop()
            if self.router_srv is not None:
                self.router_srv.shutdown()
                self.router_srv.server_close()
        finally:
            # stop the loop BEFORE tearing pods down: a live
            # _replace_once pass would read the dying fleet as a mass
            # shard-loss and spray shard_down events into the ring
            self.stop.set()
            self.thread.join(timeout=15)
            try:
                self.pool.shutdown(timeout=90)
            finally:
                for k, v in self._env_saved.items():
                    os.environ.pop(k, None)
                    if v is not None:
                        os.environ[k] = v
                if not keep_dir:
                    shutil.rmtree(self.td, ignore_errors=True)


def _score_via_router(url: str, key: str, body: dict,
                      attempts: int = 6, sleep: float = 0.4):
    """POST one scoring request through the router, retrying briefly
    (re-placement pushes may still be landing); returns the last HTTP
    status observed."""
    import urllib.error
    import urllib.request

    code = None
    for _ in range(attempts):
        req = urllib.request.Request(
            f"{url}/3/Predictions/models/{key}",
            data=json.dumps(body).encode(), method="POST",
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                return r.status
        except urllib.error.HTTPError as e:
            code = e.code
            e.read()
        except Exception:  # noqa: BLE001 — transport: retry
            code = -1
        time.sleep(sleep)
    return code


def scenario_router_shard_kill() -> None:
    """The ISSUE-11 acceptance drill: a 3-shard fleet serving a
    1000-tenant Zipf storm through the front-door router loses one
    whole shard mid-run (SIGKILL + its capacity scaled to zero — the
    node pool is gone). Contracts proven:

    - ZERO 5xx for the replicated head tenants across the kill (the
      router fails over inside the retry budget);
    - the victim's tail tenants surface as TYPED degraded 503s
      (placement_pending) — never raw 5xx lies — while the reconciler
      re-places each one onto a surviving shard via a targeted push,
      and every one of them scores 200 through the router WHILE the
      home shard is still gone;
    - per-replica scorer-cache ``resident_bytes`` never exceeds the
      byte budget at any sampled instant;
    - every cross-shard retry was token-backed (``retries ==
      retry_budget.granted`` on the router's /3/Stats) and bounded —
      a dying shard cannot amplify load onto survivors;
    - re-enabling the shard's capacity reconverges the pool
      (shard_down → tenant_replaced* → shard_recovered in events)."""
    import signal

    from tools.score_load import _get_json, _make_bodies, run_load_zipf

    tenants = int(os.environ.get("H2O_TPU_DRILL_ROUTER_TENANTS",
                                 "1000"))
    head_n = 10
    budget = 2_500_000
    saved = {k: os.environ.get(k) for k in
             ("H2O_TPU_ROUTER_RETRY_BUDGET",
              "H2O_TPU_ROUTER_HEALTH_INTERVAL")}
    # burst sized for the in-flight failover wave at the kill instant
    # (the budget must bound amplification, not starve legitimate
    # failover); sweeps fast so the ring reflects the kill quickly
    os.environ["H2O_TPU_ROUTER_RETRY_BUDGET"] = "20"
    os.environ["H2O_TPU_ROUTER_HEALTH_INTERVAL"] = "0.25"
    fx = _ShardedFixture("rshard", tenants=tenants, shards=3,
                         head=head_n, budget_bytes=budget)
    try:
        head_keys = fx.tenant_keys[:head_n]

        # live residency watcher over every pod (budget contract is
        # "never exceeded WHILE the storm runs", sampled, not final)
        resid = {"samples": 0, "max": 0, "exceeded": 0}
        watch_stop = threading.Event()

        def watcher():
            while not watch_stop.is_set():
                for u in fx.replica_urls():
                    st = _get_json(u + "/3/Stats", timeout=2.0)
                    sc = (st or {}).get("scorer_cache") or {}
                    rb = int(sc.get("resident_bytes") or 0)
                    if st:
                        resid["samples"] += 1
                        resid["max"] = max(resid["max"], rb)
                        if rb > budget:
                            resid["exceeded"] += 1
                watch_stop.wait(0.5)

        wt = threading.Thread(target=watcher, daemon=True)
        wt.start()

        storm_out: dict = {}
        storm_stop = threading.Event()

        def storm():
            storm_out.update(run_load_zipf(
                [fx.router_url], fx.tenant_keys, fx.feature_cols,
                concurrency=6, rows_per_request=8, seconds=30.0,
                zipf_s=1.1, seed=0, router=True,
                stop_event=storm_stop))

        st_thread = threading.Thread(target=storm, daemon=True)
        st_thread.start()
        time.sleep(6.0)                    # storm established

        # victim: any shard that uniquely holds tail tenants
        victim = next(sid for sid in fx.pool.recs
                      if set(fx.pool.plan.keys_for(sid))
                      - set(head_keys))
        orphans = sorted(set(fx.pool.plan.keys_for(victim))
                         - set(head_keys))
        _check(len(orphans) >= max(2, (tenants - head_n) // 10),
               f"victim shard {victim} holds only {len(orphans)} tail "
               "tenants — fixture shape wrong")
        vrec = fx.pool.recs[victim]
        with vrec._lock:
            victims = list(vrec.replicas)
        for r in victims:
            if r.pid():
                try:
                    os.kill(r.pid(), signal.SIGKILL)
                except OSError:
                    pass
        # the node pool behind the shard is GONE: no capacity to
        # respawn into until recovery is re-enabled below
        fx.store.apply_update(victim, replicas=0)

        # the event ring is BOUNDED (256): ~330 tenant_replaced
        # events will evict the earlier shard_down entry, so the
        # event contract is checked against an incremental union of
        # snapshots, not one final read
        seen_kinds: set = set()

        # the reconciler re-places every orphan via targeted pushes
        deadline = time.monotonic() + 420
        while time.monotonic() < deadline:
            seen_kinds.update(fx.event_kinds())
            if all(k in fx.pool.overrides for k in orphans):
                break
            time.sleep(0.5)
        missing = [k for k in orphans if k not in fx.pool.overrides]
        _check(not missing,
               f"{len(missing)}/{len(orphans)} tail tenants never "
               f"re-placed (sample {missing[:5]}): "
               f"{fx.store.get_status('pool')}")

        # every orphan serves through the router off a SURVIVOR while
        # the home shard is still dead
        _check(not fx.pool.shard_healthy(victim),
               "victim shard resurrected before re-placement was "
               "verified — drill invalid")
        body = _make_bodies(fx.feature_cols, 4, seed=1, pool=1)[0]
        failed = []
        for k in orphans:
            code = _score_via_router(fx.router_url, k, body)
            if code != 200:
                failed.append((k, code))
        _check(not failed,
               f"{len(failed)} re-placed tenants not serving via "
               f"survivors (sample {failed[:5]})")

        storm_stop.set()
        st_thread.join(timeout=120)
        watch_stop.set()
        wt.join(timeout=10)

        _check(storm_out.get("requests", 0) > 200,
               f"Zipf storm barely ran: {storm_out}")
        _check(storm_out["errors"] == 0,
               f"client transport errors during the storm: "
               f"{storm_out['error_sample']}")
        head_5xx = sum(storm_out["by_model"][k]["fivexx"]
                       for k in head_keys)
        _check(head_5xx == 0,
               f"{head_5xx} 5xx on replicated HEAD tenants across the "
               f"shard kill: {storm_out['fivexx_sample']}")
        _check(storm_out.get("degraded", 0) > 0,
               "no typed degraded 503 observed — the kill window "
               "never exercised degraded mode (storm/kill timing "
               "broken)")
        _check(resid["samples"] > 10, "residency watcher never ran")
        _check(resid["exceeded"] == 0 and resid["max"] <= budget,
               f"scorer-cache resident bytes exceeded the "
               f"{budget}B budget: {resid}")

        rst = _get_json(fx.router_url + "/3/Stats", timeout=5.0)
        _check(rst is not None, "router /3/Stats unreachable")
        rstats, rbudget = rst["stats"], rst["retry_budget"]
        _check(rstats["retries"] == rbudget["granted"],
               f"cross-shard retries not token-backed: {rstats} "
               f"{rbudget}")
        _check(rstats["retries"] <= 200,
               f"retry amplification past the budget's intent: "
               f"{rstats}")
        _check(rstats["degraded_503"] > 0,
               f"router never served the typed degraded 503: {rstats}")

        # recovery: capacity returns, the shard reloads its catalog
        # and the pool reconverges
        fx.store.apply_update(victim, replicas=1)
        _check(fx.pool.wait_converged(timeout=600),
               f"pool never reconverged after shard recovery: "
               f"{fx.store.get_status('pool')}")
        code = _score_via_router(fx.router_url, orphans[0], body)
        _check(code == 200,
               f"native tenant not serving after shard recovery "
               f"(HTTP {code})")
        # the recovery event lands on the loop's NEXT replace pass —
        # poll briefly instead of racing it
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            seen_kinds.update(fx.event_kinds())
            if "shard_recovered" in seen_kinds:
                break
            time.sleep(0.25)
        seen_kinds.update(fx.event_kinds())
        for needed in ("shard_down", "tenant_replaced",
                       "shard_recovered"):
            _check(needed in seen_kinds,
                   f"event '{needed}' missing from the pool's event "
                   f"log: {sorted(seen_kinds)}")
    finally:
        fx.close()
        for k, v in saved.items():
            os.environ.pop(k, None)
            if v is not None:
                os.environ[k] = v


def scenario_trace_failover() -> None:
    """Fleet-telemetry acceptance drill (ISSUE 14): kill a replica
    under the router, then send one traced scoring request whose
    round-robin primary is the corpse. Asserts the trace id survives
    the intra-shard failover retry (router span record: >=1
    transport_error attempt, exactly ONE terminal `forwarded`
    dispatch), the survivor's /3/Trace/{id} carries the full
    queue/batch/dispatch span decomposition with exactly one dispatch,
    and /metrics on the router + survivor expose the failover/request
    counters plus the build-info block — the end-to-end proof that one
    scrape + one trace id explain a request that crossed a dying
    fleet."""
    import signal
    import urllib.request

    from h2o_kubernetes_tpu.operator.router import start_router
    from h2o_kubernetes_tpu.runtime import telemetry

    fx = _PoolFixture("tracefail")
    saved_hi = os.environ.get("H2O_TPU_ROUTER_HEALTH_INTERVAL")
    # freeze the health ring after the initial sweep: the drill needs
    # the dead replica still listed READY so the REQUEST performs the
    # failover (not the sweep quietly removing the corpse first)
    os.environ["H2O_TPU_ROUTER_HEALTH_INTERVAL"] = "3600"
    srv = router = None
    try:
        victim, survivor = fx.rec.replicas[0], fx.rec.replicas[1]
        # victim FIRST in the shard's replica list: round-robin starts
        # at 0, so the first routed request's primary is the corpse
        table = {"keys": {"pm": ["s0"]},
                 "shards": {"s0": [victim.url, survivor.url]}}
        srv, router = start_router(table)
        rurl = f"http://127.0.0.1:{srv.server_address[1]}"
        _check(router.any_shard_healthy(),
               "router never saw a healthy shard")
        os.kill(victim.pid(), signal.SIGKILL)
        deadline = time.monotonic() + 10
        while victim.alive() and time.monotonic() < deadline:
            time.sleep(0.02)
        _check(not victim.alive(), "SIGKILL did not kill the victim")

        body = json.dumps({"rows": [
            {c: 0.25 for c in fx.feature_cols}] * 4}).encode()
        req = urllib.request.Request(
            f"{rurl}/3/Predictions/models/pm", data=body,
            method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            _check(r.status == 200,
                   f"routed request not 200: {r.status}")
            tid = r.headers.get("X-H2O-Trace-Id")
            payload = json.loads(r.read())
        _check(bool(tid), "router response carries no X-H2O-Trace-Id")
        _check("predict" in payload or "pontime" in payload,
               f"unexpected scoring payload keys: "
               f"{sorted(payload)[:6]}")

        # router half of the trace: the failover is VISIBLE — the dead
        # primary's attempt recorded, exactly one terminal dispatch
        with urllib.request.urlopen(f"{rurl}/3/Trace/{tid}",
                                    timeout=30) as r:
            rtrace = json.loads(r.read())
        disp = [s for s in rtrace["spans"] if s["name"] == "dispatch"]
        fwd = [s for s in disp if s["outcome"] == "forwarded"]
        terr = [s for s in disp
                if s["outcome"] == "transport_error"]
        _check(len(fwd) == 1,
               f"want exactly 1 terminal forwarded dispatch, got "
               f"{len(fwd)}: {rtrace['spans']}")
        _check(len(terr) >= 1,
               f"dead-primary attempt not recorded: {rtrace['spans']}")

        # survivor half: same trace id, full per-hop decomposition,
        # exactly one device dispatch for the whole failover story
        with urllib.request.urlopen(
                f"{survivor.url}/3/Trace/{tid}", timeout=30) as r:
            strace = json.loads(r.read())
        names = [s["name"] for s in strace["spans"]]
        for want in ("admission", "queue", "assemble", "dispatch",
                     "total"):
            _check(want in names,
                   f"survivor trace missing span '{want}': {names}")
        _check(names.count("dispatch") == 1,
               f"survivor recorded {names.count('dispatch')} device "
               f"dispatches for one request: {names}")

        # /metrics on both hops: failover counters + build identity
        with urllib.request.urlopen(f"{rurl}/metrics",
                                    timeout=30) as r:
            rmet = telemetry.parse_prometheus_text(r.read().decode())

        def rv(name, **lbls):
            return rmet.get((name, tuple(sorted(lbls.items()))), 0.0)

        _check(rv("h2o_stats_router_stats_transport_errors") >= 1,
               "router /metrics missing the transport-error count")
        _check(rv("h2o_stats_router_stats_failovers") >= 1,
               "router /metrics missing the failover count")
        # per-tenant no-double-count: asserted on THIS router's own
        # counters (snapshot + /3/Stats by_model), NOT the global
        # registry label — earlier drills in the same process (the
        # 1000-tenant shard-kill) legitimately fill the capped
        # top-K label set, rolling a one-request tenant into `other`
        snap = router.snapshot()
        _check(snap["by_model"].get("pm") == 1
               and snap["stats"]["forwarded"] == 1,
               "tenant forwarded counter != 1 after one request "
               f"(by_model={snap['by_model']}, "
               f"forwarded={snap['stats']['forwarded']})")
        _check(any(k[0] == "h2o_build_info" for k in rmet),
               "router /metrics missing h2o_build_info")
        with urllib.request.urlopen(f"{survivor.url}/metrics",
                                    timeout=30) as r:
            smet = telemetry.parse_prometheus_text(r.read().decode())
        sm = {k[0] for k in smet}
        for want in ("h2o_stats_batcher_requests", "h2o_build_info",
                     "h2o_request_phase_seconds_bucket"):
            _check(want in sm, f"survivor /metrics missing {want}")
        with urllib.request.urlopen(f"{survivor.url}/3/Stats",
                                    timeout=30) as r:
            st = json.loads(r.read())
        _check(isinstance(st.get("build"), dict)
               and st["build"].get("version")
               and st["build"].get("pid"),
               f"survivor /3/Stats missing the build block: "
               f"{st.get('build')}")

        # the operator's one-screen aggregator reads both hops
        from tools.fleet_top import scrape as ft_scrape

        row_r = ft_scrape(rurl)
        row_s = ft_scrape(survivor.url)
        _check(row_r["up"] and row_r["kind"] == "router",
               f"fleet_top cannot read the router: {row_r}")
        _check(row_s["up"] and row_s["kind"] == "replica"
               and row_s["requests"] >= 1,
               f"fleet_top cannot read the survivor: {row_s}")
    finally:
        if saved_hi is None:
            os.environ.pop("H2O_TPU_ROUTER_HEALTH_INTERVAL", None)
        else:
            os.environ["H2O_TPU_ROUTER_HEALTH_INTERVAL"] = saved_hi
        if router is not None:
            router.stop()
        if srv is not None:
            srv.shutdown()
            srv.server_close()
        fx.close()


def _post_raw(url: str, key: str, body: dict,
              headers: dict | None = None,
              timeout: float = 30.0) -> tuple:
    """POST one scoring request directly; returns (status, bytes) —
    the bitwise-comparison primitive (same artifact + same rows must
    produce byte-identical predictions on any replica serving them)."""
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        f"{url}/3/Predictions/models/{key}",
        data=json.dumps(body).encode(), method="POST",
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()
    except Exception:  # noqa: BLE001 — transport
        return -1, b""


def scenario_router_ha_kill() -> None:
    """The ISSUE-16 acceptance drill: the whole FRONT DOOR goes highly
    available. A 3-shard fleet is run by TWO ``operator.run --ha``
    replicas (lease-fenced: exactly one reconciles) and fronted by TWO
    stateless router processes reading the store-backed routing table.
    Under a live Zipf storm the drill:

    - floods one tail tenant with 1 ms-deadline requests until its
      per-tenant 504 pressure sustains and the holder REBALANCES it
      (make-before-break: the destination serves bitwise-identical
      predictions while the source still serves);
    - SIGKILLs one router AND the lease holder simultaneously: the
      storm fails over to the surviving router with zero client
      errors, the standby takes the lease (epoch+1) within TTL +
      heartbeat of the dead holder's last renewal, adopts every pod
      (same pids — zero respawns), RESUMES the in-flight move, and a
      routing publish fenced on the dead holder's epoch is provably
      rejected (StaleGenerationError);
    - after the move's dwell the NEW holder retires the source (the
      move record survived takeover through the status doc);
    - then loses a whole shard (loss-driven overrides re-place its
      tenants onto survivors) and recovers it: failback EMPTIES the
      overrides once the home shard is provably healthy again;
    - end to end: zero 5xx on the replicated head tenants, zero
      client transport errors, and ``retries == granted`` on the
      surviving router."""
    import re
    import shutil
    import signal
    import subprocess

    import numpy as np

    import h2o_kubernetes_tpu as h2o
    from h2o_kubernetes_tpu.models import GBM
    from h2o_kubernetes_tpu.operator import (DurablePoolStore,
                                             ModelRegistry,
                                             ScorerPoolSpec,
                                             StaleGenerationError)
    from tools.score_load import _get_json, _make_bodies, run_load_zipf

    tenants = int(os.environ.get("H2O_TPU_DRILL_HA_TENANTS", "60"))
    head_n = 6
    ttl, hb = 4.0, 0.5
    retire_s, failback_s = 8.0, 4.0
    td = tempfile.mkdtemp(prefix="chaos_rhakill_")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    storedir = os.path.join(td, "store")
    workdir = os.path.join(td, "work")
    regdir = os.path.join(td, "registry")
    procs: dict = {}
    # subprocess-only env: the drill process itself keeps its own
    ha_env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        H2O_TPU_LEASE_TTL=str(ttl), H2O_TPU_LEASE_HEARTBEAT=str(hb),
        H2O_TPU_REBALANCE="1", H2O_TPU_REBALANCE_SUSTAIN="2",
        H2O_TPU_REBALANCE_COOLDOWN="2",
        H2O_TPU_REBALANCE_RETIRE_S=str(retire_s),
        H2O_TPU_REBALANCE_FAILBACK_S=str(failback_s),
        H2O_TPU_POOL_STARTUP_DEADLINE="600",
        H2O_TPU_ROUTER_RETRY_BUDGET="20",
        H2O_TPU_ROUTER_HEALTH_INTERVAL="0.25",
        H2O_TPU_ROUTER_TABLE_INTERVAL="0.25")
    try:
        rng = np.random.default_rng(0)
        n = 400
        cols = {f"x{i}": rng.normal(size=n).astype(np.float32)
                for i in range(4)}
        cols["y"] = np.where(cols["x0"] - cols["x1"] > 0, "late",
                             "ontime")
        feature_cols = [f"x{i}" for i in range(4)]
        fr = h2o.Frame.from_arrays(cols)
        registry = ModelRegistry(regdir)
        arts = []
        for b in range(2):
            m = GBM(ntrees=2 + b, max_depth=2, seed=b + 1).train(
                y="y", training_frame=fr)
            registry.publish(m, f"t{b}")
            arts.append(f"t{b}")
        keys = [f"m{i:03d}" for i in range(tenants)]
        head_keys = keys[:head_n]
        extra = tuple((arts[i % 2], 1, k)
                      for i, k in enumerate(keys) if i > 0)
        store = DurablePoolStore(storedir)
        store.apply(ScorerPoolSpec(
            name="pool", artifact=arts[0], version=1,
            model_key=keys[0], replicas=1, shards=3,
            head_models=head_n, tail_replicas=1, warm_buckets=(128,),
            extra_artifacts=extra))

        def spawn_operator(tag: str):
            log = open(os.path.join(td, f"operator_{tag}.log"), "ab")
            p = subprocess.Popen(
                [sys.executable, "-m",
                 "h2o_kubernetes_tpu.operator.run",
                 "--store", storedir, "--registry", regdir,
                 "--pool", "pool", "--workdir", workdir,
                 "--interval", "0.25", "--ha", "--holder-id", tag],
                cwd=repo, env=ha_env, stdout=log, stderr=log,
                start_new_session=True)
            procs[tag] = p
            return p

        def spawn_router(tag: str) -> str:
            logp = os.path.join(td, f"{tag}.log")
            log = open(logp, "ab")
            p = subprocess.Popen(
                [sys.executable, "-m",
                 "h2o_kubernetes_tpu.operator.router",
                 "--store", storedir, "--pool", "pool", "--port", "0"],
                cwd=repo, env=ha_env, stdout=log, stderr=log,
                start_new_session=True)
            procs[tag] = p
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                with open(logp, "rb") as f:
                    txt = f.read().decode(errors="replace")
                mm = re.search(r"ROUTER_UP port=(\d+)", txt)
                if mm:
                    return f"http://127.0.0.1:{mm.group(1)}"
                _check(p.poll() is None,
                       f"{tag} died at startup: {txt[-400:]}")
                time.sleep(0.2)
            raise ChaosFailure(f"{tag} never printed ROUTER_UP")

        def wait_status(pred, timeout: float, what: str) -> dict:
            deadline = time.monotonic() + timeout
            st = store.get_status("pool") or {}
            while time.monotonic() < deadline:
                st = store.get_status("pool") or {}
                if pred(st):
                    return st
                time.sleep(0.25)
            raise ChaosFailure(f"timed out waiting for {what}: {st} "
                               f"(logs under {td})")

        spawn_operator("op-a")
        spawn_operator("op-b")
        st = wait_status(lambda s: s.get("converged"), 600,
                         "the HA fleet to converge")
        lease = store.get_lease("pool")
        _check(lease is not None and not lease.get("released")
               and lease.get("holder") in ("op-a", "op-b"),
               f"no live lease after convergence: {lease}")
        rdoc = store.get_routing("pool")
        _check(rdoc is not None
               and int(rdoc.get("table_generation", 0)) >= 1
               and rdoc.get("keys"),
               f"holder never published a routing table: {rdoc}")

        url_a = spawn_router("router-a")
        url_b = spawn_router("router-b")
        body = _make_bodies(feature_cols, 8, seed=1, pool=1)[0]
        for u in (url_a, url_b):
            code = _score_via_router(u, keys[0], body)
            _check(code == 200,
                   f"store-backed router {u} not serving the head "
                   f"tenant (HTTP {code})")
        # N routers, ONE table: both converge on the store generation
        gens = [(_get_json(u + "/3/Stats", timeout=5.0) or {})
                .get("table_generation") for u in (url_a, url_b)]
        _check(gens[0] is not None and gens[0] == gens[1]
               and gens[0] >= rdoc["table_generation"],
               f"routers disagree on table_generation: {gens} vs "
               f"store {rdoc['table_generation']}")

        storm_out: dict = {}
        storm_stop = threading.Event()

        def storm():
            storm_out.update(run_load_zipf(
                [url_a, url_b], keys, feature_cols, concurrency=4,
                rows_per_request=8, seconds=900.0, zipf_s=1.1, seed=0,
                router=True, stop_event=storm_stop))

        st_thread = threading.Thread(target=storm, daemon=True)
        st_thread.start()
        time.sleep(4.0)                     # storm established

        # -- phase 1: sustained-pressure rebalance (make-before-break)
        hot = next(k for k in reversed(keys) if k not in head_keys
                   and len(rdoc["keys"].get(k) or ()) == 1)
        hot_src = rdoc["keys"][hot][0]
        src_reps = [r for r in st["shards"][hot_src]["replicas"]
                    if r["state"] == "READY"]
        _check(src_reps, f"no READY replica on shard {hot_src}")
        src_url = f"http://127.0.0.1:{src_reps[0]['port']}"
        flood_stop = threading.Event()

        def flood():
            # 1 ms deadlines 504 inside the hot shard's batcher: the
            # per-tenant deadline_504 counter attributes the pressure
            # to `hot` alone — nobody else sheds
            while not flood_stop.is_set():
                _post_raw(src_url, hot, body,
                          headers={"X-H2O-Deadline-Ms": "1"},
                          timeout=10.0)
                time.sleep(0.02)

        fl = threading.Thread(target=flood, daemon=True)
        fl.start()
        mv = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            stx = store.get_status("pool") or {}
            mv = ((stx.get("placement") or {}).get("moves")
                  or {}).get(hot)
            if mv:
                break
            time.sleep(0.3)
        flood_stop.set()
        fl.join(timeout=10)
        _check(mv is not None,
               f"sustained 504 pressure on '{hot}' never triggered a "
               f"rebalance move: {store.get_status('pool')}")
        _check(mv["src"] == hot_src and mv["state"] == "serving",
               f"move record wrong: {mv} (expected src={hot_src}, "
               "state=serving)")
        dst = mv["dst"]

        # make-before-break: while the move is `serving`, BOTH shards
        # serve the tenant and the destination's predictions are
        # bitwise-identical to the source's
        stx = store.get_status("pool")
        dst_reps = [r for r in stx["shards"][dst]["replicas"]
                    if r["state"] == "READY"]
        _check(dst_reps, f"move destination {dst} has no READY "
               "replica — the 'make' half did not hold")
        dst_url = f"http://127.0.0.1:{dst_reps[0]['port']}"
        c_src, b_src = _post_raw(src_url, hot, body)
        c_dst, b_dst = _post_raw(dst_url, hot, body)
        _check(c_src == 200 and c_dst == 200,
               f"mid-move scoring failed: src HTTP {c_src}, "
               f"dst HTTP {c_dst}")
        _check(b_src == b_dst,
               "make-before-break violated: destination predictions "
               f"differ from source (src {b_src[:80]!r} vs dst "
               f"{b_dst[:80]!r})")
        # the routing table prefers dst while src still serves
        deadline = time.monotonic() + 15
        pref: list = []
        while time.monotonic() < deadline:
            rdoc = store.get_routing("pool") or {}
            pref = list((rdoc.get("keys") or {}).get(hot) or ())
            if pref and pref[0] == dst and hot_src in pref:
                break
            time.sleep(0.25)
        _check(pref and pref[0] == dst and hot_src in pref,
               f"mid-move routing should prefer {dst} with {hot_src} "
               f"still serving, got {pref}")

        # -- phase 2: SIGKILL a router AND the lease holder together
        lease = store.get_lease("pool")
        holder, old_epoch = lease["holder"], int(lease["epoch"])
        standby = "op-b" if holder == "op-a" else "op-a"
        pods_before = sorted(p for p, _ in _live_pods_for(workdir))
        procs["router-a"].kill()
        procs[holder].kill()
        lease_at_kill = store.get_lease("pool")   # final heartbeat
        new_lease = None
        deadline = time.monotonic() + ttl + 60
        while time.monotonic() < deadline:
            new_lease = store.get_lease("pool")
            if new_lease and new_lease.get("holder") == standby:
                break
            time.sleep(0.1)
        _check(new_lease is not None
               and new_lease.get("holder") == standby,
               f"standby {standby} never took the lease: {new_lease}")
        _check(int(new_lease["epoch"]) == old_epoch + 1,
               f"takeover must bump the epoch exactly once: "
               f"{old_epoch} -> {new_lease['epoch']}")
        lag = float(new_lease["acquired"]) \
            - float(lease_at_kill["renewed"])
        _check(lag <= ttl + hb + 2.0,
               f"takeover took {lag:.1f}s from the dead holder's last "
               f"heartbeat (ttl={ttl:g} hb={hb:g})")

        # the fence: a routing publish carrying the DEAD holder's
        # epoch must be rejected — split-brain resolves to one writer
        try:
            store.publish_routing("pool", {"keys": {}, "shards": {}},
                                  epoch=old_epoch)
            raise ChaosFailure(
                "a routing publish fenced on the deposed holder's "
                "epoch was ACCEPTED — split-brain is possible")
        except StaleGenerationError:
            pass

        # adoption, not respawn: the new holder converges on the SAME
        # pod pids and its status carries the new epoch
        wait_status(lambda s: s.get("converged")
                    and s.get("lease_epoch") == old_epoch + 1,
                    300, "the new holder to adopt and reconverge")
        pods_after = sorted(p for p, _ in _live_pods_for(workdir))
        _check(pods_after == pods_before,
               f"takeover changed the pod set (respawn/leak): "
               f"{pods_before} -> {pods_after}")
        seen_kinds = set()
        for pool_name in ["pool"] + list(stx["shards"]):
            try:
                seen_kinds.update(e["kind"]
                                  for e in store.events(pool_name))
            except KeyError:
                pass
        _check("replica_adopted" in seen_kinds,
               f"no replica_adopted event after takeover: "
               f"{sorted(seen_kinds)}")

        # -- phase 3: the NEW holder retires the in-flight move (the
        # move record survived takeover through the status doc)
        deadline = time.monotonic() + 120
        retired = False
        while time.monotonic() < deadline:
            stx = store.get_status("pool") or {}
            m3 = ((stx.get("placement") or {}).get("moves")
                  or {}).get(hot)
            if m3 and m3.get("state") == "retired":
                retired = True
                break
            time.sleep(0.3)
        _check(retired,
               f"the new holder never retired the move of '{hot}': "
               f"{(stx.get('placement') or {}).get('moves')}")
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            rdoc = store.get_routing("pool") or {}
            pref = list((rdoc.get("keys") or {}).get(hot) or ())
            if pref and pref[0] == dst and hot_src not in pref:
                break
            time.sleep(0.25)
        _check(pref and pref[0] == dst and hot_src not in pref,
               f"retired source {hot_src} still routed for '{hot}': "
               f"{pref}")
        code = _score_via_router(url_b, hot, body)
        _check(code == 200, f"moved tenant '{hot}' not serving via "
               f"the surviving router after retirement (HTTP {code})")

        # -- phase 4: loss-driven overrides, then failback hygiene
        rdoc = store.get_routing("pool")
        stx = store.get_status("pool")
        orphan_by_sid = {
            sid: [k for k in keys if k not in head_keys and k != hot
                  and list(rdoc["keys"].get(k) or ()) == [sid]]
            for sid in stx["shards"] if sid != dst}
        vsid = max(orphan_by_sid, key=lambda s: len(orphan_by_sid[s]))
        orphans = orphan_by_sid[vsid]
        _check(len(orphans) >= 2,
               f"shard {vsid} uniquely holds only {len(orphans)} "
               "tail tenants — fixture shape wrong")
        for r in stx["shards"][vsid]["replicas"]:
            if r.get("pid"):
                try:
                    os.kill(r["pid"], signal.SIGKILL)
                except OSError:
                    pass
        store.apply_update(vsid, replicas=0)   # the node pool is gone
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            stx = store.get_status("pool") or {}
            ov = (stx.get("placement") or {}).get("overrides") or {}
            if all(k in ov for k in orphans):
                break
            time.sleep(0.5)
        ov = (stx.get("placement") or {}).get("overrides") or {}
        missing = [k for k in orphans if k not in ov]
        _check(not missing,
               f"{len(missing)}/{len(orphans)} lost tenants never "
               f"re-placed (sample {missing[:5]}): {stx}")
        for k in orphans[:3]:
            code = _score_via_router(url_b, k, body)
            _check(code == 200,
                   f"re-placed tenant '{k}' not serving via a "
                   f"survivor (HTTP {code})")
        # recovery: capacity returns; once the home shard is provably
        # healthy for H2O_TPU_REBALANCE_FAILBACK_S the override copies
        # age out — the overrides map EMPTIES
        store.apply_update(vsid, replicas=1)
        wait_status(lambda s: s.get("converged"), 600,
                    "the recovered shard to reconverge")
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            stx = store.get_status("pool") or {}
            ov = (stx.get("placement") or {}).get("overrides") or {}
            if not ov:
                break
            time.sleep(0.5)
        _check(not ov,
               f"failback never emptied the overrides: {ov}")
        seen_kinds.update(e["kind"] for e in store.events("pool"))
        _check("tenant_failback" in seen_kinds,
               f"no tenant_failback event: {sorted(seen_kinds)}")
        code = _score_via_router(url_b, orphans[0], body)
        _check(code == 200,
               f"failed-back tenant not serving from its home shard "
               f"(HTTP {code})")

        # -- epilogue: the storm's end-to-end contracts
        storm_stop.set()
        st_thread.join(timeout=120)
        _check(storm_out.get("requests", 0) > 300,
               f"Zipf storm barely ran: {storm_out}")
        _check(storm_out["errors"] == 0,
               f"client transport errors across the HA kill: "
               f"{storm_out['error_sample']}")
        _check(storm_out.get("target_failovers", 0) > 0,
               "the router kill never exercised client-side target "
               "failover — the drill timing is broken")
        head_5xx = sum(storm_out["by_model"][k]["fivexx"]
                       for k in head_keys)
        _check(head_5xx == 0,
               f"{head_5xx} 5xx on replicated HEAD tenants across the "
               f"router+holder kill: {storm_out['fivexx_sample']}")
        rst = _get_json(url_b + "/3/Stats", timeout=5.0)
        _check(rst is not None,
               "surviving router /3/Stats unreachable")
        _check(rst["stats"]["retries"] ==
               rst["retry_budget"]["granted"],
               f"retries not token-backed on the surviving router: "
               f"{rst['stats']} {rst['retry_budget']}")
        final_gen = (store.get_routing("pool")
                     or {}).get("table_generation")
        _check(rst.get("table_generation") is not None
               and rst["table_generation"] <= final_gen,
               f"surviving router claims a table generation the store "
               f"never published: {rst.get('table_generation')} > "
               f"{final_gen}")
    finally:
        import signal as _sig

        for p in procs.values():
            if p.poll() is None:
                p.kill()
        for p in procs.values():
            try:
                p.wait(timeout=15)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        for pid, _ in _live_pods_for(workdir):
            try:
                os.kill(pid, _sig.SIGKILL)
            except OSError:
                pass
        shutil.rmtree(td, ignore_errors=True)


SCENARIOS = {
    "persist-503": scenario_persist_503,
    "probe-hang": scenario_probe_hang,
    "device-error": scenario_device_error,
    "resume": scenario_resume,
    "score-under-fault": scenario_score_under_fault,
    "ingest-truncated-csv": scenario_ingest_truncated_csv,
    "breaker-trip": scenario_breaker_trip,
    "drain-under-load": scenario_drain_under_load,
    "automl-pipelined-fault": scenario_automl_pipelined_fault,
    "rolling-update": scenario_rolling_update,
    "replica-kill": scenario_replica_kill,
    "tenant-storm": scenario_tenant_storm,
    "operator-restart": scenario_operator_restart,
    "poison-rollback": scenario_poison_rollback,
    "router-shard-kill": scenario_router_shard_kill,
    "trace-failover": scenario_trace_failover,
    "router-ha-kill": scenario_router_ha_kill,
}


def main(argv: list[str]) -> int:
    names = argv or ["all"]
    if names == ["all"]:
        names = list(SCENARIOS)
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        print(f"unknown scenario(s): {', '.join(unknown)} — choose from "
              f"{', '.join(SCENARIOS)} or 'all'", file=sys.stderr)
        return 2
    from h2o_kubernetes_tpu.runtime import make_mesh, set_global_mesh
    from h2o_kubernetes_tpu.runtime.telemetry import build_info

    # every drill artifact states which build produced it
    print(f"[chaos] build={json.dumps(build_info())}")
    set_global_mesh(make_mesh())
    for name in names:
        t0 = time.monotonic()
        try:
            SCENARIOS[name]()
        except ChaosFailure as e:
            print(f"[chaos] {name}: FAIL — {e}", file=sys.stderr)
            return 1
        except Exception as e:  # noqa: BLE001 — a crash is also a fail
            import traceback

            traceback.print_exc()
            print(f"[chaos] {name}: ERROR — {e!r}", file=sys.stderr)
            return 1
        print(f"[chaos] {name}: PASS ({time.monotonic() - t0:.1f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
