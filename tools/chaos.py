#!/usr/bin/env python
"""Chaos drill CLI — rehearse failure scenarios on CPU, exit nonzero
if recovery fails.

Runs a short GBM train (and, for the resume scenario, a small AutoML
run) under a named fault scenario from the fault-injection harness
(h2o_kubernetes_tpu/runtime/faults.py) and asserts the system recovers
the way docs/RESILIENCE.md promises. Intended for CI gates and for
operators validating a new image before it meets real traffic.

Usage::

    python tools/chaos.py persist-503
    python tools/chaos.py all            # every scenario, first failure wins

Scenarios:

- ``persist-503``   HTTP 503 burst on the persist path: a model save
  to s3:// must land after retries — and must FAIL when the retry
  layer is disabled (proving the fault exercises the path).
- ``probe-hang``    the heartbeat probe wedges: unhealthy at the
  deadline, no probe-thread pileup, recovery after reset().
- ``device-error``  a device error escapes a GBM training step: the
  cloud locks, retraining without a restart fails fast, restart works.
- ``resume``        device error mid-AutoML with a checkpoint_dir: the
  rerun resumes finished steps instead of retraining them.
- ``score-under-fault``  REST scoring during a probe-hang unhealthy
  episode: requests must fail FAST with 503 (never queue behind the
  micro-batcher indefinitely) and recover after ``health.reset()``.
- ``ingest-truncated-csv``  a CSV stream aborts mid-file: the parse
  must fail cleanly on BOTH the streamed arrow reader and the
  pure-Python parser — never ship a short frame.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time

# chaos drills always run on the virtual-CPU mesh: they rehearse
# failures, they must not depend on (or wedge) a real chip
os.environ["JAX_PLATFORMS"] = "cpu"
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8"
                               ).strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


class ChaosFailure(AssertionError):
    """A scenario's recovery contract was broken."""


def _check(cond: bool, msg: str) -> None:
    if not cond:
        raise ChaosFailure(msg)


def _frame(n=160, seed=7):
    import numpy as np

    import h2o_kubernetes_tpu as h2o

    rng = np.random.default_rng(seed)
    x = rng.normal(size=n).astype(np.float32)
    y = np.where(x + rng.normal(scale=0.4, size=n) > 0, "p", "n")
    return h2o.Frame.from_arrays({"x": x, "y": y})


def _fake_store():
    """In-process object store for s3:// drills; returns (server, url)."""
    from http.server import BaseHTTPRequestHandler, HTTPServer

    class Store(BaseHTTPRequestHandler):
        store: dict[str, bytes] = {}

        def log_message(self, *a):
            pass

        def do_GET(self):
            key = self.path.split("?", 1)[0]
            if key not in self.store:
                self.send_response(404)
                self.end_headers()
                return
            body = self.store[key]
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_PUT(self):
            n = int(self.headers.get("Content-Length", 0))
            self.store[self.path.split("?", 1)[0]] = self.rfile.read(n)
            self.send_response(200)
            self.end_headers()

        do_POST = do_PUT

    srv = HTTPServer(("127.0.0.1", 0), Store)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"http://127.0.0.1:{srv.server_port}", Store


def scenario_persist_503() -> None:
    import h2o_kubernetes_tpu as h2o
    from h2o_kubernetes_tpu.runtime import faults

    srv, url, store = _fake_store()
    saved = {k: os.environ.get(k) for k in
             ("AWS_ENDPOINT_URL", "AWS_ACCESS_KEY_ID",
              "AWS_SECRET_ACCESS_KEY", "H2O_TPU_RETRY_BASE")}
    os.environ["AWS_ENDPOINT_URL"] = url
    os.environ.pop("AWS_ACCESS_KEY_ID", None)
    os.environ.pop("AWS_SECRET_ACCESS_KEY", None)
    os.environ["H2O_TPU_RETRY_BASE"] = "0.02"
    try:
        fr = _frame()
        from h2o_kubernetes_tpu.models import GBM

        m = GBM(ntrees=3, max_depth=2, seed=0).train(
            y="y", training_frame=fr)
        with faults.inject("persist.http:http_503*2"):
            h2o.save_model(m, "s3://bkt/chaos/gbm.model")
        _check("/bkt/chaos/gbm.model" in store.store,
               "model save did not land after the 503 burst")
        m2 = h2o.load_model("s3://bkt/chaos/gbm.model")
        _check(m2.predict(fr).nrows == fr.nrows,
               "reloaded model does not predict")
        # negative control: same burst, retries disabled -> must fail
        os.environ["H2O_TPU_RETRY_DISABLE"] = "1"
        try:
            with faults.inject("persist.http:http_503*2"):
                try:
                    h2o.save_model(m, "s3://bkt/chaos/nope.model")
                except IOError:
                    pass
                else:
                    raise ChaosFailure(
                        "save survived a 503 burst with retries "
                        "DISABLED — the fault is not exercising the "
                        "retry path")
        finally:
            os.environ.pop("H2O_TPU_RETRY_DISABLE", None)
    finally:
        srv.shutdown()
        for k, v in saved.items():     # no leaks into later scenarios
            os.environ.pop(k, None)
            if v is not None:
                os.environ[k] = v


def scenario_probe_hang() -> None:
    from h2o_kubernetes_tpu.runtime import faults, health

    health.reset()
    with faults.inject("health.probe:hang~0.7"):
        _check(health.heartbeat(timeout=0.1) is False,
               "hung probe reported healthy")
        _check(not health.healthy(), "hang did not trip unhealthy")
        _check(health.heartbeat(timeout=0.1) is False,
               "second heartbeat did not skip-and-return-False")
        alive = [t for t in threading.enumerate()
                 if t.name == "h2o-tpu-probe" and t.is_alive()]
        _check(len(alive) <= 1,
               f"probe threads piled up: {len(alive)}")
    deadline = time.monotonic() + 10
    while [t for t in threading.enumerate()
           if t.name == "h2o-tpu-probe" and t.is_alive()] \
            and time.monotonic() < deadline:
        time.sleep(0.05)
    health.reset()
    _check(health.heartbeat(timeout=120.0) is True,
           "heartbeat did not recover after reset")


def scenario_device_error() -> None:
    from h2o_kubernetes_tpu.models import GBM
    from h2o_kubernetes_tpu.runtime import faults, health

    health.reset()
    fr = _frame()
    with faults.inject("train.step:device_error@1"):
        try:
            GBM(ntrees=4, max_depth=2, seed=0).train(
                y="y", training_frame=fr)
        except (faults.InjectedDeviceError, health.ClusterHealthError):
            pass
        else:
            raise ChaosFailure("train survived an injected device error")
    _check(not health.healthy(), "device error did not lock the cloud")
    try:
        GBM(ntrees=4, max_depth=2, seed=0).train(y="y", training_frame=fr)
    except health.ClusterHealthError:
        pass
    else:
        raise ChaosFailure("locked cloud accepted a new train")
    health.reset()
    m = GBM(ntrees=4, max_depth=2, seed=0).train(y="y", training_frame=fr)
    _check(m.predict(fr).nrows == fr.nrows,
           "post-restart model does not predict")


def scenario_resume() -> None:
    import h2o_kubernetes_tpu as h2o
    from h2o_kubernetes_tpu.runtime import faults, health

    health.reset()
    fr = _frame(seed=12)
    with tempfile.TemporaryDirectory() as ckpt:
        kw = dict(max_models=2, nfolds=2, seed=11, verbosity=None,
                  include_algos=["glm", "deeplearning"],
                  project_name="chaos_cli", checkpoint_dir=ckpt)
        a1 = h2o.AutoML(**kw)
        with faults.inject("automl.step:device_error@1"):
            try:
                a1.train(y="y", training_frame=fr)
            except health.ClusterHealthError:
                pass
            else:
                raise ChaosFailure(
                    "AutoML survived a mid-run device error")
        manifest = json.load(
            open(os.path.join(ckpt, "automl_manifest.json")))
        _check(len(manifest) == 1,
               f"manifest should hold 1 finished step, has "
               f"{len(manifest)}")
        health.reset()
        a2 = h2o.AutoML(**kw)
        a2.train(y="y", training_frame=fr)
        _check(any("resumed from checkpoint" in m
                   for _, m in a2.event_log),
               "rerun did not resume from the manifest")
        _check(len(a2.leaderboard.rows) >= 2,
               "resumed run did not finish the plan")


def scenario_score_under_fault() -> None:
    """Scoring during an unhealthy episode: 503 fast, then recovery.

    The serving contract (docs/SERVING.md): a request must NEVER wait
    out H2O_TPU_SCORE_TIMEOUT behind the micro-batcher while the cloud
    is locked — the health gate rejects it up front."""
    import json as _json
    import socket
    import urllib.error
    import urllib.request

    import numpy as np

    import h2o_kubernetes_tpu as h2o
    from h2o_kubernetes_tpu import rest
    from h2o_kubernetes_tpu.models import GBM
    from h2o_kubernetes_tpu.runtime import faults, health

    health.reset()
    fr = _frame()
    m = GBM(ntrees=3, max_depth=2, seed=0).train(y="y", training_frame=fr)
    rest.MODELS["chaos_scorer"] = m
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    srv = rest.start_server(port)
    url = f"http://127.0.0.1:{port}/3/Predictions/models/chaos_scorer"

    def score(timeout=30.0):
        req = urllib.request.Request(
            url, data=_json.dumps(
                {"rows": [{"x": 0.3}, {"x": -0.7}]}).encode(),
            method="POST", headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return _json.loads(r.read())

    try:
        out = score()
        _check(len(out["predict"]) == 2, "healthy scoring broken")
        with faults.inject("health.probe:hang~0.7"):
            _check(health.heartbeat(timeout=0.1) is False,
                   "hung probe reported healthy")
            _check(not health.healthy(), "hang did not trip unhealthy")
            t0 = time.monotonic()
            try:
                score()
            except urllib.error.HTTPError as e:
                dt = time.monotonic() - t0
                _check(e.code == 503,
                       f"unhealthy scoring returned {e.code}, want 503")
                _check(dt < 5.0,
                       f"503 took {dt:.1f}s — request queued behind "
                       "the micro-batcher instead of failing fast")
            else:
                raise ChaosFailure(
                    "scoring succeeded on an unhealthy cloud")
        # drain the hung probe thread, then recover
        deadline = time.monotonic() + 10
        while [t for t in threading.enumerate()
               if t.name == "h2o-tpu-probe" and t.is_alive()] \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        health.reset()
        out = score()
        _check(len(out["predict"]) == 2,
               "scoring did not recover after health.reset()")
    finally:
        srv.shutdown()
        rest.MODELS.pop("chaos_scorer", None)
        health.reset()


def _mid_record_cut(blob: bytes, near: int, sep: bytes = b",") -> int:
    """Byte offset near ``near`` that truncates ``blob`` two fields
    into a record: the partial trailing line then has fewer columns
    than any complete row, so BOTH parsers must reject it. (A cut at a
    record boundary — or inside the last field — yields a legally
    parseable shorter/equal row and cannot distinguish 'truncated'
    from 'complete shorter file'.)"""
    line_start = blob.rindex(b"\n", 0, near) + 1
    return blob.index(sep, line_start) + 1


def scenario_ingest_truncated_csv() -> None:
    """A CSV stream aborting mid-file must FAIL the parse cleanly —
    never ship a short frame (docs/SCALING.md §ingest). Rehearsed on
    both the streamed pyarrow record-batch reader (forced into many
    small batches) and the pure-Python parser that defines the parse
    semantics. The cut lands two fields into a record so the trailing
    partial line can never parse as a complete row — a cut exactly at
    a record boundary (or inside the LAST field) is indistinguishable
    from a complete shorter file and would false-alarm the drill."""
    import tempfile

    import h2o_kubernetes_tpu as h2o
    from tools import datasets as D

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "air.csv")
        D.airlines_csv(path, 20_000, chunk=20_000)
        fr = h2o.import_file(path)
        _check(fr.nrows == 20_000, "control parse lost rows")
        with open(path, "rb") as f:
            blob = f.read()
        cut = _mid_record_cut(blob, int(len(blob) * 0.6))
        with open(path, "r+b") as f:
            f.truncate(cut)
        saved = {k: os.environ.get(k) for k in
                 ("H2O_TPU_ARROW_CSV", "H2O_TPU_INGEST_CHUNK_BYTES")}
        try:
            # streamed arrow reader, tiny batches (stream abort lands
            # mid-iteration, not on the first block)
            os.environ.pop("H2O_TPU_ARROW_CSV", None)
            os.environ["H2O_TPU_INGEST_CHUNK_BYTES"] = str(64 << 10)
            try:
                h2o.import_file(path)
                _check(False, "streamed parse shipped a short frame "
                       "from a truncated CSV")
            except ChaosFailure:
                raise
            except Exception:
                pass                         # loud failure: correct
            # pure-Python definition path
            os.environ["H2O_TPU_ARROW_CSV"] = "0"
            try:
                h2o.import_file(path)
                _check(False, "python parse shipped a short frame "
                       "from a truncated CSV")
            except ChaosFailure:
                raise
            except ValueError:
                pass                         # loud failure: correct
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v


SCENARIOS = {
    "persist-503": scenario_persist_503,
    "probe-hang": scenario_probe_hang,
    "device-error": scenario_device_error,
    "resume": scenario_resume,
    "score-under-fault": scenario_score_under_fault,
    "ingest-truncated-csv": scenario_ingest_truncated_csv,
}


def main(argv: list[str]) -> int:
    names = argv or ["all"]
    if names == ["all"]:
        names = list(SCENARIOS)
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        print(f"unknown scenario(s): {', '.join(unknown)} — choose from "
              f"{', '.join(SCENARIOS)} or 'all'", file=sys.stderr)
        return 2
    from h2o_kubernetes_tpu.runtime import make_mesh, set_global_mesh

    set_global_mesh(make_mesh())
    for name in names:
        t0 = time.monotonic()
        try:
            SCENARIOS[name]()
        except ChaosFailure as e:
            print(f"[chaos] {name}: FAIL — {e}", file=sys.stderr)
            return 1
        except Exception as e:  # noqa: BLE001 — a crash is also a fail
            import traceback

            traceback.print_exc()
            print(f"[chaos] {name}: ERROR — {e!r}", file=sys.stderr)
            return 1
        print(f"[chaos] {name}: PASS ({time.monotonic() - t0:.1f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
