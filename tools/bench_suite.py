"""Multi-config benchmark suite — the BASELINE.json eval configs
beyond the headline GBM number (bench.py):

- ingest: airlines-shaped CSV → Frame rows/s (the pyarrow fast path;
  SURVEY C8 — the reference's parse is chunk-parallel for this);
- config #2a GLM: binomial IRLSM on a HIGGS-shaped table (28 numeric
  features) — ≥50 IRLS iterations on ≥100k rows so the number
  measures the Gram path, not dispatch overhead;
- config #2b DRF: HIGGS-shaped forest — rides the 2-channel
  unit-hessian histogram path (h ≡ 1);
- config #3  XGBoost tree_method=hist semantics — regularized-gain
  boosting on the shared tree core;
- config #3b lambdarank on the MSLR shape (qid groups, graded rel);
- config #4  DeepLearning MLP (model-averaging allreduce) — rows/sec
  through one epoch;
- config #4b Word2Vec skip-gram, Zipf corpus;
- config #5  gbm_score_rows_per_sec — the compiled SERVING fast path
  (flattened-tree scorer + jitted-predict cache, docs/SERVING.md):
  warm ``score_numpy`` rows/s on a 100k-row batch, recorded next to
  the per-call ``predict()`` Frame path it replaces, with a
  recompile check (warm repeat must add 0 scorer-cache misses);
- config #7  ``automl_wall_100k`` — pipelined vs serial AutoML
  wall-clock on the airlines shape (docs/SCALING.md "Pipelined
  AutoML"): two cold subprocess legs with isolated persistent caches,
  leaderboard-identity check, warm-repeat compile count, and the
  scheduler's overlap accounting (device-busy / compile-wait /
  host-busy / compile-ahead fills). ``AUTOML_BENCH_ROWS`` /
  ``AUTOML_BENCH_MODELS`` size it;
- config #6  the 10M-row chunked-data-path proofs (docs/SCALING.md):
  ``ingest_airlines_csv_10m`` — streamed pyarrow record-batch CSV
  ingest of a ~1.5 GB airlines-shaped file; ``gbm_higgs_10m`` — GBM
  training where the uint8 binned matrix is the only full-width
  training-resident array. Row counts via ``BENCH_ROWS_10M``
  (default 10M), tree count via ``BENCH_GBM_10M_TREES`` (default 5).
  Both are single-shot (no warm repeat: one call IS minutes of work).

Every config row carries memory watermarks — ``peak_rss_mb`` (VmHWM:
process-lifetime peak, so a regression anywhere shows in the BENCH
trajectory), ``rss_before_mb``/``rss_after_mb`` (per-config
attribution) and ``device_peak_mb`` (sum of per-device
``memory_stats()`` peaks where the backend reports them; None on
CPU builds that don't).

``BENCH_SUITE_CONFIGS`` (comma list of config names) restricts the run
to a subset — e.g. ``BENCH_SUITE_CONFIGS=gbm_score_rows_per_sec`` for
a quick serving capture; partial runs write to a ``_partial`` file so
they never clobber a full-suite artifact.

- config #8  ``gbm_wide_sparse`` — Exclusive Feature Bundling on a
  ≥1k-column one-hot CTR-style frame (docs/SCALING.md "Wide sparse
  frames"): unbundled (H2O_TPU_EFB=0) vs bundled train wall,
  histogram width F→Fb, binned-matrix bytes both ways.
  ``BENCH_WS_ROWS`` / ``BENCH_WS_GROUPS`` / ``BENCH_WS_CARD`` size it;

- config #5c ``gbm_shap_rows_per_sec`` — compiled TreeSHAP serving
  (docs/SERVING.md "Explainable serving"): warm device
  ``contrib_numpy`` rows/s at a 100k-row serving shape vs the
  host-numpy ``ensemble_shap`` recursion (measured single-shot at the
  same shape), with the device additivity check
  (``sum phi + bias == margin`` to 1e-4), a device-vs-host parity
  check on the slice, and the warm-repeat recompile check.
  ``BENCH_SHAP_ROWS`` / ``BENCH_SHAP_HOST_ROWS`` size it;

- config #6b ``gbm_goss_10m`` — GOSS gradient-based sampling
  (docs/SCALING.md "Gradient-based sampling"): sampled (a=0.1,
  b=0.1) vs unsampled GBM at the 10M airlines shape, matched tree
  count; records histogram rows-per-level (the static compaction
  capacity), steady per-tree train time both legs, and the AUC delta
  with its ≤0.002 acceptance flag. ``BENCH_GOSS_ROWS`` /
  ``BENCH_GOSS_TREES`` size it.

Every config reports BOTH timings: ``compile_seconds`` (the first
call — what a cold user pays, XLA compile included) and ``seconds``
(steady state, compile cached; repeated until ≥1 s of measured work
or 3 calls on the CPU mesh, single repeat on TPU where trains are
long and chip windows are ~20 min). One JSON line per config + a
trailing summary; writes ``BENCH_SUITE_{TPU|CPU}_r14.json`` at the
repo root. Run by tools/tpu_watch.py once per chip window.
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _timed(fn, on_tpu: bool, min_secs: float = 1.0):
    """(out, steady_seconds_per_call, calls, compile_seconds)."""
    t0 = time.perf_counter()
    out = fn()
    compile_dt = time.perf_counter() - t0
    total, calls = 0.0, 0
    max_calls = 1 if on_tpu else 3
    while calls < max_calls:
        t0 = time.perf_counter()
        out = fn()
        total += time.perf_counter() - t0
        calls += 1
        if total >= min_secs:
            break
    return out, total / calls, calls, compile_dt


def _rss_mb() -> float:
    """Current VmRSS in MiB (Linux /proc; 0.0 where unavailable)."""
    try:
        with open("/proc/self/status") as f:
            for ln in f:
                if ln.startswith("VmRSS:"):
                    return round(int(ln.split()[1]) / 1024, 1)
    except OSError:
        pass
    return 0.0


def _mem_watermarks() -> dict:
    """Host + device memory watermarks recorded with EVERY config so
    memory regressions show in the BENCH trajectory, not just wall
    clock. peak_rss_mb is ru_maxrss (process-lifetime high-water)."""
    import resource

    import jax

    peak_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    dev, have = 0, False
    for d in jax.local_devices():
        try:
            ms = d.memory_stats()
        except Exception:
            ms = None
        if ms:
            dev += ms.get("peak_bytes_in_use",
                          ms.get("bytes_in_use", 0))
            have = True
    return {"peak_rss_mb": round(peak_kb / 1024, 1),
            "rss_after_mb": _rss_mb(),
            "device_peak_mb": round(dev / 2 ** 20, 1) if have else None}


def main() -> int:
    from h2o_kubernetes_tpu.runtime.backend import ensure_live_backend

    ensure_live_backend(budget=float(
        os.environ.get("H2O_TPU_PROBE_BUDGET", "300")))
    import jax
    import numpy as np

    import h2o_kubernetes_tpu as h2o
    from h2o_kubernetes_tpu.models import (DRF, GBM, GLM, DeepLearning,
                                           Word2Vec, XGBoost)
    from tools import datasets as D

    platform = jax.default_backend()
    on_tpu = platform == "tpu"
    rows = int(os.environ.get("BENCH_SUITE_ROWS",
                              1_000_000 if on_tpu else 30_000))
    results = []
    only = {c.strip() for c in os.environ.get(
        "BENCH_SUITE_CONFIGS", "").split(",") if c.strip()}

    def _want(name: str) -> bool:
        return not only or name in only

    _higgs_cache: dict = {}

    def _higgs(nr, seed=None):
        key = (nr, seed)
        if key not in _higgs_cache:
            _higgs_cache[key] = (D.higgs_frame(nr) if seed is None
                                 else D.higgs_frame(nr, seed=seed))
        return _higgs_cache[key]

    rss_mark = [_rss_mb()]

    def record(config, value, unit, seconds, calls, compile_s, **extra):
        row = {"config": config, "value": round(value, 1), "unit": unit,
               "seconds": round(seconds, 3), "calls": calls,
               "compile_seconds": round(compile_s, 3), "rows": rows,
               "platform": platform,
               "rss_before_mb": rss_mark[0], **_mem_watermarks(),
               **extra}
        rss_mark[0] = row["rss_after_mb"]
        results.append(row)
        print(json.dumps(row), flush=True)

    if _want("ingest_airlines_csv"):
        # ingest: airlines-shaped CSV through import_file (arrow fast
        # path)
        import tempfile
        ing_rows = min(max(rows, 100_000), 2_000_000)
        with tempfile.TemporaryDirectory() as td:
            csv_path = os.path.join(td, "air.csv")
            D.airlines_csv(csv_path, ing_rows, chunk=1_000_000)
            mb = os.path.getsize(csv_path) / 1e6
            fr_ing, dt, calls, cdt = _timed(
                lambda: h2o.import_file(csv_path), on_tpu)
            ncells = ing_rows * fr_ing.ncols
            record("ingest_airlines_csv", ing_rows / dt, "rows/s", dt,
                   calls, cdt, rows_ingest=ing_rows, mb=round(mb, 1),
                   cells_per_s=round(ncells / dt, 1),
                   mb_per_s=round(mb / dt, 2))

    if _want("glm_binomial_irlsm"):
        # config #2a: GLM binomial IRLSM — north-star "GLM iters/sec".
        # 50 iterations on >=100k rows: the r04 number (4 iters on 15k
        # rows, 0.024 s) measured dispatch, not the Gram path.
        fr_glm = _higgs(rows if on_tpu else max(rows, 100_000))
        # epsilons at 0 force the full 50 iterations — the benchmark
        # wants a fixed, comparable amount of Gram work, not a
        # convergence race
        m, dt, calls, cdt = _timed(lambda: GLM(
            family="binomial", solver="IRLSM", lambda_=0.0,
            max_iterations=50, objective_epsilon=0.0, beta_epsilon=0.0,
            seed=1).train(y="y", training_frame=fr_glm), on_tpu)
        record("glm_binomial_irlsm", m.n_iterations / dt, "iters/s", dt,
               calls, cdt, iterations=m.n_iterations,
               rows_glm=fr_glm.nrows,
               auc=round(float(
                   m.model_performance(fr_glm, y="y")["auc"]), 5))

    ntrees, depth = 10, 8
    if _want("drf_higgs"):
        # config #2b: DRF (unit-hessian 2-channel histograms)
        fr = _higgs(rows)
        m, dt, calls, cdt = _timed(lambda: DRF(
            ntrees=ntrees, max_depth=depth, seed=1).train(
            y="y", training_frame=fr), on_tpu)
        record("drf_higgs", fr.nrows * ntrees / dt, "rows*trees/s",
               dt, calls, cdt, ntrees=ntrees, max_depth=depth)

    if _want("xgboost_hist"):
        # config #3: XGBoost hist semantics
        fr = _higgs(rows)
        m, dt, calls, cdt = _timed(lambda: XGBoost(
            ntrees=ntrees, max_depth=6, learn_rate=0.2, seed=1).train(
            y="y", training_frame=fr), on_tpu)
        record("xgboost_hist", fr.nrows * ntrees / dt, "rows*trees/s",
               dt, calls, cdt, ntrees=ntrees, max_depth=6)

    if _want("gbm_multinomial"):
        # multinomial GBM: K class trees per round through the
        # class-flattened batching rule (custom_vmap lowers the class
        # axis into the node axis — the round-4 Mosaic fix; K x fuller
        # MXU M)
        mn_rows = min(rows, 500_000)
        rngm = np.random.default_rng(3)
        Xm = rngm.normal(size=(mn_rows, 10)).astype(np.float32)
        score = Xm[:, 0] + 0.5 * Xm[:, 1]
        ym = np.where(score > 0.6, "a",
                      np.where(score < -0.6, "b",
                               np.where(Xm[:, 2] > 0, "c", "d")))
        mcols = {f"f{i}": Xm[:, i] for i in range(10)}
        mcols["y"] = ym
        fr_mn = h2o.Frame.from_arrays(mcols)
        mn_ntrees = 5
        m, dt, calls, cdt = _timed(lambda: GBM(
            ntrees=mn_ntrees, max_depth=5, learn_rate=0.2, seed=1).train(
            y="y", training_frame=fr_mn), on_tpu)
        record("gbm_multinomial", mn_rows * mn_ntrees * m.nclasses / dt,
               "rows*classtrees/s", dt, calls, cdt, rows_mn=mn_rows,
               classes=m.nclasses,
               logloss=round(float(
                   m.scoring_history[-1].get("train_logloss",
                                             float("nan"))), 5))

    if _want("xgboost_lambdarank"):
        # config #3b: lambdarank (MSLR-WEB30K shape — graded relevance
        # over query groups, rank:ndcg LambdaMART)
        rk_rows = min(rows, 200_000)
        fr_rk = D.mslr_frame(rk_rows, seed=4, n_features=20)
        m, dt, calls, cdt = _timed(lambda: XGBoost(
            ntrees=10, max_depth=6, objective="rank:ndcg", seed=1).train(
            y="rel", training_frame=fr_rk, group_column="qid"), on_tpu)
        ndcg = m.model_performance(fr_rk, y="rel")
        record("xgboost_lambdarank", rk_rows * 10 / dt, "rows*trees/s",
               dt, calls, cdt, rows_rank=rk_rows,
               ndcg10=round(float(ndcg.get("ndcg@10", float("nan"))), 5))

    if _want("deeplearning_mlp"):
        # config #4: DeepLearning MLP, one pass (model-averaging
        # allreduce)
        dl_rows = min(rows, 200_000)
        fr_dl = _higgs(dl_rows, seed=2)
        m, dt, calls, cdt = _timed(lambda: DeepLearning(
            hidden=[64, 64], epochs=1, seed=1).train(
            y="y", training_frame=fr_dl), on_tpu)
        record("deeplearning_mlp", dl_rows / dt, "rows/s", dt, calls,
               cdt, rows_dl=dl_rows, hidden=[64, 64])

    if _want("word2vec_skipgram"):
        # config #4b: Word2Vec skip-gram over a Zipf NA-delimited corpus
        n_tok = 200_000
        toks = D.text8_like_tokens(n_tok, vocab_size=5_000, seed=5)
        fr_w2v = h2o.Frame.from_arrays({"words": np.array(toks)})
        m, dt, calls, cdt = _timed(lambda: Word2Vec(
            vec_size=32, epochs=1, min_word_freq=2, seed=1).train(
            fr_w2v), on_tpu)
        record("word2vec_skipgram", n_tok / dt, "tokens/s", dt, calls,
               cdt, tokens=n_tok, vec_size=32)

    if _want("gbm_score_rows_per_sec"):
        # config #5: the compiled serving fast path (ISSUE 2 tentpole)
        # on a HIGGS-shaped table: warm score_numpy at the full batch
        # AND the "100k×1" per-call shape, against the pre-flattening
        # per-call predict() baseline, with the warm-repeat recompile
        # check. THE harness lives in bench.py::measure_scoring (one
        # protocol for bench.py score mode and this config — no drift).
        from bench import measure_scoring

        sc_rows = int(os.environ.get("BENCH_SCORE_ROWS", 100_000))
        fr_sc = _higgs(sc_rows, seed=6)
        m_sc = GBM(ntrees=20, max_depth=5, learn_rate=0.2, seed=1).train(
            y="y", training_frame=fr_sc)
        X_sc = np.asarray(m_sc._design_matrix(fr_sc))[:sc_rows]
        fr_1 = h2o.Frame.from_arrays(
            {n_: fr_sc.vec(n_).to_numpy()[:1]
             for n_ in fr_sc.names if n_ != "y"})
        out = measure_scoring(m_sc, fr_sc, fr_1, X_sc, sc_rows,
                              reps_full=1 if on_tpu else 3)
        record("gbm_score_rows_per_sec", out.pop("value"),
               out.pop("unit"), out.pop("seconds"), out.pop("calls"),
               out.pop("compile_seconds"),
               rows_score=out.pop("rows"), ntrees=20, max_depth=5,
               **out)

    if _want("gbm_shap_rows_per_sec"):
        # config #5c (ISSUE 10): compiled TreeSHAP serving — the
        # device path-enumeration kernel (models/tree/shap.flat_shap,
        # dispatched via Model.contrib_numpy through the jitted-scorer
        # cache) against the host-numpy ensemble_shap recursion it
        # replaces on the serving path. The host leg is measured on a
        # SLICE (the recursion is linear in rows — per-node numpy ops
        # are [rows]-vectorized, so rows/s is shape-stable) and
        # reported as rows/s; the device leg runs the full serving
        # shape warm, with the recompile check and the on-device
        # additivity + host-parity assertions recorded in the row.
        import jax.numpy as jnp

        from h2o_kubernetes_tpu.models.base import scorer_cache_stats
        from h2o_kubernetes_tpu.models.tree.binning import apply_bins_jit
        from h2o_kubernetes_tpu.models.tree.shap import ensemble_shap

        sh_rows = int(os.environ.get("BENCH_SHAP_ROWS", 100_000))
        fr_sh = _higgs(sh_rows, seed=6)
        m_sh = GBM(ntrees=20, max_depth=5, learn_rate=0.2,
                   seed=1).train(y="y", training_frame=fr_sh)
        X_sh = np.asarray(m_sh._design_matrix(fr_sh))[:sh_rows]
        phi, dt, calls, cdt = _timed(
            lambda: m_sh.contrib_numpy(X_sh), on_tpu)
        # warm-repeat recompile check: one more full-shape call must
        # add zero scorer-cache misses
        s0 = scorer_cache_stats()
        m_sh.contrib_numpy(X_sh)
        warm_misses = scorer_cache_stats()["misses"] - s0["misses"]
        # device additivity: sum_f phi + bias == the flat margin
        margins = np.asarray(
            m_sh._margins(jnp.asarray(X_sh)))[:sh_rows]
        add_err = float(np.abs(phi.sum(axis=1) - margins).max())
        # host-numpy baseline + parity — at the FULL serving shape by
        # default (single-shot, like the 10M configs: the recursion is
        # ~10s at 100k rows); BENCH_SHAP_HOST_ROWS shrinks it for
        # quick captures
        host_rows = min(sh_rows,
                        int(os.environ.get("BENCH_SHAP_HOST_ROWS",
                                           sh_rows)))
        binned_h = np.asarray(apply_bins_jit(
            jnp.asarray(X_sh[:host_rows]), m_sh._edges,
            m_sh._enum_mask, m_sh.bin_spec.na_bin))
        trees_np = {f: np.asarray(getattr(m_sh.trees, f))
                    for f in ("split_feat", "split_bin", "na_left",
                              "is_split", "value", "cover")}
        t0 = time.perf_counter()
        phi_h = ensemble_shap(trees_np, binned_h,
                              len(m_sh.feature_names),
                              m_sh.bin_spec.na_bin)
        host_dt = time.perf_counter() - t0
        phi_h[:, -1] += float(m_sh.init_score)
        parity_err = float(np.abs(phi[:host_rows] - phi_h).max())
        dev_rps = sh_rows / dt
        host_rps = host_rows / host_dt
        # XLA-vs-kernel leg pair (ISSUE 17): each impl forced via
        # H2O_TPU_SHAP_KERNEL on a FRESH pickle copy — the scorer
        # cache keys on shape, not impl, so a warm executable would
        # otherwise shadow the flip. The kernel leg is recorded ONLY
        # with a chip attached: off-chip the Pallas kernel runs in
        # INTERPRET mode, which is a correctness harness, not a
        # throughput claim.
        import pickle

        def _impl_leg(env_val):
            mc = pickle.loads(pickle.dumps(m_sh))
            os.environ["H2O_TPU_SHAP_KERNEL"] = env_val
            try:
                phi_l, dt_l, _, _ = _timed(
                    lambda: mc.contrib_numpy(X_sh), on_tpu)
            finally:
                os.environ.pop("H2O_TPU_SHAP_KERNEL", None)
            return phi_l, sh_rows / dt_l

        phi_x, xla_rps = _impl_leg("0")
        legs = {"xla_rows_per_s": round(xla_rps, 1)}
        if on_tpu:
            phi_k, k_rps = _impl_leg("1")
            legs.update(
                kernel_rows_per_s=round(k_rps, 1),
                kernel_speedup_vs_xla=round(
                    k_rps / max(xla_rps, 1e-9), 2),
                kernel_vs_xla_bitwise=bool(
                    np.array_equal(phi_k, phi_x)))
        else:
            legs.update(
                kernel_rows_per_s=None,
                kernel_leg="skipped: no chip attached (interpret "
                           "mode is excluded from throughput claims)")
        record("gbm_shap_rows_per_sec", dev_rps, "rows/s", dt, calls,
               cdt, rows_shap=sh_rows, ntrees=20, max_depth=5,
               host_rows=host_rows, host_seconds=round(host_dt, 3),
               host_rows_per_s=round(host_rps, 1),
               speedup_vs_host=round(dev_rps / max(host_rps, 1e-9), 1),
               additivity_max_err=add_err,
               host_parity_max_err=parity_err,
               warm_repeat_misses=warm_misses, **legs)
        del fr_sh, m_sh, X_sh, phi

    if _want("automl_wall_100k"):
        # config #7: pipelined AutoML wall-clock (ISSUE 5 tentpole) on
        # the AUTOML_SCALE airlines shape. Two COLD legs in separate
        # subprocesses — serial (H2O_TPU_AUTOML_PIPELINE=0) then
        # pipelined — each with its own fresh persistent-cache dir so
        # neither inherits the other's compiles; the pipelined leg
        # also runs automl_scale's warm repeat (warm-repeat compile
        # count must stay 0). Recorded: the wall ratio, per-leg walls
        # and compile counts, the scheduler overlap accounting
        # (device-busy / compile-wait / host-busy / compile-ahead
        # fills), and the leaderboard identity check (model ids,
        # ranking, metrics to every printed digit — wall-clock fields
        # excluded). NOTE: on a single-core host the streams time-slice
        # one CPU, so the ratio is bounded near 1.0 by construction —
        # the overlap stats still show what LEFT the critical path
        # (the wall win materializes where the compile/host streams
        # have their own core, and on the tunneled chip where every
        # compile is a remote round trip).
        import subprocess
        import tempfile

        aml_rows = int(os.environ.get("AUTOML_BENCH_ROWS", 100_000))
        aml_models = int(os.environ.get("AUTOML_BENCH_MODELS", 2))

        def _aml_leg(pipeline: str, cache_dir: str, out_path: str,
                     recompile_check: bool) -> dict:
            env = dict(os.environ,
                       JAX_PLATFORMS="cpu" if not on_tpu
                       else os.environ.get("JAX_PLATFORMS", ""),
                       H2O_TPU_AUTOML_PIPELINE=pipeline,
                       JAX_COMPILATION_CACHE_DIR=cache_dir)
            cmd = [sys.executable,
                   os.path.join(REPO, "tools", "automl_scale.py"),
                   "--rows", str(aml_rows),
                   "--max-models", str(aml_models),
                   "--nfolds", "3",
                   "--include-algos", "glm", "gbm",
                   "--out", out_path]
            if not recompile_check:
                cmd.append("--no-recompile-check")
            r = subprocess.run(cmd, cwd=REPO, env=env,
                               capture_output=True)
            if r.returncode != 0:
                raise RuntimeError(
                    f"automl_wall leg pipeline={pipeline} rc="
                    f"{r.returncode}: "
                    f"{r.stderr.decode(errors='replace')[-400:]}")
            with open(out_path) as f:
                out = json.load(f)
            # run_shape swallows AutoML crashes into 'error' (and
            # automl_scale still exits 0) — a crashed leg must fail
            # the config, not record a 0-second "identical" row
            err = out["curve"][0].get("error")
            if err:
                raise RuntimeError(
                    f"automl_wall leg pipeline={pipeline} AutoML "
                    f"crashed: {err[-400:]}")
            return out

        def _strip_rows(rows):
            return [{k: v for k, v in r.items()
                     if k != "training_time_s"} for r in rows]

        with tempfile.TemporaryDirectory() as td:
            serial = _aml_leg("0", os.path.join(td, "cache_serial"),
                              os.path.join(td, "serial.json"), False)
            pipe = _aml_leg("1", os.path.join(td, "cache_pipe"),
                            os.path.join(td, "pipe.json"), True)
        s0, p0 = serial["curve"][0], pipe["curve"][0]
        lb_identical = _strip_rows(s0["leaderboard"]) == \
            _strip_rows(p0["leaderboard"])
        ratio = s0["wall_seconds"] / max(p0["wall_seconds"], 1e-9)
        rc = pipe.get("recompile_check") or {}
        record("automl_wall_100k", ratio, "x_speedup_vs_serial",
               p0["wall_seconds"], 1, 0.0,
               rows_automl=aml_rows, max_models=aml_models, nfolds=3,
               serial_wall_s=s0["wall_seconds"],
               pipelined_wall_s=p0["wall_seconds"],
               serial_compiles=s0["xla_compiles"],
               pipelined_compiles=p0["xla_compiles"],
               warm_repeat_compiles=rc.get("warm_compiles"),
               leaderboard_identical=lb_identical,
               leader=p0["leader"], leader_auc=p0["leader_auc"],
               scheduler_stats=p0.get("scheduler_stats"))

    if _want("multitenant_zipf_p99"):
        # config #5b (ISSUE 7): multi-tenant serving under a byte-
        # budgeted executable cache — ≥100 registry-pushed tenants,
        # Zipf(s) popularity, per-decile p99, residency vs budget,
        # evict→promote pcache proof, and the hot-model storm with
        # fairness ON vs OFF (the unfair leg must provably miss the
        # tail's SLO). Runs in THIS process (self-hosted REST server);
        # see tools/score_load.run_zipf_bench for the contract.
        from tools.score_load import run_zipf_bench

        mt_models = int(os.environ.get("BENCH_MT_MODELS", 100))
        t0 = time.perf_counter()
        mt = run_zipf_bench(
            n_models=mt_models,
            seconds=float(os.environ.get("BENCH_MT_SECONDS", 20)),
            zipf_s=float(os.environ.get("BENCH_MT_ZIPF_S", 1.1)),
            budget_mb=float(os.environ.get("BENCH_MT_BUDGET_MB", 4.0)))
        dt = time.perf_counter() - t0
        sweep = mt["sweep"]
        res = sweep["residency"]
        tail_decile = sweep["deciles"][-1] if sweep["deciles"] else {}
        record("multitenant_zipf_p99",
               sweep["p99_ms"] or 0.0, "p99_ms", dt, 1, 0.0,
               models=mt["models"], zipf_s=mt["zipf_s"],
               budget_mb=mt["budget_mb"],
               sweep_requests=sweep["requests"],
               sweep_rows_per_s=sweep["value"],
               sweep_p50_ms=sweep["p50_ms"],
               sweep_fivexx=sweep["fivexx"],
               tail_decile_p99_ms=tail_decile.get("p99_ms"),
               deciles=sweep["deciles"],
               residency=res,
               budget_held=bool(res["samples"] > 0
                                and res["budget_exceeded"] == 0),
               promotions=res["promotions_delta"],
               promotion_compiles_all_pcache_hits=bool(
                   res["pcache_misses_delta"] == 0
                   and res["compiles_delta"]
                   == res["pcache_hits_delta"]),
               evict_promote_bitwise=mt["evict_promote_bitwise"],
               storm_fair=mt["storm_fair"],
               storm_unfair=mt["storm_unfair"],
               fair_tail_slo_met=mt["storm_fair"]["tail_slo_met"],
               unfair_tail_slo_met=mt["storm_unfair"]["tail_slo_met"],
               scorer_cache_final=mt["scorer_cache_final"],
               # exposition-cost hygiene (ISSUE 14): one /metrics
               # scrape timed before + after the sweep; acceptance
               # note = the post-sweep scrape (full tenant series
               # resident) costs < 1% of the storm-shape p99, so
               # Prometheus polling cannot move the serving tail
               metrics_scrape=mt.get("metrics_scrape"),
               metrics_scrape_under_1pct_p99=bool(
                   mt.get("metrics_scrape", {}).get(
                       "after", {}).get("ok")
                   and (sweep["p99_ms"] or 0) > 0
                   and mt["metrics_scrape"]["after"]["ms"]
                   < 0.01 * sweep["p99_ms"]))

    if _want("router_zipf_p99"):
        # config #5d (ISSUE 11): the tenant-sharded fleet router vs
        # the everyone-has-everything pool at EQUAL total cache
        # budget — the same Zipf tenant storm through (a) a 3-shard
        # fleet behind the device-free front-door router (catalog
        # rendezvous-placed, head replicated) and (b) a direct
        # 3-replica pool where every replica holds the full catalog
        # under the same per-replica byte budget. Real subprocess
        # pods both ways; acceptance: router head-decile p99 within
        # 1.3x of the direct baseline (the routing hop must be
        # cheap), aggregate rows/s + tail-decile p99 recorded for
        # both. See tools/score_load.run_router_bench.
        from tools.score_load import run_router_bench

        t0 = time.perf_counter()
        rt = run_router_bench(
            tenants=int(os.environ.get("BENCH_ROUTER_TENANTS", 120)),
            shards=int(os.environ.get("BENCH_ROUTER_SHARDS", 3)),
            head=int(os.environ.get("BENCH_ROUTER_HEAD", 8)),
            budget_bytes=int(os.environ.get("BENCH_ROUTER_BUDGET",
                                            2_000_000)),
            seconds=float(os.environ.get("BENCH_ROUTER_SECONDS", 15)),
            zipf_s=float(os.environ.get("BENCH_ROUTER_ZIPF_S", 1.1)))
        dt = time.perf_counter() - t0
        record("router_zipf_p99",
               rt["router"]["head_p99_ms"] or 0.0, "p99_ms", dt, 1,
               0.0, tenants=rt["tenants"], shards=rt["shards"],
               head=rt["head"], budget_bytes=rt["budget_bytes"],
               zipf_s=rt["zipf_s"],
               router_leg=rt["router"], direct_leg=rt["direct"],
               head_p99_ratio=rt["head_p99_ratio"],
               head_p99_within_1_3x=rt["head_p99_within_1_3x"],
               router_rows_per_s=rt["router"]["rows_per_s"],
               direct_rows_per_s=rt["direct"]["rows_per_s"],
               router_tail_p99_ms=rt["router"]["tail_p99_ms"],
               direct_tail_p99_ms=rt["direct"]["tail_p99_ms"],
               router_metrics_scrape=rt["router"].get(
                   "metrics_scrape"),
               direct_metrics_scrape=rt["direct"].get(
                   "metrics_scrape"))

    if _want("gbm_wide_sparse"):
        # config #8 (ISSUE 8): Exclusive Feature Bundling on a >= 1k-
        # column one-hot-dominated CTR-style frame (docs/SCALING.md
        # "Wide sparse frames"). Two in-process legs on the SAME
        # frame: unbundled (H2O_TPU_EFB=0) then bundled
        # (H2O_TPU_EFB=1); recorded: the train-wall ratio, the
        # histogram width F -> Fb (also the per-level psum payload
        # factor), and the binned-matrix bytes both ways. The
        # env-keyed plan cache keeps the legs honest (EFB=0 never
        # builds a plan; the bundled leg's cold wall INCLUDES the
        # planning + bundled-apply passes).
        from h2o_kubernetes_tpu.models.tree import efb as E

        ws_rows = int(os.environ.get("BENCH_WS_ROWS",
                                     min(max(rows, 20_000), 100_000)))
        ws_groups = int(os.environ.get("BENCH_WS_GROUPS", 40))
        ws_card = int(os.environ.get("BENCH_WS_CARD", 25))
        fr_ws = D.wide_sparse_frame(ws_rows, n_groups=ws_groups,
                                    group_card=ws_card, seed=9)
        F_ws = fr_ws.ncols - 1
        padded_ws = fr_ws.vec("d0").padded_len
        ws_trees, ws_depth = 5, 5

        _efb_prior = os.environ.get("H2O_TPU_EFB")

        def _restore_efb():
            if _efb_prior is None:
                os.environ.pop("H2O_TPU_EFB", None)
            else:
                os.environ["H2O_TPU_EFB"] = _efb_prior

        def _ws_leg(efb_env):
            os.environ["H2O_TPU_EFB"] = efb_env
            try:
                return _timed(lambda: GBM(
                    ntrees=ws_trees, max_depth=ws_depth, learn_rate=0.2,
                    seed=1).train(y="y", training_frame=fr_ws), on_tpu)
            finally:
                _restore_efb()

        m_u, dt_u, calls_u, cdt_u = _ws_leg("0")
        m_b, dt_b, calls_b, cdt_b = _ws_leg("1")
        os.environ["H2O_TPU_EFB"] = "1"
        try:
            names_ws = [n for n in fr_ws.names if n != "y"]
            _, plan_ws = E.fit_plan_cached(fr_ws, names_ws,
                                           m_b.params.nbins)
        finally:
            _restore_efb()
        fb = plan_ws.fb if plan_ws is not None else F_ws
        # both legs must train the same model family; the structural
        # check rides the varimp ranking head (full bitwise parity is
        # tier-1 tested — tests/test_efb.py)
        top_u = sorted(m_u.varimp(), key=m_u.varimp().get)[-3:]
        top_b = sorted(m_b.varimp(), key=m_b.varimp().get)[-3:]
        record("gbm_wide_sparse", dt_u / max(dt_b, 1e-9),
               "x_speedup_vs_unbundled", dt_b, calls_b, cdt_b,
               rows_ws=ws_rows, features=F_ws, ntrees=ws_trees,
               max_depth=ws_depth,
               unbundled_wall_s=round(dt_u, 3),
               bundled_wall_s=round(dt_b, 3),
               unbundled_cold_s=round(cdt_u, 3),
               bundled_cold_s=round(cdt_b, 3),
               hist_width_unbundled=F_ws, hist_width_bundled=fb,
               hist_width_reduction=round(F_ws / max(fb, 1), 1),
               binned_mb_unbundled=round(padded_ws * F_ws / 2**20, 1),
               binned_mb_bundled=round(padded_ws * fb / 2**20, 1),
               bundles=sum(1 for c in (plan_ws.cols if plan_ws else [])
                           if c[0] == "bundle"),
               efb_conflicts=plan_ws.conflicts if plan_ws else None,
               efb_demoted=len(plan_ws.demoted) if plan_ws else None,
               varimp_top3_agree=top_u == top_b)
        del fr_ws, m_u, m_b

    # -- config #6: the 10M-row chunked-path proofs --------------------
    rows_10m = int(os.environ.get("BENCH_ROWS_10M", 10_000_000))

    if _want("gbm_goss_10m"):
        # config #6b (ISSUE 13): GOSS gradient-based one-side sampling
        # at the 10M airlines shape (docs/SCALING.md "Gradient-based
        # sampling") — sampled (a=0.1, b=0.1) vs unsampled legs at
        # matched tree count. Records the histogram rows-per-level the
        # kernel actually streams (the static compaction capacity),
        # steady per-tree train time both ways, and the AUC delta.
        # Acceptance: >=2.5x steady per-tree with GOSS on, |dAUC| <=
        # 0.002. BENCH_GOSS_ROWS/TREES shrink it for partial captures;
        # below 2M rows each leg runs cold+warm so the steady number
        # is compile-free, at the full shape legs are single-shot.
        import gc

        from h2o_kubernetes_tpu.models.tree import core as TC
        from h2o_kubernetes_tpu.runtime import mesh as meshlib

        goss_rows = int(os.environ.get("BENCH_GOSS_ROWS", rows_10m))
        nt_g = int(os.environ.get("BENCH_GOSS_TREES", 10))
        a_s = os.environ.get("BENCH_GOSS_TOP_A", "0.1")
        b_s = os.environ.get("BENCH_GOSS_RAND_B", "0.1")
        fr_g = D.airlines_frame(goss_rows, seed=10)
        padded_g = fr_g.vec("Year").padded_len
        shards = meshlib.global_mesh().shape[meshlib.ROWS]
        cap_rows = shards * TC.goss_cap_rows(
            padded_g // shards, float(a_s), float(b_s))
        legs = 1 if goss_rows > 2_000_000 else 2
        _goss_prior = {k: os.environ.get(k) for k in
                       ("H2O_TPU_GOSS", "H2O_TPU_GOSS_TOP_A",
                        "H2O_TPU_GOSS_RAND_B")}

        def _goss_leg(on: bool):
            os.environ["H2O_TPU_GOSS"] = "1" if on else "0"
            os.environ["H2O_TPU_GOSS_TOP_A"] = a_s
            os.environ["H2O_TPU_GOSS_RAND_B"] = b_s
            try:
                walls = []
                for _ in range(legs):
                    t0 = time.perf_counter()
                    mg = GBM(ntrees=nt_g, max_depth=5, learn_rate=0.2,
                             seed=1).train(y="IsDepDelayed",
                                           training_frame=fr_g)
                    walls.append(time.perf_counter() - t0)
                auc = float(mg.scoring_history[-1].get(
                    "train_auc", float("nan")))
                del mg
                gc.collect()
                return walls[0], walls[-1], auc
            finally:
                for k, v in _goss_prior.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v

        cold_off, steady_off, auc_off = _goss_leg(False)
        cold_on, steady_on, auc_on = _goss_leg(True)
        ratio = steady_off / max(steady_on, 1e-9)
        record("gbm_goss_10m", ratio,
               "x_per_tree_speedup_vs_unsampled", steady_on, legs,
               cold_on, rows_goss=goss_rows, ntrees=nt_g, max_depth=5,
               goss_top_a=float(a_s), goss_rand_b=float(b_s),
               unsampled_wall_s=round(steady_off, 3),
               sampled_wall_s=round(steady_on, 3),
               unsampled_cold_s=round(cold_off, 3),
               sampled_cold_s=round(cold_on, 3),
               per_tree_s_unsampled=round(steady_off / nt_g, 4),
               per_tree_s_sampled=round(steady_on / nt_g, 4),
               hist_rows_per_level_unsampled=padded_g,
               hist_rows_per_level_sampled=cap_rows,
               hist_rows_reduction=round(padded_g / max(cap_rows, 1),
                                         2),
               auc_unsampled=round(auc_off, 5),
               auc_sampled=round(auc_on, 5),
               auc_delta=round(abs(auc_off - auc_on), 5),
               auc_within_0_002=bool(abs(auc_off - auc_on) <= 0.002),
               per_tree_speedup_ge_2_5x=bool(ratio >= 2.5))
        del fr_g
        gc.collect()

    if _want("ingest_airlines_csv_10m"):
        import gc
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            csv_path = os.path.join(td, "air10m.csv")
            t0 = time.perf_counter()
            D.airlines_csv(csv_path, rows_10m, chunk=1_000_000)
            gen_dt = time.perf_counter() - t0
            mb = os.path.getsize(csv_path) / 1e6
            t0 = time.perf_counter()
            fr10 = h2o.import_file(csv_path)
            dt = time.perf_counter() - t0
            assert fr10.nrows == rows_10m, fr10.nrows
            record("ingest_airlines_csv_10m", rows_10m / dt, "rows/s",
                   dt, 1, 0.0, rows_ingest=rows_10m, mb=round(mb, 1),
                   mb_per_s=round(mb / dt, 2),
                   csv_gen_seconds=round(gen_dt, 1),
                   cells_per_s=round(rows_10m * fr10.ncols / dt, 1))
            del fr10
            gc.collect()

    if _want("gbm_higgs_10m"):
        import gc

        nt10 = int(os.environ.get("BENCH_GBM_10M_TREES", 5))
        t0 = time.perf_counter()
        fr10 = D.higgs_frame(rows_10m, seed=8)
        gen_dt = time.perf_counter() - t0
        F10 = fr10.ncols - 1
        padded10 = fr10.vec("f0").padded_len
        binned_mb = round(padded10 * F10 / 2 ** 20, 1)
        budget_b = float(os.environ.get("H2O_TPU_HIST_BYTES_BUDGET",
                                        2 ** 30))
        t0 = time.perf_counter()
        m10 = GBM(ntrees=nt10, max_depth=6, seed=1).train(
            y="y", training_frame=fr10)
        dt = time.perf_counter() - t0
        record("gbm_higgs_10m", rows_10m * nt10 / dt, "rows*trees/s",
               dt, 1, 0.0, rows_gbm=rows_10m, ntrees=nt10, max_depth=6,
               binned_matrix_mb=binned_mb,
               hist_budget_mb=round(budget_b / 2 ** 20, 1),
               ooc=os.environ.get("H2O_TPU_OOC", "auto"),
               frame_gen_seconds=round(gen_dt, 1),
               train_auc=round(float(
                   m10.scoring_history[-1].get("train_auc",
                                               float("nan"))), 5))
        del fr10, m10
        gc.collect()

    from h2o_kubernetes_tpu.runtime.telemetry import build_info

    out = {"suite": results, "captured_at":
           time.strftime("%Y-%m-%dT%H:%M:%S"),
           "build": build_info()}
    suffix = "" if not only else "_partial"
    path = os.path.join(
        REPO,
        f"BENCH_SUITE_{'TPU' if on_tpu else 'CPU'}_r14{suffix}.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"bench_suite": "done", "configs": len(results),
                      "platform": platform}))
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception as e:    # one diagnostic line, never a bare death
        import traceback

        traceback.print_exc()
        print(json.dumps({"bench_suite": "error", "error": repr(e)[:300]}))
        sys.exit(1)
