"""Multi-config benchmark suite — the BASELINE.json eval configs
beyond the headline GBM number (bench.py):

- config #2a GLM: binomial IRLSM on a HIGGS-shaped table (28 numeric
  features) — reports the north-star "GLM iters/sec" plus wall;
- config #2b DRF: HIGGS-shaped forest — rides the 2-channel
  unit-hessian histogram path (h ≡ 1);
- config #3  XGBoost tree_method=hist semantics — regularized-gain
  boosting on the shared tree core;
- config #4  DeepLearning MLP (model-averaging allreduce) — rows/sec
  through one epoch.

Each config warms up once (compile excluded, same contract as
bench.py) then times a steady-state train. One JSON line per config +
a trailing summary; writes ``BENCH_SUITE_{TPU|CPU}_r04.json`` at the
repo root. Run by tools/tpu_watch.py once per chip window.
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _higgs_like(rows: int, seed: int = 0):
    """HIGGS-shaped synthetic: 28 numeric features, binary response
    driven by a few nonlinear combinations (the real set's low-level
    kinematics + derived masses)."""
    import numpy as np

    import h2o_kubernetes_tpu as h2o

    rng = np.random.default_rng(seed)
    F = 28
    X = rng.normal(size=(rows, F)).astype(np.float32)
    logit = (0.8 * X[:, 0] - 0.6 * X[:, 1] * X[:, 2]
             + 0.5 * np.abs(X[:, 3]) - 0.4 * (X[:, 4] ** 2)
             + rng.normal(scale=0.7, size=rows))
    cols = {f"f{i}": X[:, i] for i in range(F)}
    cols["y"] = np.where(logit > 0, "s", "b")
    return h2o.Frame.from_arrays(cols)


def _timed(fn):
    fn()                                   # warm-up: compile cached
    t0 = time.perf_counter()
    out = fn()
    return out, time.perf_counter() - t0


def main() -> int:
    from h2o_kubernetes_tpu.runtime.backend import ensure_live_backend

    ensure_live_backend(budget=float(
        os.environ.get("H2O_TPU_PROBE_BUDGET", "300")))
    import jax

    from h2o_kubernetes_tpu.models import DRF, GLM, DeepLearning, XGBoost

    platform = jax.default_backend()
    on_tpu = platform == "tpu"
    rows = int(os.environ.get("BENCH_SUITE_ROWS",
                              1_000_000 if on_tpu else 30_000))
    results = []

    def record(config, value, unit, seconds, **extra):
        row = {"config": config, "value": round(value, 1), "unit": unit,
               "seconds": round(seconds, 3), "rows": rows,
               "platform": platform, **extra}
        results.append(row)
        print(json.dumps(row), flush=True)

    fr = _higgs_like(rows)

    # config #2a: GLM binomial IRLSM — north-star "GLM iters/sec"
    m, dt = _timed(lambda: GLM(
        family="binomial", solver="IRLSM", lambda_=0.0,
        max_iterations=20, seed=1).train(y="y", training_frame=fr))
    record("glm_binomial_irlsm", m.n_iterations / dt, "iters/s", dt,
           iterations=m.n_iterations,
           auc=round(float(m.model_performance(fr, y="y")["auc"]), 5))

    # config #2b: DRF (unit-hessian 2-channel histograms)
    ntrees, depth = 10, 8
    m, dt = _timed(lambda: DRF(
        ntrees=ntrees, max_depth=depth, seed=1).train(
        y="y", training_frame=fr))
    record("drf_higgs", rows * ntrees / dt, "rows*trees/s", dt,
           ntrees=ntrees, max_depth=depth)

    # config #3: XGBoost hist semantics
    m, dt = _timed(lambda: XGBoost(
        ntrees=ntrees, max_depth=6, learn_rate=0.2, seed=1).train(
        y="y", training_frame=fr))
    record("xgboost_hist", rows * ntrees / dt, "rows*trees/s", dt,
           ntrees=ntrees, max_depth=6)

    # multinomial GBM: K class trees per round through the
    # class-flattened batching rule (custom_vmap lowers the class axis
    # into the node axis — the round-4 Mosaic fix; K x fuller MXU M)
    import numpy as np

    import h2o_kubernetes_tpu as h2o

    from h2o_kubernetes_tpu.models import GBM

    mn_rows = min(rows, 500_000)
    rngm = np.random.default_rng(3)
    Xm = rngm.normal(size=(mn_rows, 10)).astype(np.float32)
    score = Xm[:, 0] + 0.5 * Xm[:, 1]
    ym = np.where(score > 0.6, "a",
                  np.where(score < -0.6, "b",
                           np.where(Xm[:, 2] > 0, "c", "d")))
    mcols = {f"f{i}": Xm[:, i] for i in range(10)}
    mcols["y"] = ym
    fr_mn = h2o.Frame.from_arrays(mcols)
    mn_ntrees = 5
    m, dt = _timed(lambda: GBM(
        ntrees=mn_ntrees, max_depth=5, learn_rate=0.2, seed=1).train(
        y="y", training_frame=fr_mn))
    record("gbm_multinomial", mn_rows * mn_ntrees * m.nclasses / dt,
           "rows*classtrees/s", dt, rows_mn=mn_rows,
           classes=m.nclasses,
           logloss=round(float(
               m.scoring_history[-1].get("train_logloss",
                                         float("nan"))), 5))

    # config #3b: lambdarank (MSLR-WEB30K shape — graded relevance over
    # query groups, rank:ndcg LambdaMART)

    rk_rows = min(rows, 200_000)
    rng = np.random.default_rng(4)
    Xr = rng.normal(size=(rk_rows, 20)).astype(np.float32)
    qid = np.sort(rng.integers(0, rk_rows // 100, size=rk_rows))
    rel = np.clip((Xr[:, 0] + 0.5 * Xr[:, 1]
                   + rng.normal(scale=0.8, size=rk_rows)) * 1.2 + 2,
                  0, 4).astype(np.float32).round()
    rcols = {f"f{i}": Xr[:, i] for i in range(20)}
    rcols["rel"] = rel
    rcols["qid"] = qid.astype(np.float32)
    fr_rk = h2o.Frame.from_arrays(rcols)
    m, dt = _timed(lambda: XGBoost(
        ntrees=10, max_depth=6, objective="rank:ndcg", seed=1).train(
        y="rel", training_frame=fr_rk, group_column="qid"))
    ndcg = m.model_performance(fr_rk, y="rel")
    record("xgboost_lambdarank", rk_rows * 10 / dt, "rows*trees/s", dt,
           rows_rank=rk_rows,
           ndcg10=round(float(ndcg.get("ndcg@10", float("nan"))), 5))

    # config #4: DeepLearning MLP, one pass (model-averaging allreduce)
    dl_rows = min(rows, 200_000)
    fr_dl = _higgs_like(dl_rows, seed=2)
    m, dt = _timed(lambda: DeepLearning(
        hidden=[64, 64], epochs=1, seed=1).train(
        y="y", training_frame=fr_dl))
    record("deeplearning_mlp", dl_rows / dt, "rows/s", dt,
           rows_dl=dl_rows, hidden=[64, 64])

    # config #4b: Word2Vec skip-gram over a synthetic NA-delimited
    # corpus (sentence rows; negative-sampling epochs)
    from h2o_kubernetes_tpu.models import Word2Vec

    n_tok = min(rows // 2, 200_000)
    vocab = np.array([f"w{i}" for i in range(2000)])
    toks = vocab[rng.integers(0, 2000, size=n_tok)].astype(object)
    toks[:: 17] = None                       # sentence breaks
    fr_w2v = h2o.Frame.from_arrays({"words": np.array(toks)})
    m, dt = _timed(lambda: Word2Vec(
        vec_size=32, epochs=1, min_word_freq=2, seed=1).train(fr_w2v))
    record("word2vec_skipgram", n_tok / dt, "tokens/s", dt,
           tokens=n_tok, vec_size=32)

    out = {"suite": results, "captured_at":
           time.strftime("%Y-%m-%dT%H:%M:%S")}
    path = os.path.join(
        REPO,
        f"BENCH_SUITE_{'TPU' if on_tpu else 'CPU'}_r04.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"bench_suite": "done", "configs": len(results),
                      "platform": platform}))
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception as e:    # one diagnostic line, never a bare death
        import traceback

        traceback.print_exc()
        print(json.dumps({"bench_suite": "error", "error": repr(e)[:300]}))
        sys.exit(1)
