"""AutoML wall-clock scaling evidence (Airlines-10M config shape).

The north star (`BASELINE.json` config #5) is AutoML wall-clock on an
Airlines-10M-shaped table. This harness produces the round's evidence
either way:

- on a live TPU (``--rows 10000000 --max-models 12``, run by
  tools/tpu_watch.py after a bench capture): the on-chip wall-clock +
  leaderboard the north star is phrased in;
- on the CPU mesh (default): a rows-scaling curve with XLA
  **compile-count accounting** — the count must NOT grow with
  max_models (no per-model recompiles; dispatch-budget chunking and
  shared jitted trainers mean every same-shaped model reuses the same
  executables).

Prints one JSON line per shape + a trailing summary line, and writes
``AUTOML_SCALE_r05.json`` (CPU) / ``AUTOML_TPU_r05.json`` (TPU) at the
repo root.
"""

import argparse
import json
import logging
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


class _CompileCounter(logging.Handler):
    """Counts XLA compiles via jax's log_compiles events."""

    def __init__(self):
        super().__init__()
        self.count = 0

    def emit(self, record):
        if "Compiling" in record.getMessage():
            self.count += 1


def make_table(rows: int, seed: int = 0):
    # the full airlines shape (~27 mixed columns, NAs, enum response) —
    # the table BASELINE.json config #5 is phrased in
    from tools.datasets import airlines_frame

    return airlines_frame(rows, seed=seed)


def run_shape(rows: int, max_models: int, nfolds: int,
              max_runtime_secs: float | None = None,
              exclude_algos=None, include_algos=None) -> dict:
    import traceback

    import jax

    from h2o_kubernetes_tpu.automl import AutoML

    counter = _CompileCounter()
    # ONLY the root 'jax' logger: records from jax submodules propagate
    # up the hierarchy, so attaching to a child too would double-count
    jax.config.update("jax_log_compiles", True)
    logging.getLogger("jax").addHandler(counter)
    err = None
    aml = None
    lb = []
    wall = 0.0
    try:
        fr = make_table(rows)
        t0 = time.perf_counter()
        aml = AutoML(max_models=max_models, nfolds=nfolds, seed=1,
                     max_runtime_secs=max_runtime_secs,
                     exclude_algos=exclude_algos,
                     include_algos=include_algos,
                     project_name=f"scale_{rows}")
        aml.train(y="IsDepDelayed", training_frame=fr)
        wall = time.perf_counter() - t0
        lb = aml.leaderboard.as_list()
    except Exception:
        # a crashed shape must still leave a diagnosable record — the
        # first on-chip 10M run died with nothing but an exit code
        err = traceback.format_exc()[-2000:]
    finally:
        jax.config.update("jax_log_compiles", False)
        logging.getLogger("jax").removeHandler(counter)
    out = {
        "rows": rows,
        "max_models": max_models,
        "nfolds": nfolds,
        "max_runtime_secs": max_runtime_secs,
        "models_trained": len(lb),
        "wall_seconds": round(wall, 1),
        "xla_compiles": counter.count,
        "leader": lb[0]["model_id"] if lb else None,
        "leader_auc": round(lb[0].get("auc", float("nan")), 5)
        if lb else None,
        # full-precision rows: the bench's pipelined-vs-serial identity
        # check compares every printed digit (minus wall-clock fields)
        "leaderboard": lb,
        # overlap accounting when the pipelined executor ran
        # (runtime/scheduler.py; None on H2O_TPU_AUTOML_PIPELINE=0)
        "scheduler_stats": aml.scheduler_stats if aml is not None
        else None,
        "platform": jax.default_backend(),
        # the event log carries every swallowed per-model failure —
        # a 1-model leaderboard is explainable from the artifact alone
        "event_log": [f"{ts} {m}" for ts, m in
                      (aml.event_log if aml is not None else [])][-60:],
        "error": err,
    }
    print(json.dumps(out), flush=True)
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, nargs="+", default=None,
                    help="row counts (default: 100k/300k/1M cpu curve)")
    ap.add_argument("--max-models", type=int, default=6)
    ap.add_argument("--nfolds", type=int, default=3)
    ap.add_argument("--max-runtime-secs", type=float, default=None,
                    help="AutoML time budget per shape (the on-chip "
                    "10M capture sets this so it fits inside a chip "
                    "availability window; the metric becomes "
                    "models+leader-AUC within the budget — the same "
                    "fixed-time framing the reference's AutoML wall-"
                    "clock comparisons use)")
    ap.add_argument("--exclude-algos", nargs="+", default=None,
                    help="AutoML families to skip (the 1M-row CPU "
                    "curve drops drf/deeplearning: 100 depth-12 CPU "
                    "trees per point measure the box, not the design)")
    ap.add_argument("--include-algos", nargs="+", default=None,
                    help="restrict the plan to these families "
                    "(mutually exclusive with --exclude-algos)")
    ap.add_argument("--no-recompile-check", action="store_true",
                    help="skip the warm-repeat recompile check (the "
                    "automl_wall bench runs serial/pipelined legs in "
                    "separate processes and checks warm compiles on "
                    "one leg only)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from h2o_kubernetes_tpu.runtime.backend import ensure_live_backend

    ensure_live_backend()
    import jax

    on_tpu = jax.default_backend() == "tpu"
    rows_list = args.rows or ([10_000_000] if on_tpu
                              else [100_000, 300_000, 1_000_000])
    results = [run_shape(r, args.max_models, args.nfolds,
                         args.max_runtime_secs, args.exclude_algos,
                         args.include_algos)
               for r in rows_list]
    # per-model recompile check: a WARM repeat of the smallest shape
    # (same families, same row count, same plan) must compile ~nothing
    # — every fold/final/ensemble train reuses the shape-keyed
    # executables from the first pass. (A half-max_models comparison is
    # confounded: fewer models means fewer FAMILIES, so the compile
    # delta measures family difference, not per-model recompiles.)
    # CPU-mesh only: on chip it would double the wall inside a scarce
    # availability window for a diagnostic the CPU curve already gives.
    recompile_check = None
    if not on_tpu and not args.no_recompile_check and len(results) >= 1 \
            and not results[0].get("error"):
        warm = run_shape(rows_list[0], args.max_models, args.nfolds,
                         args.max_runtime_secs, args.exclude_algos,
                         args.include_algos)
        recompile_check = {
            "cold_models": results[0]["models_trained"],
            "cold_compiles": results[0]["xla_compiles"],
            "warm_models": warm["models_trained"],
            "warm_compiles": warm["xla_compiles"],
            "warm_run_ok": warm["xla_compiles"]
            <= max(5, results[0]["xla_compiles"] // 20),
        }
    summary = {"curve": results, "recompile_check": recompile_check,
               "captured_at": time.strftime("%Y-%m-%dT%H:%M:%S")}
    out = args.out or os.path.join(
        REPO, "AUTOML_TPU_r05.json" if on_tpu else "AUTOML_SCALE_r05.json")
    with open(out, "w") as f:
        json.dump(summary, f, indent=1)
    print(json.dumps({"automl_scale": "done", "file": out,
                      "shapes": len(results)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
