"""Per-phase + per-op GBM profile on the live accelerator.

The bench number (bench.py) times the whole ``train()``; this tool
breaks it down so kernel work is attacked where the time actually is:

1. wall-clock per phase (parse→device, fit_bins, apply_bins, init,
   fused boost dispatch, model finalize), each block_until_ready'd;
2. an XLA op-level profile of the boost dispatch alone via
   ``jax.profiler.trace``, aggregated from the perfetto trace into
   top-op self-times (no tensorboard needed — the trace JSON is parsed
   directly).

Writes ``PROFILE_TPU_r05.json`` (or ``PROFILE_CPU_r05.json``) at the
repo root and prints one JSON summary line. Run by tools/tpu_watch.py
once per chip window after the bench capture.
"""

import glob
import gzip
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _phase(name, fn, out):
    t0 = time.perf_counter()
    r = fn()
    import jax

    jax.block_until_ready(r) if r is not None else None
    dt = time.perf_counter() - t0
    out[name] = round(dt, 4)
    return r


def _parse_trace(log_dir: str, top: int = 30):
    """Aggregate device-track op self-times from the perfetto trace.

    The device pid carries several thread tracks — "XLA Ops" (leaf op
    executions) but also "XLA Modules" / "Steps" spans that COVER the
    ops; summing every complete event under the pid would double-count
    each op inside its module span. Only op-level tracks are summed:
    the "XLA Ops" threads when present, else the pid's threads minus
    the known enclosing-span tracks."""
    paths = glob.glob(os.path.join(log_dir, "**", "*.trace.json.gz"),
                      recursive=True)
    if not paths:
        return {"error": "no trace file"}
    with gzip.open(sorted(paths)[-1], "rt") as f:
        trace = json.load(f)
    ev = trace.get("traceEvents", [])
    pid_names, tid_names = {}, {}
    for e in ev:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            pid_names[e.get("pid")] = e.get("args", {}).get("name", "")
        elif e.get("name") == "thread_name":
            tid_names[(e.get("pid"), e.get("tid"))] = \
                e.get("args", {}).get("name", "")
    device_pids = {p for p, n in pid_names.items()
                   if "TPU" in n or "device" in n.lower()}
    op_tracks = {k for k, n in tid_names.items()
                 if (not device_pids or k[0] in device_pids)
                 and "XLA Ops" in n}
    if not op_tracks:
        span = ("XLA Modules", "Steps", "Framework")
        op_tracks = {k for k, n in tid_names.items()
                     if (not device_pids or k[0] in device_pids)
                     and not any(s in n for s in span)}
    agg: dict[str, float] = {}
    total = 0.0
    for e in ev:
        if e.get("ph") != "X":
            continue
        if op_tracks and (e.get("pid"), e.get("tid")) not in op_tracks:
            continue
        if not op_tracks and device_pids \
                and e.get("pid") not in device_pids:
            continue
        name = e.get("name", "?")
        dur = float(e.get("dur", 0.0)) / 1e6       # us -> s
        agg[name] = agg.get(name, 0.0) + dur
        total += dur
    ops = sorted(agg.items(), key=lambda kv: -kv[1])[:top]
    return {"total_device_s": round(total, 4),
            "ops": [{"name": k, "s": round(v, 4)} for k, v in ops]}


def main() -> int:
    from h2o_kubernetes_tpu.runtime.backend import ensure_live_backend

    ensure_live_backend(budget=float(
        os.environ.get("H2O_TPU_PROBE_BUDGET", "300")))
    import jax
    import numpy as np

    import h2o_kubernetes_tpu as h2o
    from h2o_kubernetes_tpu.models.gbm import (GBM, _init_margin)
    from h2o_kubernetes_tpu.models.tree.binning import (apply_bins_jit,
                                                        fit_bins)
    from h2o_kubernetes_tpu.models.tree.core import (BoostParams,
                                                     TreeParams,
                                                     boost_trees)
    from h2o_kubernetes_tpu.models.base import resolve_xy

    platform = jax.default_backend()
    rows = int(os.environ.get("BENCH_ROWS",
                              1_000_000 if platform == "tpu" else 50_000))
    ntrees = int(os.environ.get("BENCH_TREES", 10))
    rng = np.random.default_rng(0)
    F = 10
    X = {f"x{i}": rng.normal(size=rows).astype(np.float32)
         for i in range(F - 2)}
    X["c1"] = np.array(["a", "b", "c", "d", "e", "f", "g", "h"])[
        rng.integers(0, 8, size=rows)]
    X["dep_delay"] = rng.exponential(10.0, size=rows).astype(np.float32)
    logit = (1.2 * X["x0"] - 0.8 * X["x1"] + 0.05 * X["dep_delay"]
             - 1.0 + rng.normal(scale=0.5, size=rows))
    X["y"] = np.where(logit > 0, "late", "ontime")

    phases: dict[str, float] = {}
    import jax.numpy as jnp

    fr = _phase("frame_build", lambda: h2o.Frame.from_arrays(X), phases)
    data = resolve_xy(fr, "y", None, None, None, "auto", None)
    jax.block_until_ready(data.X)
    spec = _phase("fit_bins", lambda: fit_bins(fr, data.feature_names,
                                               n_bins=256), phases)
    edges = jnp.asarray(spec.edges_matrix())
    enum_mask = jnp.asarray(np.array(spec.is_enum))
    binned = _phase("apply_bins", lambda: apply_bins_jit(
        data.X, edges, enum_mask, spec.na_bin), phases)
    off = jnp.zeros_like(data.y)
    init, margin = _phase("init_margin", lambda: _init_margin(
        data.y, data.w, off, "bernoulli", 1), phases)
    tp = TreeParams(max_depth=5, n_bins=256)
    bp = BoostParams(distribution="bernoulli", learn_rate=0.2)
    key = jax.random.key(1)

    # compile (untimed), then timed steady-state dispatch
    _phase("boost_compile+run", lambda: boost_trees(
        binned, data.y, data.w, margin, key, ntrees, tp, bp)[0], phases)
    _phase("boost_steady", lambda: boost_trees(
        binned, data.y, data.w, margin, key, ntrees, tp, bp)[0], phases)

    # op-level profile of ONE steady-state boost dispatch
    log_dir = os.path.join(REPO, "tools", "_profile_run")
    os.makedirs(log_dir, exist_ok=True)
    with jax.profiler.trace(log_dir):
        m2, trees = boost_trees(binned, data.y, data.w, margin, key,
                                ntrees, tp, bp)
        jax.block_until_ready(m2)
    op_profile = _parse_trace(log_dir)

    # end-to-end train() for reference (same as bench.py's timed unit)
    def full():
        return GBM(ntrees=ntrees, max_depth=5, learn_rate=0.2,
                   seed=1).train(y="y", training_frame=fr)

    full()                                  # warm
    _phase("full_train_steady", full, phases)

    out = {"platform": platform, "rows": rows, "trees": ntrees,
           "phases": phases, "op_profile": op_profile,
           "captured_at": time.strftime("%Y-%m-%dT%H:%M:%S")}
    path = os.path.join(
        REPO, f"PROFILE_{'TPU' if platform == 'tpu' else 'CPU'}_r05.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"profile": "ok", "platform": platform,
                      "phases": phases,
                      "device_total_s":
                      op_profile.get("total_device_s")}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
