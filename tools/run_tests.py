"""Chunked test runner — the supported way to run the whole suite.

A monolithic ``pytest tests/`` on a small host can stall indefinitely:
XLA:CPU's collective rendezvous starves when many mesh tests share one
core with background load (tests/conftest.py documents the failure
mode; VERDICT r4 hit it live). Running module-by-module bounds each
rendezvous window and makes a hang attributable to a file. CI and the
round ritual both use this entry point.

Usage:
    python tools/run_tests.py           # fast tier (-m "not slow")
    python tools/run_tests.py --slow    # slow tier only
    python tools/run_tests.py --all     # both tiers
    python tools/run_tests.py --chaos   # chaos drill suite only
                                        # (tools/chaos.py all); combine
                                        # with --all/--slow to append it
    python tools/run_tests.py --timeout 1200   # per-module cap
    python tools/run_tests.py --tier1-sharded  # THE tier-1 verify:
                                        # fast tier, per-module
                                        # timeouts, aggregate
                                        # DOTS_PASSED=<n> + rc

``--tier1-sharded`` is the ROADMAP verify entry point: the monolithic
``pytest tests/`` command outgrew any single wall cap on a 1-core box
(rc 124 at ~76% with zero failures), so the verify now runs the same
fast tier sharded module-by-module — each module under its own
``--timeout`` — and aggregates the per-module pytest pass counts into
one ``DOTS_PASSED=<total>`` line and one exit code (0 only if every
module passed). Same tests, same markers; only the wall-cap
granularity changed.

A preflight scan warns (or, with ``--strict-preflight`` /
``H2O_TPU_PREFLIGHT_STRICT=1``, fails) when orphaned bench/AutoML
processes are still running on the box — a leftover
``automl_scale_10m.py`` once starved tier-1 into rendezvous stalls,
and nothing timed on a contended core is trustworthy.

Prints one status line per module and a final JSON summary; exit 0
only if every module passed.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# cmdline fragments that mark a bench/AutoML workload: one such process
# left over from an earlier round starves the shared core and turns
# tier-1's collective rendezvous into timeouts (the stale
# automl_scale_10m.py found at 72% CPU during the PR-4 round did
# exactly that — CHANGES.md PR 4 ops note)
_ORPHAN_PATTERNS = ("automl_scale", "bench_suite", "bench.py",
                    "boost_profile", "tpu_watch", "score_load",
                    "automl_wall", "operator.pod")

# operator scorer-pool pods are REAPED (SIGKILL), not just reported —
# but ONLY when their parent reconciler is gone (the pod has been
# reparented to init): a pod only exists as a child of a reconciler,
# so an orphaned one is unambiguously a wedged drill's leftover — a
# full JAX interpreter holding a port and a core, guaranteed to starve
# the tier-1 run that follows. A pod whose parent is still alive may
# belong to a drill or operator running concurrently on this box and
# is reported, never killed. The other patterns stay warn-only.
_REAP_PATTERNS = ("operator.pod",)


def _ppid(pid: int) -> int | None:
    try:
        with open(f"/proc/{pid}/stat") as f:
            return int(f.read().split(")")[-1].split()[1])
    except (OSError, ValueError, IndexError):
        return None


def find_orphan_processes() -> list[tuple[int, str]]:
    """(pid, cmdline) of processes that look like leftover bench/AutoML
    workloads — excluding this process and its ancestors (running the
    suite FROM a bench wrapper must not flag itself)."""
    me = os.getpid()
    ancestors = set()
    pid = me
    for _ in range(32):                     # walk up to init
        try:
            with open(f"/proc/{pid}/stat") as f:
                ppid = int(f.read().split(")")[-1].split()[1])
        except (OSError, ValueError, IndexError):
            break
        ancestors.add(pid)
        if ppid <= 1:
            break
        pid = ppid
    out = []
    try:
        pids = [int(d) for d in os.listdir("/proc") if d.isdigit()]
    except OSError:
        return out                          # no procfs (macOS): skip
    for pid in pids:
        if pid == me or pid in ancestors:
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                argv = f.read().split(b"\0")
                cmd = b" ".join(argv).decode(errors="replace").strip()
        except OSError:
            continue
        # only interpreter processes count: 'vim tools/bench.py' or a
        # grep mentioning the name is not a workload
        if not argv or b"python" not in argv[0].lower():
            continue
        if cmd and any(pat in cmd for pat in _ORPHAN_PATTERNS):
            out.append((pid, cmd[:160]))
    return out


def _adoptable_manifest(pid: int, cmd: str) -> str | None:
    """Path of a VALID adoption manifest on this pod's cmdline, else
    None. A parentless pod whose `--manifest` file exists and names
    this pid is ADOPTABLE — a restartable operator's data plane
    surviving its controller (docs/OPERATOR.md "Control-plane
    recovery"), not a leak. The reaper must report it, never kill it.
    A pod whose manifest is gone (drill workdir deleted) or lies
    about the pid is an ordinary leak and still gets reaped."""
    parts = cmd.split()
    try:
        path = parts[parts.index("--manifest") + 1]
    except (ValueError, IndexError):
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
        return path if int(doc.get("pid", -1)) == pid else None
    except (OSError, ValueError):
        return None


def reap_orphan_pods(orphans: list[tuple[int, str]]
                     ) -> list[tuple[int, str]]:
    """SIGKILL orphaned scorer-pool pods — pods whose reconciler
    parent is gone (ppid reparented to init); see _REAP_PATTERNS.
    Returns the orphans still left to report: pods with a live parent
    (a concurrent drill/operator owns them), ADOPTABLE pods (live
    manifest — a restarted operator will inherit them) and anything
    that refuses to die, so a strict preflight still fails on them."""
    import signal

    remaining = []
    for pid, cmd in orphans:
        ppid = _ppid(pid)
        if not any(pat in cmd for pat in _REAP_PATTERNS) \
                or ppid is None or ppid > 1:
            remaining.append((pid, cmd))
            continue
        man = _adoptable_manifest(pid, cmd)
        if man is not None:
            print(f"[preflight] pod {pid} is parentless but "
                  f"ADOPTABLE (manifest {man}) — reporting, not "
                  f"killing: {cmd}", flush=True)
            remaining.append((pid, cmd))
            continue
        try:
            os.kill(pid, signal.SIGKILL)
            print(f"[preflight] reaped orphaned scorer-pool pod "
                  f"{pid} (parent gone): {cmd}", flush=True)
        except ProcessLookupError:
            pass                     # already gone
        except PermissionError:
            remaining.append((pid, cmd))
    return remaining


def preflight(strict: bool) -> bool:
    """Scan for orphaned bench/AutoML processes BEFORE timing anything;
    returns False (and prints the PIDs) when the box is not clean.
    Orphaned scorer-pool pods are reaped outright (a wedged drill's
    leftover must not starve the run); the rest warn by default and
    fail the run under --strict-preflight or
    H2O_TPU_PREFLIGHT_STRICT=1."""
    orphans = reap_orphan_pods(find_orphan_processes())
    if not orphans:
        return True
    print(f"[preflight] {len(orphans)} orphaned bench/automl "
          "process(es) are competing for this box — timings below "
          "are not trustworthy:", flush=True)
    for pid, cmd in orphans:
        print(f"[preflight]   pid {pid}: {cmd}", flush=True)
    if strict:
        print("[preflight] strict mode: refusing to run "
              "(kill the processes above or drop --strict-preflight)",
              flush=True)
        return False
    print("[preflight] continuing anyway (pass --strict-preflight to "
          "fail instead)", flush=True)
    return True


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--slow", action="store_true",
                    help="run only the slow-marked tier")
    ap.add_argument("--all", action="store_true",
                    help="run both tiers (fast then slow)")
    ap.add_argument("--chaos", action="store_true",
                    help="run the chaos drill suite (tools/chaos.py "
                    "all); alone it replaces the pytest tiers")
    ap.add_argument("--timeout", type=float, default=1500.0,
                    help="per-module wall cap (a starved rendezvous "
                    "hangs forever; this converts it into a named "
                    "module failure)")
    ap.add_argument("--strict-preflight", action="store_true",
                    help="fail (rc 2) when orphaned bench/automl "
                    "processes are found instead of warning")
    ap.add_argument("--tier1-sharded", action="store_true",
                    help="tier-1 verify mode: run the fast tier "
                    "module-by-module (each under its own --timeout) "
                    "and print an aggregate DOTS_PASSED=<n> line; "
                    "exit 0 only if every module passed")
    args = ap.parse_args()

    strict = args.strict_preflight or \
        os.environ.get("H2O_TPU_PREFLIGHT_STRICT") == "1"
    if not preflight(strict):
        return 2

    modules = sorted(glob.glob(os.path.join(REPO, "tests", "test_*.py")))
    tiers = (["not slow", "slow"] if args.all
             else ["slow"] if args.slow else ["not slow"])
    if args.tier1_sharded:
        tiers = ["not slow"]         # THE tier-1 verify tier
    if args.chaos and not (args.all or args.slow
                           or args.tier1_sharded):
        tiers = []                   # drills only
    results = []
    passed_total = 0
    t0 = time.monotonic()
    # per-test timing lines ([time] …, tests/conftest.py hook): on a
    # module TIMEOUT the partial output still carries every COMPLETED
    # test's duration, so the cap failure names the slow tests instead
    # of just the module
    env = dict(os.environ, H2O_TPU_TEST_TIMINGS="1")
    for tier in tiers:
        for mod in modules:
            name = os.path.basename(mod)
            cmd = [sys.executable, "-m", "pytest", mod, "-q",
                   "-m", tier, "--no-header", "-p", "no:cacheprovider"]
            start = time.monotonic()
            # own process group: on timeout kill the WHOLE group —
            # pytest's grandchildren (test_distributed's DCN workers)
            # would otherwise survive and starve every later module
            # into a cascade of timeouts
            proc = subprocess.Popen(cmd, cwd=REPO, env=env,
                                    stdout=subprocess.PIPE,
                                    stderr=subprocess.PIPE,
                                    start_new_session=True)
            try:
                out_b, err_b = proc.communicate(timeout=args.timeout)
                out = out_b.decode(errors="replace")
                if not out.strip():
                    # collection/usage errors (rc 2-4) print to stderr
                    out = err_b.decode(errors="replace")
                tail = out.strip().splitlines()[-1] if out.strip() else ""
                # rc 5 = no tests collected for this -m filter
                status = "ok" if proc.returncode == 0 else \
                    "none" if proc.returncode == 5 else "FAIL"
            except subprocess.TimeoutExpired as e:
                import signal

                try:
                    os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                out_b, _ = proc.communicate()
                partial = (e.stdout or out_b or b"").decode(
                    errors="replace")
                status = "TIMEOUT"
                tail = partial.strip().splitlines()[-1] \
                    if partial.strip() else ""
                # keep the per-module cap honest: name the slowest 5
                # COMPLETED tests (and by elimination, the stuck one is
                # whatever started after the last [time] line)
                times = []
                for ln in partial.splitlines():
                    if ln.startswith("[time] "):
                        parts = ln.split(maxsplit=2)
                        try:
                            times.append((float(parts[1].rstrip("s")),
                                          parts[2]))
                        except (IndexError, ValueError):
                            pass
                for secs, node in sorted(times, reverse=True)[:5]:
                    print(f"    [slow] {secs:8.2f}s {node}", flush=True)
            dt = time.monotonic() - start
            # pytest -q summary tail ("30 passed, 1 warning in 27.7s")
            # → per-module pass count, aggregated into DOTS_PASSED for
            # --tier1-sharded (the sharded analog of counting dots)
            m = re.search(r"(\d+) passed", tail)
            mod_passed = int(m.group(1)) if m else 0
            passed_total += mod_passed
            results.append({"module": name, "tier": tier,
                            "status": status, "seconds": round(dt, 1),
                            "passed": mod_passed,
                            "tail": tail[-120:]})
            print(f"[{status:>7}] {name:<32} ({tier}) {dt:6.1f}s "
                  f"{tail[-80:]}", flush=True)

    if args.chaos:
        # the drill suite is one subprocess, same timeout discipline as
        # a test module (a wedged drain must become a named failure)
        cmd = [sys.executable, os.path.join(REPO, "tools", "chaos.py"),
               "all"]
        start = time.monotonic()
        # own process group, like the module loop above: on timeout the
        # drill's grandchildren (drain-under-load's pod subprocess — a
        # full JAX interpreter with a REST server) must die with it
        proc = subprocess.Popen(cmd, cwd=REPO, env=env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE,
                                start_new_session=True)
        try:
            out_b, err_b = proc.communicate(timeout=args.timeout)
            out = (out_b + err_b).decode(errors="replace")
            status = "ok" if proc.returncode == 0 else "FAIL"
        except subprocess.TimeoutExpired as e:
            import signal

            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            out_b, err_b = proc.communicate()
            out = ((e.stdout or out_b or b"")
                   + (e.stderr or err_b or b"")).decode(errors="replace")
            status = "TIMEOUT"
        dt = time.monotonic() - start
        tail = out.strip().splitlines()[-1] if out.strip() else ""
        results.append({"module": "chaos.py all", "tier": "chaos",
                        "status": status, "seconds": round(dt, 1),
                        "tail": tail[-120:]})
        print(f"[{status:>7}] {'chaos.py all':<32} (chaos) {dt:6.1f}s "
              f"{tail[-80:]}", flush=True)

    failed = [r for r in results if r["status"] in ("FAIL", "TIMEOUT")]
    summary = {
        "run_tests": "pass" if not failed else "fail",
        "modules": len(results),
        "failed": [r["module"] for r in failed],
        "wall_seconds": round(time.monotonic() - t0, 1)}
    if args.tier1_sharded:
        summary["passed"] = passed_total
        # same grep-able shape as the old monolithic verify line, so
        # round tooling keeps one regex across both eras
        print(f"DOTS_PASSED={passed_total}", flush=True)
    print(json.dumps(summary))
    return 0 if not failed else 1


if __name__ == "__main__":
    sys.exit(main())
