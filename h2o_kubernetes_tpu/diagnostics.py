"""Diagnostics — timeline ring, leveled logging, profiler hooks.

Reference (SURVEY.md §5.1, §5.5):
- water/TimeLine.java: per-node in-memory ring of runtime events,
  exposed at /3/Timeline — here a host-side ring buffer that training
  drivers and the runtime append to;
- water/util/Log: leveled per-node log — here a thin stdlib-logging
  wrapper with the same level names;
- WaterMeter CPU ticks / jProfile: device-side profiling — here
  `profile()` wraps jax.profiler.trace (xprof/perfetto traces viewable
  in TensorBoard), and `device_memory()` surfaces live HBM usage.
"""

from __future__ import annotations

import collections
import contextlib
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Iterator

__all__ = ["TimeLine", "timeline", "log", "profile", "device_memory"]


@dataclass
class _Event:
    ts: float
    kind: str
    msg: str
    data: dict[str, Any] = field(default_factory=dict)


class TimeLine:
    """Fixed-size event ring (water/TimeLine analog; thread-safe).

    Alongside the ring it keeps CUMULATIVE per-kind counts: the ring
    is bounded (a long ooc train's per-level phase spans would
    otherwise evict the rare operational events' history entirely),
    so rates and totals live in ``kind_counts()`` — registered as the
    ``timeline`` stat group with the fleet-telemetry registry, which
    puts event rates on ``GET /metrics`` even after the events
    themselves aged out of ``/3/Timeline``."""

    def __init__(self, capacity: int = 4096):
        self._ring: collections.deque[_Event] = collections.deque(
            maxlen=capacity)
        self._counts: collections.Counter = collections.Counter()
        self._lock = threading.Lock()

    def record(self, kind: str, msg: str = "", **data) -> None:
        with self._lock:
            self._ring.append(_Event(time.time(), kind, msg, data))
            self._counts[kind] += 1

    def events(self, kind: str | None = None) -> list[dict[str, Any]]:
        """Snapshot, oldest first (the /3/Timeline payload)."""
        with self._lock:
            evs = list(self._ring)
        return [{"ts": e.ts, "kind": e.kind, "msg": e.msg, **e.data}
                for e in evs if kind is None or e.kind == kind]

    def kind_counts(self) -> dict[str, int]:
        """Cumulative events per kind since process start — NOT
        ring-bounded (the counts survive eviction)."""
        with self._lock:
            return dict(self._counts)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


timeline = TimeLine()

from .runtime.telemetry import register_group as _register_tel_group  # noqa: E402

_register_tel_group("timeline", timeline.kind_counts)

log = logging.getLogger("h2o_kubernetes_tpu")
if not log.handlers:
    _h = logging.StreamHandler()
    _h.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname).4s %(name)s: %(message)s"))
    log.addHandler(_h)
    from .config import get_config

    log.setLevel(getattr(logging, str(get_config("log_level")).upper(),
                         logging.WARNING))


@contextlib.contextmanager
def profile(logdir: str) -> Iterator[None]:
    """Device profiler trace around a block (xprof; open in TensorBoard).

    The analog of the reference's WaterMeter/jProfile endpoints — but
    captured by XLA itself, so it shows real MXU/HBM activity.
    """
    import jax

    timeline.record("profile_start", logdir=logdir)
    with jax.profiler.trace(logdir):
        yield
    timeline.record("profile_stop", logdir=logdir)


def device_memory() -> list[dict[str, Any]]:
    """Live per-device memory stats (HBM analog of /3/Cloud free_mem)."""
    import jax

    out = []
    for d in jax.devices():
        stats = {}
        try:
            stats = d.memory_stats() or {}
        except Exception:
            pass
        out.append({"device": str(d),
                    "bytes_in_use": stats.get("bytes_in_use"),
                    "bytes_limit": stats.get("bytes_limit")})
    return out
