"""Cloud persist backends: s3://, gs://, hdfs:// for PERSIST_SCHEMES.

Reference: water/persist/{PersistS3,PersistGcs,PersistHdfs} (SURVEY.md
§2b C20) back the same verbs (save_model/load_model/export_file/
AutoML checkpoint_dir) on cloud object stores. These implementations
speak the stores' REST protocols directly with the standard library —
no SDK import is required, so a TPU pod image needs nothing extra:

- s3://bucket/key — AWS Signature V4 over HTTPS. Credentials from the
  standard env (AWS_ACCESS_KEY_ID / AWS_SECRET_ACCESS_KEY /
  AWS_SESSION_TOKEN, region from AWS_REGION); unsigned anonymous
  requests when no credentials are set (public buckets, fakes).
  Endpoint override: AWS_ENDPOINT_URL (path-style addressing — the
  convention minio/localstack/moto use).
- gs://bucket/key — GCS JSON API (storage/v1). Bearer token from
  GOOGLE_OAUTH_ACCESS_TOKEN when set, else anonymous. Endpoint
  override: STORAGE_EMULATOR_HOST (the official GCS emulator env).
- hdfs://path — WebHDFS (OPEN / CREATE with the two-step redirect
  dance). Namenode from H2O_TPU_WEBHDFS (e.g. http://namenode:9870);
  the hdfs:// path maps to /webhdfs/v1<path>.

All three register in persist.PERSIST_SCHEMES at import (persist.py
imports this module), exactly like a PersistManager provider.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import os
import urllib.error
import urllib.parse
import urllib.request
from datetime import datetime, timezone

__all__ = ["s3_read", "s3_write", "gs_read", "gs_write",
           "hdfs_read", "hdfs_write"]


def _http(method: str, url: str, data: bytes | None = None,
          headers: dict | None = None, timeout: float = 60.0) -> bytes:
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:  # noqa: S310
            return r.read()
    except urllib.error.HTTPError as e:
        body = e.read()[:300].decode(errors="replace")
        if e.code == 404:
            # missing-object reads behave like a missing local file so
            # callers (e.g. the AutoML resume manifest) can distinguish
            # "not there yet" from auth/transport failures
            raise FileNotFoundError(f"{method} {url} -> HTTP 404") \
                from None
        raise IOError(
            f"{method} {url} -> HTTP {e.code}: {body}") from None


# -- s3:// -------------------------------------------------------------------

def _split_bucket_key(path: str) -> tuple[str, str]:
    scheme, _, rest = path.partition("://")
    if "/" not in rest:
        raise ValueError(f"{path}: expected {scheme}://bucket/key")
    bucket, key = rest.split("/", 1)
    if not bucket or not key:
        raise ValueError(f"{path}: expected {scheme}://bucket/key")
    return bucket, key


def _s3_url(bucket: str, key: str) -> tuple[str, str, str]:
    """(url, host, canonical_uri) with path-style for custom endpoints."""
    key_enc = urllib.parse.quote(key, safe="/~-._")
    endpoint = os.environ.get("AWS_ENDPOINT_URL")
    if endpoint:
        endpoint = endpoint.rstrip("/")
        parsed = urllib.parse.urlparse(endpoint)
        # the endpoint may be mounted under a subpath (gateway:9000/minio)
        # — the signature must cover the path the server actually sees
        base_path = parsed.path.rstrip("/")
        return (f"{endpoint}/{bucket}/{key_enc}", parsed.netloc,
                f"{base_path}/{bucket}/{key_enc}")
    region = os.environ.get("AWS_REGION",
                            os.environ.get("AWS_DEFAULT_REGION",
                                           "us-east-1"))
    host = f"{bucket}.s3.{region}.amazonaws.com"
    return f"https://{host}/{key_enc}", host, f"/{key_enc}"


def _sigv4_headers(method: str, host: str, canonical_uri: str,
                   payload: bytes) -> dict:
    """AWS Signature V4 (the exact algorithm PersistS3's SDK applies);
    returns {} when no credentials are in the env (anonymous)."""
    akid = os.environ.get("AWS_ACCESS_KEY_ID")
    secret = os.environ.get("AWS_SECRET_ACCESS_KEY")
    payload_hash = hashlib.sha256(payload).hexdigest()
    if bool(akid) != bool(secret):
        # half-configured credentials (e.g. a failed secret mount) must
        # not silently degrade to anonymous — the resulting 403 would
        # point at bucket policy instead of the real misconfiguration
        raise ValueError(
            "AWS credentials half-configured: set BOTH "
            "AWS_ACCESS_KEY_ID and AWS_SECRET_ACCESS_KEY (or neither "
            "for anonymous access)")
    if not akid:
        return {"x-amz-content-sha256": payload_hash}
    region = os.environ.get("AWS_REGION",
                            os.environ.get("AWS_DEFAULT_REGION",
                                           "us-east-1"))
    now = datetime.now(timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")
    token = os.environ.get("AWS_SESSION_TOKEN")
    headers = {"host": host, "x-amz-content-sha256": payload_hash,
               "x-amz-date": amz_date}
    if token:
        headers["x-amz-security-token"] = token
    signed = ";".join(sorted(headers))
    canonical = "\n".join([
        method, canonical_uri, "",
        "".join(f"{k}:{headers[k]}\n" for k in sorted(headers)),
        signed, payload_hash])
    scope = f"{datestamp}/{region}/s3/aws4_request"
    to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canonical.encode()).hexdigest()])

    def _hm(key: bytes, msg: str) -> bytes:
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = _hm(_hm(_hm(_hm(b"AWS4" + secret.encode(), datestamp),
                    region), "s3"), "aws4_request")
    sig = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
    out = dict(headers)
    del out["host"]          # urllib sets Host itself
    out["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={akid}/{scope}, "
        f"SignedHeaders={signed}, Signature={sig}")
    return out


def s3_read(path: str) -> bytes:
    bucket, key = _split_bucket_key(path)
    url, host, uri = _s3_url(bucket, key)
    return _http("GET", url, headers=_sigv4_headers("GET", host, uri,
                                                    b""))


def s3_write(path: str, data: bytes) -> None:
    bucket, key = _split_bucket_key(path)
    url, host, uri = _s3_url(bucket, key)
    _http("PUT", url, data=data,
          headers=_sigv4_headers("PUT", host, uri, data))


# -- gs:// -------------------------------------------------------------------

def _gs_endpoint() -> str:
    ep = os.environ.get("STORAGE_EMULATOR_HOST")
    if ep:
        if "://" not in ep:
            ep = "http://" + ep
        return ep.rstrip("/")
    return "https://storage.googleapis.com"


def _gs_headers() -> dict:
    tok = os.environ.get("GOOGLE_OAUTH_ACCESS_TOKEN")
    return {"Authorization": f"Bearer {tok}"} if tok else {}


def gs_read(path: str) -> bytes:
    bucket, key = _split_bucket_key(path)
    obj = urllib.parse.quote(key, safe="")
    url = (f"{_gs_endpoint()}/storage/v1/b/{bucket}/o/{obj}?alt=media")
    return _http("GET", url, headers=_gs_headers())


def gs_write(path: str, data: bytes) -> None:
    bucket, key = _split_bucket_key(path)
    name = urllib.parse.quote(key, safe="")
    url = (f"{_gs_endpoint()}/upload/storage/v1/b/{bucket}/o"
           f"?uploadType=media&name={name}")
    headers = {"Content-Type": "application/octet-stream",
               **_gs_headers()}
    _http("POST", url, data=data, headers=headers)


# -- hdfs:// -----------------------------------------------------------------

def _webhdfs_base() -> str:
    base = os.environ.get("H2O_TPU_WEBHDFS")
    if not base:
        raise ValueError(
            "hdfs:// needs H2O_TPU_WEBHDFS (namenode HTTP address, "
            "e.g. http://namenode:9870)")
    return base.rstrip("/")


def _hdfs_path(path: str) -> str:
    # hdfs://nn/path and hdfs:///path both map to /path on the
    # configured namenode (the authority names the cluster, not a host
    # we contact directly — WebHDFS goes through H2O_TPU_WEBHDFS)
    rest = path[len("hdfs://"):]
    if rest.startswith("/"):
        p = rest
    else:
        p = "/" + rest.split("/", 1)[1] if "/" in rest else "/"
    return urllib.parse.quote(p, safe="/")


def hdfs_read(path: str) -> bytes:
    url = (f"{_webhdfs_base()}/webhdfs/v1{_hdfs_path(path)}?op=OPEN")
    # urllib follows the namenode->datanode redirect automatically
    return _http("GET", url)


class _NoRedirect(urllib.request.HTTPRedirectHandler):
    def redirect_request(self, *a, **k):
        return None


def hdfs_write(path: str, data: bytes) -> None:
    """WebHDFS two-step CREATE: PUT (no body) to the namenode, which
    307-redirects to a datanode; then PUT the data there.  urllib never
    follows redirects for PUT (and would drop the body if it did), so
    the dance is explicit.  Gateways/fakes that accept the create
    directly (2xx, no redirect) get the data in a second direct PUT."""
    url = (f"{_webhdfs_base()}/webhdfs/v1{_hdfs_path(path)}"
           f"?op=CREATE&overwrite=true")
    opener = urllib.request.build_opener(_NoRedirect)
    req = urllib.request.Request(url, method="PUT")
    ct = {"Content-Type": "application/octet-stream"}
    try:
        with opener.open(req, timeout=60) as r:
            r.read()
        target = url                  # direct-accepting endpoint
    except urllib.error.HTTPError as e:
        if e.code in (301, 302, 307) and e.headers.get("Location"):
            target = e.headers["Location"]
        else:
            body = e.read()[:300].decode(errors="replace")
            raise IOError(
                f"PUT {url} -> HTTP {e.code}: {body}") from None
    _http("PUT", target, data=data, headers=ct)


def register(schemes: dict) -> None:
    schemes["s3"] = (s3_read, s3_write)
    schemes["gs"] = (gs_read, gs_write)
    schemes["gcs"] = (gs_read, gs_write)
    schemes["hdfs"] = (hdfs_read, hdfs_write)
