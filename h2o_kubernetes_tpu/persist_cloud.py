"""Cloud persist backends: s3://, gs://, hdfs:// for PERSIST_SCHEMES.

Reference: water/persist/{PersistS3,PersistGcs,PersistHdfs} (SURVEY.md
§2b C20) back the same verbs (save_model/load_model/export_file/
AutoML checkpoint_dir) on cloud object stores. These implementations
speak the stores' REST protocols directly with the standard library —
no SDK import is required, so a TPU pod image needs nothing extra:

- s3://bucket/key — AWS Signature V4 over HTTPS. Credentials from the
  standard env (AWS_ACCESS_KEY_ID / AWS_SECRET_ACCESS_KEY /
  AWS_SESSION_TOKEN, region from AWS_REGION); unsigned anonymous
  requests when no credentials are set (public buckets, fakes).
  Endpoint override: AWS_ENDPOINT_URL (path-style addressing — the
  convention minio/localstack/moto use).
- gs://bucket/key — GCS JSON API (storage/v1). Bearer token from
  GOOGLE_OAUTH_ACCESS_TOKEN when set, else anonymous. Endpoint
  override: STORAGE_EMULATOR_HOST (the official GCS emulator env).
- hdfs://path — WebHDFS (OPEN / CREATE with the two-step redirect
  dance). Namenode from H2O_TPU_WEBHDFS (e.g. http://namenode:9870);
  the hdfs:// path maps to /webhdfs/v1<path>.

All three register in persist.PERSIST_SCHEMES at import (persist.py
imports this module), exactly like a PersistManager provider.
"""

from __future__ import annotations

import hashlib
import hmac
import http.client
import json
import os
import urllib.error
import urllib.parse
import urllib.request
from datetime import datetime, timezone

__all__ = ["s3_read", "s3_write", "gs_read", "gs_write",
           "hdfs_read", "hdfs_write"]


def _retry_after(e: urllib.error.HTTPError) -> float | None:
    """Seconds from a Retry-After header (numeric form only — the
    HTTP-date form is rare on object stores and not worth a parser)."""
    try:
        raw = e.headers.get("Retry-After") if e.headers else None
        return float(raw) if raw else None
    except (TypeError, ValueError):
        return None


def _http(method: str, url: str, data: bytes | None = None,
          headers=None, timeout: float = 60.0,
          read: bool | None = None, policy=None) -> bytes:
    """One HTTP verb with the shared retry/backoff policy.

    Transients — 429, 5xx (honoring Retry-After), timeouts, connection
    resets, truncated transfers — retry under H2O_TPU_RETRY_* knobs, so
    an S3/GCS/WebHDFS blip no longer destroys a model save or an AutoML
    checkpoint. `read` marks the verb as a data fetch: ONLY reads map
    HTTP 404 to FileNotFoundError (callers like the resume manifest
    probe for "not there yet"); a 404 on a write (a WebHDFS CREATE
    redirect target or a deleted GCS upload session) is an IOError —
    the object is not "missing", the write path is broken.

    `headers` may be a dict or a zero-arg callable re-evaluated per
    attempt: SigV4 signatures (x-amz-date, 15-min validity) and OAuth
    bearer tokens must be FRESH on each retry, or a long outage ridden
    out under a raised H2O_TPU_RETRY_DEADLINE ends in a permanent 403
    once the first attempt's signature goes stale.
    """
    if read is None:
        read = method == "GET"
    from .runtime import faults, retry

    def attempt() -> bytes:
        hdrs = headers() if callable(headers) else (headers or {})
        req = urllib.request.Request(url, data=data, method=method,
                                     headers=hdrs)
        try:
            # the fault point sits INSIDE the classifier so injected
            # errors (real HTTPError/URLError/... instances) take the
            # exact retry/permanent path their real twins would
            faults.fire("persist.http", method=method, url=url)
            with urllib.request.urlopen(req, timeout=timeout) as r:  # noqa: S310
                return r.read()
        except urllib.error.HTTPError as e:
            body = e.read()[:300].decode(errors="replace")
            if e.code == 404:
                if read:
                    raise FileNotFoundError(
                        f"{method} {url} -> HTTP 404") from None
                raise IOError(f"{method} {url} -> HTTP 404 "
                              "(write target gone)") from None
            if e.code == 429 or e.code >= 500:
                raise retry.TransientError(
                    f"{method} {url} -> HTTP {e.code}: {body}",
                    retry_after=_retry_after(e)) from None
            raise IOError(
                f"{method} {url} -> HTTP {e.code}: {body}") from None
        except http.client.IncompleteRead as e:
            raise retry.TransientError(
                f"{method} {url} -> truncated transfer: {e!r}") from None
        except (TimeoutError, ConnectionError) as e:
            raise retry.TransientError(
                f"{method} {url} -> {e!r}") from None
        except urllib.error.URLError as e:
            raise retry.TransientError(
                f"{method} {url} -> {e.reason!r}") from None

    return retry.call(attempt, policy=policy, describe=f"{method} {url}")


# -- s3:// -------------------------------------------------------------------

def _split_bucket_key(path: str) -> tuple[str, str]:
    scheme, _, rest = path.partition("://")
    if "/" not in rest:
        raise ValueError(f"{path}: expected {scheme}://bucket/key")
    bucket, key = rest.split("/", 1)
    if not bucket or not key:
        raise ValueError(f"{path}: expected {scheme}://bucket/key")
    return bucket, key


def _s3_url(bucket: str, key: str) -> tuple[str, str, str]:
    """(url, host, canonical_uri) with path-style for custom endpoints."""
    key_enc = urllib.parse.quote(key, safe="/~-._")
    endpoint = os.environ.get("AWS_ENDPOINT_URL")
    if endpoint:
        endpoint = endpoint.rstrip("/")
        parsed = urllib.parse.urlparse(endpoint)
        # the endpoint may be mounted under a subpath (gateway:9000/minio)
        # — the signature must cover the path the server actually sees
        base_path = parsed.path.rstrip("/")
        return (f"{endpoint}/{bucket}/{key_enc}", parsed.netloc,
                f"{base_path}/{bucket}/{key_enc}")
    region = os.environ.get("AWS_REGION",
                            os.environ.get("AWS_DEFAULT_REGION",
                                           "us-east-1"))
    host = f"{bucket}.s3.{region}.amazonaws.com"
    return f"https://{host}/{key_enc}", host, f"/{key_enc}"


def _sigv4_headers(method: str, host: str, canonical_uri: str,
                   payload: bytes) -> dict:
    """AWS Signature V4 (the exact algorithm PersistS3's SDK applies);
    returns {} when no credentials are in the env (anonymous)."""
    akid = os.environ.get("AWS_ACCESS_KEY_ID")
    secret = os.environ.get("AWS_SECRET_ACCESS_KEY")
    payload_hash = hashlib.sha256(payload).hexdigest()
    if bool(akid) != bool(secret):
        # half-configured credentials (e.g. a failed secret mount) must
        # not silently degrade to anonymous — the resulting 403 would
        # point at bucket policy instead of the real misconfiguration
        raise ValueError(
            "AWS credentials half-configured: set BOTH "
            "AWS_ACCESS_KEY_ID and AWS_SECRET_ACCESS_KEY (or neither "
            "for anonymous access)")
    if not akid:
        return {"x-amz-content-sha256": payload_hash}
    region = os.environ.get("AWS_REGION",
                            os.environ.get("AWS_DEFAULT_REGION",
                                           "us-east-1"))
    now = datetime.now(timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")
    token = os.environ.get("AWS_SESSION_TOKEN")
    headers = {"host": host, "x-amz-content-sha256": payload_hash,
               "x-amz-date": amz_date}
    if token:
        headers["x-amz-security-token"] = token
    signed = ";".join(sorted(headers))
    canonical = "\n".join([
        method, canonical_uri, "",
        "".join(f"{k}:{headers[k]}\n" for k in sorted(headers)),
        signed, payload_hash])
    scope = f"{datestamp}/{region}/s3/aws4_request"
    to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canonical.encode()).hexdigest()])

    def _hm(key: bytes, msg: str) -> bytes:
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = _hm(_hm(_hm(_hm(b"AWS4" + secret.encode(), datestamp),
                    region), "s3"), "aws4_request")
    sig = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
    out = dict(headers)
    del out["host"]          # urllib sets Host itself
    out["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={akid}/{scope}, "
        f"SignedHeaders={signed}, Signature={sig}")
    return out


def s3_read(path: str) -> bytes:
    bucket, key = _split_bucket_key(path)
    url, host, uri = _s3_url(bucket, key)
    return _http("GET", url,
                 headers=lambda: _sigv4_headers("GET", host, uri, b""))


def s3_write(path: str, data: bytes) -> None:
    bucket, key = _split_bucket_key(path)
    url, host, uri = _s3_url(bucket, key)
    _http("PUT", url, data=data,
          headers=lambda: _sigv4_headers("PUT", host, uri, data))


# -- gs:// -------------------------------------------------------------------

def _gs_endpoint() -> str:
    ep = os.environ.get("STORAGE_EMULATOR_HOST")
    if ep:
        if "://" not in ep:
            ep = "http://" + ep
        return ep.rstrip("/")
    return "https://storage.googleapis.com"


def _gs_headers() -> dict:
    tok = os.environ.get("GOOGLE_OAUTH_ACCESS_TOKEN")
    return {"Authorization": f"Bearer {tok}"} if tok else {}


def gs_read(path: str) -> bytes:
    bucket, key = _split_bucket_key(path)
    obj = urllib.parse.quote(key, safe="")
    url = (f"{_gs_endpoint()}/storage/v1/b/{bucket}/o/{obj}?alt=media")
    return _http("GET", url, headers=_gs_headers)


def gs_write(path: str, data: bytes) -> None:
    bucket, key = _split_bucket_key(path)
    name = urllib.parse.quote(key, safe="")
    url = (f"{_gs_endpoint()}/upload/storage/v1/b/{bucket}/o"
           f"?uploadType=media&name={name}")
    _http("POST", url, data=data,
          headers=lambda: {"Content-Type": "application/octet-stream",
                           **_gs_headers()})


# -- hdfs:// -----------------------------------------------------------------

def _webhdfs_base() -> str:
    base = os.environ.get("H2O_TPU_WEBHDFS")
    if not base:
        raise ValueError(
            "hdfs:// needs H2O_TPU_WEBHDFS (namenode HTTP address, "
            "e.g. http://namenode:9870)")
    return base.rstrip("/")


def _hdfs_path(path: str) -> str:
    # hdfs://nn/path and hdfs:///path both map to /path on the
    # configured namenode (the authority names the cluster, not a host
    # we contact directly — WebHDFS goes through H2O_TPU_WEBHDFS)
    rest = path[len("hdfs://"):]
    if rest.startswith("/"):
        p = rest
    else:
        p = "/" + rest.split("/", 1)[1] if "/" in rest else "/"
    return urllib.parse.quote(p, safe="/")


def hdfs_read(path: str) -> bytes:
    url = (f"{_webhdfs_base()}/webhdfs/v1{_hdfs_path(path)}?op=OPEN")
    # urllib follows the namenode->datanode redirect automatically
    return _http("GET", url)


class _NoRedirect(urllib.request.HTTPRedirectHandler):
    def redirect_request(self, *a, **k):
        return None


def hdfs_write(path: str, data: bytes) -> None:
    """WebHDFS two-step CREATE: PUT (no body) to the namenode, which
    307-redirects to a datanode; then PUT the data there.  urllib never
    follows redirects for PUT (and would drop the body if it did), so
    the dance is explicit.  Gateways/fakes that accept the create
    directly (2xx, no redirect) get the data in a second direct PUT."""
    url = (f"{_webhdfs_base()}/webhdfs/v1{_hdfs_path(path)}"
           f"?op=CREATE&overwrite=true")
    ct = {"Content-Type": "application/octet-stream"}
    from .runtime import faults, retry

    def create() -> str:
        """Namenode step: returns the datanode target (or `url` itself
        for direct-accepting gateways). Transients propagate to the
        whole-dance retry below — a namenode failover 503s for a few
        seconds."""
        opener = urllib.request.build_opener(_NoRedirect)
        req = urllib.request.Request(url, method="PUT")
        try:
            faults.fire("persist.http", method="PUT", url=url)
            with opener.open(req, timeout=60) as r:
                r.read()
            return url                # direct-accepting endpoint
        except urllib.error.HTTPError as e:
            if e.code in (301, 302, 307) and e.headers.get("Location"):
                return e.headers["Location"]
            body = e.read()[:300].decode(errors="replace")
            if e.code == 429 or e.code >= 500:
                raise retry.TransientError(
                    f"PUT {url} -> HTTP {e.code}: {body}",
                    retry_after=_retry_after(e)) from None
            # note: a 404 here is an IOError, not FileNotFoundError —
            # CREATE is a write; "the file isn't there yet" is its job
            raise IOError(
                f"PUT {url} -> HTTP {e.code}: {body}") from None
        except (TimeoutError, ConnectionError) as e:
            raise retry.TransientError(f"PUT {url} -> {e!r}") from None
        except urllib.error.URLError as e:
            raise retry.TransientError(
                f"PUT {url} -> {e.reason!r}") from None

    def dance() -> None:
        """One CREATE + data PUT. The data PUT gets a SINGLE attempt:
        a dead datanode must send the retry back through CREATE for a
        FRESH redirect target, not hammer the stale one."""
        target = create()
        _http("PUT", target, data=data, headers=ct,
              policy=retry.RetryPolicy(attempts=1))

    retry.call(dance, describe=f"hdfs CREATE+PUT {url}")


def register(schemes: dict) -> None:
    schemes["s3"] = (s3_read, s3_write)
    schemes["gs"] = (gs_read, gs_write)
    schemes["gcs"] = (gs_read, gs_write)
    schemes["hdfs"] = (hdfs_read, hdfs_write)
