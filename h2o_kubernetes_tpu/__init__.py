"""h2o_kubernetes_tpu — a TPU-native rebuild of the H2O-3 + h2o-kubernetes
capability surface: distributed columnar Frames as sharded JAX arrays, an
MRTask-style map/reduce runtime on ICI collectives, histogram tree learners
(GBM/DRF/XGBoost-hist) and GLM/DeepLearning/Word2Vec on JAX/Pallas, AutoML
with stacked ensembles, and a C++ Kubernetes deployment stack (native/:
tpuk CLI + h2o-tpu-operator reconciling the H2OTpu CRD).

See SURVEY.md for the reference blueprint this is built against.
"""

from .automl import AutoML, Job, Leaderboard, jobs
from .config import get_config, set_config
from .grid import GridSearch, H2OGridSearch
from .diagnostics import device_memory, log, profile, timeline
from .frame import Frame, Vec, import_file, parse_setup
from .mojo import MojoModel, export_mojo, import_mojo
from .persist import (export_file, load_frame, load_model, save_frame,
                      save_model)
from .runtime import (ClusterHealthError, global_mesh, health_status,
                      heartbeat, initialize_distributed, make_mesh,
                      set_global_mesh, start_heartbeat, stop_heartbeat,
                      use_mesh)

__version__ = "0.2.0"


def init(coordinator: str | None = None, **kw) -> None:
    """Connect/boot the cluster (analog of h2o.init()).

    On TPU the 'cluster' is the pod slice this process can see; multi-host
    formation goes through the JAX distributed runtime using env injected
    by the operator (see runtime/mesh.py).

    Also points JAX's persistent compilation cache at a per-user dir
    (unless the user already set JAX_COMPILATION_CACHE_DIR): a cold
    AutoML run is otherwise dominated by XLA compiles, and on the
    tunneled chip each one is a remote round trip — the disk cache
    keys on hardware+HLO, so a SECOND process pays none of them.
    """
    from .runtime.backend import enable_persistent_compile_cache

    enable_persistent_compile_cache()
    initialize_distributed(coordinator, **kw)
    global_mesh()


def cluster_status() -> dict:
    """Analog of GET /3/Cloud."""
    import jax

    mesh = global_mesh()
    from .runtime.health import health_status as _hs

    return {
        "version": __version__,
        "cloud_healthy": bool(_hs()["healthy"]),
        "cloud_size": len(mesh.devices.flat),
        "mesh_shape": dict(mesh.shape),
        "process_count": jax.process_count(),
        "devices": [str(d) for d in mesh.devices.flat],
    }
