"""REST v3 API server — the water/api RequestServer analog.

Reference: h2o-core water/api (RequestServer + schemas3, SURVEY.md §2b
C9): a Jetty server on :54321 where every client verb is a versioned
endpoint — /3/Cloud, /3/ImportFiles, /3/Parse, /3/Frames,
/3/ModelBuilders/{algo}, /3/Models, /3/Predictions, /3/Jobs,
/99/AutoMLBuilder + /3/AutoML, /99/Grid, DELETE on frames/models,
/3/Timeline, and the leader-only readiness probe
/kubernetes/isLeaderNode (h2o-kubernetes [U] wires its readiness to
this — only the clustered leader node answers 200).

This build is Python-first (the client talks to the library directly),
so the REST layer is a thin JSON adapter over the same registries the
Python API uses: Frames and Models live in module-level key-value
stores (the DKV-for-small-objects analog), model builds run on a
worker thread under a Job, and every response is plain JSON. Start one
with `h2o_kubernetes_tpu.rest.start_server(port)` or
`python -m h2o_kubernetes_tpu.rest`.
"""

from __future__ import annotations

import json
import os
import threading
import time
import traceback
import urllib.parse
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from .runtime import lifecycle, telemetry
from .runtime.health import ClusterHealthError
from .runtime.lifecycle import CircuitOpenError, NodeDrainingError
from .runtime.retry import _env_float


class QueueFullError(RuntimeError):
    """The scoring admission queue is full — load shed (REST: 429 +
    Retry-After) instead of queueing into latency collapse."""

    def __init__(self, msg: str, retry_after: float = 1.0):
        super().__init__(msg)
        self.retry_after = retry_after

FRAMES: dict[str, object] = {}     # key -> Frame (DKV analog)
MODELS: dict[str, object] = {}     # key -> Model
AUTOML: dict[str, object] = {}     # project_name -> AutoML
GRIDS: dict[str, object] = {}      # grid_id -> GridSearch
_ID_LOCK = threading.Lock()
_MODEL_SEQ = 0

# -- scorer-pool replica surface (operator/, docs/OPERATOR.md) --------------
#
# READINESS_GATES: extra predicates AND-ed into /readyz beyond the
# lifecycle conjunction (SERVING ∧ breaker ∧ healthy). A gate returns
# (ok, reason); the model-registry gate below holds a pool replica
# unready until an artifact has been pushed AND its pow2 batch buckets
# pre-traced — the warm-up contract: no router sends traffic to a
# replica that would pay a compile on its first request.
READINESS_GATES: dict[str, object] = {}

# model_id -> {name, version, algo, slo, warmed_buckets,
#              warm_baseline, loaded_at} for artifacts loaded over
# POST /3/ModelRegistry/load. `warm_baseline` snapshots the MODEL's
# own (misses - promotions) right after warm-up, so /3/Stats reports
# warm_cache_misses = (misses - promotions) - baseline per model: a
# re-trace caused by byte-budget eviction (a `promotion`) re-baselines
# out instead of reading as an SLO-violating first-request compile,
# and one hot tenant's traces never pollute another tenant's counter.
REGISTRY_MODELS: dict[str, dict] = {}

# model_ids that must ALL be loaded+warmed before the model-registry
# readiness gate passes (POST /3/ModelRegistry/require — the
# multi-artifact push contract: the operator declares the full tenant
# set up front so /readyz cannot flip between pushes). Empty = the
# legacy ">= 1 artifact loaded" gate.
REQUIRED_MODEL_IDS: set[str] = set()

# who this replica IS (pool, replica id, pid, port, started_at) —
# set by the operator pod entry, reported on GET /3/Stats. A
# restarting reconciler identity-probes adoption candidates against
# it, so a recycled port can never masquerade as a pool's pod.
IDENTITY: dict = {}

# REST-level counters scraped by the operator's autoscale signal
# (GET /3/Stats): 504s from expired X-H2O-Deadline-Ms budgets, scoring
# requests admitted while the node could not serve readiness
# (cordon excluded — a cordoned replica serving routed stragglers is
# the rolling-update contract, not a violation), and per-tenant
# rate-limit rejections. Incremented under _STATS_LOCK: handler
# threads race, and a lost increment would suppress an autoscale
# scale-up for a scrape window.
STATS = {"deadline_504": 0, "scored_while_unready": 0,
         "rate_limited": 0}
_STATS_LOCK = threading.Lock()


def _bump_stat(key: str) -> None:
    with _STATS_LOCK:
        STATS[key] += 1


# -- SLO classes + per-model fairness (multi-tenant serving) ----------------
#
# One hot model must not starve the tail of a tenant population: every
# scoring request carries an SLO class (X-H2O-SLO header, else the
# model's registry default, else H2O_TPU_SLO_DEFAULT) that sets (a)
# its dispatch priority inside a batch window, (b) the share of the
# admission queue any ONE model in that class may occupy, and (c) an
# implicit per-request deadline for latency-class traffic.
# H2O_TPU_SCORE_FAIRNESS=0 turns both the share cap and the priority
# ordering off (the unfair baseline the Zipf bench measures against).

SLO_CLASSES: dict[str, dict] = {
    # latency-sensitive: dispatched first, smallest queue share, and
    # an implicit deadline so a starved request 504s instead of
    # silently blowing its budget
    "interactive": {"priority": 0, "deadline_ms": 500.0,
                    "queue_share": 0.25},
    # the default: no implicit deadline (H2O_TPU_SCORE_TIMEOUT rules)
    "standard": {"priority": 1, "deadline_ms": None,
                 "queue_share": 0.5},
    # throughput traffic: dispatched last, may fill the whole queue
    "batch": {"priority": 2, "deadline_ms": None, "queue_share": 1.0},
    # explainability traffic (the contributions route's own class):
    # TreeSHAP is O(leaves·depth) heavier per row than scoring, so it
    # dispatches behind latency traffic and one model's explain flood
    # may hold at most half the queue
    "explain": {"priority": 2, "deadline_ms": None, "queue_share": 0.5},
}

# model_key -> per-tenant serving counters, scraped via GET /3/Stats
# (the operator/autoscaler read per-model shed/deadline/breaker
# pressure off this). Guarded by _STATS_LOCK.
MODEL_STATS: dict[str, dict] = {}


def _fairness_on() -> bool:
    """H2O_TPU_SCORE_FAIRNESS (default on): per-model queue-share caps
    + SLO-priority dispatch ordering. 0 restores the unfair FIFO
    coalescer — kept as a measurable baseline, not a recommendation."""
    return os.environ.get("H2O_TPU_SCORE_FAIRNESS", "1") != "0"


def _default_slo() -> str:
    raw = (os.environ.get("H2O_TPU_SLO_DEFAULT") or "standard").lower()
    return raw if raw in SLO_CLASSES else "standard"


def _model_queue_share(cls: dict) -> float:
    """Fraction of the admission queue ONE model may occupy:
    H2O_TPU_SCORE_MODEL_QUEUE_SHARE when set (> 0 — one global
    override for every class), else the SLO class's own share."""
    share = _env_float("H2O_TPU_SCORE_MODEL_QUEUE_SHARE", 0.0)
    return min(share, 1.0) if share > 0 else cls["queue_share"]


def _slo_class(name: str | None) -> dict:
    return SLO_CLASSES.get(name or "", SLO_CLASSES["standard"])


def _model_stats(key: str, slo: str | None = None) -> dict:
    """The per-model counter record (created on first touch); caller
    must hold _STATS_LOCK."""
    rec = MODEL_STATS.get(key)
    if rec is None:
        rec = {"slo": slo or _default_slo(), "requests": 0, "shed": 0,
               "deadline_504": 0, "breaker_rejects": 0, "batches": 0,
               "rows": 0, "rate_limited": 0, "contrib_requests": 0,
               "contrib_batches": 0, "contrib_rows": 0}
        MODEL_STATS[key] = rec
    elif slo:
        rec["slo"] = slo
    return rec


def _bump_model_stat(key: str | None, stat: str, n: int = 1,
                     slo: str | None = None) -> None:
    if key is None:
        return
    with _STATS_LOCK:
        _model_stats(key, slo)[stat] += n


# -- per-tenant rate limits (PR 7 "Remaining") ------------------------------
#
# A token bucket per model key, applied at ScoreBatcher admission —
# BEFORE the queue and the fairness share, so a tenant past its quota
# never occupies a queue slot at all. H2O_TPU_MODEL_RATE_LIMIT is the
# sustained requests/second any ONE model key may submit (0/unset =
# off, the default: the chaos drills and every existing deployment see
# no behavior change); burst capacity is one second of traffic.
# Exhaustion is a 429 + Retry-After sized to the bucket's refill time,
# counted in STATS["rate_limited"] and per model in MODEL_STATS —
# both scraped off GET /3/Stats.

_RATE_BUCKETS: dict[str, list] = {}     # model_key -> [tokens, last]
_RATE_LOCK = threading.Lock()
# indirection so tests can freeze the bucket clock (exact burst-count
# assertions would otherwise flake against real refill on a slow box)
_bucket_now = time.monotonic


def _model_rate_limit() -> float:
    return max(0.0, _env_float("H2O_TPU_MODEL_RATE_LIMIT", 0.0))


def _rate_limit_admit(model_key: str | None,
                      slo: str | None) -> None:
    """Take one token from ``model_key``'s bucket or raise the 429.

    Read-at-use (like every serving knob): changing the env mid-process
    applies to the next request. Buckets refill continuously at the
    limit rate and cap at one second of burst."""
    rate = _model_rate_limit()
    if rate <= 0 or model_key is None:
        return
    from .runtime.retry import bucket_take

    with _RATE_LOCK:
        retry = bucket_take(_RATE_BUCKETS, model_key, rate,
                            _bucket_now())
        if retry == 0.0:
            return
    _bump_stat("rate_limited")
    _bump_model_stat(model_key, "rate_limited", slo=slo)
    raise QueueFullError(
        f"model '{model_key}' is over its rate limit "
        f"(H2O_TPU_MODEL_RATE_LIMIT={rate:g}/s); retry after the "
        "bucket refills", retry_after=retry)


def reset_rate_buckets() -> None:
    """Tests / in-process restart hook."""
    with _RATE_LOCK:
        _RATE_BUCKETS.clear()


def _request_slo(headers) -> str | None:
    """SLO class from X-H2O-SLO, or None. Unknown classes are a 400 —
    silently downgrading a request that asked for 'interactive' to
    best-effort would hide the typo until the p99 regression."""
    raw = headers.get("X-H2O-SLO")
    if raw is None:
        return None
    name = str(raw).strip().lower()
    if name not in SLO_CLASSES:
        raise ValueError(
            f"unknown X-H2O-SLO class {raw!r} "
            f"(known: {', '.join(sorted(SLO_CLASSES))})")
    return name


def _resolve_slo(mkey: str, header_slo: str | None) -> str:
    """Per-request header wins, else the model's registry default
    (set at artifact push), else H2O_TPU_SLO_DEFAULT."""
    if header_slo:
        return header_slo
    info = REGISTRY_MODELS.get(mkey)
    if info and info.get("slo") in SLO_CLASSES:
        return info["slo"]
    return _default_slo()


def _resolve_contrib_slo(header_slo: str | None) -> str:
    """Contributions requests get their OWN SLO class by default
    (`explain` — heavier per row than scoring, never ahead of latency
    traffic): X-H2O-SLO still wins per request, and
    H2O_TPU_CONTRIB_SLO_DEFAULT re-tunes the route-level default.
    The model's scoring registry default deliberately does NOT apply
    here — an `interactive` scoring tenant must not get interactive
    priority for its explain flood."""
    if header_slo:
        return header_slo
    raw = (os.environ.get("H2O_TPU_CONTRIB_SLO_DEFAULT")
           or "explain").lower()
    return raw if raw in SLO_CLASSES else "explain"


def _registry_gate():
    if REQUIRED_MODEL_IDS:
        missing = sorted(REQUIRED_MODEL_IDS - set(REGISTRY_MODELS))
        if missing:
            return False, (f"required artifact(s) not loaded+warmed "
                           f"yet: {missing[:4]}")
        return True, ""
    if REGISTRY_MODELS:
        return True, ""
    return False, "no model artifact loaded+warmed yet"


def install_pool_replica_gate() -> None:
    """Make /readyz require a warmed registry artifact (scorer-pool
    replicas; also installed by start_server when
    H2O_TPU_POOL_REPLICA=1 so the plain rest.py entry can be a pool
    pod)."""
    READINESS_GATES["model-registry"] = _registry_gate


def _ready_state(ignore_cordon: bool = False) -> tuple[bool, list, dict]:
    """(ready, reasons, lifecycle status) — THE readiness computation,
    shared by /readyz, /3/Stats and the scored_while_unready counter.
    ``ignore_cordon`` gives capability-readiness: a cordoned node is
    routing-unready (routers must drop it) but still serving-capable
    (admission stays open for stragglers during the deregister
    grace)."""
    st = lifecycle.status()
    reasons = []
    if st["state"] != lifecycle.SERVING:
        reasons.append(f"state={st['state']}")
    if st["breaker"]["state"] == "open":
        reasons.append("breaker=open")
    if not st["healthy"]:
        reasons.append("cloud unhealthy")
    for name, gate in list(READINESS_GATES.items()):
        try:
            ok, why = gate()
        except Exception as e:  # noqa: BLE001 — a buggy gate must fail
            ok, why = False, f"error: {e!r}"    # unready, not crash /readyz
        if not ok:
            reasons.append(f"gate:{name}: {why}")
    if not ignore_cordon and st.get("cordoned"):
        reasons.append(f"cordoned: {st['cordoned']}")
    return (not reasons), reasons, st


# ---------------------------------------------------------------------------
# Scoring micro-batcher
# ---------------------------------------------------------------------------
#
# ThreadingHTTPServer gives every /3/Predictions request its own
# thread, but each would dispatch its own device program — at serving
# concurrency that is many small dispatches instead of one full batch.
# The micro-batcher collects concurrent scoring requests for up to
# H2O_TPU_SCORE_BATCH_US microseconds (default 2000; 0 = no wait),
# concatenates same-model requests into ONE padded batch through
# Model.score_numpy (the jitted-scorer cache), and fans results back
# out.  Train/build POSTs keep the existing single-dispatch path.
#
# Failure contract (docs/RESILIENCE.md): requests NEVER queue behind a
# dead cloud — submit() and the dispatcher both check cluster health
# and fail ClusterHealthError (the routes map it to 503), and a result
# that misses H2O_TPU_SCORE_TIMEOUT seconds (default 60) raises
# TimeoutError (503) instead of hanging the client.


def _row_cap(env: str) -> int:
    """A H2O_TPU_*_MAX_ROWS knob as a usable int cap. <= 0 or inf
    reads as UNCAPPED (the 0-disables convention of the other H2O_TPU
    knobs) — and never raises, whatever the env holds: this runs on
    the dispatcher thread, where an OverflowError would kill the
    batcher with waiters still queued."""
    import math

    v = _env_float(env, 100_000.0)
    if not math.isfinite(v) or v <= 0:
        import sys

        return sys.maxsize
    return max(1, int(v))


def _score_row_cap() -> int:
    return _row_cap("H2O_TPU_SCORE_MAX_ROWS")


def _contrib_row_cap() -> int:
    """H2O_TPU_CONTRIB_MAX_ROWS (default 100k) — the contributions
    route's own per-request row cap (413 past it): a contributions
    response is [rows, F+1] floats, and one oversized TreeSHAP
    dispatch must no more lock the cloud than an oversized score."""
    return _row_cap("H2O_TPU_CONTRIB_MAX_ROWS")


class _ScoreJob:
    __slots__ = ("model", "X", "offset", "event", "out", "err",
                 "deadline", "key", "slo", "kind", "span")

    def __init__(self, model, X, offset, key=None, slo=None,
                 kind="score", span=None):
        self.model = model
        self.X = X
        self.offset = offset
        self.event = threading.Event()
        self.out = None
        self.err = None
        self.deadline = float("inf")
        self.key = key          # model key (per-tenant accounting)
        self.slo = slo          # SLO class name (fairness + priority)
        self.kind = kind        # "score" | "contrib" (dispatch target)
        self.span = span        # trace marks dict (telemetry) or None

    def mark(self, name: str) -> None:
        """Record a monotonic phase timestamp for the request trace —
        no-op when the request carries no span sink."""
        if self.span is not None:
            self.span[name] = time.monotonic()


class ScoreBatcher:
    """Collects concurrent scoring requests into per-model batches.

    Per-MODEL aware (multi-tenant serving): jobs coalesce per
    (model, offset?) group into one padded dispatch each; with
    fairness on, any one model's share of the admission queue is
    capped by its SLO class and groups dispatch in SLO-priority order
    (smallest first within a class), so a hot model's flood cannot
    starve a tail model out of its deadline."""

    def __init__(self):
        self._cond = threading.Condition()
        self._pending: list[_ScoreJob] = []
        self._inflight: list[_ScoreJob] = []
        self._pending_by_key: dict[str, int] = {}
        self._thread: threading.Thread | None = None
        self._stopped = False
        self.stats = {"requests": 0, "batches": 0, "batched_rows": 0,
                      "max_batch_requests": 0, "shed": 0,
                      "fairness_shed": 0}

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, name="h2o-tpu-score-batcher",
                daemon=True)
            self._thread.start()

    @staticmethod
    def _queue_max() -> int:
        """H2O_TPU_SCORE_QUEUE_MAX admission bound (requests pending
        behind the dispatcher); <= 0 reads as unbounded."""
        v = _env_float("H2O_TPU_SCORE_QUEUE_MAX", 256.0)
        import sys

        return sys.maxsize if v <= 0 else max(1, int(v))

    def submit(self, model, X: np.ndarray, offset=None,
               timeout: float | None = None,
               deadline: float | None = None,
               model_key: str | None = None,
               slo: str | None = None,
               kind: str = "score",
               span: dict | None = None) -> np.ndarray:
        """Enqueue one scoring request; blocks until its slice of the
        batched result (or raises: health/breaker/drain fail-fast,
        queue-full load shed, timeout).

        ``deadline`` is an absolute ``time.monotonic()`` instant (the
        per-request X-H2O-Deadline-Ms contract): the waiter stops
        waiting there, and the dispatcher drops the job unscored if it
        only reaches it afterwards. ``model_key``/``slo`` drive the
        per-tenant fairness cap + accounting; a deadline-less request
        in a latency SLO class inherits the class's implicit
        deadline. ``span`` is an optional dict the batcher fills with
        monotonic phase marks (admit/enqueue/pop/dispatch_start/
        dispatch_end) — the request-trace contract: the route turns
        them into queue-vs-device spans after the result lands."""
        from .runtime import health

        if span is not None:
            span["admit"] = time.monotonic()

        if self._stopped or not lifecycle.accepting():
            raise NodeDrainingError(
                f"node {lifecycle.state()}: draining — new scoring "
                "requests are not admitted (finish in-flight work, "
                "then route to a ready replica)")
        if not health.healthy():
            raise ClusterHealthError(
                "cluster unhealthy: "
                f"{health.health_status()['error']} — scoring refused "
                "(fail-fast, not queued)")
        # an OPEN breaker must reject at the front door — before the
        # queue, before the batch window. check() never claims the
        # half-open probe slot; that belongs to the dispatch itself.
        try:
            lifecycle.BREAKER.check()
        except CircuitOpenError:
            _bump_model_stat(model_key, "breaker_rejects", slo=slo)
            raise
        # per-tenant rate limit: over-quota tenants 429 BEFORE taking
        # a queue slot (fairness caps bound queue OCCUPANCY; this
        # bounds admission RATE)
        _rate_limit_admit(model_key, slo)
        cls = _slo_class(slo)
        if deadline is None and cls["deadline_ms"]:
            # latency-class traffic without an explicit budget still
            # gets one: a starved interactive request must 504 inside
            # its SLO, not wait out H2O_TPU_SCORE_TIMEOUT
            deadline = time.monotonic() + cls["deadline_ms"] / 1000.0
        if timeout is None:
            timeout = _env_float("H2O_TPU_SCORE_TIMEOUT", 60.0)
        job = _ScoreJob(model, X, offset, key=model_key, slo=slo,
                        kind=kind, span=span)
        # the dispatcher drops jobs whose waiter has already timed out
        # (503'd and gone) instead of burning device time on them
        job.deadline = time.monotonic() + timeout
        if deadline is not None:
            job.deadline = min(job.deadline, deadline)
        wait_s = max(0.0, job.deadline - time.monotonic())
        with self._cond:
            # re-check under the lock: stop() may have completed its
            # flush between the fast-path gate above and here, and an
            # append now would respawn the dispatcher on a batcher the
            # drain already declared flushed (racing os._exit)
            if self._stopped or not lifecycle.accepting():
                raise NodeDrainingError(
                    f"node {lifecycle.state()}: draining — new scoring "
                    "requests are not admitted (finish in-flight work, "
                    "then route to a ready replica)")
            qmax = self._queue_max()
            if len(self._pending) >= qmax:
                # load shedding: a full queue means latency is already
                # past the batch window × depth — a fast 429 beats a
                # slow 503 (and the OOM that unbounded queueing risks)
                self.stats["shed"] += 1
                _bump_model_stat(model_key, "shed", slo=slo)
                raise QueueFullError(
                    f"scoring admission queue is full "
                    f"({len(self._pending)} pending, "
                    f"H2O_TPU_SCORE_QUEUE_MAX={qmax}); "
                    "shed — retry with backoff", retry_after=1.0)
            if model_key is not None and _fairness_on():
                # per-model fairness: ONE model may hold at most its
                # SLO class's share of the admission queue, so a hot
                # tenant's flood sheds against ITS OWN cap while tail
                # tenants still find queue room — the starvation
                # bound the Zipf bench measures
                cap_m = max(1, int(qmax * _model_queue_share(cls)))
                if self._pending_by_key.get(model_key, 0) >= cap_m:
                    # counted as fairness_shed (+ the model's own
                    # shed), NOT the global `shed` the autoscaler
                    # scales up on: one hot tenant pinned at its
                    # queue share is the cap working as designed,
                    # not node capacity pressure — feeding it into
                    # the autoscale signal would ride the pool to
                    # max_replicas on an otherwise idle node
                    self.stats["fairness_shed"] += 1
                    _bump_model_stat(model_key, "shed", slo=slo)
                    raise QueueFullError(
                        f"model '{model_key}' holds its fair share of "
                        f"the scoring queue ({cap_m} of {qmax}, SLO "
                        f"class {slo or _default_slo()}); shed — "
                        "retry with backoff "
                        "(H2O_TPU_SCORE_FAIRNESS=0 disables)",
                        retry_after=0.5)
                self._pending_by_key[model_key] = \
                    self._pending_by_key.get(model_key, 0) + 1
            self._ensure_thread()
            self._pending.append(job)
            job.mark("enqueue")
            self.stats["requests"] += 1
            _bump_model_stat(
                model_key,
                "contrib_requests" if kind == "contrib" else "requests",
                slo=slo)
            self._cond.notify_all()
        # admitted: account serving-while-not-capable. The full
        # _ready_state() would add several lock acquisitions per
        # request on the serving hot path; at this point the admission
        # checks above already ruled out draining/unhealthy/open-
        # breaker, so the only remaining capability gaps are state !=
        # SERVING and an unsatisfied readiness gate (the warm-up gate)
        # — test exactly those, cheaply. Cordon deliberately excluded
        # (see STATS).
        unready = lifecycle.state() != lifecycle.SERVING
        if not unready:
            for _name, gate in list(READINESS_GATES.items()):
                try:
                    ok, _why = gate()
                except Exception:  # noqa: BLE001 — buggy gate reads
                    ok = False     # unready, same as _ready_state
                if not ok:
                    unready = True
                    break
        if unready:
            _bump_stat("scored_while_unready")
        if not job.event.wait(wait_s):
            if deadline is not None and time.monotonic() >= deadline:
                # the CLIENT's budget ran out while queued: 504, same
                # status as pre-admission expiry — a 503 would invite
                # a retry of a request whose budget is already spent
                _bump_model_stat(model_key, "deadline_504", slo=slo)
                raise _DeadlineExpired(
                    "request deadline expired while queued in the "
                    "micro-batcher (X-H2O-Deadline-Ms / SLO class "
                    "deadline) — dropped unscored")
            raise TimeoutError(
                f"scoring request timed out after {wait_s:.0f}s in "
                "the micro-batcher (H2O_TPU_SCORE_TIMEOUT / "
                "X-H2O-Deadline-Ms)")
        if job.err is not None:
            raise job.err
        return job.out

    def stop(self, timeout: float | None = 30.0) -> None:
        """Drain-path shutdown: refuse new submits, let the dispatcher
        flush everything already queued (every in-flight waiter gets a
        terminal response), then stop the dispatcher thread. Jobs still
        pending past ``timeout`` are failed, never left hanging."""
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)
        with self._cond:
            leftovers, self._pending = self._pending, []
            self._pending_by_key.clear()
            # a batch the dispatcher already popped but never finished
            # (wedged dispatch) holds waiters too — fail them, don't
            # leave them to time out after os._exit
            stuck = [j for j in self._inflight if not j.event.is_set()]
        for job in leftovers + stuck:   # dispatcher died/overran: fail loud
            job.err = NodeDrainingError(
                "node draining: scoring request could not be flushed "
                "before the drain deadline")
            job.event.set()

    def reset(self) -> None:
        """Back to accepting (tests / in-process cluster restart); the
        dispatcher thread respawns lazily on the next submit."""
        with self._cond:
            self._stopped = False

    def queue_depth(self) -> int:
        """Requests currently queued behind the dispatcher — the
        instantaneous half of the autoscale signal (/3/Stats)."""
        with self._cond:
            return len(self._pending)

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._stopped:
                    self._cond.wait()
                if self._stopped and not self._pending:
                    return           # drained: exit cleanly
            win = _env_float("H2O_TPU_SCORE_BATCH_US", 2000.0) / 1e6
            if win > 0 and not self._stopped:
                # clamp: a negative value must not kill the dispatcher
                # (sleep raises), a huge one must not wedge it; a
                # draining batcher skips the collect wait entirely
                time.sleep(min(win, 1.0))    # collect concurrent arrivals
            with self._cond:
                batch, self._pending = self._pending, []
                self._pending_by_key.clear()   # fairness counts queue
                # occupancy only — popped jobs free their share
                # tracked so stop() can fail these waiters too if this
                # dispatch wedges past the drain deadline — a popped
                # batch is otherwise invisible to the flush
                self._inflight = batch
            self._dispatch(batch)
            with self._cond:
                self._inflight = []

    def _dispatch(self, batch: list[_ScoreJob]) -> None:
        now = time.monotonic()
        live = []
        for job in batch:
            if now > job.deadline:
                # the waiter already 503'd and disconnected: scoring
                # its rows would only delay live requests
                job.err = TimeoutError("scoring request abandoned "
                                       "(client wait expired)")
                job.event.set()
            else:
                job.mark("pop")    # left the admission queue
                live.append(job)
        groups: dict[tuple, list[_ScoreJob]] = {}
        for job in live:
            # kind in the key: score and contrib dispatches run
            # different programs and must never concatenate
            groups.setdefault(
                (id(job.model), job.offset is not None, job.kind),
                []).append(job)
        ordered = list(groups.values())
        if _fairness_on() and len(ordered) > 1:
            # SLO-priority dispatch order, smallest group first within
            # a class: a tail model's 8-row interactive request goes
            # to the device BEFORE the hot model's coalesced flood,
            # so its latency is bounded by its own work + one small
            # dispatch — not by the hot model's batch size
            ordered.sort(key=lambda jobs: (
                min(_slo_class(j.slo)["priority"] for j in jobs),
                sum(j.X.shape[0] for j in jobs)))
        # the per-request H2O_TPU_SCORE_MAX_ROWS cap must also bound
        # the COALESCED dispatch: N capped requests in one window would
        # otherwise concatenate into an N×-cap device program (the OOM
        # → locked-cloud outage the cap exists to prevent)
        cap = _score_row_cap()
        for jobs in ordered:
            while jobs:
                rows = 0
                chunk = []
                while jobs and (not chunk
                                or rows + jobs[0].X.shape[0] <= cap):
                    rows += jobs[0].X.shape[0]
                    chunk.append(jobs.pop(0))
                self._score_group(chunk)

    def _score_group(self, jobs: list[_ScoreJob]) -> None:
        from .runtime import health

        try:
            if not health.healthy():
                raise ClusterHealthError(
                    "cluster unhealthy: "
                    f"{health.health_status()['error']} — queued "
                    "scoring request dropped (fail-fast)")
            model = jobs[0].model
            contrib = jobs[0].kind == "contrib"
            self.stats["batches"] += 1
            self.stats["max_batch_requests"] = max(
                self.stats["max_batch_requests"], len(jobs))
            if jobs[0].key is not None:
                _bump_model_stat(jobs[0].key,
                                 "contrib_batches" if contrib
                                 else "batches")
                _bump_model_stat(jobs[0].key,
                                 "contrib_rows" if contrib else "rows",
                                 sum(j.X.shape[0] for j in jobs))

            def dispatch(X, offset=None):
                for j in jobs:
                    j.mark("dispatch_start")
                try:
                    if contrib:
                        return model.contrib_numpy(X)
                    return model.score_numpy(X, offset=offset)
                finally:
                    for j in jobs:
                        j.mark("dispatch_end")

            if len(jobs) == 1:
                jobs[0].out = dispatch(jobs[0].X,
                                       offset=jobs[0].offset)
            else:
                X = np.concatenate([j.X for j in jobs])
                off = None
                if jobs[0].offset is not None:
                    off = np.concatenate([j.offset for j in jobs])
                self.stats["batched_rows"] += X.shape[0]
                out = dispatch(X, offset=off)
                lo = 0
                for j in jobs:
                    hi = lo + j.X.shape[0]
                    j.out = out[lo:hi]
                    lo = hi
        except BaseException as e:  # noqa: BLE001 — every waiter
            for j in jobs:          # must be released, whatever died
                j.err = e
        finally:
            for j in jobs:
                j.event.set()


BATCHER = ScoreBatcher()


# -- telemetry registration -------------------------------------------------
#
# Every serving surface this module owns registers as a STAT GROUP in
# the process-wide metrics registry (runtime/telemetry.py): the dicts
# above stay the storage their hot paths mutate, but /3/Stats is
# assembled from the registry snapshot and GET /metrics flattens the
# same groups into Prometheus text — one source of truth, two renders,
# and a fleet scraper sees every counter /3/Stats ever reported.
# (scorer_cache registers in models/base.py, compiles in
# runtime/backend.py, lifecycle in runtime/lifecycle.py — each group
# lives with its owner.)


def _counters_snapshot() -> dict:
    with _STATS_LOCK:
        return dict(STATS)


def _model_stats_snapshot() -> dict:
    with _STATS_LOCK:
        return {k: dict(v) for k, v in MODEL_STATS.items()}


def _batcher_snapshot() -> dict:
    return {**BATCHER.stats, "queue_depth": BATCHER.queue_depth()}


def _registry_snapshot() -> dict:
    """Per-artifact registry state incl. the eviction-aware
    warm_cache_misses contract (see /3/Stats docstring history)."""
    from .models.base import model_scorer_counters

    reg = {}
    for mid, info in list(REGISTRY_MODELS.items()):
        model = MODELS.get(mid)
        wcm = None
        if model is not None:
            ctr = model_scorer_counters(model)
            wcm = max(0, ctr["misses"] - ctr["promotions"]
                      - info.get("warm_baseline", 0))
        reg[mid] = {
            "name": info.get("name"),
            "version": info.get("version"),
            "algo": info.get("algo"),
            "slo": info.get("slo"),
            "warmed_buckets": info.get("warmed_buckets"),
            "contributions": info.get("contributions"),
            "warm_cache_misses": wcm,
        }
    return reg


telemetry.register_group("counters", _counters_snapshot)
telemetry.register_group("batcher", _batcher_snapshot)
telemetry.register_group("models", _model_stats_snapshot,
                         labeled="model")
telemetry.register_group("registry", _registry_snapshot,
                         labeled="model")
telemetry.register_group("identity", lambda: dict(IDENTITY))
telemetry.register_group("build", telemetry.build_info)


def _traced_submit(model, X, *, tid, t0, model_key, slo,
                   kind="score", offset=None, deadline=None):
    """BATCHER.submit with the request-trace contract on BOTH exits:
    a request that dies in the queue (shed / deadline 504 / breaker /
    timeout) still lands in the trace ring and the latency
    histograms with its error name as the outcome — the slow requests
    tracing exists to debug are exactly the failed ones, and a
    success-only histogram would bias the exported p99 low."""
    marks: dict = {}
    try:
        out = BATCHER.submit(model, X, offset=offset,
                             deadline=deadline, model_key=model_key,
                             slo=slo, kind=kind, span=marks)
    except BaseException as e:
        telemetry.record_request_phases(
            tid, marks, t0 if t0 is not None else marks.get("admit"),
            time.monotonic(), model=model_key, slo=slo, kind=kind,
            outcome=type(e).__name__)
        raise
    telemetry.record_request_phases(
        tid, marks, t0 if t0 is not None else marks.get("admit"),
        time.monotonic(), model=model_key, slo=slo, kind=kind)
    return out


def _predict_via_batcher(model, frame, deadline=None, model_key=None,
                         slo=None, tid=None, t0=None):
    """Frame prediction through the micro-batcher: design matrix ->
    one (possibly coalesced) scoring dispatch -> prediction Frame.
    Models outside the jitted serving set keep the classic path."""
    from .runtime.health import device_dispatch

    # coalescing only pays for many small concurrent requests; a big
    # (or empty) single-frame predict through the batcher would add a
    # device->host->device round trip + a padding copy for nothing —
    # keep those on the classic device-resident predict() path (which
    # rides the jitted-scorer cache anyway)
    if not getattr(model, "_serving_jit", False) \
            or frame.nrows == 0 or frame.nrows > 8192:
        return model.predict(frame)
    with device_dispatch("model scoring"):
        X = np.asarray(model._design_matrix(frame))[: frame.nrows]
        off = model._frame_offset(frame)   # the predict_raw contract
        if off is not None:
            off = np.asarray(off)[: frame.nrows]
    out = _traced_submit(model, X, tid=tid, t0=t0,
                         model_key=model_key, slo=slo, offset=off,
                         deadline=deadline)
    return model._prediction_frame(out)


class _DeadlineExpired(Exception):
    """The request's X-H2O-Deadline-Ms budget ran out before dispatch
    (REST: 504 — the client stopped caring; don't score it)."""


def _request_deadline(headers) -> float | None:
    """Absolute monotonic deadline from X-H2O-Deadline-Ms, or None.

    The header carries the client's REMAINING budget in milliseconds
    (a relative deadline propagates across machines; an absolute wall
    time would need synchronized clocks). Unparseable values raise
    ValueError (400); a budget that is already <= 0 raises
    _DeadlineExpired (504) so the request is dropped before it wastes
    a queue slot or a device dispatch."""
    raw = headers.get("X-H2O-Deadline-Ms")
    if raw is None:
        return None
    try:
        ms = float(raw)
    except (TypeError, ValueError):
        raise ValueError(
            f"bad X-H2O-Deadline-Ms {raw!r} (want milliseconds)") \
            from None
    if ms <= 0:
        raise _DeadlineExpired(
            f"request deadline already expired (X-H2O-Deadline-Ms="
            f"{ms:g}) — rejected without a dispatch")
    return time.monotonic() + ms / 1000.0


def _rows_to_matrix(model, rows, columns=None):
    """JSON scoring payload -> [n, F] float32 in TRAINING value space.

    `rows` is a list of per-row dicts (col -> value) or a list of
    lists with `columns` naming their order. Enum levels map through
    the training domain (unseen/None -> NaN = NA)."""
    names = model.feature_names
    if not isinstance(rows, list) or not rows:
        raise ValueError("'rows' must be a non-empty list")
    if isinstance(rows[0], dict):
        missing = [n for n in names if n not in rows[0]]
        if missing:
            raise ValueError(f"missing feature column(s) {missing} "
                             "(send null for NA, not absence)")

        def get(r, name):
            # direct indexing: a LATER row omitting a feature must
            # reject (KeyError -> 400), not silently score it as NA
            return r[name]
    else:
        if not columns:
            raise ValueError(
                "list-shaped rows need 'columns' naming their order")
        pos = {c: i for i, c in enumerate(columns)}
        missing = [n for n in names if n not in pos]
        if missing:
            raise ValueError(f"missing feature column(s) {missing}")

        def get(r, name):
            return r[pos[name]]

    n = len(rows)
    X = np.empty((n, len(names)), dtype=np.float32)
    doms = getattr(model, "feature_domains", {}) or {}
    # domain->code LUTs are request-invariant: cached per model (and
    # dropped from pickles, like the jitted scorers) so the serving
    # hot path does not rebuild an O(domain) dict per request
    luts = model.__dict__.setdefault("_serving_luts", {})
    for j, name in enumerate(names):
        dom = doms.get(name)
        if dom is not None:
            lut = luts.get(name)
            if lut is None:
                lut = {d: float(i) for i, d in enumerate(dom)}
                luts[name] = lut
            X[:, j] = [lut.get(str(v), np.nan)
                       if (v := get(r, name)) is not None else np.nan
                       for r in rows]
        else:
            X[:, j] = [float(v) if (v := get(r, name)) is not None
                       else np.nan for r in rows]
    return X


def _runtime_process_index() -> int | None:
    """jax.process_index() IF the distributed runtime is up, else None.

    Deliberately inspects the distributed client state instead of
    calling jax.process_index(): that call initializes the backends,
    and the readiness probe must never be the thing that hangs on a
    recovering TPU client init."""
    try:
        from jax._src import distributed

        if distributed.global_state.client is None:
            return None
        import jax

        return int(jax.process_index())
    except Exception:
        return None


def _is_leader() -> bool:
    """True on the clustered leader (process 0). The operator injects
    H2O_TPU_PROCESS_ID into every pod (native/deployment/manifests.cc);
    single-process clouds are their own leader.

    When the distributed runtime is actually up, the env var claim is
    CROSS-CHECKED against jax.process_index(): a mislabeled pod (env
    says 0, runtime disagrees — or vice versa) must fail readiness
    rather than route client traffic to a non-leader (the reference's
    /kubernetes/isLeaderNode answers from cluster state, not pod
    metadata; h2o-k8s [U3])."""
    import os

    raw = os.environ.get("H2O_TPU_PROCESS_ID") or "0"
    try:
        env_leader = int(raw) == 0
    except ValueError:
        # an unparseable pod index must read as not-leader (503), not
        # crash the probe into a 500 on every pod
        return False
    rt = _runtime_process_index()
    if rt is not None:
        rt_leader = rt == 0
        if rt_leader != env_leader:
            from .diagnostics import log, timeline

            msg = (f"H2O_TPU_PROCESS_ID={raw!r} but "
                   f"jax.process_index()={rt}")
            timeline.record("leader_mismatch", msg)
            log.error("leader identity mismatch: %s", msg)
            return False
        return rt_leader
    return env_leader

def _reap_jobs() -> None:
    """Terminalize RUNNING jobs whose worker can no longer report.

    A worker thread that dies between /3/Jobs polls (OOM-killed, a
    non-Exception abort in native code) would leave its Job RUNNING
    forever and the polling client hanging.  Every /3/Jobs poll first
    fails (terminally) any RUNNING job whose recorded worker thread is
    dead, and — when H2O_TPU_JOB_TIMEOUT seconds is set > 0 — any
    RUNNING job older than the timeout."""
    from .automl import JOBS

    timeout = _env_float("H2O_TPU_JOB_TIMEOUT", 0.0)
    for job in list(JOBS.values()):
        if job.status != "RUNNING":
            continue
        th = getattr(job, "_thread", None)
        if th is not None and not th.is_alive():
            job.failed("worker thread died between polls without "
                       "reporting a result")
        elif timeout > 0 and job.start_time and \
                time.time() - job.start_time > timeout:
            job.failed(f"server-side job-poll timeout: still RUNNING "
                       f"after {timeout:.0f}s (H2O_TPU_JOB_TIMEOUT)")


_ALGOS = ("gbm", "drf", "glm", "deeplearning", "xgboost", "kmeans",
          "naivebayes", "pca", "isolationforest", "glrm", "coxph",
          "aggregator")


def _algo_estimator(algo: str):
    from . import models as M

    return {
        "gbm": M.GBM, "drf": M.DRF, "glm": M.GLM,
        "deeplearning": M.DeepLearning, "xgboost": M.XGBoost,
        "kmeans": M.KMeans, "naivebayes": M.NaiveBayes, "pca": M.PCA,
        "isolationforest": M.IsolationForest, "glrm": M.GLRM,
        "coxph": M.CoxPH, "aggregator": M.Aggregator,
    }[algo]


def _definite(obj):
    """Recursively replace non-finite floats with None (JSON null)."""
    if isinstance(obj, float):
        import math

        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: _definite(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_definite(v) for v in obj]
    return obj


def _frame_schema(key: str, fr) -> dict:
    return {"frame_id": {"name": key}, "rows": fr.nrows,
            "columns": [{"label": n,
                         "type": fr.vec(n).kind} for n in fr.names]}


class JsonHttpHandler(BaseHTTPRequestHandler):
    """The JSON request-handler plumbing every server in this package
    shares — the REST node below AND the device-free scoring router
    (operator/router.py rides exactly this base so error shapes,
    Retry-After semantics, and the drain-safe body discard cannot
    drift between the front door and the replicas)."""

    server_version = "h2o-tpu-rest/1"

    def log_message(self, *a):       # quiet by default
        pass

    def _json(self, obj, code: int = 200, headers: dict | None = None):
        # metrics can be NaN (single-class CV folds, zero-weight rmse);
        # json.dumps would emit bare `NaN` — invalid JSON that strict
        # parsers (fetch, jsonlite) reject. Null them out instead.
        body = json.dumps(_definite(obj)).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code: int, msg: str,
               retry_after: float | None = None):
        hdrs = None
        if retry_after is not None:
            # whole seconds, min 1: the header is delta-seconds and a
            # zero would read as "hammer immediately"
            hdrs = {"Retry-After": str(max(1, int(retry_after + 0.999)))}
        self._json({"__schema": "H2OErrorV3", "http_status": code,
                    "msg": msg}, code, headers=hdrs)

    def _discard_body(self) -> None:
        """Read and drop an unread request body before an early error
        reply: closing the connection with unread bytes still in the
        receive buffer makes the kernel send RST, which can discard the
        buffered error response client-side — and the drain contract
        promises every client a terminal HTTP response, not a reset."""
        try:
            n = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            return
        while n > 0:
            chunk = self.rfile.read(min(n, 1 << 20))
            if not chunk:
                break
            n -= len(chunk)


class _Handler(JsonHttpHandler):

    # -- plumbing ------------------------------------------------------------

    def _unhealthy_503(self) -> bool:
        """Send 503 + the health error when the cloud is locked-
        unhealthy — graceful degradation instead of spawning a doomed
        job (or a 500 with a raw traceback). False when healthy."""
        from .runtime import health

        if health.healthy():
            return False
        err = health.health_status()["error"]
        self._error(503, f"cluster unhealthy: {err} — restart the "
                    "cluster and resume from the last checkpoint")
        return True

    def _params(self) -> dict:
        q = urllib.parse.urlparse(self.path).query
        out = {k: v[0] for k, v in urllib.parse.parse_qs(q).items()}
        ln = int(self.headers.get("Content-Length") or 0)
        if ln:
            raw = self.rfile.read(ln).decode()
            ctype = self.headers.get("Content-Type", "")
            if "json" in ctype:
                out.update(json.loads(raw))
            else:
                out.update({k: v[0] for k, v in
                            urllib.parse.parse_qs(raw).items()})
        return out

    # -- routes --------------------------------------------------------------

    def do_GET(self):
        try:
            path = urllib.parse.urlparse(self.path).path.rstrip("/")
            if path == "/healthz":
                # LIVENESS: true for the whole STARTING→DRAINING span —
                # the kubelet must not kill a pod that is busy draining.
                # Only a TERMINATED process (drain done, exit pending —
                # or wedged) should be restarted. Never touches the
                # device: the probe must not hang on what it probes.
                st = lifecycle.status()
                if st["state"] == lifecycle.TERMINATED:
                    return self._json({"alive": False, **st}, 503)
                return self._json({"alive": True, **st})
            if path == "/readyz":
                # READINESS = SERVING ∧ breaker-not-open ∧ cloud
                # healthy ∧ every READINESS_GATE ∧ not cordoned: flips
                # the instant a drain begins (or the breaker trips, or
                # the operator cordons this replica), while /healthz
                # stays green — the Service stops routing long before
                # the kubelet kills
                ready, reasons, st = _ready_state()
                if ready:
                    return self._json({"ready": True, **st})
                return self._json({"ready": False,
                                   "reasons": reasons, **st}, 503)
            if path == "/3/Stats":
                # ONE scrape for operators + the autoscale signal —
                # now assembled from the process-wide metrics registry
                # (runtime/telemetry.py): every section below is a
                # registered stat group, so this JSON and the
                # Prometheus exposition at GET /metrics render the
                # SAME snapshot (the inventory-diff test pins that).
                # The dict shape is byte-compatible with the
                # pre-registry payload; `build` is the one sanctioned
                # addition (which build produced this scrape).
                # Device-free: safe to poll on a wedged node.
                from .models import base as _base  # noqa: F401 —
                # importing registers the scorer_cache group
                from .runtime.backend import start_compile_watch

                start_compile_watch()   # idempotent: registers the
                # compiles group even when start_server never ran
                ready, reasons, st = _ready_state()
                snap = telemetry.group_snapshot((
                    "scorer_cache", "batcher", "counters", "models",
                    "compiles", "registry"))
                return self._json({
                    "ready": ready, "reasons": reasons, **st,
                    "identity": dict(IDENTITY),
                    "scorer_cache": snap.get("scorer_cache", {}),
                    "batcher": snap.get("batcher", {}),
                    "counters": snap.get("counters", {}),
                    "models": snap.get("models", {}),
                    "fairness": _fairness_on(),
                    "compiles": snap.get("compiles", {}),
                    "registry": snap.get("registry", {}),
                    "build": telemetry.build_info()})
            if path == "/metrics":
                # Prometheus text exposition: every first-class metric
                # (latency/phase histograms, hedge/event counters) plus
                # every registered stat group's numeric leaves — one
                # scrape sees everything /3/Stats reports
                from .models import base as _base  # noqa: F401
                from .runtime.backend import start_compile_watch

                start_compile_watch()
                telemetry.write_metrics(self)
                return None
            if path.startswith("/3/Trace/"):
                # per-request span record from the bounded trace ring:
                # the "why was this p99 slow" decomposition (admission
                # wait / batcher queue / batch assembly / device
                # dispatch) for a request that carried (or was minted)
                # an X-H2O-Trace-Id
                tid = urllib.parse.unquote(path[len("/3/Trace/"):])
                rec = telemetry.TRACER.get(tid)
                if rec is None:
                    return self._error(
                        404, f"trace '{tid}' not in the ring (bounded "
                        "at H2O_TPU_TRACE_RING entries — old traces "
                        "age out)")
                return self._json(rec)
            if path == "/3/ModelRegistry":
                return self._json({
                    "models": {
                        mid: {k: v for k, v in info.items()
                              if k != "warm_baseline"}
                        for mid, info in REGISTRY_MODELS.items()},
                    "required": sorted(REQUIRED_MODEL_IDS)})
            if path in ("", "/flow", "/flow/index.html"):
                # the h2o-web Flow analog (SURVEY §2b C19): one
                # self-contained page, same REST verbs as any client
                import os

                page = os.path.join(os.path.dirname(
                    os.path.abspath(__file__)), "flow", "index.html")
                with open(page, "rb") as f:
                    body = f.read()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return None
            if path == "/3/Cloud":
                from . import cluster_status

                return self._json(cluster_status())
            if path in ("/kubernetes/isLeaderNode", "/3/IsLeaderNode"):
                # readiness must pass ONLY on the leader so the Service
                # routes clients to one consistent node (reference
                # /kubernetes/isLeaderNode, SURVEY.md §2b C2)
                if _is_leader():
                    return self._json({"leader": True})
                return self._error(503, "not the leader node")
            if path == "/3/Timeline":
                from .diagnostics import timeline

                return self._json({"events": timeline.events()})
            if path.startswith("/3/AutoML/"):
                key = urllib.parse.unquote(
                    path[len("/3/AutoML/"):])
                if key not in AUTOML:
                    return self._error(404, f"automl '{key}' not found")
                aml = AUTOML[key]
                lb = aml.leaderboard.as_list() if aml.leaderboard else []
                leader = lb[0]["model_id"] if lb else None
                return self._json({
                    "project_name": key,
                    "leader": {"name": leader},
                    "leaderboard": lb,
                    "sort_metric": aml.leaderboard.sort_metric
                    if aml.leaderboard else None})
            if path.startswith("/99/Grids/"):
                key = urllib.parse.unquote(
                    path[len("/99/Grids/"):])
                if key not in GRIDS:
                    return self._error(404, f"grid '{key}' not found")
                g = GRIDS[key]
                return self._json({
                    "grid_id": {"name": key},
                    "model_ids": [{"name": m} for m in g.model_ids],
                    "summary": g.get_grid()})
            if path == "/3/Jobs":
                from .automl import jobs

                _reap_jobs()    # dead workers must read as FAILED,
                return self._json({"jobs": jobs()})  # never hang pollers
            if path == "/3/Frames":
                return self._json({"frames": [
                    _frame_schema(k, f) for k, f in FRAMES.items()]})
            if path.startswith("/3/Frames/"):
                rest = path[len("/3/Frames/"):]
                key, _, verb = rest.partition("/")
                key = urllib.parse.unquote(key)
                if key not in FRAMES:
                    return self._error(404, f"frame '{key}' not found")
                fr = FRAMES[key]
                if verb == "summary":
                    return self._json({"frame_id": {"name": key},
                                       "summary": fr.summary()})
                return self._json(_frame_schema(key, fr))
            if path == "/3/Models":
                return self._json({"models": [
                    {"model_id": {"name": k}, "algo": m.algo}
                    for k, m in MODELS.items()]})
            if path.startswith("/3/Models/"):
                rest_part = path[len("/3/Models/"):]
                key, _, verb = rest_part.partition("/")
                key = urllib.parse.unquote(key)
                if key not in MODELS:
                    return self._error(404, f"model '{key}' not found")
                m = MODELS[key]
                if verb == "mojo":
                    # artifact download (h2o-py model.download_mojo via
                    # GET /3/Models/{id}/mojo [U3])
                    import os
                    import tempfile

                    from .mojo import export_mojo

                    if hasattr(m, "export_artifact"):
                        # a registry FlatTreeScorer has no heap trees
                        # for export_mojo to walk — it serves its kept
                        # artifact parts directly
                        blob = m.export_artifact()
                    else:
                        # fixed artifact name inside the tempdir: model
                        # keys come verbatim from POST bodies, so using
                        # them as a path component would allow ../
                        # traversal out of td
                        with tempfile.TemporaryDirectory() as td:
                            p = export_mojo(
                                m, os.path.join(td, "model.mojo"))
                            with open(p, "rb") as f:
                                blob = f.read()
                    # header filename: strip path separators, quotes and
                    # control chars (CRLF here = response splitting)
                    safe = "".join(
                        c for c in key
                        if c.isalnum() or c in "._- ") or "model"
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/octet-stream")
                    self.send_header(
                        "Content-Disposition",
                        f'attachment; filename="{safe}.mojo"')
                    self.send_header("Content-Length", str(len(blob)))
                    self.end_headers()
                    self.wfile.write(blob)
                    return None
                if verb:
                    return self._error(404, f"no route for GET {path}")
                cvm = getattr(m, "cross_validation_metrics", None)
                out = {"model_id": {"name": key},
                       "algo": m.algo,
                       "nclasses": m.nclasses,
                       "scoring_history":
                           getattr(m, "scoring_history", []),
                       "validation_metrics":
                           getattr(m, "validation_metrics", None),
                       "cross_validation_metrics":
                           cvm() if callable(cvm) else cvm}
                varimp = getattr(m, "varimp", None)
                if callable(varimp):
                    try:
                        out["variable_importances"] = {
                            k: float(v) for k, v in varimp().items()}
                    except Exception:   # noqa: BLE001 — detail is
                        pass            # best-effort, not the contract
                return self._json(out)
            return self._error(404, f"no route for GET {path}")
        except ClusterHealthError as e:
            return self._error(503, str(e))
        except Exception as e:       # noqa: BLE001
            traceback.print_exc()
            return self._error(500, repr(e))

    def do_POST(self):
        try:
            t0 = time.monotonic()   # request-trace total-span anchor
            path = urllib.parse.urlparse(self.path).path.rstrip("/")
            # drain admission gate BEFORE parsing the body: a draining
            # node admits no new work of any kind (in-flight requests
            # already past this line run to completion and respond).
            # The unread body is still drained off the socket first so
            # the 503 arrives as a response, not a connection reset
            if not lifecycle.accepting():
                self._discard_body()
                return self._error(
                    503, f"node {lifecycle.state()}: draining — not "
                    "accepting new work; route to a ready replica",
                    retry_after=lifecycle.remaining_drain_budget())
            try:
                params = self._params()
                # per-request deadline: parsed up front so an expired
                # budget is rejected before any queue slot or dispatch
                deadline = _request_deadline(self.headers)
                slo = _request_slo(self.headers)
                # trace propagation: take the router's X-H2O-Trace-Id
                # (or mint one for direct requests) — scoring routes
                # record their span decomposition under it and echo it
                tid = telemetry.trace_id_from(self.headers)
            except ValueError as e:
                # bad request envelope only: malformed JSON body or an
                # unparseable X-H2O-Deadline-Ms — a ValueError from a
                # route handler below is a server bug and must 500
                return self._error(400, str(e))
            if path in ("/3/Cordon", "/3/Uncordon"):
                # ops verbs, device-free and allowed on an UNHEALTHY
                # node (the operator must be able to pull a sick
                # replica out of rotation): flip routing-readiness
                # without touching admission — the rolling-update
                # endpoint-removal step (docs/OPERATOR.md)
                if path == "/3/Cordon":
                    lifecycle.cordon(str(params.get("reason")
                                         or "operator"))
                else:
                    lifecycle.uncordon()
                ready, reasons, st = _ready_state()
                return self._json({"ready": ready,
                                   "reasons": reasons, **st})
            # every POST verb does device work (parse shards onto the
            # mesh, builds/predictions dispatch collectives): on a dead
            # cloud degrade to 503 up front — reads (GET) stay served
            if path == "/3/ModelRegistry/require":
                # multi-artifact readiness: the operator declares the
                # FULL tenant set before pushing, so /readyz cannot
                # flip between artifact 1 landing and artifact N —
                # device-free, allowed whatever the cloud's health
                ids = params.get("model_ids")
                if not isinstance(ids, list) or \
                        not all(isinstance(i, str) and i for i in ids):
                    return self._error(
                        400, "need 'model_ids' (list of model id "
                        "strings; [] clears the requirement)")
                # monotone-safe swap (no lock shared with the /readyz
                # gate): add the new ids FIRST, then drop the stale
                # ones — between the two steps the set is a superset
                # of old ∪ new, so a concurrent gate read can only be
                # MORE strict, never observe an empty set and fall
                # through to the legacy any-model-loaded gate
                new_ids = set(ids)
                REQUIRED_MODEL_IDS.update(new_ids)
                REQUIRED_MODEL_IDS.intersection_update(new_ids)
                ok, why = _registry_gate()
                return self._json({"required": sorted(
                    REQUIRED_MODEL_IDS), "satisfied": ok,
                    "reason": why})
            if self._unhealthy_503():
                return None
            if path == "/3/ModelRegistry/load":
                return self._registry_load(params)
            if path == "/3/ImportFiles" or path == "/3/Parse":
                from .frame import import_file

                src = params.get("path") or params.get("source_frames")
                if isinstance(src, (list, tuple)):
                    if not src:
                        return self._error(400, "missing 'path'")
                    if len(src) != 1:   # refuse, don't silently truncate
                        return self._error(
                            400, "multi-file Parse is not supported over "
                            "REST; pass one path (globs allowed)")
                    src = src[0]
                if not src or not isinstance(src, str):
                    return self._error(400, "missing 'path'")
                key = params.get("destination_frame") or \
                    src.rsplit("/", 1)[-1]
                FRAMES[key] = import_file(src)
                return self._json(_frame_schema(key, FRAMES[key]))
            if path in ("/3/AutoML", "/99/AutoMLBuilder"):
                return self._build_automl(params)
            if path.startswith("/99/Grid/"):
                return self._build_grid(path[len("/99/Grid/"):], params)
            if path.startswith("/3/ModelBuilders/"):
                algo = path[len("/3/ModelBuilders/"):]
                if algo not in _ALGOS:
                    return self._error(404, f"unknown algo '{algo}'")
                return self._build_model(algo, params)
            if path.startswith("/3/Predictions/models/"):
                rest = path[len("/3/Predictions/models/"):]
                if rest.endswith("/contributions") and \
                        "/frames/" not in rest:
                    # explainable serving: per-row TreeSHAP through
                    # the micro-batcher, under its own SLO class
                    mkey = urllib.parse.unquote(
                        rest[: -len("/contributions")])
                    if mkey not in MODELS:
                        return self._error(404,
                                           f"model '{mkey}' not found")
                    return self._contrib_rows(MODELS[mkey], mkey,
                                              params, deadline=deadline,
                                              slo=slo, tid=tid, t0=t0)
                mkey, sep, fpart = rest.partition("/frames/")
                mkey = urllib.parse.unquote(mkey)
                fpart = urllib.parse.unquote(fpart)
                if mkey not in MODELS:
                    return self._error(404, f"model '{mkey}' not found")
                if not sep:
                    # inline serving route: JSON rows in, predictions
                    # out — no frame registration, scored through the
                    # micro-batcher + jitted-scorer cache
                    return self._score_rows(MODELS[mkey], mkey, params,
                                            deadline=deadline,
                                            slo=slo, tid=tid, t0=t0)
                if fpart not in FRAMES:
                    return self._error(404, f"frame '{fpart}' not found")
                pred = _predict_via_batcher(MODELS[mkey], FRAMES[fpart],
                                            deadline=deadline,
                                            model_key=mkey,
                                            slo=_resolve_slo(mkey, slo),
                                            tid=tid, t0=t0)
                key = f"prediction_{mkey}_{fpart}"
                FRAMES[key] = pred
                return self._json({"predictions_frame": {"name": key},
                                   **_frame_schema(key, pred)},
                                  headers={"X-H2O-Trace-Id": tid})
            return self._error(404, f"no route for POST {path}")
        except _DeadlineExpired as e:
            # the client's budget ran out before we dispatched: 504,
            # zero device work wasted on an answer nobody is awaiting
            _bump_stat("deadline_504")
            return self._error(504, str(e))
        except QueueFullError as e:
            # load shedding: the admission queue is full — fast 429 +
            # Retry-After beats queueing into latency collapse
            return self._error(429, str(e), retry_after=e.retry_after)
        except CircuitOpenError as e:
            # breaker open: instant 503, Retry-After = cooldown left
            return self._error(503, str(e), retry_after=e.retry_after)
        except ClusterHealthError as e:
            # the cloud died between the up-front gate and the dispatch
            return self._error(503, str(e))
        except TimeoutError as e:
            # a scoring request must never hang behind the batcher
            return self._error(503, str(e))
        except Exception as e:       # noqa: BLE001
            traceback.print_exc()
            return self._error(500, repr(e))

    def do_DELETE(self):
        try:
            path = urllib.parse.urlparse(self.path).path.rstrip("/")
            if path.startswith("/3/Frames/"):
                key = urllib.parse.unquote(path[len("/3/Frames/"):])
                if FRAMES.pop(key, None) is None:
                    return self._error(404, f"frame '{key}' not found")
                return self._json({"frame_id": {"name": key},
                                   "removed": True})
            if path.startswith("/3/Models/"):
                key = urllib.parse.unquote(path[len("/3/Models/"):])
                if MODELS.pop(key, None) is None:
                    return self._error(404, f"model '{key}' not found")
                return self._json({"model_id": {"name": key},
                                   "removed": True})
            if path == "/3/DKV":          # remove-all (h2o DELETE /3/DKV)
                n = (len(FRAMES) + len(MODELS) + len(AUTOML)
                     + len(GRIDS))
                FRAMES.clear()
                MODELS.clear()
                AUTOML.clear()
                GRIDS.clear()
                return self._json({"removed": n})
            return self._error(404, f"no route for DELETE {path}")
        except Exception as e:       # noqa: BLE001
            traceback.print_exc()
            return self._error(500, repr(e))

    @staticmethod
    def _coerce(params: dict) -> dict:
        """Form-encoded values arrive as strings — JSON-decode the
        obvious scalars/lists ('50' -> 50, '[1,2]' -> [1,2])."""
        kw = {}
        for k, v in params.items():
            if isinstance(v, str):
                try:
                    v = json.loads(v)
                except (ValueError, TypeError):
                    pass
            kw[k] = v
        return kw

    def _registry_load(self, params: dict):
        """POST /3/ModelRegistry/load — the operator push route: load a
        MOJO-v2 artifact (by persist path or inline base64 bytes),
        pre-trace its pow2 batch buckets, and ONLY THEN publish it
        under ``model_id`` — so the model-registry readiness gate (and
        a rolling update's traffic shift) can never observe a model
        that would compile on its first request."""
        import base64
        import hashlib

        from . import persist
        from .models.base import model_scorer_counters
        from .operator.registry import load_artifact

        model_id = params.get("model_id")
        if not model_id or not isinstance(model_id, str):
            return self._error(400, "missing 'model_id'")
        slo = params.get("slo")
        if slo is not None and slo not in SLO_CLASSES:
            return self._error(
                400, f"unknown SLO class {slo!r} "
                f"(known: {', '.join(sorted(SLO_CLASSES))})")
        b64 = params.get("artifact_b64")
        path = params.get("path")
        if b64:
            try:
                blob = base64.b64decode(b64, validate=True)
            except Exception:  # noqa: BLE001 — binascii detail useless
                return self._error(400, "bad 'artifact_b64' (not valid "
                                   "base64)")
        elif path:
            try:
                blob = persist.read_bytes(str(path))
            except FileNotFoundError:
                return self._error(404, f"artifact not found at "
                                   f"{path!r}")
        else:
            return self._error(400, "need 'path' (persist-readable "
                               "artifact) or 'artifact_b64'")
        want_sha = params.get("sha256")
        if want_sha:
            got = hashlib.sha256(blob).hexdigest()
            if got != str(want_sha):
                return self._error(
                    409, f"artifact digest mismatch (got {got[:12]}, "
                    f"registry says {str(want_sha)[:12]}) — refusing "
                    "to serve a corrupted model")
        try:
            model = load_artifact(blob)
        except ValueError as e:
            return self._error(400, f"unservable artifact: {e}")
        buckets = params.get("warm_buckets")
        # contributions ride the same warm-up contract: when the
        # artifact supports TreeSHAP (has the cover part, binomial/
        # regression, no offset), its contrib executables pre-trace
        # here too, so the FIRST explain request after readyz is also
        # zero-compile (warm_cache_misses == 0 covers both programs)
        warm_contrib = model.contrib_support() is None
        try:
            warmed = model.warm_up(buckets, contributions=warm_contrib)
        except ValueError as e:
            return self._error(400, str(e))
        MODELS[model_id] = model
        ctr = model_scorer_counters(model)
        REGISTRY_MODELS[model_id] = {
            "name": params.get("name"),
            "version": params.get("version"),
            "algo": model.algo,
            "slo": slo,
            "warmed_buckets": warmed,
            "contributions": warm_contrib,
            # per-MODEL baseline: traces paid so far that were not
            # promotions — /3/Stats diffs against this, so eviction
            # re-traces (promotions) can never read as warm misses
            "warm_baseline": ctr["misses"] - ctr["promotions"],
            "loaded_at": time.time(),
        }
        with _STATS_LOCK:
            _model_stats(model_id, slo)
        return self._json({"model_id": {"name": model_id},
                           "name": params.get("name"),
                           "version": params.get("version"),
                           "algo": model.algo,
                           "slo": slo,
                           "warmed_buckets": warmed,
                           "contributions": warm_contrib})

    def _score_rows(self, model, mkey: str, params: dict,
                    deadline: float | None = None,
                    slo: str | None = None,
                    tid: str | None = None,
                    t0: float | None = None):
        """POST /3/Predictions/models/{key} — serving-shaped scoring:
        JSON rows in, predictions out, one micro-batched dispatch
        under the model's SLO class (header > registry default >
        H2O_TPU_SLO_DEFAULT)."""
        if not getattr(model, "_serving_jit", False):
            # kmeans/isolationforest/stackedensemble & co. have no raw-
            # matrix serving contract (predict() overrides / composed
            # scoring) — reject cleanly instead of 500ing in score_numpy
            # or leaking unlabeled _score_matrix output
            return self._error(
                400, f"model '{mkey}' ({getattr(model, 'algo', '?')}) "
                "does not support inline row scoring; use "
                f"/3/Predictions/models/{mkey}/frames/{{frame}}")
        rows = params.get("rows")
        if rows is None:
            return self._error(400, "missing 'rows' (JSON list of "
                               "row dicts, or lists + 'columns')")
        max_rows = _score_row_cap()
        if isinstance(rows, list) and len(rows) > max_rows:
            # cap the PUBLIC route's dispatch size: one oversized
            # payload OOM-ing the device would trip the locked-cloud
            # protocol and 503 every later request — a single bad
            # request must never become a cluster-wide serving outage
            return self._error(
                413, f"{len(rows)} rows exceeds the per-request limit "
                f"of {max_rows} (H2O_TPU_SCORE_MAX_ROWS); split the "
                "batch or use the frames route")
        off = None
        oc = getattr(model, "offset_column", None)
        try:
            X = _rows_to_matrix(model, rows, params.get("columns"))
            if oc:
                if not isinstance(rows[0], dict):
                    raise ValueError(f"offset column '{oc}' needs "
                                     "dict-shaped rows")
                # r[oc] (not .get): a row omitting the offset must
                # reject like any other absent column
                off = np.asarray(
                    [float(r[oc]) if r[oc] is not None else np.nan
                     for r in rows], dtype=np.float32)
        except (ValueError, TypeError, KeyError, IndexError) as e:
            return self._error(400, f"bad scoring payload: {e!r}")
        out = _traced_submit(model, X, tid=tid, t0=t0, model_key=mkey,
                             slo=_resolve_slo(mkey, slo), offset=off,
                             deadline=deadline)
        resp: dict = {"model_id": {"name": mkey}, "rows": len(rows)}
        if getattr(model, "nclasses", 1) > 1:
            dom = model.response_domain or \
                [str(i) for i in range(model.nclasses)]
            labels = out.argmax(axis=1)
            resp["predict"] = [dom[int(i)] for i in labels]
            for k, name in enumerate(dom):
                resp[f"p{name}"] = [float(v) for v in out[:, k]]
        else:
            out = np.asarray(out)
            if out.ndim > 1:     # e.g. autoencoder reconstruction
                resp["predict"] = [[float(v) for v in row]
                                   for row in out]
            else:
                resp["predict"] = [float(v) for v in out]
        return self._json(resp, headers={"X-H2O-Trace-Id": tid}
                          if tid else None)

    def _contrib_rows(self, model, mkey: str, params: dict,
                      deadline: float | None = None,
                      slo: str | None = None,
                      tid: str | None = None,
                      t0: float | None = None):
        """POST /3/Predictions/models/{key}/contributions — per-row
        TreeSHAP contributions over the serving stack: JSON rows in,
        one [rows, F+1] device TreeSHAP dispatch (coalesced by the
        micro-batcher under the `explain` SLO class) out.

        Error hygiene contract: every precondition failure —
        multinomial, offset-trained, a pre-cover / NaN-cover model or
        artifact — surfaces as a clean 400 carrying the model's own
        retrain/re-export message, never a 500 traceback."""
        support = getattr(model, "contrib_support", None)
        reason = support() if callable(support) else (
            f"model '{mkey}' ({getattr(model, 'algo', '?')}) does not "
            "support predict_contributions")
        if reason:
            return self._error(
                400, f"contributions unavailable for model '{mkey}': "
                f"{reason}")
        rows = params.get("rows")
        if rows is None:
            return self._error(400, "missing 'rows' (JSON list of "
                               "row dicts, or lists + 'columns')")
        max_rows = _contrib_row_cap()
        if isinstance(rows, list) and len(rows) > max_rows:
            return self._error(
                413, f"{len(rows)} rows exceeds the per-request limit "
                f"of {max_rows} (H2O_TPU_CONTRIB_MAX_ROWS); split the "
                "batch")
        try:
            X = _rows_to_matrix(model, rows, params.get("columns"))
        except (ValueError, TypeError, KeyError, IndexError) as e:
            return self._error(400, f"bad contributions payload: {e!r}")
        out = _traced_submit(model, X, tid=tid, t0=t0, model_key=mkey,
                             slo=_resolve_contrib_slo(slo),
                             kind="contrib", deadline=deadline)
        cols = list(model.feature_names) + ["BiasTerm"]
        return self._json({
            "model_id": {"name": mkey}, "rows": len(rows),
            "columns": cols,
            "contributions": [[float(v) for v in row] for row in out]},
            headers={"X-H2O-Trace-Id": tid} if tid else None)

    def _run_job(self, job, fn, sync_timeout: float):
        """Run fn on a worker thread under `job`, waiting up to
        sync_timeout (the Job keeps running past the wait — poll
        /3/Jobs, like the reference's async builds)."""
        def run():
            try:
                fn()
                job.done()
            except BaseException as e:  # noqa: BLE001 — a worker dying
                # for ANY reason (incl. SystemExit from a wedged
                # runtime) must land on the Job, never leave it RUNNING
                # forever for pollers of /3/Jobs
                traceback.print_exc()
                job.failed(repr(e))

        t = threading.Thread(target=run, daemon=True)
        t.start()
        # recorded AFTER start: the /3/Jobs reaper treats a RUNNING job
        # with a dead recorded thread as failed, and a created-but-not-
        # yet-started thread reads not-alive — assigning first would
        # let a concurrent poll reap a healthy build
        job._thread = t
        t.join(timeout=sync_timeout)

    def _build_automl(self, params: dict):
        from .automl import AutoML, Job

        training = params.pop("training_frame", None)
        if training not in FRAMES:
            return self._error(404, f"frame '{training}' not found")
        y = params.pop("response_column", params.pop("y", None))
        if y is None:
            return self._error(400, "missing 'response_column'")
        sync_timeout = float(params.pop("_sync_timeout", 600))
        # ids stay strings: _coerce would turn '2024' into int 2024 and
        # the string-keyed GET routes could never find the registry entry
        project = str(params.pop("project_name", "automl"))
        kw = self._coerce(params)
        kw["project_name"] = project
        aml = AutoML(**kw)
        AUTOML[project] = aml
        # AutoML.train registers its own Job under the project name;
        # the REST wrapper job tracks the HTTP build as a whole
        job = Job(dest=f"{project}.rest",
                  description=f"AutoML on {training}")
        job.start()

        def run():
            aml.train(y=y, training_frame=FRAMES[training])
            # publish every trained model into the DKV-analog registry
            MODELS.update(aml.leaderboard.models)

        self._run_job(job, run, sync_timeout)
        return self._json({"job": {"dest": {"name": project},
                                   "status": job.status,
                                   "msg": job.msg},
                           "project_name": project})

    def _build_grid(self, algo: str, params: dict):
        from .automl import Job
        from .grid import GridSearch

        if algo not in _ALGOS:
            return self._error(404, f"unknown algo '{algo}'")
        training = params.pop("training_frame", None)
        if training not in FRAMES:
            return self._error(404, f"frame '{training}' not found")
        y = params.pop("response_column", params.pop("y", None))
        if y is None:
            # without it every combo fails silently into failed_params
            # and the grid reports DONE with zero models
            return self._error(400, "missing 'response_column'")
        sync_timeout = float(params.pop("_sync_timeout", 600))
        grid_id = str(params.pop("grid_id", "") or f"grid_{algo}")
        kw = self._coerce(params)
        hyper = kw.pop("hyper_parameters", None)
        if not isinstance(hyper, dict) or not hyper:
            return self._error(400, "missing 'hyper_parameters' (JSON "
                               "object of param -> list of values)")
        criteria = kw.pop("search_criteria", None)
        est = _algo_estimator(algo)(**kw)
        gs = GridSearch(est, hyper, grid_id=grid_id,
                        search_criteria=criteria)
        GRIDS[grid_id] = gs
        # GridSearch.train registers its own Job under grid_id
        job = Job(dest=f"{grid_id}.rest",
                  description=f"grid {algo} on {training}")
        job.start()

        def run():
            gs.train(y=y, training_frame=FRAMES[training])
            MODELS.update(gs.leaderboard.models)

        self._run_job(job, run, sync_timeout)
        return self._json({"job": {"dest": {"name": grid_id},
                                   "status": job.status,
                                   "msg": job.msg},
                           "grid_id": {"name": grid_id}})

    def _build_model(self, algo: str, params: dict):
        from .automl import Job

        training = params.pop("training_frame", None)
        if training not in FRAMES:
            return self._error(404, f"frame '{training}' not found")
        y = params.pop("response_column", params.pop("y", None))
        sync_timeout = float(params.pop("_sync_timeout", 600))
        model_id = params.pop("model_id", None)
        if not model_id:
            with _ID_LOCK:                 # ThreadingHTTPServer: no races
                global _MODEL_SEQ
                _MODEL_SEQ += 1
                model_id = f"{algo}_{_MODEL_SEQ}"
        ignored = params.pop("ignored_columns", None)
        kw = self._coerce(params)
        job = Job(dest=model_id,
                  description=f"{algo} on {training}").start()

        def run():
            est = _algo_estimator(algo)(**kw)
            if y is not None:
                model = est.train(y=y, training_frame=FRAMES[training],
                                  ignored_columns=ignored)
            else:
                model = est.train(training_frame=FRAMES[training],
                                  ignored_columns=ignored)
            MODELS[model_id] = model

        self._run_job(job, run, sync_timeout)
        return self._json({"job": {"dest": {"name": model_id},
                                   "status": job.status,
                                   "msg": job.msg}})


_SERVERS: "weakref.WeakSet[ThreadingHTTPServer]" = weakref.WeakSet()


def _shutdown_servers() -> None:
    """Drain-path hook: stop every live REST server's accept loop AND
    close its listening socket — a TERMINATED in-process node must
    refuse connections instantly, not accept ones it will never serve
    (in-flight handler threads keep their own sockets and finish)."""
    for srv in list(_SERVERS):
        try:
            srv.shutdown()
            srv.server_close()
        except Exception:  # noqa: BLE001 — drain must not die on one
            pass
        _SERVERS.discard(srv)


def start_server(port: int = 54321, host: str = "127.0.0.1",
                 background: bool = True,
                 install_signals: bool = False) -> ThreadingHTTPServer:
    """Start the REST server (:54321 is the reference's default port).

    The node goes SERVING (``/readyz`` can pass) and the server's
    shutdown is registered on the drain path, so SIGTERM → drain stops
    accepting connections only AFTER the micro-batcher flushed and
    jobs settled. ``install_signals=True`` (the ``__main__``/pod entry)
    installs the SIGTERM handler and exits the process when the drain
    completes — inside ``terminationGracePeriodSeconds``, ahead of the
    kubelet's SIGKILL."""
    srv = ThreadingHTTPServer((host, port), _Handler)
    # compile accounting from server start: /3/Stats exposes the watch
    # so operators (and the tenant-storm drill) can assert promotion
    # compiles are persistent-cache hits, not cold compiles
    from .runtime.backend import start_compile_watch

    start_compile_watch()
    if os.environ.get("H2O_TPU_POOL_REPLICA") == "1":
        # operator-provisioned scorer replica: readiness additionally
        # requires a pushed+warmed registry artifact, so the Service
        # never routes to a pod that would compile on request one
        install_pool_replica_gate()
    lifecycle.mark_serving()
    # one module-level hook over the set of live servers (not one hook
    # per start_server call): register_shutdown is idempotent by
    # identity, and dead servers fall out of the WeakSet, so a process
    # that restarts the REST server many times neither leaks server
    # objects nor replays stale shutdowns at drain time
    _SERVERS.add(srv)
    lifecycle.register_shutdown(_shutdown_servers)
    if install_signals:
        lifecycle.install_sigterm(exit_on_drain=True)
    if background:
        t = threading.Thread(target=srv.serve_forever,
                             name="h2o-tpu-rest", daemon=True)
        t.start()
    else:
        srv.serve_forever()
    return srv


if __name__ == "__main__":
    import sys

    start_server(int(sys.argv[1]) if len(sys.argv) > 1 else 54321,
                 background=False, install_signals=True)
