"""REST v3 API server — the water/api RequestServer analog.

Reference: h2o-core water/api (RequestServer + schemas3, SURVEY.md §2b
C9): a Jetty server on :54321 where every client verb is a versioned
endpoint — /3/Cloud, /3/ImportFiles, /3/Parse, /3/Frames,
/3/ModelBuilders/{algo}, /3/Models, /3/Predictions, /3/Jobs,
/99/AutoMLBuilder + /3/AutoML, /99/Grid, DELETE on frames/models,
/3/Timeline, and the leader-only readiness probe
/kubernetes/isLeaderNode (h2o-kubernetes [U] wires its readiness to
this — only the clustered leader node answers 200).

This build is Python-first (the client talks to the library directly),
so the REST layer is a thin JSON adapter over the same registries the
Python API uses: Frames and Models live in module-level key-value
stores (the DKV-for-small-objects analog), model builds run on a
worker thread under a Job, and every response is plain JSON. Start one
with `h2o_kubernetes_tpu.rest.start_server(port)` or
`python -m h2o_kubernetes_tpu.rest`.
"""

from __future__ import annotations

import json
import threading
import traceback
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .runtime.health import ClusterHealthError

FRAMES: dict[str, object] = {}     # key -> Frame (DKV analog)
MODELS: dict[str, object] = {}     # key -> Model
AUTOML: dict[str, object] = {}     # project_name -> AutoML
GRIDS: dict[str, object] = {}      # grid_id -> GridSearch
_ID_LOCK = threading.Lock()
_MODEL_SEQ = 0


def _runtime_process_index() -> int | None:
    """jax.process_index() IF the distributed runtime is up, else None.

    Deliberately inspects the distributed client state instead of
    calling jax.process_index(): that call initializes the backends,
    and the readiness probe must never be the thing that hangs on a
    recovering TPU client init."""
    try:
        from jax._src import distributed

        if distributed.global_state.client is None:
            return None
        import jax

        return int(jax.process_index())
    except Exception:
        return None


def _is_leader() -> bool:
    """True on the clustered leader (process 0). The operator injects
    H2O_TPU_PROCESS_ID into every pod (native/deployment/manifests.cc);
    single-process clouds are their own leader.

    When the distributed runtime is actually up, the env var claim is
    CROSS-CHECKED against jax.process_index(): a mislabeled pod (env
    says 0, runtime disagrees — or vice versa) must fail readiness
    rather than route client traffic to a non-leader (the reference's
    /kubernetes/isLeaderNode answers from cluster state, not pod
    metadata; h2o-k8s [U3])."""
    import os

    raw = os.environ.get("H2O_TPU_PROCESS_ID") or "0"
    try:
        env_leader = int(raw) == 0
    except ValueError:
        # an unparseable pod index must read as not-leader (503), not
        # crash the probe into a 500 on every pod
        return False
    rt = _runtime_process_index()
    if rt is not None:
        rt_leader = rt == 0
        if rt_leader != env_leader:
            from .diagnostics import log, timeline

            msg = (f"H2O_TPU_PROCESS_ID={raw!r} but "
                   f"jax.process_index()={rt}")
            timeline.record("leader_mismatch", msg)
            log.error("leader identity mismatch: %s", msg)
            return False
        return rt_leader
    return env_leader

_ALGOS = ("gbm", "drf", "glm", "deeplearning", "xgboost", "kmeans",
          "naivebayes", "pca", "isolationforest", "glrm", "coxph",
          "aggregator")


def _algo_estimator(algo: str):
    from . import models as M

    return {
        "gbm": M.GBM, "drf": M.DRF, "glm": M.GLM,
        "deeplearning": M.DeepLearning, "xgboost": M.XGBoost,
        "kmeans": M.KMeans, "naivebayes": M.NaiveBayes, "pca": M.PCA,
        "isolationforest": M.IsolationForest, "glrm": M.GLRM,
        "coxph": M.CoxPH, "aggregator": M.Aggregator,
    }[algo]


def _definite(obj):
    """Recursively replace non-finite floats with None (JSON null)."""
    if isinstance(obj, float):
        import math

        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: _definite(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_definite(v) for v in obj]
    return obj


def _frame_schema(key: str, fr) -> dict:
    return {"frame_id": {"name": key}, "rows": fr.nrows,
            "columns": [{"label": n,
                         "type": fr.vec(n).kind} for n in fr.names]}


class _Handler(BaseHTTPRequestHandler):
    server_version = "h2o-tpu-rest/1"

    def log_message(self, *a):       # quiet by default
        pass

    # -- plumbing ------------------------------------------------------------

    def _json(self, obj, code: int = 200):
        # metrics can be NaN (single-class CV folds, zero-weight rmse);
        # json.dumps would emit bare `NaN` — invalid JSON that strict
        # parsers (fetch, jsonlite) reject. Null them out instead.
        body = json.dumps(_definite(obj)).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code: int, msg: str):
        self._json({"__schema": "H2OErrorV3", "http_status": code,
                    "msg": msg}, code)

    def _unhealthy_503(self) -> bool:
        """Send 503 + the health error when the cloud is locked-
        unhealthy — graceful degradation instead of spawning a doomed
        job (or a 500 with a raw traceback). False when healthy."""
        from .runtime import health

        if health.healthy():
            return False
        err = health.health_status()["error"]
        self._error(503, f"cluster unhealthy: {err} — restart the "
                    "cluster and resume from the last checkpoint")
        return True

    def _params(self) -> dict:
        q = urllib.parse.urlparse(self.path).query
        out = {k: v[0] for k, v in urllib.parse.parse_qs(q).items()}
        ln = int(self.headers.get("Content-Length") or 0)
        if ln:
            raw = self.rfile.read(ln).decode()
            ctype = self.headers.get("Content-Type", "")
            if "json" in ctype:
                out.update(json.loads(raw))
            else:
                out.update({k: v[0] for k, v in
                            urllib.parse.parse_qs(raw).items()})
        return out

    # -- routes --------------------------------------------------------------

    def do_GET(self):
        try:
            path = urllib.parse.urlparse(self.path).path.rstrip("/")
            if path in ("", "/flow", "/flow/index.html"):
                # the h2o-web Flow analog (SURVEY §2b C19): one
                # self-contained page, same REST verbs as any client
                import os

                page = os.path.join(os.path.dirname(
                    os.path.abspath(__file__)), "flow", "index.html")
                with open(page, "rb") as f:
                    body = f.read()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return None
            if path == "/3/Cloud":
                from . import cluster_status

                return self._json(cluster_status())
            if path in ("/kubernetes/isLeaderNode", "/3/IsLeaderNode"):
                # readiness must pass ONLY on the leader so the Service
                # routes clients to one consistent node (reference
                # /kubernetes/isLeaderNode, SURVEY.md §2b C2)
                if _is_leader():
                    return self._json({"leader": True})
                return self._error(503, "not the leader node")
            if path == "/3/Timeline":
                from .diagnostics import timeline

                return self._json({"events": timeline.events()})
            if path.startswith("/3/AutoML/"):
                key = urllib.parse.unquote(
                    path[len("/3/AutoML/"):])
                if key not in AUTOML:
                    return self._error(404, f"automl '{key}' not found")
                aml = AUTOML[key]
                lb = aml.leaderboard.as_list() if aml.leaderboard else []
                leader = lb[0]["model_id"] if lb else None
                return self._json({
                    "project_name": key,
                    "leader": {"name": leader},
                    "leaderboard": lb,
                    "sort_metric": aml.leaderboard.sort_metric
                    if aml.leaderboard else None})
            if path.startswith("/99/Grids/"):
                key = urllib.parse.unquote(
                    path[len("/99/Grids/"):])
                if key not in GRIDS:
                    return self._error(404, f"grid '{key}' not found")
                g = GRIDS[key]
                return self._json({
                    "grid_id": {"name": key},
                    "model_ids": [{"name": m} for m in g.model_ids],
                    "summary": g.get_grid()})
            if path == "/3/Jobs":
                from .automl import jobs

                return self._json({"jobs": jobs()})
            if path == "/3/Frames":
                return self._json({"frames": [
                    _frame_schema(k, f) for k, f in FRAMES.items()]})
            if path.startswith("/3/Frames/"):
                rest = path[len("/3/Frames/"):]
                key, _, verb = rest.partition("/")
                key = urllib.parse.unquote(key)
                if key not in FRAMES:
                    return self._error(404, f"frame '{key}' not found")
                fr = FRAMES[key]
                if verb == "summary":
                    return self._json({"frame_id": {"name": key},
                                       "summary": fr.summary()})
                return self._json(_frame_schema(key, fr))
            if path == "/3/Models":
                return self._json({"models": [
                    {"model_id": {"name": k}, "algo": m.algo}
                    for k, m in MODELS.items()]})
            if path.startswith("/3/Models/"):
                rest_part = path[len("/3/Models/"):]
                key, _, verb = rest_part.partition("/")
                key = urllib.parse.unquote(key)
                if key not in MODELS:
                    return self._error(404, f"model '{key}' not found")
                m = MODELS[key]
                if verb == "mojo":
                    # artifact download (h2o-py model.download_mojo via
                    # GET /3/Models/{id}/mojo [U3])
                    import os
                    import tempfile

                    from .mojo import export_mojo

                    # fixed artifact name inside the tempdir: model keys
                    # come verbatim from POST bodies, so using them as a
                    # path component would allow ../ traversal out of td
                    with tempfile.TemporaryDirectory() as td:
                        p = export_mojo(m, os.path.join(td, "model.mojo"))
                        with open(p, "rb") as f:
                            blob = f.read()
                    # header filename: strip path separators, quotes and
                    # control chars (CRLF here = response splitting)
                    safe = "".join(
                        c for c in key
                        if c.isalnum() or c in "._- ") or "model"
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/octet-stream")
                    self.send_header(
                        "Content-Disposition",
                        f'attachment; filename="{safe}.mojo"')
                    self.send_header("Content-Length", str(len(blob)))
                    self.end_headers()
                    self.wfile.write(blob)
                    return None
                if verb:
                    return self._error(404, f"no route for GET {path}")
                cvm = getattr(m, "cross_validation_metrics", None)
                out = {"model_id": {"name": key},
                       "algo": m.algo,
                       "nclasses": m.nclasses,
                       "scoring_history":
                           getattr(m, "scoring_history", []),
                       "validation_metrics":
                           getattr(m, "validation_metrics", None),
                       "cross_validation_metrics":
                           cvm() if callable(cvm) else cvm}
                varimp = getattr(m, "varimp", None)
                if callable(varimp):
                    try:
                        out["variable_importances"] = {
                            k: float(v) for k, v in varimp().items()}
                    except Exception:   # noqa: BLE001 — detail is
                        pass            # best-effort, not the contract
                return self._json(out)
            return self._error(404, f"no route for GET {path}")
        except ClusterHealthError as e:
            return self._error(503, str(e))
        except Exception as e:       # noqa: BLE001
            traceback.print_exc()
            return self._error(500, repr(e))

    def do_POST(self):
        try:
            path = urllib.parse.urlparse(self.path).path.rstrip("/")
            params = self._params()
            # every POST verb does device work (parse shards onto the
            # mesh, builds/predictions dispatch collectives): on a dead
            # cloud degrade to 503 up front — reads (GET) stay served
            if self._unhealthy_503():
                return None
            if path == "/3/ImportFiles" or path == "/3/Parse":
                from .frame import import_file

                src = params.get("path") or params.get("source_frames")
                if isinstance(src, (list, tuple)):
                    if not src:
                        return self._error(400, "missing 'path'")
                    if len(src) != 1:   # refuse, don't silently truncate
                        return self._error(
                            400, "multi-file Parse is not supported over "
                            "REST; pass one path (globs allowed)")
                    src = src[0]
                if not src or not isinstance(src, str):
                    return self._error(400, "missing 'path'")
                key = params.get("destination_frame") or \
                    src.rsplit("/", 1)[-1]
                FRAMES[key] = import_file(src)
                return self._json(_frame_schema(key, FRAMES[key]))
            if path in ("/3/AutoML", "/99/AutoMLBuilder"):
                return self._build_automl(params)
            if path.startswith("/99/Grid/"):
                return self._build_grid(path[len("/99/Grid/"):], params)
            if path.startswith("/3/ModelBuilders/"):
                algo = path[len("/3/ModelBuilders/"):]
                if algo not in _ALGOS:
                    return self._error(404, f"unknown algo '{algo}'")
                return self._build_model(algo, params)
            if path.startswith("/3/Predictions/models/"):
                rest = path[len("/3/Predictions/models/"):]
                mkey, _, fpart = rest.partition("/frames/")
                mkey = urllib.parse.unquote(mkey)
                fpart = urllib.parse.unquote(fpart)
                if mkey not in MODELS:
                    return self._error(404, f"model '{mkey}' not found")
                if fpart not in FRAMES:
                    return self._error(404, f"frame '{fpart}' not found")
                pred = MODELS[mkey].predict(FRAMES[fpart])
                key = f"prediction_{mkey}_{fpart}"
                FRAMES[key] = pred
                return self._json({"predictions_frame": {"name": key},
                                   **_frame_schema(key, pred)})
            return self._error(404, f"no route for POST {path}")
        except ClusterHealthError as e:
            # the cloud died between the up-front gate and the dispatch
            return self._error(503, str(e))
        except Exception as e:       # noqa: BLE001
            traceback.print_exc()
            return self._error(500, repr(e))

    def do_DELETE(self):
        try:
            path = urllib.parse.urlparse(self.path).path.rstrip("/")
            if path.startswith("/3/Frames/"):
                key = urllib.parse.unquote(path[len("/3/Frames/"):])
                if FRAMES.pop(key, None) is None:
                    return self._error(404, f"frame '{key}' not found")
                return self._json({"frame_id": {"name": key},
                                   "removed": True})
            if path.startswith("/3/Models/"):
                key = urllib.parse.unquote(path[len("/3/Models/"):])
                if MODELS.pop(key, None) is None:
                    return self._error(404, f"model '{key}' not found")
                return self._json({"model_id": {"name": key},
                                   "removed": True})
            if path == "/3/DKV":          # remove-all (h2o DELETE /3/DKV)
                n = (len(FRAMES) + len(MODELS) + len(AUTOML)
                     + len(GRIDS))
                FRAMES.clear()
                MODELS.clear()
                AUTOML.clear()
                GRIDS.clear()
                return self._json({"removed": n})
            return self._error(404, f"no route for DELETE {path}")
        except Exception as e:       # noqa: BLE001
            traceback.print_exc()
            return self._error(500, repr(e))

    @staticmethod
    def _coerce(params: dict) -> dict:
        """Form-encoded values arrive as strings — JSON-decode the
        obvious scalars/lists ('50' -> 50, '[1,2]' -> [1,2])."""
        kw = {}
        for k, v in params.items():
            if isinstance(v, str):
                try:
                    v = json.loads(v)
                except (ValueError, TypeError):
                    pass
            kw[k] = v
        return kw

    def _run_job(self, job, fn, sync_timeout: float):
        """Run fn on a worker thread under `job`, waiting up to
        sync_timeout (the Job keeps running past the wait — poll
        /3/Jobs, like the reference's async builds)."""
        def run():
            try:
                fn()
                job.done()
            except BaseException as e:  # noqa: BLE001 — a worker dying
                # for ANY reason (incl. SystemExit from a wedged
                # runtime) must land on the Job, never leave it RUNNING
                # forever for pollers of /3/Jobs
                traceback.print_exc()
                job.failed(repr(e))

        t = threading.Thread(target=run, daemon=True)
        t.start()
        t.join(timeout=sync_timeout)

    def _build_automl(self, params: dict):
        from .automl import AutoML, Job

        training = params.pop("training_frame", None)
        if training not in FRAMES:
            return self._error(404, f"frame '{training}' not found")
        y = params.pop("response_column", params.pop("y", None))
        if y is None:
            return self._error(400, "missing 'response_column'")
        sync_timeout = float(params.pop("_sync_timeout", 600))
        # ids stay strings: _coerce would turn '2024' into int 2024 and
        # the string-keyed GET routes could never find the registry entry
        project = str(params.pop("project_name", "automl"))
        kw = self._coerce(params)
        kw["project_name"] = project
        aml = AutoML(**kw)
        AUTOML[project] = aml
        # AutoML.train registers its own Job under the project name;
        # the REST wrapper job tracks the HTTP build as a whole
        job = Job(dest=f"{project}.rest",
                  description=f"AutoML on {training}")
        job.start()

        def run():
            aml.train(y=y, training_frame=FRAMES[training])
            # publish every trained model into the DKV-analog registry
            MODELS.update(aml.leaderboard.models)

        self._run_job(job, run, sync_timeout)
        return self._json({"job": {"dest": {"name": project},
                                   "status": job.status,
                                   "msg": job.msg},
                           "project_name": project})

    def _build_grid(self, algo: str, params: dict):
        from .automl import Job
        from .grid import GridSearch

        if algo not in _ALGOS:
            return self._error(404, f"unknown algo '{algo}'")
        training = params.pop("training_frame", None)
        if training not in FRAMES:
            return self._error(404, f"frame '{training}' not found")
        y = params.pop("response_column", params.pop("y", None))
        if y is None:
            # without it every combo fails silently into failed_params
            # and the grid reports DONE with zero models
            return self._error(400, "missing 'response_column'")
        sync_timeout = float(params.pop("_sync_timeout", 600))
        grid_id = str(params.pop("grid_id", "") or f"grid_{algo}")
        kw = self._coerce(params)
        hyper = kw.pop("hyper_parameters", None)
        if not isinstance(hyper, dict) or not hyper:
            return self._error(400, "missing 'hyper_parameters' (JSON "
                               "object of param -> list of values)")
        criteria = kw.pop("search_criteria", None)
        est = _algo_estimator(algo)(**kw)
        gs = GridSearch(est, hyper, grid_id=grid_id,
                        search_criteria=criteria)
        GRIDS[grid_id] = gs
        # GridSearch.train registers its own Job under grid_id
        job = Job(dest=f"{grid_id}.rest",
                  description=f"grid {algo} on {training}")
        job.start()

        def run():
            gs.train(y=y, training_frame=FRAMES[training])
            MODELS.update(gs.leaderboard.models)

        self._run_job(job, run, sync_timeout)
        return self._json({"job": {"dest": {"name": grid_id},
                                   "status": job.status,
                                   "msg": job.msg},
                           "grid_id": {"name": grid_id}})

    def _build_model(self, algo: str, params: dict):
        from .automl import Job

        training = params.pop("training_frame", None)
        if training not in FRAMES:
            return self._error(404, f"frame '{training}' not found")
        y = params.pop("response_column", params.pop("y", None))
        sync_timeout = float(params.pop("_sync_timeout", 600))
        model_id = params.pop("model_id", None)
        if not model_id:
            with _ID_LOCK:                 # ThreadingHTTPServer: no races
                global _MODEL_SEQ
                _MODEL_SEQ += 1
                model_id = f"{algo}_{_MODEL_SEQ}"
        ignored = params.pop("ignored_columns", None)
        kw = self._coerce(params)
        job = Job(dest=model_id,
                  description=f"{algo} on {training}").start()

        def run():
            est = _algo_estimator(algo)(**kw)
            if y is not None:
                model = est.train(y=y, training_frame=FRAMES[training],
                                  ignored_columns=ignored)
            else:
                model = est.train(training_frame=FRAMES[training],
                                  ignored_columns=ignored)
            MODELS[model_id] = model

        self._run_job(job, run, sync_timeout)
        return self._json({"job": {"dest": {"name": model_id},
                                   "status": job.status,
                                   "msg": job.msg}})


def start_server(port: int = 54321, host: str = "127.0.0.1",
                 background: bool = True) -> ThreadingHTTPServer:
    """Start the REST server (:54321 is the reference's default port)."""
    srv = ThreadingHTTPServer((host, port), _Handler)
    if background:
        t = threading.Thread(target=srv.serve_forever,
                             name="h2o-tpu-rest", daemon=True)
        t.start()
    else:
        srv.serve_forever()
    return srv


if __name__ == "__main__":
    import sys

    start_server(int(sys.argv[1]) if len(sys.argv) > 1 else 54321,
                 background=False)
