"""REST v3 API server — the water/api RequestServer analog.

Reference: h2o-core water/api (RequestServer + schemas3, SURVEY.md §2b
C9): a Jetty server on :54321 where every client verb is a versioned
endpoint — /3/Cloud, /3/ImportFiles, /3/Parse, /3/Frames,
/3/ModelBuilders/{algo}, /3/Models, /3/Predictions, /3/Jobs.

This build is Python-first (the client talks to the library directly),
so the REST layer is a thin JSON adapter over the same registries the
Python API uses: Frames and Models live in module-level key-value
stores (the DKV-for-small-objects analog), model builds run on a
worker thread under a Job, and every response is plain JSON. Start one
with `h2o_kubernetes_tpu.rest.start_server(port)` or
`python -m h2o_kubernetes_tpu.rest`.
"""

from __future__ import annotations

import json
import threading
import traceback
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

FRAMES: dict[str, object] = {}     # key -> Frame (DKV analog)
MODELS: dict[str, object] = {}     # key -> Model
_ID_LOCK = threading.Lock()
_MODEL_SEQ = 0

_ALGOS = ("gbm", "drf", "glm", "deeplearning", "xgboost", "kmeans",
          "naivebayes", "pca", "isolationforest", "glrm", "coxph",
          "aggregator")


def _algo_estimator(algo: str):
    from . import models as M

    return {
        "gbm": M.GBM, "drf": M.DRF, "glm": M.GLM,
        "deeplearning": M.DeepLearning, "xgboost": M.XGBoost,
        "kmeans": M.KMeans, "naivebayes": M.NaiveBayes, "pca": M.PCA,
        "isolationforest": M.IsolationForest, "glrm": M.GLRM,
        "coxph": M.CoxPH, "aggregator": M.Aggregator,
    }[algo]


def _frame_schema(key: str, fr) -> dict:
    return {"frame_id": {"name": key}, "rows": fr.nrows,
            "columns": [{"label": n,
                         "type": fr.vec(n).kind} for n in fr.names]}


class _Handler(BaseHTTPRequestHandler):
    server_version = "h2o-tpu-rest/1"

    def log_message(self, *a):       # quiet by default
        pass

    # -- plumbing ------------------------------------------------------------

    def _json(self, obj, code: int = 200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code: int, msg: str):
        self._json({"__schema": "H2OErrorV3", "http_status": code,
                    "msg": msg}, code)

    def _params(self) -> dict:
        q = urllib.parse.urlparse(self.path).query
        out = {k: v[0] for k, v in urllib.parse.parse_qs(q).items()}
        ln = int(self.headers.get("Content-Length") or 0)
        if ln:
            raw = self.rfile.read(ln).decode()
            ctype = self.headers.get("Content-Type", "")
            if "json" in ctype:
                out.update(json.loads(raw))
            else:
                out.update({k: v[0] for k, v in
                            urllib.parse.parse_qs(raw).items()})
        return out

    # -- routes --------------------------------------------------------------

    def do_GET(self):
        try:
            path = urllib.parse.urlparse(self.path).path.rstrip("/")
            if path == "/3/Cloud":
                from . import cluster_status

                return self._json(cluster_status())
            if path == "/3/Jobs":
                from .automl import jobs

                return self._json({"jobs": jobs()})
            if path == "/3/Frames":
                return self._json({"frames": [
                    _frame_schema(k, f) for k, f in FRAMES.items()]})
            if path.startswith("/3/Frames/"):
                rest = path[len("/3/Frames/"):]
                key, _, verb = rest.partition("/")
                if key not in FRAMES:
                    return self._error(404, f"frame '{key}' not found")
                fr = FRAMES[key]
                if verb == "summary":
                    return self._json({"frame_id": {"name": key},
                                       "summary": fr.summary()})
                return self._json(_frame_schema(key, fr))
            if path == "/3/Models":
                return self._json({"models": [
                    {"model_id": {"name": k}, "algo": m.algo}
                    for k, m in MODELS.items()]})
            if path.startswith("/3/Models/"):
                key = path[len("/3/Models/"):]
                if key not in MODELS:
                    return self._error(404, f"model '{key}' not found")
                m = MODELS[key]
                return self._json({"model_id": {"name": key},
                                   "algo": m.algo,
                                   "nclasses": m.nclasses})
            return self._error(404, f"no route for GET {path}")
        except Exception as e:       # noqa: BLE001
            traceback.print_exc()
            return self._error(500, repr(e))

    def do_POST(self):
        try:
            path = urllib.parse.urlparse(self.path).path.rstrip("/")
            params = self._params()
            if path == "/3/ImportFiles" or path == "/3/Parse":
                from .frame import import_file

                src = params.get("path") or params.get("source_frames")
                if isinstance(src, (list, tuple)):
                    if not src:
                        return self._error(400, "missing 'path'")
                    if len(src) != 1:   # refuse, don't silently truncate
                        return self._error(
                            400, "multi-file Parse is not supported over "
                            "REST; pass one path (globs allowed)")
                    src = src[0]
                if not src or not isinstance(src, str):
                    return self._error(400, "missing 'path'")
                key = params.get("destination_frame") or \
                    src.rsplit("/", 1)[-1]
                FRAMES[key] = import_file(src)
                return self._json(_frame_schema(key, FRAMES[key]))
            if path.startswith("/3/ModelBuilders/"):
                algo = path[len("/3/ModelBuilders/"):]
                if algo not in _ALGOS:
                    return self._error(404, f"unknown algo '{algo}'")
                return self._build_model(algo, params)
            if path.startswith("/3/Predictions/models/"):
                rest = path[len("/3/Predictions/models/"):]
                mkey, _, fpart = rest.partition("/frames/")
                if mkey not in MODELS:
                    return self._error(404, f"model '{mkey}' not found")
                if fpart not in FRAMES:
                    return self._error(404, f"frame '{fpart}' not found")
                pred = MODELS[mkey].predict(FRAMES[fpart])
                key = f"prediction_{mkey}_{fpart}"
                FRAMES[key] = pred
                return self._json({"predictions_frame": {"name": key},
                                   **_frame_schema(key, pred)})
            return self._error(404, f"no route for POST {path}")
        except Exception as e:       # noqa: BLE001
            traceback.print_exc()
            return self._error(500, repr(e))

    def _build_model(self, algo: str, params: dict):
        from .automl import Job

        training = params.pop("training_frame", None)
        if training not in FRAMES:
            return self._error(404, f"frame '{training}' not found")
        y = params.pop("response_column", params.pop("y", None))
        sync_timeout = float(params.pop("_sync_timeout", 600))
        model_id = params.pop("model_id", None)
        if not model_id:
            with _ID_LOCK:                 # ThreadingHTTPServer: no races
                global _MODEL_SEQ
                _MODEL_SEQ += 1
                model_id = f"{algo}_{_MODEL_SEQ}"
        ignored = params.pop("ignored_columns", None)
        # remaining params go to the estimator; numbers arrive as strings
        # from form encoding — coerce the obvious ones
        kw = {}
        for k, v in params.items():
            if isinstance(v, str):
                try:
                    v = json.loads(v)      # "50" -> 50, "true" -> True
                except (ValueError, TypeError):
                    pass
            kw[k] = v
        job = Job(dest=model_id,
                  description=f"{algo} on {training}").start()

        def run():
            try:
                est = _algo_estimator(algo)(**kw)
                if y is not None:
                    model = est.train(y=y, training_frame=FRAMES[training],
                                      ignored_columns=ignored)
                else:
                    model = est.train(training_frame=FRAMES[training],
                                      ignored_columns=ignored)
                MODELS[model_id] = model
                job.done()
            except Exception as e:     # noqa: BLE001
                job.failed(repr(e))

        t = threading.Thread(target=run, daemon=True)
        t.start()
        t.join(timeout=sync_timeout)
        return self._json({"job": {"dest": {"name": model_id},
                                   "status": job.status,
                                   "msg": job.msg}})


def start_server(port: int = 54321, host: str = "127.0.0.1",
                 background: bool = True) -> ThreadingHTTPServer:
    """Start the REST server (:54321 is the reference's default port)."""
    srv = ThreadingHTTPServer((host, port), _Handler)
    if background:
        t = threading.Thread(target=srv.serve_forever,
                             name="h2o-tpu-rest", daemon=True)
        t.start()
    else:
        srv.serve_forever()
    return srv


if __name__ == "__main__":
    import sys

    start_server(int(sys.argv[1]) if len(sys.argv) > 1 else 54321,
                 background=False)
