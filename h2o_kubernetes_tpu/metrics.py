"""Model metrics — the analog of hex.ModelMetrics* in the reference
(h2o-core hex/ModelMetricsBinomial, ModelMetricsRegression etc.,
SURVEY.md §2b C9/C18): AUC, logloss, RMSE/MAE, confusion-style accuracy.

All metrics are jittable jnp code; callers may pass device or host
arrays. Distributed callers gather first (metrics are O(n) scalar
reductions — cheap next to training).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


_AUC_BINS = 4096        # reference AUC2 uses 400 bins; 4096 is ~free here
_AUC_EXACT_MAX = 65536  # above this, the histogram path takes over


@functools.partial(jax.jit, static_argnums=(3,))
def _pad_jit(y, s, wt, pad):
    # jitted (NOT eager) because the inputs are often committed
    # multi-device arrays — eager sharded ops are the XLA:CPU
    # rendezvous-flake pattern purged from the training paths
    return (jnp.concatenate([y, jnp.zeros(pad, y.dtype)]),
            jnp.concatenate([s, jnp.zeros(pad, s.dtype)]),
            jnp.concatenate([wt, jnp.zeros(pad, wt.dtype)]))


def _pad_pow2(y, s, wt):
    """Pad metric inputs to the next power of two with w=0 rows.

    Every distinct holdout length would otherwise compile a fresh XLA
    executable for the sort/histogram jits — grids, CV, and AutoML
    score hundreds of slightly-different-sized frames, and per-shape
    compiles dominated the CPU test-suite wall clock. All metric jits
    ignore w=0 rows, so bucketing shapes is free (the tiny pad program
    still compiles per shape, but in milliseconds, not seconds).
    """
    n = y.shape[0]
    m = 1 << max(n - 1, 1).bit_length()
    if m == n:
        return y, s, wt
    return _pad_jit(y, s, wt, m - n)


def roc_auc(y_true, score, w=None, exact: bool | None = None) -> float:
    """AUC with average-rank tie handling (Mann-Whitney U).

    Two paths, both jitted:
    - exact: full sort — O(n log n), used for n <= 65536 (or exact=True);
    - histogram: scores binned into 4096 equal-width bins, in-bin pairs
      tied at 0.5 — the reference's own design (hex/AUC2 computes AUC
      from a 400-bin score histogram [U3]), error bounded by in-bin pair
      mass (~1e-4 here). The binning rides ops/histogram's MXU kernel,
      replacing a ~0.5 s 1M-row device sort with one histogram pass.

    Optionally weighted; rows with w == 0 (e.g. shard padding) are
    excluded entirely, so callers can pass padded device arrays without
    a host-side mask round trip.
    """
    y = jnp.asarray(y_true).astype(jnp.float32).ravel()
    s = jnp.asarray(score).astype(jnp.float32).ravel()
    wt = jnp.ones_like(y) if w is None else \
        jnp.asarray(w).astype(jnp.float32).ravel()
    if exact is None:
        exact = y.shape[0] <= _AUC_EXACT_MAX
    y, s, wt = _pad_pow2(y, s, wt)
    if exact:
        return float(_auc_impl(y, s, wt))
    return float(_auc_hist_impl(y, s, wt))


@jax.jit
def _score_hist(y, s, wt):
    """Shared score-binning pass: [NB, 2] (pos, neg) mass per bin +
    (smin, smax, bad). `bad` flags NaN on a live row — callers must
    surface it as NaN metrics, not plausible numbers.

    NaN scores are parked at 0 with the NaN→bad flag set (nan_to_num
    would also finitize ±inf); ±inf live scores (diverged model) must
    not set the bin scale — they'd collapse every finite score into
    bin 0 — so the finite range is binned and infinities pin to the
    end bins (= the exact-path rank)."""
    from .ops.histogram import build_histogram

    live = wt > 0
    bad = jnp.any(live & (jnp.isnan(y) | jnp.isnan(s)))
    y = jnp.where(live, jnp.nan_to_num(y), 0.0)
    sx = jnp.where(live & ~jnp.isnan(s), s, 0.0)
    fin = live & jnp.isfinite(sx)
    smin = jnp.min(jnp.where(fin, sx, jnp.inf))
    smax = jnp.max(jnp.where(fin, sx, -jnp.inf))
    scale = (_AUC_BINS - 1) / jnp.maximum(smax - smin, 1e-30)
    idx = jnp.clip((sx - smin) * scale, 0, _AUC_BINS - 1).astype(jnp.int32)
    idx = jnp.where(sx == jnp.inf, _AUC_BINS - 1, idx)
    idx = jnp.where(sx == -jnp.inf, 0, idx)
    rel = jnp.where(live, 0, -1).astype(jnp.int32)
    # per-bin (Σ y·w, Σ (1-y)·w, Σ w) in one kernel pass
    hist = build_histogram(idx[:, None], rel, y, 1.0 - y, wt,
                           1, _AUC_BINS)[0, 0]
    return hist[:, :2], smin, smax, bad


@jax.jit
def _auc_hist_impl(y, s, wt):
    hist, _, _, bad = _score_hist(y, s, wt)
    posb, negb = hist[:, 0], hist[:, 1]
    below = jnp.cumsum(negb) - negb
    P, N = jnp.sum(posb), jnp.sum(negb)
    auc = jnp.sum(posb * (below + 0.5 * negb)) / (P * N)
    return jnp.where(bad, jnp.nan, auc)


@jax.jit
def _auc_impl(y, s, wt):
    # one compiled program: eagerly this is ~15 dispatches, which costs
    # seconds per first call when the chip sits behind a network tunnel
    live = wt > 0
    # NaN on a LIVE row (diverged model, NA leak) must surface as NaN
    # AUC, not be silently ranked at score 0
    bad = jnp.any(live & (jnp.isnan(y) | jnp.isnan(s)))
    wt = jnp.where(live, wt, 0.0)
    y = jnp.where(live, jnp.nan_to_num(y), 0.0)
    s = jnp.where(live, jnp.nan_to_num(s), jnp.inf)  # dead rows sort last
    order = jnp.argsort(s)
    ss, ys, ws = s[order], y[order], wt[order]
    negw = ws * (1.0 - ys)
    posw = ws * ys
    cneg = jnp.cumsum(negw)                          # inclusive
    lo = jnp.searchsorted(ss, ss, side="left")
    hi = jnp.searchsorted(ss, ss, side="right")
    below = jnp.where(lo > 0, cneg[jnp.maximum(lo - 1, 0)], 0.0)
    tied = cneg[hi - 1] - below
    auc = jnp.sum(posw * (below + 0.5 * tied)) / \
        (jnp.sum(posw) * jnp.sum(negw))
    return jnp.where(bad, jnp.nan, auc)


def binomial_stats(y_true, p1, w=None) -> dict:
    """Threshold-derived binomial metrics from one score histogram —
    the reference's ModelMetricsBinomial/AUC2 surface [U3]: pr_auc,
    Gini, max-F1 (+ its threshold), max-accuracy, mean_per_class_error
    at the F1-optimal threshold, and the confusion counts there.

    One device histogram pass (4096 bins of p1 with pos/neg mass), then
    host-side cumulative sweeps over bin-edge thresholds — exactly how
    hex/AUC2 computes its threshold tables from 400 bins.
    """
    y = jnp.asarray(y_true).astype(jnp.float32).ravel()
    s = jnp.asarray(p1).astype(jnp.float32).ravel()
    wt = jnp.ones_like(y) if w is None else \
        jnp.asarray(w).astype(jnp.float32).ravel()
    y, s, wt = _pad_pow2(y, s, wt)
    hist, smin, smax, bad = (np.asarray(a) for a in _score_hist(y, s, wt))
    if bool(bad):
        # NaN on a live row: every derived metric is NaN, same as
        # roc_auc — finite-looking stats would mask a diverged model
        nan = float("nan")
        return {k: nan for k in
                ("auc", "gini", "pr_auc", "f1", "max_f1_threshold",
                 "accuracy", "mean_per_class_error")} | {
                "confusion": np.full((2, 2), nan)}
    pos, neg = hist[:, 0].astype(np.float64), hist[:, 1].astype(
        np.float64)
    P, N = pos.sum(), neg.sum()
    if P == 0 or N == 0:
        raise ValueError("binomial metrics need both classes present")
    # threshold k: predict positive when the score bin >= k
    tp = np.cumsum(pos[::-1])[::-1]
    fp = np.cumsum(neg[::-1])[::-1]
    fn = P - tp
    tn = N - fp
    with np.errstate(divide="ignore", invalid="ignore"):
        precision = np.where(tp + fp > 0, tp / (tp + fp), 1.0)
        recall = tp / P
        f1 = np.where(precision + recall > 0,
                      2 * precision * recall / (precision + recall), 0.0)
    acc = (tp + tn) / (P + N)
    k_f1 = int(np.argmax(f1))
    span = max(float(smax) - float(smin), 1e-30)
    thr = float(smin) + k_f1 * span / (_AUC_BINS - 1)
    # PR AUC: trapezoid over (recall, precision) with the conventional
    # (0, 1) endpoint appended (an "above max score" threshold) — the
    # same convention sklearn's precision_recall_curve uses
    r_ext = np.append(recall, 0.0)
    p_ext = np.append(precision, 1.0)
    order = np.argsort(r_ext)
    r_s, p_s = r_ext[order], p_ext[order]
    pr_auc = float(np.trapezoid(p_s, r_s)) if hasattr(np, "trapezoid") \
        else float(np.trapz(p_s, r_s))
    auc = float(_auc_from_hist(pos, neg))
    return {
        "auc": auc,
        "gini": 2 * auc - 1,
        "pr_auc": pr_auc,
        "f1": float(f1[k_f1]),
        "max_f1_threshold": thr,
        "accuracy": float(acc.max()),
        "mean_per_class_error": float(
            0.5 * (fn[k_f1] / P + fp[k_f1] / N)),
        "confusion": np.array([[tn[k_f1], fp[k_f1]],
                               [fn[k_f1], tp[k_f1]]]),
    }


def _auc_from_hist(pos, neg):
    below = np.cumsum(neg) - neg
    return (pos * (below + 0.5 * neg)).sum() / (pos.sum() * neg.sum())


def confusion_matrix(y_true, p1, threshold: float | None = None,
                     w=None) -> np.ndarray:
    """2x2 [[TN, FP], [FN, TP]] (rows actual, cols predicted) at the
    given threshold — F1-optimal when None, like the reference."""
    if threshold is None:
        return binomial_stats(y_true, p1, w=w)["confusion"]
    y = np.asarray(y_true).ravel()
    p = np.asarray(p1).ravel()
    wt = np.ones_like(p) if w is None else np.asarray(w).ravel()
    pred = p >= threshold
    pos = y > 0
    tp = float(wt[pred & pos].sum())
    fp = float(wt[pred & ~pos].sum())
    fn = float(wt[~pred & pos].sum())
    tn = float(wt[~pred & ~pos].sum())
    return np.array([[tn, fp], [fn, tp]])


def logloss(y_true, p, eps: float = 1e-7, w=None) -> float:
    y = jnp.asarray(y_true).astype(jnp.float32).ravel()
    p = jnp.asarray(p).astype(jnp.float32).ravel()
    if w is None:
        return float(_logloss_unw(y, p, eps))
    return float(_logloss_w(y, p, jnp.asarray(w).astype(
        jnp.float32).ravel(), eps))


@functools.partial(jax.jit, static_argnums=(2,))
def _logloss_unw(y, p, eps):
    # eps must stay f32-representable: with 1e-15, 1-eps rounds to 1.0
    # and the (1-y)*log1p(-1) term produces 0*inf = NaN
    p = jnp.clip(p, eps, 1 - eps)
    return -jnp.mean(y * jnp.log(p) + (1 - y) * jnp.log1p(-p))


@functools.partial(jax.jit, static_argnums=(3,))
def _logloss_w(y, p, wt, eps):
    p = jnp.clip(p, eps, 1 - eps)
    bad = jnp.any((wt > 0) & jnp.isnan(y))     # NaN on live rows surfaces
    y = jnp.where(wt > 0, jnp.nan_to_num(y), 0.0)
    ll = y * jnp.log(p) + (1 - y) * jnp.log1p(-p)
    out = -jnp.sum(wt * jnp.where(wt > 0, ll, 0.0)) / jnp.sum(wt)
    return jnp.where(bad, jnp.nan, out)


def multinomial_logloss(y_true, probs, eps: float = 1e-7, w=None) -> float:
    """y_true: int class ids [n]; probs: [n, K]."""
    yraw = jnp.asarray(y_true).astype(jnp.float32).ravel()
    y = jnp.nan_to_num(yraw).astype(jnp.int32)
    p = jnp.clip(jnp.asarray(probs), eps, 1.0)
    ll = jnp.log(p[jnp.arange(y.shape[0]), y])
    if w is None:
        return float(-jnp.mean(ll))
    wt = jnp.asarray(w).astype(jnp.float32).ravel()
    bad = jnp.any((wt > 0) & jnp.isnan(yraw))
    out = -jnp.sum(wt * jnp.where(wt > 0, ll, 0.0)) / jnp.sum(wt)
    return float(jnp.where(bad, jnp.nan, out))


def rmse(y_true, pred, w=None) -> float:
    y = jnp.asarray(y_true).astype(jnp.float32).ravel()
    p = jnp.asarray(pred).astype(jnp.float32).ravel()
    if w is None:
        return float(_rmse_unw(y, p))
    return float(_rmse_w(y, p,
                         jnp.asarray(w).astype(jnp.float32).ravel()))


@jax.jit
def _rmse_unw(y, p):
    return jnp.sqrt(jnp.mean((y - p) ** 2))


@jax.jit
def _rmse_w(y, p, wt):
    bad = jnp.any((wt > 0) & jnp.isnan(y - p))
    se = jnp.where(wt > 0, jnp.nan_to_num(y - p) ** 2, 0.0)
    out = jnp.sqrt(jnp.sum(wt * se) / jnp.sum(wt))
    return jnp.where(bad, jnp.nan, out)


def mae(y_true, pred) -> float:
    y = jnp.asarray(y_true).astype(jnp.float32).ravel()
    p = jnp.asarray(pred).astype(jnp.float32).ravel()
    return float(jnp.mean(jnp.abs(y - p)))


def mean_residual_deviance(y_true, pred, distribution: str = "gaussian") -> float:
    y = jnp.asarray(y_true).astype(jnp.float32).ravel()
    p = jnp.asarray(pred).astype(jnp.float32).ravel()
    if distribution == "gaussian":
        return float(jnp.mean((y - p) ** 2))
    if distribution == "poisson":
        p = jnp.clip(p, 1e-10, None)
        yl = jnp.where(y > 0, y * jnp.log(y / p), 0.0)
        return float(2.0 * jnp.mean(yl - (y - p)))
    raise ValueError(distribution)


def accuracy(y_true, label) -> float:
    y = np.asarray(y_true).ravel()
    l = np.asarray(label).ravel()
    return float((y == l).mean())


def ndcg(y_true, score, group, k: int = 10) -> float:
    """Mean NDCG@k over query groups (learning-to-rank metric).

    Analog of the reference XGBoost extension's ranking eval
    (h2o-extensions/xgboost eval_metric=ndcg, SURVEY.md §2b C14).
    y_true: graded relevance per row; group: query id per row.
    """
    y = np.asarray(y_true).ravel().astype(np.float64)
    s = np.asarray(score).ravel().astype(np.float64)
    g = np.asarray(group).ravel()
    # one argsort by group, then contiguous slices — O(n log n), not O(n·G)
    order = np.argsort(g, kind="stable")
    y, s, g = y[order], s[order], g[order]
    _, starts = np.unique(g, return_index=True)
    bounds = np.append(starts, len(g))
    total, n = 0.0, 0
    for a, b in zip(bounds[:-1], bounds[1:]):
        yy, ss = y[a:b], s[a:b]
        kk = min(k, b - a)
        disc = 1.0 / np.log2(np.arange(2, kk + 2))
        top = np.argsort(-ss, kind="stable")[:kk]
        dcg = ((2.0 ** yy[top] - 1.0) * disc).sum()
        ideal = np.sort(2.0 ** yy - 1.0)[::-1]
        idcg = (ideal[:kk] * disc).sum()
        if idcg > 0:
            total += dcg / idcg
            n += 1
    return total / max(n, 1)


def r2(y_true, pred) -> float:
    y = jnp.asarray(y_true).astype(jnp.float32).ravel()
    p = jnp.asarray(pred).astype(jnp.float32).ravel()
    ss_res = jnp.sum((y - p) ** 2)
    ss_tot = jnp.sum((y - jnp.mean(y)) ** 2)
    return float(1.0 - ss_res / ss_tot)
