"""Grid search — the H2OGridSearch analog.

Reference: h2o-py/h2o/grid/grid_search.py + hex/grid/GridSearch.java
(SURVEY.md §2b C16/C19): a hyper-parameter grid over ONE estimator
class, walked either exhaustively ("Cartesian") or by random draws
("RandomDiscrete" with max_models / max_runtime_secs / seed), each
model trained with the shared train() arguments, ranked on a metric.

The TPU build runs models sequentially on the host loop — each train()
is already a fused device program, and H2O's grid is likewise a serial
builder queue per priority level. Models are ranked exactly like the
AutoML Leaderboard (auc desc / logloss, rmse asc).
"""

from __future__ import annotations

import itertools
import time
from typing import Any, Sequence

import numpy as np

from .automl import _DESC, Job, Leaderboard
from .frame import Frame

__all__ = ["GridSearch", "H2OGridSearch"]


class GridSearch:
    """Hyper-parameter search over one estimator class.

    `model` is an estimator class (GBM, GLM, ...) or an instance whose
    constructor params become the grid's fixed base params.
    `hyper_params` maps param name -> list of candidate values.
    `search_criteria`: {"strategy": "Cartesian"} (default) or
    {"strategy": "RandomDiscrete", "max_models": N,
     "max_runtime_secs": S, "seed": K}.
    """

    def __init__(self, model, hyper_params: dict[str, Sequence[Any]],
                 grid_id: str | None = None,
                 search_criteria: dict[str, Any] | None = None):
        if not hyper_params:
            raise ValueError("hyper_params must name at least one "
                             "parameter to search")
        if isinstance(model, type):
            self.model_cls = model
            self.base_params: dict[str, Any] = {}
        else:
            self.model_cls = type(model)
            # reconstruct constructor kwargs from the instance's params
            # dataclass (estimators store them on .params) AND its CV
            # settings (popped into .cv_args at construction — dropping
            # them would silently train grid models without the
            # requested cross-validation)
            p = getattr(model, "params", None)
            self.base_params = {
                k: v for k, v in vars(p).items()
                if not k.startswith("_")} if p is not None else {}
            cv = getattr(model, "cv_args", None)
            if cv is not None:
                self.base_params.update(
                    {k: v for k, v in vars(cv).items()
                     if not k.startswith("_")})
        self.hyper_params = {k: list(v) for k, v in hyper_params.items()}
        crit = dict(search_criteria or {})
        self.strategy = crit.pop("strategy", "Cartesian")
        if self.strategy not in ("Cartesian", "RandomDiscrete"):
            raise ValueError(f"unknown strategy '{self.strategy}'")
        self.max_models = crit.pop("max_models", 0)
        self.max_runtime_secs = crit.pop("max_runtime_secs", 0)
        self.seed = crit.pop("seed", 0)
        crit.pop("stopping_rounds", None)       # accepted, not used
        crit.pop("stopping_tolerance", None)
        crit.pop("stopping_metric", None)
        if crit:
            raise ValueError(f"unknown search_criteria {sorted(crit)}")
        self.grid_id = grid_id or f"Grid_{self.model_cls.__name__}"
        self.models: list[Any] = []
        self.model_ids: list[str] = []
        self.failed_params: list[dict[str, Any]] = []
        self.leaderboard: Leaderboard | None = None
        self.job: Job | None = None

    # -- combination generators ---------------------------------------------
    def _cartesian(self):
        names = sorted(self.hyper_params)
        for combo in itertools.product(
                *(self.hyper_params[n] for n in names)):
            yield dict(zip(names, combo))

    def _random(self):
        rng = np.random.default_rng(self.seed)
        names = sorted(self.hyper_params)
        seen: set[tuple] = set()
        total = 1
        for n in names:
            total *= len(self.hyper_params[n])
        while len(seen) < total:
            combo = tuple(
                rng.integers(0, len(self.hyper_params[n])) for n in names)
            if combo in seen:
                continue
            seen.add(combo)
            yield {n: self.hyper_params[n][i]
                   for n, i in zip(names, combo)}

    def train(self, y: str, training_frame: Frame,
              x: Sequence[str] | None = None,
              validation_frame: Frame | None = None,
              **train_kw) -> "GridSearch":
        t0 = time.monotonic()
        deadline = t0 + self.max_runtime_secs if self.max_runtime_secs \
            else None
        yv = training_frame.vec(y) if y in training_frame.names else None
        nclasses = yv.cardinality() if yv is not None and yv.is_enum() \
            else 1
        if nclasses == 2:
            metric, asc = "auc", False
        elif nclasses > 2:
            metric, asc = "logloss", True
        else:
            metric, asc = "rmse", True
        self.sort_metric = metric
        self.leaderboard = Leaderboard(metric, asc)
        self.job = Job(dest=self.grid_id,
                       description=f"grid {self.model_cls.__name__}")
        self.job.start()           # registers itself in automl.JOBS

        combos = self._cartesian() if self.strategy == "Cartesian" \
            else self._random()
        built = attempt = 0
        try:
            for hp in combos:
                # H2O's max_models bounds BUILT models, not attempts —
                # a failed combo doesn't eat the budget (generators are
                # finite, so all-failing grids still terminate)
                if self.max_models and built >= self.max_models:
                    break
                if deadline is not None and time.monotonic() > deadline:
                    break
                attempt += 1
                params = {**self.base_params, **hp}
                model_id = f"{self.grid_id}_model_{attempt}"
                call_kw = dict(train_kw)
                if x is not None:
                    call_kw["x"] = x
                if validation_frame is not None:
                    call_kw["validation_frame"] = validation_frame
                try:
                    est = self.model_cls(**params)
                    model = est.train(y=y, training_frame=training_frame,
                                      **call_kw)
                    if validation_frame is not None:
                        metrics = model.model_performance(
                            validation_frame, y)
                    elif getattr(model, "cv", None) is not None:
                        metrics = model.cv.metrics
                    else:
                        metrics = model.model_performance(
                            training_frame, y)
                except Exception as e:  # noqa: BLE001 - grid keeps going
                    self.failed_params.append({**hp, "error": repr(e)})
                    continue
                model.grid_params = dict(hp)
                self.leaderboard.add(model_id, model, metrics)
                built += 1
                self.job.update(
                    min(0.99, built / max(self.max_models or 20, 1)))
        except BaseException as e:
            self.job.failed(repr(e))
            raise
        # expose models sorted by the grid metric (H2O sorts get_grid
        # output; .models follows the sorted order for convenience)
        rows = self.leaderboard.as_list()
        self.model_ids = [r["model_id"] for r in rows]
        self.models = [self.leaderboard.models[i] for i in self.model_ids]
        self.job.done()
        return self

    # -- h2o-py surface ------------------------------------------------------
    def get_grid(self, sort_by: str | None = None,
                 decreasing: bool | None = None) -> list[dict[str, Any]]:
        """Ranked [{model_id, <metrics>, <hyper params>}] rows."""
        if self.leaderboard is None:
            raise ValueError("grid has not been trained")
        rows = [dict(r) for r in self.leaderboard.as_list()]
        for r in rows:
            m = self.leaderboard.models[r["model_id"]]
            r.update(getattr(m, "grid_params", {}))
        if sort_by:
            if decreasing is None:
                decreasing = sort_by in _DESC
            rows.sort(key=lambda r: r.get(sort_by, float("inf")),
                      reverse=bool(decreasing))
        return rows

    @property
    def leader(self):
        if not self.models:
            raise ValueError("grid has no successful models")
        return self.models[0]

    def __repr__(self):
        done = len(self.model_ids)
        return (f"GridSearch({self.model_cls.__name__}, {done} models, "
                f"{len(self.failed_params)} failed)")


H2OGridSearch = GridSearch
