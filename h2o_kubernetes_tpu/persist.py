"""Persistence — model/frame save & load (local filesystem).

Reference: water/persist/* (SURVEY.md §2b C20) provides binary model
save/load and frame export over pluggable backends (local/S3/HDFS/GCS);
h2o.save_model / h2o.load_model / h2o.export_file are the client verbs
(h2o-py). Built-in backends: local FS, mem:// (in-process object
store), read-only http(s)://, and the cloud stores s3:// gs:// hdfs://
(persist_cloud.py — stdlib REST clients, no SDK required); more can
register via PERSIST_SCHEMES (the reference's PersistManager registry).

Device arrays are converted to host numpy on save (a model file is
readable on any backend — the reference's binary models are likewise
cluster-independent), and flow back to device lazily on first use.
"""

from __future__ import annotations

import io
import json
import os
import pickle
from typing import Any, Callable

import numpy as np

__all__ = ["save_model", "load_model", "export_file", "save_frame",
           "load_frame", "PERSIST_SCHEMES", "read_bytes", "write_bytes",
           "write_bytes_atomic", "list_names", "is_remote", "join_path"]

_MAGIC = b"H2OTPU1\n"

# scheme -> (reader: path->bytes, writer: path,bytes->None) — the
# PersistManager registry (water/persist/PersistManager [U3]). Built-ins:
# bare paths (local FS), mem:// (in-process object store — the DKV-style
# scratch space), http(s):// (read-only remote fetch, the analog of the
# reference's PersistHTTP importFiles path). S3/GCS/HDFS register here
# the same way when their client libraries are present.
PERSIST_SCHEMES: dict[str, tuple[Callable, Callable]] = {}

_MEM_STORE: dict[str, bytes] = {}


def _mem_read(path: str) -> bytes:
    if path not in _MEM_STORE:
        raise FileNotFoundError(path)
    return _MEM_STORE[path]


def _mem_write(path: str, data: bytes) -> None:
    _MEM_STORE[path] = data


def _http_read(path: str) -> bytes:
    # one transient classifier for every persist HTTP verb: retries
    # 429/5xx (honoring Retry-After)/timeouts/resets/truncation, maps
    # 404 on this read to FileNotFoundError, fires the persist.http
    # fault point
    from .persist_cloud import _http

    return _http("GET", path)


def _http_write(path: str, data: bytes) -> None:
    raise ValueError("http(s):// is a read-only persist backend")


PERSIST_SCHEMES["mem"] = (_mem_read, _mem_write)
PERSIST_SCHEMES["http"] = (_http_read, _http_write)
PERSIST_SCHEMES["https"] = (_http_read, _http_write)

# cloud backends (s3/gs/hdfs) — stdlib REST clients, no SDK needed
from . import persist_cloud as _persist_cloud  # noqa: E402

_persist_cloud.register(PERSIST_SCHEMES)


def _write_bytes(path: str, data: bytes) -> None:
    scheme = path.split("://", 1)[0] if "://" in path else ""
    if scheme:
        if scheme not in PERSIST_SCHEMES:
            raise ValueError(f"no persist backend for scheme "
                             f"'{scheme}://' (register in PERSIST_SCHEMES)")
        PERSIST_SCHEMES[scheme][1](path, data)
        return
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        f.write(data)


def _read_bytes(path: str) -> bytes:
    scheme = path.split("://", 1)[0] if "://" in path else ""
    if scheme:
        if scheme not in PERSIST_SCHEMES:
            raise ValueError(f"no persist backend for scheme "
                             f"'{scheme}://'")
        return PERSIST_SCHEMES[scheme][0](path)
    with open(path, "rb") as f:
        return f.read()


# public raw-bytes surface so other subsystems (AutoML checkpoints,
# REST export) stay backend-agnostic without reaching into privates
read_bytes = _read_bytes
write_bytes = _write_bytes


def write_bytes_atomic(path: str, data: bytes,
                       verify: bool = True) -> None:
    """Crash-safe write: readers see the OLD bytes or the NEW bytes,
    never a torn prefix.

    Local FS: write-temp in the same directory + fsync + os.replace
    (the rename is atomic on POSIX), so a process killed mid-write can
    never leave a half-written file at `path` — the durable PoolStore
    and the registry index both depend on this (a corrupted index
    would break every subsequent fetch). Scheme backends (mem://,
    s3://...) already replace whole objects, so they take the plain
    write. ``verify`` reads the bytes back and compares digests — a
    cheap end-to-end check that the backend stored what it was given.
    """
    import hashlib

    if "://" in path:
        _write_bytes(path, data)
    else:
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = os.path.join(d, f".{os.path.basename(path)}."
                              f"{os.getpid()}.tmp")
        try:
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                try:
                    os.remove(tmp)
                except OSError:
                    pass
    if verify:
        got = _read_bytes(path)
        if hashlib.sha256(got).digest() != \
                hashlib.sha256(data).digest():
            raise IOError(
                f"atomic write to {path} did not read back intact "
                f"({len(got)} bytes back vs {len(data)} written)")


def list_names(base: str) -> list[str]:
    """Child object names directly under a local dir or a mem://
    prefix (the two backends the durable PoolStore supports); other
    schemes have no cheap listing and return []. Missing dir = []."""
    if not is_remote(base):
        try:
            return sorted(
                n for n in os.listdir(base)
                if os.path.isfile(os.path.join(base, n)))
        except (FileNotFoundError, NotADirectoryError):
            return []
    if base.startswith("mem://"):
        prefix = base.rstrip("/") + "/"
        out = set()
        for key in list(_MEM_STORE):
            if key.startswith(prefix):
                rest = key[len(prefix):]
                if rest and "/" not in rest:
                    out.add(rest)
        return sorted(out)
    return []


def is_remote(path: str) -> bool:
    """True when `path` routes through a PERSIST_SCHEMES backend."""
    return "://" in path


def join_path(base: str, name: str) -> str:
    """Join a child name onto a local dir or a scheme://-addressed one."""
    if is_remote(base):
        return base.rstrip("/") + "/" + name
    return os.path.join(base, name)


class _HostPickler(pickle.Pickler):
    """Pickler that lands every jax.Array as host numpy."""

    def persistent_id(self, obj):
        import jax

        if isinstance(obj, jax.Array):
            return ("jax_array", np.asarray(obj))
        return None


# modules a model file may legitimately reference: this package, numpy
# internals, and stdlib builders of plain containers. Everything else —
# os, subprocess, builtins beyond the basics — is refused, so a
# tampered model file cannot execute arbitrary code via a crafted
# GLOBAL opcode (the classic pickle RCE).
_SAFE_MODULE_PREFIXES = ("h2o_kubernetes_tpu.",)
_SAFE_GLOBALS = {
    ("builtins", "dict"), ("builtins", "list"), ("builtins", "tuple"),
    ("builtins", "set"), ("builtins", "frozenset"), ("builtins", "int"),
    ("builtins", "float"), ("builtins", "str"), ("builtins", "bytes"),
    ("builtins", "bool"), ("builtins", "complex"), ("builtins", "slice"),
    ("builtins", "bytearray"),
    ("collections", "OrderedDict"), ("collections", "defaultdict"),
    ("numpy", "ndarray"), ("numpy", "dtype"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "scalar"),
    ("numpy.core.multiarray", "scalar"),
    ("numpy._core.numeric", "_frombuffer"),
    ("numpy.core.numeric", "_frombuffer"),
    ("_codecs", "encode"),
}


class _HostUnpickler(pickle.Unpickler):
    def persistent_load(self, pid):
        tag, val = pid
        if tag == "jax_array":
            return val          # numpy; flows back to device on first use
        raise pickle.UnpicklingError(f"unknown persistent id {tag!r}")

    def find_class(self, module, name):
        if (module, name) in _SAFE_GLOBALS:
            return super().find_class(module, name)
        if module.startswith(_SAFE_MODULE_PREFIXES):
            obj = super().find_class(module, name)
            # CLASSES defined in this package only: a bare module-prefix
            # rule would also hand back re-exported imports (os, json)
            # and package-level functions callable with attacker args
            if isinstance(obj, type) and getattr(
                    obj, "__module__", "").startswith(
                    _SAFE_MODULE_PREFIXES):
                return obj
        raise pickle.UnpicklingError(
            f"model file references {module}.{name}, which is outside "
            "the allowed model-class set — refusing to load (possible "
            "tampering; use MOJO artifacts for untrusted scoring)")


def save_model(model, path: str, force: bool = True) -> str:
    """h2o.save_model analog: binary model file at `path`.

    If `path` has no extension it is treated as a directory and the
    file is named <algo>.model inside it (h2o-py's directory behavior).
    """
    if not force and os.path.exists(path):
        raise FileExistsError(path)
    if "://" not in path and not os.path.splitext(path)[1]:
        path = os.path.join(path, f"{model.algo}.model")
    buf = io.BytesIO()
    buf.write(_MAGIC)
    _HostPickler(buf, protocol=pickle.HIGHEST_PROTOCOL).dump(model)
    _write_bytes(path, buf.getvalue())
    return path


def load_model(path: str):
    """h2o.load_model analog.

    Trust model: binary model files are pickle-based (like the
    reference's binary models, they are for same-owner save/restore
    only), but the loader REFUSES any class outside this package /
    numpy container internals (`_HostUnpickler.find_class`), so a
    tampered file cannot reach os/subprocess/arbitrary constructors.
    Defense in depth, not a sandbox — for artifacts that must cross a
    real trust boundary use the MOJO path (mojo.py), whose npz+JSON
    format is data-only.
    """
    data = _read_bytes(path)
    if not data.startswith(_MAGIC):
        raise ValueError(f"{path} is not an h2o_kubernetes_tpu model file")
    model = _HostUnpickler(io.BytesIO(data[len(_MAGIC):])).load()
    trees = getattr(model, "trees", None)
    if trees is not None and getattr(trees, "cover", 1) is None:
        # model was saved before Tree grew the cover field (r2): backfill
        # a sentinel so predict/varimp work; predict_contributions
        # detects the all-NaN cover and asks for a re-train
        model.trees = trees._replace(
            cover=np.full_like(np.asarray(trees.value), np.nan))
    return model


def export_file(frame, path: str, header: bool = True,
                sep: str = ",") -> str:
    """h2o.export_file analog: write a Frame as CSV (local or scheme)."""
    from .frame.frame import NA_ENUM

    cols = []
    for name in frame.names:
        v = frame.vec(name)
        if v.is_enum():
            codes = v.to_numpy()
            dom = np.array(list(v.domain) + [""], dtype=object)
            col = dom[np.where(codes < 0, len(dom) - 1, codes)]
        elif v.kind == "time":
            ms = v.to_numpy()
            col = np.array(
                [np.datetime64(int(m), "ms").astype(str) if m == m else ""
                 for m in ms], dtype=object)
        else:
            x = v.to_numpy()
            col = np.where(np.isnan(x), "",
                           np.char.mod("%g", np.nan_to_num(x)))
        cols.append(col.astype(object))
    out = io.StringIO()
    if header:
        out.write(sep.join(frame.names) + "\n")
    quoted = []
    for c in cols:
        # RFC 4180: embedded quotes double inside a quoted field
        q = np.array(
            [f'"{str(s).replace(chr(34), chr(34) * 2)}"'
             if (sep in str(s) or '"' in str(s) or "\n" in str(s))
             else str(s) for s in c], dtype=object)
        quoted.append(q)
    for i in range(frame.nrows):
        out.write(sep.join(str(q[i]) for q in quoted) + "\n")
    _write_bytes(path, out.getvalue().encode())
    return path


def save_frame(frame, path: str) -> str:
    """Binary frame save (npz of columns + metadata) — the analog of the
    reference's distributed frame snapshot in the persist layer."""
    arrays: dict[str, np.ndarray] = {}
    meta: dict[str, Any] = {"names": frame.names, "kinds": {},
                            "domains": {}, "origins": {}}
    for name in frame.names:
        v = frame.vec(name)
        arrays[f"col_{name}"] = v.to_numpy()
        meta["kinds"][name] = v.kind
        if v.domain is not None:
            meta["domains"][name] = list(v.domain)
        if v.kind == "time":
            meta["origins"][name] = v.origin
    buf = io.BytesIO()
    # JSON, not pickle: frame files stay data-only so load_frame is safe
    # on untrusted input (matching the reference's data-only formats)
    meta_bytes = json.dumps(meta).encode()
    np.savez_compressed(buf, __meta__=np.frombuffer(
        meta_bytes, dtype=np.uint8), **arrays)
    _write_bytes(path, buf.getvalue())
    return path


def load_frame(path: str):
    from .frame import Frame, Vec

    with np.load(io.BytesIO(_read_bytes(path)), allow_pickle=False) as z:
        try:
            meta = json.loads(z["__meta__"].tobytes().decode())
        except (UnicodeDecodeError, json.JSONDecodeError):
            raise ValueError(
                f"{path}: frame metadata is not JSON — this looks like a "
                f"frame saved by a pre-0.2 build (pickle metadata); "
                f"re-export it with export_file/save_frame") from None
        vecs = {}
        for name in meta["names"]:
            arr = z[f"col_{name}"]
            kind = meta["kinds"][name]
            if kind == "time":
                # to_numpy returned absolute epoch-ms float64
                vecs[name] = Vec.from_numpy(arr, name, kind="time")
            elif kind == "enum":
                vecs[name] = Vec.from_numpy(
                    arr.astype(np.int32), name,
                    domain=meta["domains"][name], kind="enum")
            else:
                vecs[name] = Vec.from_numpy(arr, name)
    return Frame(vecs)
