from .frame import Frame, Vec, NA_ENUM

__all__ = ["Frame", "Vec", "NA_ENUM"]
