from .frame import Frame, Vec, NA_ENUM
from .parse import import_file, parse_setup

__all__ = ["Frame", "Vec", "NA_ENUM", "import_file", "parse_setup"]
