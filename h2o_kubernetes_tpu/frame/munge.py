"""Group-by aggregation and joins — the Rapids munging surface.

Reference: H2O's Rapids `GroupBy` / `merge` ASTs (water/rapids/ast/
prims/mungers: AstGroup, AstMerge [U3]) exposed through h2o-py's
`H2OFrame.group_by(...)` builder and `h2o.merge`.

TPU-first design:
- group_by is ONE MRTask `doall` over the mesh: each shard segment-sums
  its rows into a dense [G] accumulator per statistic (G = product of
  key cardinalities, static at trace time), then the accumulators psum /
  pmin / pmax across the ROWS axis — the same shape as the reference's
  per-node NewChunk accumulation + reduce, with XLA segment_sum standing
  in for the per-row Java loop.
- merge is a host-side reshard (like select_rows): keys re-encode to a
  shared vocabulary, matches resolve by sort+searchsorted, and both
  sides gather into fresh sharded columns. Joins reorder rows
  arbitrarily, so they are ingest-shaped work, not collective work.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

# module-level jit: a fresh wrapper per column would retrace per call
_jit_nanquantile = jax.jit(jnp.nanquantile)

from ..runtime.mrtask import doall
from .frame import NA_ENUM, Frame, Vec

_STATS = ("sum", "mean", "min", "max", "sd", "var", "count", "nrow")


class GroupBy:
    """Builder collecting aggregate specs, h2o-py style:

        g = fr.group_by("c1").sum("x").mean(["x", "y"]).count()
        out = g.get_frame()

    Aggregate columns are named `<stat>_<col>` (count → `nrow`).
    """

    def __init__(self, frame: Frame, by):
        self._fr = frame
        self._by = [by] if isinstance(by, str) else list(by)
        for k in self._by:
            if k not in frame:
                raise KeyError(f"group_by key '{k}' not in frame")
        self._aggs: list[tuple[str, str]] = []   # (stat, col)

    def _add(self, stat: str, cols) -> "GroupBy":
        if cols is None:
            raise ValueError(f"{stat}() needs a column name")
        for c in ([cols] if isinstance(cols, str) else cols):
            if c not in self._fr:
                raise KeyError(f"column '{c}' not in frame")
            if self._fr.vec(c).is_enum():
                raise ValueError(f"cannot aggregate enum column '{c}'")
            self._aggs.append((stat, c))
        return self

    def sum(self, col=None): return self._add("sum", col)
    def mean(self, col=None): return self._add("mean", col)
    def min(self, col=None): return self._add("min", col)
    def max(self, col=None): return self._add("max", col)
    def sd(self, col=None): return self._add("sd", col)
    def var(self, col=None): return self._add("var", col)

    def count(self) -> "GroupBy":
        self._aggs.append(("nrow", ""))
        return self

    @property
    def frame(self) -> Frame:
        return self.get_frame()

    def get_frame(self) -> Frame:
        fr = self._fr
        # mixed-radix composite key over the (factorized) key columns;
        # one extra bucket per key for NA groups (h2o keeps NA groups)
        key_vecs = [fr.vec(k) if fr.vec(k).is_enum() else
                    fr.vec(k).asfactor() for k in self._by]
        cards = [len(v.domain) + 1 for v in key_vecs]
        G = int(np.prod(cards))
        combined = jnp.zeros(key_vecs[0].padded_len, dtype=jnp.int32)
        for v, card in zip(key_vecs, cards):
            code = jnp.where(v.data == NA_ENUM, card - 1, v.data)
            combined = combined * card + code
        # pad rows route to an overflow bucket G, sliced off post-reduce
        n = fr.nrows
        idx = jnp.arange(key_vecs[0].padded_len)
        valid = idx < n
        combined = jnp.where(valid, combined, G)

        agg_cols = sorted({c for _, c in self._aggs if c})
        arrays = [fr.vec(c).as_float() for c in agg_cols]

        def m(codes, valid_f, *cols):
            out = {"nrow": jnp.zeros(G)}
            out["nrow"] = _seg(valid_f.astype(jnp.float32), codes, G)
            for name, x in zip(agg_cols, cols):
                ok = (~jnp.isnan(x)) & (codes < G)
                xz = jnp.where(ok, x, 0.0)
                okf = ok.astype(jnp.float32)
                out[f"cnt_{name}"] = _seg(okf, codes, G)
                out[f"sum_{name}"] = _seg(xz, codes, G)
                out[f"ssq_{name}"] = _seg(xz * x_safe(x), codes, G)
                out[f"min_{name}"] = _segmin(x, codes, G, ok)
                out[f"max_{name}"] = _segmax(x, codes, G, ok)
            return out

        def x_safe(x):
            return jnp.where(jnp.isnan(x), 0.0, x)

        reds = {"nrow": "sum"}
        for c in agg_cols:
            reds.update({f"cnt_{c}": "sum", f"sum_{c}": "sum",
                         f"ssq_{c}": "sum", f"min_{c}": "min",
                         f"max_{c}": "max"})
        acc = doall(m, combined, valid.astype(jnp.float32), *arrays,
                    reduce=reds)
        acc = {k: np.asarray(v) for k, v in acc.items()}

        live = np.flatnonzero(acc["nrow"] > 0)       # groups present
        # decode composite ids back into per-key label columns
        out_cols: dict[str, np.ndarray] = {}
        rem = live.copy()
        for k, v, card in zip(reversed(self._by), reversed(key_vecs),
                              reversed(cards)):
            code = rem % card
            rem = rem // card
            out_cols[k] = np.where(code == card - 1, NA_ENUM,
                                   code).astype(np.int32)
        vecs: dict[str, Vec] = {}
        for k, v in zip(self._by, key_vecs):
            kv = Vec.from_numpy(out_cols[k], k, domain=v.domain)
            if not self._fr.vec(k).is_enum():
                # numeric key was factorized only for segmenting — give
                # it back as numbers (h2o GroupBy keeps key types)
                kv = kv.asnumeric()
            vecs[k] = kv
        result = Frame(vecs)

        for stat, c in self._aggs:
            if stat == "nrow":
                result["nrow"] = Vec.from_numpy(
                    acc["nrow"][live].astype(np.float32), "nrow")
                continue
            cnt = acc[f"cnt_{c}"][live]
            s = acc[f"sum_{c}"][live]
            with np.errstate(invalid="ignore", divide="ignore"):
                if stat == "sum":
                    col = s
                elif stat == "mean":
                    col = np.where(cnt > 0, s / cnt, np.nan)
                elif stat in ("sd", "var"):
                    mean = np.where(cnt > 0, s / cnt, np.nan)
                    var = acc[f"ssq_{c}"][live] / cnt - mean * mean
                    var = np.where(cnt > 1, var * cnt / (cnt - 1), np.nan)
                    col = np.sqrt(np.maximum(var, 0)) if stat == "sd" \
                        else np.maximum(var, 0)
                else:                                 # min / max
                    col = acc[f"{stat}_{c}"][live]
                    col = np.where(cnt > 0, col, np.nan)
            result[f"{stat}_{c}"] = Vec.from_numpy(
                col.astype(np.float32), f"{stat}_{c}")
        return result.sort(self._by)


def _seg(vals, codes, G):
    import jax
    return jax.ops.segment_sum(vals, codes, num_segments=G + 1)[:G]


def _segmin(x, codes, G, ok):
    import jax
    v = jnp.where(ok, x, jnp.inf)
    out = jax.ops.segment_min(v, codes, num_segments=G + 1)[:G]
    return jnp.where(jnp.isfinite(out), out, jnp.inf)


def _segmax(x, codes, G, ok):
    import jax
    v = jnp.where(ok, x, -jnp.inf)
    out = jax.ops.segment_max(v, codes, num_segments=G + 1)[:G]
    return jnp.where(jnp.isfinite(out), out, -jnp.inf)


# -- merge -------------------------------------------------------------------

def _key_codes(vl: Vec, vr: Vec) -> tuple[np.ndarray, np.ndarray, int]:
    """Encode one key column from both frames against a shared vocab.

    Returns (left_codes, right_codes, cardinality) with NA → card-1
    (its own value: h2o merge matches NA to NA).
    """
    if vl.is_enum() != vr.is_enum():
        raise ValueError(f"merge key '{vl.name}': enum vs numeric")
    if vl.is_enum():
        dom = sorted(set(vl.domain or []) | set(vr.domain or []))
        pos = {d: i for i, d in enumerate(dom)}

        def enc(v):
            lut = np.array([pos[d] for d in (v.domain or [])] + [len(dom)],
                           dtype=np.int64)
            c = v.to_numpy().astype(np.int64)
            return lut[np.where(c < 0, len(lut) - 1, c)]

        return enc(vl), enc(vr), len(dom) + 1
    a, b = vl.to_numpy().astype(np.float64), vr.to_numpy().astype(np.float64)
    vals = np.unique(np.concatenate([a[~np.isnan(a)], b[~np.isnan(b)]]))

    def enc(x):
        c = np.searchsorted(vals, x)
        return np.where(np.isnan(x), len(vals), c).astype(np.int64)

    return enc(a), enc(b), len(vals) + 1


def merge(left: Frame, right: Frame, by=None, all_x: bool = False) -> Frame:
    """Inner (or left, when all_x) join on shared key columns."""
    if by is None:
        by = [c for c in left.names if c in right.names]
    by = [by] if isinstance(by, str) else list(by)
    if not by:
        raise ValueError("merge: no common key columns")

    lk = np.zeros(left.nrows, dtype=np.int64)
    rk = np.zeros(right.nrows, dtype=np.int64)
    for k in by:
        cl, cr, card = _key_codes(left.vec(k), right.vec(k))
        lk = lk * card + cl
        rk = rk * card + cr

    order = np.argsort(rk, kind="stable")
    rs = rk[order]
    lo = np.searchsorted(rs, lk, side="left")
    hi = np.searchsorted(rs, lk, side="right")
    cnt = hi - lo
    if all_x:
        cnt = np.maximum(cnt, 1)             # unmatched left rows survive
    li = np.repeat(np.arange(left.nrows), cnt)
    # right row index per output row; -1 marks an unmatched left join
    # row. Vectorized expansion: out row j of left row i maps to sorted
    # right position lo[i] + (j - start[i]) — no per-row Python loop
    total = int(cnt.sum())
    pos = np.cumsum(cnt) - cnt                    # output start per row
    offset = np.arange(total) - np.repeat(pos, cnt)
    src = np.repeat(lo, cnt) + offset
    matched_row = np.repeat(hi > lo, cnt)
    ri = np.where(matched_row, order[np.minimum(src, len(order) - 1)
                                     ] if len(order) else -1, -1)

    out = left.select_rows(li)
    for name in right.names:
        if name in by:
            continue
        v = right.vec(name)
        a = v.to_numpy()
        if v.is_enum():
            col = np.where(ri >= 0, a[np.maximum(ri, 0)], NA_ENUM)
            nv = Vec.from_numpy(col.astype(np.int32), name, domain=v.domain)
        else:
            col = np.where(ri >= 0, a[np.maximum(ri, 0)], np.nan)
            nv = Vec.from_numpy(col, name, kind=v.kind)
        n = name
        while n in out:
            n += "0"                          # cbind-style dedup suffix
        out[n] = nv
    return out


# -- impute / table / quantile / unique --------------------------------------
# h2o-py surface: h2o.frame.H2OFrame.impute / .table / .quantile /
# .unique (water/rapids AstImpute, AstTable, AstQtile, AstUnique [U3]).

def impute(frame: Frame, column: str, method: str = "mean",
           by=None) -> float | str:
    """Fill NAs in `column` in place; returns the fill value used
    (or the per-group fill vector's mean when `by` is given).

    method: mean | median (numeric) | mode (enum). `by` (mean only):
    group-wise fill from the group means, NA groups fall back to the
    global mean — one segment-sum doall, reference AstImpute semantics.
    """
    v = frame.vec(column)
    if method not in ("mean", "median", "mode"):
        raise ValueError(f"unknown impute method '{method}'")
    if v.is_enum():
        if method != "mode":
            raise ValueError(f"impute '{column}': categorical columns "
                             "impute with method='mode'")
        codes = v.to_numpy()
        counts = np.bincount(codes[codes >= 0],
                             minlength=v.cardinality())
        fill = int(np.argmax(counts))
        out = np.where(codes < 0, fill, codes).astype(np.int32)
        frame[column] = Vec.from_numpy(out, column, domain=v.domain)
        return (v.domain or [])[fill]
    x = v.to_numpy()
    if by is not None:
        if method != "mean":
            raise ValueError("grouped impute supports method='mean'")
        by = [by] if isinstance(by, str) else list(by)
        if len(by) != 1:
            raise ValueError("impute by= takes one grouping column")
        g = frame.vec(by[0])
        if not g.is_enum():
            raise ValueError(f"impute by='{by[0]}': must be categorical")
        G = g.cardinality()
        codes = g.to_numpy().astype(np.int64)
        ok = ~np.isnan(x) & (codes >= 0)
        s = np.bincount(codes[ok], weights=x[ok], minlength=G)
        c = np.bincount(codes[ok], minlength=G)
        gmean = np.divide(s, c, out=np.full(G, np.nan), where=c > 0)
        glob = float(np.nanmean(x)) if np.any(~np.isnan(x)) else 0.0
        gmean = np.where(np.isnan(gmean), glob, gmean)
        fill_vec = np.where(codes >= 0, gmean[np.maximum(codes, 0)],
                            glob)
        out = np.where(np.isnan(x), fill_vec, x)
        # kind= keeps time columns time-typed (origin-relative f32
        # storage; a bare from_numpy would flatten them to numeric and
        # round full epoch magnitudes into f32)
        frame[column] = Vec.from_numpy(out, column, kind=v.kind)
        return float(np.mean(gmean))
    if method == "mean":
        fill = float(np.nanmean(x)) if np.any(~np.isnan(x)) else 0.0
    else:
        fill = float(np.nanmedian(x)) if np.any(~np.isnan(x)) else 0.0
    out = np.where(np.isnan(x), fill, x)
    frame[column] = Vec.from_numpy(out, column, kind=v.kind)
    return fill


def table(frame: Frame, col: str, col2: str | None = None) -> Frame:
    """Frequency table of one or two categorical columns → Frame with
    the level column(s) + 'Count' (NA rows excluded, zero rows kept
    out, h2o table semantics)."""
    v1 = frame.vec(col)
    if not v1.is_enum():
        raise ValueError(f"table: '{col}' must be categorical")
    c1 = v1.to_numpy().astype(np.int64)
    d1 = list(v1.domain or [])
    if col2 is None:
        cnt = np.bincount(c1[c1 >= 0], minlength=len(d1))
        keep = cnt > 0
        lv = np.flatnonzero(keep)
        out = Frame()
        out[col] = Vec.from_numpy(lv.astype(np.int32), col, domain=d1)
        out["Count"] = Vec.from_numpy(cnt[keep].astype(np.float32),
                                      "Count")
        return out
    v2 = frame.vec(col2)
    if not v2.is_enum():
        raise ValueError(f"table: '{col2}' must be categorical")
    c2 = v2.to_numpy().astype(np.int64)
    d2 = list(v2.domain or [])
    ok = (c1 >= 0) & (c2 >= 0)
    flat = c1[ok] * len(d2) + c2[ok]
    cnt = np.bincount(flat, minlength=len(d1) * len(d2))
    keep = cnt > 0
    lv = np.flatnonzero(keep)
    out = Frame()
    out[col] = Vec.from_numpy((lv // len(d2)).astype(np.int32), col,
                              domain=d1)
    out[col2] = Vec.from_numpy((lv % len(d2)).astype(np.int32), col2,
                               domain=d2)
    out["Count"] = Vec.from_numpy(cnt[keep].astype(np.float32), "Count")
    return out


def quantile(frame: Frame, prob: Sequence[float] = (
        0.001, 0.01, 0.1, 0.25, 0.333, 0.5, 0.667, 0.75, 0.9, 0.99,
        0.999)) -> Frame:
    """Per-numeric-column quantiles (device nanquantile, one sort per
    column) → Frame with 'Probs' + one column per numeric input."""
    import jax

    probs = np.asarray(list(prob), dtype=np.float32)
    if probs.size == 0 or np.any((probs < 0) | (probs > 1)):
        raise ValueError("quantile probs must be in [0, 1]")
    out = Frame()
    out["Probs"] = Vec.from_numpy(probs, "Probs")
    qs = jnp.asarray(probs)
    for name in frame.names:
        v = frame.vec(name)
        if v.is_enum():
            continue
        col = _jit_nanquantile(v.as_float()[: len(v)], qs)
        out[name] = Vec.from_numpy(
            np.asarray(col).astype(np.float32), name)
    if out.ncols == 1:
        raise ValueError("quantile: frame has no numeric columns")
    return out


def unique(vec: Vec) -> Frame:
    """Distinct non-NA values of one column as a single-column Frame."""
    if vec.is_enum():
        codes = vec.to_numpy()
        lv = np.unique(codes[codes >= 0]).astype(np.int32)
        out = Frame()
        out[vec.name or "C1"] = Vec.from_numpy(lv, vec.name,
                                               domain=vec.domain)
        return out
    x = vec.to_numpy()
    vals = np.unique(x[~np.isnan(x)]).astype(np.float32)
    out = Frame()
    out[vec.name or "C1"] = Vec.from_numpy(vals, vec.name)
    return out
