"""Frame: distributed columnar table with HBM-resident sharded columns.

The reference's Fluid-Vector store (water/fvec: Frame → Vec → Chunk,
SURVEY.md §2b C5) keeps each column as a chain of compressed Chunks spread
over the node ring via the DKV. The TPU-native design collapses all of
that: a column IS one `jax.Array`, row-sharded over the mesh ROWS axis.
There is no chunk zoo — XLA memory layouts replace per-chunk compression —
and no DKV — addressing is the NamedSharding.

Column kinds (mirroring H2O Vec types):
  numeric — float32, NA = NaN
  int     — float32 storage too (H2O stores ints in compressed chunks but
            exposes doubles at the API; we keep one numeric device dtype)
  enum    — int32 category codes + host-side `domain` (vocab), NA = -1
  time    — float64 epoch-millis, NA = NaN
  string  — host-resident list (no device array; used for vocab building)

Rows are padded to a multiple of the ROWS-axis size; padding is encoded as
NA so NA-aware reductions ignore it. `nrows` is the logical row count.

Rollups (lazy cached per-Vec min/max/mean/σ/NA-count — the analog of
water/fvec/RollupStats.java, SURVEY.md §2b C6) are computed by one MRTask
`doall` on first access and invalidated on mutation.
"""

from __future__ import annotations

import os
from typing import Any, Iterable, Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..runtime import mesh as meshlib
from ..runtime.mrtask import doall, shard_rows

NA_ENUM = -1  # NA/pad sentinel for enum codes


# jitted row gather for Vec.select_rows: an eager fancy-index on a
# committed multi-device array is the XLA:CPU rendezvous flake pattern;
# pad rows resolve to the NA sentinel so they behave like shard_rows pads
_gather_rows_jit = jax.jit(
    lambda data, idx, valid, na: jnp.where(valid, data[idx], na))


def _device_gather_min() -> int:
    """Row threshold for the on-device select_rows gather (below it
    the host path wins — the jitted gather traces once per result
    shape, and CV fold slices on toy frames would pay a compile each).
    H2O_TPU_DEVICE_GATHER_MIN overrides (tests force 0)."""
    try:
        return int(os.environ.get("H2O_TPU_DEVICE_GATHER_MIN", "65536"))
    except ValueError:
        return 65536


def _rollup_map(x):
    """Per-shard rollup stats (module-level so doall can cache the
    jitted callable across Vecs — CV fold frames re-derive rollups)."""
    ok = ~jnp.isnan(x)
    xz = jnp.where(ok, x, 0.0)
    return dict(
        cnt=jnp.sum(ok, dtype=jnp.float32),
        sum=jnp.sum(xz, dtype=jnp.float32),
        sumsq=jnp.sum(xz * xz),
        min=jnp.min(jnp.where(ok, x, jnp.inf)),
        max=jnp.max(jnp.where(ok, x, -jnp.inf)),
        zeros=jnp.sum(ok & (x == 0.0), dtype=jnp.float32),
    )


class Vec:
    """One column: a row-sharded device array plus host-side metadata."""

    def __init__(self, data: jax.Array, nrows: int, kind: str = "numeric",
                 domain: list[str] | None = None, name: str = "",
                 origin: float = 0.0):
        self.data = data          # padded, sharded over ROWS
        self.nrows = nrows
        self.kind = kind          # numeric | enum | time
        self.domain = domain
        self.name = name
        # time columns store float32 millis RELATIVE to `origin` (a float64
        # epoch-ms) — at absolute 2026 epoch magnitudes a float32 ulp is
        # ~131s, so the shift is what keeps timestamps exact.
        self.origin = origin
        self._rollups: dict[str, float] | None = None

    # -- construction -------------------------------------------------------

    @staticmethod
    def from_numpy(x: np.ndarray, name: str = "", domain=None,
                   kind: str | None = None) -> "Vec":
        x = np.asarray(x)
        if kind is None:
            if domain is not None:
                kind = "enum"
            elif x.dtype.kind == "M":
                kind = "time"
            else:
                kind = "numeric"
        origin = 0.0
        if kind == "enum":
            if x.dtype.kind == "f":  # pre-encoded float codes: NaN is NA
                x = np.where(np.isnan(x), NA_ENUM, x)
            arr = x.astype(np.int32)
            data = shard_rows(arr, pad_value=NA_ENUM)
        elif kind == "time":
            if x.dtype.kind == "M":
                ms = x.astype("datetime64[ms]").astype(np.float64)
                ms[np.isnat(x)] = np.nan  # NaT would otherwise become 2^63-
            else:
                ms = x.astype(np.float64)
            origin = float(np.nanmin(ms)) if len(ms) else 0.0
            arr = (ms - origin).astype(np.float32)
            data = shard_rows(arr, pad_value=np.nan)
        else:
            arr = x.astype(np.float32)
            data = shard_rows(arr, pad_value=np.nan)
        return Vec(data, nrows=len(x), kind=kind, domain=domain, name=name,
                   origin=origin)

    # -- basics -------------------------------------------------------------

    def __len__(self) -> int:
        return self.nrows

    @property
    def padded_len(self) -> int:
        return self.data.shape[0]

    def is_enum(self) -> bool:
        return self.kind == "enum"

    def cardinality(self) -> int:
        return len(self.domain) if self.domain is not None else -1

    def as_float(self) -> jax.Array:
        """Device column as float32 with NA→NaN (pads included as NaN).

        Time columns come back as ABSOLUTE epoch-ms (origin added, f32
        rounded — fine for binning/modeling; use to_numpy()/rollups()
        for exact timestamps).
        """
        if self.kind == "enum":
            d = self.data
            return jnp.where(d == NA_ENUM, jnp.nan, d.astype(jnp.float32))
        if self.kind == "time":
            return (self.data + np.float32(self.origin)).astype(jnp.float32)
        return self.data.astype(jnp.float32)

    def to_numpy(self) -> np.ndarray:
        a = np.asarray(self.data)[: self.nrows]
        if self.kind == "time":
            return a.astype(np.float64) + self.origin
        return a

    # -- rollups ------------------------------------------------------------

    def _compute_rollups(self) -> dict[str, float]:
        if self.nrows == 0:
            return dict(min=float("nan"), max=float("nan"),
                        mean=float("nan"), sigma=0.0, nacnt=0, zeros=0,
                        rows=0)
        if self.kind == "time":
            col = self.data  # origin-relative: full precision; shift below
        elif self.kind == "enum":
            col = self.as_float()
        else:
            col = self.data.astype(jnp.float32)

        r = doall(_rollup_map, col,
                  reduce=dict(cnt="sum", sum="sum", sumsq="sum",
                              min="min", max="max", zeros="sum"),
                  cache_key="vec_rollups")
        r = {k: float(v) for k, v in r.items()}
        n = r["cnt"]
        mean = r["sum"] / n if n > 0 else float("nan")
        var = r["sumsq"] / n - mean * mean if n > 1 else 0.0
        sigma = float(np.sqrt(max(var * n / (n - 1), 0.0))) if n > 1 else 0.0
        shift = self.origin if (self.kind == "time" and n) else 0.0
        return dict(  # time stats shift back to absolute epoch-ms;
            min=(r["min"] + shift) if n else float("nan"),  # sigma invariant
            max=(r["max"] + shift) if n else float("nan"),
            mean=mean + shift, sigma=sigma,
            nacnt=int(self.nrows - n), zeros=int(r["zeros"]), rows=int(n),
        )

    def rollups(self) -> dict[str, float]:
        if self._rollups is None:
            self._rollups = self._compute_rollups()
        return self._rollups

    def invalidate(self) -> None:
        self._rollups = None

    def min(self): return self.rollups()["min"]
    def max(self): return self.rollups()["max"]
    def mean(self): return self.rollups()["mean"]
    def sigma(self): return self.rollups()["sigma"]
    def nacnt(self): return self.rollups()["nacnt"]

    # -- row/type ops --------------------------------------------------------

    def select_rows(self, idx: np.ndarray) -> "Vec":
        """New Vec of rows at `idx` — gathered ON DEVICE.

        The round-5 path round-tripped the whole column through the
        host per selection (one fetch + re-shard per fold slice for
        sliced CV). Now the gather is a jitted `jnp.take` inside the
        source sharding followed by ONE reshard (device-to-device
        `device_put`); the host only ever holds the index vector.
        Values pass through bit-exactly (time columns keep their
        origin, so the stored f32 offsets are untouched). CV and
        similar row-masked training paths should still prefer weight
        masks, which skip even the reshard (see models/cv.py).
        """
        idx = np.asarray(idx)
        if idx.dtype == bool:
            idx = np.flatnonzero(idx)
        elif not np.issubdtype(idx.dtype, np.integer):
            # match numpy fancy-index semantics: float indices are an
            # error, not a silent truncation
            raise IndexError(
                f"select_rows: indices must be integers or booleans, "
                f"got {idx.dtype}")
        idx = idx.astype(np.int64)
        n = len(idx)
        # normalize negative indices and bounds-check like numpy (the
        # device gather clamps silently, which would corrupt selections)
        idx = np.where(idx < 0, idx + self.nrows, idx)
        if n and (idx.min() < 0 or idx.max() >= self.nrows):
            raise IndexError(
                f"select_rows: index out of range for {self.nrows} rows")
        mesh = meshlib.global_mesh()
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(mesh, P(meshlib.ROWS))
        if n == 0 or n < _device_gather_min() \
                or not sharding.is_fully_addressable:
            # small selections and multi-host (DCN) meshes take the
            # host path: device_put cannot target other processes'
            # devices, and below the threshold the jitted gather's
            # trace cost (one per result shape — CV fold sizes vary)
            # outweighs the host round trip it removes
            a = np.asarray(self.data)[: self.nrows][idx]
            if self.kind == "time":
                return Vec.from_numpy(a.astype(np.float64) + self.origin,
                                      self.name, kind="time")
            return Vec.from_numpy(a, self.name, domain=self.domain,
                                  kind=self.kind)
        shards = mesh.shape[meshlib.ROWS]
        m = ((n + shards - 1) // shards) * shards
        na = NA_ENUM if self.kind == "enum" else np.nan
        idx_p = np.zeros(m, dtype=np.int32)
        idx_p[:n] = idx
        valid = np.zeros(m, dtype=bool)
        valid[:n] = True
        out = _gather_rows_jit(self.data, jnp.asarray(idx_p),
                               jnp.asarray(valid),
                               jnp.asarray(na, dtype=self.data.dtype))
        out = jax.device_put(out, sharding)      # the ONE reshard
        return Vec(out, nrows=n, kind=self.kind, domain=self.domain,
                   name=self.name, origin=self.origin)

    def asfactor(self) -> "Vec":
        """Numeric → enum, domain = sorted distinct values (h2o asfactor)."""
        if self.is_enum():
            return self
        a = self.to_numpy()
        ok = ~np.isnan(a)
        vals = np.unique(a[ok])
        domain = [_num_str(v) for v in vals]
        codes = np.full(len(a), NA_ENUM, dtype=np.int32)
        codes[ok] = np.searchsorted(vals, a[ok]).astype(np.int32)
        return Vec.from_numpy(codes, self.name, domain=domain)

    def asnumeric(self) -> "Vec":
        """Enum → numeric: parse domain labels as numbers where possible,
        else fall back to the codes (h2o asnumeric semantics)."""
        if not self.is_enum():
            return self
        a = self.to_numpy()
        if not self.domain:  # all-NA enum column
            return Vec.from_numpy(np.full(len(a), np.nan, np.float32),
                                  self.name)
        try:
            lut = np.array([float(d) for d in self.domain], dtype=np.float32)
        except ValueError:
            lut = np.arange(len(self.domain), dtype=np.float32)
        out = np.where(a >= 0, lut[np.maximum(a, 0)], np.nan)
        return Vec.from_numpy(out.astype(np.float32), self.name)

    # -- elementwise algebra (the Rapids expression surface) -----------------
    # Reference: H2O's Rapids AST ops (water/rapids/ast/prims/math,
    # operators [U3]) exposed through h2o-py Frame/Vec operators. Here an
    # expression is just jnp math on the padded sharded column — XLA fuses
    # chains of these into one kernel; NA (NaN) propagates; pads stay NaN
    # so downstream filters/rollups ignore them.

    def _operand(self, other, op: str = "arithmetic") -> jax.Array | float:
        if isinstance(other, Vec):
            if other.nrows != self.nrows:
                raise ValueError("Vec length mismatch "
                                 f"({other.nrows} vs {self.nrows})")
            if other.is_enum():
                raise TypeError(
                    f"{op} is not applicable to enum column "
                    f"'{other.name}' (use asnumeric() first)")
            return other.as_float()
        if isinstance(other, (bool, int, float, np.floating, np.integer)):
            return float(other)
        raise TypeError(f"cannot combine Vec with {type(other).__name__}")

    def _arith(self, other, fn, name="") -> "Vec":
        if self.is_enum():
            # h2o-py raises for math on factors; as_float() would expose
            # the CODES and silently compute nonsense
            raise TypeError(f"arithmetic is not applicable to enum column "
                            f"'{self.name}' (use asnumeric() first)")
        out = fn(self.as_float(), self._operand(other))
        return Vec(out.astype(jnp.float32), self.nrows, name=name or
                   self.name)

    def __add__(self, o): return self._arith(o, jnp.add)
    def __radd__(self, o): return self._arith(o, lambda a, b: b + a)
    def __sub__(self, o): return self._arith(o, jnp.subtract)
    def __rsub__(self, o): return self._arith(o, lambda a, b: b - a)
    def __mul__(self, o): return self._arith(o, jnp.multiply)
    def __rmul__(self, o): return self._arith(o, lambda a, b: b * a)
    def __truediv__(self, o): return self._arith(o, jnp.divide)
    def __rtruediv__(self, o): return self._arith(o, lambda a, b: b / a)
    def __pow__(self, o): return self._arith(o, jnp.power)
    def __mod__(self, o): return self._arith(o, jnp.mod)
    def __floordiv__(self, o): return self._arith(o, jnp.floor_divide)
    def __neg__(self): return self._arith(0.0, lambda a, _: -a)

    def _cmp(self, other, fn) -> "Vec":
        if isinstance(other, str):
            # enum == "label": compare codes against the domain index
            # (h2o-py `fr["c"] == "cat"`); unknown label matches nothing
            if not self.is_enum():
                raise TypeError(
                    f"'{self.name}': string comparison needs an enum column")
            code = (self.domain or []).index(other) \
                if other in (self.domain or []) else -2
            a = self.data.astype(jnp.float32)
            a = jnp.where(self.data == NA_ENUM, jnp.nan, a)
            b = float(code)
        else:
            if self.is_enum():
                raise TypeError(
                    f"numeric comparison is not applicable to enum column "
                    f"'{self.name}' (compare against a level string)")
            a, b = self.as_float(), self._operand(other, "comparison")
        res = fn(a, b).astype(jnp.float32)
        bad = jnp.isnan(a) | jnp.isnan(jnp.asarray(b, dtype=jnp.float32))
        out = jnp.where(bad, jnp.nan, res)   # NA compares to NA (h2o)
        return Vec(out, self.nrows, name=self.name)

    def __lt__(self, o): return self._cmp(o, jnp.less)
    def __le__(self, o): return self._cmp(o, jnp.less_equal)
    def __gt__(self, o): return self._cmp(o, jnp.greater)
    def __ge__(self, o): return self._cmp(o, jnp.greater_equal)
    def __eq__(self, o): return self._cmp(o, jnp.equal)       # noqa: E731
    def __ne__(self, o): return self._cmp(o, jnp.not_equal)   # noqa: E731
    __hash__ = None  # mirrors h2o-py: Vecs are expressions, not dict keys

    def _bool(self) -> jax.Array:
        """Truth mask with NA→False (filter semantics)."""
        a = self.as_float()
        return jnp.where(jnp.isnan(a), 0.0, a) != 0.0

    def __and__(self, o):
        if not isinstance(o, Vec):
            raise TypeError("& needs two Vecs")
        out = (self._bool() & o._bool()).astype(jnp.float32)
        return Vec(out, self.nrows, name=self.name)

    def __or__(self, o):
        if not isinstance(o, Vec):
            raise TypeError("| needs two Vecs")
        out = (self._bool() | o._bool()).astype(jnp.float32)
        return Vec(out, self.nrows, name=self.name)

    def __invert__(self):
        return Vec((~self._bool()).astype(jnp.float32), self.nrows,
                   name=self.name)

    def _math(self, fn) -> "Vec":
        if self.is_enum():
            raise TypeError(f"math is not applicable to enum column "
                            f"'{self.name}' (use asnumeric() first)")
        return Vec(fn(self.as_float()).astype(jnp.float32), self.nrows,
                   name=self.name)

    def log(self): return self._math(jnp.log)
    def log1p(self): return self._math(jnp.log1p)
    def exp(self): return self._math(jnp.exp)
    def sqrt(self): return self._math(jnp.sqrt)
    def abs(self): return self._math(jnp.abs)
    def floor(self): return self._math(jnp.floor)
    def ceil(self): return self._math(jnp.ceil)
    def sign(self): return self._math(jnp.sign)

    def unique(self):
        """Distinct non-NA values as a 1-column Frame (h2o unique)."""
        from .munge import unique as _unique
        return _unique(self)

    def isna(self) -> "Vec":
        """1.0 where the value is NA (h2o isna — NA itself maps to 1)."""
        if self.kind == "enum":
            out = (self.data == NA_ENUM).astype(jnp.float32)
        else:
            out = jnp.isnan(self.data).astype(jnp.float32)
        # re-mark pad rows as NaN so they never count as real NA rows
        idx = jnp.arange(self.padded_len)
        out = jnp.where(idx < self.nrows, out, jnp.nan)
        return Vec(out, self.nrows, name=self.name)


def _num_str(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else str(v)


class Frame:
    """An ordered collection of equal-length Vecs (row-aligned shards)."""

    def __init__(self, vecs: Mapping[str, Vec] | None = None):
        self._vecs: dict[str, Vec] = dict(vecs or {})
        ns = {v.nrows for v in self._vecs.values()}
        if len(ns) > 1:
            raise ValueError(f"ragged columns: nrows {ns}")
        # binned-matrix cache (Frame.binned / binning.fused_fit_bins):
        # {key: uint8 device array | (BinSpec, uint8 device array)}
        self._binned_cache: dict = {}
        # content version for the fused-binning fit keys: edges are a
        # pure function of (columns, names, n_bins), so a cache entry is
        # valid exactly while the version holds (binning.fused_fit_bins)
        self._version: int = 0

    # -- construction -------------------------------------------------------

    @staticmethod
    def from_arrays(cols: Mapping[str, Any],
                    domains: Mapping[str, list[str]] | None = None) -> "Frame":
        """Build from {name: array-like}. Object/str columns become enums."""
        domains = dict(domains or {})
        vecs: dict[str, Vec] = {}
        for name, col in cols.items():
            arr = np.asarray(col)
            if name in domains:
                if arr.dtype.kind in "OUS":  # encode against given domain
                    codes, _ = _factorize(arr, domain=domains[name])
                else:
                    codes = arr
                vecs[name] = Vec.from_numpy(codes, name, domain=domains[name])
            elif arr.dtype.kind in "OUS":  # strings -> enum with built vocab
                codes, domain = _factorize(arr)
                vecs[name] = Vec.from_numpy(codes, name, domain=domain)
            elif arr.dtype.kind == "b":
                vecs[name] = Vec.from_numpy(arr.astype(np.float32), name)
            else:
                vecs[name] = Vec.from_numpy(arr, name)
        return Frame(vecs)

    @staticmethod
    def from_pandas(df) -> "Frame":
        return Frame.from_arrays({c: df[c].to_numpy() for c in df.columns})

    # -- basics -------------------------------------------------------------

    @property
    def names(self) -> list[str]:
        return list(self._vecs)

    @property
    def nrows(self) -> int:
        return next(iter(self._vecs.values())).nrows if self._vecs else 0

    @property
    def ncols(self) -> int:
        return len(self._vecs)

    @property
    def shape(self) -> tuple[int, int]:
        return (self.nrows, self.ncols)

    def vec(self, name: str) -> Vec:
        return self._vecs[name]

    def __getitem__(self, key):
        if isinstance(key, str):
            return self._vecs[key]
        if isinstance(key, Vec):
            # boolean row filter: fr[fr["x"] > 0] — NA mask rows drop
            # (h2o-py Rapids row-slice semantics)
            if key.nrows != self.nrows:
                raise ValueError("filter mask length != nrows")
            return self.select_rows(np.asarray(key._bool())[: self.nrows])
        if isinstance(key, (list, tuple)):
            return Frame({k: self._vecs[k] for k in key})
        raise TypeError(f"bad key {key!r}")

    def __setitem__(self, name: str, vec: Vec):
        if self._vecs and vec.nrows != self.nrows:
            raise ValueError("nrows mismatch")
        self._vecs[name] = vec
        # column set changed: binned stale (setdefault: frames from old
        # pickles predate the cache attribute); the version bump also
        # invalidates any fused-binning fit key a live BinSpec carries
        self.__dict__.setdefault("_binned_cache", {}).clear()
        self.__dict__["_version"] = self.__dict__.get("_version", 0) + 1

    def __contains__(self, name: str) -> bool:
        return name in self._vecs

    def drop(self, names: str | Sequence[str]) -> "Frame":
        if isinstance(names, str):
            names = [names]
        return Frame({k: v for k, v in self._vecs.items() if k not in names})

    # -- device views -------------------------------------------------------

    def columns(self, names: Iterable[str] | None = None) -> list[Vec]:
        return [self._vecs[n] for n in
                (self.names if names is None else names)]

    def to_matrix(self, names: Iterable[str] | None = None) -> jax.Array:
        """[padded_rows, k] float32 matrix (enums as raw codes, NA→NaN)."""
        cols = [v.as_float() for v in self.columns(names)]
        return jnp.stack(cols, axis=1)

    def binned(self, bin_spec) -> jax.Array:
        """[padded_rows, F] uint8 bin codes for this frame under
        ``bin_spec`` (models/tree/binning.BinSpec), cached per frame.

        This is the chunked training data path's device working set:
        the tree learners train from it directly — the full-width
        float32 ``to_matrix`` is never materialized (binning happens
        column-block-wise straight from the Frame columns, see
        binning.bin_frame). Bitwise-identical to
        ``apply_bins_jit(self.to_matrix(bin_spec.names), ...)``.

        The cache key includes a content fingerprint of the edge
        matrix, so a checkpoint's BinSpec (edges fit on ANOTHER frame)
        never collides with this frame's own fit. Mutating the frame
        (``__setitem__``) invalidates. At most two entries are kept
        (e.g. a 256-bin GBM and a 64-bin DRF working set side by side).
        """
        import hashlib

        from ..models.tree.binning import bin_frame

        edges = np.asarray(bin_spec.edges_matrix())
        fp = hashlib.sha1(edges.tobytes()
                          + np.array(bin_spec.is_enum).tobytes()
                          ).hexdigest()[:16]
        key = (tuple(bin_spec.names), bin_spec.n_bins, fp)
        cache = self.__dict__.setdefault("_binned_cache", {})
        hit = cache.pop(key, None)
        if hit is not None:
            cache[key] = hit          # true LRU: a hit refreshes recency
            return hit
        out = bin_frame(self, bin_spec)
        while len(cache) >= 2:                  # tiny LRU: drop oldest
            cache.pop(next(iter(cache)))
        cache[key] = out
        return out

    def valid_mask(self) -> jax.Array:
        """float32 [padded_rows]: 1.0 for logical rows, 0.0 for padding."""
        if not self._vecs:
            raise ValueError("valid_mask() on an empty Frame")
        v = next(iter(self._vecs.values()))
        mask = (np.arange(v.padded_len) < v.nrows).astype(np.float32)
        return shard_rows(mask)   # multi-host-safe placement

    def to_pandas(self):
        import pandas as pd
        out = {}
        for n, v in self._vecs.items():
            a = v.to_numpy()
            if v.is_enum():
                dom = np.asarray(list(v.domain) + [None], dtype=object)
                col = dom[np.where(a >= 0, a, len(dom) - 1)]
                out[n] = col
            else:
                out[n] = a
        return pd.DataFrame(out)

    def summary(self) -> dict[str, dict[str, float]]:
        return {n: v.rollups() for n, v in self._vecs.items()}

    # -- row ops -------------------------------------------------------------

    def select_rows(self, idx) -> "Frame":
        """New Frame of rows at `idx` (int index array or bool mask)."""
        idx = np.asarray(idx)
        if idx.dtype == bool:
            if len(idx) != self.nrows:
                raise ValueError("mask length != nrows")
            idx = np.flatnonzero(idx)
        return Frame({n: v.select_rows(idx) for n, v in self._vecs.items()})

    def head(self, n: int = 10) -> "Frame":
        return self.select_rows(np.arange(min(n, self.nrows)))

    def split_frame(self, ratios: Sequence[float] = (0.75,),
                    seed: int = -1) -> list["Frame"]:
        """Random row split into len(ratios)+1 frames (h2o split_frame).

        Same sampling scheme as the reference's FrameSplitter: one uniform
        draw per row against the cumulative ratio boundaries.
        """
        if sum(ratios) >= 1.0:
            raise ValueError("ratios must sum to < 1")
        rng = np.random.default_rng(None if seed < 0 else seed)
        u = rng.random(self.nrows)
        bounds = np.cumsum(list(ratios) + [1.0])
        part = np.searchsorted(bounds, u, side="right")
        return [self.select_rows(part == k) for k in range(len(bounds))]

    def rbind(self, other: "Frame") -> "Frame":
        """Stack rows of two column-compatible frames."""
        if self.names != other.names:
            raise ValueError("rbind: column names differ")
        out: dict[str, Vec] = {}
        for n in self.names:
            a, b = self._vecs[n], other._vecs[n]
            if a.kind != b.kind:
                raise ValueError(f"rbind: column '{n}' kinds differ "
                                 f"({a.kind} vs {b.kind})")
            if a.is_enum() and list(a.domain) != list(b.domain):
                dom = sorted(set(a.domain) | set(b.domain))
                pos = {d: i for i, d in enumerate(dom)}
                lut_a = np.array([pos[d] for d in a.domain] + [NA_ENUM],
                                 dtype=np.int32)
                lut_b = np.array([pos[d] for d in b.domain] + [NA_ENUM],
                                 dtype=np.int32)
                ca, cb = a.to_numpy(), b.to_numpy()
                cat = np.concatenate([lut_a[np.where(ca < 0, len(lut_a) - 1, ca)],
                                      lut_b[np.where(cb < 0, len(lut_b) - 1, cb)]])
                out[n] = Vec.from_numpy(cat, n, domain=dom)
            else:
                cat = np.concatenate([a.to_numpy(), b.to_numpy()])
                out[n] = Vec.from_numpy(cat, n, domain=a.domain, kind=a.kind)
        return Frame(out)

    def group_by(self, by) -> "Any":
        """h2o-py GroupBy builder: fr.group_by("c").sum("x").get_frame()."""
        from .munge import GroupBy
        return GroupBy(self, by)

    def merge(self, other: "Frame", by=None, all_x: bool = False) -> "Frame":
        """Join on key columns (h2o merge: inner, or left when all_x)."""
        from .munge import merge as _merge
        return _merge(self, other, by=by, all_x=all_x)

    def impute(self, column: str, method: str = "mean", by=None):
        """Fill NAs in place (h2o.impute: mean/median/mode, by-groups)."""
        from .munge import impute as _impute
        return _impute(self, column, method=method, by=by)

    def table(self, col: str, col2: str | None = None) -> "Frame":
        """Frequency table of 1-2 categorical columns (h2o table)."""
        from .munge import table as _table
        return _table(self, col, col2)

    def quantile(self, prob=None) -> "Frame":
        """Per-numeric-column quantiles (h2o quantile defaults)."""
        from .munge import quantile as _quantile
        return _quantile(self) if prob is None else _quantile(self, prob)

    def sort(self, by, ascending: bool = True) -> "Frame":
        """Rows ordered by the given column(s) (h2o sort; stable,
        NA rows last either direction)."""
        keys = [by] if isinstance(by, str) else list(by)
        cols = []
        for k in reversed(keys):   # lexsort: last key is primary
            v = self._vecs[k]
            a = v.to_numpy().astype(np.float64)
            na = (a < 0) if v.is_enum() else np.isnan(a)
            # descending: negate the key rather than reversing the
            # permutation — keeps the sort stable and NA rows last
            key = a if ascending else -a
            cols.append(np.where(na, np.inf, key))
        return self.select_rows(np.lexsort(cols))

    def cbind(self, other: "Frame") -> "Frame":
        """Adjoin columns of an equal-length frame (suffix dups like h2o)."""
        if other.nrows != self.nrows:
            raise ValueError("cbind: nrows differ")
        out = dict(self._vecs)
        for n, v in other._vecs.items():
            name = n
            while name in out:
                name += "0"   # h2o suffixes duplicate names
            out[name] = v
        return Frame(out)


def _factorize(arr: np.ndarray,
               domain: list[str] | None = None) -> tuple[np.ndarray, list[str]]:
    """String column → (int32 codes, sorted vocab).

    NA is only true missingness: None / float NaN cells in object arrays
    and empty strings. Literal tokens like "NA" or "nan" stay categories —
    parse-time NA-token handling is the CSV reader's job, not ours.
    """
    if arr.dtype.kind == "O":
        isna = np.array([x is None or x != x for x in arr], dtype=bool)
    else:
        isna = np.zeros(len(arr), dtype=bool)
    s = np.where(isna, "", arr.astype(str))
    isna |= s == ""
    if domain is None:
        uniq, inv = np.unique(s[~isna], return_inverse=True)
        domain = [str(d) for d in uniq]
        codes = np.full(len(s), NA_ENUM, dtype=np.int32)
        codes[~isna] = inv.astype(np.int32)
    else:
        lookup = {d: i for i, d in enumerate(domain)}
        codes = np.array([lookup.get(x, NA_ENUM) for x in s], dtype=np.int32)
        codes[isna] = NA_ENUM
    return codes, domain
