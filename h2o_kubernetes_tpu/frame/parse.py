"""Ingest: CSV (and friends) → Frame.

The reference's distributed parse (water/parser/ParseDataset — preview →
type inference → chunk-parallel parse into NewChunks → categorical
interning across nodes; SURVEY.md §2b C8) becomes a host-side two-pass
parse here: a preview pass infers per-column types exactly like
ParseSetup does, then a typed bulk read materialises columns that are
`device_put`-sharded over the mesh rows axis (Frame construction does the
sharding). There is no cross-node string interning to do — the vocab is
built once on the host and only int32 codes reach the device.

Supported: separator sniffing, header detection, NA-token handling,
gz/bz2/xz transparently, globs and directories (multi-file import is
concatenated in name order, like ParseDataset over several keys), and
explicit per-column type overrides (col_types) mirroring h2o.import_file.
Formats: CSV, ARFF, Parquet/ORC (pyarrow), Avro (stdlib container
reader), SVMLight/LIBSVM — the reference's h2o-parsers surface.
"""

from __future__ import annotations

import bz2
import glob as globlib
import gzip
import io
import itertools
import lzma
import os
from typing import Mapping, Sequence

import numpy as np

from .frame import Frame, Vec, NA_ENUM

# the reference's default NA tokens (water/parser/ParseSetup) plus pandas'
_NA_TOKENS = {"", "na", "n/a", "nan", "null", "none", "-", "?",
              "#n/a", "#na", "1.#qnan", "-nan", "-1.#qnan"}

_SEPS = [",", "\t", ";", "|", " "]

_PREVIEW_ROWS = 1000


def _open_text(path: str):
    if path.endswith(".gz"):
        return io.TextIOWrapper(gzip.open(path, "rb"), encoding="utf-8",
                                errors="replace")
    if path.endswith(".bz2"):
        return io.TextIOWrapper(bz2.open(path, "rb"), encoding="utf-8",
                                errors="replace")
    if path.endswith((".xz", ".lzma")):
        return io.TextIOWrapper(lzma.open(path, "rb"), encoding="utf-8",
                                errors="replace")
    return open(path, "r", encoding="utf-8", errors="replace", newline="")


def _expand_paths(path: str | Sequence[str]) -> list[str]:
    if isinstance(path, (list, tuple)):
        out: list[str] = []
        for p in path:
            out.extend(_expand_paths(p))
        return out
    if os.path.isdir(path):
        return sorted(
            os.path.join(path, f) for f in os.listdir(path)
            if not f.startswith("."))
    if any(c in path for c in "*?["):
        hits = sorted(globlib.glob(path))
        if not hits:
            raise FileNotFoundError(path)
        return hits
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    return [path]


def _sniff_sep(lines: list[str]) -> str:
    """Pick the separator that yields the most consistent column count > 1
    (ParseSetup's separator guess)."""
    best, best_score = ",", -1
    for sep in _SEPS:
        counts = [len(_split_line(ln, sep)) for ln in lines if ln.strip()]
        if not counts:
            continue
        mode = max(set(counts), key=counts.count)
        if mode < 2:
            continue
        score = counts.count(mode) * mode
        if score > best_score:
            best, best_score = sep, score
    return best


def _read_records(f, limit: int | None = None):
    """Yield logical CSV records, joining physical lines while inside an
    unterminated double-quoted field (multi-line cells)."""
    count = 0
    buf: list[str] = []
    for ln in f:
        buf.append(ln)
        joined = "".join(buf)
        if joined.count('"') % 2 == 1:
            continue  # quote still open → record spans to next line
        buf = []
        if not joined.strip():
            continue
        yield joined
        count += 1
        if limit is not None and count >= limit:
            return
    if buf and "".join(buf).strip():
        yield "".join(buf)


def _split_line(line: str, sep: str) -> list[str]:
    """Split one CSV record honoring double-quote quoting."""
    if '"' not in line:
        return line.rstrip("\r\n").split(sep)
    out, cur, inq = [], [], False
    i, n = 0, len(line)
    while i < n:
        c = line[i]
        if inq:
            if c == '"':
                if i + 1 < n and line[i + 1] == '"':
                    cur.append('"'); i += 1
                else:
                    inq = False
            else:
                cur.append(c)
        elif c == '"':
            inq = True
        elif c == sep:
            out.append("".join(cur)); cur = []
        elif c not in "\r\n":
            cur.append(c)
        i += 1
    out.append("".join(cur))
    return out


def _is_na(tok: str, na_strings: set[str]) -> bool:
    return tok.strip().lower() in na_strings


def _try_float(tok: str) -> float | None:
    try:
        return float(tok)
    except ValueError:
        return None


def _infer_col_type(vals: list[str], na_strings: set[str]) -> str:
    """ParseSetup-style vote over preview values: numeric if every non-NA
    token parses as a number; time if they parse as dates; else enum."""
    nnum = ntime = nother = 0
    for tok in vals:
        if _is_na(tok, na_strings):
            continue
        if _try_float(tok) is not None:
            nnum += 1
        elif _parse_time_ms(tok) is not None:
            ntime += 1
        else:
            nother += 1
    if nother == 0 and ntime > 0 and nnum == 0:
        return "time"
    if nother == 0 and ntime == 0 and nnum > 0:
        return "numeric"
    if nnum + ntime + nother == 0:
        return "numeric"  # all-NA column
    return "enum"


_TIME_FORMATS = ["%Y-%m-%d %H:%M:%S", "%Y-%m-%dT%H:%M:%S", "%Y-%m-%d",
                 "%m/%d/%Y", "%d-%b-%y", "%Y%m%d"]


def _parse_time_ms(tok: str) -> float | None:
    tok = tok.strip()
    if not tok or tok[0] not in "0123456789":
        return None
    import datetime as dt
    for fmt in _TIME_FORMATS:
        try:
            d = dt.datetime.strptime(tok, fmt)
            return d.replace(tzinfo=dt.timezone.utc).timestamp() * 1000.0
        except ValueError:
            continue
    return None


def _header_vote(rows: list[list[str]], na_strings: set[str]) -> bool:
    """ParseSetup-style header heuristic: row 1 must be all non-numeric;
    then either the body has numbers (type break) or, for all-string data,
    row-1 labels are unique and never recur in their own columns."""
    first = rows[0]
    if any(_try_float(t) is not None for t in first):
        return False
    body = rows[1:]
    if not body:
        return True
    if any(_try_float(t) is not None for r in body for t in r
           if not _is_na(t, na_strings)):
        return True
    # all-string dataset: column labels are unique and don't repeat below
    if len(set(first)) != len(first):
        return False
    for c, label in enumerate(first):
        if any(c < len(r) and r[c] == label for r in body):
            return False
    return True


def parse_setup(path: str | Sequence[str], sep: str | None = None,
                header: int = -1,
                na_strings: Sequence[str] | None = None) -> dict:
    """Preview pass → {files, sep, header, names, types} (the /3/ParseSetup
    analog). `header`: -1 auto, 0 none, 1 forced."""
    files = _expand_paths(path)
    nas = set(_NA_TOKENS if na_strings is None
              else [s.lower() for s in na_strings])
    with _open_text(files[0]) as f:
        lines = list(_read_records(f, limit=_PREVIEW_ROWS))
    if not lines:
        raise ValueError(f"{files[0]}: empty file")
    if sep is None:
        sep = _sniff_sep(lines[:50])
    rows = [_split_line(ln, sep) for ln in lines]
    has_header = bool(header) if header >= 0 else _header_vote(rows, nas)
    if has_header:
        ncol = len(rows[0])
    else:  # modal column count over the preview (ParseSetup vote)
        counts = [len(r) for r in rows]
        ncol = max(set(counts), key=counts.count)
    names = (rows[0] if has_header else [f"C{i+1}" for i in range(ncol)])
    body = rows[1:] if has_header else rows
    types = []
    for c in range(ncol):
        vals = [r[c] for r in body if c < len(r)]
        types.append(_infer_col_type(vals, nas))
    return {"files": files, "sep": sep, "header": has_header,
            "names": names, "types": types, "na_strings": nas}


_PARQUET_MAGIC = b"PAR1"
_ORC_MAGIC = b"ORC"
_AVRO_MAGIC = b"Obj\x01"


def _binary_format(path: str) -> str | None:
    """Sniff columnar binary formats by magic bytes (the reference's
    parser provider detection, water/parser GuessParserSetup [U3])."""
    try:
        with open(path, "rb") as f:
            head = f.read(4)
    except (OSError, IsADirectoryError):
        return None
    if head == _PARQUET_MAGIC:
        return "parquet"
    if head[:3] == _ORC_MAGIC:
        return "orc"
    if head == _AVRO_MAGIC:
        return "avro"
    return None


def _import_arrow(files: list[str], fmt: str,
                  col_types: Mapping[str, str] | None,
                  skipped: set[str]) -> Frame:
    """Parquet/ORC ingest via pyarrow (h2o-parsers/h2o-parquet-parser
    analog): host-side columnar read → typed numpy → sharded device
    columns. Arrow dictionary columns keep their vocab as the enum
    domain; timestamps become time Vecs (epoch ms)."""
    import pyarrow as pa

    if fmt == "parquet":
        import pyarrow.parquet as pq
        tables = [pq.read_table(f) for f in files]
    else:
        from pyarrow import orc
        tables = [orc.ORCFile(f).read() for f in files]
    table = tables[0] if len(tables) == 1 else pa.concat_tables(
        tables, promote_options="default")

    overrides = dict(col_types or {}) if isinstance(col_types, Mapping) \
        else {}
    cols: dict[str, Vec] = {}
    for name in table.column_names:
        if name in skipped:
            continue
        col = table.column(name).combine_chunks()
        t = col.type
        want = _norm_type(overrides[name]) if name in overrides else None
        if pa.types.is_dictionary(t):
            codes = col.indices.to_numpy(zero_copy_only=False).astype(
                np.float64)          # nulls → NaN before int cast
            null = np.asarray(col.is_null())
            codes = np.where(null, -1, np.nan_to_num(codes, nan=-1))
            dom = [str(v) for v in col.dictionary.to_pylist()]
            v = Vec.from_numpy(codes.astype(np.int32), name, domain=dom)
        elif pa.types.is_timestamp(t) or pa.types.is_date(t):
            ms = col.cast(pa.timestamp("ms")).to_numpy(
                zero_copy_only=False)
            v = Vec.from_numpy(ms, name)   # datetime64 → time kind
        elif pa.types.is_string(t) or pa.types.is_large_string(t) or \
                pa.types.is_binary(t):
            arr = np.asarray(col.to_pylist(), dtype=object)
            from .frame import _factorize
            codes, dom = _factorize(arr)
            v = Vec.from_numpy(codes, name, domain=dom)
        else:
            a = col.to_numpy(zero_copy_only=False).astype(np.float64)
            v = Vec.from_numpy(a.astype(np.float32), name)
        if want == "enum" and not v.is_enum():
            v = v.asfactor()
        elif want == "numeric" and v.is_enum():
            v = v.asnumeric()
        cols[name] = v
    return Frame(cols)


# -- Avro (h2o-parsers/h2o-avro-parser analog [U3]) --------------------------
#
# Stdlib-only reader for the Avro Object Container File format: header
# (magic + metadata map carrying the writer schema JSON + codec), then
# sync-delimited blocks of binary-encoded records. Covers the tabular
# subset the reference's parser ingests: records of primitive fields
# (boolean/int/long/float/double/string/bytes), enums, and nullable
# unions [null, primitive]; codecs null and deflate; logicalType
# timestamp-millis -> time column.

class _AvroReader:
    def __init__(self, buf: bytes):
        self.b = buf
        self.i = 0

    def read(self, n: int) -> bytes:
        out = self.b[self.i:self.i + n]
        if len(out) < n:
            raise ValueError("truncated avro data")
        self.i += n
        return out

    def long(self) -> int:
        """Zig-zag varint (avro int and long share the encoding)."""
        shift, acc = 0, 0
        while True:
            if self.i >= len(self.b):
                raise ValueError("truncated avro data")
            byte = self.b[self.i]
            self.i += 1
            acc |= (byte & 0x7F) << shift
            if not byte & 0x80:
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)

    def bytes_(self) -> bytes:
        return self.read(self.long())

    def string(self) -> str:
        return self.bytes_().decode("utf-8", errors="replace")

    def at_end(self) -> bool:
        return self.i >= len(self.b)


def _avro_decode(r: _AvroReader, schema):
    """Decode ONE value of `schema` (parsed JSON) from the stream."""
    if isinstance(schema, list):            # union: index then branch
        idx = r.long()
        if not 0 <= idx < len(schema):
            raise ValueError(f"avro union index {idx} out of range")
        return _avro_decode(r, schema[idx])
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "record":
            return {f["name"]: _avro_decode(r, f["type"])
                    for f in schema["fields"]}
        if t == "enum":
            idx = r.long()
            syms = schema["symbols"]
            if not 0 <= idx < len(syms):
                raise ValueError(f"avro enum index {idx} out of range")
            return syms[idx]
        if t in ("int", "long", "float", "double", "string", "bytes",
                 "boolean", "null"):
            return _avro_decode(r, t)
        if t == "array" or t == "map" or t == "fixed":
            raise ValueError(
                f"avro type '{t}' is not tabular; flatten it upstream")
        raise ValueError(f"unsupported avro type {t!r}")
    if schema == "null":
        return None
    if schema == "boolean":
        return r.read(1)[0] != 0
    if schema in ("int", "long"):
        return r.long()
    if schema == "float":
        import struct

        return struct.unpack("<f", r.read(4))[0]
    if schema == "double":
        import struct

        return struct.unpack("<d", r.read(8))[0]
    if schema == "bytes":
        return r.bytes_()
    if schema == "string":
        return r.string()
    raise ValueError(f"unsupported avro type {schema!r}")


def _avro_field_kind(ftype) -> str:
    """numeric | time | enum | bool for a field schema (unions unwrap)."""
    if isinstance(ftype, list):
        branches = [b for b in ftype if b != "null"]
        if len(branches) != 1:
            raise ValueError(f"unsupported avro union {ftype!r}")
        return _avro_field_kind(branches[0])
    if isinstance(ftype, dict):
        if ftype.get("logicalType") in ("timestamp-millis",
                                        "timestamp-micros"):
            return "time-" + ftype["logicalType"]
        if ftype["type"] == "enum":
            return "enum"
        return _avro_field_kind(ftype["type"])
    if ftype in ("int", "long", "float", "double"):
        return "numeric"
    if ftype == "boolean":
        return "bool"
    if ftype in ("string", "bytes"):
        return "str"
    raise ValueError(f"unsupported avro field type {ftype!r}")


def _import_avro(files: list[str], skipped: set[str]) -> Frame:
    import json as jsonlib
    import zlib

    names: list[str] = []
    schema = None
    cols: dict[str, list] = {}
    for fi, fp in enumerate(files):
        with open(fp, "rb") as f:
            r = _AvroReader(f.read())
        if r.read(4) != _AVRO_MAGIC:
            raise ValueError(f"{fp}: not an avro container file")
        meta: dict[str, bytes] = {}
        while True:                      # metadata map, possibly chunked
            n = r.long()
            if n == 0:
                break
            if n < 0:                    # negative count prefixes a size
                n = -n
                r.long()
            for _ in range(n):
                # two statements: Python evaluates an assignment's RHS
                # first, which would read the value bytes before the key
                key = r.string()
                meta[key] = r.bytes_()
        codec = meta.get("avro.codec", b"null").decode()
        if codec not in ("null", "deflate"):
            raise ValueError(f"{fp}: unsupported avro codec '{codec}'")
        fschema = jsonlib.loads(meta["avro.schema"].decode())
        if not (isinstance(fschema, dict) and
                fschema.get("type") == "record"):
            raise ValueError(f"{fp}: top-level avro schema must be a "
                             "record")
        if fi == 0:
            schema = fschema
            names = [f["name"] for f in schema["fields"]]
            cols = {n: [] for n in names}
        elif fschema["fields"] != schema["fields"]:
            # FULL field equality (names + types + enum symbol order):
            # decoding a later file's blocks against a different writer
            # schema would read varints as doubles / remap enum codes
            # silently
            raise ValueError(f"{fp}: avro schema differs from {files[0]}")
        sync = r.read(16)
        while not r.at_end():
            count = r.long()
            blk = r.bytes_()
            if codec == "deflate":
                blk = zlib.decompress(blk, -15)
            br = _AvroReader(blk)
            for _ in range(count):
                rec = _avro_decode(br, schema)
                for n in names:
                    cols[n].append(rec[n])
            if r.read(16) != sync:
                raise ValueError(f"{fp}: avro sync marker mismatch")

    vecs: dict[str, Vec] = {}
    for fld in schema["fields"]:
        name = fld["name"]
        if name in skipped:
            continue
        kind = _avro_field_kind(fld["type"])
        vals = cols[name]
        if kind == "numeric" or kind == "bool":
            arr = np.array([np.nan if v is None else float(v)
                            for v in vals], dtype=np.float32)
            vecs[name] = Vec.from_numpy(arr, name)
        elif kind.startswith("time-"):
            scale = 1.0 if kind.endswith("millis") else 1e-3
            arr = np.array([np.nan if v is None else float(v) * scale
                            for v in vals], dtype=np.float64)
            vecs[name] = Vec.from_numpy(arr, name, kind="time")
        elif kind == "enum":
            dom = _avro_enum_symbols(fld["type"])
            pos = {s: i for i, s in enumerate(dom)}
            codes = np.array([NA_ENUM if v is None else pos[v]
                              for v in vals], dtype=np.int32)
            vecs[name] = Vec.from_numpy(codes, name, domain=dom)
        else:                                  # str/bytes -> interned enum
            # intern directly: None must become NA without hijacking a
            # genuine empty-string level (union [null, string] columns
            # routinely carry both)
            lut: dict[str, int] = {}
            codes = np.empty(len(vals), dtype=np.int32)
            for i, v in enumerate(vals):
                if v is None:
                    codes[i] = NA_ENUM
                    continue
                tok = (v.decode("utf-8", errors="replace")
                       if isinstance(v, bytes) else str(v))
                codes[i] = lut.setdefault(tok, len(lut))
            vecs[name] = _lut_to_vec(codes, lut, name)
    return Frame(vecs)


def _avro_enum_symbols(ftype) -> list[str]:
    if isinstance(ftype, list):
        ftype = [b for b in ftype if b != "null"][0]
    return list(ftype["symbols"])


# -- SVMLight (water/parser/SVMLightParser analog [U3]) ----------------------

def _svmlight_line_ok(s: str) -> int:
    """-1 if the line does not conform; else its idx:val pair count."""
    toks = s.split()
    if len(toks) < 2 or _try_float(toks[0]) is None:
        return -1
    pairs = toks[1:]
    if pairs and pairs[0].startswith("qid:"):
        pairs = pairs[1:]
    if not pairs:
        return -1
    last = 0
    for p in pairs:
        idx, _, val = p.partition(":")
        if not idx.isdigit() or _try_float(val) is None:
            return -1
        if int(idx) <= last:
            return -1
        last = int(idx)
    return len(pairs)


def _looks_svmlight(path: str) -> bool:
    """Content sniff for EXTENSIONLESS files: every previewed
    non-comment line must be `label [qid:q] i:v ...` with strictly
    increasing indices, AND at least one line must carry >= 2 pairs.
    The second condition keeps generic space-separated data whose rows
    happen to look like `3 08:30` (count + clock time) out of the
    svmlight parser — a real one-pair-per-row svmlight file is still
    importable via its .svm/.svmlight extension."""
    try:
        with _open_text(path) as f:
            seen = 0
            max_pairs = 0
            for ln in f:
                s = ln.split("#", 1)[0].strip()
                if not s:
                    continue
                n = _svmlight_line_ok(s)
                if n < 0:
                    return False
                max_pairs = max(max_pairs, n)
                seen += 1
                if seen >= 32:
                    break
            return seen > 0 and max_pairs >= 2
    except OSError:
        return False


def _import_svmlight(files: list[str], skipped: set[str]) -> Frame:
    """SVMLight/LIBSVM ingest: `label [qid:q] idx:val ... [# comment]`.

    1-based feature indices become columns C2..C{d+1} with the label in
    C1 (the reference's SVMLightParser layout); absent entries are 0
    (sparse semantics, NOT NA). An optional qid column is kept for
    ranking objectives (XGBoost group_column)."""
    labels: list[float] = []
    qids: list[float] = []
    entries: list[tuple[int, int, float]] = []   # (row, col0, val)
    has_qid = False
    max_idx = 0
    row = 0
    for fp in files:
        with _open_text(fp) as f:
            for lineno, ln in enumerate(f, start=1):
                s = ln.split("#", 1)[0].strip()
                if not s:
                    continue
                toks = s.split()
                lab = _try_float(toks[0])
                if lab is None:
                    raise ValueError(
                        f"{fp}:{lineno}: bad svmlight label "
                        f"'{toks[0]}'")
                labels.append(lab)
                pairs = toks[1:]
                qid = np.nan
                if pairs and pairs[0].startswith("qid:"):
                    q = _try_float(pairs[0][4:])
                    if q is None:
                        raise ValueError(
                            f"{fp}:{lineno}: bad qid "
                            f"'{pairs[0]}'")
                    qid = q
                    has_qid = True
                    pairs = pairs[1:]
                qids.append(qid)
                last = 0
                for p in pairs:
                    idx_s, _, val_s = p.partition(":")
                    v = _try_float(val_s)
                    if not idx_s.isdigit() or v is None:
                        raise ValueError(
                            f"{fp}:{lineno}: bad svmlight pair '{p}'")
                    idx = int(idx_s)
                    if idx <= last:
                        # out-of-order/duplicate indices would silently
                        # overwrite; the reference rejects them too
                        raise ValueError(
                            f"{fp}:{lineno}: non-increasing feature "
                            f"index {idx}")
                    last = idx
                    max_idx = max(max_idx, idx)
                    entries.append((row, idx - 1, v))
                row += 1
    # the Frame model is dense float32 columns, so an SVMLight import
    # materializes rows x max_index cells no matter how sparse the file
    # is — cap it so a 1M-feature text corpus raises a clear error
    # instead of a ~400GB allocation attempt
    budget = int(os.environ.get("H2O_TPU_SVMLIGHT_DENSE_BUDGET",
                                200_000_000))
    if row * max_idx > budget:
        raise ValueError(
            f"svmlight file would densify to {row} rows x {max_idx} "
            f"features = {row * max_idx:,} cells (> budget {budget:,}); "
            "this frame store is dense — reduce the feature space or "
            "raise H2O_TPU_SVMLIGHT_DENSE_BUDGET if you really have "
            "the memory")
    X = np.zeros((row, max_idx), dtype=np.float32)
    if entries:
        e = np.array(entries)
        X[e[:, 0].astype(np.int64), e[:, 1].astype(np.int64)] = e[:, 2]
    vecs: dict[str, Vec] = {}
    if "C1" not in skipped:
        vecs["C1"] = Vec.from_numpy(
            np.asarray(labels, dtype=np.float32), "C1")
    if has_qid and "qid" not in skipped:
        vecs["qid"] = Vec.from_numpy(
            np.asarray(qids, dtype=np.float32), "qid")
    for j in range(max_idx):
        name = f"C{j + 2}"
        if name in skipped:
            continue
        vecs[name] = Vec.from_numpy(X[:, j], name)
    return Frame(vecs)


def _looks_arff(path: str) -> bool:
    """Content sniff: first non-comment line starts with @relation."""
    try:
        with _open_text(path) as f:
            for ln in f:
                s = ln.strip()
                if not s or s.startswith("%"):
                    continue
                return s.lower().startswith("@relation")
    except OSError:
        return False
    return False


def _arff_split(line: str) -> list[str]:
    """Split an ARFF record on commas honoring ARFF quoting: values may
    be SINGLE- or double-quoted (ARFF convention is single quotes, which
    the CSV splitter ignores — a domain like {'a,b','c'} or a quoted
    data token containing a comma would mis-split), with backslash
    escapes inside quotes. Quotes are removed and bare tokens stripped."""
    out: list[str] = []
    cur: list[str] = []
    q: str | None = None
    close_at: int | None = None   # cur length when the quote closed
    i, n = 0, len(line)

    def flush():
        if close_at is None:
            out.append("".join(cur).strip())
        else:
            # quoted fields keep inner spaces verbatim; whitespace
            # AFTER the closing quote is separator padding, not content
            out.append("".join(cur[:close_at])
                       + "".join(cur[close_at:]).strip())

    while i < n:
        c = line[i]
        if q is not None:
            if c == "\\" and i + 1 < n:
                cur.append(line[i + 1])
                i += 2
                continue
            if c == q:
                q = None
                close_at = len(cur)
            else:
                cur.append(c)
        elif c in "'\"" and not "".join(cur).strip():
            # a quote only OPENS a field at its (whitespace-trimmed)
            # start; mid-token apostrophes (don't) stay literal
            cur = []                  # drop leading spaces before quote
            q = c
        elif c == ",":
            flush()
            cur = []
            close_at = None
        elif c not in "\r\n":
            cur.append(c)
        i += 1
    if q is not None:
        # silently closing would corrupt the token and swallow commas
        raise ValueError(f"unterminated {q} quote in ARFF record: "
                         f"{line[:80]!r}")
    flush()
    return out


def _import_arff(files: list[str], skipped: set[str]) -> Frame:
    """ARFF ingest (h2o-parsers ARFF parser analog [U3]): @attribute
    declarations give names AND types — numeric/real/integer,
    {nominal,...} with the DECLARED level order kept (unlike CSV enum
    inference, which sorts), string (interned like nominal), date
    (epoch-ms time column). '?' is NA. Dense rows only; the sparse
    `{i v, ...}` form is rejected loudly."""
    names: list[str] = []
    types: list[str | list[str]] = []
    raw: list[list[str]] = []
    for fi, fp in enumerate(files):
        in_data = False
        f_names: list[str] = []
        f_types: list[str | list[str]] = []
        with _open_text(fp) as f:
            for lineno, ln in enumerate(f, start=1):
                s = ln.strip()
                if not s or s.startswith("%"):
                    continue
                low = s.lower()
                if not in_data:
                    if low.startswith("@relation"):
                        continue
                    if low.startswith("@attribute"):
                        body = s[len("@attribute"):].strip()
                        if body.startswith(("'", '"')):
                            q = body[0]
                            end = body.find(q, 1)
                            if end < 0:
                                raise ValueError(
                                    f"{fp}:{lineno}: unterminated "
                                    f"quoted attribute name '{s}'")
                            aname = body[1:end]
                            atype = body[end + 1:].strip()
                        else:
                            parts = body.split(None, 1)
                            if len(parts) != 2:
                                raise ValueError(
                                    f"{fp}:{lineno}: malformed "
                                    f"@attribute '{s}'")
                            aname, atype = parts
                        if atype.startswith("{"):
                            try:
                                dom = _arff_split(atype.strip("{}"))
                            except ValueError as e:
                                raise ValueError(
                                    f"{fp}:{lineno}: {e}") from None
                            f_types.append(dom)
                        else:
                            t = atype.split()[0].lower()
                            if t in ("numeric", "real", "integer"):
                                f_types.append("numeric")
                            elif t == "string":
                                f_types.append("string")
                            elif t == "date":
                                f_types.append("time")
                            else:
                                raise ValueError(
                                    f"{fp}:{lineno}: unsupported ARFF "
                                    f"type '{atype}'")
                        f_names.append(aname)
                        continue
                    if low.startswith("@data"):
                        if fi == 0:
                            names, types = f_names, f_types
                            raw = [[] for _ in names]
                        elif f_names != names or f_types != types:
                            # a type mismatch silently materializing
                            # under the first file's types would turn
                            # nominal tokens into NaNs
                            raise ValueError(
                                f"{fp}: ARFF attributes differ from "
                                f"{files[0]}")
                        in_data = True
                        continue
                    raise ValueError(
                        f"{fp}:{lineno}: unexpected ARFF line '{s}'")
                else:
                    if s.startswith("{"):
                        raise ValueError(
                            f"{fp}:{lineno}: sparse ARFF rows are not "
                            "supported")
                    try:
                        toks = _arff_split(s)
                    except ValueError as e:
                        raise ValueError(f"{fp}:{lineno}: {e}") from None
                    if len(toks) != len(names):
                        raise ValueError(
                            f"{fp}:{lineno}: {len(toks)} values, "
                            f"expected {len(names)}")
                    for c, t in enumerate(toks):
                        raw[c].append(t)
        if not in_data:
            raise ValueError(f"{fp}: no @data section")
    vecs: dict[str, Vec] = {}
    for c, (name, typ) in enumerate(zip(names, types)):
        if name in skipped:
            continue
        if isinstance(typ, list):          # declared nominal domain
            pos = {d: i for i, d in enumerate(typ)}
            codes = np.empty(len(raw[c]), dtype=np.int32)
            for i, tok in enumerate(raw[c]):
                if tok == "?" or tok == "":
                    codes[i] = -1
                elif tok in pos:
                    codes[i] = pos[tok]
                else:
                    raise ValueError(
                        f"'{tok}' not in declared domain of '{name}'")
            vecs[name] = Vec.from_numpy(codes, name, domain=list(typ))
        elif typ == "string":
            vecs[name] = _materialize(raw[c], "enum", name, {"?", ""})
        else:
            vecs[name] = _materialize(raw[c], typ, name, {"?", ""})
    return Frame(vecs)


def import_file(path: str | Sequence[str], sep: str | None = None,
                header: int = -1, col_names: Sequence[str] | None = None,
                col_types: Mapping[str, str] | Sequence[str] | None = None,
                na_strings: Sequence[str] | None = None,
                skipped_columns: Sequence[str] | None = None) -> Frame:
    """h2o.import_file analog: parse CSV/Parquet/ORC file(s) into a
    sharded Frame (format sniffed per file set, like the reference's
    parser-provider guess)."""
    files = _expand_paths(path)
    fmt = _binary_format(files[0])
    if fmt == "avro":
        return _import_avro(files, set(skipped_columns or []))
    if fmt is not None:
        return _import_arrow(files, fmt,
                             col_types if isinstance(col_types, Mapping)
                             else None, set(skipped_columns or []))
    base = files[0].lower()
    for z in (".gz", ".bz2", ".xz"):
        if base.endswith(z):
            base = base[: -len(z)]
    if base.endswith(".arff") or _looks_arff(files[0]):
        return _import_arff(files, set(skipped_columns or []))
    if base.endswith((".svm", ".svmlight", ".libsvm")) or \
            _looks_svmlight(files[0]):
        return _import_svmlight(files, set(skipped_columns or []))
    setup = parse_setup(path, sep=sep, header=header, na_strings=na_strings)
    # copy: uniquification below must not leak into setup["names"], which
    # later files' first records are compared against verbatim
    names = list(col_names) if col_names else list(setup["names"])
    # uniquify duplicate headers like the reference parser (a, a -> a, a2)
    # instead of silently collapsing same-named columns into one dict key
    seen: dict[str, int] = {}
    for i, n in enumerate(names):
        if n in seen:
            while True:          # walk past real headers like a2
                seen[n] += 1
                cand = f"{n}{seen[n]}"
                if cand not in names and cand not in seen:
                    break
            names[i] = cand
        seen.setdefault(names[i], 1)
    types = list(setup["types"])
    if col_types:
        if isinstance(col_types, Mapping):
            for n, t in col_types.items():
                types[names.index(n)] = _norm_type(t)
        else:
            types = [_norm_type(t) for t in col_types]
    skipped = set(skipped_columns or [])
    nas = setup["na_strings"]
    ncol = len(names)

    if _arrow_csv_eligible(setup, names, types):
        try:
            return _import_csv_arrow(setup, names, types, skipped)
        except Exception:
            # the pure-Python path below DEFINES the parse semantics;
            # anything arrow rejects (ragged rows, unparseable floats,
            # exotic quoting) re-parses there
            pass

    raw: list[list[str]] = [[] for _ in range(ncol)]
    for fi, fp in enumerate(setup["files"]):
        with _open_text(fp) as f:
            it = _read_records(f)
            if setup["header"]:
                if fi == 0:
                    next(it, None)
                else:
                    # later files in a multi-file parse may be headerless
                    # continuations: only drop the first record when it
                    # repeats the header (the reference checks each file's
                    # first line against the ParseSetup columns)
                    first = next(it, None)
                    if first is not None:
                        toks = _split_line(first, setup["sep"])
                        if [t.strip() for t in toks] != setup["names"]:
                            it = itertools.chain([first], it)
            for lineno, ln in enumerate(it, start=1):
                toks = _split_line(ln, setup["sep"])
                if len(toks) != ncol:
                    # fail loudly like ParseDataset on column-count
                    # breaks — BOTH directions: a short row is how a
                    # stream truncated mid-record presents, and
                    # silently padding it with NAs would ship a
                    # corrupted frame (tools/chaos.py
                    # ingest-truncated-csv rehearses exactly this)
                    raise ValueError(
                        f"{fp}:{lineno}: {len(toks)} columns, expected "
                        f"{ncol}")
                for c in range(ncol):
                    raw[c].append(toks[c])

    vecs: dict[str, Vec] = {}
    for c, (name, typ) in enumerate(zip(names, types)):
        if name in skipped:
            continue
        vecs[name] = _materialize(raw[c], typ, name, nas)
    return Frame(vecs)


class _EnumAcc:
    """Streaming categorical interner: per-batch dictionary-encoded
    chunks remapped through a growing first-seen LUT of STRIPPED
    tokens; finalize() sorts the domain and remaps once — exactly the
    strip + lowercase-NA + sorted-domain semantics of the pure-Python
    `_materialize`, paid per batch dictionary (small) instead of per
    row."""

    def __init__(self, nas: set[str]):
        self.nas = nas
        self.lut: dict[str, int] = {}
        self.chunks: list[np.ndarray] = []

    def add(self, col) -> None:
        enc = col.dictionary_encode()
        codes = np.nan_to_num(
            enc.indices.to_numpy(zero_copy_only=False).astype(
                np.float64), nan=-1).astype(np.int64)
        remap = np.empty(len(enc.dictionary) + 1, dtype=np.int32)
        remap[-1] = NA_ENUM
        for old, tok in enumerate(enc.dictionary.to_pylist()):
            tok = str(tok).strip()
            if tok.lower() in self.nas:
                remap[old] = NA_ENUM
            else:
                remap[old] = self.lut.setdefault(tok, len(self.lut))
        self.chunks.append(remap[codes])

    def finalize(self, name: str) -> Vec:
        codes = np.concatenate(self.chunks) if self.chunks else \
            np.empty(0, dtype=np.int32)
        self.chunks = []
        return _lut_to_vec(codes, self.lut, name)


class _TimeAcc:
    """Streaming time-column parser: per-batch host parse through the
    shared _parse_time_ms formats into float64 epoch-ms chunks."""

    def __init__(self, nas: set[str]):
        self.nas = nas
        self.chunks: list[np.ndarray] = []

    def add(self, col) -> None:
        vals = col.to_pylist()
        out = np.empty(len(vals), dtype=np.float64)
        for i, v in enumerate(vals):
            tok = "" if v is None else v
            ms = None if _is_na(tok, self.nas) else _parse_time_ms(tok)
            out[i] = np.nan if ms is None else ms
        self.chunks.append(out)

    def finalize(self, name: str) -> Vec:
        a = np.concatenate(self.chunks) if self.chunks else \
            np.empty(0, dtype=np.float64)
        self.chunks = []
        return Vec.from_numpy(a, name, kind="time")


class _NumAcc:
    def __init__(self):
        self.chunks: list[np.ndarray] = []

    def add(self, col) -> None:
        self.chunks.append(np.asarray(
            col.to_numpy(zero_copy_only=False), dtype=np.float32))

    def finalize(self, name: str) -> Vec:
        a = np.concatenate(self.chunks) if self.chunks else \
            np.empty(0, dtype=np.float32)
        self.chunks = []
        return Vec.from_numpy(a, name)


def _import_csv_arrow(setup: dict, names: list[str], types: list[str],
                      skipped: set[str]) -> Frame:
    """10M-row-capable CSV fast path, STREAMED: pyarrow's C++ CSV
    reader tokenizes and converts one record batch at a time
    (`pacsv.open_csv`), and each batch lands chunk-wise in per-column
    accumulators — host peak beyond the final typed columns is
    O(batch), never a whole-file pyarrow Table (the round-5 monolithic
    `read_csv` held the table + pylists + numpy copies at once). Our
    preview pass keeps type-inference semantics (the reference's
    analog is the chunk-parallel ParseDataset over NewChunks,
    water/parser/ [U3]). Batch bytes: H2O_TPU_INGEST_CHUNK_BYTES
    (default 16 MiB).

    Eligibility is decided by the caller; any arrow-level failure
    (ragged rows, unparseable numerics, unsupported codec — including
    a stream TRUNCATED mid-record) raises and the caller falls back to
    the pure-Python path, which defines the parse semantics and fails
    a truncated file loudly rather than shipping a short frame."""
    import pyarrow as pa
    import pyarrow.csv as pacsv

    nas = setup["na_strings"]
    # arrow null matching is exact; cover the case variants of our
    # lowercase token set (the slow path lowercases before comparing)
    null_values = sorted({v for t in nas for v in
                          (t, t.upper(), t.capitalize(), t.title())})
    col_types: dict[str, pa.DataType] = {}
    time_cols = set()
    for name, typ in zip(names, types):
        if typ == "numeric":
            col_types[name] = pa.float32()
        else:
            # enum AND time columns land as strings; time parsing uses
            # the shared _parse_time_ms formats host-side (rare columns
            # — the 10M-row cost is numeric/enum, which stay in C++)
            col_types[name] = pa.string()
            if typ == "time":
                time_cols.add(name)

    keep = [n for n in names if n not in skipped]
    acc: dict[str, object] = {}
    for name, typ in zip(names, types):
        if name in skipped:
            continue
        acc[name] = _NumAcc() if typ == "numeric" else \
            _TimeAcc(nas) if name in time_cols else _EnumAcc(nas)

    try:
        block = int(os.environ.get("H2O_TPU_INGEST_CHUNK_BYTES",
                                   16 << 20))
    except ValueError:
        # a typo'd knob must not silently demote every ingest to the
        # ~10x-slower pure-Python fallback (the caller's blanket
        # except would eat the ValueError as "arrow failed")
        block = 16 << 20
    for fi, fp in enumerate(setup["files"]):
        # arrow's skip_rows counts PHYSICAL lines while the slow path
        # skips blank lines anywhere — count the leading blank/
        # whitespace-only lines so the header row is the one skipped
        blanks = 0
        with _open_text(fp) as f:
            for ln in f:
                if ln.strip():
                    break
                blanks += 1
        skip = blanks
        if setup["header"]:
            if fi == 0:
                skip += 1
            else:
                # later files may be headerless continuations (same
                # check as the slow path): drop the first record only
                # when it repeats the header
                with _open_text(fp) as f:
                    first = next(_read_records(f, limit=1), None)
                if first is not None and [
                        t.strip() for t in
                        _split_line(first, setup["sep"])] == setup["names"]:
                    skip += 1
        # pa.input_stream decompresses gz/bz2 by extension; xz is
        # rejected by the caller's eligibility check
        with pa.input_stream(fp, compression="detect") as stream:
            reader = pacsv.open_csv(
                stream,
                read_options=pacsv.ReadOptions(
                    column_names=names, skip_rows=skip,
                    block_size=block),
                parse_options=pacsv.ParseOptions(
                    delimiter=setup["sep"], newlines_in_values=True),
                convert_options=pacsv.ConvertOptions(
                    column_types=col_types, null_values=null_values,
                    strings_can_be_null=True,
                    quoted_strings_can_be_null=False,
                    # drop skipped columns inside the reader — at 10M
                    # rows their C++ conversion is real money
                    include_columns=keep))
            with reader:
                for batch in reader:
                    for name in keep:
                        acc[name].add(
                            batch.column(batch.schema.get_field_index(
                                name)))

    vecs: dict[str, Vec] = {}
    for name in names:
        if name in skipped:
            continue
        vecs[name] = acc.pop(name).finalize(name)
    return Frame(vecs)


def _arrow_csv_eligible(setup: dict, names: list[str],
                        types: list[str]) -> bool:
    """The fast path must only run where it reproduces the slow path's
    semantics: single-char separator, no xz/lzma (arrow can't detect
    it), pyarrow importable, and not disabled via env."""
    if os.environ.get("H2O_TPU_ARROW_CSV", "1") == "0":
        return False
    # MAIN THREAD ONLY: pyarrow materialization segfaulted (flaky,
    # ~3-in-4 module runs) when this path ran inside a REST handler
    # thread on a 1-core box (tests/test_rest.py::
    # test_model_detail_fields; crash stack in _import_csv_arrow), and
    # ReadOptions(use_threads=False) did NOT cure it — so server-side
    # imports take the pure-Python parser, and the 10M-row fast reader
    # stays a Python-API (main-thread) feature. Narrowing this guard
    # needs a root cause, not another heuristic.
    import threading

    if threading.current_thread() is not threading.main_thread():
        return False
    # whitespace-only lines are records to arrow but skipped by the
    # slow path; with >= 2 columns they raise a column-count error and
    # fall back, but a 1-column frame (or space separator) would
    # silently grow NA rows instead
    if len(names) < 2 or setup["sep"] == " ":
        return False
    if len(setup["sep"]) != 1:
        return False
    if any(f.lower().endswith((".xz", ".lzma")) for f in setup["files"]):
        return False
    if len(set(names)) != len(names):
        return False
    try:
        import pyarrow.csv  # noqa: F401
    except ImportError:
        return False
    return True


def _norm_type(t: str) -> str:
    t = t.lower()
    return {"real": "numeric", "int": "numeric", "float": "numeric",
            "factor": "enum", "categorical": "enum", "string": "enum",
            }.get(t, t)


def _materialize(vals: list[str], typ: str, name: str,
                 nas: set[str]) -> Vec:
    n = len(vals)
    if typ == "numeric":
        out = np.empty(n, dtype=np.float32)
        for i, tok in enumerate(vals):
            if _is_na(tok, nas):
                out[i] = np.nan
            else:
                f = _try_float(tok)
                out[i] = np.nan if f is None else f
        return Vec.from_numpy(out, name)
    if typ == "time":
        out = np.empty(n, dtype=np.float64)
        for i, tok in enumerate(vals):
            ms = None if _is_na(tok, nas) else _parse_time_ms(tok)
            out[i] = np.nan if ms is None else ms
        return Vec.from_numpy(out, name, kind="time")
    # enum: intern strings host-side, codes to device; domain sorted
    # alphabetically like the reference's categorical domains
    lut: dict[str, int] = {}
    codes = np.empty(n, dtype=np.int32)
    for i, tok in enumerate(vals):
        tok = tok.strip()
        if _is_na(tok, nas):
            codes[i] = NA_ENUM
        else:
            codes[i] = lut.setdefault(tok, len(lut))
    return _lut_to_vec(codes, lut, name)


def _lut_to_vec(codes: np.ndarray, lut: dict[str, int], name: str) -> Vec:
    """First-seen intern codes (-1 = NA) → Vec with a SORTED domain —
    the one remap implementation shared by the CSV/ARFF and Avro
    interning paths."""
    domain = sorted(lut)
    order = {tok: i for i, tok in enumerate(domain)}
    remap = np.empty(len(lut) + 1, dtype=np.int32)
    remap[-1] = NA_ENUM
    for tok, old in lut.items():
        remap[old] = order[tok]
    return Vec.from_numpy(remap[codes], name, domain=domain)
