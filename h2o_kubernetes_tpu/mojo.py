"""MOJO-analog: standalone scoring artifacts, pure numpy at score time.

Reference: h2o-genmodel + ModelMojoWriter (SURVEY.md §2b C18) — a model
exports to a self-contained artifact scoreable WITHOUT a running
cluster. Here the artifact is a zip of npz arrays + JSON metadata, and
`MojoModel` scores it with numpy only (no jax import needed), so the
artifact runs on any serving host.

Supported: GBM / DRF / XGBoost (trees + bin edges), GLM (beta + design
layout, all families/links incl. multinomial), KMeans (centers),
DeepLearning (layer weights; MLP, softmax and autoencoder modes),
NaiveBayes (priors + likelihood tables), PCA (eigenvectors),
Word2Vec (embeddings + vocab with word_vector/find_synonyms accessors),
IsolationForest, CoxPH (linear log-hazard), GLRM (archetypes; predict
gives the per-row factor projection, reconstruct() the imputed frame),
TargetEncoder (transform() applies the fitted level→encoding tables),
and StackedEnsemble (every base-model MOJO plus the metalearner MOJO
nested in one artifact — the AutoML leader exports whole).
"""

from __future__ import annotations

import io
import json
import zipfile

import numpy as np

__all__ = ["export_mojo", "import_mojo", "MojoModel", "MOJO_FORMAT",
           "read_mojo_parts"]

# format 2: tree ensembles carry the flattened serving arrays
# (flat_*) instead of heap tree_* + bin edges — bumped so an OLD
# reader rejects a new artifact cleanly instead of KeyError-ing deep
# in its scorer; THIS reader accepts both (legacy branch kept)
_FORMAT = "h2o_kubernetes_tpu/mojo/2"
_READABLE_FORMATS = ("h2o_kubernetes_tpu/mojo/1", _FORMAT)

# public name for consumers that must pin the CURRENT format (the
# operator model registry only ships v2 artifacts: replicas serve the
# flat_* arrays directly, so a v1 artifact has nothing to serve)
MOJO_FORMAT = _FORMAT


def _np(a):
    return np.asarray(a)


def _export_dinfo(meta: dict, arrays: dict, d) -> None:
    """Serialize a DataInfo design layout (shared by every expanded-
    design algo: glm/deeplearning/pca/kmeans/coxph/glrm)."""
    meta["numeric_idx"] = list(d.numeric_idx)
    meta["enum_specs"] = [list(s) for s in d.enum_specs]
    meta["drop_first"] = d.drop_first
    arrays["means"] = _np(d.means)
    arrays["stds"] = _np(d.stds)


def export_mojo(model, path) -> str:
    """Write `model` as a standalone scoring artifact at `path` (a
    filesystem path or a binary file-like object)."""
    algo = model.algo
    extra_files: dict[str, bytes] = {}
    # word2vec has no tabular design, so the shared fields are optional
    meta = {
        "format": _FORMAT,
        "algo": algo,
        "feature_names": getattr(model, "feature_names", []),
        "feature_domains": getattr(model, "feature_domains", {}),
        "nclasses": getattr(model, "nclasses", 1),
        "response_domain": getattr(model, "response_domain", None),
        "distribution": getattr(model, "distribution", None),
        # offset-trained models need the per-row offset at scoring time
        # too — omitting it would silently shift every MOJO prediction
        "offset_column": getattr(model, "offset_column", None),
    }
    arrays: dict[str, np.ndarray] = {}
    if algo in ("gbm", "drf", "xgboost"):
        meta["max_depth"] = model.params.max_depth
        meta["nbins"] = model.params.nbins
        meta["drf_mode"] = bool(model.params._drf_mode)
        meta["ntrees"] = model.ntrees
        meta["na_bin"] = model.bin_spec.na_bin
        meta["margin_scale"] = float(getattr(model, "margin_scale", 1.0))
        arrays["init_score"] = _np(model.init_score)
        arrays["enum_mask"] = _np(model._enum_mask)
        # the SAME flattening the in-process serving scorer descends
        # (models/tree/core.py flatten_trees, cached on the model):
        # compact reachable nodes + raw-feature thresholds — the
        # artifact scores without bin edges or re-binning
        flat = model._flat()
        for f in ("split_feat", "thresh", "left", "na_left", "value"):
            arrays[f"flat_{f}"] = _np(getattr(flat, f))
        # OPTIONAL cover part (still format 2 — extra npz keys are
        # invisible to older readers): per-flat-node training weight
        # mass, slot-aligned with the arrays above, which is all a
        # scorer replica needs to serve predict_contributions
        # (TreeSHAP path tables). Omitted when the source model
        # predates per-node cover (persist.py NaN-backfill sentinel) —
        # such artifacts keep serving margins and reject contributions
        # with a re-export message.
        cov = getattr(model.trees, "cover", None)
        if cov is not None and not np.isnan(_np(cov)).any():
            from .models.tree.core import flatten_cover

            arrays["flat_cover"] = flatten_cover(
                model.trees, model.params.max_depth)
    elif algo == "glm":
        from .models.glm import _famspec

        meta["family"] = model.params.family
        meta["link"] = _famspec(model.params).link
        arrays["beta"] = _np(model.beta)
        d = model.dinfo
        _export_dinfo(meta, arrays, d)
    elif algo == "deeplearning":
        meta["activation"] = model.params.activation
        meta["loss_kind"] = model.loss_kind
        meta["autoencoder"] = bool(model.params.autoencoder)
        meta["n_layers"] = len(model.net)
        d = model.dinfo
        _export_dinfo(meta, arrays, d)
        for i, lyr in enumerate(model.net):
            arrays[f"net_{i}_w"] = _np(lyr["w"])
            arrays[f"net_{i}_b"] = _np(lyr["b"])
    elif algo == "naivebayes":
        meta["num_cols"] = list(model.num_cols)
        meta["enum_cols"] = list(model.enum_cols)
        meta["n_enum_tables"] = len(model.enum_tables)
        arrays["priors"] = _np(model.priors)
        arrays["num_mean"] = _np(model.num_mean)
        arrays["num_sd"] = _np(model.num_sd)
        for i, tab in enumerate(model.enum_tables):
            arrays[f"nbtab_{i}"] = _np(tab)
    elif algo == "pca":
        d = model.dinfo
        _export_dinfo(meta, arrays, d)
        arrays["eigenvectors"] = _np(model.eigenvectors)
        arrays["eigenvalues"] = _np(model.eigenvalues)
    elif algo == "word2vec":
        meta["vocab"] = list(model.vocab)
        arrays["embeddings"] = _np(model.W)
    elif algo == "kmeans":
        arrays["centers"] = _np(model.centers_std)
        d = model.dinfo
        _export_dinfo(meta, arrays, d)
    elif algo == "isolationforest":
        meta["max_depth"] = model.params.max_depth
        meta["ntrees"] = model.ntrees
        meta["sample_size_effective"] = int(model.sample_size_effective)
        for f in ("split_feat", "split_val", "is_split", "count"):
            arrays[f"iso_{f}"] = _np(getattr(model.trees, f))
    elif algo == "coxph":
        # hex/coxph scoring is the linear log-hazard Xe·beta (SURVEY.md
        # §2b C17); the artifact is the expanded-design layout + beta
        d = model.dinfo
        _export_dinfo(meta, arrays, d)
        arrays["beta"] = _np(model.beta)
    elif algo == "glrm":
        # archetypes V + design layout: scoring solves the per-row
        # ridge U-step against fixed V (models/glrm.py::_solve_u)
        d = model.dinfo
        _export_dinfo(meta, arrays, d)
        meta["coef_names"] = list(d.coef_names[:-1])
        arrays["V"] = _np(model.V)
    elif algo == "targetencoder":
        # level→encoding tables; mojo transform is the SCORING path
        # (full-data stats, no leakage handling / noise — matching the
        # reference's TE mojo)
        p = model.params
        meta["te_columns"] = list(model.columns)
        meta["prior"] = float(model.prior)
        meta["blending"] = bool(p.blending)
        meta["inflection_point"] = float(p.inflection_point)
        meta["smoothing"] = float(p.smoothing)
        meta["te_domains"] = {c: list(model.tables[c]["domain"])
                              for c in model.columns}
        for i, c in enumerate(model.columns):
            arrays[f"te_sum_{i}"] = _np(model.tables[c]["sum"])
            arrays[f"te_cnt_{i}"] = _np(model.tables[c]["cnt"])
    elif algo == "stackedensemble":
        # one artifact nests every base model's MOJO plus the
        # metalearner's (reference: StackedEnsembleMojoWriter packs the
        # base mojos into the ensemble zip, SURVEY.md §2b C18) — so the
        # AutoML leader is servable even when it is an ensemble
        meta["base_tags"] = list(model.base_tags)
        meta["base_count"] = len(model.base_models)
        for i, bm in enumerate(model.base_models):
            buf = io.BytesIO()
            export_mojo(bm, buf)
            extra_files[f"base_{i}.mojo"] = buf.getvalue()
        buf = io.BytesIO()
        export_mojo(model.metalearner, buf)
        extra_files["metalearner.mojo"] = buf.getvalue()
    else:
        raise ValueError(f"mojo export not supported for algo '{algo}'")

    npz = io.BytesIO()
    np.savez_compressed(npz, **arrays)
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("model.json", json.dumps(meta))
        z.writestr("arrays.npz", npz.getvalue())
        for name, blob in extra_files.items():
            z.writestr(name, blob)
    return path


def import_mojo(path: str) -> "MojoModel":
    return MojoModel(path)


def read_mojo_parts(path, want_nested: bool = False
                    ) -> tuple[dict, dict, dict]:
    """(meta, arrays, nested) of a mojo artifact without building a
    scorer — the shared reader for MojoModel and the operator model
    registry (operator/registry.py validates the format/algo and wraps
    the arrays in a jitted serving scorer instead of numpy descent).

    ``nested`` holds the inner ``*.mojo`` blobs of a stackedensemble
    artifact when ``want_nested``; empty otherwise."""
    with zipfile.ZipFile(path) as z:
        meta = json.loads(z.read("model.json"))
        if meta.get("format") not in _READABLE_FORMATS:
            raise ValueError(f"not a {_FORMAT} artifact "
                             f"(format={meta.get('format')!r})")
        with np.load(io.BytesIO(z.read("arrays.npz"))) as npz:
            arrays = {k: npz[k] for k in npz.files}
        nested = {}
        if want_nested:
            nested = {n: z.read(n) for n in z.namelist()
                      if n.endswith(".mojo")}
    return meta, arrays, nested


class MojoModel:
    """Loads and scores a mojo artifact with numpy only."""

    def __init__(self, path):
        self.meta, self.arrays, nested = read_mojo_parts(
            path, want_nested=True)
        if self.meta["algo"] == "stackedensemble":
            self._base = [
                MojoModel(io.BytesIO(nested[f"base_{i}.mojo"]))
                for i in range(self.meta["base_count"])]
            self._metalearner = MojoModel(
                io.BytesIO(nested["metalearner.mojo"]))
        self.algo = self.meta["algo"]
        self.feature_names = self.meta["feature_names"]
        self.nclasses = self.meta["nclasses"]
        if self.algo == "word2vec":   # O(1) lookups on large vocabs
            self._word_index = {w: i for i, w in
                                enumerate(self.meta["vocab"])}

    # -- feature matrix from a dict of columns ------------------------------

    def _matrix(self, data) -> np.ndarray:
        """data: mapping name -> array (numeric values or string levels),
        or a Frame (columns decoded to raw values first — scoring-frame
        enum codes are NOT assumed to share the training domain)."""
        if hasattr(data, "vec") and hasattr(data, "names"):
            decoded = {}
            tdoms = self.meta["feature_domains"]
            for n in self.feature_names:
                if n not in data.names:
                    raise ValueError(f"missing feature column '{n}'")
                v = data.vec(n)
                # kind mismatches raise exactly like the in-process
                # Model._design_matrix — silently treating numerics as
                # category codes (or vice versa) scores garbage
                if tdoms.get(n) is not None and not v.is_enum():
                    raise ValueError(
                        f"column '{n}' was categorical at training time "
                        f"but is {v.kind} in the scoring frame")
                if tdoms.get(n) is None and v.is_enum():
                    raise ValueError(
                        f"column '{n}' was numeric at training time "
                        "but is categorical in the scoring frame")
                if v.is_enum():
                    dom = np.array(list(v.domain or []) + [None],
                                   dtype=object)
                    codes = v.to_numpy()
                    decoded[n] = dom[np.where(codes < 0, len(dom) - 1,
                                              codes)]
                elif v.kind == "time":
                    # reproduce as_float() f32 rounding (rel + f32
                    # origin) — training bin edges were fit on those
                    # values, and exact float64 epochs can land a
                    # boundary timestamp in a different bin
                    ms = v.to_numpy()
                    rel = (ms - v.origin).astype(np.float32)
                    decoded[n] = rel + np.float32(v.origin)
                else:
                    decoded[n] = v.to_numpy()
            data = decoded
        cols = []
        doms = self.meta["feature_domains"]
        for name in self.feature_names:
            if name not in data:
                raise ValueError(f"missing feature column '{name}'")
            col = np.asarray(data[name])
            dom = doms.get(name)
            if dom is not None and col.dtype.kind in ("U", "S", "O"):
                lut = {d: i for i, d in enumerate(dom)}
                col = np.array([lut.get(str(s), -1) for s in col],
                               dtype=np.float32)
                col[col < 0] = np.nan
            cols.append(col.astype(np.float32))
        return np.stack(cols, axis=1)

    def predict(self, data) -> np.ndarray:
        """[n, K] probabilities / [n] predictions / [n] cluster ids."""
        if self.algo == "stackedensemble":
            # bases consume the raw columns themselves — no shared
            # design matrix exists at the ensemble level
            return self._predict_se(data)
        if self.algo == "targetencoder":
            raise ValueError(
                "targetencoder artifacts score via transform(), not "
                "predict()")
        off = self._offset(data)
        X = self._matrix(data) if not isinstance(data, np.ndarray) \
            else data.astype(np.float32)
        if self.algo in ("gbm", "drf", "xgboost"):
            return self._predict_trees(X, off)
        if self.algo == "glm":
            return self._predict_glm(X, off)
        if self.algo == "kmeans":
            return self._predict_kmeans(X)
        if self.algo == "deeplearning":
            return self._predict_deeplearning(X, off)
        if self.algo == "naivebayes":
            return self._predict_naivebayes(X)
        if self.algo == "pca":
            return self._predict_pca(X)
        if self.algo == "isolationforest":
            return self._predict_isolationforest(X)
        if self.algo == "coxph":
            return self._predict_coxph(X)
        if self.algo == "glrm":
            return self._solve_u_glrm(X)
        raise ValueError(self.algo)

    def _offset(self, data) -> np.ndarray | None:
        """Per-row offset for offset-trained artifacts (same contract
        as the in-process Model.predict_raw: the column must be
        supplied at scoring time; NA offsets propagate as NaN)."""
        oc = self.meta.get("offset_column")
        if not oc:
            return None
        if isinstance(data, np.ndarray):
            raise ValueError(
                f"this artifact was trained with offset_column='{oc}'; "
                "score with a dict/Frame including that column, not a "
                "bare matrix")
        if hasattr(data, "vec") and hasattr(data, "names"):
            if oc not in data.names:
                raise ValueError(f"offset column '{oc}' missing from "
                                 "the scoring frame")
            return data.vec(oc).to_numpy().astype(np.float64)
        if oc not in data:
            raise ValueError(f"offset column '{oc}' missing from the "
                             "scoring data")
        return np.asarray(data[oc], dtype=np.float64)

    def _predict_se(self, data):
        """Run every base MOJO, assemble the level-one columns exactly
        like models/stackedensemble.py::_level_one_columns, then run
        the metalearner MOJO on them."""
        cols: dict[str, np.ndarray] = {}
        for bm, tag in zip(self._base, self.meta["base_tags"]):
            preds = bm.predict(data)
            if bm.nclasses == 2:
                cols[tag] = preds[:, 1]
            elif bm.nclasses > 2:
                for k in range(bm.nclasses):
                    cols[f"{tag}_p{k}"] = preds[:, k]
            else:
                cols[tag] = preds
        return self._metalearner.predict(cols)

    def _predict_coxph(self, X):
        """Linear log-hazard Xe·beta (CoxPHModel._score_matrix)."""
        return self._expand(X)[:, :-1] @ self.arrays["beta"]

    def _solve_u_glrm(self, X):
        """[n, k] row factors: per-row ridge solve against fixed V —
        numpy mirror of GLRMModel._solve_u, with the observed mask from
        the RAW matrix (expand mean-imputes, so the mask must not come
        from the expanded values)."""
        m = self.meta
        Xe = self._expand(X)[:, :-1]
        cols = [~np.isnan(X[:, i]) for i in m["numeric_idx"]]
        mats = [np.stack(cols, axis=1)] if cols else []
        for (i, L, has_na, mode) in m["enum_specs"]:
            ok = ~np.isnan(X[:, i])
            width = L - (1 if m["drop_first"] else 0) + (1 if has_na
                                                         else 0)
            mats.append(np.broadcast_to(ok[:, None], (X.shape[0], width)))
        mask = np.concatenate(mats, axis=1).astype(np.float32)
        Xz = np.nan_to_num(Xe) * mask
        V = self.arrays["V"]
        G = V.T @ V + 1e-6 * np.eye(V.shape[1], dtype=V.dtype)
        return Xz @ V @ np.linalg.inv(G)

    def reconstruct(self, data) -> dict[str, np.ndarray]:
        """GLRM imputation: U·Vᵀ in the expanded layout, keyed by
        coefficient name (GLRMModel.reconstruct analog)."""
        if self.algo != "glrm":
            raise ValueError("reconstruct() is a glrm accessor")
        X = self._matrix(data) if not isinstance(data, np.ndarray) \
            else data.astype(np.float32)
        rec = self._solve_u_glrm(X) @ self.arrays["V"].T
        return {f"reconstr_{n}": rec[:, i]
                for i, n in enumerate(self.meta["coef_names"])}

    def transform(self, data) -> dict[str, np.ndarray]:
        """TargetEncoder scoring transform: `<col>_te` encodings from
        the fitted full-data tables (no leakage handling, no noise —
        the TargetEncoderModel.transform(as_training=False) path)."""
        if self.algo != "targetencoder":
            raise ValueError("transform() is a targetencoder accessor")
        m = self.meta
        out: dict[str, np.ndarray] = {}
        for i, col in enumerate(m["te_columns"]):
            dom = m["te_domains"][col]
            if hasattr(data, "vec") and hasattr(data, "names"):
                v = data.vec(col)
                if not v.is_enum():
                    # same kind-mismatch contract as the in-process
                    # TargetEncoderModel._codes_for — str()-ifying
                    # numerics would silently encode every row as the
                    # prior (no domain string matches '1.0')
                    raise ValueError(f"'{col}' is not categorical")
                doms = list(v.domain or [])
                raw = v.to_numpy().astype(np.int64)
                vals = np.array(doms + [None], dtype=object)[
                    np.where(raw < 0, len(doms), raw)]
            else:
                vals = np.asarray(data[col])
                if vals.dtype.kind not in ("U", "S", "O"):
                    raise ValueError(f"'{col}' is not categorical")
            lut = {d: j for j, d in enumerate(dom)}
            codes = np.array([lut.get(str(s), -1) if s is not None
                              else -1 for s in vals], dtype=np.int64)
            sums = self.arrays[f"te_sum_{i}"].astype(np.float64)
            cnts = self.arrays[f"te_cnt_{i}"].astype(np.float64)
            mean = sums / np.maximum(cnts, 1.0)
            if m["blending"]:
                lam = 1.0 / (1.0 + np.exp(
                    -(cnts - m["inflection_point"])
                    / max(m["smoothing"], 1e-12)))
                enc_tab = lam * mean + (1.0 - lam) * m["prior"]
            else:
                enc_tab = mean
            enc_tab = np.where(cnts > 0, enc_tab, m["prior"])
            enc = np.where(codes >= 0, enc_tab[np.maximum(codes, 0)],
                           m["prior"])
            out[f"{col}_te"] = enc.astype(np.float32)
        return out

    def _predict_isolationforest(self, X):
        """[n, 2] (anomaly score, mean path length) — numpy mirror of
        IsolationForestModel._score_matrix (models/isolationforest.py)."""
        m = self.meta
        sf = self.arrays["iso_split_feat"]       # [T, N]
        sv = self.arrays["iso_split_val"]
        sp = self.arrays["iso_is_split"]
        cnt = self.arrays["iso_count"]
        Xf = np.nan_to_num(X.astype(np.float32))
        n = Xf.shape[0]

        def c_avg(x):
            x = np.maximum(x, 2.0)
            return (2.0 * (np.log(x - 1.0) + 0.5772156649)
                    - 2.0 * (x - 1.0) / x)

        total = np.zeros(n, dtype=np.float64)
        for t in range(m["ntrees"]):
            node = np.zeros(n, dtype=np.int64)
            depth = np.zeros(n, dtype=np.float64)
            for _ in range(m["max_depth"]):
                f = sf[t][node]
                v = sv[t][node]
                split = sp[t][node]
                rowval = Xf[np.arange(n), np.maximum(f, 0)]
                child = 2 * node + 1 + (rowval >= v).astype(np.int64)
                node = np.where(split, child, node)
                depth += split.astype(np.float64)
            leaf_n = cnt[t][node]
            total += depth + np.where(leaf_n > 1.0, c_avg(leaf_n), 0.0)
        mean_len = total / m["ntrees"]
        score = np.exp2(-mean_len / c_avg(
            np.float64(m["sample_size_effective"])))
        return np.stack([score, mean_len], axis=1).astype(np.float32)

    # -- word2vec accessors (no row scoring; embeddings ARE the model) ------

    def word_vector(self, word: str) -> np.ndarray:
        if self.algo != "word2vec":
            raise ValueError("word_vector() is a word2vec accessor")
        if word not in self._word_index:
            raise KeyError(word)
        return self.arrays["embeddings"][self._word_index[word]]

    def find_synonyms(self, word: str, count: int = 10) -> dict:
        if self.algo != "word2vec":
            raise ValueError("find_synonyms() is a word2vec accessor")
        W = self.arrays["embeddings"]
        vocab = self.meta["vocab"]
        v = self.word_vector(word)
        sims = (W @ v) / (np.linalg.norm(W, axis=1) *
                          np.linalg.norm(v) + 1e-12)
        order = np.argsort(-sims)
        out = {}
        for i in order:
            if vocab[i] == word:
                continue
            out[vocab[i]] = float(sims[i])
            if len(out) >= count:
                break
        return out

    # -- scorers -------------------------------------------------------------

    def _expand(self, X):
        """DataInfo.expand re-implemented in numpy (glm / kmeans)."""
        m = self.meta
        means, stds = self.arrays["means"], self.arrays["stds"]
        out = []
        for j, i in enumerate(m["numeric_idx"]):
            c = X[:, i].copy()
            c[np.isnan(c)] = means[j]
            out.append((c - means[j]) / stds[j])
        mats = [np.stack(out, axis=1)] if out else []
        for (i, L, has_na, mode) in m["enum_specs"]:
            c = X[:, i]
            code = np.where(np.isnan(c), L, c).astype(np.int32)
            if not has_na:
                code = np.where(code >= L, mode, code)
            lo = 1 if m["drop_first"] else 0
            width = L - lo + (1 if has_na else 0)
            levels = np.arange(lo, lo + width)
            mats.append((code[:, None] == levels[None, :])
                        .astype(np.float32))
        mats.append(np.ones((X.shape[0], 1), dtype=np.float32))
        return np.concatenate(mats, axis=1)

    def _bin(self, X):
        edges = self.arrays["edges"]
        enum_mask = self.arrays["enum_mask"]
        na_bin = self.meta["na_bin"]
        out = np.empty(X.shape, dtype=np.int32)
        for f in range(X.shape[1]):
            col = X[:, f]
            if enum_mask[f]:
                b = np.clip(np.nan_to_num(col, nan=-1), -1,
                            na_bin - 1).astype(np.int32)
                b[(col < 0) | np.isnan(col)] = na_bin
            else:
                b = np.searchsorted(edges[f], col, side="right")
                b = b.astype(np.int32)
                b[np.isnan(col)] = na_bin
            out[:, f] = b
        return out

    def _predict_trees(self, X, off=None):
        if "flat_split_feat" in self.arrays:
            totals = self._tree_totals_flat(X)
        else:            # artifact written by a pre-flattening build
            totals = self._tree_totals_binned(X)
        return self._combine_tree_totals(totals, off)

    def _tree_totals_flat(self, X):
        """[n, K] per-class leaf-value sums over the flattened ensemble
        (raw-feature thresholds; no binning) — the numpy mirror of
        models/tree/core.py flat_margin, same descent decisions."""
        m = self.meta
        sf = self.arrays["flat_split_feat"]      # [T, M]
        th = self.arrays["flat_thresh"]
        lf = self.arrays["flat_left"]
        nl = self.arrays["flat_na_left"]
        val = self.arrays["flat_value"]
        enum_mask = self.arrays["enum_mask"].astype(bool)
        Xc = np.where(enum_mask[None, :] & (X < 0), np.nan, X)
        T = sf.shape[0]
        n = Xc.shape[0]
        K = m["nclasses"] if m["nclasses"] > 2 else 1
        totals = np.zeros((n, K), dtype=np.float64)
        rows = np.arange(n)
        for t in range(T):
            node = np.zeros(n, dtype=np.int64)
            for _ in range(m["max_depth"]):
                f = sf[t][node]
                x = Xc[rows, np.maximum(f, 0)]
                with np.errstate(invalid="ignore"):
                    go_right = np.where(np.isnan(x), ~nl[t][node],
                                        x >= th[t][node])
                child = lf[t][node] + go_right.astype(np.int64)
                node = np.where(f >= 0, child, node)
            totals[:, t % K] += val[t][node]
        return totals

    def _tree_totals_binned(self, X):
        """Legacy-artifact scorer: re-bin, then heap re-descent."""
        m = self.meta
        binned = self._bin(X)
        sf = self.arrays["tree_split_feat"]      # [T, N]
        sb = self.arrays["tree_split_bin"]
        nl = self.arrays["tree_na_left"]
        sp = self.arrays["tree_is_split"]
        val = self.arrays["tree_value"]
        T = sf.shape[0]
        n = binned.shape[0]
        na_bin = m["na_bin"]
        K = m["nclasses"] if m["nclasses"] > 2 else 1
        totals = np.zeros((n, K), dtype=np.float64)
        for t in range(T):
            node = np.zeros(n, dtype=np.int64)
            for _ in range(m["max_depth"]):
                f = sf[t][node]
                b = sb[t][node]
                nleft = nl[t][node]
                split = sp[t][node]
                rowbin = binned[np.arange(n), np.maximum(f, 0)]
                is_na = rowbin == na_bin
                go_right = np.where(is_na, ~nleft, rowbin > b)
                child = 2 * node + 1 + go_right.astype(np.int64)
                node = np.where(split, child, node)
            totals[:, t % K] += val[t][node]
        return totals

    def _combine_tree_totals(self, totals, off=None):
        """Totals -> predictions: init/drf averaging/link, shared by
        the flat and legacy tree scorers."""
        m = self.meta
        T = m["ntrees"]            # total stacked trees (K-interleaved)
        K = m["nclasses"] if m["nclasses"] > 2 else 1
        init = np.atleast_1d(self.arrays["init_score"].astype(np.float64))
        if m["drf_mode"]:
            totals = totals / (T // K)
        probsum = totals + init[None, :]
        if off is not None:
            probsum = probsum + off[:, None]
        d = m["distribution"]
        if d == "bernoulli":
            mgn = probsum[:, 0]
            p1 = np.clip(mgn, 0, 1) if m["drf_mode"] else \
                1.0 / (1.0 + np.exp(-mgn))
            return np.stack([1 - p1, p1], axis=1)
        if d == "multinomial":
            if m["drf_mode"]:
                z = np.clip(probsum, 0, None)
                return z / (z.sum(axis=1, keepdims=True) + 1e-10)
            z = np.exp(probsum - probsum.max(axis=1, keepdims=True))
            return z / z.sum(axis=1, keepdims=True)
        if d in ("poisson", "gamma", "tweedie"):
            return np.exp(probsum[:, 0])
        scale = m.get("margin_scale", 1.0)
        if scale != 1.0:
            # laplace robust scaling never combines with an offset
            # (GBM.train rejects it), so off is None here
            return init[0] + scale * totals[:, 0]
        return probsum[:, 0]

    def _predict_glm(self, X, off=None):
        Xe = self._expand(X)
        eta = Xe @ self.arrays["beta"]
        if off is not None:
            eta = eta + off
        fam = self.meta["family"]
        if fam == "multinomial":
            z = np.exp(eta - eta.max(axis=1, keepdims=True))
            return z / z.sum(axis=1, keepdims=True)
        link = self.meta.get("link", "identity")
        if link == "logit":
            mu = 1.0 / (1.0 + np.exp(-eta))
        elif link == "log":
            mu = np.exp(np.clip(eta, -30, 30))
        elif link == "inverse":
            e = np.where(np.abs(eta) < 1e-6,
                         np.where(eta < 0, -1e-6, 1e-6), eta)
            mu = 1.0 / e
        else:
            mu = eta
        if fam == "binomial":
            return np.stack([1 - mu, mu], axis=1)
        return mu

    def _predict_deeplearning(self, X, off=None):
        m = self.meta
        h = self._expand(X)[:, :-1]          # bias lives in the layers
        act = np.tanh if m["activation"] == "tanh" else \
            (lambda v: np.maximum(v, 0.0))
        L = m["n_layers"]
        for i in range(L - 1):
            h = act(h @ self.arrays[f"net_{i}_w"] +
                    self.arrays[f"net_{i}_b"])
        out = h @ self.arrays[f"net_{L-1}_w"] + self.arrays[f"net_{L-1}_b"]
        if m["loss_kind"] == "ce":
            z = np.exp(out - out.max(axis=1, keepdims=True))
            return z / z.sum(axis=1, keepdims=True)
        if m["autoencoder"]:
            return out
        if off is not None:     # regression net was fit to y - offset
            return out[:, 0] + off
        return out[:, 0]

    def _predict_naivebayes(self, X):
        m = self.meta
        K = m["nclasses"]
        ll = np.broadcast_to(np.log(self.arrays["priors"])[None, :],
                             (X.shape[0], K)).copy()
        if m["num_cols"]:
            Xn = X[:, np.asarray(m["num_cols"])]
            mu, sd = self.arrays["num_mean"], self.arrays["num_sd"]
            z = (Xn[:, None, :] - mu[None]) / sd[None]
            lp = -0.5 * z * z - np.log(sd)[None]
            lp = np.where(np.isnan(Xn)[:, None, :], 0.0, lp)
            ll += lp.sum(axis=2)
        for i, ci in enumerate(m["enum_cols"]):
            tab = self.arrays[f"nbtab_{i}"]
            c = X[:, ci]
            code = np.clip(np.where(np.isnan(c), 0, c).astype(np.int64),
                           0, tab.shape[1] - 1)
            lp = np.log(tab.T)[code]
            ll += np.where(np.isnan(c)[:, None], 0.0, lp)
        mx = ll.max(axis=1, keepdims=True)
        p = np.exp(ll - mx)
        return p / p.sum(axis=1, keepdims=True)

    def _predict_pca(self, X):
        return self._expand(X)[:, :-1] @ self.arrays["eigenvectors"]

    def _predict_kmeans(self, X):
        Xe = self._expand(X)[:, :-1]
        C = self.arrays["centers"]
        d = (Xe * Xe).sum(1)[:, None] - 2 * Xe @ C.T + (C * C).sum(1)[None]
        return d.argmin(axis=1)
