"""Backend liveness probing + virtual-CPU forcing.

The scoreboard files (`bench.py`, `__graft_entry__.py`) must never hang
or crash on a flaky TPU backend: the axon/TPU client init *hangs* (not
errors) when the tunneled chip is unavailable, and an env-level
``JAX_PLATFORMS=cpu`` override is re-asserted by ``sitecustomize`` —
the only reliable controls are an out-of-process probe and an
in-process ``jax.config`` update before first backend use.  This module
is the single shared implementation of both.
"""

from __future__ import annotations

import os
import re
import subprocess
import sys
import threading
import time

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def host_features_fingerprint(cpuinfo_path: str = "/proc/cpuinfo") -> str:
    """Stable short hash of this host's CPU feature set (ISA flags).

    XLA:CPU AOT-compiles with the build host's features: BENCH_r05.json
    caught a cache entry compiled with +amx-*/+avx512* loading on a
    host WITHOUT them ("could lead to execution errors such as
    SIGILL").  jax's persistent-cache key does not include host
    features, so the cache DIRECTORY must — a copied cache dir can then
    never serve a mismatched binary (the lookup simply misses).

    Order-insensitive over the flag set (kernel flag ordering is not
    stable across reboots); falls back to the platform tuple where
    /proc/cpuinfo is unavailable (macOS, containers without procfs)."""
    import hashlib

    feats = ""
    try:
        with open(cpuinfo_path) as f:
            for line in f:
                # x86 says "flags", arm64 says "Features"
                if line.lower().startswith(("flags", "features")):
                    feats = " ".join(sorted(set(
                        line.split(":", 1)[1].split())))
                    break
    except OSError:
        pass
    if not feats:
        import platform

        feats = f"{platform.machine()}|{platform.processor()}"
    return hashlib.sha1(feats.encode()).hexdigest()[:10]


def force_cpu_devices(n: int) -> None:
    """Pin this process to the CPU platform with >= n virtual devices.

    Must run before jax initializes its backends; raises/parses nothing
    if they already exist (callers detect that via device count).
    """
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(rf"{_COUNT_FLAG}=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = f"{flags} {_COUNT_FLAG}={max(n, 1)}".strip()
    elif int(m.group(1)) < n:
        os.environ["XLA_FLAGS"] = flags.replace(
            m.group(0), f"{_COUNT_FLAG}={n}")
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def backends_initialized() -> bool:
    if "jax" not in sys.modules:
        return False
    try:
        import jax._src.xla_bridge as xb

        return bool(xb._backends)  # noqa: SLF001
    except Exception:
        return False


def enable_persistent_compile_cache(
        min_compile_secs: float | None = None) -> None:
    """Point jax's persistent compilation cache at a repo-local dir.

    Every capture tool runs in its own subprocess, so without this each
    one re-pays every XLA compile — and on the tunneled chip a compile
    is a remote round trip. The disk cache keys on hardware + HLO, so
    cross-process reuse is exact; bench warm-up/AutoML cold paths drop
    from minutes of compiles to reads.

    ``min_compile_secs`` (or ``H2O_TPU_PCACHE_MIN_SECS``) overrides
    the 0.5 s persistence threshold. Serving pods pass 0.0: the
    byte-budgeted scorer cache's evict→promote contract ("an eviction
    costs a pcache hit, never a cold compile") needs even sub-second
    tenant-model compiles persisted, or a promotion would silently
    recompile from scratch.

    Never IMPORTS jax (preserving this module's never-hang contract —
    the probe must run before any backend touch): env vars cover a
    not-yet-imported jax, and when jax IS already imported (its config
    no longer reads env) the config is updated through sys.modules,
    which touches no backend. Cache-DIR selection is a no-op when the
    user already set JAX_COMPILATION_CACHE_DIR (their cache policy
    wins), but an explicit ``min_compile_secs`` still applies."""
    if min_compile_secs is None:
        raw = os.environ.get("H2O_TPU_PCACHE_MIN_SECS")
        if raw:
            try:
                min_compile_secs = float(raw)
            except ValueError:
                min_compile_secs = None
    if min_compile_secs is not None:
        os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = \
            str(min_compile_secs)
        j = sys.modules.get("jax")
        if j is not None:
            try:
                j.config.update(
                    "jax_persistent_cache_min_compile_time_secs",
                    float(min_compile_secs))
            except Exception:   # noqa: BLE001 — acceleration only
                pass
    if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        return
    try:
        repo_dir = os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))),
            "tools", "_jax_cache")
        # the repo-relative path only exists in a git checkout; from an
        # installed wheel fall back to a per-user cache dir rather than
        # polluting site-packages' parent (or silently losing caching)
        candidates = [repo_dir] if os.path.isdir(
            os.path.dirname(repo_dir)) else []
        candidates.append(os.path.join(
            os.environ.get("XDG_CACHE_HOME")
            or os.path.join(os.path.expanduser("~"), ".cache"),
            "h2o_tpu_jax_cache"))
        import tempfile
        candidates.append(
            os.path.join(tempfile.gettempdir(), "h2o_tpu_jax_cache"))
        cache_dir = None
        # key the cache dir by host CPU features: an AOT entry compiled
        # with +amx/+avx512 must never load on a host without them
        # (SIGILL class — see host_features_fingerprint)
        fp = f"hostfp-{host_features_fingerprint()}"
        for cand in candidates:
            cand = os.path.join(cand, fp)
            try:
                os.makedirs(cand, exist_ok=True)
                # pid suffix: two capture tools probing the shared repo
                # cache concurrently must not delete each other's probe
                probe = os.path.join(cand, f".writable.{os.getpid()}")
                with open(probe, "w") as f:
                    f.write("")
                os.remove(probe)
                cache_dir = cand
                break
            except OSError:
                continue
        if cache_dir is None:
            return
        os.environ["JAX_COMPILATION_CACHE_DIR"] = cache_dir
        # 0.5s threshold: catches every real XLA compile (the cheapest
        # boost-step compile on this box is ~1s) while keeping the
        # trivial scalar dispatches from growing the dir without bound
        os.environ.setdefault(
            "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
        j = sys.modules.get("jax")
        if j is not None:
            j.config.update("jax_compilation_cache_dir", cache_dir)
            # post-setdefault value: a user-exported threshold wins
            j.config.update(
                "jax_persistent_cache_min_compile_time_secs",
                float(os.environ[
                    "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]))
    except Exception:   # noqa: BLE001 — acceleration only, never fatal
        pass


# ---------------------------------------------------------------------------
# XLA compile accounting (jax.monitoring listeners)
# ---------------------------------------------------------------------------
#
# The pipelined AutoML scheduler (runtime/scheduler.py) needs to know
# how much XLA compilation ran on WHICH thread: compiles on the device
# stream are critical-path compile-wait, compiles on the compile-ahead
# stream are overlapped cache fills.  jax.monitoring emits exactly the
# events needed ('/jax/core/compile/backend_compile_duration' per
# compile request, '/jax/compilation_cache/cache_hits|misses' for the
# persistent cache) without the stderr spam of jax_log_compiles, so the
# watch is a pair of listeners feeding per-thread counters.  Listeners
# are registered once per process and are pure accounting — they can
# never raise into jax.

_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_PCACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_PCACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"

_watch_lock = threading.Lock()
_watch_installed = False
# global counters + per-thread breakdown
# {ident: [compiles, seconds, pcache_hits, pcache_misses]}
_watch = {"compiles": 0, "compile_s": 0.0,
          "pcache_hits": 0, "pcache_misses": 0}
_watch_threads: dict[int, list] = {}


def _per_thread() -> list:
    return _watch_threads.setdefault(threading.get_ident(),
                                     [0, 0.0, 0, 0])


def _on_compile_duration(event: str, duration: float, **kw) -> None:
    if event != _BACKEND_COMPILE_EVENT:
        return
    with _watch_lock:
        _watch["compiles"] += 1
        _watch["compile_s"] += duration
        per = _per_thread()
        per[0] += 1
        per[1] += duration


def _on_compile_event(event: str, **kw) -> None:
    # the listener runs on the compiling thread, so per-thread cache
    # attribution is exact even with a concurrent compile-ahead stream
    if event == _PCACHE_HIT_EVENT:
        with _watch_lock:
            _watch["pcache_hits"] += 1
            _per_thread()[2] += 1
    elif event == _PCACHE_MISS_EVENT:
        with _watch_lock:
            _watch["pcache_misses"] += 1
            _per_thread()[3] += 1


def start_compile_watch() -> None:
    """Install the jax.monitoring listeners (idempotent, never raises).

    Counting starts at install; callers diff snapshots, so a late
    install only shortens history, never corrupts it."""
    global _watch_installed
    with _watch_lock:
        if _watch_installed:
            return
        _watch_installed = True
    try:
        # the compile watch registers with the fleet-telemetry
        # registry where it lives (lazy import: telemetry itself
        # lazily imports this module for the host fingerprint)
        from .telemetry import register_group

        register_group("compiles", compile_watch_snapshot)
    except Exception:   # noqa: BLE001 — accounting only, never fatal
        pass
    try:
        from jax import monitoring

        monitoring.register_event_duration_secs_listener(
            _on_compile_duration)
        monitoring.register_event_listener(_on_compile_event)
    except Exception:   # noqa: BLE001 — accounting only, never fatal
        pass


def compile_watch_snapshot(thread_ident: int | None = None) -> dict:
    """Cumulative compile counters; with ``thread_ident``, that
    thread's share under ``thread_compiles``/``thread_compile_s`` —
    diff two snapshots to attribute a code region's compile cost."""
    with _watch_lock:
        if len(_watch_threads) > 64:
            # prune dead threads' entries: every AutoML run spawns
            # fresh scheduler workers, and a long-lived REST server
            # would otherwise grow this dict (and risk ident-reuse
            # mixing a dead stream's counters into a new thread's)
            # without bound. Callers diff snapshots over short windows,
            # so dropping finished threads' history is safe.
            live = {t.ident for t in threading.enumerate()}
            live.add(thread_ident)
            for ident in [i for i in _watch_threads if i not in live]:
                del _watch_threads[ident]
        out = dict(_watch)
        if thread_ident is not None:
            per = _watch_threads.get(thread_ident, [0, 0.0, 0, 0])
            out["thread_compiles"] = per[0]
            out["thread_compile_s"] = per[1]
            out["thread_pcache_hits"] = per[2]
            out["thread_pcache_misses"] = per[3]
    return out


def ensure_live_backend(timeout: float = 90.0,
                        budget: float | None = None) -> str:
    """Probe default-backend init in a throwaway subprocess; pin this
    process to CPU if the probe crashes or hangs.

    Returns the platform this process should proceed on: "cpu" after a
    fallback, "initialized" when backends are already up (trusted
    as-is), else the environment's default platform name.

    Budget policy (round-3 hardening): the round-2 capture gave up after
    two attempts (~120 s) while the tunneled chip was merely *recovering*
    and recorded a CPU number as the round's official artifact.  The
    probe must never hang — but it should be stubborn: keep retrying
    with a pause between attempts until a total wall-clock budget is
    spent.  Default budget 600 s, overridable via
    ``H2O_TPU_PROBE_BUDGET`` (seconds; 0 disables probing retries and
    falls back to CPU after one attempt's failure).
    """
    enable_persistent_compile_cache()
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        return "cpu"
    if "jax" in sys.modules:
        try:
            if sys.modules["jax"].config.jax_platforms == "cpu":
                return "cpu"
        except Exception:
            pass
    if backends_initialized():
        return "initialized"
    if budget is None:
        try:
            budget = float(os.environ.get("H2O_TPU_PROBE_BUDGET", "600"))
        except ValueError:
            budget = 600.0
    deadline = time.monotonic() + max(budget, 0.0)
    attempt = 0
    fast_fails = 0
    while True:
        attempt += 1
        if budget <= 0:
            # single-attempt mode: the one probe gets the full timeout
            # (cold TPU client init takes ~15-30s; a 10s clamp would
            # misclassify a healthy chip as dead)
            t = timeout
        else:
            # otherwise never exceed the remaining budget (10s floor so
            # a probe can at least start), so small budgets hold
            t = min(timeout if attempt == 1 else 60.0,
                    max(10.0, deadline - time.monotonic()))
        try:
            r = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=t, capture_output=True)
            if r.returncode == 0:
                return os.environ.get("JAX_PLATFORMS") or "default"
            sys.stderr.write(
                f"backend probe attempt {attempt} rc={r.returncode}: "
                f"{r.stderr.decode(errors='replace')[-400:]}\n")
            # stubbornness is for a recovering chip that HANGS the
            # probe; a deterministic fast error (broken plugin install)
            # will not heal with retries — give up after 3
            fast_fails += 1
            if fast_fails >= 3:
                break
        except subprocess.TimeoutExpired:
            sys.stderr.write(
                f"backend probe attempt {attempt} hung >{t}s\n")
            fast_fails = 0
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        if fast_fails == 0:
            # pause before re-probing: a recovering chip needs tens of
            # seconds; hammering it back-to-back re-hits the same hang.
            # (skipped after a fast deterministic failure — sleeping
            # cannot heal a broken install)
            time.sleep(min(30.0, max(5.0, remaining / 4)))
    sys.stderr.write(
        f"backend unavailable after {attempt} attempts over "
        f"{budget:.0f}s budget; pinning this process to CPU\n")
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    return "cpu"
