"""Backend liveness probing + virtual-CPU forcing.

The scoreboard files (`bench.py`, `__graft_entry__.py`) must never hang
or crash on a flaky TPU backend: the axon/TPU client init *hangs* (not
errors) when the tunneled chip is unavailable, and an env-level
``JAX_PLATFORMS=cpu`` override is re-asserted by ``sitecustomize`` —
the only reliable controls are an out-of-process probe and an
in-process ``jax.config`` update before first backend use.  This module
is the single shared implementation of both.
"""

from __future__ import annotations

import os
import re
import subprocess
import sys

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def force_cpu_devices(n: int) -> None:
    """Pin this process to the CPU platform with >= n virtual devices.

    Must run before jax initializes its backends; raises/parses nothing
    if they already exist (callers detect that via device count).
    """
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(rf"{_COUNT_FLAG}=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = f"{flags} {_COUNT_FLAG}={max(n, 1)}".strip()
    elif int(m.group(1)) < n:
        os.environ["XLA_FLAGS"] = flags.replace(
            m.group(0), f"{_COUNT_FLAG}={n}")
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def backends_initialized() -> bool:
    if "jax" not in sys.modules:
        return False
    try:
        import jax._src.xla_bridge as xb

        return bool(xb._backends)  # noqa: SLF001
    except Exception:
        return False


def ensure_live_backend(timeout: float = 90.0, retries: int = 2) -> str:
    """Probe default-backend init in a throwaway subprocess; pin this
    process to CPU if the probe crashes or hangs.

    Returns the platform this process should proceed on: "cpu" after a
    fallback, "initialized" when backends are already up (trusted
    as-is), else the environment's default platform name.

    Budget: first attempt gets the full timeout, later attempts 30s, no
    trailing sleep — worst case ~timeout+30s, small enough to fit under
    the driver's own watchdog.
    """
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        return "cpu"
    if "jax" in sys.modules:
        try:
            if sys.modules["jax"].config.jax_platforms == "cpu":
                return "cpu"
        except Exception:
            pass
    if backends_initialized():
        return "initialized"
    for attempt in range(max(retries, 1)):
        t = timeout if attempt == 0 else min(30.0, timeout)
        try:
            r = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=t, capture_output=True)
            if r.returncode == 0:
                return os.environ.get("JAX_PLATFORMS") or "default"
            sys.stderr.write(
                f"backend probe attempt {attempt + 1} rc={r.returncode}: "
                f"{r.stderr.decode(errors='replace')[-400:]}\n")
        except subprocess.TimeoutExpired:
            sys.stderr.write(
                f"backend probe attempt {attempt + 1} hung >{t}s\n")
    sys.stderr.write("backend unavailable; pinning this process to CPU\n")
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    return "cpu"
