"""Failure detection — the heartbeat analog (SURVEY.md §5.3).

The reference's water/HeartBeatThread gossips liveness between nodes;
a node missing heartbeats is declared gone and, because the cloud is
locked, the cluster becomes unusable: jobs fail cleanly and the cloud
reports unhealthy. On TPU the failure mode is a chip/runtime hang or a
dead ICI link, so the heartbeat is a tiny collective probe across the
mesh executed under a deadline in a worker thread.

Semantics mirror the reference — detection + fail-fast, no elasticity:
once a probe fails, `healthy()` flips false, `require_healthy()` raises
`ClusterHealthError`, and `cluster_status()` reports unhealthy.
`require_healthy()` guards every MRTask `doall`, train() entry
(models/base.py resolve_xy), AND the hot driver loops that dispatch
shard_map directly — GBM/DRF chunk boundaries, XGBoost rank rounds,
GLM iterations, DL averaging rounds — so a dead mesh mid-train surfaces
as a clean error, not a hang. Recovery is checkpoint-restart
(persist/orbax + AutoML's resume manifest), not cloud re-formation.
"""

from __future__ import annotations

import contextlib
import threading
import time

_state = {
    "healthy": True,
    "last_beat": None,    # wall time of last successful probe
    "beats": 0,
    "error": "",
}
_lock = threading.Lock()
_thread: threading.Thread | None = None
_stop = threading.Event()
# the in-flight probe worker: once the mesh wedges, every heartbeat()
# would otherwise leak one more hung daemon thread (each parked inside
# a collective that never completes) — track it and refuse to stack up
_probe_thread: threading.Thread | None = None


class ClusterHealthError(RuntimeError):
    """The device mesh failed its liveness probe (fail-fast)."""


def _probe() -> float:
    """One heartbeat: psum a scalar across the whole mesh."""
    from . import faults

    faults.fire("health.probe")   # rehearse hangs/errors without a TPU
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from .mesh import ROWS, global_mesh
    from .mrtask import shard_rows

    mesh = global_mesh()
    fn = jax.jit(jax.shard_map(
        lambda x: jax.lax.psum(jnp.sum(x), ROWS), mesh=mesh,
        in_specs=P(ROWS), out_specs=P()))
    # shard_rows handles the multi-host mesh (make_array_from_callback)
    # — the probe must work exactly where it matters most, on a DCN
    # cluster with non-addressable devices
    arr = shard_rows(np.ones(mesh.shape[ROWS], np.float32), mesh=mesh)
    return float(fn(arr))


def heartbeat(timeout: float = 60.0) -> bool:
    """Run one liveness probe under a deadline; update cluster health.

    The probe runs on a DAEMON thread joined with a timeout — an
    executor/`with` block would join the hung worker (the very failure
    this probe detects) and block heartbeat() itself, and a non-daemon
    worker would also block interpreter exit.

    A probe that outlives its deadline keeps running (nothing can
    interrupt a thread stuck in a collective); while it is still
    alive, further heartbeat() calls log-and-return-False instead of
    stacking up one more hung thread per call."""
    global _probe_thread
    box: dict = {}

    def run():
        try:
            box["val"] = _probe()
        except Exception as e:  # noqa: BLE001 — any device error is fatal
            box["exc"] = e

    # check-claim-START under ONE lock hold: two concurrent heartbeats
    # (the background loop + a direct call) must not both see the slot
    # free and spawn two probes into the same hung collective. The
    # start() must happen inside the lock too — an unstarted Thread
    # reports is_alive()==False, so a claimed-but-not-started probe
    # would look like a free slot to the second caller.
    with _lock:
        if _probe_thread is not None and _probe_thread.is_alive():
            t = None
        else:
            t = threading.Thread(target=run, name="h2o-tpu-probe",
                                 daemon=True)
            _probe_thread = t
            t.start()
    if t is None:
        from ..diagnostics import log, timeline

        log.warning("heartbeat: previous probe still in flight — "
                    "skipping spawn")
        timeline.record("heartbeat_skipped",
                        "previous probe still in flight")
        # no probe ran: report the standing health state. In the wedged
        # case the earlier deadline already flipped it to False; a
        # caller merely racing the background loop's HEALTHY in-flight
        # probe must not read a false outage.
        return healthy()
    t.join(timeout)
    if t.is_alive():
        ok, err = False, f"heartbeat probe hung > {timeout}s"
    elif "exc" in box:
        ok, err = False, f"heartbeat probe failed: {box['exc']!r}"
    else:
        ok, err = True, ""
    with _lock:
        if ok:
            # a success does NOT clear a tripped unhealthy state: the
            # cloud is locked (reference semantics — no elasticity);
            # recovery is an explicit restart via reset()
            _state["last_beat"] = time.time()
            _state["beats"] += 1
        else:
            _state["healthy"] = False
            _state["error"] = err
    return ok and healthy()


def healthy() -> bool:
    with _lock:
        return bool(_state["healthy"])


def health_status() -> dict:
    with _lock:
        return dict(_state)


def require_healthy(fault_site: str | None = "train.step") -> None:
    """Fail fast (reference: jobs on a broken cloud fail cleanly).

    The training hot loops call this at chunk boundaries, which makes
    it the natural ``train.step`` fault point: an armed device_error
    flips health and raises from here — exactly where a real device
    error escaping a training step would surface. Non-training callers
    (doall has its own ``mrtask.doall`` site; predict/scoring) pass
    ``fault_site=None`` so an armed train.step fault keeps its
    documented skip-count determinism and can never be consumed by,
    e.g., a user predict() on a healthy cluster."""
    from . import faults

    if fault_site:
        faults.fire(fault_site)
    with _lock:
        if not _state["healthy"]:
            raise ClusterHealthError(
                f"cluster unhealthy: {_state['error']} — restart the "
                "cluster and resume from the last checkpoint")


def is_device_error(e: BaseException) -> bool:
    """True for device-runtime failures (XLA runtime errors and the
    harness's InjectedDeviceError) — the class of exception that means
    the mesh, not the caller's inputs, is broken."""
    from . import faults

    return isinstance(e, faults.InjectedDeviceError) or \
        isinstance(e, _device_error_types())


def _device_error_types() -> tuple[type, ...]:
    try:
        from jax.errors import JaxRuntimeError

        return (JaxRuntimeError,)
    except ImportError:
        try:
            from jaxlib.xla_extension import XlaRuntimeError

            return (XlaRuntimeError,)
        except ImportError:
            return ()


@contextlib.contextmanager
def device_dispatch(desc: str, locking: bool = True):
    """Guard a device dispatch: a runtime error escaping it (a halted
    chip, a dead ICI link, an injected device_error) marks the cluster
    unhealthy and re-surfaces as ClusterHealthError, so callers see the
    locked-cloud protocol instead of a raw XLA traceback.

    The serving scoring path passes ``locking=False``: a real device
    error there still surfaces as ClusterHealthError (and feeds the
    circuit breaker, which gives the device a cooldown and auto-recovers
    through the half-open probe) but does NOT lock the cloud — one bad
    scoring dispatch corrupts no training state and must not demand a
    manual cluster restart. Training dispatches keep ``locking=True``."""
    from . import faults

    try:
        yield
    except faults.InjectedDeviceError as e:
        # kind=device_error already flipped health (locked cloud);
        # kind=dispatch_error deliberately did NOT — that one is a
        # single failed dispatch feeding the circuit breaker, and its
        # message must not tell operators to restart a healthy cluster
        if healthy():
            raise ClusterHealthError(
                f"{desc}: {e} — transient dispatch failure "
                "(circuit breaker territory, cloud not locked)") from e
        raise ClusterHealthError(
            f"{desc}: {e} — restart the cluster and resume from the "
            "last checkpoint") from e
    except _device_error_types() as e:
        if not locking:
            raise ClusterHealthError(
                f"{desc}: device runtime error ({e}) — transient "
                "dispatch failure (circuit breaker territory, cloud "
                "not locked)") from e
        mark_unhealthy(f"{desc}: {e}")
        raise ClusterHealthError(
            f"{desc}: device runtime error ({e}) — restart the cluster "
            "and resume from the last checkpoint") from e


def mark_unhealthy(error: str) -> None:
    """Record an externally-observed failure (e.g. a device error
    escaping a training step)."""
    with _lock:
        _state["healthy"] = False
        _state["error"] = error


def reset() -> None:
    """Clear health state (new cluster after restart).

    Also abandons any still-wedged probe thread: a probe stuck in a
    collective that never returns can't be joined, and leaving it
    tracked would make every post-reset heartbeat skip-spawn and
    report the standing (now healthy) state forever — the dead mesh
    would never be re-detected. The orphaned daemon thread is leaked
    deliberately; one fresh probe per reset is the bounded cost."""
    global _probe_thread
    with _lock:
        _state.update(healthy=True, error="", last_beat=None, beats=0)
        _probe_thread = None


def start_heartbeat(interval: float = 30.0, timeout: float = 60.0) -> None:
    """Background heartbeat loop (the HeartBeatThread analog)."""
    global _thread
    if _thread is not None and _thread.is_alive():
        return
    _stop.clear()

    def loop():
        while not _stop.wait(interval):
            heartbeat(timeout=timeout)

    _thread = threading.Thread(target=loop, name="h2o-tpu-heartbeat",
                               daemon=True)
    _thread.start()


def stop_heartbeat(join: bool = False, timeout: float = 5.0) -> None:
    """Stop the background loop. The drain path passes ``join=True`` so
    interpreter exit never races a heartbeat mid-probe; the join is
    bounded (the loop thread is a daemon — a probe wedged in a
    collective cannot be joined and must not block the drain)."""
    _stop.set()
    t = _thread
    if join and t is not None and t.is_alive():
        t.join(timeout)
