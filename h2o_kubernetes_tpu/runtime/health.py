"""Failure detection — the heartbeat analog (SURVEY.md §5.3).

The reference's water/HeartBeatThread gossips liveness between nodes;
a node missing heartbeats is declared gone and, because the cloud is
locked, the cluster becomes unusable: jobs fail cleanly and the cloud
reports unhealthy. On TPU the failure mode is a chip/runtime hang or a
dead ICI link, so the heartbeat is a tiny collective probe across the
mesh executed under a deadline in a worker thread.

Semantics mirror the reference — detection + fail-fast, no elasticity:
once a probe fails, `healthy()` flips false, `require_healthy()` raises
`ClusterHealthError`, and `cluster_status()` reports unhealthy.
`require_healthy()` guards every MRTask `doall`, train() entry
(models/base.py resolve_xy), AND the hot driver loops that dispatch
shard_map directly — GBM/DRF chunk boundaries, XGBoost rank rounds,
GLM iterations, DL averaging rounds — so a dead mesh mid-train surfaces
as a clean error, not a hang. Recovery is checkpoint-restart
(persist/orbax + AutoML's resume manifest), not cloud re-formation.
"""

from __future__ import annotations

import threading
import time

_state = {
    "healthy": True,
    "last_beat": None,    # wall time of last successful probe
    "beats": 0,
    "error": "",
}
_lock = threading.Lock()
_thread: threading.Thread | None = None
_stop = threading.Event()


class ClusterHealthError(RuntimeError):
    """The device mesh failed its liveness probe (fail-fast)."""


def _probe() -> float:
    """One heartbeat: psum a scalar across the whole mesh."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from .mesh import ROWS, global_mesh
    from .mrtask import shard_rows

    mesh = global_mesh()
    fn = jax.jit(jax.shard_map(
        lambda x: jax.lax.psum(jnp.sum(x), ROWS), mesh=mesh,
        in_specs=P(ROWS), out_specs=P()))
    # shard_rows handles the multi-host mesh (make_array_from_callback)
    # — the probe must work exactly where it matters most, on a DCN
    # cluster with non-addressable devices
    arr = shard_rows(np.ones(mesh.shape[ROWS], np.float32), mesh=mesh)
    return float(fn(arr))


def heartbeat(timeout: float = 60.0) -> bool:
    """Run one liveness probe under a deadline; update cluster health.

    The probe runs on a DAEMON thread joined with a timeout — an
    executor/`with` block would join the hung worker (the very failure
    this probe detects) and block heartbeat() itself, and a non-daemon
    worker would also block interpreter exit."""
    box: dict = {}

    def run():
        try:
            box["val"] = _probe()
        except Exception as e:  # noqa: BLE001 — any device error is fatal
            box["exc"] = e

    t = threading.Thread(target=run, name="h2o-tpu-probe", daemon=True)
    t.start()
    t.join(timeout)
    if t.is_alive():
        ok, err = False, f"heartbeat probe hung > {timeout}s"
    elif "exc" in box:
        ok, err = False, f"heartbeat probe failed: {box['exc']!r}"
    else:
        ok, err = True, ""
    with _lock:
        if ok:
            # a success does NOT clear a tripped unhealthy state: the
            # cloud is locked (reference semantics — no elasticity);
            # recovery is an explicit restart via reset()
            _state["last_beat"] = time.time()
            _state["beats"] += 1
        else:
            _state["healthy"] = False
            _state["error"] = err
    return ok and healthy()


def healthy() -> bool:
    with _lock:
        return bool(_state["healthy"])


def health_status() -> dict:
    with _lock:
        return dict(_state)


def require_healthy() -> None:
    """Fail fast (reference: jobs on a broken cloud fail cleanly)."""
    with _lock:
        if not _state["healthy"]:
            raise ClusterHealthError(
                f"cluster unhealthy: {_state['error']} — restart the "
                "cluster and resume from the last checkpoint")


def mark_unhealthy(error: str) -> None:
    """Record an externally-observed failure (e.g. a device error
    escaping a training step)."""
    with _lock:
        _state["healthy"] = False
        _state["error"] = error


def reset() -> None:
    """Clear health state (new cluster after restart)."""
    with _lock:
        _state.update(healthy=True, error="", last_beat=None, beats=0)


def start_heartbeat(interval: float = 30.0, timeout: float = 60.0) -> None:
    """Background heartbeat loop (the HeartBeatThread analog)."""
    global _thread
    if _thread is not None and _thread.is_alive():
        return
    _stop.clear()

    def loop():
        while not _stop.wait(interval):
            heartbeat(timeout=timeout)

    _thread = threading.Thread(target=loop, name="h2o-tpu-heartbeat",
                               daemon=True)
    _thread.start()


def stop_heartbeat() -> None:
    _stop.set()
