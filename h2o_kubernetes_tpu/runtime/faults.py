"""Deterministic fault injection — rehearse failures without a real outage.

TPU_PROBE_r05.txt logged 132 consecutive probe hangs we could never
rehearse against: the detection path (health.py), the retry path
(retry.py / persist_cloud.py) and the checkpoint-restart path (automl
resume manifest) were all only exercisable by waiting for real
infrastructure to fail. This module makes those paths testable on CPU:
named fault *points* in the runtime call `fire(site)`, and armed fault
*specs* decide whether that call raises, hangs, or passes through.

Fault points wired through the runtime:

- ``persist.http``  — every cloud-persist HTTP attempt (persist_cloud
  _http / WebHDFS CREATE, persist._http_read). Kinds: ``http_<code>``
  (raises a real urllib HTTPError, e.g. http_503 / http_429 — param
  sets a Retry-After header), ``timeout``, ``urlerror``, ``truncate``
  (an IncompleteRead, the partial-write/read signature).
- ``health.probe``  — the heartbeat's collective probe. Kinds:
  ``hang`` (sleeps param seconds, default 3600 — the wedged-mesh
  signature), ``error``.
- ``train.step``    — every `require_healthy()` chunk-boundary guard in
  the training hot loops (GBM/DRF/XGBoost/GLM/DL + resolve_xy). Kind
  ``device_error`` marks the cluster unhealthy and raises
  InjectedDeviceError — a device error escaping a training step.
- ``mrtask.doall``  — MRTask dispatch. Kind ``device_error`` as above.
- ``automl.step``   — one AutoML plan step about to train (resumed
  steps don't count). Kind ``device_error`` kills the run mid-plan.
- ``score.dispatch`` — the serving dispatch inside Model.score_numpy
  (every REST scoring request rides it). Kind ``dispatch_error``
  raises InjectedDeviceError WITHOUT locking the cloud — the circuit
  breaker's food: a per-dispatch device failure, not a dead mesh.
  ``device_error``/``hang`` also work here.
- ``lifecycle.drain`` — drain entry (SIGTERM path). Kinds ``hang``
  (a slow drain step) and ``error`` (a failing one); the drain must
  complete either way.

Spec grammar (documented in docs/RESILIENCE.md)::

    spec     := clause (";" clause)*          # "," also separates
    clause   := site ":" kind ["*" count] ["@" skip] ["~" param]
    count    := int | "inf"                   # how many times to fire (default 1)
    skip     := int                           # matching calls to let through first
    param    := float                         # kind-specific (seconds / Retry-After)

Examples::

    persist.http:http_503*2          # first two persist HTTP calls 503
    health.probe:hang~0.5            # probe sleeps 0.5 s (longer than its deadline)
    train.step:device_error@3        # 4th chunk boundary loses the mesh
    persist.http:http_429~0.05;train.step:device_error

Activation: the ``H2O_TPU_FAULTS`` env var (parsed lazily, counters
live for the process), or the ``inject(spec)`` context manager (test
scoped). With neither set, `fire()` is a dict lookup and a return —
safe in hot loops.
"""

from __future__ import annotations

import contextlib
import io
import os
import re
import threading
import time
import urllib.error
from dataclasses import dataclass
from email.message import Message
from typing import Iterator

__all__ = ["Fault", "FaultError", "InjectedDeviceError", "parse",
           "inject", "fire", "active", "reset"]


class FaultError(RuntimeError):
    """Base class for errors raised by an injected fault."""


class InjectedDeviceError(FaultError):
    """Simulated device/runtime error escaping a dispatch (the XLA
    'DEADLINE_EXCEEDED / device halted' family)."""


@dataclass
class Fault:
    """One armed fault: fires `count` times at `site` after letting
    `skip` matching calls through."""

    site: str
    kind: str
    count: float = 1          # float so "inf" arms a permanent fault
    skip: int = 0
    param: float | None = None

    def spec(self) -> str:
        out = f"{self.site}:{self.kind}"
        if self.count != 1:
            out += f"*{'inf' if self.count == float('inf') else int(self.count)}"
        if self.skip:
            out += f"@{self.skip}"
        if self.param is not None:
            out += f"~{self.param:g}"
        return out


_CLAUSE = re.compile(
    r"^(?P<site>[\w.]+):(?P<kind>\w+)"
    r"(?:\*(?P<count>\d+|inf))?"
    r"(?:@(?P<skip>\d+))?"
    r"(?:~(?P<param>\d+(?:\.\d+)?))?$")


def parse(spec: str) -> list[Fault]:
    """Parse a fault-spec string into armed Fault objects."""
    out = []
    for clause in re.split(r"[;,]", spec):
        clause = clause.strip()
        if not clause:
            continue
        m = _CLAUSE.match(clause)
        if not m:
            raise ValueError(
                f"bad fault clause {clause!r} — expected "
                "site:kind[*count][@skip][~param] (see docs/RESILIENCE.md)")
        out.append(Fault(
            site=m["site"], kind=m["kind"],
            count=float("inf") if m["count"] == "inf"
            else int(m["count"] or 1),
            skip=int(m["skip"] or 0),
            param=float(m["param"]) if m["param"] else None))
    return out


_lock = threading.Lock()
_CTX: list[Fault] = []                 # inject()-scoped faults
# env-armed faults, cached against the env string so counters persist
# across fire() calls but a CHANGED env value re-arms fresh counters
_ENV_CACHE: tuple[str, list[Fault]] | None = None


def _armed() -> list[Fault]:
    """All armed faults (context-scoped first), under _lock."""
    global _ENV_CACHE
    env = os.environ.get("H2O_TPU_FAULTS", "")
    if not env:
        _ENV_CACHE = None
        return list(_CTX)
    if _ENV_CACHE is None or _ENV_CACHE[0] != env:
        _ENV_CACHE = (env, parse(env))
    return list(_CTX) + _ENV_CACHE[1]


def active() -> list[str]:
    """Specs of armed, non-exhausted faults (introspection/status)."""
    with _lock:
        return [f.spec() for f in _armed() if f.count > 0]


def reset() -> None:
    """Disarm everything — context faults AND env-armed ones.

    The current H2O_TPU_FAULTS value is pinned to an EMPTY armed list
    (not just dropped from the cache): otherwise the next fire() would
    re-parse the unchanged env var and resurrect exhausted faults with
    fresh counters. A *changed* env value still re-arms normally."""
    global _ENV_CACHE
    with _lock:
        _CTX.clear()
        env = os.environ.get("H2O_TPU_FAULTS", "")
        _ENV_CACHE = (env, []) if env else None


@contextlib.contextmanager
def inject(spec: str | list[Fault]) -> Iterator[list[Fault]]:
    """Arm faults for the duration of a with-block (test scoped)."""
    faults = parse(spec) if isinstance(spec, str) else list(spec)
    with _lock:
        _CTX.extend(faults)
    try:
        yield faults
    finally:
        with _lock:
            for f in faults:
                try:
                    _CTX.remove(f)
                except ValueError:
                    pass


def fire(site: str, **ctx) -> None:
    """Fault point: called by the runtime at a named site.

    Finds the first armed fault for `site`; consumes one skip or one
    count; raises/sleeps per the fault kind. No armed faults → returns
    immediately (the hot-loop fast path).
    """
    if not _CTX and not os.environ.get("H2O_TPU_FAULTS"):
        return
    fault, desc = None, ""
    with _lock:
        for f in _armed():
            if f.site != site or f.count <= 0:
                continue
            if f.skip > 0:
                f.skip -= 1
                return
            desc = f.spec()           # before the decrement, for logs
            f.count -= 1
            fault = f
            break
    if fault is None:
        return
    from ..diagnostics import log, timeline

    timeline.record("fault_injected", desc, site=site, **{
        k: str(v)[:120] for k, v in ctx.items()})
    log.warning("fault injected at %s: %s", site, desc)
    _trigger(fault, site, ctx)


def _trigger(fault: Fault, site: str, ctx: dict) -> None:
    kind = fault.kind
    if kind.startswith("http_"):
        code = int(kind[len("http_"):])
        hdrs = Message()
        if fault.param is not None:
            hdrs["Retry-After"] = f"{fault.param:g}"
        raise urllib.error.HTTPError(
            str(ctx.get("url", "injected://fault")), code,
            f"injected HTTP {code}", hdrs, io.BytesIO(b"injected fault"))
    if kind == "timeout":
        raise TimeoutError(f"injected timeout at {site}")
    if kind == "urlerror":
        raise urllib.error.URLError(f"injected connection failure at {site}")
    if kind == "truncate":
        import http.client

        raise http.client.IncompleteRead(b"", expected=1)
    if kind == "hang":
        time.sleep(fault.param if fault.param is not None else 3600.0)
        return
    if kind == "device_error":
        # a device error escaping a training step takes the mesh down:
        # flip health first so the next chunk-boundary guard fails fast
        # with the locked-cloud error (reference semantics, SURVEY §5.3)
        from . import health

        msg = (f"injected device error at {site} "
               "(fault harness, kind=device_error)")
        health.mark_unhealthy(msg)
        raise InjectedDeviceError(msg)
    if kind == "dispatch_error":
        # a device error confined to ONE dispatch: the circuit
        # breaker's signature. Does NOT lock the cloud — tripping vs.
        # locking is exactly the distinction the breaker exists for.
        raise InjectedDeviceError(
            f"injected dispatch error at {site} "
            "(fault harness, kind=dispatch_error)")
    if kind == "error":
        raise FaultError(f"injected error at {site}")
    raise ValueError(f"unknown fault kind {kind!r} (site {site})")
