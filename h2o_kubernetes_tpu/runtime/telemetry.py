"""Fleet telemetry — ONE process-wide metrics registry + request tracing.

Before this module the fleet's observability was a pile of per-surface
JSON dicts (`rest.STATS`/`MODEL_STATS`, `scorer_cache_stats()`, the
breaker, router retry budgets, `AutoML.scheduler_stats`, the
`jax.monitoring` compile watch) that only `GET /3/Stats` on one process
at a time could see, with no way to correlate a slow request across the
router hop, the batcher queue, and the device dispatch.  This module is
the single source of truth those surfaces now register through:

- **Metrics registry** (`REGISTRY`): thread-safe counters, gauges and
  bounded-bucket histograms. Label names are validated against a fixed
  allowlist so a typo'd label cannot mint unbounded series, and the
  ``model`` label is cardinality-capped: per metric, the top-K model
  values by traffic keep their own series and everything else rolls up
  into an ``other`` series (``H2O_TPU_METRICS_TOPK``) — a
  thousand-tenant catalog costs K+1 series, not a thousand.
- **Stat groups** (`register_group`): the existing dict surfaces stay
  the storage their owning modules mutate, but they REGISTER here — the
  registry snapshots them for ``/3/Stats`` (byte-shape-compatible with
  the pre-registry JSON) and flattens every numeric leaf into the
  Prometheus text exposition at ``GET /metrics``, so one scrape sees
  every counter ``/3/Stats`` ever reported.
- **Request tracing** (`TRACER`): the router mints an
  ``X-H2O-Trace-Id``, every hop propagates it, and each process records
  its spans (router: per-attempt dispatch outcomes; replica: admission
  wait / batcher queue wait / batch assembly / device dispatch / total)
  into a bounded ring served at ``GET /3/Trace/{id}`` — "why was this
  p99 slow" decomposes into queue-vs-device-vs-hedge.
- **Training phase spans** (`phase_span`): bin / per-level histogram /
  split find / chunk upload / compile-ahead fill feed the existing
  `diagnostics.TimeLine` AND per-phase latency histograms, and the
  out-of-core stream reports the upload/compute overlap-efficiency
  gauge the SCALING docs previously estimated by hand.

Deliberately JAX-free and numpy-free: the router and operator processes
scrape and serve this without paying a device import.
"""

from __future__ import annotations

import collections
import contextlib
import os
import threading
import time
import uuid

from .retry import _env_float

__all__ = [
    "REGISTRY", "TRACER", "MetricsRegistry", "TraceRing",
    "register_group", "group_snapshot", "prometheus_text",
    "parse_prometheus_text", "build_info", "phase_span",
    "record_request_phases", "new_trace_id", "trace_id_from",
    "count_event", "ooc_stream_account", "start_status_listener",
    "metric_name", "CONTENT_TYPE", "write_metrics",
]

# the Prometheus text exposition content type (0.0.4 is the text format
# every scraper speaks)
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# Fixed label-name allowlist: metrics may carry at most ONE label and
# its NAME must come from here — labels are the cardinality lever, and
# an open-ended label vocabulary is how a registry rots into a series
# explosion nobody can aggregate. (`value` is the flattener's label for
# string leaves, `le` is the histogram bucket bound.)
ALLOWED_LABELS = frozenset({
    "model", "shard", "phase", "kind", "slo", "outcome", "state",
    "event", "route", "pool", "replica", "value", "le",
    "version", "jax", "jaxlib", "hostfp",
})

# label names whose VALUE set is unbounded by construction (tenant
# keys): series under them are capped at top-K-by-traffic + "other"
CAPPED_LABELS = frozenset({"model"})

# bounded default buckets (seconds) for latency histograms: 1ms..10s
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

_PHASES = ("admission", "queue", "assemble", "dispatch", "total")


def _topk() -> int:
    """H2O_TPU_METRICS_TOPK (default 20): per-metric series cap for
    capped labels — the top-K label values by traffic keep their own
    series, the rest roll into `other`."""
    return max(1, int(_env_float("H2O_TPU_METRICS_TOPK", 20.0)))


def _trace_on() -> bool:
    """H2O_TPU_TRACE (default 1): 0 disables span recording (ring +
    per-request phase histograms) — the perf kill switch; counters and
    /metrics stay on."""
    return os.environ.get("H2O_TPU_TRACE", "1") != "0"


def _sanitize(part: str) -> str:
    """A dict key / group name as a Prometheus metric-name component."""
    out = []
    for ch in str(part):
        out.append(ch if ch.isalnum() or ch == "_" else "_")
    s = "".join(out)
    if s and s[0].isdigit():
        s = "_" + s
    return s


def metric_name(*parts: str) -> str:
    """THE /3/Stats-leaf -> /metrics-sample naming rule, shared with
    the inventory-diff test so the two surfaces cannot drift:
    ``metric_name("batcher", "shed") == "h2o_stats_batcher_shed"``."""
    return "_".join(["h2o_stats"] + [_sanitize(p) for p in parts])


def _escape_label(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace("\n", "\\n") \
        .replace('"', '\\"')


# ---------------------------------------------------------------------------
# Metric primitives
# ---------------------------------------------------------------------------


class _LabeledMetric:
    """Shared machinery: one optional label; when the label is capped
    (`model`), series are bounded at top-K by traffic + an `other`
    rollup. All state mutations run under the registry lock (passed
    in), so a multi-threaded hammer loses no updates."""

    kind = "untyped"

    def __init__(self, name: str, help_: str, label: str | None,
                 lock: threading.Lock):
        if label is not None and label not in ALLOWED_LABELS:
            raise ValueError(
                f"metric {name!r}: label {label!r} is not in the "
                f"fixed allowlist {sorted(ALLOWED_LABELS)} — labels "
                "are the cardinality lever; add to the allowlist "
                "deliberately, never ad hoc")
        self.name = name
        self.help = help_
        self.label = label
        self._lock = lock
        self._series: dict[str | None, object] = {}
        # traffic rank for capped labels (bounded itself: evicts the
        # lowest counts past 8*K so the RANKING map cannot become the
        # cardinality leak it exists to prevent)
        self._traffic: dict[str, int] = {}

    def _new_series(self):                       # pragma: no cover
        raise NotImplementedError

    def _merge_into(self, dst, src) -> None:     # pragma: no cover
        raise NotImplementedError

    def _series_for(self, value: str | None):
        """Resolve the series a label value lands in (caller holds the
        lock). Uncapped labels get a series per value — their
        vocabulary is fixed (phases, SLO classes, outcomes)."""
        if self.label is None:
            value = None
        if value is None or self.label not in CAPPED_LABELS:
            s = self._series.get(value)
            if s is None:
                s = self._series[value] = self._new_series()
            return s
        value = str(value)
        k = _topk()
        t = self._traffic
        t[value] = t.get(value, 0) + 1
        if len(t) > 8 * k:
            for v in sorted(t, key=t.get)[: len(t) - 4 * k]:
                if v not in self._series:
                    del t[v]
        s = self._series.get(value)
        if s is not None:
            return s
        named = [v for v in self._series if v not in (None, "other")]
        if len(named) < k:
            s = self._series[value] = self._new_series()
            return s
        # at capacity: a newcomer with MORE traffic than the coldest
        # resident demotes it into `other` and takes its slot — the
        # exposed set converges on the true top-K by traffic
        coldest = min(named, key=lambda v: t.get(v, 0))
        if t[value] > t.get(coldest, 0):
            other = self._series.get("other")
            if other is None:
                other = self._series["other"] = self._new_series()
            self._merge_into(other, self._series.pop(coldest))
            s = self._series[value] = self._new_series()
            return s
        other = self._series.get("other")
        if other is None:
            other = self._series["other"] = self._new_series()
        return other

    def series_count(self) -> int:
        with self._lock:
            return len(self._series)


class Counter(_LabeledMetric):
    kind = "counter"

    def _new_series(self):
        return [0.0]

    def _merge_into(self, dst, src) -> None:
        dst[0] += src[0]

    def inc(self, n: float = 1.0, label_value: str | None = None
            ) -> None:
        with self._lock:
            self._series_for(label_value)[0] += n

    def value(self, label_value: str | None = None) -> float:
        with self._lock:
            s = self._series.get(
                label_value if self.label is not None else None)
            return s[0] if s is not None else 0.0

    def samples(self):
        with self._lock:
            return [(self.name,
                     {self.label: v} if v is not None else {}, s[0])
                    for v, s in self._series.items()]


class Gauge(_LabeledMetric):
    kind = "gauge"

    def __init__(self, name, help_, label, lock, fn=None):
        super().__init__(name, help_, label, lock)
        # callback gauges: fn() -> scalar, read at scrape time
        self._fn = fn

    def _new_series(self):
        return [0.0]

    def _merge_into(self, dst, src) -> None:
        dst[0] = src[0]

    def set(self, v: float, label_value: str | None = None) -> None:
        with self._lock:
            self._series_for(label_value)[0] = float(v)

    def value(self, label_value: str | None = None) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:  # noqa: BLE001 — a scrape must survive
                return float("nan")
        with self._lock:
            s = self._series.get(
                label_value if self.label is not None else None)
            return s[0] if s is not None else 0.0

    def samples(self):
        if self._fn is not None:
            return [(self.name, {}, self.value())]
        with self._lock:
            return [(self.name,
                     {self.label: v} if v is not None else {}, s[0])
                    for v, s in self._series.items()]


class Histogram(_LabeledMetric):
    """Bounded-bucket histogram: cumulative bucket counts, sum,
    count — the Prometheus shape, quantile-estimable by any scraper."""

    kind = "histogram"

    def __init__(self, name, help_, label, lock, buckets=None):
        super().__init__(name, help_, label, lock)
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))

    def _new_series(self):
        # [count per bucket..., +Inf count, sum, total count]
        return [0] * (len(self.buckets) + 1) + [0.0, 0]

    def _merge_into(self, dst, src) -> None:
        for i in range(len(src)):
            dst[i] += src[i]

    def observe(self, v: float, label_value: str | None = None) -> None:
        with self._lock:
            s = self._series_for(label_value)
            i = 0
            while i < len(self.buckets) and v > self.buckets[i]:
                i += 1
            s[i] += 1
            s[-2] += v
            s[-1] += 1

    def snapshot(self, label_value: str | None = None) -> dict:
        with self._lock:
            s = self._series.get(
                label_value if self.label is not None else None)
            if s is None:
                return {"count": 0, "sum": 0.0, "buckets": {}}
            cum, out = 0, {}
            for i, b in enumerate(self.buckets):
                cum += s[i]
                out[b] = cum
            return {"count": s[-1], "sum": s[-2], "buckets": out}

    def quantile(self, q: float, label_value: str | None = None
                 ) -> float | None:
        """Linear-interpolated quantile estimate off the buckets (what
        fleet_top renders as p99) — None on an empty series."""
        snap = self.snapshot(label_value)
        n = snap["count"]
        if not n:
            return None
        target = q * n
        prev_b, prev_c = 0.0, 0
        for b, c in snap["buckets"].items():
            if c >= target:
                span = c - prev_c
                frac = (target - prev_c) / span if span else 1.0
                return prev_b + (b - prev_b) * frac
            prev_b, prev_c = b, c
        return self.buckets[-1]

    def samples(self):
        out = []
        with self._lock:
            for v, s in self._series.items():
                labels = {self.label: v} if v is not None else {}
                cum = 0
                for i, b in enumerate(self.buckets):
                    cum += s[i]
                    out.append((self.name + "_bucket",
                                {**labels, "le": f"{b:g}"}, cum))
                out.append((self.name + "_bucket",
                            {**labels, "le": "+Inf"}, cum + s[-3]))
                out.append((self.name + "_sum", labels, s[-2]))
                out.append((self.name + "_count", labels, s[-1]))
        return out


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------


class MetricsRegistry:
    """Process-wide metric store + the stat-group registration point.

    First-class metrics (`counter`/`gauge`/`histogram`) are get-or-
    create by name (idempotent — module reimports re-resolve the same
    object). Stat GROUPS are zero-arg snapshot callables the existing
    dict surfaces register; both ``/3/Stats`` and ``/metrics`` render
    from them, which is what makes the registry the single source of
    truth without double-counting a single increment."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _LabeledMetric] = {}
        # name -> (fn, labeled): insertion-ordered, the /3/Stats
        # assembly order
        self._groups: dict = collections.OrderedDict()

    def _get(self, cls, name, help_, label, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help_, label, self._lock, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls) or m.label != label:
                raise ValueError(
                    f"metric {name!r} re-registered as {cls.__name__}"
                    f"/label={label!r} but exists as "
                    f"{type(m).__name__}/label={m.label!r}")
            return m

    def counter(self, name: str, help_: str = "",
                label: str | None = None) -> Counter:
        return self._get(Counter, name, help_, label)

    def gauge(self, name: str, help_: str = "",
              label: str | None = None, fn=None) -> Gauge:
        return self._get(Gauge, name, help_, label, fn=fn)

    def histogram(self, name: str, help_: str = "",
                  label: str | None = None,
                  buckets=None) -> Histogram:
        return self._get(Histogram, name, help_, label,
                         buckets=buckets)

    # -- stat groups ---------------------------------------------------------

    def register_group(self, name: str, fn, labeled: str | None = None
                       ) -> None:
        """Register a zero-arg dict-snapshot callable. ``labeled``
        names the label the group's TOP-LEVEL keys map to (e.g. the
        per-model counter dict registers ``labeled="model"`` so its
        exposition is ``h2o_stats_models_requests{model=...}`` with
        the top-K + `other` cap applied at scrape time). Idempotent
        by name — last registration wins (in-process restarts)."""
        if labeled is not None and labeled not in ALLOWED_LABELS:
            raise ValueError(f"group {name!r}: label {labeled!r} not "
                             "in the allowlist")
        with self._lock:
            self._groups[name] = (fn, labeled)

    def group_snapshot(self, names=None) -> dict:
        """{group: fn()} — THE /3/Stats payload source. A group whose
        snapshot raises contributes an error marker instead of killing
        the scrape (a stats read must never 500 the probe surface)."""
        with self._lock:
            items = [(n, f) for n, (f, _l) in self._groups.items()
                     if names is None or n in names]
        out = {}
        for n, fn in items:
            try:
                out[n] = fn()
            except Exception as e:  # noqa: BLE001
                out[n] = {"error": repr(e)[:200]}
        return out

    # -- exposition ----------------------------------------------------------

    @staticmethod
    def _flatten(path: tuple, obj, out: list) -> None:
        if isinstance(obj, bool):
            out.append((metric_name(*path), {}, 1.0 if obj else 0.0))
        elif isinstance(obj, (int, float)):
            out.append((metric_name(*path), {}, float(obj)))
        elif isinstance(obj, str):
            # string leaves (breaker/lifecycle state) become an
            # info-style sample: h2o_stats_..._state{value="open"} 1
            out.append((metric_name(*path),
                        {"value": obj[:120]}, 1.0))
        elif isinstance(obj, dict):
            for k, v in obj.items():
                MetricsRegistry._flatten(path + (str(k),), v, out)
        # lists/None: no numeric identity — skipped by design

    @staticmethod
    def _flatten_labeled(group: str, label: str, obj: dict,
                         out: list) -> None:
        """{label_value: {counter: num}} with the top-K-by-traffic +
        `other` rollup applied at scrape time (rank = the series' own
        numeric mass, so the hot tenants keep their series)."""
        k = _topk()
        vals = [(str(lv), rec) for lv, rec in obj.items()
                if isinstance(rec, dict)]

        def mass(rec: dict) -> float:
            return sum(v for v in rec.values()
                       if isinstance(v, (int, float))
                       and not isinstance(v, bool))

        vals.sort(key=lambda it: -mass(it[1]))
        keep, roll = vals[:k], vals[k:]
        rolled: dict[tuple, float] = {}
        for lv, rec in keep:
            flat: list = []
            MetricsRegistry._flatten((group,), rec, flat)
            for name, lbls, v in flat:
                out.append((name, {label: lv, **lbls}, v))
        for _lv, rec in roll:
            flat = []
            MetricsRegistry._flatten((group,), rec, flat)
            for name, lbls, v in flat:
                if lbls:        # string leaves don't aggregate
                    continue
                rolled[(name,)] = rolled.get((name,), 0.0) + v
        for (name,), v in rolled.items():
            out.append((name, {label: "other"}, v))

    def prometheus_text(self, extra_groups: dict | None = None) -> str:
        """The ``GET /metrics`` payload: every first-class metric plus
        every registered stat group's numeric leaves. ``extra_groups``
        lets a per-instance surface (the router) merge its snapshot
        into ITS server's exposition without registering process-wide
        state."""
        lines: list[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
            groups = list(self._groups.items())
        for m in metrics:
            lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for name, labels, v in m.samples():
                lines.append(_render_sample(name, labels, v))
        flat: list = []
        for gname, (fn, labeled) in groups:
            try:
                snap = fn()
            except Exception:  # noqa: BLE001 — one group must not
                continue       # kill the whole exposition
            if labeled and isinstance(snap, dict):
                self._flatten_labeled(gname, labeled, snap, flat)
            else:
                self._flatten((gname,), snap, flat)
        for gname, snap in (extra_groups or {}).items():
            self._flatten((gname,), snap, flat)
        seen_types = set()
        for name, labels, v in flat:
            base = name
            if base not in seen_types:
                seen_types.add(base)
                lines.append(f"# TYPE {base} gauge")
            lines.append(_render_sample(name, labels, v))
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Tests only: drop every first-class metric (groups stay —
        their owners registered them at import)."""
        with self._lock:
            self._metrics.clear()


def _render_sample(name: str, labels: dict, v: float) -> str:
    if labels:
        lab = ",".join(f'{k}="{_escape_label(val)}"'
                       for k, val in sorted(labels.items()))
        return f"{name}{{{lab}}} {v:g}"
    return f"{name} {v:g}"


def parse_prometheus_text(text: str) -> dict:
    """Inverse of the exposition (fleet_top + the inventory-diff test):
    {(name, ((label, value), ...)): float}."""
    out: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            body, _, val = line.rpartition(" ")
            name, labels = body, ()
            if "{" in body:
                name, _, rest = body.partition("{")
                rest = rest.rstrip("}")
                lbls = []
                for part in _split_labels(rest):
                    k, _, v = part.partition("=")
                    lbls.append((k, v.strip('"')
                                 .replace('\\"', '"')
                                 .replace("\\n", "\n")
                                 .replace("\\\\", "\\")))
                labels = tuple(sorted(lbls))
            out[(name, labels)] = float(val)
        except ValueError:
            continue
    return out


def _split_labels(s: str) -> list[str]:
    parts, depth, cur = [], False, []
    for ch in s:
        if ch == '"':
            depth = not depth
        if ch == "," and not depth:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        parts.append("".join(cur))
    return parts


REGISTRY = MetricsRegistry()


def register_group(name: str, fn, labeled: str | None = None) -> None:
    REGISTRY.register_group(name, fn, labeled)


def group_snapshot(names=None) -> dict:
    return REGISTRY.group_snapshot(names)


def prometheus_text(extra_groups: dict | None = None) -> str:
    return REGISTRY.prometheus_text(extra_groups)


def write_metrics(handler, extra_groups: dict | None = None) -> None:
    """THE GET /metrics response writer — shared by the replica REST
    handler, the router front door, and the status listener so the
    exposition response (content type, headers) cannot drift between
    surfaces. ``handler`` is any BaseHTTPRequestHandler."""
    body = prometheus_text(extra_groups).encode()
    handler.send_response(200)
    handler.send_header("Content-Type", CONTENT_TYPE)
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


# ---------------------------------------------------------------------------
# Build info
# ---------------------------------------------------------------------------

_STARTED_AT = time.time()
_BUILD: dict | None = None
_BUILD_LOCK = threading.Lock()


def build_info() -> dict:
    """Which build produced this artifact/scrape: package version,
    jax/jaxlib versions (metadata only — NEVER imports jax: the router
    and operator are device-free processes), pid, uptime, and the host
    CPU-feature fingerprint already keying the XLA cache dir."""
    global _BUILD
    with _BUILD_LOCK:
        if _BUILD is None:
            from importlib import metadata

            def _ver(pkg: str) -> str | None:
                try:
                    return metadata.version(pkg)
                except Exception:  # noqa: BLE001
                    return None

            from .backend import host_features_fingerprint

            # package version WITHOUT importing the package: the
            # top-level __init__ pulls the frame/model stack (and jax
            # with it), which a device-free router/operator process
            # must never pay for a version string
            import sys
            pkg = sys.modules.get("h2o_kubernetes_tpu")
            pkg_version = getattr(pkg, "__version__", None)
            if pkg_version is None:
                pkg_version = _ver("h2o_kubernetes_tpu") \
                    or _ver("h2o-kubernetes-tpu")
            if pkg_version is None:
                try:
                    src = os.path.join(os.path.dirname(
                        os.path.dirname(os.path.abspath(__file__))),
                        "__init__.py")
                    with open(src) as f:
                        for line in f:
                            if line.startswith("__version__"):
                                pkg_version = line.split('"')[1]
                                break
                except Exception:  # noqa: BLE001
                    pkg_version = None
            _BUILD = {
                "version": pkg_version,
                "jax": _ver("jax"),
                "jaxlib": _ver("jaxlib"),
                "hostfp": host_features_fingerprint(),
                "pid": os.getpid(),
                "started_at": round(_STARTED_AT, 3),
            }
        out = dict(_BUILD)
    out["uptime_s"] = round(time.time() - _STARTED_AT, 3)
    return out


def _register_build_gauge() -> None:
    """`h2o_build_info{version=...,jax=...,hostfp=...} 1` — the
    Prometheus idiom for build metadata (join on it, never sum it)."""
    b = build_info()

    class _Info(Gauge):
        def samples(self):
            return [("h2o_build_info",
                     {k: str(b.get(k)) for k in
                      ("version", "jax", "jaxlib", "hostfp")}, 1.0)]

    with REGISTRY._lock:
        REGISTRY._metrics.setdefault(
            "h2o_build_info",
            _Info("h2o_build_info",
                  "build identity (constant 1; labels carry it)",
                  None, REGISTRY._lock))


_register_build_gauge()


# ---------------------------------------------------------------------------
# Request tracing
# ---------------------------------------------------------------------------


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def trace_id_from(headers) -> str:
    """The propagation contract: take X-H2O-Trace-Id when present and
    well-formed (alnum/_/- up to 64 chars — a header is attacker
    input and becomes a dict key + response header), else mint."""
    raw = headers.get("X-H2O-Trace-Id") if headers is not None else None
    if raw:
        tid = str(raw).strip()[:64]
        if tid and all(c.isalnum() or c in "-_" for c in tid):
            return tid
    return new_trace_id()


class TraceRing:
    """Bounded per-process span store: trace_id -> span record. The
    ring (H2O_TPU_TRACE_RING entries, default 512) evicts oldest-
    inserted, so a serving storm can never grow it — recent traces are
    the debuggable ones anyway."""

    def __init__(self, capacity: int | None = None):
        self._lock = threading.Lock()
        self._ring: collections.OrderedDict[str, dict] = \
            collections.OrderedDict()
        self._cap = capacity

    def _capacity(self) -> int:
        if self._cap is not None:
            return self._cap
        return max(8, int(_env_float("H2O_TPU_TRACE_RING", 512.0)))

    # spans kept per RECORD: the ring bounds record count, this bounds
    # a single record — a client reusing one (valid-looking) trace id
    # for every request must not grow one record without limit
    MAX_SPANS = 256

    def record(self, trace_id: str, spans, **meta) -> None:
        """Append spans under ``trace_id`` (merging with an existing
        record — a hedged request's two legs land on one trace).
        Past MAX_SPANS per record, further spans are dropped and the
        record is flagged ``truncated`` (a reused id is a client bug
        or an attack, never a reason for unbounded memory)."""
        if not _trace_on():
            return
        with self._lock:
            rec = self._ring.get(trace_id)
            if rec is None:
                rec = {"trace_id": trace_id, "ts": time.time(),
                       "spans": []}
                self._ring[trace_id] = rec
                while len(self._ring) > self._capacity():
                    self._ring.popitem(last=False)
            room = self.MAX_SPANS - len(rec["spans"])
            if room <= 0:
                rec["truncated"] = True
            else:
                spans = list(spans)
                if len(spans) > room:
                    rec["truncated"] = True
                rec["spans"].extend(spans[:room])
            for k, v in meta.items():
                rec.setdefault(k, v)

    def get(self, trace_id: str) -> dict | None:
        with self._lock:
            rec = self._ring.get(trace_id)
            return None if rec is None else {
                **rec, "spans": list(rec["spans"])}

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


TRACER = TraceRing()


def request_phase_histogram() -> Histogram:
    return REGISTRY.histogram(
        "h2o_request_phase_seconds",
        "per-request serving phase latency "
        "(admission|queue|assemble|dispatch|total)", label="phase")


def record_request_phases(trace_id: str | None, marks: dict,
                          t_start: float, t_end: float,
                          model: str | None = None,
                          slo: str | None = None,
                          kind: str = "score",
                          outcome: str | None = None) -> list:
    """Turn the batcher's timestamp marks into named spans, feed the
    per-phase latency histograms (+ the per-model total-latency
    histogram, capped top-K), and file the span record under
    ``trace_id``. Returns the span list (the route echoes nothing —
    GET /3/Trace/{id} serves it). ``outcome`` marks a FAILED request
    (shed/504/breaker/timeout error name): the slow requests tracing
    exists to debug are exactly the ones that die in the queue, so
    they must appear in the ring and the histograms too — phases
    without marks (never dispatched) simply contribute no span."""
    hist = request_phase_histogram()

    def span(name, a, b):
        if a is None or b is None or b < a:
            return None
        dur = b - a
        hist.observe(dur, label_value=name)
        return {"name": name, "ms": round(dur * 1000.0, 3)}

    spans = [s for s in (
        span("admission", marks.get("admit"), marks.get("enqueue")),
        span("queue", marks.get("enqueue"), marks.get("pop")),
        span("assemble", marks.get("pop"), marks.get("dispatch_start")),
        span("dispatch", marks.get("dispatch_start"),
             marks.get("dispatch_end")),
        span("total", t_start, t_end),
    ) if s is not None]
    if t_start is not None:
        REGISTRY.histogram(
            "h2o_request_seconds",
            "end-to-end request latency per model (top-K + other)",
            label="model").observe(t_end - t_start,
                                   label_value=model)
    if trace_id:
        meta = {"model": model, "slo": slo, "kind": kind,
                "hop": "replica"}
        if outcome is not None:
            meta["outcome"] = outcome
        TRACER.record(trace_id, spans, **meta)
    return spans


# ---------------------------------------------------------------------------
# Training phase spans
# ---------------------------------------------------------------------------


def train_phase_histogram() -> Histogram:
    return REGISTRY.histogram(
        "h2o_train_phase_seconds",
        "training phase durations (bin|boost|level_hist|split_find|"
        "chunk_upload|compile_ahead_fill)", label="phase",
        buckets=(0.001, 0.005, 0.025, 0.1, 0.5, 2.0, 10.0, 60.0,
                 300.0))


@contextlib.contextmanager
def phase_span(phase: str, **data):
    """Time a training/scheduler phase into the per-phase histogram
    AND the diagnostics TimeLine (kind="phase") — the /3/Timeline ring
    keeps the sequence, the histogram keeps the distribution."""
    t0 = time.monotonic()
    try:
        yield
    finally:
        dur = time.monotonic() - t0
        train_phase_histogram().observe(dur, label_value=phase)
        try:
            from ..diagnostics import timeline

            timeline.record("phase", phase, phase=phase,
                            dur_ms=round(dur * 1000.0, 3), **data)
        except Exception:  # noqa: BLE001 — accounting only
            pass


# -- out-of-core stream overlap accounting ----------------------------------
#
# The ooc chunk stream double-buffers host->device uploads against the
# histogram build (arXiv:2005.09148's design); SCALING.md previously
# ESTIMATED how well that overlap works. The stream now reports it:
# upload seconds (time blocked in device_put), compute seconds (time
# the consumer held the generator suspended), and the derived
# overlap-efficiency gauge compute/(compute+upload) — 1.0 means every
# upload hid fully under compute.

_OOC_LOCK = threading.Lock()
_OOC = {"upload_s": 0.0, "compute_s": 0.0, "wall_s": 0.0, "streams": 0}


def ooc_stream_account(upload_s: float, compute_s: float,
                       wall_s: float) -> None:
    with _OOC_LOCK:
        _OOC["upload_s"] += upload_s
        _OOC["compute_s"] += compute_s
        _OOC["wall_s"] += wall_s
        _OOC["streams"] += 1
    REGISTRY.counter("h2o_ooc_upload_seconds_total",
                     "time blocked uploading ooc chunks").inc(upload_s)
    REGISTRY.counter("h2o_ooc_compute_seconds_total",
                     "consumer compute time over the ooc stream"
                     ).inc(compute_s)
    denom = _OOC["upload_s"] + _OOC["compute_s"]
    REGISTRY.gauge(
        "h2o_ooc_overlap_ratio",
        "fraction of stream time spent computing (1.0 = uploads "
        "fully hidden under compute)").set(
        _OOC["compute_s"] / denom if denom > 0 else 0.0)


def ooc_overlap_snapshot() -> dict:
    with _OOC_LOCK:
        out = dict(_OOC)
    denom = out["upload_s"] + out["compute_s"]
    out["overlap_ratio"] = round(out["compute_s"] / denom, 4) \
        if denom > 0 else None
    return out


register_group("ooc_stream", ooc_overlap_snapshot)


# ---------------------------------------------------------------------------
# Operator events
# ---------------------------------------------------------------------------


def count_event(kind: str) -> None:
    """Reconciler/ShardedPool events re-registered through the
    registry (`h2o_operator_events_total{event=...}`) — the durable
    store keeps the ring, /metrics keeps the rates."""
    REGISTRY.counter("h2o_operator_events_total",
                     "operator reconcile events by kind",
                     label="event").inc(label_value=str(kind)[:64])


# ---------------------------------------------------------------------------
# Status listener (operator.run / any device-free process)
# ---------------------------------------------------------------------------


def start_status_listener(port: int, host: str = "127.0.0.1",
                          extra_groups=None):
    """A tiny /metrics + /healthz HTTP listener for processes that do
    not run the full REST node (the operator). ``extra_groups`` is a
    zero-arg callable -> dict merged into the exposition. Returns the
    server (``server_address[1]`` is the bound port — pass 0 for an
    ephemeral one); None when port is None. The CALLER owns the
    off-by-default policy (operator.run starts one only when
    --status-port / H2O_TPU_METRICS_PORT says so). Never imports jax
    or rest.py."""
    if port is None:
        return None
    import json
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _StatusHandler(BaseHTTPRequestHandler):
        server_version = "h2o-tpu-status/1"

        def log_message(self, *a):
            pass

        def _send(self, code, body: bytes, ctype: str):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            path = self.path.split("?", 1)[0].rstrip("/")
            if path == "/metrics":
                eg = None
                if extra_groups is not None:
                    try:
                        eg = extra_groups()
                    except Exception:  # noqa: BLE001
                        eg = None
                return write_metrics(self, eg)
            if path == "/healthz":
                return self._send(
                    200, json.dumps(
                        {"alive": True, "build": build_info()}
                    ).encode(), "application/json")
            return self._send(404, b"not found", "text/plain")

    srv = ThreadingHTTPServer((host, int(port)), _StatusHandler)
    t = threading.Thread(target=srv.serve_forever,
                         name="h2o-tpu-status", daemon=True)
    t.start()
    return srv
