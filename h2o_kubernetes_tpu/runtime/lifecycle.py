"""Node lifecycle + circuit breaking — the Kubernetes-grade serving envelope.

The reference project is an operator whose whole job is keeping H2O
pods alive behind StatefulSet readiness/liveness probes; this module is
the in-process half of that contract for the TPU node:

- a **lifecycle state machine** — STARTING → SERVING → DRAINING →
  TERMINATED — with a SIGTERM drain path: stop admitting new work, let
  the REST micro-batcher flush its in-flight scoring requests, wait for
  RUNNING jobs up to ``H2O_TPU_DRAIN_TIMEOUT`` seconds (then fail them
  cleanly), join the heartbeat thread, run registered shutdown hooks
  (the REST server), and only then terminate. The kubelet's
  ``terminationGracePeriodSeconds`` should exceed the drain timeout.
- a **circuit breaker** (closed / open / half-open) over device
  dispatch: ``H2O_TPU_BREAKER_FAILURES`` *consecutive* device-dispatch
  errors trip it open; while open every guarded dispatch is rejected
  instantly with ``CircuitOpenError`` (a ``ClusterHealthError``, so the
  REST layer 503s) without touching the device; after
  ``H2O_TPU_BREAKER_COOLDOWN`` seconds the next call is admitted as the
  half-open probe — success closes the breaker, failure re-opens it
  with a fresh cooldown.

The breaker complements the health layer rather than replacing it: a
*locked* cloud (failed heartbeat, device error escaping a training
step) still needs an explicit ``health.reset()``; the breaker handles
the other shape of failure — a device that keeps erroring per dispatch
without the mesh being declared dead — where hammering it with every
request only digs the hole deeper.

Readiness (rest.py ``/readyz``) is the conjunction: state == SERVING
∧ breaker not open ∧ cloud healthy. Liveness (``/healthz``) stays true
through DRAINING so the kubelet does not kill a draining pod early.

Env knobs (read at use time, like the other robustness switches):

- ``H2O_TPU_DRAIN_TIMEOUT``     seconds to wait for RUNNING jobs +
  batcher flush before failing them (default 30)
- ``H2O_TPU_BREAKER_FAILURES``  consecutive dispatch errors that trip
  the breaker (default 5)
- ``H2O_TPU_BREAKER_COOLDOWN``  seconds open before the half-open
  probe (default 30)

Rehearsal: the ``lifecycle.drain`` fault point fires at drain entry
(kinds ``hang``/``error`` — a slow or failing drain step must never
leave the node undrained), and ``score.dispatch`` (models/base.py)
feeds the breaker deterministically via kind ``dispatch_error``.
``tools/chaos.py drain-under-load`` and ``breaker-trip`` drill both
paths end-to-end; tests/test_lifecycle.py is the tier-1 coverage.
"""

from __future__ import annotations

import contextlib
import os
import signal
import threading
import time
from typing import Callable, Iterator

from .health import ClusterHealthError
from .retry import _env_float

__all__ = [
    "STARTING", "SERVING", "DRAINING", "TERMINATED",
    "CircuitBreaker", "CircuitOpenError", "NodeDrainingError", "BREAKER",
    "breaker_guard", "state", "accepting", "mark_serving", "begin_drain",
    "drain", "install_sigterm", "remaining_drain_budget", "status",
    "register_shutdown", "terminated", "wait_terminated", "reset",
    "cordon", "uncordon", "cordoned",
]

STARTING = "STARTING"
SERVING = "SERVING"
DRAINING = "DRAINING"
TERMINATED = "TERMINATED"


class CircuitOpenError(ClusterHealthError):
    """The dispatch circuit breaker is open — the device is being given
    its cooldown, not another doomed dispatch. Subclasses
    ClusterHealthError so every existing locked-cloud handler (REST 503
    mapping, training loops' fail-fast) treats it uniformly."""

    def __init__(self, msg: str, retry_after: float = 1.0):
        super().__init__(msg)
        self.retry_after = retry_after


class NodeDrainingError(ClusterHealthError):
    """New work refused because the node is DRAINING/TERMINATED."""


class CircuitBreaker:
    """Closed / open / half-open breaker over device dispatch.

    State transitions (all under one lock):

    - closed → open: ``H2O_TPU_BREAKER_FAILURES`` consecutive failures.
    - open → half-open: reads half-open once the cooldown elapses; the
      next admitted call *claims* the single probe slot.
    - half-open → closed: the probe succeeds (consecutive count reset).
    - half-open → open: the probe fails; fresh cooldown.

    ``check()`` is the non-claiming admission test (queue front doors);
    ``allow()`` is the claiming one (the dispatch itself) — only
    ``allow()`` may take the half-open probe slot, so a front-door
    check can never burn the probe admission.
    """

    def __init__(self, name: str = "device-dispatch"):
        self.name = name
        self._lock = threading.Lock()
        self._state = "closed"
        self._consecutive = 0
        self._opened_at = 0.0
        self._probing = False
        self.stats = {"trips": 0, "short_circuited": 0, "probes": 0,
                      "closes": 0, "failures": 0}

    @staticmethod
    def _threshold() -> int:
        return max(1, int(_env_float("H2O_TPU_BREAKER_FAILURES", 5.0)))

    @staticmethod
    def _cooldown() -> float:
        return max(0.0, _env_float("H2O_TPU_BREAKER_COOLDOWN", 30.0))

    # -- state ----------------------------------------------------------------

    def _effective_locked(self) -> str:
        if self._state == "open" and not self._probing and \
                time.monotonic() - self._opened_at >= self._cooldown():
            return "half-open"
        return self._state

    def state(self) -> str:
        with self._lock:
            return self._effective_locked()

    def status(self) -> dict:
        with self._lock:
            st = self._effective_locked()
            rem = 0.0
            if st == "open":
                rem = max(0.0, self._cooldown()
                          - (time.monotonic() - self._opened_at))
            return {"state": st, "consecutive_failures": self._consecutive,
                    "cooldown_remaining_s": round(rem, 3), **self.stats}

    def reset(self) -> None:
        """Force-close (tests / explicit operator recovery)."""
        with self._lock:
            self._state = "closed"
            self._consecutive = 0
            self._probing = False

    def release_probe(self) -> None:
        """Free a claimed half-open probe slot without recording an
        outcome — the guarded dispatch died for non-device reasons
        (caller bug, KeyboardInterrupt), which says nothing about the
        device. The breaker stays open with its original cooldown (by
        now elapsed), so the NEXT dispatch becomes the probe; without
        this release the slot would leak and every later ``allow()``
        would reject forever on a healthy device."""
        with self._lock:
            if self._probing:
                self._state = "open"
                self._probing = False

    # -- admission ------------------------------------------------------------

    def _reject_locked(self) -> CircuitOpenError:
        self.stats["short_circuited"] += 1
        rem = max(0.0, self._cooldown()
                  - (time.monotonic() - self._opened_at))
        return CircuitOpenError(
            f"{self.name} circuit breaker is open "
            f"({self._consecutive} consecutive dispatch failures); "
            f"retry in {max(rem, 0.1):.1f}s",
            retry_after=max(rem, 0.1))

    def check(self) -> None:
        """Raise CircuitOpenError while firmly open; never claims the
        half-open probe slot (safe at queue front doors)."""
        with self._lock:
            st = self._effective_locked()
            if st == "open":
                raise self._reject_locked()

    def allow(self) -> None:
        """Admission for one dispatch: passes when closed, claims THE
        half-open probe when the cooldown has elapsed, raises
        CircuitOpenError otherwise."""
        with self._lock:
            st = self._effective_locked()
            if st == "closed":
                return
            if st == "half-open" and not self._probing:
                self._state = "half-open"
                self._probing = True
                self.stats["probes"] += 1
                return
            raise self._reject_locked()

    # -- outcomes -------------------------------------------------------------

    def record_success(self) -> None:
        closed_now = False
        with self._lock:
            if self._state != "closed":
                closed_now = True
                self.stats["closes"] += 1
            self._state = "closed"
            self._consecutive = 0
            self._probing = False
        if closed_now:
            from ..diagnostics import log, timeline

            timeline.record("breaker_closed", self.name)
            log.warning("circuit breaker %s: half-open probe succeeded "
                        "— closed", self.name)

    def record_failure(self, err: str = "") -> None:
        tripped = False
        with self._lock:
            self._consecutive += 1
            self.stats["failures"] += 1
            if self._state in ("open", "half-open"):
                # failed probe (or a straggler dispatch admitted before
                # the trip): stay/return open with a fresh cooldown
                self._state = "open"
                self._opened_at = time.monotonic()
                self._probing = False
            elif self._consecutive >= self._threshold():
                self._state = "open"
                self._opened_at = time.monotonic()
                self.stats["trips"] += 1
                tripped = True
        if tripped:
            from ..diagnostics import log, timeline

            timeline.record("breaker_open", err[:200],
                            consecutive=self._consecutive)
            log.error("circuit breaker %s: OPEN after %d consecutive "
                      "dispatch failures (last: %s)", self.name,
                      self._consecutive, err[:200])


BREAKER = CircuitBreaker()


@contextlib.contextmanager
def breaker_guard(desc: str = "device dispatch") -> Iterator[None]:
    """Run one device dispatch under the breaker: admission check on
    entry, outcome recording on exit. Only device-shaped failures
    (ClusterHealthError — what health.device_dispatch converts runtime
    errors into — and raw XLA/injected device errors) count against the
    breaker; a caller's bad inputs (ValueError & co.) say nothing about
    the device and pass through untallied."""
    from .health import is_device_error

    BREAKER.allow()
    try:
        yield
    except BaseException as e:
        if isinstance(e, CircuitOpenError):
            raise                     # our own rejection is not evidence
        if isinstance(e, ClusterHealthError) or is_device_error(e):
            BREAKER.record_failure(repr(e))
        else:
            # non-device failure: no evidence either way, but a claimed
            # half-open probe slot must be released or it leaks forever
            BREAKER.release_probe()
        raise
    else:
        BREAKER.record_success()


# ---------------------------------------------------------------------------
# Lifecycle state machine
# ---------------------------------------------------------------------------


class _Lifecycle:
    def __init__(self):
        self._lock = threading.Lock()
        self._state = STARTING
        self._drain_deadline: float | None = None
        self._drain_thread: threading.Thread | None = None
        self._terminated = threading.Event()
        self._callbacks: list[Callable[[], None]] = []
        self._exit_on_drain = False
        self._exit_code = 0
        self._installed = False
        self._prev_sigterm = None
        # bumped by reset(): a drain thread still in flight from the
        # previous epoch sees the mismatch and abandons instead of
        # clobbering the restarted node (forcing TERMINATED over
        # SERVING, shutting down the new server, os._exit-ing)
        self._epoch = 0
        # cordon = endpoint removal WITHOUT draining: /readyz goes 503
        # so routers stop sending, while admission stays open so
        # requests already routed here still get served (the k8s
        # endpoints-removed-before-SIGTERM window the operator's
        # rolling updates rely on for zero 5xx)
        self._cordoned: str | None = None

    # -- queries --------------------------------------------------------------

    def state(self) -> str:
        with self._lock:
            return self._state

    def accepting(self) -> bool:
        """True while new work may be admitted (STARTING covers
        library-only use that never calls mark_serving)."""
        with self._lock:
            return self._state in (STARTING, SERVING)

    def remaining_drain_budget(self) -> float | None:
        """Seconds left in the drain window: None while not draining,
        0.0 once TERMINATED. Retry sleeps consult this so a retried
        persist write can never outlive the drain timeout."""
        with self._lock:
            if self._state == TERMINATED:
                return 0.0
            if self._state != DRAINING or self._drain_deadline is None:
                return None
            return max(0.0, self._drain_deadline - time.monotonic())

    # -- transitions ----------------------------------------------------------

    def mark_serving(self) -> None:
        with self._lock:
            if self._state == STARTING:
                self._state = SERVING

    def cordon(self, reason: str = "operator") -> None:
        """Flip readiness off WITHOUT refusing work: the pool
        reconciler cordons a replica, waits the deregister grace (so
        routers drop the endpoint), and only then begins the drain —
        in-flight and straggler requests still score."""
        with self._lock:
            self._cordoned = reason or "cordoned"
        from ..diagnostics import log, timeline

        timeline.record("cordon", reason)
        log.warning("lifecycle: cordoned (%s) — readiness off, "
                    "admission still open", reason)

    def uncordon(self) -> None:
        with self._lock:
            self._cordoned = None

    def cordoned(self) -> str | None:
        with self._lock:
            return self._cordoned

    def register_shutdown(self, cb: Callable[[], None]) -> None:
        """Hook run at the END of the drain (after batcher flush and
        job settlement) — e.g. the REST server's shutdown. Idempotent
        by identity: re-registering the same callable (a module-level
        hook across server restarts) does not accumulate entries."""
        with self._lock:
            if cb not in self._callbacks:
                self._callbacks.append(cb)

    def begin_drain(self, reason: str = "",
                    timeout: float | None = None) -> threading.Thread:
        """SERVING/STARTING → DRAINING; returns the (daemon) drain
        thread. Idempotent: a second SIGTERM joins the drain already in
        flight instead of starting another."""
        if timeout is None:
            timeout = _env_float("H2O_TPU_DRAIN_TIMEOUT", 30.0)
        with self._lock:
            if self._state in (DRAINING, TERMINATED):
                return self._drain_thread
            self._state = DRAINING
            # deadline published HERE, atomically with the state flip:
            # remaining_drain_budget() must never see DRAINING with no
            # deadline (the drain-gate Retry-After and the retry
            # layer's sleep clamp both consult it immediately)
            self._drain_deadline = time.monotonic() + max(0.0, timeout)
            t = threading.Thread(target=self._drain,
                                 args=(reason, timeout, self._epoch,
                                       self._terminated),
                                 name="h2o-tpu-drain", daemon=True)
            self._drain_thread = t
        from ..diagnostics import log, timeline

        timeline.record("drain_begin", reason)
        log.warning("lifecycle: DRAINING (%s)", reason or "requested")
        t.start()
        return t

    def _stale(self, epoch: int, reason: str) -> bool:
        """True when reset() started a new epoch while this drain was
        in flight — the drain must abandon, not touch the new state."""
        with self._lock:
            stale = self._epoch != epoch
        if stale:
            from ..diagnostics import log

            log.warning("lifecycle: drain (%s) abandoned — reset() "
                        "started a new epoch mid-drain", reason)
        return stale

    def _drain(self, reason: str, timeout: float,
               epoch: int, term_event: threading.Event) -> None:
        from ..diagnostics import log, timeline

        with self._lock:
            deadline = self._drain_deadline   # published by begin_drain
        from . import faults

        try:
            faults.fire("lifecycle.drain")
        except Exception as e:  # noqa: BLE001 — an injected drain fault
            # must be observable, never leave the node undrained
            log.error("lifecycle.drain fault during drain: %r", e)

        # 1. flush the scoring micro-batcher: in-flight waiters get
        # their terminal responses; new submits are already refused
        try:
            from .. import rest

            rest.BATCHER.stop(
                timeout=max(0.0, deadline - time.monotonic()))
        except Exception as e:  # noqa: BLE001
            log.error("drain: batcher flush failed: %r", e)

        if self._stale(epoch, reason):
            return
        # 2. wait for RUNNING jobs, then fail the stragglers cleanly
        try:
            from ..automl import JOBS

            while time.monotonic() < deadline:
                if not any(j.status == "RUNNING" for j in JOBS.values()):
                    break
                time.sleep(0.05)
            for j in list(JOBS.values()):
                if j.status == "RUNNING":
                    j.failed(
                        "node draining: job still RUNNING at the drain "
                        f"deadline (H2O_TPU_DRAIN_TIMEOUT={timeout:g}s)"
                        + (f"; reason: {reason}" if reason else ""))
        except Exception as e:  # noqa: BLE001
            log.error("drain: job settlement failed: %r", e)

        if self._stale(epoch, reason):
            return
        # 3. stop + join the heartbeat thread
        try:
            from . import health

            health.stop_heartbeat(join=True, timeout=5.0)
        except Exception as e:  # noqa: BLE001
            log.error("drain: heartbeat stop failed: %r", e)

        # 4. shutdown hooks (REST server stops accepting connections)
        with self._lock:
            if self._epoch != epoch:
                cbs = None
            else:
                cbs = list(self._callbacks)
        if cbs is None:
            self._stale(epoch, reason)     # logs the abandonment
            return
        for cb in cbs:
            try:
                cb()
            except Exception as e:  # noqa: BLE001
                log.error("drain: shutdown hook %r failed: %r", cb, e)

        with self._lock:
            if self._epoch != epoch:
                stale = True
            else:
                stale = False
                self._state = TERMINATED
                exit_on_drain = self._exit_on_drain
                exit_code = self._exit_code
        if stale:
            self._stale(epoch, reason)
            return
        timeline.record("drain_complete", reason)
        log.warning("lifecycle: TERMINATED (drain complete)")
        # the event captured at begin_drain, NOT self._terminated: a
        # reset() swapped in a fresh event for the new epoch, and a
        # stale drain must never set that one
        term_event.set()
        if exit_on_drain:
            # skip atexit/GC: lingering daemon threads (a wedged probe
            # parked in a collective) must not outlive the grace period
            os._exit(exit_code)

    # -- signals --------------------------------------------------------------

    def install_sigterm(self, exit_on_drain: bool = True,
                        exit_code: int = 0) -> bool:
        """Install the SIGTERM → drain handler (main thread only;
        returns False when it cannot install). With ``exit_on_drain``
        the process exits as soon as the drain completes — the
        kubelet's SIGKILL at the grace-period boundary should never be
        needed."""
        if self._installed:
            # reset() (in-process restart) clears _exit_on_drain but the
            # handler stays installed — refresh the exit policy so a
            # re-started server still exits when its drain completes
            self._exit_on_drain = exit_on_drain
            self._exit_code = exit_code
            return True
        if threading.current_thread() is not threading.main_thread():
            return False
        self._exit_on_drain = exit_on_drain
        self._exit_code = exit_code
        prev = signal.getsignal(signal.SIGTERM)
        self._prev_sigterm = prev
        trigger = threading.Event()

        def waiter():
            # loops so a reset() (new epoch) + later SIGTERM still
            # drains; begin_drain is idempotent within an epoch
            while True:
                trigger.wait()
                trigger.clear()
                self.begin_drain(reason="SIGTERM")

        threading.Thread(target=waiter, name="h2o-tpu-sigterm-drain",
                         daemon=True).start()

        def handler(signum, frame):
            # only set a flag here: begin_drain takes the (non-
            # reentrant) lifecycle lock, and the handler runs on the
            # main thread — which may BE the current lock holder
            # (status()/state() mid-call), a guaranteed self-deadlock
            trigger.set()
            # chain an embedder's pre-existing handler (SIG_DFL/SIG_IGN
            # are ints, not callable) — its cleanup must not be lost,
            # but it also must not be able to kill the drain:
            # BaseException because sys.exit() (SystemExit) in a chained
            # handler would otherwise tear down the interpreter mid-drain
            if callable(prev):
                try:
                    prev(signum, frame)
                except BaseException:  # noqa: BLE001
                    pass

        signal.signal(signal.SIGTERM, handler)
        self._installed = True
        return True

    def reset(self) -> None:
        """Back to STARTING (tests / in-process cluster restart). Does
        NOT uninstall a signal handler; clears shutdown hooks. Bumps
        the epoch so a drain thread still in flight abandons rather
        than terminating the restarted node."""
        with self._lock:
            self._epoch += 1
            self._state = STARTING
            self._drain_deadline = None
            self._drain_thread = None
            self._callbacks.clear()
            self._exit_on_drain = False
            self._cordoned = None
            self._terminated = threading.Event()
        BREAKER.reset()


LIFECYCLE = _Lifecycle()


# module-level façade (the rest of the runtime imports functions, not
# the singleton, mirroring health.py's shape)

def state() -> str:
    return LIFECYCLE.state()


def accepting() -> bool:
    return LIFECYCLE.accepting()


def mark_serving() -> None:
    LIFECYCLE.mark_serving()


def begin_drain(reason: str = "",
                timeout: float | None = None) -> threading.Thread:
    return LIFECYCLE.begin_drain(reason=reason, timeout=timeout)


def drain(reason: str = "", timeout: float | None = None) -> None:
    """Synchronous drain (chaos drills, tests, explicit shutdown)."""
    t = LIFECYCLE.begin_drain(reason=reason, timeout=timeout)
    if t is not None:
        t.join()


def install_sigterm(exit_on_drain: bool = True, exit_code: int = 0) -> bool:
    return LIFECYCLE.install_sigterm(exit_on_drain=exit_on_drain,
                                     exit_code=exit_code)


def remaining_drain_budget() -> float | None:
    return LIFECYCLE.remaining_drain_budget()


def register_shutdown(cb: Callable[[], None]) -> None:
    LIFECYCLE.register_shutdown(cb)


def terminated() -> bool:
    return LIFECYCLE._terminated.is_set()


def wait_terminated(timeout: float | None = None) -> bool:
    return LIFECYCLE._terminated.wait(timeout)


def reset() -> None:
    LIFECYCLE.reset()


def cordon(reason: str = "operator") -> None:
    LIFECYCLE.cordon(reason)


def uncordon() -> None:
    LIFECYCLE.uncordon()


def cordoned() -> str | None:
    return LIFECYCLE.cordoned()


def status() -> dict:
    """One JSON-able snapshot for /healthz and operators."""
    from . import health

    return {"state": LIFECYCLE.state(),
            "healthy": health.healthy(),
            "breaker": BREAKER.status(),
            "cordoned": LIFECYCLE.cordoned(),
            "drain_budget_s": LIFECYCLE.remaining_drain_budget()}


# node state + breaker re-registered through the fleet-telemetry
# registry: GET /metrics flattens this group (breaker trip counters,
# consecutive-failure gauge, state as an info sample) so a scraper
# sees breaker pressure without parsing /3/Stats JSON
from .telemetry import register_group as _register_tel_group  # noqa: E402

_register_tel_group("lifecycle", status)
