"""Device-mesh management — the TPU-native replacement for H2O's "cloud".

In the reference, cluster membership is discovered via headless-Service DNS
and locked by Paxos-style gossip (h2o-k8s KubernetesDnsLookup,
water/Paxos.java — see SURVEY.md §3.3). On TPU the slice topology *is* the
cluster: a `jax.sharding.Mesh` over the slice's chips, formed once at init
and immutable thereafter — the same "cloud locks at formation" semantics,
for free.

Axes:
  ROWS — the data axis. H2O distributes Chunks round-robin over the node
         ring; we shard the row dimension of every Frame column over ROWS.
  COLS — the feature/model axis (size 1 by default). Used for wide-feature
         sharding (GLM Gram over many one-hot columns) — the reference has
         no tensor parallelism (SURVEY.md §2d), this is our TP analog.
"""

from __future__ import annotations

import contextlib
import os
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

ROWS = "rows"
COLS = "cols"

_global_mesh: Mesh | None = None


def make_mesh(n_rows: int | None = None, n_cols: int = 1,
              devices: Sequence[jax.Device] | None = None) -> Mesh:
    """Build a (ROWS, COLS) mesh. Defaults to all devices on the row axis."""
    devices = list(devices if devices is not None else jax.devices())
    if n_rows is None:
        n_rows = len(devices) // n_cols
    if n_rows * n_cols > len(devices):
        raise ValueError(
            f"mesh {n_rows}x{n_cols} needs {n_rows * n_cols} devices, "
            f"have {len(devices)}")
    devs = np.array(devices[: n_rows * n_cols]).reshape(n_rows, n_cols)
    return Mesh(devs, (ROWS, COLS))


def set_global_mesh(mesh: Mesh) -> None:
    global _global_mesh
    _global_mesh = mesh


def global_mesh() -> Mesh:
    """The process-wide mesh, created lazily over all visible devices."""
    global _global_mesh
    if _global_mesh is None:
        _global_mesh = make_mesh()
    return _global_mesh


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    """Temporarily swap the process-wide mesh (not thread-safe)."""
    global _global_mesh
    prev = _global_mesh
    _global_mesh = mesh
    try:
        yield mesh
    finally:
        _global_mesh = prev


def n_row_shards(mesh: Mesh | None = None) -> int:
    mesh = mesh or global_mesh()
    return mesh.shape[ROWS]


def row_sharding(mesh: Mesh | None = None) -> NamedSharding:
    """Sharding for a row-partitioned array (rank >= 1, rows leading)."""
    mesh = mesh or global_mesh()
    return NamedSharding(mesh, P(ROWS))


def replicated(mesh: Mesh | None = None) -> NamedSharding:
    mesh = mesh or global_mesh()
    return NamedSharding(mesh, P())


def initialize_distributed(coordinator: str | None = None,
                           num_processes: int | None = None,
                           process_id: int | None = None) -> None:
    """Multi-host bring-up: DCN via the JAX distributed runtime.

    The operator injects H2O_TPU_COORDINATOR / H2O_TPU_NUM_PROCESSES /
    H2O_TPU_PROCESS_ID into the pod spec (the analog of the reference's
    H2O_KUBERNETES_SERVICE_DNS / H2O_NODE_EXPECTED_COUNT contract,
    SURVEY.md §1a). Single-process (or absent env) is a no-op.
    """
    coordinator = coordinator or os.environ.get("H2O_TPU_COORDINATOR")
    if coordinator is None:
        return
    num_processes = num_processes or int(
        os.environ.get("H2O_TPU_NUM_PROCESSES", "1"))
    process_id = process_id if process_id is not None else int(
        os.environ.get("H2O_TPU_PROCESS_ID", "0"))
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
