from . import compat  # noqa: F401 — jax.shard_map alias on old jax
from . import faults, lifecycle, retry
from .backend import ensure_live_backend, force_cpu_devices
from .mesh import (COLS, ROWS, global_mesh, initialize_distributed, make_mesh,
                   n_row_shards, replicated, row_sharding, set_global_mesh,
                   use_mesh)
from .health import (ClusterHealthError, device_dispatch, health_status,
                     heartbeat, start_heartbeat, stop_heartbeat)
from .mrtask import doall, shard_rows

__all__ = [
    "COLS", "ROWS", "global_mesh", "initialize_distributed", "make_mesh",
    "n_row_shards", "replicated", "row_sharding", "set_global_mesh",
    "use_mesh", "doall", "shard_rows", "ensure_live_backend",
    "force_cpu_devices", "ClusterHealthError", "device_dispatch",
    "heartbeat", "health_status", "start_heartbeat", "stop_heartbeat",
    "faults", "lifecycle", "retry",
]
