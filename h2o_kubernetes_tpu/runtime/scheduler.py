"""Pipelined executor — the three resource streams of an AutoML run.

A serial AutoML loop interleaves three *independent* resources on one
thread: device compute (model training), XLA compilation (every new
(program, shape) pair), and host bookkeeping (metric extraction,
leaderboard insertion, resume-manifest writes).  This module gives each
its own stream so they overlap — the same dispatch-pipelining lesson
the GBDT-accelerator literature applies one level down (PAPERS.md:
arXiv:1806.11248 overlaps host staging with device kernels,
arXiv:2005.09148 hides transfers behind compute; `models/tree/ooc.py`
already does it per chunk, this does it per MODEL):

- **device stream** — the caller's thread, holding the device *token*:
  only the token holder dispatches device computations, so device work
  stays strictly ordered (and the XLA:CPU test mesh never sees two
  concurrent collective programs, the known rendezvous-starvation
  shape — tests/conftest.py).
- **compile stream** (`CompileStream`) — a worker that AOT
  traces/lowers/compiles executables the device stream will need next
  (shapes are known from the plan + frame schema; see
  `GBM.compile_ahead_lowerings`).  Compiled binaries land in the
  persistent XLA cache (runtime/backend.py), so the device stream's
  later dispatch is a cache *hit*: on a cold run the stream is a cache
  fill, on a warm one a no-op.  On the tunneled chip every compile
  moved off the critical path is a remote round trip saved.
- **host stream** (`HostStream`) — a worker applying completion
  callbacks (leaderboard insertion, `_save_step` manifest writes,
  logging) strictly in *submission-sequence order*, whatever order
  they become runnable: the pipelined leaderboard and resume manifest
  must be identical to the sequential run's (insertion order by plan
  index, not completion order).

Overlap accounting: `PipelinedExecutor.stats()` reports device-busy /
compile-ahead / host-busy seconds plus the compile-watch counters
(runtime/backend.py), so a bench can state exactly how much work left
the critical path.  On a host with one core the streams time-slice and
the wall gain is bounded by scheduler overhead (~0); the design targets
multi-core hosts and the tunneled chip, where the device stream is a
genuine second resource.

Knobs (read at use time, documented in config.py):

- ``H2O_TPU_AUTOML_PIPELINE``       1 (on) | 0 — the kill switch: 0
  restores the serial AutoML path bit-for-bit.
- ``H2O_TPU_AUTOML_COMPILE_AHEAD``  plan entries pre-lowered ahead of
  the training cursor (default 1; 0 disables the compile stream).
- ``H2O_TPU_AUTOML_QUEUE_DEPTH``    bound on each stream's pending
  queue (default 4): backpressure, so completed-but-unapplied models
  and stale compile requests cannot accumulate without bound.
"""

from __future__ import annotations

import collections
import contextlib
import os
import threading
import time
from typing import Any, Callable

from .backend import compile_watch_snapshot, start_compile_watch

__all__ = ["pipeline_enabled", "compile_ahead_depth", "queue_depth",
           "HostStream", "CompileStream", "PipelinedExecutor"]


def pipeline_enabled() -> bool:
    """H2O_TPU_AUTOML_PIPELINE != "0" — one switch for the AutoML
    executor AND the CV fold pipeline (models/cv.py), so the kill
    switch restores the whole serial path at once."""
    return os.environ.get("H2O_TPU_AUTOML_PIPELINE", "1") != "0"


def _int_env(name: str, default: int, lo: int) -> int:
    try:
        return max(lo, int(os.environ.get(name, str(default))))
    except ValueError:
        return default


def compile_ahead_depth() -> int:
    return _int_env("H2O_TPU_AUTOML_COMPILE_AHEAD", 1, 0)


def persistent_cache_enabled() -> bool:
    """Compile-ahead pays THROUGH the persistent XLA cache: on this
    jaxlib an AOT ``lower().compile()`` executable is not shared with
    the later call-path dispatch in memory — the handoff is the disk
    cache (fill ahead, hit at dispatch).  Without a cache dir the
    stream would compile every program twice, so the executor disables
    it (h2o.init()/ensure_live_backend sets the dir in every real
    process — runtime/backend.enable_persistent_compile_cache)."""
    if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        return True
    try:
        import sys

        j = sys.modules.get("jax")
        return bool(j is not None and
                    j.config.jax_compilation_cache_dir)
    except Exception:   # noqa: BLE001
        return False


def queue_depth() -> int:
    return _int_env("H2O_TPU_AUTOML_QUEUE_DEPTH", 4, 1)


class HostStream:
    """Single worker applying callables strictly in sequence order.

    ``submit(seq, fn)`` may arrive in any order; the worker holds a
    task back until every lower sequence number has been applied or
    explicitly ``skip()``-ed (a step that failed or fell out of budget
    produces no completion).  Task exceptions are captured — not
    raised on the worker — and surfaced via ``pop_errors``/``drain``,
    mirroring the serial loop where a failed step never kills the run.
    """

    def __init__(self, name: str = "h2o-automl-host",
                 max_pending: int | None = None):
        self._cond = threading.Condition()
        self._tasks: dict[int, tuple[Callable[[], None], str]] = {}
        self._skipped: set[int] = set()
        self._next = 0
        self._inflight = False
        self._stopped = False
        self._errors: list[tuple[int, str, BaseException]] = []
        self._max_pending = max_pending or queue_depth()
        self.stats = {"applied": 0, "skipped": 0, "busy_s": 0.0,
                      "max_pending": 0}
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)
        self._thread.start()

    def submit(self, seq: int, fn: Callable[[], None],
               label: str = "") -> None:
        """Queue ``fn`` for in-order application; blocks (backpressure)
        while the pending queue is full AND the worker has runnable
        work — a starving worker (held back by a missing lower seq)
        admits immediately, otherwise blocking the very submit that
        fills the gap would deadlock the producer against its own
        backlog."""
        with self._cond:
            if self._stopped:
                raise RuntimeError("host stream is stopped")
            if seq < self._next or seq in self._tasks \
                    or seq in self._skipped:
                raise ValueError(f"seq {seq} already submitted/applied")
            while len(self._tasks) >= self._max_pending \
                    and not self._stopped \
                    and (self._inflight or self._next in self._tasks
                         or self._next in self._skipped):
                self._cond.wait(timeout=0.5)
            if self._stopped:
                # stop() raced the backpressure wait: refuse loudly —
                # appending now would silently drop the task (the
                # worker is gone) and misreport a wedge at drain
                raise RuntimeError("host stream is stopped")
            self._tasks[seq] = (fn, label)
            self.stats["max_pending"] = max(self.stats["max_pending"],
                                            len(self._tasks))
            self._cond.notify_all()

    def skip(self, seq: int) -> None:
        """Mark a sequence number that will never be submitted."""
        with self._cond:
            if seq < self._next or seq in self._tasks:
                return
            self._skipped.add(seq)
            self._cond.notify_all()

    def pop_errors(self) -> list[tuple[int, str, BaseException]]:
        with self._cond:
            out, self._errors = self._errors, []
            return out

    def pending(self) -> list[int]:
        with self._cond:
            return sorted(self._tasks)

    def drain(self, timeout: float | None = None
              ) -> list[tuple[int, str, BaseException]]:
        """Block until everything submitted/skipped has been applied;
        returns the captured task errors.  Raises TimeoutError naming
        the wedge (a submit gap with no skip()) instead of hanging."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self._tasks or self._skipped or self._inflight:
                remaining = None if deadline is None else \
                    deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        f"host stream wedged at seq {self._next}: "
                        f"pending={sorted(self._tasks)} "
                        f"skipped={sorted(self._skipped)}")
                self._cond.wait(timeout=0.5 if remaining is None
                                else min(0.5, remaining))
        return self.pop_errors()

    def stop(self, timeout: float = 30.0) -> bool:
        """Drain then stop the worker; True when the thread exited.
        A wedged drain is reported by the bool (and by an explicit
        drain() call beforehand), never raised — stop() runs on error
        paths where a fresh TimeoutError would mask the real failure."""
        try:
            self.drain(timeout=timeout)
        except TimeoutError:
            pass
        finally:
            with self._cond:
                self._stopped = True
                self._cond.notify_all()
        self._thread.join(timeout=timeout)
        return not self._thread.is_alive()

    def _run(self) -> None:
        while True:
            with self._cond:
                task = None
                while task is None:
                    while self._next in self._skipped:
                        self._skipped.discard(self._next)
                        self._next += 1
                        self.stats["skipped"] += 1
                        self._cond.notify_all()
                    if self._next in self._tasks:
                        task = self._tasks.pop(self._next)
                        self._inflight = True
                        self._cond.notify_all()
                        break
                    if self._stopped:
                        return
                    self._cond.wait(timeout=0.5)
            fn, label = task
            seq = self._next
            t0 = time.monotonic()
            try:
                fn()
            except BaseException as e:  # noqa: BLE001 — surfaced at drain
                with self._cond:
                    self._errors.append((seq, label, e))
            finally:
                with self._cond:
                    self.stats["busy_s"] += time.monotonic() - t0
                    self.stats["applied"] += 1
                    self._next = seq + 1
                    self._inflight = False
                    self._cond.notify_all()


class CompileStream:
    """Daemon worker that AOT-compiles executables ahead of use.

    ``submit(key, builder)`` enqueues a request (deduped by ``key``);
    the worker calls ``builder()`` — which returns a list of zero-arg
    lowering thunks — and runs each thunk.  Tracing/lowering happens on
    THIS thread too, keeping even the Python-side compile cost off the
    device stream.  Per thunk the compile-watch diff classifies the
    outcome: backend-compile events observed → a cache ``fill`` (cold
    run), none → ``warm`` (executable/persistent cache already had it —
    the promised no-op warm path).  Builder/thunk exceptions are
    counted, never raised: compile-ahead is an accelerator, the device
    stream compiles on-demand exactly as before when it misfires."""

    def __init__(self, name: str = "h2o-automl-compile",
                 max_queue: int | None = None):
        start_compile_watch()
        self._cond = threading.Condition()
        self._queue: collections.deque = collections.deque()
        self._seen: set = set()
        self._stopped = False
        self._idle = True
        self.stats = {"requested": 0, "deduped": 0, "dropped": 0,
                      "unsupported": 0, "jobs": 0, "programs": 0,
                      "fills": 0, "warm": 0, "errors": 0,
                      "busy_s": 0.0, "compile_s": 0.0}
        self._max_queue = max_queue or queue_depth()
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)
        self._thread.start()

    def submit(self, key: Any, builder: Callable[[], list],
               label: str = "") -> bool:
        """True when queued; False when deduped/dropped/stopped."""
        with self._cond:
            self.stats["requested"] += 1
            if self._stopped:
                return False
            if key in self._seen:
                self.stats["deduped"] += 1
                return False
            if len(self._queue) >= self._max_queue:
                # never block the device stream on compile-ahead
                # backpressure: a dropped request just compiles
                # on-demand later
                self.stats["dropped"] += 1
                return False
            self._seen.add(key)
            self._queue.append((builder, label))
            self._cond.notify_all()
            return True

    def mark_unsupported(self) -> None:
        """Count a plan entry with no compile-ahead support (GLM/DL:
        their iterative programs are shape-shared across configs, so
        pre-lowering buys little — the accounting keeps that visible)."""
        with self._cond:
            self.stats["unsupported"] += 1

    def idle(self) -> bool:
        with self._cond:
            return self._idle and not self._queue

    def wait_idle(self, timeout: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout
        with self._cond:
            while not (self._idle and not self._queue):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(timeout=min(0.5, remaining))
        return True

    def stop(self, timeout: float = 30.0) -> bool:
        """Signal stop and join; an in-flight AOT compile finishes
        first (nothing can interrupt XLA), so the timeout bounds the
        wait — the thread is a daemon either way."""
        with self._cond:
            self._stopped = True
            self._queue.clear()
            self._cond.notify_all()
        self._thread.join(timeout=timeout)
        return not self._thread.is_alive()

    def _run(self) -> None:
        ident = threading.get_ident()
        while True:
            with self._cond:
                while not self._queue and not self._stopped:
                    self._idle = True
                    self._cond.notify_all()
                    self._cond.wait(timeout=0.5)
                if self._stopped:
                    self._idle = True
                    self._cond.notify_all()
                    return
                builder, label = self._queue.popleft()
                self._idle = False
            t0 = time.monotonic()
            before = compile_watch_snapshot(ident)
            try:
                thunks = builder() or []
                for thunk in thunks:
                    pre = compile_watch_snapshot(ident)
                    # each pre-lowered program is a compile-ahead-fill
                    # phase span (h2o_train_phase_seconds + timeline):
                    # the overlapped compile work the scheduler_stats
                    # totals previously reported only in aggregate
                    from .telemetry import phase_span

                    with phase_span("compile_ahead_fill",
                                    label=label or None):
                        thunk()
                    post = compile_watch_snapshot(ident)
                    with self._cond:
                        self.stats["programs"] += 1
                        # a "fill" is a genuinely new binary: a
                        # persistent-cache miss, or (cache disabled) any
                        # backend compile. A persistent-cache HIT or a
                        # fully in-memory reuse is the promised warm
                        # no-op.
                        misses = post["thread_pcache_misses"] \
                            - pre["thread_pcache_misses"]
                        hits = post["thread_pcache_hits"] \
                            - pre["thread_pcache_hits"]
                        compiled = post["thread_compiles"] \
                            - pre["thread_compiles"]
                        if misses > 0 or (hits == 0 and compiled > 0):
                            self.stats["fills"] += 1
                        else:
                            self.stats["warm"] += 1
            except Exception:   # noqa: BLE001 — accelerator only
                with self._cond:
                    self.stats["errors"] += 1
            finally:
                after = compile_watch_snapshot(ident)
                with self._cond:
                    self.stats["jobs"] += 1
                    self.stats["busy_s"] += time.monotonic() - t0
                    self.stats["compile_s"] += \
                        after["thread_compile_s"] - before["thread_compile_s"]


class PipelinedExecutor:
    """Device token + the two worker streams, with overlap accounting.

    The device *token* is a lock: whoever holds it may dispatch device
    computations.  The AutoML driver (the owning thread) wraps every
    training step in ``device()``, which also attributes wall time and
    critical-path compile-wait (compiles observed on the token-holding
    thread) to the device stream."""

    def __init__(self, compile_ahead: int | None = None,
                 queue: int | None = None):
        start_compile_watch()
        self._t0 = time.monotonic()
        self._token = threading.Lock()
        self._depth = compile_ahead_depth() if compile_ahead is None \
            else max(0, compile_ahead)
        self.host = HostStream(max_pending=queue)
        self.compiles = CompileStream(max_queue=queue) \
            if self._depth > 0 and persistent_cache_enabled() else None
        self._dev = {"busy_s": 0.0, "steps": 0, "compiles": 0,
                     "compile_wait_s": 0.0}
        self._watch0 = compile_watch_snapshot()

    @property
    def compile_ahead(self) -> int:
        return self._depth

    @contextlib.contextmanager
    def device(self, label: str = ""):
        """Hold the device token for one training step."""
        ident = threading.get_ident()
        with self._token:
            t0 = time.monotonic()
            before = compile_watch_snapshot(ident)
            try:
                yield
            finally:
                after = compile_watch_snapshot(ident)
                self._dev["busy_s"] += time.monotonic() - t0
                self._dev["steps"] += 1
                self._dev["compiles"] += \
                    after["thread_compiles"] - before["thread_compiles"]
                self._dev["compile_wait_s"] += \
                    after["thread_compile_s"] - before["thread_compile_s"]

    def compile_ahead_submit(self, key: Any,
                             builder: Callable[[], list],
                             label: str = "") -> bool:
        if self.compiles is None:
            return False
        return self.compiles.submit(key, builder, label)

    def shutdown(self, timeout: float = 30.0) -> None:
        """Stop both streams (drain the host stream first)."""
        try:
            self.host.stop(timeout=timeout)
        finally:
            if self.compiles is not None:
                self.compiles.stop(timeout=timeout)

    def stats(self) -> dict:
        """Overlap accounting: wall vs per-stream busy seconds, the
        device stream's critical-path compile-wait, and the
        compile-ahead fill/warm counts."""
        watch = compile_watch_snapshot()
        out = {
            "enabled": True,
            "wall_s": round(time.monotonic() - self._t0, 3),
            "device_busy_s": round(self._dev["busy_s"], 3),
            "device_steps": self._dev["steps"],
            "device_compiles": self._dev["compiles"],
            "device_compile_wait_s": round(
                self._dev["compile_wait_s"], 3),
            "host_busy_s": round(self.host.stats["busy_s"], 3),
            "host_applied": self.host.stats["applied"],
            "host_max_pending": self.host.stats["max_pending"],
            "compile_events": watch["compiles"] - self._watch0["compiles"],
            "compile_s": round(
                watch["compile_s"] - self._watch0["compile_s"], 3),
            "pcache_hits": watch["pcache_hits"]
            - self._watch0["pcache_hits"],
            "pcache_misses": watch["pcache_misses"]
            - self._watch0["pcache_misses"],
            "compile_ahead": None,
        }
        if self.compiles is not None:
            cs = dict(self.compiles.stats)
            cs["busy_s"] = round(cs["busy_s"], 3)
            cs["compile_s"] = round(cs["compile_s"], 3)
            out["compile_ahead"] = cs
        else:
            out["compile_ahead"] = {
                "disabled": "H2O_TPU_AUTOML_COMPILE_AHEAD=0"
                if self._depth == 0 else "no persistent compile cache"}
        return out
