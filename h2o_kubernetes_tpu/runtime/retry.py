"""Shared retry/timeout/backoff layer for transient faults.

The reference's persist backends ride on SDKs (AWS/GCS clients) that
retry throttles and 5xx bursts internally; our stdlib REST clients
(persist_cloud.py) had none, so a single S3 blip destroyed a model
save or an AutoML checkpoint. This module is the one retry policy for
every transient-capable path: exponential backoff with full jitter,
a Retry-After override, an attempt cap and a wall-clock deadline.

Callers wrap one *attempt* in a function that raises TransientError
for retryable outcomes (429/5xx, timeouts, connection resets, partial
reads) and any other exception for permanent ones, then hand it to
`call()`. TransientError subclasses IOError, so exhausted retries
surface to persist callers as the same exception family as before.

Env knobs (all optional, read per call so tests/operators can tune a
live process):

- ``H2O_TPU_RETRY_ATTEMPTS``   total attempts, default 5
- ``H2O_TPU_RETRY_BASE``       first backoff in seconds, default 0.2
- ``H2O_TPU_RETRY_MAX_DELAY``  per-sleep cap in seconds, default 10
- ``H2O_TPU_RETRY_DEADLINE``   total budget in seconds, default 120
- ``H2O_TPU_RETRY_MAX_ELAPSED_S``  hard cap on total elapsed time
  (attempts INCLUDED, unlike the deadline's sleep-lookahead), default
  0 = off — gives a draining node a retry budget its jobs cannot blow
- ``H2O_TPU_RETRY_DISABLE=1``  single attempt, no sleeps (chaos drills
  use this to prove a fault actually exercises the retry path)

Drain integration (runtime/lifecycle.py): while the node is DRAINING,
no retry sleep may outlive the drain deadline — a retried persist
write inside a draining node gives up (raising the last
TransientError) instead of holding the drain open past
``H2O_TPU_DRAIN_TIMEOUT``.
"""

from __future__ import annotations

import os
import random
import time
from dataclasses import dataclass
from typing import Callable, TypeVar

__all__ = ["RetryPolicy", "TransientError", "policy_from_env", "call",
           "bucket_take"]


def bucket_take(buckets: dict, key: str, rate: float, now: float,
                burst: float | None = None) -> float:
    """THE token-bucket step shared by every per-key admission budget
    (rest.py's per-tenant rate limit, the fleet router's per-tenant
    retry budget): take one token from ``buckets[key]`` (created at
    full burst on first touch), refilled continuously at ``rate``/s
    and capped at ``burst`` (default: one second of traffic, min 1).

    Returns 0.0 on success, else the seconds until a token accrues
    (the Retry-After the caller should advertise). The caller owns
    locking and the clock — ``now`` is passed in so tests can freeze
    it. Mutates ``buckets[key] = [tokens, last]`` in place."""
    burst = max(1.0, rate) if burst is None else burst
    b = buckets.get(key)
    if b is None:
        b = buckets[key] = [burst, now]
    tokens = min(burst, b[0] + (now - b[1]) * rate)
    if tokens < 1.0:
        b[0], b[1] = tokens, now
        return (1.0 - tokens) / rate
    b[0], b[1] = tokens - 1.0, now
    return 0.0

T = TypeVar("T")


class TransientError(IOError):
    """A retryable failure (throttle, 5xx, timeout, connection reset).

    `retry_after`: server-mandated wait in seconds (HTTP Retry-After),
    overriding the backoff schedule for the next sleep when set.
    """

    def __init__(self, msg: str, retry_after: float | None = None):
        super().__init__(msg)
        self.retry_after = retry_after


@dataclass(frozen=True)
class RetryPolicy:
    attempts: int = 5
    base: float = 0.2           # first backoff; doubles per attempt
    max_delay: float = 10.0     # per-sleep cap
    deadline: float = 120.0     # total wall-clock budget (0 = none)
    max_elapsed: float = 0.0    # hard elapsed-time cap incl. attempts
    jitter: bool = True         # (0 = off)

    def backoff(self, attempt: int, rng=random.random) -> float:
        """Sleep before attempt `attempt+1` (attempt is 1-based)."""
        delay = min(self.max_delay, self.base * (2 ** (attempt - 1)))
        if self.jitter:
            # full jitter in [delay/2, delay]: desynchronizes a pod
            # slice's workers hammering the same recovering endpoint
            delay *= 0.5 + 0.5 * rng()
        return delay


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        from ..diagnostics import log

        log.warning("ignoring unparseable %s=%r", name, raw)
        return default


def policy_from_env(**overrides) -> RetryPolicy:
    """Build the active policy from H2O_TPU_RETRY_* (see module doc)."""
    if os.environ.get("H2O_TPU_RETRY_DISABLE", "") not in ("", "0"):
        return RetryPolicy(attempts=1, **{k: v for k, v in
                                          overrides.items()
                                          if k != "attempts"})
    kw = dict(
        attempts=int(_env_float("H2O_TPU_RETRY_ATTEMPTS", 5)),
        base=_env_float("H2O_TPU_RETRY_BASE", 0.2),
        max_delay=_env_float("H2O_TPU_RETRY_MAX_DELAY", 10.0),
        deadline=_env_float("H2O_TPU_RETRY_DEADLINE", 120.0),
        max_elapsed=_env_float("H2O_TPU_RETRY_MAX_ELAPSED_S", 0.0),
    )
    kw.update(overrides)
    return RetryPolicy(**kw)


def call(fn: Callable[[], T], policy: RetryPolicy | None = None,
         describe: str = "", sleep: Callable[[float], None] = time.sleep,
         ) -> T:
    """Run `fn` under the retry policy.

    Retries ONLY TransientError; everything else propagates on the
    first attempt (permanent failures must not burn the deadline).
    On exhaustion the last TransientError is re-raised — an IOError
    whose message carries the final failure detail.
    """
    policy = policy or policy_from_env()
    start = time.monotonic()
    last: TransientError | None = None
    for attempt in range(1, max(1, policy.attempts) + 1):
        try:
            return fn()
        except TransientError as e:
            last = e
            if attempt >= policy.attempts:
                break
            elapsed = time.monotonic() - start
            if policy.max_elapsed and elapsed >= policy.max_elapsed:
                break    # attempts themselves burned the budget
            delay = e.retry_after if e.retry_after is not None \
                else policy.backoff(attempt)
            if policy.deadline and elapsed + delay > policy.deadline:
                break
            if policy.max_elapsed and \
                    elapsed + delay > policy.max_elapsed:
                break
            # a DRAINING node's retries must die inside the drain
            # window: sleeping past the drain deadline would leave the
            # job RUNNING at the timeout and fail it anyway — give up
            # now with the real error instead
            from .lifecycle import remaining_drain_budget

            rem = remaining_drain_budget()
            if rem is not None and delay >= rem:
                break
            from ..diagnostics import log, timeline

            timeline.record("retry", describe or str(e),
                            attempt=attempt, delay=round(delay, 3))
            log.warning("transient failure (attempt %d/%d, retrying in "
                        "%.2fs): %s", attempt, policy.attempts, delay, e)
            sleep(delay)
    from ..diagnostics import timeline

    timeline.record("retry_exhausted", describe or str(last),
                    attempts=policy.attempts)
    assert last is not None
    raise last
