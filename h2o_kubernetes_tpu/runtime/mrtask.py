"""MRTask-equivalent: per-shard map + collective reduce.

The reference's compute primitive is `MRTask.doAll(frame)` — map over each
node's local Chunks, reduce locally, then reduce up a binary tree of RPCs
over the node ring (water/MRTask.java, SURVEY.md §3.5). The TPU-native
equivalent is exactly `shard_map`: the `map(Chunk[])` body becomes the
per-shard function, and the software tree-allreduce becomes an ICI
collective (`psum`/`pmin`/`pmax`).

`doall(fn, *cols)` runs `fn` on each device's row-shard of the column
arrays and reduces the returned pytree across shards. Per-leaf reduce ops
are declared with a matching pytree of {"sum","min","max","mean","concat"}
(a bare string applies to every leaf) — the analog of an MRTask subclass's
`reduce()` method.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import ROWS, global_mesh

_REDUCERS = {
    "sum": lambda x: lax.psum(x, ROWS),
    "min": lambda x: lax.pmin(x, ROWS),
    "max": lambda x: lax.pmax(x, ROWS),
    "mean": lambda x: lax.pmean(x, ROWS),
    "concat": lambda x: lax.all_gather(x, ROWS, axis=0, tiled=True),
    "none": lambda x: x,
}


# jitted doall callables, per mesh (weak: a replaced mesh's entries die
# with it) then by (cache_key, map_fn, reduce-structure, donate).
# jax.jit keys its executable cache on the CALLABLE's identity, so the
# fresh `body` closure each doall() call builds means a fresh
# trace+compile even for byte-identical computations — CV fold frames
# re-deriving rollups paid ~25 warm recompiles per AutoML run. Callers
# whose map_fn is a stable module-level function opt in with
# `cache_key`; per-shape retracing inside one cached callable is jit's
# normal behavior.
import weakref

_DOALL_CACHE: "weakref.WeakKeyDictionary[Mesh, dict]" = \
    weakref.WeakKeyDictionary()


def _freeze(reduce) -> Any:
    leaves, treedef = jax.tree.flatten(reduce)
    return tuple(leaves), str(treedef)


def doall(map_fn: Callable[..., Any], *cols: jax.Array,
          reduce: Any = "sum", mesh: Mesh | None = None,
          donate: bool = False, cache_key: Any = None) -> Any:
    """Map `map_fn` over aligned row-shards of `cols`, reduce across shards.

    Returns the fully reduced pytree, replicated on every device (like
    `MRTask.getResult()` returning the reduced task object to the caller).

    `cache_key`: opt-in reuse of the jitted callable across calls (the
    caller asserts map_fn's computation is fully determined by the key,
    the reduce spec, and the operand shapes).
    """
    from . import faults
    from .health import device_dispatch, require_healthy
    from .lifecycle import breaker_guard

    # fail fast on a broken cloud (SURVEY.md §5.3); doall fires its OWN
    # site, so it must not also consume train.step fault counts
    require_healthy(fault_site=None)
    faults.fire("mrtask.doall")
    mesh = mesh or global_mesh()

    if cache_key is not None:
        # map_fn identity in the key: two callers sharing a cache_key
        # string with different (module-level) map_fns must not get
        # each other's computation
        key = (cache_key, map_fn, _freeze(reduce), donate)
        cached = _DOALL_CACHE.get(mesh, {}).get(key)
        if cached is not None:
            # breaker outside the device guard: a dispatch error
            # (converted to ClusterHealthError by the guard) counts one
            # consecutive failure; an open breaker rejects before any
            # device work — MRTask traffic respects the cooldown too
            with breaker_guard("doall dispatch"), \
                    device_dispatch("doall dispatch"):
                # block inside the guard: async dispatch would surface
                # a mid-execution device error at the CALLER's first
                # read, outside the guard. doall results are small
                # fully-reduced pytrees callers read immediately, so
                # the sync costs nothing real.
                return jax.block_until_ready(cached(*cols))

    def body(*shards):
        out = map_fn(*shards)
        reds = reduce
        if isinstance(reds, str):
            reds = jax.tree.map(lambda _: reduce, out)
        return jax.tree.map(lambda x, r: _REDUCERS[r](x), out, reds)

    # shard_map needs out_specs up front; "none"/"concat" leaves differ.
    # Trace map_fn (collective-free user code) on shard-shaped abstractions.
    shard_shapes = tuple(
        jax.ShapeDtypeStruct((c.shape[0] // mesh.shape[ROWS],) + c.shape[1:],
                             c.dtype) for c in cols)
    res = jax.eval_shape(map_fn, *shard_shapes)
    reds = reduce if not isinstance(reduce, str) else jax.tree.map(
        lambda _: reduce, res)
    out_specs = jax.tree.map(
        lambda _, r: P(ROWS) if r == "none" else P(), res, reds)

    fn = jax.shard_map(body, mesh=mesh, in_specs=P(ROWS), out_specs=out_specs)
    jfn = jax.jit(fn, donate_argnums=tuple(range(len(cols)))
                  if donate else ())
    if cache_key is not None:
        _DOALL_CACHE.setdefault(mesh, {})[key] = jfn
    with breaker_guard("doall dispatch"), \
            device_dispatch("doall dispatch"):
        # block inside the guard (see the cached branch above)
        return jax.block_until_ready(jfn(*cols))


@functools.lru_cache(maxsize=None)
def _padded_len(n: int, shards: int) -> int:
    return ((n + shards - 1) // shards) * shards


def shard_rows(x, mesh: Mesh | None = None, pad_value=None) -> jax.Array:
    """Pad the leading dim to a multiple of the ROWS axis and shard it.

    Default padding is NaN for floats, -1 for signed ints, 0 otherwise
    (np.full would silently turn NaN into INT_MIN for int dtypes).
    """
    import numpy as np

    mesh = mesh or global_mesh()
    shards = mesh.shape[ROWS]
    n = x.shape[0]
    m = _padded_len(n, shards)
    if m != n:
        if pad_value is None:
            kind = np.dtype(x.dtype).kind
            pad_value = (np.nan if kind == "f" else -1 if kind == "i" else 0)
        pad = np.full((m - n,) + tuple(x.shape[1:]), pad_value, dtype=x.dtype)
        x = np.concatenate([np.asarray(x), pad], axis=0)
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, P(ROWS))
    if not sharding.is_fully_addressable:
        # multi-host (DCN) mesh: device_put cannot target devices owned
        # by other processes; every process holds the same host array
        # and contributes its local shards (multi-controller SPMD)
        xnp = np.asarray(x)
        return jax.make_array_from_callback(
            xnp.shape, sharding, lambda idx: xnp[idx])
    return jax.device_put(jnp.asarray(x), sharding)
