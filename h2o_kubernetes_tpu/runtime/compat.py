"""JAX version-compatibility shims.

The runtime targets the public `jax.shard_map` API (promoted out of
jax.experimental in newer releases). Older jaxlib/jax builds — like
the baked-in toolchain on some pod images — only ship
`jax.experimental.shard_map.shard_map`, whose signature is compatible
with every call site here (f, mesh=, in_specs=, out_specs=). Alias it
onto the jax module once, at runtime-package import, so 18 call sites
across models/ and runtime/ stay written against the public name.
"""

from __future__ import annotations

import jax

if not hasattr(jax, "shard_map"):
    try:
        import inspect

        from jax.experimental.shard_map import shard_map as _shard_map

        if "check_vma" in inspect.signature(_shard_map).parameters:
            jax.shard_map = _shard_map
        else:
            # the replication-check kwarg was renamed check_rep ->
            # check_vma when shard_map went public. The old checker
            # also lacks replication rules the kernels rely on (e.g.
            # custom_vmap_call from the histogram op), so on old jax
            # the check is disabled outright — it is a static
            # validation pass with no runtime semantics
            def _compat_shard_map(f, *args, **kw):
                kw.pop("check_vma", None)
                kw["check_rep"] = False
                return _shard_map(f, *args, **kw)

            jax.shard_map = _compat_shard_map
    except ImportError:     # pragma: no cover — very old jax; let call
        pass                # sites raise their own AttributeError

if not hasattr(jax, "typeof"):
    # jax.typeof (public aval accessor) postdates this jax; the
    # classic spelling returns the same ShapedArray for concrete
    # arrays AND tracers (histogram.py reads .vma off it, which simply
    # doesn't exist here — callers already getattr with a default)
    import jax.core as _jax_core

    jax.typeof = _jax_core.get_aval
