"""AutoML — orchestrated model search + stacked ensembles + leaderboard.

Reference: h2o-automl/ai/h2o/automl/AutoML.java + Leaderboard.java +
modeling/*Steps (SURVEY.md §2b C16). The reference runs a fixed plan of
per-algorithm default models, then random-search grids, all under n-fold
CV with a shared fold assignment, then builds two stacked ensembles
(BestOfFamily and AllModels) and ranks everything on a leaderboard.

This build mirrors that plan:
- every base model trains with the same modulo fold assignment (the
  reference forces a shared fold map when stacking is enabled) and keeps
  CV holdout predictions — the level-one data for the ensembles;
- the model plan is defaults-first (GLM, DRF, XRT, 5 GBMs, 3 XGBoosts,
  1 DL) then a random GBM/XGBoost/DL grid until max_models or
  max_runtime_secs runs out;
- the leaderboard ranks by CV metrics (or on leaderboard_frame when
  given): auc desc for binomial, logloss asc for multinomial, rmse asc
  for regression — H2O's sort_metric defaults.
"""

from __future__ import annotations

import collections
import functools
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Sequence

import numpy as np

from .frame import Frame
from .models import DRF, GBM, GLM, DeepLearning, StackedEnsemble, XGBoost

# metrics where larger is better (everything else ranks ascending)
_DESC = {"auc", "accuracy", "r2", "pr_auc", "ndcg@10"}


JOBS: dict[str, "Job"] = {}       # /3/Jobs analog: every Job registers


def jobs() -> list[dict[str, Any]]:
    """List all jobs with status/progress (GET /3/Jobs analog)."""
    return [{"dest": j.dest, "description": j.description,
             "status": j.status, "progress": j.progress, "msg": j.msg}
            for j in JOBS.values()]


# one lock for all Job status transitions: transitions are rare (start/
# done/failed/reap), contention is nil, and a shared lock keeps the
# dataclass pickle-friendly (no per-instance lock field)
_JOB_STATE_LOCK = threading.Lock()

# resume-manifest read-modify-write lock: the pipelined host stream is
# the only writer during a run, but the lock makes _save_step safe
# against any concurrent manifest reader (REST pollers, a second run
# sharing the checkpoint_dir in-process) — same discipline Jobs got
_MANIFEST_LOCK = threading.Lock()


@dataclass
class Job:
    """Minimal water.Job analog: async-style progress surface."""

    dest: str
    description: str
    status: str = "CREATED"        # CREATED | RUNNING | DONE | FAILED
    progress: float = 0.0
    msg: str = ""
    start_time: float = 0.0
    end_time: float = 0.0

    def start(self):
        self.status = "RUNNING"
        self.start_time = time.time()
        JOBS[self.dest] = self
        from .diagnostics import timeline

        timeline.record("job_start", self.description, dest=self.dest)
        return self

    def update(self, progress: float, msg: str = ""):
        with _JOB_STATE_LOCK:
            if self.status in ("DONE", "FAILED"):
                # terminal: a still-running worker must not overwrite
                # the reaper's failure message with progress chatter
                return
            self.progress = float(progress)
            if msg:
                self.msg = msg

    def done(self):
        with _JOB_STATE_LOCK:
            if self.status == "FAILED":
                # FAILED is terminal: a worker completing AFTER the job
                # was reaped (rest._reap_jobs poll timeout) must not
                # resurrect it to DONE — pollers already saw and acted
                # on the failure. The lock closes the check-then-set
                # window against a concurrent reaper.
                return
            self.status = "DONE"
            self.progress = 1.0
            self.end_time = time.time()
        from .diagnostics import timeline

        timeline.record("job_done", self.description, dest=self.dest,
                        seconds=self.end_time - self.start_time)

    def failed(self, msg: str):
        with _JOB_STATE_LOCK:
            if self.status == "DONE":
                return      # same terminality, opposite direction
            self.status = "FAILED"
            self.msg = msg
            self.end_time = time.time()


# one lock for all Leaderboard mutation/reads: the pipelined executor's
# host stream inserts rows while the driver thread (or a REST poller)
# reads the ranking — same shared-lock rationale as _JOB_STATE_LOCK
# (contention is nil, instances stay pickle-friendly)
_LB_LOCK = threading.Lock()


class Leaderboard:
    """Ranked table of (model_id, metrics) — Leaderboard.java analog."""

    def __init__(self, sort_metric: str, ascending: bool):
        self.sort_metric = sort_metric
        self.ascending = ascending
        self.rows: list[dict[str, Any]] = []
        self.models: dict[str, Any] = {}

    def add(self, model_id: str, model, metrics: dict[str, float]):
        with _LB_LOCK:
            self.models[model_id] = model
            self.rows.append({"model_id": model_id, **metrics})
            self.rows.sort(key=lambda r: r.get(self.sort_metric, np.inf)
                           if self.ascending
                           else -r.get(self.sort_metric, -np.inf))

    @property
    def leader(self):
        with _LB_LOCK:
            return self.models[self.rows[0]["model_id"]] \
                if self.rows else None

    def as_list(self) -> list[dict[str, Any]]:
        with _LB_LOCK:
            return [dict(r) for r in self.rows]

    def to_pandas(self):
        import pandas as pd

        return pd.DataFrame(self.as_list())

    def __repr__(self):
        rows = self.as_list()   # locked snapshot: list.sort in add()
        if not rows:            # transiently empties the live list
            return "Leaderboard(empty)"
        cols: list[str] = []
        for r in rows:            # union of metric keys, stable order
            cols += [c for c in r if c != "model_id" and c not in cols]
        w = max(len(r["model_id"]) for r in rows)
        lines = ["  ".join([f"{'model_id':<{w}}"] +
                           [f"{c:>12}" for c in cols])]
        for r in rows:
            lines.append("  ".join(
                [f"{r['model_id']:<{w}}"] +
                [f"{r[c]:>12.5f}" if c in r else " " * 12 for c in cols]))
        return "\n".join(lines)


def _default_plan(seed: int) -> list[tuple[str, str, dict]]:
    """(family, name, params) — the defaults-first slice of the
    reference's modeling steps (DefaultStepsProvider order)."""
    return [
        ("glm", "GLM_1", {}),
        ("drf", "DRF_1", {"ntrees": 50}),
        # XRT: extremely-randomized variant (reference drf/XRT step) —
        # approximated by no-bootstrap full-data trees with default
        # per-node feature sampling (random split thresholds aren't
        # expressible in the histogram core)
        ("drf", "XRT_1", {"ntrees": 50, "sample_rate": 1.0,
                          "min_rows": 5}),
        # depths are capped at 10 vs the reference's 15-20: the dense-heap
        # tree layout (models/tree/core.py) grows histograms as 2^depth,
        # so depth>10 trades HBM for nothing on typical data
        ("gbm", "GBM_1", {"ntrees": 50, "max_depth": 6, "min_rows": 1}),
        ("gbm", "GBM_2", {"ntrees": 50, "max_depth": 7, "min_rows": 10}),
        ("gbm", "GBM_3", {"ntrees": 50, "max_depth": 8, "min_rows": 10}),
        ("gbm", "GBM_4", {"ntrees": 50, "max_depth": 9, "min_rows": 10}),
        ("gbm", "GBM_5", {"ntrees": 50, "max_depth": 10, "min_rows": 100,
                          "nbins": 64}),
        ("xgboost", "XGBoost_1", {"ntrees": 50, "max_depth": 8,
                                  "min_child_weight": 5}),
        ("xgboost", "XGBoost_2", {"ntrees": 50, "max_depth": 10,
                                  "min_child_weight": 10, "nbins": 64}),
        ("xgboost", "XGBoost_3", {"ntrees": 50, "max_depth": 5,
                                  "min_child_weight": 3}),
        ("deeplearning", "DeepLearning_1", {"hidden": (64, 64),
                                            "epochs": 10}),
    ]


def _random_grid(rng: np.random.Generator) -> tuple[str, dict]:
    """One random-search draw (reference grids: gbm/xgboost/dl spaces)."""
    fam = rng.choice(["gbm", "xgboost", "deeplearning"],
                     p=[0.4, 0.4, 0.2])
    if fam == "gbm":
        return fam, {
            "ntrees": int(rng.choice([30, 50, 80])),
            "max_depth": int(rng.integers(3, 11)),
            "learn_rate": float(rng.choice([0.05, 0.1, 0.2])),
            "sample_rate": float(rng.choice([0.6, 0.8, 1.0])),
            "col_sample_rate_per_tree": float(rng.choice([0.5, 0.8, 1.0])),
            "min_rows": float(rng.choice([1, 5, 10, 30])),
        }
    if fam == "xgboost":
        return fam, {
            "ntrees": int(rng.choice([30, 50, 80])),
            "max_depth": int(rng.integers(3, 11)),
            "learn_rate": float(rng.choice([0.05, 0.1, 0.3])),
            "reg_lambda": float(rng.choice([0.1, 1.0, 10.0])),
            "min_child_weight": float(rng.choice([1, 5, 15])),
            "subsample": float(rng.choice([0.6, 0.8, 1.0])),
        }
    return fam, {
        "hidden": tuple(rng.choice([32, 64, 128],
                                   size=int(rng.integers(1, 4)))),
        "epochs": int(rng.choice([5, 10, 20])),
        "input_dropout_ratio": float(rng.choice([0.0, 0.1, 0.2])),
    }


_EST = {"glm": GLM, "drf": DRF, "gbm": GBM, "xgboost": XGBoost,
        "deeplearning": DeepLearning}


class AutoML:
    """H2OAutoML analog."""

    def __init__(self, max_models: int = 12,
                 max_runtime_secs: float | None = None,
                 nfolds: int = 5, seed: int = 0,
                 include_algos: Sequence[str] | None = None,
                 exclude_algos: Sequence[str] | None = None,
                 sort_metric: str = "auto",
                 project_name: str = "automl",
                 checkpoint_dir: str | None = None,
                 verbosity: str | None = "info"):
        """checkpoint_dir: mid-run resume manifest (a SUPERSET of the
        reference — H2O AutoML has none, SURVEY.md §5.4): each finished
        base model is saved there with its metrics; a rerun with the
        same dir skips completed steps and reloads their models, so a
        killed run (preempted slice, failed heartbeat) continues where
        it stopped instead of starting over."""
        if include_algos and exclude_algos:
            raise ValueError("include_algos and exclude_algos are "
                             "mutually exclusive")
        self.checkpoint_dir = checkpoint_dir
        self.max_models = max_models
        self.max_runtime_secs = max_runtime_secs
        self.nfolds = nfolds
        self.seed = seed
        algos = {"glm", "drf", "gbm", "xgboost", "deeplearning",
                 "stackedensemble"}
        if include_algos:
            algos = {a.lower() for a in include_algos}
        if exclude_algos:
            algos -= {a.lower() for a in exclude_algos}
        self.algos = algos
        self.sort_metric = sort_metric
        self.project_name = project_name
        self.verbosity = verbosity
        self.leaderboard: Leaderboard | None = None
        self.job: Job | None = None
        # overlap accounting of the last train() when the pipelined
        # executor ran (runtime/scheduler.py stats dict), else None
        self.scheduler_stats: dict | None = None
        self._models_by_family: dict[str, list] = {}
        # reference parity: H2O AutoML keeps an event_log frame
        # (ai/h2o/automl/EventLog [U3]); here a list of
        # (timestamp, message) — every step outcome INCLUDING swallowed
        # per-model failures lands here, so a 1-model leaderboard is
        # always explainable after the fact
        self.event_log: list[tuple[str, str]] = []

    # -- internals ----------------------------------------------------------

    def _log(self, msg: str):
        self.event_log.append(
            (time.strftime("%Y-%m-%dT%H:%M:%S"), msg))
        if self.verbosity:
            print(f"[AutoML {self.project_name}] {msg}")

    def _resolve_sort(self, nclasses: int) -> tuple[str, bool]:
        if self.sort_metric != "auto":
            m = self.sort_metric.lower()
            return m, m not in _DESC
        if nclasses == 2:
            return "auc", False
        if nclasses > 2:
            return "logloss", True
        return "rmse", True

    # -- main entry ---------------------------------------------------------

    def train(self, y: str, training_frame: Frame,
              x: Sequence[str] | None = None,
              leaderboard_frame: Frame | None = None) -> "AutoML":
        """Run the model search.

        By default the plan executes on the PIPELINED executor
        (runtime/scheduler.py): the driver thread holds the device
        token and trains plan entries strictly in order, a compile-
        ahead worker pre-traces/lowers the next entries' boost
        executables (a persistent-cache fill cold, a no-op warm), and
        a host worker applies completions — leaderboard insertion,
        `_save_step` manifest writes, event-log lines — in PLAN order
        whatever order they become runnable.  The ordering contract is
        strict: leaderboard, model metrics, and resume manifest are
        identical to the sequential run's (same seeds, insertion order
        by plan index).  ``H2O_TPU_AUTOML_PIPELINE=0`` restores the
        serial path bit-for-bit."""
        t0 = time.monotonic()
        deadline = t0 + self.max_runtime_secs if self.max_runtime_secs \
            else None
        rng = np.random.default_rng(self.seed)
        yv = training_frame.vec(y)
        nclasses = yv.cardinality() if yv.is_enum() else 1
        metric, asc = self._resolve_sort(nclasses)
        self.leaderboard = Leaderboard(metric, asc)
        self.job = Job(dest=self.project_name, description="AutoML").start()

        plan = [(fam, name, prm) for fam, name, prm in
                _default_plan(self.seed) if fam in self.algos]
        n_done = 0
        # H2O: max_models 0/None means unlimited — bounded by the time
        # budget; with neither limit, run the default plan only
        budget = self.max_models if self.max_models else None

        def out_of_budget():
            # n_done counts at TRAIN completion, so the budget holds
            # even while completions are still pending on the host
            # stream (out-of-order completion cannot over-train)
            if budget is not None and n_done >= budget:
                return True
            return deadline is not None and time.monotonic() > deadline

        completed = self._load_manifest()

        from .runtime import scheduler as _sched

        execu = _sched.PipelinedExecutor() if _sched.pipeline_enabled() \
            else None
        self.scheduler_stats = None

        def complete_step(model_id, fam, model, metrics, resumed):
            """Everything after a step's device work — runs inline
            (serial) or on the host stream in plan order (pipelined)."""
            self.leaderboard.add(model_id, model, metrics)
            self._models_by_family.setdefault(fam, []).append(
                (model_id, model))
            if resumed:
                self._log(f"{model_id}: resumed from checkpoint")
                return
            self._save_step(model_id, fam, model, metrics)
            self._log(f"{model_id}: {metric}="
                      f"{metrics.get(metric, float('nan')):.5f}")

        def run_one(seq: int, fam: str, name: str, params: dict) -> bool:
            """Train (or resume) one model. Always returns True today;
            the bool return + the caller's skip branch are the seam a
            future step-skip predicate plugs into (a skipped step must
            not consume budget NOR a host-stream sequence slot)."""
            if fam == "glm":
                params = {**params,
                          "family": "binomial" if nclasses == 2
                          else "multinomial" if nclasses > 2
                          else "gaussian"}
            model_id = f"{name}_AutoML_{self.project_name}"
            if model_id in completed:       # resume: step already done
                model, metrics = self._load_step(model_id,
                                                 completed[model_id])
                done = functools.partial(complete_step, model_id, fam,
                                         model, metrics, True)
                if execu is not None:
                    # resumed completions ride the ordered host stream
                    # too — a resumed step k must not insert before a
                    # still-pending step k-1
                    execu.host.submit(seq, done, label=model_id)
                else:
                    done()
                return True
            from .runtime import faults

            # fault point: one plan step about to TRAIN (resumed steps
            # above don't count) — lets chaos drills kill run N's step K
            # deterministically and assert the resume round-trip
            faults.fire("automl.step", step=model_id)
            est = _EST[fam](
                **params, seed=self.seed,
                nfolds=self.nfolds, fold_assignment="modulo",
                keep_cross_validation_predictions=True)
            t = time.monotonic()

            def train_and_score():
                model = est.train(y=y, training_frame=training_frame,
                                  x=x)
                if leaderboard_frame is not None:
                    ms = model.model_performance(leaderboard_frame, y)
                elif model.cv is not None:
                    ms = model.cv.metrics
                else:   # nfolds < 2: training metrics (H2O fallback)
                    ms = model.model_performance(training_frame, y)
                return model, ms

            if execu is not None:
                with execu.device(model_id):
                    model, metrics = train_and_score()
            else:
                model, metrics = train_and_score()
            metrics = {**metrics,
                       "training_time_s": time.monotonic() - t}
            done = functools.partial(complete_step, model_id, fam,
                                     model, metrics, False)
            if execu is not None:
                execu.host.submit(seq, done, label=model_id)
            else:
                done()
            return True

        from .runtime.health import (ClusterHealthError, healthy,
                                     is_device_error, mark_unhealthy)

        def step_failed(name: str, e: Exception) -> None:
            """A failed step never kills the run — UNLESS it took the
            cluster down with it (a device error escaping the training
            step): then every later step would fail too, so escalate to
            the same clean job failure a ClusterHealthError gets."""
            self._log(f"{name} failed: {e!r}")
            if is_device_error(e) and healthy():
                # a REAL XLA runtime error from a training loop's direct
                # shard_map dispatch reaches here without having flipped
                # health (only doall/predict run under device_dispatch)
                # — flip it now, or the plan grinds through every
                # remaining step against a dead mesh
                mark_unhealthy(f"device error during {name}: {e}")
            if not healthy():
                err = ClusterHealthError(
                    f"cluster died during {name}: {e!r} — restart and "
                    "rerun with the same checkpoint_dir to resume")
                self.job.failed(repr(err))
                raise err from e

        def poll_host_errors():
            """Surface host-stream completion failures (a failed
            `_save_step`, a leaderboard error) with the SAME semantics
            the serial loop gives them: logged via step_failed, fatal
            only if the cluster died with them."""
            if execu is None:
                return
            for _s, label, err in execu.host.pop_errors():
                step_failed(label or f"step {_s}",
                            err if isinstance(err, Exception)
                            else RuntimeError(repr(err)))

        ca_seen: set = set()

        def submit_compile_ahead(fam: str, name: str, params: dict):
            """Queue the entry's boost executables on the compile
            stream. Entry names dedupe up front (the sliding lookahead
            window sees each entry `depth` times — without this the
            unsupported count would multiply and estimators would be
            rebuilt per pass); family+params dedupe again inside the
            stream, so identical grid draws stay free too."""
            if execu is None or execu.compiles is None:
                return
            if name in ca_seen:
                return
            ca_seen.add(name)
            if f"{name}_AutoML_{self.project_name}" in completed:
                return      # resumed step: _load_step never dispatches
            if not hasattr(_EST[fam], "compile_ahead_lowerings"):
                # GLM/DL today: their iterative programs are
                # shape-shared across configs, so pre-lowering buys
                # little (an estimator adding support just defines the
                # method)
                execu.compiles.mark_unsupported()
                return
            try:
                est = _EST[fam](
                    **params, seed=self.seed,
                    nfolds=self.nfolds, fold_assignment="modulo",
                    keep_cross_validation_predictions=True)
            except Exception:       # bad params fail at run_one, loudly
                return
            key = (fam, tuple(sorted(
                (k, repr(v)) for k, v in params.items())))
            execu.compile_ahead_submit(
                key,
                functools.partial(est.compile_ahead_lowerings, y,
                                  training_frame, x),
                label=name)

        grid_families = [f for f in ("gbm", "xgboost", "deeplearning")
                         if f in self.algos]
        if budget is None and deadline is None:
            grid_families = []          # nothing bounds the grid search
        grid_state = {"idx": 0}

        def draw_grid_entry():
            """One ACCEPTED grid draw — consumes rng exactly like the
            serial loop (rejected draws consume a draw and nothing
            else), so the accepted-entry sequence is identical."""
            while True:
                fam, params = _random_grid(rng)
                if fam in grid_families:
                    grid_state["idx"] += 1
                    return (fam, f"{fam.upper()}_grid_"
                            f"{grid_state['idx']}", params)

        drawn: collections.deque = collections.deque()
        seq = 0
        try:
            for idx, (fam, name, params) in enumerate(plan):
                if out_of_budget():
                    break
                poll_host_errors()
                if execu is not None:
                    # pre-lower the NEXT entries' executables while this
                    # one holds the device token (entry idx itself would
                    # just race its own on-demand compile). Bounded by
                    # the REMAINING model budget too: pre-compiling an
                    # entry the budget will never train is pure waste
                    # (it even slows a single-core host)
                    ahead = execu.compile_ahead
                    if budget is not None:
                        ahead = min(ahead, budget - n_done - 1)
                    for nfam, nname, nparams in \
                            plan[idx + 1: idx + 1 + max(ahead, 0)]:
                        submit_compile_ahead(nfam, nname, nparams)
                s, seq = seq, seq + 1
                try:
                    # a skipped step doesn't consume budget; a failed
                    # attempt does (persistent failures can't loop)
                    if not run_one(s, fam, name, params):
                        if execu is not None:
                            execu.host.skip(s)
                        continue
                except ClusterHealthError as e:
                    # dead cloud: every later step would fail too — fail
                    # the job cleanly instead of grinding through the
                    # plan (reference fail-fast semantics, SURVEY §5.3)
                    if execu is not None:
                        execu.host.skip(s)
                    self.job.failed(repr(e))
                    raise
                except Exception as e:
                    if execu is not None:
                        execu.host.skip(s)
                    step_failed(name, e)
                n_done += 1
                self.job.update(min(0.8, n_done / max(budget or 20, 1)))

            while grid_families and not out_of_budget():
                poll_host_errors()
                if execu is not None:
                    # draw-ahead keeps the compile stream fed; drawing
                    # past the budget only advances rng state nothing
                    # downstream observes (the accepted-entry order the
                    # leaderboard contract depends on is unchanged).
                    # Lookahead is budget-bounded like the plan loop's.
                    ahead = execu.compile_ahead
                    if budget is not None:
                        ahead = min(ahead, budget - n_done - 1)
                    while len(drawn) < 1 + max(ahead, 0):
                        drawn.append(draw_grid_entry())
                    for entry in list(drawn)[1:1 + max(ahead, 0)]:
                        submit_compile_ahead(*entry)
                    fam, name, params = drawn.popleft()
                else:
                    fam, params = _random_grid(rng)
                    if fam not in grid_families:
                        continue
                    grid_state["idx"] += 1
                    name = f"{fam.upper()}_grid_{grid_state['idx']}"
                s, seq = seq, seq + 1
                try:
                    run_one(s, fam, name, params)
                except ClusterHealthError as e:
                    if execu is not None:
                        execu.host.skip(s)
                    self.job.failed(repr(e))
                    raise
                except Exception as e:
                    if execu is not None:
                        execu.host.skip(s)
                    step_failed(f"grid {fam}", e)
                n_done += 1
                self.job.update(min(0.9, n_done / max(budget or 20, 1)))

            if execu is not None:
                # barrier before the ensembles: every base model's
                # completion must be applied (the ensembles read the
                # leaderboard + family map), and pending completion
                # failures get their serial-semantics escalation now
                try:
                    execu.host.drain(timeout=600.0)
                except TimeoutError as te:
                    # a wedged host stream is a scheduler defect — fail
                    # the job loudly, never hang the run
                    self.job.failed(repr(te))
                    raise
                poll_host_errors()

            try:
                if "stackedensemble" in self.algos and \
                        leaderboard_frame is None and \
                        len(self.leaderboard.models) >= 2 and \
                        self.nfolds >= 2:
                    self._build_ensembles(y, training_frame, metric, asc)
            except Exception as e:      # surface fatal errors on the Job
                self.job.failed(repr(e))
                raise

            self.job.done()
        finally:
            # EVERY exit path (success, dead cloud, injected fault)
            # settles the streams: pending completions are applied so
            # finished steps' manifest writes land before the error
            # propagates (the resume round-trip depends on it), and no
            # scheduler thread outlives the run
            if execu is not None:
                try:
                    execu.host.drain(timeout=120.0)
                except TimeoutError as te:
                    self._log(f"scheduler drain wedged: {te}")
                for _s, label, err in execu.host.pop_errors():
                    self._log(f"{label or _s} completion failed: "
                              f"{err!r}")
                execu.shutdown(timeout=30.0)
                self.scheduler_stats = execu.stats()
                st = self.scheduler_stats
                ca = st.get("compile_ahead") or {}
                self._log(
                    "pipeline: "
                    f"device_busy={st['device_busy_s']:.1f}s "
                    f"compile_wait={st['device_compile_wait_s']:.1f}s "
                    f"host_busy={st['host_busy_s']:.1f}s "
                    f"compile_ahead={ca.get('busy_s', 0.0):.1f}s "
                    f"(fills={ca.get('fills', 0)} "
                    f"warm={ca.get('warm', 0)})")

        self._log(f"done in {time.monotonic() - t0:.1f}s — leader: "
                  f"{self.leaderboard.rows[0]['model_id']}"
                  if self.leaderboard.rows else "done (no models)")
        return self

    # -- resume manifest (checkpoint_dir) -----------------------------------

    def _manifest_path(self):
        """checkpoint_dir may live on any persist backend
        (s3://bucket/run1 — the save-AutoML-state-from-a-pod story the
        operator deploys, SURVEY.md §2b C20)."""
        from .persist import join_path

        return join_path(self.checkpoint_dir, "automl_manifest.json")

    def _load_manifest(self) -> dict:
        """{model_id: {file, fam, metrics}} of completed steps."""
        if not self.checkpoint_dir:
            return {}
        import json
        import os

        from .persist import is_remote, read_bytes

        try:
            return json.loads(read_bytes(self._manifest_path()))
        except FileNotFoundError:
            # only a genuinely-missing manifest means "fresh run" —
            # auth/transport failures must NOT silently retrain (and
            # then clobber the valid manifest they failed to read)
            if not is_remote(self.checkpoint_dir):
                os.makedirs(self.checkpoint_dir, exist_ok=True)
            return {}

    def _save_step(self, model_id, fam, model, metrics) -> None:
        if not self.checkpoint_dir:
            return
        import json
        import os

        from .persist import is_remote, join_path, save_model, write_bytes

        path = join_path(self.checkpoint_dir, f"{model_id}.model")
        save_model(model, path)
        with _MANIFEST_LOCK:       # read-modify-write must be atomic
            manifest = self._load_manifest()
            manifest[model_id] = {"file": path, "fam": fam,
                                  "metrics": metrics}
            if is_remote(self.checkpoint_dir):
                # object stores overwrite atomically per PUT
                write_bytes(self._manifest_path(),
                            json.dumps(manifest).encode())
            else:
                tmp = self._manifest_path() + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(manifest, f)
                os.replace(tmp, self._manifest_path())   # crash-atomic

    def _load_step(self, model_id, entry):
        from .persist import load_model

        return load_model(entry["file"]), entry["metrics"]

    def _build_ensembles(self, y, frame, metric, asc):
        """BestOfFamily + AllModels ensembles (reference StackedEnsembleStep).

        Only base models sharing the leader's fold assignment stack; CV
        metrics for the SEs themselves are skipped (the reference scores
        SEs on CV too, at 2x cost — the leaderboard uses training CV
        holdout scoring instead, flagged in the model_id)."""
        id2fam = {}
        for fam, lst in self._models_by_family.items():
            for mid, _ in lst:
                id2fam[mid] = fam

        ranked = [(r["model_id"], self.leaderboard.models[r["model_id"]])
                  for r in self.leaderboard.rows
                  if r["model_id"] in id2fam]
        usable = [(mid, m) for mid, m in ranked
                  if m.cv is not None and m.cv.holdout_predictions is not None]
        if len(usable) < 2:
            return
        best_of_family = {}
        for mid, m in usable:
            best_of_family.setdefault(id2fam[mid], (mid, m))

        for tag, pool in (
                ("BestOfFamily", list(best_of_family.values())),
                ("AllModels", usable)):
            if len(pool) < 2:
                continue
            try:
                se = StackedEnsemble(
                    [m for _, m in pool],
                    metalearner_nfolds=self.nfolds).train(
                    y=y, training_frame=frame)
                # the metalearner CVs over the level-one (holdout) frame
                # — its CV metrics are the ensemble's honest rank
                metrics = se.cv.metrics if se.cv else \
                    se.model_performance(frame, y)
                self.leaderboard.add(
                    f"StackedEnsemble_{tag}_AutoML_{self.project_name}",
                    se, metrics)
                self._log(f"StackedEnsemble_{tag}: "
                          f"{metric}={metrics.get(metric, float('nan')):.5f}")
            except Exception as e:
                self._log(f"StackedEnsemble_{tag} failed: {e!r}")

    # -- results ------------------------------------------------------------

    @property
    def leader(self):
        return self.leaderboard.leader if self.leaderboard else None

    def predict(self, frame: Frame):
        if self.leader is None:
            raise ValueError("AutoML has no trained models")
        return self.leader.predict(frame)
