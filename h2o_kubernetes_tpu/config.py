"""Configuration tiers — the reference's flag system, TPU-shaped.

Reference (SURVEY.md §5.6): three tiers — CRD spec (declarative),
CLI flags, and H2O-3 runtime options (`H2O.OptArgs` command line,
`sys.ai.h2o.*` system properties, `H2O_KUBERNETES_*` env vars). Here:

1. CRD spec → the C++ operator (native/deployment/crd.*) — declarative.
2. Env vars (`H2O_TPU_*`) → read once at import, listed below.
3. Programmatic `set_config(key, value)` — the in-process tier, wins
   over env.

| env var | default | meaning |
|---|---|---|
| H2O_TPU_LOG_LEVEL | WARNING | package logger level (water/util/Log) |
| H2O_TPU_HIST_IMPL | auto | histogram kernel: auto/pallas/segment |
| H2O_TPU_NBINS | 256 | default tree-learner bin count |
| H2O_TPU_COORDINATOR | — | jax.distributed coordinator (runtime/mesh) |
| H2O_TPU_NUM_PROCESSES | 1 | multi-host process count (runtime/mesh) |
| H2O_TPU_PROCESS_ID | 0 | this host's process id (runtime/mesh) |
| H2O_TPU_HIST_TERMS | 3 | bf16 mantissa terms (2 = throughput mode, ~2⁻¹⁶ products; ops/histogram) |
| H2O_TPU_HIST_DIMSEM | 1 | 0 drops the Pallas grid dimension_semantics annotation (compile-regression escape hatch) |
| H2O_TPU_HIST_BYTES_BUDGET | 2³⁰ | deep-tree level-histogram memory budget (models/gbm validation + grouped-DRF sizing) |
| H2O_TPU_CV_SHAPE_SHARE_ROWS | tpu≤1M | weights-masked CV row threshold; 0 disables, N forces on any backend (models/cv) |
| H2O_TPU_ARROW_CSV | 1 | 0 disables the pyarrow CSV fast path (frame/parse) |
| H2O_TPU_INGEST_CHUNK_BYTES | 16 MiB | pyarrow record-batch size for streamed CSV ingest (frame/parse, docs/SCALING.md) |
| H2O_TPU_DEVICE_GATHER_MIN | 65536 | row threshold for the on-device Vec.select_rows gather; 0 forces it, below it the host path wins (frame/frame) |
| H2O_TPU_BIN_BLOCK_COLS | derived | columns binned per block in Frame.binned (≤256 MB f32 transient; models/tree/binning) |
| H2O_TPU_EFB | auto | Exclusive Feature Bundling for wide sparse frames: 0 kill switch, 1 force, auto = plan on >= MIN_F-feature frames, keep when the shrink gate passes (models/tree/efb, docs/SCALING.md) |
| H2O_TPU_EFB_CONFLICT | 0 | allowed conflict-ROW fraction per bundle (LightGBM max_conflict_rate analog); 0 = exact exclusivity, the parity-gated default |
| H2O_TPU_EFB_MIN_F | 64 | feature-count floor below which auto mode skips EFB planning entirely (narrow frames keep the fused no-host-sync prologue) |
| H2O_TPU_EFB_MIN_SHRINK | 0.75 | auto mode keeps a plan only when bundled width Fb <= this fraction of F |
| H2O_TPU_GOSS | 0 (off) | GOSS gradient-based one-side sampling for the boosted-tree growers (GBM + XGBoost-hist; DRF stays bagged): per round keep the top-TOP_A row fraction by \|gradient\| + a seeded RAND_B fraction of the rest amplified by (1-a)/b, compacted into a static buffer so histogram kernels stream ~(a+b)·rows per level; 0 restores unsampled training bit-for-bit (models/gbm.goss_params, docs/SCALING.md "Gradient-based sampling") |
| H2O_TPU_GOSS_TOP_A | 0.1 | GOSS: fraction of rows kept outright by top \|gradient\| rank (0 <= a < 1, a + b <= 1) |
| H2O_TPU_GOSS_RAND_B | 0.1 | GOSS: seeded random fraction of the remaining rows kept with (1-a)/b weight amplification (0 < b, a + b <= 1) |
| H2O_TPU_OOC | auto | out-of-core tree training: 1 force, 0 never, auto = binned matrix past the budget headroom (models/gbm, docs/SCALING.md) |
| H2O_TPU_OOC_CHUNK_ROWS | derived | rows per host-pinned binned chunk in out-of-core mode (models/tree/ooc) |
| H2O_TPU_OOC_RESIDENT | 0 | debug: keep out-of-core chunks device-resident (the bitwise streamed-vs-resident parity harness) |
| H2O_TPU_SCORER_CACHE_BYTES | 1 GiB | byte budget over every resident model's serving state (live traces + LUTs + device flat arrays); past it the least-recently-scored model's executables/device arrays are evicted and re-promote via the persistent XLA cache; <=0 unbounded (models/base, docs/SERVING.md) |
| H2O_TPU_SCORER_CACHE_MAX | 0 (off) | optional resident-model COUNT cap on top of the byte budget; evictions counted in scorer_cache_stats() (models/base) |
| H2O_TPU_SCORE_FAIRNESS | 1 | per-model queue-share caps + SLO-priority dispatch in the micro-batcher; 0 = unfair FIFO baseline (rest.py, docs/SERVING.md) |
| H2O_TPU_SCORE_MODEL_QUEUE_SHARE | per class | global override of the admission-queue fraction ONE model may occupy (rest.py) |
| H2O_TPU_SLO_DEFAULT | standard | SLO class (interactive/standard/batch) when neither the X-H2O-SLO header nor the model's registry default applies (rest.py) |
| H2O_TPU_MODEL_RATE_LIMIT | 0 (off) | per-tenant token bucket: sustained scoring requests/second any ONE model key may submit (burst = 1 s of traffic); past it 429 + Retry-After at admission, counted in /3/Stats `rate_limited` (rest.py, docs/SERVING.md) |
| H2O_TPU_PCACHE_MIN_SECS | — | persistent-XLA-cache compile-time threshold override; serving pods pin 0 so every tenant compile persists and evictions re-promote from disk (runtime/backend.py) |
| H2O_TPU_PROBE_BUDGET | 600 | backend-probe stubbornness seconds (runtime/backend) |
| H2O_TPU_SCORE_BATCH_US | 2000 | REST scoring micro-batcher window, µs; 0 = dispatch immediately (rest.py, docs/SERVING.md) |
| H2O_TPU_SCORE_TIMEOUT | 60 | seconds a scoring request may wait for its micro-batched result before 503 (rest.py) |
| H2O_TPU_SCORE_MAX_ROWS | 100000 | per-request row cap on the inline scoring route (413 past it — one oversized dispatch must not lock the cloud) |
| H2O_TPU_CONTRIB_MAX_ROWS | 100000 | per-request row cap on the TreeSHAP contributions route (413 past it; rest.py, docs/SERVING.md "Explainable serving") |
| H2O_TPU_CONTRIB_CHUNK | 16384 | upper bound on rows per device TreeSHAP dispatch — the kernel's [rows × leaves × depth] working set is chunked under it, pow2-floored so full chunks share one trace key (models/base.py) |
| H2O_TPU_CONTRIB_SLO_DEFAULT | explain | SLO class for contributions requests when no X-H2O-SLO header is sent (rest.py; the model's scoring registry default deliberately does not apply) |
| H2O_TPU_SHAP_KERNEL | auto | TreeSHAP serving impl: auto = chip-native Pallas kernel on TPU / lowered-XLA `flat_shap_tab` elsewhere, 1 forces the kernel (interpret mode off-chip), 0 kill switch restoring the XLA path bitwise; read at TRACE time like hist_impl — a cached contributions executable keeps its impl until scorer-cache evict/re-promote (ops/shap_kernel.py, docs/SERVING.md "Explainable serving") |
| H2O_TPU_JOB_TIMEOUT | 0 (off) | server-side job-poll timeout: RUNNING jobs older than this read FAILED on /3/Jobs (rest.py) |
| H2O_TPU_SCORE_QUEUE_MAX | 256 | scoring admission-queue bound: requests past it are load-shed with 429 + Retry-After; <=0 unbounded (rest.py, docs/RESILIENCE.md) |
| H2O_TPU_DRAIN_TIMEOUT | 30 | seconds the SIGTERM drain waits for RUNNING jobs / batcher flush before failing them (runtime/lifecycle.py) |
| H2O_TPU_BREAKER_FAILURES | 5 | consecutive device-dispatch errors that trip the serving circuit breaker open (runtime/lifecycle.py) |
| H2O_TPU_BREAKER_COOLDOWN | 30 | seconds the breaker stays open before admitting the half-open probe (runtime/lifecycle.py) |
| H2O_TPU_RETRY_MAX_ELAPSED_S | 0 (off) | hard cap on a retry loop's total elapsed time, attempts included (runtime/retry.py) |
| H2O_TPU_AUTOML_PIPELINE | 1 | 0 kills the pipelined AutoML executor AND the CV fold pipeline — restores the serial path bit-for-bit (runtime/scheduler.py, docs/SCALING.md) |
| H2O_TPU_AUTOML_COMPILE_AHEAD | 1 | plan entries whose boost executables are pre-lowered ahead of the training cursor; 0 disables the compile stream (needs the persistent XLA cache to pay — auto-disabled without it) |
| H2O_TPU_AUTOML_QUEUE_DEPTH | 4 | bound on the scheduler's host/compile queues: completed-but-unapplied models and stale compile requests cannot accumulate (runtime/scheduler.py) |
| H2O_TPU_FUSED_BINNING | 1 | 0 restores the two-dispatch fit_bins→Frame.binned train prologue instead of the fused single-dispatch fit+apply (models/tree/binning.py) |
| H2O_TPU_POOL_REPLICA | — | 1 marks this rest.py process an operator-provisioned scorer replica: /readyz additionally requires a pushed+warmed registry artifact (rest.py, docs/OPERATOR.md) |
| H2O_TPU_POOL_WARM_BUCKETS | 128,1024 | default warm-up ladder: Model.warm_up pre-traces every pow2 batch bucket up to the largest listed, before a replica's readyz flips (models/base.py) |
| H2O_TPU_POOL_RECONCILE_INTERVAL | 0.5 | seconds between scorer-pool reconcile passes (operator/reconcile.py) |
| H2O_TPU_POOL_STARTUP_DEADLINE | 180 | seconds a provisioned replica may take to reach READY before the reconciler replaces it |
| H2O_TPU_POOL_DEREGISTER_GRACE | 0.75 | cordon→SIGTERM gap of a rolling update, so routers drop the endpoint before the drain begins (zero-5xx contract) |
| H2O_TPU_POOL_QUEUE_HIGH | 8 | mean admission-queue depth per replica that scales the pool up (operator/autoscale.py) |
| H2O_TPU_POOL_PROBE_TIMEOUT | 2 | per-probe cap on every reconciler health/readyz//3/Stats scrape — one hung replica cannot stall the whole reconcile pass (operator/reconcile.py) |
| H2O_TPU_POOL_BACKOFF_BASE | 0.5 | crash-loop backoff: first respawn delay after a replica failure; doubles per recent failure (operator/reconcile.py, docs/OPERATOR.md) |
| H2O_TPU_POOL_BACKOFF_MAX | 30 | crash-loop backoff delay cap, seconds |
| H2O_TPU_POOL_BACKOFF_WINDOW | 120 | seconds a failure stays in the backoff history; a version clean this long respawns immediately again |
| H2O_TPU_POOL_ROLLOUT_RETRIES | 3 | new-version readiness failures before a surge-one rollout auto-rolls-back to the pinned last-good version (`rollout_rolled_back` event) |
| H2O_TPU_POOL_LOG_MAX_BYTES | 8 MiB | per-replica log size that triggers rotate-on-respawn (operator/reconcile.py) |
| H2O_TPU_POOL_LOG_KEEP | 16 | replica log files kept per pool; older ones are pruned at spawn so a crash loop cannot fill the disk the durable store lives on |
| H2O_TPU_ROUTER_RETRY_BUDGET | 2 | fleet router: per-TENANT cross-shard retry budget, retries/second (burst = 1 s, min 1 token); 0 = no retries, every failure relays to the client — a dying shard must not amplify load onto survivors (operator/router.py, docs/OPERATOR.md "Sharded routing") |
| H2O_TPU_ROUTER_HEDGE_MS | 0 (off) | hedged-dispatch kill switch: > 0 arms speculative re-dispatch for `interactive`-class requests after this many ms without a primary answer (first response wins; hedges consume retry-budget tokens) |
| H2O_TPU_ROUTER_HEALTH_INTERVAL | 0.5 | seconds between router health sweeps over every replica's /3/Stats; each scrape rides the shared probe helper (H2O_TPU_POOL_PROBE_TIMEOUT + 3 attempts before unhealthy, so a scoring burst can't flap a shard out of the ring) |
| H2O_TPU_ROUTER_MAX_INFLIGHT | 256 | router admission bound on concurrently forwarded requests; past it 429 + Retry-After (<=0 unbounded) |
| H2O_TPU_ROUTER_TIMEOUT | 30 | per-forward upstream timeout on the router, seconds; clamped under the request's remaining X-H2O-Deadline-Ms budget |
| H2O_TPU_ROUTER_TABLE_INTERVAL | 0 | extra throttle, seconds, between STORE reads of the published routing table by a stateless router (`StoreRoutingTable`); 0 = refresh on every health sweep (operator/router.py, docs/OPERATOR.md "Router HA & rebalancing") |
| H2O_TPU_LEASE_TTL | 5 | controller-lease TTL, seconds: an `operator.run --ha` replica that misses renewals this long is structurally deposed (epoch bump fences its routing writes) and a standby takes over (operator/spec.py, docs/OPERATOR.md) |
| H2O_TPU_LEASE_HEARTBEAT | ttl/3 | seconds between the lease holder's renew heartbeats (operator/run.py) |
| H2O_TPU_REBALANCE | 0 (off) | live hot-shard rebalancing: 1 lets the controller MOVE a sustained-pressure tenant to the next healthy shard in its HRW preference, make-before-break (operator/reconcile.py, docs/OPERATOR.md "Router HA & rebalancing") |
| H2O_TPU_REBALANCE_SUSTAIN | 3 | consecutive reconcile passes a tenant's shed/504 delta must stay positive before it counts as hot — one blip never moves anyone |
| H2O_TPU_REBALANCE_COOLDOWN | 30 | seconds between moves, fleet-wide: rebalancing converges one tenant at a time instead of thrashing |
| H2O_TPU_REBALANCE_RETIRE_S | 5 | make-before-break dwell: seconds the move's SOURCE keeps serving after the destination took routing-preference position 0, and only while the destination stays healthy |
| H2O_TPU_REBALANCE_FAILBACK_S | 30 | failback hygiene for loss-driven re-placements: once every home shard of an overridden tenant has been healthy this long, the override copies age out of the survivor's child spec and the routing table |
| H2O_TPU_METRICS_TOPK | 20 | fleet telemetry: per-metric series cap for tenant-cardinality labels (`model`) — the top-K label values by traffic keep their own series, everything else rolls into `other`, so 1000 tenants cost K+1 series on GET /metrics (runtime/telemetry.py, docs/OBSERVABILITY.md) |
| H2O_TPU_METRICS_PORT | — (off) | operator.run status listener: bind /metrics + /healthz on this port so the control plane is scrapeable like any replica (0 = ephemeral; `--status-port` overrides) |
| H2O_TPU_TRACE | 1 | 0 disables request-span recording (trace ring + per-request phase histograms) — the tracing perf kill switch; counters and /metrics stay on (runtime/telemetry.py) |
| H2O_TPU_TRACE_RING | 512 | per-process bound on retained trace records (GET /3/Trace/{id}); oldest-inserted evict, so a serving storm cannot grow the ring |
| JAX_COMPILATION_CACHE_DIR | auto | persistent XLA cache dir; h2o.init() picks repo/user default when unset (keyed by host CPU feature fingerprint) |

COORDINATOR/NUM_PROCESSES/PROCESS_ID are the operator's injection
contract, consumed directly by `runtime/mesh.initialize_distributed`.
The knobs below the line are read at USE time by their owning modules
(perf/robustness switches, not cluster identity), so they stay
env-only rather than entering the programmatic tier.

Caveat: `hist_impl` is read when a training program is TRACED; XLA
executables already compiled for a shape keep the kernel they were
traced with, so changing it mid-process affects new shapes only (the
usual jit-static-argument semantics).
"""

from __future__ import annotations

import logging
import os
from typing import Any

__all__ = ["get_config", "set_config", "CONFIG"]

_DEFAULTS: dict[str, Any] = {
    "log_level": "WARNING",
    "hist_impl": "auto",
    "nbins": 256,
}

_ENV_KEYS = {
    "log_level": "H2O_TPU_LOG_LEVEL",
    "hist_impl": "H2O_TPU_HIST_IMPL",
    "nbins": "H2O_TPU_NBINS",
}

CONFIG: dict[str, Any] = {}


def _validate(key: str, value: Any) -> Any:
    """ONE rule set for both tiers (env `_load` and programmatic
    `set_config`); returns the coerced value or raises ValueError."""
    if key == "nbins":
        value = int(value)
        if not 4 <= value <= 256:
            raise ValueError("nbins must be in [4, 256]")
    if key == "hist_impl" and value not in ("auto", "pallas", "segment"):
        raise ValueError(f"hist_impl must be auto/pallas/segment, "
                         f"got '{value}'")
    if key == "log_level" and not isinstance(
            getattr(logging, str(value).upper(), None), int):
        raise ValueError(f"unknown log level '{value}'")
    return value


def _load() -> None:
    """Env tier. Shares _validate with set_config — a typo'd
    H2O_TPU_NBINS must produce a clear message, not crash the package
    import inside int()."""
    for key, default in _DEFAULTS.items():
        raw = os.environ.get(_ENV_KEYS[key])
        if raw is None:
            CONFIG.setdefault(key, default)
            continue
        try:
            CONFIG[key] = _validate(key, raw)
        except (ValueError, TypeError) as e:
            raise ValueError(
                f"bad {_ENV_KEYS[key]}={raw!r}: {e}") from None


def get_config(key: str) -> Any:
    if key not in _DEFAULTS:
        raise KeyError(f"unknown config key '{key}' "
                       f"(known: {sorted(_DEFAULTS)})")
    return CONFIG[key]


def set_config(key: str, value: Any) -> None:
    """Programmatic tier — applies immediately (and re-levels the
    package logger for log_level)."""
    if key not in _DEFAULTS:
        raise KeyError(f"unknown config key '{key}' "
                       f"(known: {sorted(_DEFAULTS)})")
    value = _validate(key, value)   # raises BEFORE assignment
    CONFIG[key] = value
    if key == "log_level":
        from .diagnostics import log

        log.setLevel(getattr(logging, str(value).upper()))


_load()
